"""Workflow-level black-box conformance suite (parity role: reference
fugue_test/builtin_suite.py:114-1729): checkpoints, yields, transform/
cotransform/out_transform, joins/set ops, callbacks, validation — everything
through FugueWorkflow against an arbitrary engine."""

import os
import pickle
from typing import Any, Callable, Iterable, List

import pandas as pd
import pytest

from fugue_tpu.exceptions import FugueWorkflowCompileValidationError
from fugue_tpu.collections.partition import PartitionSpec
from fugue_tpu.dataframe import ArrayDataFrame, DataFrame, DataFrames, LocalDataFrame
from fugue_tpu.dataframe.utils import df_eq
from fugue_tpu.execution import ExecutionEngine
from fugue_tpu.extensions import (
    CoTransformer,
    Transformer,
    register_transformer,
    transformer,
)
from fugue_tpu.workflow import FugueWorkflow


class BuiltInTests:
    class Tests:
        @classmethod
        def setup_class(cls):
            cls._engine = cls.make_engine(cls)

        @classmethod
        def teardown_class(cls):
            cls._engine.stop()

        def make_engine(self) -> ExecutionEngine:  # pragma: no cover
            raise NotImplementedError

        @property
        def engine(self) -> ExecutionEngine:
            return self._engine  # type: ignore

        def dag(self) -> FugueWorkflow:
            return FugueWorkflow()

        def run(self, dag: FugueWorkflow):
            return dag.run(self.engine)

        # ---- basic workflow ---------------------------------------------
        def test_create_show(self):
            dag = self.dag()
            dag.df([[1, "a"]], "x:long,y:str").show()
            self.run(dag)

        def test_create_process_output(self):
            dag = self.dag()
            a = dag.df([[1], [2]], "x:long")

            def double(df: pd.DataFrame) -> pd.DataFrame:
                return df.assign(x=df["x"] * 2)

            b = a.process(double, schema="x:long")
            b.assert_eq(dag.df([[2], [4]], "x:long"))
            self.run(dag)

        def test_assert_eq_fail(self):
            dag = self.dag()
            a = dag.df([[1]], "x:long")
            a.assert_eq(dag.df([[2]], "x:long"))
            with pytest.raises(Exception):
                self.run(dag)

        def test_transform_basic(self):
            dag = self.dag()
            a = dag.df([[1, "a"], [2, "b"]], "x:long,y:str")

            def f(df: pd.DataFrame) -> pd.DataFrame:
                return df.assign(z=df["x"] + 1)

            b = a.transform(f, schema="*,z:long")
            b.assert_eq(dag.df([[1, "a", 2], [2, "b", 3]], "x:long,y:str,z:long"))
            self.run(dag)

        def test_transform_with_partition_and_presort(self):
            dag = self.dag()
            a = dag.df([[1, "a"], [5, "a"], [2, "b"]], "x:long,k:str")

            # keep first row of each partition sorted by x desc
            def top1(rows: Iterable[List[Any]]) -> List[List[Any]]:
                return [next(iter(rows))]

            b = a.partition(by=["k"], presort="x desc").transform(
                top1, schema="*"
            )
            b.assert_eq(dag.df([[5, "a"], [2, "b"]], "x:long,k:str"))
            self.run(dag)

        def test_transform_binary_and_iterable(self):
            dag = self.dag()
            a = dag.df([[b"\x01\x02"]], "data:bytes")

            def f(rows: Iterable[List[Any]]) -> Iterable[List[Any]]:
                for r in rows:
                    yield [r[0] + b"\x03"]

            b = a.transform(f, schema="data:bytes")
            b.assert_eq(dag.df([[b"\x01\x02\x03"]], "data:bytes"))
            self.run(dag)

        def test_transform_iterable_pandas_chunks(self):
            dag = self.dag()
            a = dag.df([[1], [2], [3], [4]], "x:long")

            def f(dfs: Iterable[pd.DataFrame]) -> Iterable[pd.DataFrame]:
                for df in dfs:
                    yield df[df["x"] % 2 == 0]

            b = a.transform(f, schema="*")
            b.assert_eq(dag.df([[2], [4]], "x:long"))
            self.run(dag)

        def test_transform_class_with_params(self):
            class AddN(Transformer):
                def get_output_schema(self, df):
                    return df.schema

                def transform(self, df):
                    n = self.params.get("n", 0)
                    pdf = df.as_pandas()
                    return ArrayDataFrame(
                        (pdf["x"] + n).to_frame().values.tolist(), df.schema
                    )

            dag = self.dag()
            a = dag.df([[1], [2]], "x:long")
            b = a.transform(AddN, params={"n": 10})
            b.assert_eq(dag.df([[11], [12]], "x:long"))
            self.run(dag)

        def test_out_transform(self):
            collected: List[int] = []

            def f(df: pd.DataFrame) -> None:
                collected.append(len(df))

            dag = self.dag()
            a = dag.df([[1], [2], [3]], "x:long")
            a.out_transform(f)
            self.run(dag)
            assert sum(collected) == 3

        def test_transform_ignore_errors(self):
            def f(df: pd.DataFrame) -> pd.DataFrame:
                if df["k"].iloc[0] == "b":
                    raise NotImplementedError("boom")
                return df

            dag = self.dag()
            a = dag.df([[1, "a"], [2, "b"]], "x:long,k:str")
            b = a.partition_by("k").transform(
                f, schema="*", ignore_errors=[NotImplementedError]
            )
            b.assert_eq(dag.df([[1, "a"]], "x:long,k:str"))
            self.run(dag)

        # ---- cotransform -------------------------------------------------
        def test_zip_cotransform(self):
            def cm(df1: pd.DataFrame, df2: pd.DataFrame) -> pd.DataFrame:
                return df1.assign(w=df2["w"].iloc[0] if len(df2) else -1.0)

            dag = self.dag()
            a = dag.df([[1, "a"], [2, "a"], [3, "b"]], "x:long,k:str")
            b = dag.df([["a", 10.0], ["b", 20.0]], "k:str,w:double")
            z = a.partition_by("k").zip(b)
            c = z.transform(cm, schema="x:long,k:str,w:double")
            c.assert_eq(
                dag.df(
                    [[1, "a", 10.0], [2, "a", 10.0], [3, "b", 20.0]],
                    "x:long,k:str,w:double",
                )
            )
            self.run(dag)

        def test_cotransform_with_dataframes_arg(self):
            def cm(dfs: DataFrames) -> LocalDataFrame:
                total = sum(df.count() for df in dfs.values())
                return ArrayDataFrame([[total]], "n:long")

            dag = self.dag()
            a = dag.df([[1, "a"]], "x:long,k:str")
            b = dag.df([["a", 1.0], ["a", 2.0]], "k:str,w:double")
            z = a.partition_by("k").zip(b)
            c = z.transform(cm, schema="n:long")
            c.assert_eq(dag.df([[3]], "n:long"))
            self.run(dag)

        # ---- joins & set ops via workflow -------------------------------
        def test_workflow_joins(self):
            dag = self.dag()
            a = dag.df([[1, "a"], [2, "b"]], "x:long,y:str")
            b = dag.df([[1, 1.0]], "x:long,z:double")
            a.inner_join(b).assert_eq(dag.df([[1, "a", 1.0]], "x:long,y:str,z:double"))
            a.semi_join(b).assert_eq(dag.df([[1, "a"]], "x:long,y:str"))
            a.anti_join(b).assert_eq(dag.df([[2, "b"]], "x:long,y:str"))
            self.run(dag)

        def test_workflow_set_ops(self):
            dag = self.dag()
            a = dag.df([[1], [2]], "x:long")
            b = dag.df([[2], [3]], "x:long")
            a.union(b).assert_eq(dag.df([[1], [2], [3]], "x:long"))
            a.union(b, distinct=False).assert_eq(
                dag.df([[1], [2], [2], [3]], "x:long")
            )
            a.subtract(b).assert_eq(dag.df([[1]], "x:long"))
            a.intersect(b).assert_eq(dag.df([[2]], "x:long"))
            self.run(dag)

        def test_workflow_ops(self):
            dag = self.dag()
            a = dag.df([[1, None], [2, "b"], [2, "b"]], "x:long,y:str")
            a.distinct().assert_eq(dag.df([[1, None], [2, "b"]], "x:long,y:str"))
            a.dropna().assert_eq(dag.df([[2, "b"], [2, "b"]], "x:long,y:str"))
            a.fillna("z", subset=["y"]).assert_eq(
                dag.df([[1, "z"], [2, "b"], [2, "b"]], "x:long,y:str")
            )
            a.rename({"y": "yy"}).assert_eq(
                dag.df([[1, None], [2, "b"], [2, "b"]], "x:long,yy:str")
            )
            a.drop(["y"]).assert_eq(dag.df([[1], [2], [2]], "x:long"))
            a[["y"]].assert_eq(dag.df([[None], ["b"], ["b"]], "y:str"))
            a.alter_columns("x:double").assert_eq(
                dag.df([[1.0, None], [2.0, "b"], [2.0, "b"]], "x:double,y:str")
            )
            self.run(dag)

        def test_take_sample(self):
            dag = self.dag()
            a = dag.df([[i] for i in range(20)], "x:long")
            a.take(3, presort="x desc").assert_eq(
                dag.df([[19], [18], [17]], "x:long")
            )
            s = a.sample(n=5, seed=3)

            def check_n(df: pd.DataFrame) -> pd.DataFrame:
                assert len(df) == 5
                assert df.x.isin(range(20)).all()
                return df.head(0)

            s.transform(check_n, schema="x:long")
            # same seed -> same rows (determinism through the DAG)
            a.sample(n=5, seed=3).assert_eq(s)
            f = a.sample(frac=0.5, seed=9)

            def check_f(df: pd.DataFrame) -> pd.DataFrame:
                assert len(df) == 10
                return df.head(0)

            f.transform(check_f, schema="x:long")
            self.run(dag)

        def test_select_filter_assign_aggregate(self):
            from fugue_tpu.column import col, functions as ff

            dag = self.dag()
            a = dag.df([[1, "a"], [2, "a"], [3, "b"]], "x:long,k:str")
            a.filter(col("x") > 1).assert_eq(
                dag.df([[2, "a"], [3, "b"]], "x:long,k:str")
            )
            a.assign(y=(col("x") * 2).cast("long")).assert_eq(
                dag.df([[1, "a", 2], [2, "a", 4], [3, "b", 6]], "x:long,k:str,y:long")
            )
            a.partition_by("k").aggregate(s=ff.sum(col("x"))).assert_eq(
                dag.df([["a", 3], ["b", 3]], "k:str,s:long")
            )
            a.select("k", ff.max(col("x")).alias("mx")).assert_eq(
                dag.df([["a", 2], ["b", 3]], "k:str,mx:long")
            )
            self.run(dag)

        # ---- io ----------------------------------------------------------
        def test_save_load(self, tmp_path):
            path = os.path.join(str(tmp_path), "wf.parquet")
            dag = self.dag()
            a = dag.df([[1, "a"]], "x:long,y:str")
            a.save(path)
            self.run(dag)
            dag = self.dag()
            dag.load(path).assert_eq(dag.df([[1, "a"]], "x:long,y:str"))
            self.run(dag)

        def test_save_and_use(self, tmp_path):
            path = os.path.join(str(tmp_path), "su.parquet")
            dag = self.dag()
            a = dag.df([[1]], "x:long")
            b = a.save_and_use(path)
            b.assert_eq(dag.df([[1]], "x:long"))
            self.run(dag)
            assert os.path.exists(path)

        # ---- checkpoints & yields ---------------------------------------
        def test_persist_weak_checkpoint(self):
            dag = self.dag()
            a = dag.df([[1]], "x:long").persist()
            a.assert_eq(dag.df([[1]], "x:long"))
            self.run(dag)

        def test_yield_dataframe(self):
            dag = self.dag()
            a = dag.df([[1], [2]], "x:long")
            a.yield_dataframe_as("r", as_local=True)
            res = self.run(dag)
            assert res["r"].as_array() == [[1], [2]]

        def test_strong_checkpoint_and_yield_file(self, tmp_path):
            engine = self.engine
            engine.conf["fugue.workflow.checkpoint.path"] = str(tmp_path)
            dag = self.dag()
            a = dag.df([[1]], "x:long").checkpoint()
            a.assert_eq(dag.df([[1]], "x:long"))
            self.run(dag)
            # yield file
            dag = self.dag()
            a = dag.df([[7]], "x:long")
            a.yield_file_as("f")
            res = self.run(dag)
            path = res.yields["f"].name
            assert os.path.exists(path)

        def test_deterministic_checkpoint_skips_recompute(self, tmp_path):
            engine = self.engine
            engine.conf["fugue.workflow.checkpoint.path"] = str(tmp_path)
            calls: List[int] = []

            def expensive(df: pd.DataFrame) -> pd.DataFrame:
                calls.append(1)
                return df

            def build():
                dag = self.dag()
                a = dag.df([[1]], "x:long")
                b = a.transform(expensive, schema="*").deterministic_checkpoint()
                b.yield_dataframe_as(f"r{len(calls)}_{id(dag)}", as_local=True)
                return dag

            self.run(build())
            n1 = len(calls)
            assert n1 >= 1
            self.run(build())  # identical dag -> checkpoint file reused
            assert len(calls) == n1

        # ---- callbacks (RPC) --------------------------------------------
        def test_callback(self):
            hits: List[str] = []

            def cb(value: str) -> None:
                hits.append(value)

            def f(df: pd.DataFrame, announce: Callable) -> pd.DataFrame:
                announce(f"rows={len(df)}")
                return df

            dag = self.dag()
            a = dag.df([[1], [2]], "x:long")
            b = a.transform(f, schema="*", callback=cb)
            b.assert_eq(dag.df([[1], [2]], "x:long"))
            self.run(dag)
            assert len(hits) >= 1

        # ---- validation --------------------------------------------------
        def test_validation_errors(self):
            # partitionby_has: k
            def f(df: pd.DataFrame) -> pd.DataFrame:
                return df

            dag = self.dag()
            a = dag.df([[1, "a"]], "x:long,k:str")
            a.transform(f, schema="*")
            with pytest.raises(FugueWorkflowCompileValidationError):
                self.run(dag)

        def test_module_decorator(self):
            from fugue_tpu.workflow.module import module

            @module
            def double(df: Any) -> Any:
                def _d(pdf: pd.DataFrame) -> pd.DataFrame:
                    return pdf.assign(x=pdf.x * 2)

                return df.transform(_d, schema="*")

            dag = self.dag()
            a = dag.df([[1], [2]], "x:long")
            double(double(a)).assert_eq(dag.df([[4], [8]], "x:long"))
            self.run(dag)

        def test_workflow_select_sql(self):
            dag = self.dag()
            a = dag.df([[1, "a"], [2, "a"], [3, "b"]], "x:long,k:str")
            res = dag.select("SELECT k, SUM(x) AS s FROM", a, "GROUP BY k")
            res.assert_eq(dag.df([["a", 3], ["b", 3]], "k:str,s:long"))
            self.run(dag)

        def test_yield_table_through_suite(self):
            dag = self.dag()
            a = dag.df([[7]], "x:long")
            a.yield_table_as("suite_tbl")
            self.run(dag)
            y = dag.yields["suite_tbl"]
            assert y.storage_type == "table"
            dag2 = self.dag()
            dag2.df(y).assert_eq(dag2.df([[7]], "x:long"))
            self.run(dag2)

        def test_out_cotransform(self):
            collected: List[Any] = []

            def ocm(dfs: DataFrames) -> None:
                collected.append((dfs[0].count(), dfs[1].count()))

            dag = self.dag()
            a = dag.df([[1, "a"], [2, "a"]], "x:long,k:str")
            b = dag.df([["a", 1.0]], "k:str,v:double")
            z = a.partition_by("k").zip(b)
            z.out_transform(ocm)
            self.run(dag)
            assert collected == [(2, 1)]

        def test_callback_with_partitions(self):
            seen: List[Any] = []

            def cb(k: str, n: int) -> None:
                seen.append((k, n))

            def t(df: pd.DataFrame, announce: Callable) -> pd.DataFrame:
                announce(str(df.k.iloc[0]), len(df))
                return df

            dag = self.dag()
            a = dag.df([[1, "a"], [2, "a"], [3, "b"]], "x:long,k:str")
            a.partition_by("k").transform(
                t, schema="*", callback=cb
            ).assert_eq(a)
            self.run(dag)
            assert sorted(seen) == [("a", 2), ("b", 1)]

        def test_load_save_csv_json(self, tmp_path):
            dag = self.dag()
            a = dag.df([[1, "a"], [2, "b"]], "x:long,y:str")
            csvp = os.path.join(str(tmp_path), "t.csv")
            jsonp = os.path.join(str(tmp_path), "t.json")
            a.save(csvp, header=True)
            a.save(jsonp)
            self.run(dag)
            dag2 = self.dag()
            c = dag2.load(csvp, header=True, columns="x:long,y:str")
            c.assert_eq(dag2.df([[1, "a"], [2, "b"]], "x:long,y:str"))
            j = dag2.load(jsonp)
            j.assert_eq(dag2.df([[1, "a"], [2, "b"]], "x:long,y:str"))
            self.run(dag2)

        def test_cotransform_presort_and_empty_side(self):
            def cm(dfs: DataFrames) -> LocalDataFrame:
                rows = dfs[0].as_array()
                first = rows[0][0] if rows else -1
                k = rows[0][1] if rows else -1
                return ArrayDataFrame(
                    [[k, first, dfs[1].count()]], "k:long,top:long,nb:long"
                )

            dag = self.dag()
            a = dag.df([[1, 1], [3, 1], [2, 1]], "x:long,k:long")
            b = dag.df([[2, 9.0]], "k:long,w:double")
            z = a.partition(by=["k"], presort="x desc").zip(
                b, how="left_outer"
            )
            res = z.transform(cm, schema="k:long,top:long,nb:long")
            res.assert_eq(dag.df([[1, 3, 0]], "k:long,top:long,nb:long"))
            self.run(dag)

        def test_engine_inference_from_engine_frame(self):
            # fa.transform on an engine-native frame infers this engine
            import fugue_tpu.api as fa

            src = self.engine.to_df([[1], [2]], "x:long")

            def t(df: pd.DataFrame) -> pd.DataFrame:
                return df.assign(y=df.x + 1)

            out = fa.transform(src, t, schema="*,y:long", as_fugue=True)
            assert df_eq(
                fa.as_fugue_df(out), [[1, 2], [2, 3]], "x:long,y:long",
                throw=True,
            )

        def test_any_column_name(self):
            # special characters in column names flow through the workflow
            dag = self.dag()
            a = dag.df(
                pd.DataFrame({"a b": [1, 2], "c-d": ["x", "y"]}),
                "`a b`:long,`c-d`:str",
            )

            def f(df: pd.DataFrame) -> pd.DataFrame:
                return df

            a.transform(f, schema="*").assert_eq(a)
            a.rename({"a b": "ab"}).assert_eq(
                dag.df(
                    pd.DataFrame({"ab": [1, 2], "c-d": ["x", "y"]}),
                    "ab:long,`c-d`:str",
                )
            )
            self.run(dag)

        def test_datetime_in_workflow(self):
            import datetime

            dag = self.dag()
            a = dag.df(
                [["2020-01-01 10:00:00", "2020-01-02"]], "t:datetime,d:date"
            )

            def f(df: pd.DataFrame) -> pd.DataFrame:
                assert df["t"].iloc[0].hour == 10
                return df

            a.transform(f, schema="*").assert_eq(a)
            self.run(dag)

        def test_local_instance_as_extension(self):
            from fugue_tpu.extensions import Transformer

            class AddK(Transformer):
                def __init__(self, k: int):
                    self._k = k

                def get_output_schema(self, df: Any) -> Any:
                    return df.schema

                def transform(self, df: Any) -> Any:
                    pdf = df.as_pandas()
                    from fugue_tpu.dataframe import PandasDataFrame

                    return PandasDataFrame(
                        pdf.assign(x=pdf.x + self._k), df.schema
                    )

            dag = self.dag()
            a = dag.df([[1], [2]], "x:long")
            a.transform(AddK(10)).assert_eq(dag.df([[11], [12]], "x:long"))
            self.run(dag)

        def test_deterministic_checkpoint_complex_dag(self, tmp_path):
            # the checkpoint skip must key on the FULL upstream lineage
            calls: List[int] = []

            def expensive(df: pd.DataFrame) -> pd.DataFrame:
                calls.append(1)
                return df.assign(y=df.x * 2)

            conf = {"fugue.workflow.checkpoint.path": str(tmp_path)}

            def build(val: int) -> FugueWorkflow:
                dag = FugueWorkflow()
                a = dag.df([[val]], "x:long")
                b = dag.df([[val + 1]], "x:long")
                u = a.union(b, distinct=False)
                t = u.transform(
                    expensive, schema="*,y:long"
                ).deterministic_checkpoint()
                t.yield_dataframe_as("out", as_local=True)
                return dag

            key = "fugue.workflow.checkpoint.path"
            old_path = self.engine.conf.get(key, "")
            self.engine.conf[key] = conf[key]
            try:
                build(1).run(self.engine)
                build(1).run(self.engine)  # identical lineage: skipped
                assert len(calls) == 1, calls
                build(2).run(self.engine)  # different upstream: recomputed
                assert len(calls) == 2, calls
            finally:
                self.engine.conf[key] = old_path

        # ---- registry ----------------------------------------------------
        def test_registered_alias(self):
            def rt(df: pd.DataFrame) -> pd.DataFrame:
                return df.assign(via="alias")

            register_transformer("builtin_suite_alias", rt)
            dag = self.dag()
            a = dag.df([[1]], "x:long")
            b = a.transform("builtin_suite_alias", schema="*,via:str")
            b.assert_eq(dag.df([[1, "alias"]], "x:long,via:str"))
            self.run(dag)

        # ---- workflow determinism ---------------------------------------
        def test_workflow_determinism(self):
            def build() -> FugueWorkflow:
                dag = FugueWorkflow()
                a = dag.df([[1, "a"]], "x:long,y:str")
                b = a.partition_by("y").transform(
                    lambda df: df, schema="*"
                )
                return dag

            # identical construction code produces identical task uuids
            d1, d2 = build(), build()
            assert d1.__uuid__() == d2.__uuid__()
            dag3 = FugueWorkflow()
            dag3.df([[2, "b"]], "x:long,y:str")
            assert d1.__uuid__() != dag3.__uuid__()

        def test_runtime_exception_callsite(self):
            def bad(df: pd.DataFrame) -> pd.DataFrame:
                raise RuntimeError("user error")

            dag = self.dag()
            a = dag.df([[1]], "x:long")
            a.transform(bad, schema="*")
            with pytest.raises(RuntimeError, match="user error"):
                self.run(dag)

        # ---- df-level column ops (reference builtin_suite test_col_ops) --
        def test_col_ops(self):
            dag = self.dag()
            a = dag.df([[1, 10], [2, 20]], "x:long,y:long")
            aa = dag.df([[1, 10], [2, 20]], "xx:long,y:long")
            a.rename({"x": "xx"}).assert_eq(aa)
            a[["x"]].assert_eq(ArrayDataFrame([[1], [2]], "x:long"))
            a.drop(["y", "yy"], if_exists=True).assert_eq(
                ArrayDataFrame([[1], [2]], "x:long")
            )
            a[["x"]].rename({"x": "xx"}).assert_eq(
                ArrayDataFrame([[1], [2]], "xx:long")
            )
            a.alter_columns("x:str").assert_eq(
                ArrayDataFrame([["1", 10], ["2", 20]], "x:str,y:long")
            )
            self.run(dag)

        def test_create_df_equivalence(self):
            # dag.df and dag.create of the same engine frame build the SAME
            # deterministic spec (reference builtin_suite.py:106)
            src = self.engine.to_df(pd.DataFrame([[0]], columns=["a"]))
            dag1 = FugueWorkflow()
            dag1.df(src).show()
            dag2 = FugueWorkflow()
            dag2.create(src).show()
            assert dag1.__uuid__() == dag2.__uuid__()

        def test_transform_binary(self):
            # bytes columns round-trip through transformers (reference
            # builtin_suite.py:504)
            def tf(rows: Iterable[List[Any]]) -> Iterable[List[Any]]:
                for r in rows:
                    obj = pickle.loads(r[1])
                    obj[0] += r[0]
                    obj[1] += "x"
                    yield [r[0], pickle.dumps(obj)]

            dag = self.dag()
            a = dag.df([[1, pickle.dumps([0, "a"])]], "a:int,b:bytes")
            c = a.transform(tf, schema="*").persist()
            dag.df([[1, pickle.dumps([1, "ax"])]], "a:int,b:bytes").assert_eq(c)
            self.run(dag)

        def test_transform_iterable_dfs(self):
            # Iterable[pd.DataFrame] -> Iterator[pd.DataFrame], including
            # empty generators with and without partitioning (reference
            # builtin_suite.py:441 — the mapInPandas-critical shape)
            from typing import Iterator

            import pyarrow as pa

            # schema: *,c:int
            def mt_pandas(
                dfs: Iterable[pd.DataFrame], empty: bool = False
            ) -> Iterator[pd.DataFrame]:
                for df in dfs:
                    if not empty:
                        yield df.assign(c=2)

            dag = self.dag()
            a = dag.df([[1, 2], [3, 4]], "a:int,b:int")
            a.transform(mt_pandas).assert_eq(
                ArrayDataFrame([[1, 2, 2], [3, 4, 2]], "a:int,b:int,c:int")
            )
            a.transform(mt_pandas, params=dict(empty=True)).assert_eq(
                ArrayDataFrame([], "a:int,b:int,c:int")
            )
            a.partition(by=["a"]).transform(
                mt_pandas, params=dict(empty=True)
            ).assert_eq(ArrayDataFrame([], "a:int,b:int,c:int"))
            self.run(dag)

            # schema: a:long
            def mt_arrow(dfs: Iterable[pa.Table]) -> Iterator[pa.Table]:
                for df in dfs:
                    yield df.drop_columns(["b"])

            dag = self.dag()
            a = dag.df([[1, 2], [3, 4]], "a:long,b:int")
            a.transform(mt_arrow).assert_eq(
                ArrayDataFrame([[1], [3]], "a:long")
            )
            self.run(dag)

        def test_out_transform_annotations(self):
            # the out_transform annotation matrix (reference
            # builtin_suite.py:400-792): pandas, iterable-of-lists,
            # iterable-of-pandas, arrow, and Transformer-class variants
            from typing import Iterator

            import pyarrow as pa

            hits: List[str] = []

            def t_pandas(df: pd.DataFrame) -> None:
                hits.append("pandas")

            def t_rows(rows: Iterable[List[Any]]) -> None:
                for _ in rows:
                    pass
                hits.append("rows")

            def t_iter_pd(dfs: Iterable[pd.DataFrame]) -> None:
                for _ in dfs:
                    pass
                hits.append("iter_pd")

            def t_arrow(df: pa.Table) -> None:
                hits.append("arrow")

            def t_iter_arrow(dfs: Iterable[pa.Table]) -> None:
                for _ in dfs:
                    pass
                hits.append("iter_arrow")

            # yields are consumed and discarded by out_transform
            def t_gen(df: pd.DataFrame) -> Iterator[pd.DataFrame]:
                hits.append("gen")
                yield df

            dag = self.dag()
            a = dag.df([[1, 2], [3, 4]], "a:int,b:int")
            for f in (t_pandas, t_rows, t_iter_pd, t_arrow, t_iter_arrow):
                a.out_transform(f)
            a.out_transform(t_gen)
            self.run(dag)
            assert set(hits) >= {
                "pandas", "rows", "iter_pd", "arrow", "iter_arrow", "gen"
            }, hits

        def test_transform_annotation_matrix(self):
            # the INPUT x OUTPUT annotation matrix of transform()
            # (reference builtin_suite.py:400-511): arrow in/out,
            # dict-rows in/out, rows->pandas, pandas->rows — every
            # combination round-trips values and nulls
            from typing import Dict as _Dict, Iterator

            import pyarrow as pa
            import pyarrow.compute  # noqa: F401  (pa.compute below)

            dag = self.dag()
            a = dag.df([[1, "a"], [2, None]], "x:long,y:str")

            def arrow_in_out(df: pa.Table) -> pa.Table:
                return df.set_column(
                    0, "x", pa.compute.add(df.column("x"), 10)
                )

            dag.df([[1, "a"], [2, None]], "x:long,y:str").transform(
                arrow_in_out, schema="*"
            ).assert_eq(dag.df([[11, "a"], [12, None]], "x:long,y:str"))

            def dicts_in_rows_out(
                rows: Iterable[_Dict[str, Any]],
            ) -> List[List[Any]]:
                return [[r["x"] * 2, r["y"]] for r in rows]

            a.transform(dicts_in_rows_out, schema="x:long,y:str").assert_eq(
                dag.df([[2, "a"], [4, None]], "x:long,y:str")
            )

            def rows_in_pandas_out(
                rows: List[List[Any]],
            ) -> pd.DataFrame:
                return pd.DataFrame(
                    {"x": [r[0] for r in rows], "y": [r[1] for r in rows]}
                )

            a.transform(rows_in_pandas_out, schema="x:long,y:str").assert_eq(
                dag.df([[1, "a"], [2, None]], "x:long,y:str")
            )

            def pandas_in_dicts_out(
                df: pd.DataFrame,
            ) -> Iterator[_Dict[str, Any]]:
                for _, r in df.iterrows():
                    yield dict(x=int(r["x"]) + 100, y=r["y"])

            a.transform(pandas_in_dicts_out, schema="x:long,y:str").assert_eq(
                dag.df([[101, "a"], [102, None]], "x:long,y:str")
            )
            self.run(dag)

        def test_processor_validation(self):
            # processors carry the same validation-comment machinery as
            # transformers (reference builtin_suite.py:1429)
            # partitionby_has: k
            def p(df: pd.DataFrame) -> pd.DataFrame:
                return df

            dag = self.dag()
            a = dag.df([[1, "a"]], "x:long,k:str")
            a.process(p, schema="x:long,k:str")
            with pytest.raises(FugueWorkflowCompileValidationError):
                self.run(dag)
            # satisfying the rule runs clean
            dag = self.dag()
            a = dag.df([[1, "a"]], "x:long,k:str")
            a.partition(by=["k"]).process(p, schema="x:long,k:str")
            self.run(dag)

        def test_outputter_validation(self):
            # input_has is a RUNTIME validation on outputters
            # (reference builtin_suite.py:1476)
            from fugue_tpu.exceptions import (
                FugueWorkflowRuntimeValidationError,
            )

            # input_has: zz
            def out(df: pd.DataFrame) -> None:
                pass

            dag = self.dag()
            a = dag.df([[1]], "x:long")
            a.output(out)
            with pytest.raises(FugueWorkflowRuntimeValidationError):
                self.run(dag)

            # input_has: x
            def out2(df: pd.DataFrame) -> None:
                assert list(df.columns) == ["x"]

            dag = self.dag()
            a = dag.df([[1]], "x:long")
            a.output(out2)
            self.run(dag)

        def test_cotransform_key_access(self):
            # per-group key values through the cursor in a class-based
            # cotransformer (reference builtin_suite.py:595-632)
            from fugue_tpu.extensions import CoTransformer

            class KeyAware(CoTransformer):
                def get_output_schema(self, dfs: DataFrames) -> Any:
                    return "k:str,na:long,nb:long"

                def transform(self, dfs: DataFrames) -> LocalDataFrame:
                    k = self.cursor.key_value_dict["k"]
                    return ArrayDataFrame(
                        [[k, dfs[0].count(), dfs[1].count()]],
                        "k:str,na:long,nb:long",
                    )

            dag = self.dag()
            a = dag.df([["x", 1], ["x", 2], ["y", 3]], "k:str,v:long")
            b = dag.df([["x", 10]], "k:str,w:long")
            z = a.partition_by("k").zip(b, how="left_outer")
            res = z.transform(KeyAware)
            res.assert_eq(
                dag.df([["x", 2, 1], ["y", 1, 0]], "k:str,na:long,nb:long")
            )
            self.run(dag)

        def test_transform_schema_expressions(self):
            # schema hint arithmetic: *, +col, -col and replacements
            # (reference builtin_suite.py transform schema handling)
            dag = self.dag()
            a = dag.df([[1, "a", 2.0]], "x:long,y:str,z:double")

            def add(df: pd.DataFrame) -> pd.DataFrame:
                return df.assign(w=1)

            dag.df([[1, "a", 2.0]], "x:long,y:str,z:double").transform(
                add, schema="*,w:long"
            ).assert_eq(
                dag.df([[1, "a", 2.0, 1]], "x:long,y:str,z:double,w:long")
            )

            def drop_y(df: pd.DataFrame) -> pd.DataFrame:
                return df.drop(columns=["y"])

            a.transform(drop_y, schema="*,-y").assert_eq(
                dag.df([[1, 2.0]], "x:long,z:double")
            )
            self.run(dag)
