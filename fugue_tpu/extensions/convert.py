"""Interfaceless converters: accept a class, an instance, a plain function
(schema via argument or ``# schema:`` comment) or a registered alias, and
produce extension objects (reference fugue/extensions/*/convert.py).

Signature acceptance is validated by regex over the one-letter param codes of
DataFrameFunctionWrapper (reference convert.py:328-560 pattern)."""

import copy
from typing import Any, Callable, Dict, List, Optional

from fugue_tpu.exceptions import FugueInterfacelessError
from fugue_tpu.dataframe import DataFrame, DataFrames, LocalDataFrame
from fugue_tpu.dataframe.function_wrapper import DataFrameFunctionWrapper
from fugue_tpu.extensions.interfaces import (
    CoTransformer,
    Creator,
    OutputCoTransformer,
    Outputter,
    OutputTransformer,
    Processor,
    Transformer,
)
from fugue_tpu.extensions.schema_hint import apply_schema_hint, parse_comment_annotation
from fugue_tpu.extensions.validation import (
    parse_validation_rules_from_comment,
    validate_rules,
)
from fugue_tpu.plugins import fugue_plugin
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.hash import to_uuid

class ExtensionConvertError(FugueInterfacelessError, ValueError):
    """An object can't be adapted into the requested extension
    (ValueError kept for pre-hierarchy callers)."""


_DF = "[dlpqrRmMPQj]"

_REGISTRIES: Dict[str, Dict[str, Any]] = {
    "creator": {},
    "processor": {},
    "outputter": {},
    "transformer": {},
    "output_transformer": {},
    "cotransformer": {},
    "output_cotransformer": {},
}


def _register(kind: str, name: str, extension: Any, on_dup: str = "overwrite") -> None:
    reg = _REGISTRIES[kind]
    if name in reg:
        if on_dup == "throw":
            raise KeyError(f"{kind} {name} already registered")
        if on_dup == "ignore":
            return
    reg[name] = extension


def register_creator(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _register("creator", alias, obj, on_dup)


def register_processor(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _register("processor", alias, obj, on_dup)


def register_outputter(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _register("outputter", alias, obj, on_dup)


def register_transformer(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _register("transformer", alias, obj, on_dup)


def register_output_transformer(alias: str, obj: Any, on_dup: str = "overwrite") -> None:
    _register("output_transformer", alias, obj, on_dup)


# ---- parse plugins (backends add namespaced creators like "myio:...") ------
@fugue_plugin
def parse_creator(obj: Any) -> Any:
    return obj


@fugue_plugin
def parse_processor(obj: Any) -> Any:
    return obj


@fugue_plugin
def parse_outputter(obj: Any) -> Any:
    return obj


@fugue_plugin
def parse_transformer(obj: Any) -> Any:
    return obj


@fugue_plugin
def parse_output_transformer(obj: Any) -> Any:
    return obj


# ---- function-backed extensions -------------------------------------------
class _FuncExtension:
    """Shared machinery for _FuncAs* wrappers."""

    def __init__(self, wrapper: DataFrameFunctionWrapper, validation: Dict[str, Any]):
        self._wrapper = wrapper
        self._validation = validation

    @property
    def validation_rules(self) -> Dict[str, Any]:
        return self._validation

    @property
    def wrapper(self) -> DataFrameFunctionWrapper:
        return self._wrapper

    def __uuid__(self) -> str:
        return to_uuid(type(self).__name__, self._wrapper.func, self._validation)

    def _ctx(self) -> Dict[str, Any]:
        return dict(
            callback=getattr(self, "_callback", None),
            engine=getattr(self, "_execution_engine", None),
        )


class _FuncAsTransformer(_FuncExtension, Transformer):
    """Plain function -> Transformer (reference convert.py:328)."""

    def __init__(
        self, wrapper: DataFrameFunctionWrapper, schema: Any, validation: Dict[str, Any]
    ):
        super().__init__(wrapper, validation)
        self._schema_hint = schema

    def get_output_schema(self, df: DataFrame) -> Any:
        return apply_schema_hint(df.schema, self._schema_hint)

    def get_format_hint(self) -> Optional[str]:
        return self._wrapper.get_format_hint()

    def transform(self, df: LocalDataFrame) -> LocalDataFrame:
        return self._wrapper.run(
            [df], dict(self.params), output_schema=self.output_schema, ctx=self._ctx()
        )

    def __uuid__(self) -> str:
        return to_uuid(super().__uuid__(), str(self._schema_hint))

    @staticmethod
    def from_func(
        func: Callable, schema: Any, validation: Dict[str, Any]
    ) -> "_FuncAsTransformer":
        if schema is None:
            schema = parse_comment_annotation(func, "schema")
        assert_or_throw(
            schema is not None,
            ExtensionConvertError(f"schema hint is required for transformer {func}"),
        )
        validation = dict(parse_validation_rules_from_comment(func), **validation)
        wrapper = DataFrameFunctionWrapper(
            func, f"^{_DF}[fF]?x*$", f"^{_DF}$"
        )
        return _FuncAsTransformer(wrapper, schema, validate_rules(validation))


class _FuncAsOutputTransformer(_FuncExtension, OutputTransformer):
    def __init__(self, wrapper: DataFrameFunctionWrapper, validation: Dict[str, Any]):
        super().__init__(wrapper, validation)

    def get_format_hint(self) -> Optional[str]:
        return self._wrapper.get_format_hint()

    def process(self, df: LocalDataFrame) -> None:
        self._wrapper.run(
            [df], dict(self.params), output=False, ctx=self._ctx()
        )

    @staticmethod
    def from_func(
        func: Callable, validation: Dict[str, Any]
    ) -> "_FuncAsOutputTransformer":
        validation = dict(parse_validation_rules_from_comment(func), **validation)
        wrapper = DataFrameFunctionWrapper(func, f"^{_DF}[fF]?x*$", "^[dlpqrRmMPQjn]$")
        return _FuncAsOutputTransformer(wrapper, validate_rules(validation))


class _FuncAsCoTransformer(_FuncExtension, CoTransformer):
    def __init__(
        self, wrapper: DataFrameFunctionWrapper, schema: Any, validation: Dict[str, Any]
    ):
        super().__init__(wrapper, validation)
        self._schema_hint = schema

    def get_output_schema(self, dfs: DataFrames) -> Any:
        if isinstance(self._schema_hint, str) and "*" in self._schema_hint:
            raise ValueError("cotransformer schema hint can't use *")
        return Schema(self._schema_hint) if isinstance(self._schema_hint, str) \
            else self._schema_hint

    def get_format_hint(self) -> Optional[str]:
        return self._wrapper.get_format_hint()

    def transform(self, dfs: DataFrames) -> LocalDataFrame:
        if self._wrapper.input_code.startswith("c"):
            args: List[Any] = [dfs]
        else:
            args = list(dfs.values())
        return self._wrapper.run(
            args, dict(self.params), output_schema=self.output_schema, ctx=self._ctx()
        )

    def __uuid__(self) -> str:
        return to_uuid(super().__uuid__(), str(self._schema_hint))

    @staticmethod
    def from_func(
        func: Callable, schema: Any, validation: Dict[str, Any]
    ) -> "_FuncAsCoTransformer":
        if schema is None:
            schema = parse_comment_annotation(func, "schema")
        assert_or_throw(
            schema is not None,
            ExtensionConvertError(
                f"schema hint is required for cotransformer {func}"
            ),
        )
        validation = dict(parse_validation_rules_from_comment(func), **validation)
        wrapper = DataFrameFunctionWrapper(
            func, f"^(c|{_DF}+)[fF]?x*$", f"^{_DF}$"
        )
        return _FuncAsCoTransformer(wrapper, schema, validate_rules(validation))


class _FuncAsOutputCoTransformer(_FuncExtension, OutputCoTransformer):
    def __init__(self, wrapper: DataFrameFunctionWrapper, validation: Dict[str, Any]):
        super().__init__(wrapper, validation)

    def get_format_hint(self) -> Optional[str]:
        return self._wrapper.get_format_hint()

    def process(self, dfs: DataFrames) -> None:
        if self._wrapper.input_code.startswith("c"):
            args: List[Any] = [dfs]
        else:
            args = list(dfs.values())
        self._wrapper.run(args, dict(self.params), output=False, ctx=self._ctx())

    @staticmethod
    def from_func(
        func: Callable, validation: Dict[str, Any]
    ) -> "_FuncAsOutputCoTransformer":
        validation = dict(parse_validation_rules_from_comment(func), **validation)
        wrapper = DataFrameFunctionWrapper(
            func, f"^(c|{_DF}+)[fF]?x*$", "^[dlpqrRmMPQjn]$"
        )
        return _FuncAsOutputCoTransformer(wrapper, validate_rules(validation))


class _FuncAsCreator(_FuncExtension, Creator):
    def __init__(self, wrapper: DataFrameFunctionWrapper, schema: Any):
        super().__init__(wrapper, {})
        self._schema_hint = schema

    def create(self) -> DataFrame:
        schema = None if self._schema_hint is None else Schema(self._schema_hint)
        res = self._wrapper.run(
            [], dict(self.params),
            output_schema=schema,
            ctx=dict(engine=getattr(self, "_execution_engine", None)),
        )
        if isinstance(res, DataFrame):
            return res
        return self.execution_engine.to_df(
            res, schema
        )

    def __uuid__(self) -> str:
        return to_uuid(super().__uuid__(), str(self._schema_hint))

    @staticmethod
    def from_func(func: Callable, schema: Any) -> "_FuncAsCreator":
        if schema is None:
            schema = parse_comment_annotation(func, "schema")
        wrapper = DataFrameFunctionWrapper(func, "^e?x*$", f"^{_DF}$")
        return _FuncAsCreator(wrapper, schema)


class _FuncAsProcessor(_FuncExtension, Processor):
    def __init__(
        self,
        wrapper: DataFrameFunctionWrapper,
        schema: Any,
        validation: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(wrapper, validation or {})
        self._schema_hint = schema

    def process(self, dfs: DataFrames) -> DataFrame:
        if self._wrapper.input_code.replace("e", "").startswith("c"):
            args: List[Any] = [dfs]
        else:
            args = [df.as_local() for df in dfs.values()]
        schema = None if self._schema_hint is None else Schema(self._schema_hint)
        res = self._wrapper.run(
            args,
            dict(self.params),
            output_schema=schema,
            ctx=dict(engine=getattr(self, "_execution_engine", None)),
        )
        if isinstance(res, DataFrame):
            return res
        return self.execution_engine.to_df(res, schema)

    def __uuid__(self) -> str:
        return to_uuid(super().__uuid__(), str(self._schema_hint))

    @staticmethod
    def from_func(func: Callable, schema: Any) -> "_FuncAsProcessor":
        if schema is None:
            schema = parse_comment_annotation(func, "schema")
        validation = validate_rules(parse_validation_rules_from_comment(func))
        wrapper = DataFrameFunctionWrapper(
            func, f"^e?(c|{_DF}+)x*$", f"^{_DF}$"
        )
        return _FuncAsProcessor(wrapper, schema, validation)


class _FuncAsOutputter(_FuncExtension, Outputter):
    def process(self, dfs: DataFrames) -> None:
        if self._wrapper.input_code.replace("e", "").startswith("c"):
            args: List[Any] = [dfs]
        else:
            args = [df.as_local() for df in dfs.values()]
        self._wrapper.run(
            args, dict(self.params), output=False,
            ctx=dict(engine=getattr(self, "_execution_engine", None)),
        )

    @staticmethod
    def from_func(func: Callable) -> "_FuncAsOutputter":
        validation = validate_rules(parse_validation_rules_from_comment(func))
        wrapper = DataFrameFunctionWrapper(func, f"^e?(c|{_DF}+)x*$", "^.*$")
        return _FuncAsOutputter(wrapper, validation)


# ---- converters ------------------------------------------------------------
def _lookup(kind: str, name: str) -> Optional[Any]:
    return _REGISTRIES[kind].get(name)


def _to_extension(
    obj: Any,
    kind: str,
    base: type,
    from_func: Callable,
    parse: Callable,
    copy_instance: bool = True,
) -> Any:
    obj = parse(obj)
    if isinstance(obj, str):
        registered = _lookup(kind, obj)
        assert_or_throw(
            registered is not None, ValueError(f"{obj!r} is not a registered {kind}")
        )
        return _to_extension(registered, kind, base, from_func, parse, copy_instance)
    if isinstance(obj, base):
        return copy.copy(obj) if copy_instance else obj
    if isinstance(obj, type) and issubclass(obj, base):
        return obj()
    if callable(obj):
        return from_func(obj)
    raise ExtensionConvertError(f"can't convert {obj!r} to {kind}")


def _to_creator(obj: Any, schema: Any = None) -> Creator:
    return _to_extension(
        obj, "creator", Creator, lambda f: _FuncAsCreator.from_func(f, schema),
        parse_creator,
    )


def _to_processor(obj: Any, schema: Any = None) -> Processor:
    return _to_extension(
        obj, "processor", Processor, lambda f: _FuncAsProcessor.from_func(f, schema),
        parse_processor,
    )


def _to_outputter(obj: Any) -> Outputter:
    return _to_extension(
        obj, "outputter", Outputter, _FuncAsOutputter.from_func, parse_outputter
    )


def _to_transformer(
    obj: Any, schema: Any = None, validation: Optional[Dict[str, Any]] = None
) -> Transformer:
    """Convert to Transformer OR CoTransformer (dispatch on signature: a
    DataFrames/multi-df first param means cotransform)."""
    validation = validation or {}
    obj = parse_transformer(obj)
    if isinstance(obj, str):
        registered = _lookup("transformer", obj) or _lookup("cotransformer", obj)
        assert_or_throw(
            registered is not None,
            ValueError(f"{obj!r} is not a registered transformer"),
        )
        return _to_transformer(registered, schema, validation)
    if isinstance(obj, (Transformer, CoTransformer)):
        return copy.copy(obj)  # type: ignore
    if isinstance(obj, type) and issubclass(obj, (Transformer, CoTransformer)):
        return obj()  # type: ignore
    if callable(obj):
        if _is_cotransform_func(obj):
            return _FuncAsCoTransformer.from_func(obj, schema, validation)  # type: ignore
        return _FuncAsTransformer.from_func(obj, schema, validation)
    raise ExtensionConvertError(f"can't convert {obj!r} to transformer")


def _to_output_transformer(
    obj: Any, validation: Optional[Dict[str, Any]] = None
) -> Transformer:
    validation = validation or {}
    obj = parse_output_transformer(obj)
    if isinstance(obj, str):
        registered = (
            _lookup("output_transformer", obj)
            or _lookup("output_cotransformer", obj)
            or _lookup("transformer", obj)
        )
        assert_or_throw(
            registered is not None,
            ValueError(f"{obj!r} is not a registered output transformer"),
        )
        return _to_output_transformer(registered, validation)
    if isinstance(obj, (OutputTransformer, OutputCoTransformer)):
        return copy.copy(obj)  # type: ignore
    if isinstance(obj, type) and issubclass(
        obj, (OutputTransformer, OutputCoTransformer)
    ):
        return obj()  # type: ignore
    if callable(obj):
        if _is_cotransform_func(obj):
            return _FuncAsOutputCoTransformer.from_func(obj, validation)  # type: ignore
        return _FuncAsOutputTransformer.from_func(obj, validation)
    raise ExtensionConvertError(f"can't convert {obj!r} to output transformer")


def _is_cotransform_func(func: Callable) -> bool:
    try:
        wrapper = DataFrameFunctionWrapper(func)
    except TypeError:
        return False
    code = wrapper.input_code
    dfs = "".join(c for c in code if c in "dlpqrRmMPQjc")  # mirrors _DF
    return code.startswith("c") or len(dfs) > 1


# ---- decorators ------------------------------------------------------------
def creator(schema: Any = None) -> Callable:
    def deco(func: Callable) -> "_FuncAsCreator":
        return _FuncAsCreator.from_func(func, schema)

    return deco


def processor(schema: Any = None) -> Callable:
    def deco(func: Callable) -> "_FuncAsProcessor":
        return _FuncAsProcessor.from_func(func, schema)

    return deco


def outputter() -> Callable:
    def deco(func: Callable) -> "_FuncAsOutputter":
        return _FuncAsOutputter.from_func(func)

    return deco


def transformer(schema: Any, **validation: Any) -> Callable:
    def deco(func: Callable) -> "_FuncAsTransformer":
        return _FuncAsTransformer.from_func(func, schema, validation)

    return deco


def output_transformer(**validation: Any) -> Callable:
    def deco(func: Callable) -> "_FuncAsOutputTransformer":
        return _FuncAsOutputTransformer.from_func(func, validation)

    return deco


def cotransformer(schema: Any, **validation: Any) -> Callable:
    def deco(func: Callable) -> "_FuncAsCoTransformer":
        return _FuncAsCoTransformer.from_func(func, schema, validation)

    return deco


def output_cotransformer(**validation: Any) -> Callable:
    def deco(func: Callable) -> "_FuncAsOutputCoTransformer":
        return _FuncAsOutputCoTransformer.from_func(func, validation)

    return deco
