"""Schema hints + comment annotations for interfaceless extensions
(reference fugue/_utils/interfaceless.py:9-40)."""

import inspect
import re
from typing import Any, Dict, List, Optional

from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw

_COMMENT_ANNO_RE = re.compile(r"^\s*#\s*(\w+)\s*:\s*(.*)$")


def parse_comment_annotation(func: Any, key: str) -> Optional[str]:
    """Find ``# key: value`` comment lines right above a function def."""
    annos = parse_comment_annotations(func)
    return annos.get(key)


def parse_comment_annotations(func: Any) -> Dict[str, str]:
    """Scan upward from the function's ``def`` line: consecutive comment
    lines (and decorators) directly above it carry the annotations."""
    try:
        file = inspect.getsourcefile(func)
        _, lineno = inspect.getsourcelines(func)  # 1-based first line of def
        assert file is not None
        with open(file, "r") as fp:
            all_lines = fp.readlines()
    except (OSError, TypeError, AssertionError):
        return {}
    res: Dict[str, str] = {}
    i = lineno - 2  # the line above `def`
    while i >= 0:
        stripped = all_lines[i].strip()
        if stripped.startswith("@"):  # decorators between comments and def
            i -= 1
            continue
        m = _COMMENT_ANNO_RE.match(all_lines[i])
        if m is None:
            break
        # nearest annotation wins on duplicates
        res.setdefault(m.group(1), m.group(2).strip())
        i -= 1
    return res


def split_top_level(expr: str) -> List[str]:
    """Split on commas not nested inside []{}<>()."""
    parts: List[str] = []
    depth = 0
    buf = ""
    for ch in expr:
        if ch in "[{(<":
            depth += 1
        elif ch in "]})>":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append(buf)
            buf = ""
        else:
            buf += ch
    if buf.strip() != "":
        parts.append(buf)
    return [p.strip() for p in parts]


def apply_schema_hint(input_schema: Schema, hint: Any) -> Schema:
    """Resolve a transformer's schema hint against the input schema.

    Supported: plain expressions (``a:int,b:str``), ``*`` (all inputs),
    ``-col`` (exclusion), ``+a:int`` (addition), mixed with commas:
    ``"*,c:double"``, ``"*,-b"``.
    """
    if isinstance(hint, Schema):
        return hint
    if callable(hint):
        return Schema(hint(input_schema))
    assert_or_throw(isinstance(hint, str), ValueError(f"invalid schema hint {hint!r}"))
    if "*" not in hint and not hint.startswith(("+", "-")):
        return Schema(hint)
    return input_schema.transform(*split_top_level(hint))
