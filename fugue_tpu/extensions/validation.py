"""Compile/runtime validation rules for extensions (reference
fugue/extensions/_utils.py): declared in params or function comments.

Keys: ``input_has`` (columns present), ``input_is`` (schema equals),
``partitionby_has``/``partitionby_is``, ``presort_has``/``presort_is``.
"""

from typing import Any, Dict, List

from fugue_tpu.collections.partition import PartitionSpec, parse_presort_exp
from fugue_tpu.schema import Schema
from fugue_tpu.exceptions import (
    FugueWorkflowCompileError,
    FugueWorkflowCompileValidationError,
    FugueWorkflowRuntimeValidationError,
)
from fugue_tpu.utils.assertion import assert_or_throw

class InvalidValidationRuleError(FugueWorkflowCompileError, ValueError):
    """Unknown validation rule key (ValueError kept for pre-hierarchy
    callers)."""


class CompileValidationError(FugueWorkflowCompileValidationError, ValueError):
    """Compile-time validation failure (ValueError kept for
    pre-hierarchy callers)."""


class RuntimeValidationError(FugueWorkflowRuntimeValidationError, ValueError):
    """Runtime validation failure (ValueError kept for pre-hierarchy
    callers)."""


_VALID_KEYS = {
    "input_has",
    "input_is",
    "partitionby_has",
    "partitionby_is",
    "presort_has",
    "presort_is",
}


def parse_validation_rules_from_comment(func: Any) -> Dict[str, Any]:
    from fugue_tpu.extensions.schema_hint import parse_comment_annotations

    annos = parse_comment_annotations(func)
    return {k: v for k, v in annos.items() if k in _VALID_KEYS}


def _to_list(v: Any) -> List[str]:
    if isinstance(v, str):
        return [x.strip() for x in v.split(",") if x.strip() != ""]
    return list(v)


def validate_rules(rules: Dict[str, Any]) -> Dict[str, Any]:
    for k in rules:
        assert_or_throw(
            k in _VALID_KEYS,
            InvalidValidationRuleError(f"invalid validation rule {k}"),
        )
    return rules


def validate_partition_spec(rules: Dict[str, Any], spec: PartitionSpec) -> None:
    """Compile-time: the partition spec must satisfy the extension's rules."""
    if "partitionby_has" in rules:
        req = _to_list(rules["partitionby_has"])
        assert_or_throw(
            all(k in spec.partition_by for k in req),
            CompileValidationError(
                f"partitionby_has: {req} required but got {spec.partition_by}"
            ),
        )
    if "partitionby_is" in rules:
        req = _to_list(rules["partitionby_is"])
        assert_or_throw(
            req == spec.partition_by,
            CompileValidationError(f"partitionby_is: expected {req} got {spec.partition_by}"),
        )
    if "presort_has" in rules:
        req = parse_presort_exp(rules["presort_has"])
        assert_or_throw(
            all(k in spec.presort and spec.presort[k] == v for k, v in req.items()),
            CompileValidationError(f"presort_has: {req} required but got {spec.presort}"),
        )
    if "presort_is" in rules:
        req = parse_presort_exp(rules["presort_is"])
        assert_or_throw(
            req == spec.presort,
            CompileValidationError(f"presort_is: expected {req} got {spec.presort}"),
        )


def validate_input_schema(rules: Dict[str, Any], schema: Schema) -> None:
    """Runtime: the input dataframe must satisfy the extension's rules."""
    if "input_has" in rules:
        req = _to_list(rules["input_has"])
        missing = [c for c in req if c not in schema]
        assert_or_throw(
            len(missing) == 0,
            RuntimeValidationError(f"input_has: missing columns {missing} in {schema}"),
        )
    if "input_is" in rules:
        assert_or_throw(
            schema == Schema(rules["input_is"]),
            RuntimeValidationError(f"input_is: expected {rules['input_is']} got {schema}"),
        )
