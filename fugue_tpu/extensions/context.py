"""ExtensionContext: what every extension sees at runtime (reference
fugue/extensions/context.py:13-121)."""

from typing import Any, Dict, Optional

from fugue_tpu.collections.partition import PartitionCursor, PartitionSpec
from fugue_tpu.rpc import RPCClient, RPCServer
from fugue_tpu.schema import Schema
from fugue_tpu.utils.params import ParamDict


class ExtensionContext:
    """Mixin giving extensions access to params, engine, partition info,
    callback channel and validation rules. The framework fills the underlying
    attributes before invoking the extension."""

    @property
    def params(self) -> ParamDict:
        return getattr(self, "_params", ParamDict())

    @property
    def workflow_conf(self) -> ParamDict:
        return getattr(self, "_workflow_conf", ParamDict())

    @property
    def execution_engine(self) -> Any:
        e = getattr(self, "_execution_engine", None)
        assert e is not None, "execution_engine not set"
        return e

    @property
    def output_schema(self) -> Schema:
        s = getattr(self, "_output_schema", None)
        assert s is not None, "output_schema not set"
        return s

    @property
    def key_schema(self) -> Schema:
        return getattr(self, "_key_schema", Schema())

    @property
    def partition_spec(self) -> PartitionSpec:
        return getattr(self, "_partition_spec", PartitionSpec())

    @property
    def cursor(self) -> PartitionCursor:
        c = getattr(self, "_cursor", None)
        assert c is not None, "cursor not set"
        return c

    @property
    def has_callback(self) -> bool:
        return getattr(self, "_callback", None) is not None

    @property
    def callback(self) -> RPCClient:
        c = getattr(self, "_callback", None)
        assert c is not None, "callback not set"
        return c

    @property
    def rpc_server(self) -> RPCServer:
        s = getattr(self, "_rpc_server", None)
        assert s is not None, "rpc_server not set"
        return s

    @property
    def validation_rules(self) -> Dict[str, Any]:
        return {}

    def validate_on_compile(self) -> None:
        """Hook: raise on invalid config at DAG build time."""
        pass

    def validate_on_runtime(self, data: Any) -> None:
        """Hook: raise on invalid input at execution time."""
        pass
