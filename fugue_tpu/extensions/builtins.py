"""Builtin extensions: the operations workflow tasks lower to (reference
fugue/extensions/_builtins/{creators,processors,outputters}.py)."""

from typing import Any, Callable, Dict, List, Optional, Type

from fugue_tpu.collections.partition import PartitionCursor, PartitionSpec
from fugue_tpu.collections.sql import StructuredRawSQL
from fugue_tpu.column.expressions import ColumnExpr
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.dataframe import (
    ArrayDataFrame,
    DataFrame,
    DataFrames,
    LocalDataFrame,
)
from fugue_tpu.dataframe.utils import df_eq
from fugue_tpu.extensions.convert import (
    _to_output_transformer,
    _to_transformer,
)
from fugue_tpu.extensions.interfaces import (
    CoTransformer,
    Creator,
    OUTPUT_TRANSFORMER_DUMMY_SCHEMA,
    Outputter,
    Processor,
    Transformer,
)
from fugue_tpu.extensions.validation import (
    validate_input_schema,
    validate_partition_spec,
)
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


# ---- creators --------------------------------------------------------------
class Load(Creator):
    def create(self) -> DataFrame:
        kwargs = self.params.get("params", dict())
        path = self.params.get_or_throw("path", object)
        format_hint = self.params.get("fmt", "")
        columns = self.params.get("columns", None)
        return self.execution_engine.load_df(
            path=path,
            format_hint=format_hint if format_hint != "" else None,
            columns=columns,
            **kwargs,
        )


class CreateData(Creator):
    def create(self) -> DataFrame:
        data = self.params.get_or_throw("data", object)
        schema = self.params.get("schema", None)
        return self.execution_engine.to_df(
            data, schema=None if schema is None else Schema(schema)
        )


# ---- transform lowering ----------------------------------------------------
class _TransformerRunner:
    """Worker-side runner: fills cursor/context, converts, applies the user
    transformer, optionally swallowing per-partition failures (reference
    _builtins/processors.py:322)."""

    def __init__(
        self,
        df: DataFrame,
        transformer: Transformer,
        ignore_errors: List[type],
    ):
        self.schema = df.schema
        self.metadata = df.metadata if df.has_metadata else None
        self.transformer = transformer
        self.ignore_errors = tuple(ignore_errors)

    def run(self, cursor: PartitionCursor, df: LocalDataFrame) -> LocalDataFrame:
        self.transformer._cursor = cursor  # type: ignore
        df._metadata = self.metadata
        if len(self.ignore_errors) == 0:
            return self.transformer.transform(df)
        try:
            return self.transformer.transform(df).as_local_bounded()
        except self.ignore_errors:
            return ArrayDataFrame([], self.transformer.output_schema)

    def on_init(self, partition_no: int, df: DataFrame) -> None:
        s = self.transformer.partition_spec
        self.transformer._cursor = s.get_cursor(self.schema, partition_no)  # type: ignore
        self.transformer.on_init(df)


class _CoTransformerRunner:
    def __init__(
        self,
        df: DataFrame,
        transformer: CoTransformer,
        ignore_errors: List[type],
    ):
        self.schema = df.schema
        self.transformer = transformer
        self.ignore_errors = tuple(ignore_errors)

    def run(self, cursor: PartitionCursor, dfs: DataFrames) -> LocalDataFrame:
        self.transformer._cursor = cursor  # type: ignore
        if len(self.ignore_errors) == 0:
            return self.transformer.transform(dfs)
        try:
            return self.transformer.transform(dfs).as_local_bounded()
        except self.ignore_errors:
            return ArrayDataFrame([], self.transformer.output_schema)

    def on_init(self, partition_no: int, dfs: DataFrames) -> None:
        s = self.transformer.partition_spec
        self.transformer._cursor = s.get_cursor(self.schema, partition_no)  # type: ignore
        self.transformer.on_init(dfs)


class RunTransformer(Processor):
    """Lower transform() to map_dataframe / comap (reference processors.py:23)."""

    def process(self, dfs: DataFrames) -> DataFrame:
        df = dfs[0]
        tf = _to_transformer(
            self.params.get_or_throw("transformer", object),
            self.params.get("schema", None),
        )
        return _lower_transform(self, df, tf)

    def _run_cotransform(
        self, df: DataFrame, tf: CoTransformer, ignore_errors: List[type]
    ) -> DataFrame:
        return _lower_cotransform(self, df, tf, ignore_errors)


def _lower_transform(host: Any, df: DataFrame, tf: Any) -> DataFrame:
    """Shared lowering used by RunTransformer and RunOutputTransformer:
    configure the transformer and dispatch to map_dataframe or comap."""
    tf._workflow_conf = host.execution_engine.conf
    tf._params = host.params.get("params", dict())
    tf._partition_spec = host.partition_spec
    rpc_handler = host.params.get("rpc_handler", None)
    if rpc_handler is not None:
        tf._callback = host.rpc_server.make_client(rpc_handler)
    ignore_errors = host.params.get("ignore_errors", [])
    validate_partition_spec(tf.validation_rules, host.partition_spec)
    if bool(df.metadata.get("serialized", False)):
        assert_or_throw(
            isinstance(tf, CoTransformer),
            TypeError(f"{tf} is not a CoTransformer but the input is zipped"),
        )
        return _lower_cotransform(host, df, tf, ignore_errors)
    assert_or_throw(
        isinstance(tf, Transformer), TypeError(f"{tf} is not a Transformer")
    )
    validate_input_schema(tf.validation_rules, df.schema)
    tf._key_schema = host.partition_spec.get_key_schema(df.schema)
    output_schema = Schema(tf.get_output_schema(df))
    tf._output_schema = output_schema
    runner = _TransformerRunner(df, tf, ignore_errors)
    fmt = getattr(tf, "get_format_hint", lambda: None)()
    return host.execution_engine.map_engine.map_dataframe(
        df,
        map_func=runner.run,
        output_schema=output_schema,
        partition_spec=host.partition_spec,
        on_init=runner.on_init,
        map_func_format_hint=fmt,
    )


def _lower_cotransform(
    host: Any, df: DataFrame, tf: CoTransformer, ignore_errors: List[type]
) -> DataFrame:
    from fugue_tpu.execution.execution_engine import (
        _ZIP_NAMES_META,
        _ZIP_SCHEMAS_META,
    )

    schemas = [Schema(s) for s in df.metadata[_ZIP_SCHEMAS_META]]
    names = df.metadata[_ZIP_NAMES_META]
    if any(n != "" for n in names):
        empty_dfs = DataFrames(
            {n: ArrayDataFrame([], s) for n, s in zip(names, schemas)}
        )
    else:
        empty_dfs = DataFrames([ArrayDataFrame([], s) for s in schemas])
    tf._key_schema = Schema(
        [df.schema[n] for n in df.schema.names
         if not n.startswith("_fugue_ser_")]
    )
    output_schema = Schema(tf.get_output_schema(empty_dfs))
    tf._output_schema = output_schema
    runner = _CoTransformerRunner(df, tf, ignore_errors)
    return host.execution_engine.comap(
        df,
        map_func=runner.run,
        output_schema=output_schema,
        partition_spec=host.partition_spec,
        on_init=runner.on_init,
    )


class RunJoin(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        if len(dfs) == 1:
            return dfs[0]
        how = self.params.get_or_throw("how", str)
        on = self.params.get("on", [])
        df = dfs[0]
        for i in range(1, len(dfs)):
            df = self.execution_engine.join(
                df, dfs[i], how=how, on=on if len(on) > 0 else None
            )
        return df


class RunSetOperation(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        if len(dfs) == 1:
            return dfs[0]
        how = self.params.get_or_throw("how", str)
        func: Callable = {
            "union": self.execution_engine.union,
            "subtract": self.execution_engine.subtract,
            "intersect": self.execution_engine.intersect,
        }[how]
        distinct = self.params.get("distinct", True)
        df = dfs[0]
        for i in range(1, len(dfs)):
            df = func(df, dfs[i], distinct=distinct)
        return df


class Distinct(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("distinct takes 1 df"))
        return self.execution_engine.distinct(dfs[0])


class Dropna(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("dropna takes 1 df"))
        return self.execution_engine.dropna(
            dfs[0],
            how=self.params.get("how", "any"),
            thresh=self.params.get_or_none("thresh", int),
            subset=self.params.get("subset", None),
        )


class Fillna(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("fillna takes 1 df"))
        return self.execution_engine.fillna(
            dfs[0],
            value=self.params.get_or_throw("value", object),
            subset=self.params.get("subset", None),
        )


class RunSQLSelect(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        statement = self.params.get_or_throw("statement", object)
        if isinstance(statement, str):
            statement = StructuredRawSQL([(False, statement)])
        engine = self.execution_engine.sql_engine
        return engine.select(dfs, statement)


class Zip(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        how = self.params.get("how", "inner")
        return self.execution_engine.zip(
            dfs,
            how=how,
            partition_spec=self.partition_spec,
            temp_path=self.params.get("temp_path", None),
            to_file_threshold=self.params.get("to_file_threshold", -1),
        )


class Select(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("select takes 1 df"))
        return self.execution_engine.select(
            dfs[0],
            cols=self.params.get_or_throw("columns", SelectColumns),
            where=self.params.get("where", None),
            having=self.params.get("having", None),
        )


class Filter(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("filter takes 1 df"))
        return self.execution_engine.filter(
            dfs[0], condition=self.params.get_or_throw("condition", ColumnExpr)
        )


class Assign(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("assign takes 1 df"))
        return self.execution_engine.assign(
            dfs[0], columns=self.params.get_or_throw("columns", list)
        )


class Aggregate(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("aggregate takes 1 df"))
        return self.execution_engine.aggregate(
            dfs[0],
            partition_spec=self.partition_spec,
            agg_cols=self.params.get_or_throw("columns", list),
        )


class Rename(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("rename takes 1 df"))
        return dfs[0].rename(self.params.get_or_throw("columns", dict))


class AlterColumns(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("alter_columns takes 1 df"))
        return dfs[0].alter_columns(self.params.get_or_throw("columns", object))


class DropColumns(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("drop takes 1 df"))
        if self.params.get("if_exists", False):
            columns = [
                c for c in self.params.get_or_throw("columns", list)
                if c in dfs[0].schema
            ]
            if len(columns) == 0:
                return dfs[0]
        else:
            columns = self.params.get_or_throw("columns", list)
        return dfs[0].drop(columns)


class SelectColumnsP(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("select_columns takes 1 df"))
        return dfs[0][self.params.get_or_throw("columns", list)]


class Sample(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("sample takes 1 df"))
        return self.execution_engine.sample(
            dfs[0],
            n=self.params.get_or_none("n", int),
            frac=self.params.get_or_none("frac", float),
            replace=self.params.get("replace", False),
            seed=self.params.get_or_none("seed", int),
        )


class Take(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("take takes 1 df"))
        return self.execution_engine.take(
            dfs[0],
            n=self.params.get_or_throw("n", int),
            presort=self.params.get("presort", ""),
            na_position=self.params.get("na_position", "last"),
            partition_spec=self.partition_spec,
        )


class SaveAndUse(Processor):
    def process(self, dfs: DataFrames) -> DataFrame:
        assert_or_throw(len(dfs) == 1, ValueError("save_and_use takes 1 df"))
        kwargs = self.params.get("params", dict())
        path = self.params.get_or_throw("path", str)
        format_hint = self.params.get("fmt", "")
        mode = self.params.get("mode", "overwrite")
        force_single = self.params.get("single", False)
        self.execution_engine.save_df(
            dfs[0], path=path,
            format_hint=format_hint if format_hint != "" else None,
            mode=mode, partition_spec=self.partition_spec,
            force_single=force_single, **kwargs,
        )
        return self.execution_engine.load_df(
            path, format_hint=format_hint if format_hint != "" else None
        )


# ---- outputters ------------------------------------------------------------
class Show(Outputter):
    def process(self, dfs: DataFrames) -> None:
        n = self.params.get("n", 10)
        with_count = self.params.get("with_count", False)
        title = self.params.get("title", "")
        for df in dfs.values():
            df.show(n, with_count, title if title != "" else None)


class AssertEqFunc(Outputter):
    def process(self, dfs: DataFrames) -> None:
        assert_or_throw(len(dfs) >= 2, ValueError("assert_eq requires >= 2 dfs"))
        expected = dfs[0]
        for i in range(1, len(dfs)):
            df_eq(
                expected,
                dfs[i],
                throw=True,
                check_order=self.params.get("check_order", False),
                check_schema=self.params.get("check_schema", True),
                digits=self.params.get("digits", 8),
            )


class AssertNotEqFunc(Outputter):
    def process(self, dfs: DataFrames) -> None:
        assert_or_throw(len(dfs) >= 2, ValueError("assert_not_eq requires >= 2 dfs"))
        expected = dfs[0]
        for i in range(1, len(dfs)):
            assert_or_throw(
                not df_eq(
                    expected,
                    dfs[i],
                    check_order=self.params.get("check_order", False),
                    check_schema=self.params.get("check_schema", True),
                ),
                AssertionError("dataframes are equal"),
            )


class Save(Outputter):
    def process(self, dfs: DataFrames) -> None:
        assert_or_throw(len(dfs) == 1, ValueError("save takes 1 df"))
        kwargs = self.params.get("params", dict())
        path = self.params.get_or_throw("path", str)
        format_hint = self.params.get("fmt", "")
        mode = self.params.get("mode", "overwrite")
        force_single = self.params.get("single", False)
        self.execution_engine.save_df(
            dfs[0],
            path=path,
            format_hint=format_hint if format_hint != "" else None,
            mode=mode,
            partition_spec=self.partition_spec,
            force_single=force_single,
            **kwargs,
        )


class RunOutputTransformer(Outputter):
    """Lower out_transform() to map (discarding output)."""

    def process(self, dfs: DataFrames) -> None:
        df = dfs[0]
        tf = _to_output_transformer(
            self.params.get_or_throw("transformer", object),
        )
        out = _lower_transform(self, df, tf)
        # materialize to force execution on lazy engines
        out.as_local()
