"""Extension interfaces (reference fugue/extensions/{creator,processor,
outputter,transformer}/*.py): the five extension kinds of the framework.

Driver side: Creator/Processor/Outputter run on the driver and can use the
full ExecutionEngine. Worker side: Transformer/CoTransformer run per logical
partition inside the map primitive (no engine access)."""

from abc import ABC, abstractmethod
from typing import Any

from fugue_tpu.dataframe import DataFrame, DataFrames, LocalDataFrame
from fugue_tpu.extensions.context import ExtensionContext


class Creator(ExtensionContext, ABC):
    """Generate a dataframe from nothing (load, create from config...)."""

    @abstractmethod
    def create(self) -> DataFrame:  # pragma: no cover - interface
        raise NotImplementedError


class Processor(ExtensionContext, ABC):
    """Driver-side dataframes -> dataframe (joins, repartition, ...)."""

    @abstractmethod
    def process(self, dfs: DataFrames) -> DataFrame:  # pragma: no cover
        raise NotImplementedError


class Outputter(ExtensionContext, ABC):
    """Driver-side dataframes -> side effect (save, show, assert...)."""

    @abstractmethod
    def process(self, dfs: DataFrames) -> None:  # pragma: no cover
        raise NotImplementedError


class Transformer(ExtensionContext, ABC):
    """Worker-side per-logical-partition map. ``get_output_schema`` runs on
    the driver; ``on_init`` once per physical partition; ``transform`` per
    logical partition (reference transformer.py:8)."""

    @abstractmethod
    def get_output_schema(self, df: DataFrame) -> Any:  # pragma: no cover
        raise NotImplementedError

    def on_init(self, df: DataFrame) -> None:  # pragma: no cover - hook
        pass

    @abstractmethod
    def transform(self, df: LocalDataFrame) -> LocalDataFrame:  # pragma: no cover
        raise NotImplementedError


class OutputTransformer(Transformer):
    """Transformer with no output (side effects only)."""

    def get_output_schema(self, df: DataFrame) -> Any:
        return OUTPUT_TRANSFORMER_DUMMY_SCHEMA

    @abstractmethod
    def process(self, df: LocalDataFrame) -> None:  # pragma: no cover
        raise NotImplementedError

    def transform(self, df: LocalDataFrame) -> LocalDataFrame:
        from fugue_tpu.dataframe import ArrayDataFrame

        self.process(df)
        return ArrayDataFrame([], OUTPUT_TRANSFORMER_DUMMY_SCHEMA)


class CoTransformer(ExtensionContext, ABC):
    """Worker-side map over co-partitioned (zipped) dataframes."""

    @abstractmethod
    def get_output_schema(self, dfs: DataFrames) -> Any:  # pragma: no cover
        raise NotImplementedError

    def on_init(self, dfs: DataFrames) -> None:  # pragma: no cover - hook
        pass

    @abstractmethod
    def transform(self, dfs: DataFrames) -> LocalDataFrame:  # pragma: no cover
        raise NotImplementedError


class OutputCoTransformer(CoTransformer):
    def get_output_schema(self, dfs: DataFrames) -> Any:
        return OUTPUT_TRANSFORMER_DUMMY_SCHEMA

    @abstractmethod
    def process(self, dfs: DataFrames) -> None:  # pragma: no cover
        raise NotImplementedError

    def transform(self, dfs: DataFrames) -> LocalDataFrame:
        from fugue_tpu.dataframe import ArrayDataFrame

        self.process(dfs)
        return ArrayDataFrame([], OUTPUT_TRANSFORMER_DUMMY_SCHEMA)


OUTPUT_TRANSFORMER_DUMMY_SCHEMA = "_fugue_output_dummy:int"
