"""fsspec adapter: any scheme fsspec knows (``gs://``, ``s3://``,
``az://``, ``http://``...) becomes a VirtualFileSystem.

Registered as the ``"*"`` fallback so explicit builtin schemes
(``file``, ``memory``) keep their native backends. fsspec is OPTIONAL:
when absent the fallback registration is skipped and unknown schemes
raise the registry's NotImplementedError instead of an import error."""

from typing import Any, BinaryIO, Callable, List

from fugue_tpu.fs.base import FileInfo, VirtualFileSystem, register_filesystem


def _mtime_of(detail: Any) -> float:
    """Normalize fsspec's per-backend modified-time vocabulary (mtime /
    LastModified / last_modified / created as float, datetime or ISO
    string) into epoch seconds; 0.0 when the backend reports none."""
    for key in ("mtime", "LastModified", "last_modified", "created"):
        v = (detail or {}).get(key)
        if v is None:
            continue
        if isinstance(v, (int, float)):
            return float(v)
        ts = getattr(v, "timestamp", None)
        if callable(ts):
            return float(ts())
        try:
            from datetime import datetime

            return datetime.fromisoformat(str(v)).timestamp()
        except Exception:
            continue
    return 0.0


class FsspecFileSystem(VirtualFileSystem):
    """Thin mapping onto ``fsspec.AbstractFileSystem`` (one instance per
    scheme; connection conf comes from the environment the way fsspec
    backends already standardize)."""

    def __init__(self, scheme: str):
        import fsspec

        self.scheme = scheme
        self._fs = fsspec.filesystem(scheme)

    def _q(self, path: str) -> str:
        # fsspec backends accept scheme-less paths for their own protocol
        return path

    def open_input_stream(self, path: str) -> BinaryIO:
        return self._fs.open(self._q(path), "rb")

    def open_output_stream(self, path: str) -> BinaryIO:
        p = self._q(path)
        parent = p.rsplit("/", 1)[0] if "/" in p else ""
        if parent:
            try:  # contract: parents exist after this call; object
                # stores have no real dirs and may no-op or refuse
                self._fs.makedirs(parent, exist_ok=True)
            except Exception:
                pass
        return self._fs.open(p, "wb")

    def exists(self, path: str) -> bool:
        return bool(self._fs.exists(self._q(path)))

    def isdir(self, path: str) -> bool:
        return bool(self._fs.isdir(self._q(path)))

    def listdir(self, path: str) -> List[str]:
        out = []
        for p in self._fs.ls(self._q(path), detail=False):
            out.append(str(p).rstrip("/").rsplit("/", 1)[-1])
        return sorted(out)

    def file_size(self, path: str) -> int:
        return int(self._fs.size(self._q(path)))

    def info(self, path: str) -> FileInfo:
        p = self._q(path)
        try:
            detail = self._fs.info(p)
        except FileNotFoundError:
            raise
        except Exception as ex:  # pragma: no cover - backend-specific
            raise FileNotFoundError(f"{self.scheme}://{p}: {ex}")
        isdir = str(detail.get("type", "file")) == "directory"
        return FileInfo(
            path=path,
            size=0 if isdir else int(detail.get("size") or 0),
            mtime=_mtime_of(detail),
            isdir=isdir,
        )

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        self._fs.makedirs(self._q(path), exist_ok=exist_ok)

    def rm(self, path: str, recursive: bool = False) -> None:
        p = self._q(path)
        if not self._fs.exists(p):
            return
        self._fs.rm(p, recursive=recursive)

    def rename(self, src: str, dst: str) -> None:
        self._fs.mv(self._q(src), self._q(dst), recursive=False)

    def glob(self, pattern: str) -> List[str]:
        return sorted(str(p) for p in self._fs.glob(self._q(pattern)))

    def write_file_if_absent(
        self, path: str, writer: Callable[[BinaryIO], None]
    ) -> None:
        # ADVISORY on generic object stores: fsspec exposes no portable
        # exclusive-create, so this is exists-check + exclusive local
        # semantics where the backend honors mode "xb", else check +
        # atomic write. Stores with conditional puts (GCS
        # if-generation-match, S3 If-None-Match) should get a dedicated
        # backend for contended multi-writer commit paths; single-writer
        # and low-contention uses are safe here.
        p = self._q(path)
        if self._fs.exists(p):
            raise FileExistsError(f"{self.scheme}://{p}")
        try:
            fp = self._fs.open(p, "xb")
        except (NotImplementedError, ValueError):
            self.write_file_atomic(path, writer)
            return
        except FileExistsError:
            raise FileExistsError(f"{self.scheme}://{p}")
        with fp:
            writer(fp)

    def pyarrow_native(self) -> Any:
        """Object stores skip the python FileSystemHandler shim: pyarrow
        wraps fsspec directly (C++-thread-safe handler)."""
        from pyarrow.fs import FSSpecHandler, PyFileSystem

        return PyFileSystem(FSSpecHandler(self._fs))


try:  # pragma: no cover - environment dependent
    import fsspec  # noqa: F401

    register_filesystem("*", lambda scheme: FsspecFileSystem(scheme))
except ImportError:  # pragma: no cover
    pass
