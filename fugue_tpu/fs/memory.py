"""In-process ``memory://`` backend.

The non-local filesystem every test environment has: a process-wide
blob store (the role the in-memory table catalog plays for table
yields), used to exercise URI plumbing — checkpoints, yield files,
multi-part folder writes — without object storage. Process-wide on
purpose: yields/checkpoints must cross engine instances within one
driver process, exactly like a real remote store would."""

import io
import posixpath
import time
from threading import RLock
from typing import BinaryIO, Callable, Dict, List

from fugue_tpu.fs.base import FileInfo, VirtualFileSystem, register_filesystem

_LOCK = RLock()
_FILES: Dict[str, bytes] = {}
_DIRS: set = set()
# commit-time timestamps: files stamp at (every) commit, dirs at
# creation. Strictly non-decreasing so a same-granule burst still
# resolves deterministically through the (mtime, name) listing order.
_MTIMES: Dict[str, float] = {}


def reset_memory_fs() -> None:
    """Drop every memory:// object (test isolation)."""
    with _LOCK:
        _FILES.clear()
        _DIRS.clear()
        _MTIMES.clear()


def _norm(path: str) -> str:
    p = posixpath.normpath(path.strip("/"))
    return "" if p == "." else p


def _parents(path: str) -> List[str]:
    out = []
    while True:
        path = posixpath.dirname(path)
        if path == "":
            return out
        out.append(path)


class _WriteBuffer(io.BytesIO):
    """Commits the blob on close — a reader never sees a partial file,
    which is also what makes single-file overwrite atomic here."""

    def __init__(self, commit: Callable[[bytes], None]):
        super().__init__()
        self._commit = commit
        self._committed = False

    def abort(self) -> None:
        """Discard the buffer without publishing (failed atomic write)."""
        self._committed = True
        super().close()

    def close(self) -> None:
        if not self._committed:
            self._committed = True
            self._commit(self.getvalue())
        super().close()


class MemoryFileSystem(VirtualFileSystem):
    scheme = "memory"

    def open_input_stream(self, path: str) -> BinaryIO:
        p = _norm(path)
        with _LOCK:
            if p not in _FILES:
                raise FileNotFoundError(f"memory://{p}")
            return io.BytesIO(_FILES[p])

    def open_output_stream(self, path: str) -> BinaryIO:
        p = _norm(path)

        def commit(data: bytes) -> None:
            with _LOCK:
                _FILES[p] = data
                _MTIMES[p] = time.time()
                for d in _parents(p):
                    _DIRS.add(d)
                    _MTIMES.setdefault(d, _MTIMES[p])

        return _WriteBuffer(commit)

    def exists(self, path: str) -> bool:
        p = _norm(path)
        with _LOCK:
            return p == "" or p in _FILES or p in _DIRS

    def isdir(self, path: str) -> bool:
        p = _norm(path)
        with _LOCK:
            return p == "" or p in _DIRS

    def listdir(self, path: str) -> List[str]:
        p = _norm(path)
        with _LOCK:
            if p != "" and p not in _DIRS:
                raise FileNotFoundError(f"memory://{p} is not a directory")
            prefix = p + "/" if p != "" else ""
            names = set()
            for k in list(_FILES) + list(_DIRS):
                if k != p and k.startswith(prefix):
                    names.add(k[len(prefix):].split("/", 1)[0])
            return sorted(names)

    def file_size(self, path: str) -> int:
        p = _norm(path)
        with _LOCK:
            if p not in _FILES:
                raise FileNotFoundError(f"memory://{p}")
            return len(_FILES[p])

    def info(self, path: str) -> FileInfo:
        p = _norm(path)
        with _LOCK:
            if p in _FILES:
                return FileInfo(
                    path=p,
                    size=len(_FILES[p]),
                    mtime=_MTIMES.get(p, 0.0),
                    isdir=False,
                )
            if p == "" or p in _DIRS:
                return FileInfo(
                    path=p, size=0, mtime=_MTIMES.get(p, 0.0), isdir=True
                )
            raise FileNotFoundError(f"memory://{p}")

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        p = _norm(path)
        with _LOCK:
            if not exist_ok and p in _DIRS:
                raise FileExistsError(f"memory://{p}")
            if p != "":
                now = time.time()
                for d in [p] + _parents(p):
                    _DIRS.add(d)
                    _MTIMES.setdefault(d, now)

    def rm(self, path: str, recursive: bool = False) -> None:
        p = _norm(path)
        with _LOCK:
            if p in _FILES:
                del _FILES[p]
                _MTIMES.pop(p, None)
                return
            if p in _DIRS:
                prefix = p + "/"
                children = [k for k in _FILES if k.startswith(prefix)]
                subdirs = [k for k in _DIRS if k.startswith(prefix)]
                if not recursive and (children or subdirs):
                    raise OSError(f"memory://{p} is not empty")
                for k in children:
                    del _FILES[k]
                    _MTIMES.pop(k, None)
                for k in subdirs:
                    _DIRS.discard(k)
                    _MTIMES.pop(k, None)
                _DIRS.discard(p)
                _MTIMES.pop(p, None)

    def rename(self, src: str, dst: str) -> None:
        s, d = _norm(src), _norm(dst)
        with _LOCK:
            if s in _FILES:
                _FILES[d] = _FILES.pop(s)
                # rename preserves the source's commit time (os.replace
                # semantics): an atomic temp+rename write carries the
                # moment the bytes were committed, not the rename
                _MTIMES[d] = _MTIMES.pop(s, time.time())
                _DIRS.update(_parents(d))
                return
            if s in _DIRS:
                prefix = s + "/"
                for k in [k for k in _FILES if k.startswith(prefix)]:
                    moved = d + "/" + k[len(prefix):]
                    _FILES[moved] = _FILES.pop(k)
                    _MTIMES[moved] = _MTIMES.pop(k, time.time())
                for k in [k for k in _DIRS if k.startswith(prefix)]:
                    _DIRS.discard(k)
                    _DIRS.add(d + "/" + k[len(prefix):])
                    _MTIMES[d + "/" + k[len(prefix):]] = _MTIMES.pop(
                        k, time.time()
                    )
                _DIRS.discard(s)
                _DIRS.add(d)
                _MTIMES[d] = _MTIMES.pop(s, time.time())
                _DIRS.update(_parents(d))
                return
            raise FileNotFoundError(f"memory://{s}")

    def write_file_if_absent(
        self, path: str, writer: Callable[[BinaryIO], None]
    ) -> None:
        # the whole check-absent-then-publish runs as ONE critical
        # section under the store lock at commit time, so two racing
        # writers serialize: the loser's fully-buffered payload is
        # discarded and FileExistsError raised — a true CAS
        p = _norm(path)

        def commit(data: bytes) -> None:
            with _LOCK:
                if p in _FILES:
                    raise FileExistsError(f"memory://{p}")
                _FILES[p] = data
                _MTIMES[p] = time.time()
                for d in _parents(p):
                    _DIRS.add(d)
                    _MTIMES.setdefault(d, _MTIMES[p])

        fp = _WriteBuffer(commit)
        try:
            writer(fp)
        except BaseException:
            fp.abort()
            raise
        fp.close()

    def write_file_atomic(self, path: str, writer: Callable[[BinaryIO], None]) -> None:
        # the commit-on-close buffer IS the atomic swap; no temp object.
        # A failing writer ABORTS the buffer — partial bytes must never
        # publish, or a deterministic checkpoint would reuse the torn file
        fp = self.open_output_stream(path)
        try:
            writer(fp)
        except BaseException:
            fp.abort()  # type: ignore[attr-defined]
            raise
        fp.close()


register_filesystem("memory", lambda scheme: MemoryFileSystem())
