"""Local-disk backend: ``file://`` URIs and bare paths."""

import glob as _glob
import os
import shutil
from typing import BinaryIO, Callable, List
from uuid import uuid4

from fugue_tpu.fs.base import FileInfo, VirtualFileSystem, register_filesystem


class LocalFileSystem(VirtualFileSystem):
    scheme = "file"

    def open_input_stream(self, path: str) -> BinaryIO:
        return open(path, "rb")

    def open_output_stream(self, path: str) -> BinaryIO:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        return open(path, "wb")

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def file_size(self, path: str) -> int:
        return os.path.getsize(path)

    def info(self, path: str) -> FileInfo:
        st = os.stat(path)
        isdir = os.path.isdir(path)
        return FileInfo(
            path=path,
            size=0 if isdir else int(st.st_size),
            mtime=float(st.st_mtime),
            isdir=isdir,
        )

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        os.makedirs(path, exist_ok=exist_ok)

    def rm(self, path: str, recursive: bool = False) -> None:
        if not os.path.exists(path):
            return
        if os.path.isdir(path):
            if recursive:
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.rmdir(path)
        else:
            os.remove(path)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def write_file_if_absent(
        self, path: str, writer: Callable[[BinaryIO], None]
    ) -> None:
        # stage the full payload into a hidden temp, then publish with
        # os.link: link(2) is atomic AND fails with EEXIST when the
        # target exists, so of N racing writers exactly one wins and a
        # reader only ever sees a complete winner. The 'xb' fallback
        # covers filesystems without hard links (FAT, some network
        # mounts) — there the create is exclusive but the bytes stream
        # in after it, which is still safe for dot/underscore-skipping
        # readers and single-read-after-commit consumers.
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        base = os.path.basename(path)
        tmp = os.path.join(parent, f".{base}.cas-{uuid4().hex[:8]}")
        try:
            with open(tmp, "wb") as fp:
                writer(fp)
            try:
                os.link(tmp, path)
            except OSError as ex:
                if isinstance(ex, FileExistsError):
                    raise
                # hard links unsupported: exclusive-create fallback
                with open(path, "xb") as out, open(tmp, "rb") as src:
                    shutil.copyfileobj(src, out)
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def glob(self, pattern: str) -> List[str]:
        return sorted(_glob.glob(pattern))


register_filesystem("file", lambda scheme: LocalFileSystem())
