"""VirtualFileSystem -> pyarrow.fs bridge.

Parquet dataset machinery (hive partition discovery, directory reads,
``write_to_dataset``) is pyarrow C++ code that talks to a
``pyarrow.fs.FileSystem``. This module makes any VFS backend usable
there: local disk maps to pyarrow's native LocalFileSystem (zero
overhead), fsspec backends wrap through pyarrow's FSSpecHandler, and
everything else (memory://, custom backends) goes through a python
``FileSystemHandler`` shim."""

from typing import Any, List

import pyarrow as pa
from pyarrow import fs as pafs

from fugue_tpu.fs.base import VirtualFileSystem


def to_pyarrow_fs(vfs: VirtualFileSystem) -> pafs.FileSystem:
    from fugue_tpu.fs.local import LocalFileSystem

    if isinstance(vfs, LocalFileSystem):
        return pafs.LocalFileSystem()
    native = getattr(vfs, "pyarrow_native", None)
    if native is not None:
        return native()
    return pafs.PyFileSystem(_VFSHandler(vfs))


class _VFSHandler(pafs.FileSystemHandler):
    def __init__(self, vfs: VirtualFileSystem):
        self._vfs = vfs

    def get_type_name(self) -> str:
        return f"fugue-vfs-{self._vfs.scheme}"

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, _VFSHandler) and other._vfs is self._vfs
        )

    def __ne__(self, other: Any) -> bool:
        return not self.__eq__(other)

    # ---- info ------------------------------------------------------------
    def _info(self, path: str) -> pafs.FileInfo:
        v = self._vfs
        if v.isdir(path):
            return pafs.FileInfo(path, pafs.FileType.Directory)
        if v.exists(path):
            return pafs.FileInfo(
                path, pafs.FileType.File, size=v.file_size(path)
            )
        return pafs.FileInfo(path, pafs.FileType.NotFound)

    def get_file_info(self, paths: List[str]) -> List[pafs.FileInfo]:
        return [self._info(p) for p in paths]

    def get_file_info_selector(self, selector: Any) -> List[pafs.FileInfo]:
        base = selector.base_dir
        if not self._vfs.isdir(base):
            if selector.allow_not_found:
                return []
            raise FileNotFoundError(base)
        out: List[pafs.FileInfo] = []
        stack = [base]
        while stack:
            d = stack.pop()
            for name in self._vfs.listdir(d):
                child = f"{d.rstrip('/')}/{name}" if d not in ("", "/") else name
                info = self._info(child)
                out.append(info)
                if selector.recursive and info.type == pafs.FileType.Directory:
                    stack.append(child)
        return out

    def normalize_path(self, path: str) -> str:
        return path

    # ---- mutation ---------------------------------------------------------
    def create_dir(self, path: str, recursive: bool) -> None:
        self._vfs.makedirs(path, exist_ok=True)

    def delete_dir(self, path: str) -> None:
        self._vfs.rm(path, recursive=True)

    def delete_dir_contents(self, path: str, missing_dir_ok: bool = False) -> None:
        if not self._vfs.isdir(path):
            if missing_dir_ok:
                return
            raise FileNotFoundError(path)
        for name in self._vfs.listdir(path):
            self._vfs.rm(f"{path.rstrip('/')}/{name}", recursive=True)

    def delete_root_dir_contents(self) -> None:  # pragma: no cover
        self.delete_dir_contents("")

    def delete_file(self, path: str) -> None:
        self._vfs.rm(path)

    def move(self, src: str, dest: str) -> None:
        self._vfs.rename(src, dest)

    def copy_file(self, src: str, dest: str) -> None:
        data = self._vfs.read_bytes(src)
        with self._vfs.open_output_stream(dest) as fp:
            fp.write(data)

    # ---- streams -----------------------------------------------------------
    def open_input_stream(self, path: str) -> Any:
        return pa.PythonFile(self._vfs.open_input_stream(path), mode="r")

    def open_input_file(self, path: str) -> Any:
        return pa.PythonFile(self._vfs.open_input_stream(path), mode="r")

    def open_output_stream(self, path: str, metadata: Any = None) -> Any:
        return pa.PythonFile(self._vfs.open_output_stream(path), mode="w")

    def open_append_stream(self, path: str, metadata: Any = None) -> Any:
        raise NotImplementedError("append streams are not supported")
