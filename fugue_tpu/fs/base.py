"""VirtualFileSystem: URI-addressed IO for every persistence path.

The reference fugue does all IO through an abstract FileSystem with URI
support and exposes it as ``ExecutionEngine.fs`` (reference
fugue/_utils/io.py:9,100-128, execution_engine.py:476). This subsystem
rebuilds that seam natively: a scheme registry maps URI prefixes
(``file://``, ``memory://``, and via the fsspec adapter ``gs://``/
``s3://``/...) to :class:`VirtualFileSystem` backends, so checkpoint
dirs, yield files and ``save/load`` targets work identically on a laptop
and on a TPU pod whose data lives in object storage.

Design rules:

- Paths are URIs. A bare path (no scheme) is the local filesystem; a
  single-letter "scheme" (``C:\\...``) is a windows drive, also local.
- A backend sees SCHEME-LESS paths: :func:`get_filesystem` splits the
  URI and hands the backend its own path form. ``join``/``dirname``
  stay URI-aware so callers never touch ``os.path`` for URIs.
- Multi-part folder writes follow the distributed convention: a folder
  of part files is one dataset; :meth:`VirtualFileSystem.makedirs` +
  per-part streams build it, and single-file writes go through
  :meth:`write_file_atomic` (temp + rename where the backend can, so a
  concurrent reader never sees a torn file).
"""

import fnmatch
import posixpath
import re
from abc import ABC, abstractmethod
from typing import (
    Any,
    BinaryIO,
    Callable,
    Dict,
    List,
    NamedTuple,
    Optional,
    Tuple,
)

from fugue_tpu.testing.faults import fault_point
from fugue_tpu.utils.assertion import assert_or_throw

_URI_RE = re.compile(r"^([A-Za-z][A-Za-z0-9+.-]*)://(.*)$")


class FileInfo(NamedTuple):
    """One filesystem entry's metadata. ``mtime`` is seconds since the
    epoch and is guaranteed PRESENT on every backend (memory:// stamps
    commit time; object stores map their last-modified) — the streaming
    tail source's discovery order depends on it."""

    path: str
    size: int
    mtime: float
    isdir: bool


def split_uri(uri: str) -> Tuple[str, str]:
    """``"gs://bucket/a/b"`` -> ``("gs", "bucket/a/b")``; bare and
    windows-drive paths -> ``("file", path)`` unchanged."""
    m = _URI_RE.match(uri)
    if m is None or len(m.group(1)) == 1:  # C:\... is a drive, not a scheme
        return "file", uri
    return m.group(1).lower(), m.group(2)


def is_uri(path: str) -> bool:
    return split_uri(path)[0] != "file" or _URI_RE.match(path) is not None


def join_uri(base: str, *parts: str) -> str:
    """Join path segments under a base that may be a URI. Local bare
    paths use the OS convention; URI paths always join with ``/``."""
    scheme, rest = split_uri(base)
    if _URI_RE.match(base) is None:
        import os

        return os.path.join(base, *parts)
    return f"{scheme}://" + posixpath.join(rest, *parts)


def uri_dirname(path: str) -> str:
    scheme, rest = split_uri(path)
    if _URI_RE.match(path) is None:
        import os

        return os.path.dirname(path)
    return f"{scheme}://" + posixpath.dirname(rest)


def uri_basename(path: str) -> str:
    if _URI_RE.match(path) is None:
        import os

        return os.path.basename(path)
    return posixpath.basename(split_uri(path)[1])


class VirtualFileSystem(ABC):
    """One storage backend. All methods take backend-local paths (the
    URI with its ``scheme://`` prefix stripped — see :func:`split_uri`)."""

    scheme: str = ""

    # ---- streams ---------------------------------------------------------
    @abstractmethod
    def open_input_stream(self, path: str) -> BinaryIO:
        """Readable binary file object. MUST be seekable (parquet footers
        read from the end)."""
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def open_output_stream(self, path: str) -> BinaryIO:
        """Writable binary file object; parent dirs are created."""
        raise NotImplementedError  # pragma: no cover

    # ---- metadata --------------------------------------------------------
    @abstractmethod
    def exists(self, path: str) -> bool:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def isdir(self, path: str) -> bool:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def listdir(self, path: str) -> List[str]:
        """Base names of a directory's direct children."""
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def file_size(self, path: str) -> int:
        raise NotImplementedError  # pragma: no cover

    def info(self, path: str) -> FileInfo:
        """Metadata of one entry, ``mtime`` included — every BUILTIN
        backend produces a real modification time (the streaming tail
        source's mtime-then-name discovery order depends on it). This
        default (not abstract: out-of-tree backends written before it
        existed must keep instantiating) derives size/isdir from the
        required primitives and reports ``mtime=0.0`` — a backend used
        as a streaming source SHOULD override with real timestamps
        (with 0.0 everywhere, discovery degrades to pure name order).
        Raises ``FileNotFoundError`` for missing paths."""
        if not self.exists(path):
            raise FileNotFoundError(path)
        isdir = self.isdir(path)
        return FileInfo(
            path=path,
            size=0 if isdir else int(self.file_size(path)),
            mtime=0.0,
            isdir=isdir,
        )

    # ---- mutation --------------------------------------------------------
    @abstractmethod
    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def rm(self, path: str, recursive: bool = False) -> None:
        """Remove a file, or a directory tree with ``recursive=True``.
        Missing paths are a no-op (idempotent cleanup)."""
        raise NotImplementedError  # pragma: no cover

    # ---- composites (backends may override with native fast paths) ------
    def read_bytes(self, path: str) -> bytes:
        with self.open_input_stream(path) as fp:
            return fp.read()

    def write_file_atomic(self, path: str, writer: Callable[[BinaryIO], None]) -> None:
        """Single-file write that never exposes a torn file: write a
        sibling temp object, then rename over the target. Backends
        without rename override with their own all-or-nothing commit."""
        from uuid import uuid4

        # hidden-name temp ('.'-prefixed): a crash mid-write must not
        # poison part-file folders — every reader (part listing, pyarrow
        # datasets) skips dot-files by convention
        head, _, tail = path.rpartition("/")
        tmp = (
            f"{head}/.{tail}.tmp-{uuid4().hex[:8]}"
            if head
            else f".{tail}.tmp-{uuid4().hex[:8]}"
        )
        try:
            with self.open_output_stream(tmp) as fp:
                writer(fp)
            self.rename(tmp, path)
        except BaseException:
            self.rm(tmp)
            raise

    def rename(self, src: str, dst: str) -> None:
        """Move ``src`` over ``dst`` (replacing it). Default: copy+rm."""
        data = self.read_bytes(src)
        with self.open_output_stream(dst) as fp:
            fp.write(data)
        self.rm(src)

    def write_file_if_absent(
        self, path: str, writer: Callable[[BinaryIO], None]
    ) -> None:
        """Fail-if-exists single-file write: the optimistic compare-and-
        swap primitive versioned-table commits are built on. Exactly one
        of N concurrent callers targeting the same path succeeds; every
        loser gets ``FileExistsError`` and NO bytes of the loser's
        payload are ever visible. Like :meth:`write_file_atomic`, a
        reader never observes a torn file.

        This default stages through a hidden temp then performs an
        exists-check + rename — atomic only as far as the backend's
        primitives allow. Backends with a native all-or-nothing
        "create exclusive" (local ``os.link``, memory's single-lock
        commit, object-store ``If-None-Match``) MUST override; the
        conformance suite (``fugue_tpu_test/fs_suite.py``) races
        concurrent writers against the contract."""
        if self.exists(path):
            raise FileExistsError(path)
        self.write_file_atomic(path, writer)

    def list_chronological(
        self, path: str, pattern: str = "*"
    ) -> List[FileInfo]:
        """Direct-child FILES of a directory in deterministic
        (mtime, name) order — the streaming tail source's discovery
        order: arrival order where mtimes differ, name order where a
        burst of files lands within one timestamp granule. Dot/
        underscore-prefixed names are skipped (atomic-write temps and
        marker files, the same convention every part-file reader
        applies); directories are skipped. A MISSING dir is an empty
        list (a tail source may start before its first file arrives);
        any other listing failure (auth, network) PROPAGATES — an
        unreachable source must look broken, not merely idle."""
        try:
            names = self.listdir(path)
        except FileNotFoundError:
            return []
        out: List[FileInfo] = []
        for name in names:
            if name.startswith(".") or name.startswith("_"):
                continue
            if not fnmatch.fnmatchcase(name, pattern):
                continue
            child = f"{path.rstrip('/')}/{name}" if path else name
            try:
                inf = self.info(child)
            except FileNotFoundError:  # raced away between list and stat
                continue
            if inf.isdir:
                continue
            out.append(inf)
        return sorted(out, key=lambda i: (i.mtime, i.path))

    def glob(self, pattern: str) -> List[str]:
        """Expand ``*``/``?``/``[...]`` PER PATH SEGMENT (``*`` never
        crosses ``/`` — standard glob semantics, matching the native
        local and fsspec backends), sorted. Default walks listdir —
        backends with native globbing override."""
        if not any(c in pattern for c in "*?["):
            return [pattern] if self.exists(pattern) else []
        cur = ["/"] if pattern.startswith("/") else [""]
        for seg in pattern.split("/"):
            if seg == "":
                continue
            nxt: List[str] = []
            for base in cur:
                joined = base + seg if base in ("", "/") else f"{base}/{seg}"
                if not any(c in seg for c in "*?["):
                    nxt.append(joined)  # existence filtered at the end
                    continue
                list_at = base if base != "" else "/"
                if not self.isdir(list_at):
                    continue
                for name in self.listdir(list_at):
                    if fnmatch.fnmatchcase(name, seg):
                        nxt.append(
                            base + name if base in ("", "/")
                            else f"{base}/{name}"
                        )
            cur = nxt
        return sorted(p for p in cur if self.exists(p))

    # identity for deterministic hashing (conf-independent)
    def __uuid__(self) -> str:
        from fugue_tpu.utils.hash import to_uuid

        return to_uuid(type(self).__name__, self.scheme)


class FileSystemRegistry:
    """The multiplexer handed out as ``ExecutionEngine.fs``: routes every
    URI to its scheme's backend, exposing the same operations with FULL
    URIs so engine/checkpoint code never splits schemes by hand."""

    def __init__(self, factories: Optional[Dict[str, Callable[[], Any]]] = None):
        # None = track the LIVE global table, so register_filesystem()
        # calls made after this registry (or the process default / an
        # engine's fs) was created still take effect; an explicit dict
        # pins the scheme set (tests, sandboxed registries)
        self._factories = None if factories is None else dict(factories)
        # scheme -> (producing factory, instance): the factory is kept so
        # re-registering a scheme invalidates the cached instance instead
        # of serving the stale backend forever
        self._instances: Dict[str, Tuple[Any, VirtualFileSystem]] = {}

    def resolve(self, uri: str) -> Tuple[VirtualFileSystem, str]:
        scheme, path = split_uri(uri)
        factories = _FACTORIES if self._factories is None else self._factories
        factory = factories.get(scheme)
        if factory is None:
            factory = factories.get("*")
        assert_or_throw(
            factory is not None,
            NotImplementedError(f"no filesystem registered for {uri!r}"),
        )
        cached = self._instances.get(scheme)
        if cached is not None and cached[0] is factory:
            return cached[1], path
        fs = factory(scheme)  # type: ignore[misc]
        self._instances[scheme] = (factory, fs)
        return fs, path

    # ---- URI-level operations -------------------------------------------
    # fault_point calls are the fault-injection harness's fs sites
    # ("fs.open" / "fs.write" keyed by full URI): free when no plan is
    # active, and they sit at the REGISTRY level so every consumer —
    # utils/io, streamed ingest, checkpoints, spill files — is covered.
    def open_input_stream(self, uri: str) -> BinaryIO:
        fault_point("fs.open", uri)
        fs, path = self.resolve(uri)
        return fs.open_input_stream(path)

    def open_output_stream(self, uri: str) -> BinaryIO:
        fault_point("fs.write", uri)
        fs, path = self.resolve(uri)
        return fs.open_output_stream(path)

    def read_bytes(self, uri: str) -> bytes:
        fault_point("fs.open", uri)
        fs, path = self.resolve(uri)
        return fs.read_bytes(path)

    def write_file_atomic(self, uri: str, writer: Callable[[BinaryIO], None]) -> None:
        fault_point("fs.write", uri)
        fs, path = self.resolve(uri)
        fs.write_file_atomic(path, writer)

    def write_file_if_absent(
        self, uri: str, writer: Callable[[BinaryIO], None]
    ) -> None:
        """Fail-if-exists write (the CAS primitive — see the backend
        method). Raises ``FileExistsError`` when the target already
        exists; exactly one of N concurrent writers wins."""
        fault_point("fs.write", uri)
        fs, path = self.resolve(uri)
        fs.write_file_if_absent(path, writer)

    def exists(self, uri: str) -> bool:
        fs, path = self.resolve(uri)
        return fs.exists(path)

    def isdir(self, uri: str) -> bool:
        fs, path = self.resolve(uri)
        return fs.isdir(path)

    def listdir(self, uri: str) -> List[str]:
        fs, path = self.resolve(uri)
        return fs.listdir(path)

    def file_size(self, uri: str) -> int:
        fs, path = self.resolve(uri)
        return fs.file_size(path)

    def info(self, uri: str) -> FileInfo:
        """Entry metadata with the FULL URI restored into ``path`` so a
        consumer can hand it straight back to any registry method."""
        scheme, path = split_uri(uri)
        fs, _ = self.resolve(uri)
        inf = fs.info(path)
        prefix = f"{scheme}://" if _URI_RE.match(uri) else ""
        return inf._replace(path=prefix + inf.path)

    def list_chronological(
        self, uri: str, pattern: str = "*"
    ) -> List[FileInfo]:
        """Direct-child files of a directory URI in deterministic
        (mtime, name) order (see the backend method); paths come back
        as full URIs."""
        scheme, path = split_uri(uri)
        fs, _ = self.resolve(uri)
        prefix = f"{scheme}://" if _URI_RE.match(uri) else ""
        return [
            i._replace(path=prefix + i.path)
            for i in fs.list_chronological(path, pattern)
        ]

    def makedirs(self, uri: str, exist_ok: bool = True) -> None:
        fs, path = self.resolve(uri)
        fs.makedirs(path, exist_ok=exist_ok)

    def rm(self, uri: str, recursive: bool = False) -> None:
        fs, path = self.resolve(uri)
        fs.rm(path, recursive=recursive)

    def rename(self, src: str, dst: str) -> None:
        s1, p1 = self.resolve(src)
        s2, p2 = self.resolve(dst)
        assert_or_throw(
            s1 is s2, NotImplementedError("cross-filesystem rename")
        )
        s1.rename(p1, p2)

    def glob(self, pattern: str) -> List[str]:
        scheme, path = split_uri(pattern)
        fs, _ = self.resolve(pattern)
        prefix = f"{scheme}://" if _URI_RE.match(pattern) else ""
        return [prefix + p for p in fs.glob(path)]

    def join(self, base: str, *parts: str) -> str:
        return join_uri(base, *parts)

    def pyarrow_fs(self, uri: str) -> Tuple[Any, str]:
        """A ``pyarrow.fs.FileSystem`` view of the URI's backend plus the
        backend-local path — the bridge that lets pyarrow's dataset
        machinery (hive partition discovery, multi-file reads) run on ANY
        backend, not just local disk."""
        fs, path = self.resolve(uri)
        from fugue_tpu.fs.pafs import to_pyarrow_fs

        return to_pyarrow_fs(fs), path

    def __uuid__(self) -> str:
        from fugue_tpu.utils.hash import to_uuid

        factories = _FACTORIES if self._factories is None else self._factories
        return to_uuid(type(self).__name__, sorted(factories.keys()))


_FACTORIES: Dict[str, Callable[[str], VirtualFileSystem]] = {}


def register_filesystem(
    scheme: str, factory: Callable[[str], VirtualFileSystem]
) -> None:
    """Register a backend factory for a URI scheme. ``"*"`` is the
    fallback consulted for unknown schemes (the fsspec adapter)."""
    _FACTORIES[scheme.lower()] = factory


def make_default_registry() -> FileSystemRegistry:
    """A registry with every globally-registered scheme. Engines create
    one lazily for :attr:`ExecutionEngine.fs`."""
    _ensure_builtin_schemes()
    return FileSystemRegistry()


_BUILTINS_LOADED = False


def _ensure_builtin_schemes() -> None:
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    _BUILTINS_LOADED = True
    import fugue_tpu.fs.local  # noqa: F401 (registers "file")
    import fugue_tpu.fs.memory  # noqa: F401 (registers "memory")
    import fugue_tpu.fs.fsspec_fs  # noqa: F401 (registers "*" when available)
