"""URI-addressed virtual filesystem layer (see base.py for the design)."""

from fugue_tpu.fs.base import (
    FileInfo,
    FileSystemRegistry,
    VirtualFileSystem,
    is_uri,
    join_uri,
    make_default_registry,
    register_filesystem,
    split_uri,
    uri_basename,
    uri_dirname,
)
from fugue_tpu.fs.memory import reset_memory_fs

__all__ = [
    "FileInfo",
    "FileSystemRegistry",
    "VirtualFileSystem",
    "is_uri",
    "join_uri",
    "make_default_registry",
    "register_filesystem",
    "reset_memory_fs",
    "split_uri",
    "uri_basename",
    "uri_dirname",
]
