"""Materialized views: the serving loop closed over a standing pipeline.

A :class:`MaterializedView` binds a :class:`StandingPipeline`'s refresh
to ``ServeSession.save_table`` — each emission

- swaps the device-resident session table under the engine's
  ``task_execution_lock`` (save_table's dispatch guard),
- bumps the session's ``cache_epoch``, so the in-process serve result
  cache (keyed on the epoch) and the fleet's content-addressed fs cache
  (keyed on the artifact sha256s) can NEVER serve a pre-refresh payload,
- journals the durable parquet artifact + fingerprint, so the view
  survives a daemon restart (lazy integrity-verified reload) and fleet
  adoption, exactly like a user-saved hot table.

The daemon records the pipeline SPEC in the session's journal record;
a restarted or adopting daemon rebuilds the view from the spec, the
progress manifest restores the accumulator state, and a commit whose
refresh never confirmed re-emits once.
"""

from typing import Any, Dict, Optional

from fugue_tpu.stream.pipeline import PipelineSpec, StandingPipeline


class MaterializedView:
    """One pipeline-maintained session table."""

    def __init__(self, engine: Any, session: Any, spec: PipelineSpec):
        self._session = session
        self.spec = spec
        self.pipeline = StandingPipeline(engine, spec, on_refresh=self._swap)

    @property
    def session_id(self) -> str:
        return self._session.session_id

    @property
    def name(self) -> str:
        return self.spec.name

    def _swap(self, df: Any) -> None:
        # save_table IS the swap: dispatch-guarded catalog overwrite,
        # cache_epoch bump, durable artifact + journal record
        self._session.save_table(self.spec.name, df)

    def step(self, force_refresh: bool = False) -> Dict[str, Any]:
        report = self.pipeline.step(force_refresh=force_refresh)
        report["session_id"] = self.session_id
        report["view"] = self.spec.name
        return report

    def refresh(self) -> bool:
        return self.pipeline.refresh()

    def start(self) -> "MaterializedView":
        self.pipeline.start()
        return self

    def stop(self) -> None:
        self.pipeline.stop()

    def remove(self, drop_table: bool = False) -> None:
        """Unregister: stop the ticker and clear the progress manifest;
        ``drop_table`` additionally drops the maintained session table
        (default keeps it — the view's last snapshot stays queryable)."""
        self.pipeline.stop()
        self.pipeline.progress.clear()
        if drop_table:
            try:
                self._session.drop_table(self.spec.name)
            except Exception:  # pragma: no cover - best-effort cleanup
                pass

    def describe(self) -> Dict[str, Any]:
        out = self.pipeline.describe()
        out["session_id"] = self.session_id
        out["view"] = self.spec.name
        out["cache_epoch"] = self._session.cache_epoch
        return out


def view_progress_uri(
    fs: Any, state_path: Optional[str], session_id: str, name: str
) -> Optional[str]:
    """Where a serve-registered pipeline keeps its progress manifest:
    under the daemon's durable state path, namespaced per session —
    shared-fs-reachable, so fleet adoption resumes the SAME manifest.
    None for an ephemeral daemon (progress dies with the process)."""
    base = str(state_path or "").strip()
    if base == "":
        return None
    return fs.join(base, "pipelines", session_id, f"{name}.json")
