"""The micro-batch driver: one FugueWorkflow-shaped aggregation re-run
incrementally over arriving files.

A :class:`StandingPipeline` owns

- a :class:`~fugue_tpu.stream.source.ParquetTailSource` (discovery in
  deterministic (mtime, name) order through the fs layer),
- ONE :class:`~fugue_tpu.jax_backend.streaming.StreamingAggregator`
  whose per-group accumulators live on device and are carried ACROSS
  micro-batches (``pad_spans`` on, so key-dictionary growth within the
  padded space neither rebases nor recompiles — after the first batch
  the update program only executes),
- a :class:`~fugue_tpu.stream.progress.StreamProgress` manifest whose
  per-batch atomic commit (consumed files + accumulator snapshot) is
  the exactly-once boundary a hard-killed driver restarts from,
- optional event-time windowing: rows bucket into fixed windows of the
  event column, the watermark (max event time seen − allowed lateness)
  gates emission so a window only publishes once it can no longer
  receive rows.

``step()`` runs one micro-batch: discover → fold (device dispatch under
the engine's ``task_execution_lock``) → commit → refresh the registered
materialized view. Steps are serialized through a CLAIM flag, never by
holding a lock across fold/IO — a ticker-thread step racing a manual
HTTP step coalesces instead of queueing behind device work.

The equivalent batch run is the pipeline's correctness oracle: over any
consumed file union, the emitted view is row-identical to the one-shot
``engine.aggregate`` over the concatenated files (parity-tested).
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from fugue_tpu.constants import (
    FUGUE_CONF_STREAM_BATCH_ROWS,
    FUGUE_CONF_STREAM_INTERVAL,
    FUGUE_CONF_STREAM_MAX_FILES,
    FUGUE_CONF_STREAM_PATTERN,
    FUGUE_CONF_STREAM_SOURCE,
    FUGUE_CONF_STREAM_WATERMARK_DELAY,
    FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH,
    FUGUE_CONF_WORKFLOW_RESUME,
    typed_conf_get,
)
from fugue_tpu.jax_backend.streaming import StreamingAggregator
from fugue_tpu.obs.trace import start_span
from fugue_tpu.stream.progress import StreamProgress
from fugue_tpu.stream.source import (
    ParquetTailSource,
    read_parquet_chunks,
    schema_of_parquet,
)
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.workflow.fault import engine_dispatch_guard


class PipelineSpec:
    """Declarative description of one standing pipeline — the unit the
    serve journal records so a restarted/adopting daemon can rebuild
    the pipeline object. ``aggs`` is ``[(out_name, func, src_col)]``
    with func in the streaming whitelist; ``window`` (optional) is
    ``{"column", "size", "delay"?, "emit_as"?}``."""

    def __init__(
        self,
        name: str,
        source: str,
        keys: List[str],
        aggs: List[Tuple[str, str, str]],
        window: Optional[Dict[str, Any]] = None,
        pattern: str = "*.parquet",
        interval: float = 0.0,
        max_files_per_batch: int = 0,
        batch_rows: int = 0,
        progress: Optional[str] = None,
        sink: Optional[str] = None,
    ):
        assert_or_throw(
            str(name).isidentifier(), ValueError(f"invalid pipeline name {name!r}")
        )
        assert_or_throw(
            str(source).strip() != "", ValueError("pipeline source is required")
        )
        assert_or_throw(
            len(keys) > 0, ValueError("pipeline needs at least one group key")
        )
        assert_or_throw(
            len(aggs) > 0, ValueError("pipeline needs at least one aggregation")
        )
        self.name = str(name)
        self.source = str(source).rstrip("/")
        self.keys = [str(k) for k in keys]
        self.aggs = [
            (str(o), str(f).lower(), str(s)) for o, f, s in
            (tuple(a) for a in aggs)
        ]
        self.window = dict(window) if window else None
        if self.window is not None:
            assert_or_throw(
                str(self.window.get("column") or "") != ""
                and float(self.window.get("size") or 0) > 0,
                ValueError("window needs a 'column' and a positive 'size'"),
            )
            self.window.setdefault("delay", 0.0)
            self.window.setdefault("emit_as", "window_start")
            # closed windows KEPT behind the watermark (0 = unlimited —
            # complete-mode semantics, but window-id state then grows
            # with wall time; a truly standing deployment should bound
            # it). Evicted windows leave the view on the next refresh.
            self.window.setdefault("retention", 0)
        self.pattern = pattern
        self.interval = float(interval)
        self.max_files_per_batch = int(max_files_per_batch)
        self.batch_rows = int(batch_rows)
        self.progress = progress
        # optional lake sink: every micro-batch's RAW rows also append
        # to this versioned table (exactly-once via the writer token +
        # progress manifest — see StandingPipeline._append_sink)
        if sink is not None:
            from fugue_tpu.lake.format import is_lake_uri

            assert_or_throw(
                is_lake_uri(str(sink)),
                ValueError(
                    f"pipeline sink must be a lake:// URI, got {sink!r}"
                ),
            )
        self.sink = None if sink is None else str(sink)

    @property
    def uuid(self) -> str:
        """Deterministic identity: same (source, shape) -> same progress
        manifest across restarts."""
        from fugue_tpu.utils.hash import to_uuid

        return to_uuid(
            "stream.pipeline",
            self.source,
            self.keys,
            [list(a) for a in self.aggs],
            sorted((self.window or {}).items(), key=lambda kv: kv[0]),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "source": self.source,
            "keys": list(self.keys),
            "aggs": [list(a) for a in self.aggs],
            "window": dict(self.window) if self.window else None,
            "pattern": self.pattern,
            "interval": self.interval,
            "max_files_per_batch": self.max_files_per_batch,
            "batch_rows": self.batch_rows,
            "progress": self.progress,
            "sink": self.sink,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PipelineSpec":
        # .get, not [] — a missing field must surface as the
        # constructor's ValueError (HTTP 400), never a KeyError (404)
        return cls(
            d.get("name") or "",
            d.get("source") or "",
            list(d.get("keys") or []),
            [tuple(a) for a in (d.get("aggs") or [])],
            window=d.get("window"),
            pattern=d.get("pattern", "*.parquet"),
            interval=float(d.get("interval", 0.0) or 0.0),
            max_files_per_batch=int(d.get("max_files_per_batch", 0) or 0),
            batch_rows=int(d.get("batch_rows", 0) or 0),
            progress=d.get("progress"),
            sink=d.get("sink"),
        )

    @classmethod
    def from_conf(
        cls,
        conf: Any,
        name: str,
        keys: List[str],
        aggs: List[Tuple[str, str, str]],
        window: Optional[Dict[str, Any]] = None,
        progress: Optional[str] = None,
    ) -> "PipelineSpec":
        """Build a spec from the ``fugue.stream.*`` conf keys (source,
        pattern, interval, lateness, batch caps) — the conf-driven
        construction FWF506 lints. With ``fugue.workflow.resume`` on and
        a checkpoint path set, the progress manifest defaults under the
        checkpoint dir (exactly-once restart); resume off keeps the
        pipeline EPHEMERAL — exactly what FWF506 warns about."""
        window = dict(window) if window else None
        if window is not None and "delay" not in window:
            window["delay"] = typed_conf_get(
                conf, FUGUE_CONF_STREAM_WATERMARK_DELAY
            )
        if progress is None and typed_conf_get(
            conf, FUGUE_CONF_WORKFLOW_RESUME
        ):
            base = str(
                typed_conf_get(conf, FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH)
                or ""
            ).strip()
            if base:
                from fugue_tpu.fs.base import join_uri

                progress = join_uri(
                    base, f"stream_progress_{name}.json"
                )
        return cls(
            name,
            typed_conf_get(conf, FUGUE_CONF_STREAM_SOURCE),
            keys,
            aggs,
            window=window,
            pattern=typed_conf_get(conf, FUGUE_CONF_STREAM_PATTERN),
            interval=typed_conf_get(conf, FUGUE_CONF_STREAM_INTERVAL),
            max_files_per_batch=typed_conf_get(
                conf, FUGUE_CONF_STREAM_MAX_FILES
            ),
            batch_rows=typed_conf_get(conf, FUGUE_CONF_STREAM_BATCH_ROWS),
            progress=progress,
        )


class StandingPipeline:
    """One standing micro-batch pipeline against one engine.

    ``on_refresh(df)`` receives the freshly-finalized JaxDataFrame per
    emission — the materialized-view swap point (serve binds
    ``session.save_table`` here, which bumps the catalog epoch and
    journals the durable artifact)."""

    def __init__(
        self,
        engine: Any,
        spec: PipelineSpec,
        on_refresh: Optional[Callable[[Any], None]] = None,
    ):
        self._engine = engine
        self.spec = spec
        fs = engine.fs
        self._source = ParquetTailSource(fs, spec.source, spec.pattern)
        self._progress = StreamProgress(
            fs, spec.progress, spec.uuid, log=engine.log
        )
        self._on_refresh = on_refresh
        self._agg: Optional[StreamingAggregator] = None
        self._max_event: Optional[float] = None
        self._dropped_null_event_rows = 0
        self._last_step: Optional[Dict[str, Any]] = None
        self._last_refresh_at: Optional[float] = None
        # serializes STEPS via a claim flag: the lock itself is held
        # only for O(1) flag/counter flips, never across fold/IO —
        # concurrent step attempts coalesce instead of queueing
        self._lock = tracked_lock("stream.pipeline.StandingPipeline._lock")
        self._busy = False
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # exactly-once restore: last committed micro-batch's accumulator
        # state comes back onto device; un-committed files re-discover
        if self._progress.load() and self._progress.state is not None:
            self._agg = StreamingAggregator.from_snapshot(
                engine, self._progress.state
            )
            wm = self._progress.watermark
            if wm is not None and self.spec.window is not None:
                self._max_event = float(wm) + float(
                    self.spec.window.get("delay", 0.0)
                )
        metrics = engine.metrics
        self._m_batches = metrics.counter(
            "fugue_stream_batches_total",
            "committed micro-batches per standing pipeline",
            ["pipeline"],
        )
        self._m_files = metrics.counter(
            "fugue_stream_files_total",
            "source files folded per standing pipeline",
            ["pipeline"],
        )
        self._m_rows = metrics.counter(
            "fugue_stream_rows_total",
            "rows folded per standing pipeline",
            ["pipeline"],
        )
        self._m_refreshes = metrics.counter(
            "fugue_stream_view_refreshes_total",
            "materialized-view refreshes per standing pipeline",
            ["pipeline"],
        )
        self._m_freshness = metrics.histogram(
            "fugue_stream_freshness_seconds",
            "file arrival (mtime) to queryable-view latency",
            ["pipeline"],
        )
        for fam in (
            self._m_batches, self._m_files, self._m_rows, self._m_refreshes
        ):
            fam.labels(pipeline=spec.name)

    # ---- observability ---------------------------------------------------
    @property
    def progress(self) -> StreamProgress:
        return self._progress

    @property
    def watermark(self) -> Optional[float]:
        if self.spec.window is None or self._max_event is None:
            return None
        return self._max_event - float(self.spec.window.get("delay", 0.0))

    def stats(self) -> Dict[str, Any]:
        agg = self._agg
        return {
            "aggregator": agg.stats() if agg is not None else None,
            "progress": self._progress.describe(),
            "watermark": self.watermark,
            "dropped_null_event_rows": self._dropped_null_event_rows,
            "mutated_files": list(self._source.mutated_files),
        }

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            last = dict(self._last_step or {})
            busy = self._busy
        return {
            "name": self.spec.name,
            "source": self.spec.source,
            "pattern": self.spec.pattern,
            "keys": list(self.spec.keys),
            "aggs": [list(a) for a in self.spec.aggs],
            "window": dict(self.spec.window) if self.spec.window else None,
            "interval": self.spec.interval,
            "busy": busy,
            "last_step": last,
            **self.stats(),
        }

    # ---- stepping --------------------------------------------------------
    def step(self, force_refresh: bool = False) -> Dict[str, Any]:
        """Run ONE micro-batch now (discover → fold → commit → refresh).
        Concurrent steps coalesce: a second caller gets
        ``{"skipped": "busy"}`` instead of double-folding."""
        with self._lock:
            if self._busy:
                return {"pipeline": self.spec.name, "skipped": "busy"}
            self._busy = True
        try:
            report = self._step_impl(force_refresh)
        finally:
            with self._lock:
                self._busy = False
        with self._lock:
            self._last_step = report
        return report

    def _step_impl(self, force_refresh: bool) -> Dict[str, Any]:
        t0 = time.monotonic()
        entries = self._source.discover(
            self._progress.consumed, self.spec.max_files_per_batch
        )
        if self.spec.sink is not None and entries:
            entries = self._restrict_to_dangling_sink_batch(entries)
        report: Dict[str, Any] = {
            "pipeline": self.spec.name,
            "files": len(entries),
            "rows": 0,
            "batches": self._progress.batches,
            "refreshed": False,
        }
        if not entries:
            # idle tick — but a commit whose refresh never confirmed
            # (kill between commit and swap) still re-emits here
            if force_refresh or not self._progress.refreshed:
                report["refreshed"] = self._refresh()
            report["secs"] = round(time.monotonic() - t0, 4)
            return report
        with start_span(
            "stream.batch", pipeline=self.spec.name, files=len(entries)
        ):
            rows = 0
            sink_chunks: List[pd.DataFrame] = []
            try:
                for e in entries:
                    for chunk in read_parquet_chunks(
                        self._engine.fs, e.path, self.spec.batch_rows
                    ):
                        if self.spec.sink is not None and len(chunk) > 0:
                            # RAW rows (pre-windowing: the sink is the
                            # faithful event log, not the aggregate)
                            sink_chunks.append(chunk)
                        chunk = self._prepare(chunk)
                        if len(chunk) == 0:
                            continue
                        agg = self._ensure_aggregator(e.path, chunk)
                        # device dispatch serializes with concurrent
                        # serve jobs sharing the engine
                        with engine_dispatch_guard(self._engine, None):
                            rows += agg.fold(chunk)
                # window-state retention: evict closed windows that
                # fell behind the retention horizon BEFORE the commit,
                # so the snapshot (and the restart) carry the bounded
                # state — without this the window-id span grows with
                # wall time until it exceeds the bin cap and wedges
                # the pipeline
                self._evict_expired_windows()
                # THE exactly-once boundary: consumed set + state
                # snapshot land atomically, BEFORE the view swap
                # publishes anything. Ephemeral pipelines keep the
                # snapshot in memory too — it is the rollback point a
                # failed LATER step restores.
                # lake sink append FIRST, then the progress commit that
                # references its committed version: a kill between the
                # two leaves a DANGLING lake batch whose writer token
                # carries this batch's file list — the restart restricts
                # re-discovery to exactly those files, refolds them, and
                # the idempotent append dedupes instead of doubling
                lake_version = self._append_sink(sink_chunks, entries)
                self._progress.commit(
                    entries,
                    self._agg.snapshot() if self._agg is not None else None,
                    self.watermark,
                    rows,
                    lake_version=lake_version,
                )
            except BaseException:
                # a step that dies AFTER folding began (unreadable
                # file, NULL keys mid-file, failing commit) must not
                # leave the partial fold in the LIVE accumulator: the
                # next tick re-discovers the same files and would
                # double-count them. Roll the device state back to the
                # last committed snapshot — the in-process twin of the
                # process-death restart path.
                self._rollback_to_committed()
                raise
            report["rows"] = rows
            report["batches"] = self._progress.batches
            report["refreshed"] = self._refresh()
        self._m_batches.labels(pipeline=self.spec.name).inc()
        self._m_files.labels(pipeline=self.spec.name).inc(len(entries))
        self._m_rows.labels(pipeline=self.spec.name).inc(rows)
        if report["refreshed"]:
            now = time.time()
            for e in entries:
                if e.mtime > 0:
                    self._m_freshness.labels(
                        pipeline=self.spec.name
                    ).observe(max(0.0, now - e.mtime))
        report["secs"] = round(time.monotonic() - t0, 4)
        return report

    def _sink_table(self) -> Any:
        from fugue_tpu.lake import LakeTable, parse_lake_uri

        table_uri, _ = parse_lake_uri(self.spec.sink)
        return LakeTable(
            table_uri, fs=self._engine.fs,
            conf=getattr(self._engine, "conf", None) or {},
        )

    def _restrict_to_dangling_sink_batch(self, entries: List[Any]) -> List[Any]:
        """Crash recovery for the lake sink: if the NEXT batch number
        already committed to the lake (we died between the lake append
        and the progress commit), replay exactly the file set that
        append covered — new arrivals wait one tick. The refolded batch
        then dedupes against the existing lake commit and the progress
        record converges. Only meaningful with durable progress (an
        ephemeral pipeline restarts at batch 0 and must not dedupe
        against a prior life's numbering)."""
        if not self._progress.durable:
            return entries
        try:
            dangling = self._sink_table().find_writer_commit(
                self.spec.uuid, self._progress.batches + 1
            )
        except Exception:  # pragma: no cover - sink unreachable: fold on
            return entries
        if dangling is None:
            return entries
        files = set((dangling.writer or {}).get("files") or [])
        if not files:
            return entries
        replay = [e for e in entries if e.path in files]
        return replay if replay else entries

    def _append_sink(
        self, chunks: List[pd.DataFrame], entries: List[Any]
    ) -> Optional[int]:
        """Append the batch's raw rows to the lake sink; returns the
        committed version (referenced by the progress manifest). The
        writer token (pipeline uuid + batch number + file list) makes
        the append idempotent under crash-replay."""
        if self.spec.sink is None or not chunks:
            return None
        table = pa.concat_tables(
            [
                pa.Table.from_pandas(c, preserve_index=False)
                for c in chunks
            ],
            promote_options="default",
        )
        lt = self._sink_table()
        if self._progress.durable:
            manifest = lt.append(
                table,
                writer_id=self.spec.uuid,
                writer_batch=self._progress.batches + 1,
                writer_meta={"files": sorted(e.path for e in entries)},
            )
        else:
            manifest = lt.append(table)
        return manifest.version

    def _evict_expired_windows(self) -> None:
        """Drop window slots older than ``retention`` closed windows
        behind the watermark. Amortized: eviction only runs once at
        least ``retention`` slots are droppable, so the (total-changing)
        retrace it causes happens at most once per retention-span of
        event time."""
        w = self.spec.window
        if w is None or int(w.get("retention", 0) or 0) <= 0:
            return
        wm = self.watermark
        agg = self._agg
        if wm is None or agg is None or agg.empty:
            return
        retention = int(w["retention"])
        size = float(w["size"])
        cutoff_id = int(np.floor(wm / size)) - retention
        bounds = agg.key_bounds
        lo = bounds[0][0]  # leading key IS the window id
        if cutoff_id - lo >= retention:
            agg.evict_leading_below(cutoff_id)

    def _rollback_to_committed(self) -> None:
        """Discard un-committed device state: restore the aggregator
        (and watermark clock) from the last committed snapshot, or
        reset to empty when nothing ever committed. The restored
        update program re-traces once on the next fold — correctness
        over the one saved trace."""
        state = self._progress.state
        if state is not None:
            try:
                self._agg = StreamingAggregator.from_snapshot(
                    self._engine, state
                )
            except Exception:  # pragma: no cover - corrupt snapshot
                self._agg = None
        else:
            self._agg = None
        wm = self._progress.watermark
        if wm is not None and self.spec.window is not None:
            self._max_event = float(wm) + float(
                self.spec.window.get("delay", 0.0)
            )
        else:
            self._max_event = None

    def _prepare(self, chunk: pd.DataFrame) -> pd.DataFrame:
        """Event-time windowing: bucket rows into fixed windows of the
        event column (the window id becomes the leading group key) and
        advance the max event time the watermark derives from. Rows
        with a NULL event time cannot be assigned a window and are
        dropped (counted) — Structured Streaming's convention."""
        w = self.spec.window
        if w is None:
            return chunk
        col = w["column"]
        size = float(w["size"])
        ts = pd.to_numeric(chunk[col], errors="coerce").to_numpy(
            dtype=np.float64
        )
        valid = ~np.isnan(ts)
        if not valid.all():
            self._dropped_null_event_rows += int((~valid).sum())
            chunk = chunk.loc[valid]
            ts = ts[valid]
        if len(ts):
            mx = float(ts.max())
            self._max_event = (
                mx if self._max_event is None else max(self._max_event, mx)
            )
        out = chunk.copy()
        out[w["emit_as"]] = np.floor(ts / size).astype(np.int64)
        return out

    def _ensure_aggregator(
        self, path: str, chunk: pd.DataFrame
    ) -> StreamingAggregator:
        """Type the aggregator off the FIRST arriving file's footer
        (chunk dtypes as fallback); window pipelines lead with the
        window-id key."""
        if self._agg is not None:
            return self._agg
        from fugue_tpu.schema import Schema

        schema = schema_of_parquet(self._engine.fs, path)
        if schema is None:
            schema = Schema(pa.Schema.from_pandas(chunk))
        keys = list(self.spec.keys)
        if self.spec.window is not None:
            emit_as = self.spec.window["emit_as"]
            assert_or_throw(
                emit_as not in schema,
                ValueError(
                    f"window emit_as column {emit_as!r} collides with a "
                    "source column"
                ),
            )
            fields = [pa.field(emit_as, pa.int64())] + list(schema.fields)
            schema = Schema(fields)
            keys = [emit_as] + keys
        for k in keys + [s for _, _, s in self.spec.aggs]:
            assert_or_throw(
                k in schema,
                ValueError(f"column {k!r} not in source schema {schema}"),
            )
        # pad_spans: key-dictionary growth within the padded space must
        # not recompile — the standing-pipeline steady state
        self._agg = StreamingAggregator(
            self._engine, schema, keys, self.spec.aggs, pad_spans=True
        )
        return self._agg

    # ---- emission --------------------------------------------------------
    def _emission_filters(self) -> Tuple[Any, Any]:
        w = self.spec.window
        if w is None:
            return None, None
        size = float(w["size"])
        emit_as = w["emit_as"]
        wm = self.watermark

        def closed(keys: Dict[str, np.ndarray]) -> np.ndarray:
            ids = keys[emit_as]
            if wm is None:
                return np.zeros(len(ids), dtype=bool)
            return (ids + 1) * size <= wm

        int_size = float(size).is_integer()
        tp = pa.int64() if int_size else pa.float64()

        def starts(ids: np.ndarray) -> np.ndarray:
            return (
                (ids * int(size)).astype(np.int64)
                if int_size
                else ids.astype(np.float64) * size
            )

        return closed, {emit_as: (starts, tp)}

    def _refresh(self) -> bool:
        """Materialize the current state and hand it to the registered
        view swap. Windowed pipelines emit CLOSED windows only (the
        watermark has passed their end); complete-mode pipelines emit
        every group. False when nothing is emittable yet."""
        agg = self._agg
        if agg is None or agg.empty:
            return False
        key_filter, key_transform = self._emission_filters()
        with engine_dispatch_guard(self._engine, None):
            df = agg.finalize(
                key_filter=key_filter, key_transform=key_transform
            )
        if df is None:
            # nothing emittable YET (e.g. no window closed): the commit
            # is settled — without this, every idle tick would redo the
            # full device->host finalize. The watermark only advances on
            # a fold, and a fold re-opens the pending flag via commit.
            self._progress.mark_refreshed()
            return False
        # the swap runs OUTSIDE the dispatch guard: a serve-bound
        # on_refresh (session.save_table) acquires the SESSION lock
        # first and the dispatch lock inside — holding the dispatch
        # lock across the callback would invert that order against a
        # concurrent job already inside save_table (ABBA deadlock)
        if self._on_refresh is not None:
            self._on_refresh(df)
        self._progress.mark_refreshed()
        self._last_refresh_at = time.time()
        self._m_refreshes.labels(pipeline=self.spec.name).inc()
        return True

    def refresh(self) -> bool:
        """Force one view emission from the CURRENT state (no folding) —
        what a restarted daemon calls so a commit-then-kill batch still
        publishes."""
        with self._lock:
            if self._busy:
                return False
            self._busy = True
        try:
            return self._refresh()
        finally:
            with self._lock:
                self._busy = False

    # ---- ticker ----------------------------------------------------------
    def start(self) -> "StandingPipeline":
        """Start the poll ticker (``spec.interval`` > 0); manual
        ``step()`` keeps working alongside (steps coalesce)."""
        if self.spec.interval <= 0 or self._thread is not None:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._tick_loop,
            daemon=True,
            name=f"fugue-stream-{self.spec.name}",
        )
        self._thread.start()
        return self

    def _tick_loop(self) -> None:
        while not self._stop_evt.wait(self.spec.interval):
            try:
                self.step()
            except Exception as ex:  # keep ticking: transient fs errors
                self._engine.log.warning(
                    "fugue_tpu stream: pipeline %s step failed (%s: %s); "
                    "retrying next tick",
                    self.spec.name, type(ex).__name__, ex,
                )

    def stop(self) -> None:
        self._stop_evt.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None
