"""Exactly-once progress manifest for a standing pipeline.

Per committed micro-batch the driver atomically rewrites ONE JSON file
(:func:`fugue_tpu.workflow.manifest.atomic_json_write` — the same crash-
durability primitive as the run manifest and the serve journal)::

    {"pipeline": <id>, "batches": n, "rows": n,
     "consumed": {path: {"size": ..., "mtime": ...}},
     "watermark": <max event time seen - delay, or null>,
     "state": <StreamingAggregator.snapshot()>, "refreshed": bool}

The commit point IS the exactly-once boundary:

- killed MID-FOLD (before commit): the manifest still holds the
  pre-batch accumulator snapshot and the pre-batch consumed set — the
  restart restores that state and re-discovers the un-consumed files,
  so the interrupted fold re-runs from exactly where it started.
  Nothing the torn fold pushed onto the device survives the process,
  so nothing is double-counted.
- killed BETWEEN commit and view refresh: the state is committed with
  ``refreshed=false``; the restart re-emits the view from the restored
  snapshot without re-folding anything.

Concurrency contract: a StreamProgress instance is only touched by the
pipeline's CLAIMED step (the driver serializes steps through a busy
flag, not by holding a lock across this IO), so no lock lives here.
"""

from typing import Any, Dict, List, Optional

from fugue_tpu.fs.base import FileInfo
from fugue_tpu.testing.faults import fault_point
from fugue_tpu.workflow.manifest import atomic_json_write, read_json


class StreamProgress:
    """The consumed-file ledger + state checkpoint of one pipeline.
    ``uri=None`` keeps progress in memory only (an EPHEMERAL pipeline:
    a restart refolds from scratch — FWF506's warning subject)."""

    def __init__(
        self, fs: Any, uri: Optional[str], pipeline_id: str, log: Any = None
    ):
        self._fs = fs
        self.uri = uri
        self.pipeline_id = pipeline_id
        self._log = log
        self.consumed: Dict[str, Dict[str, Any]] = {}
        self.batches = 0
        self.rows = 0
        self.watermark: Optional[float] = None
        self.state: Optional[Dict[str, Any]] = None
        self.refreshed = True
        self.restored = False
        # version of the lake-sink snapshot the last committed batch
        # appended (None: no sink, or nothing appended yet) — the
        # exactly-once cross-reference between this manifest and the
        # versioned table
        self.lake_version: Optional[int] = None

    @property
    def durable(self) -> bool:
        return self.uri is not None

    def load(self) -> bool:
        """Read a prior run's manifest; True when prior state existed
        (the pipeline restarts from its last committed micro-batch)."""
        if self.uri is None:
            return False
        data = read_json(
            self._fs, self.uri, log=self._log, what="stream progress manifest"
        )
        if data is None or data.get("pipeline") != self.pipeline_id:
            return False
        self.consumed = dict(data.get("consumed") or {})
        self.batches = int(data.get("batches", 0))
        self.rows = int(data.get("rows", 0))
        self.watermark = data.get("watermark")
        self.state = data.get("state")
        self.refreshed = bool(data.get("refreshed", True))
        lv = data.get("lake_version")
        self.lake_version = None if lv is None else int(lv)
        self.restored = True
        return True

    def _payload(self) -> Dict[str, Any]:
        return {
            "pipeline": self.pipeline_id,
            "batches": self.batches,
            "rows": self.rows,
            "consumed": self.consumed,
            "watermark": self.watermark,
            "state": self.state,
            "refreshed": self.refreshed,
            "lake_version": self.lake_version,
        }

    def commit(
        self,
        entries: List[FileInfo],
        state: Optional[Dict[str, Any]],
        watermark: Optional[float],
        rows: int,
        lake_version: Optional[int] = None,
    ) -> None:
        """Commit one folded micro-batch: consumed set + state snapshot
        land in ONE atomic write (chaos site ``stream.commit``), with
        ``refreshed=False`` until the view refresh confirms. A failing
        durable commit RAISES and applies NOTHING in memory either —
        the fold result must not be observable (via the view or this
        object) without its exactly-once record, or a restart (or a
        retried step) would double-count the batch."""
        staged = dict(self.consumed)
        for e in entries:
            staged[e.path] = {"size": e.size, "mtime": e.mtime}
        payload = {
            "pipeline": self.pipeline_id,
            "batches": self.batches + 1,
            "rows": self.rows + rows,
            "consumed": staged,
            "watermark": watermark,
            "state": state,
            "refreshed": False,
            "lake_version": (
                lake_version
                if lake_version is not None
                else self.lake_version
            ),
        }
        if self.uri is not None:
            fault_point("stream.commit", self.uri)
            atomic_json_write(self._fs, self.uri, payload)
        # durable record landed (or the pipeline is ephemeral): the
        # in-memory view now matches it exactly
        self.consumed = staged
        self.batches += 1
        self.rows += rows
        self.state = state
        self.watermark = watermark
        self.refreshed = False
        if lake_version is not None:
            self.lake_version = int(lake_version)

    def mark_refreshed(self) -> None:
        """The view refresh landed: record it so a restart does not
        re-emit an already-published snapshot. Best-effort — a failed
        write only means one redundant (idempotent) refresh later."""
        self.refreshed = True
        if self.uri is None:
            return
        try:
            atomic_json_write(self._fs, self.uri, self._payload())
        except Exception:  # pragma: no cover - degraded durability only
            if self._log is not None:
                self._log.warning(
                    "fugue_tpu stream: refresh marker write to %s failed; "
                    "the next restart re-emits the view once",
                    self.uri,
                )

    def clear(self) -> None:
        """Remove the manifest (pipeline removal). Idempotent."""
        if self.uri is None:
            return
        try:
            self._fs.rm(self.uri)
        except Exception:  # pragma: no cover - best effort
            pass

    def describe(self) -> Dict[str, Any]:
        return {
            "uri": self.uri,
            "batches": self.batches,
            "rows": self.rows,
            "files_consumed": len(self.consumed),
            "watermark": self.watermark,
            "refreshed": self.refreshed,
            "restored": self.restored,
            "lake_version": self.lake_version,
        }
