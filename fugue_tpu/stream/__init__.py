"""Continuous execution: standing pipelines over arriving data.

The subsystem composing the repo's batch parts into Spark-Structured-
Streaming's micro-batch role (ROADMAP open item 4):

- :mod:`fugue_tpu.stream.source` — a tail source discovering new
  parquet files/URIs through the fs layer in deterministic
  (mtime, name) order, with a consumed-file ledger;
- :mod:`fugue_tpu.stream.progress` — the exactly-once progress
  manifest: consumed-file set + accumulator-state checkpoint,
  atomically rewritten per committed micro-batch;
- :mod:`fugue_tpu.stream.pipeline` — the micro-batch driver: groupby/
  window accumulator state carried ACROSS micro-batches on device
  (:class:`~fugue_tpu.jax_backend.streaming.StreamingAggregator`),
  watermark-based emission for event-time windows;
- :mod:`fugue_tpu.stream.view` — the serving loop closure: a standing
  pipeline maintaining a serve session table as a continuously-
  refreshed materialized view (each refresh bumps the catalog epoch so
  the serve result caches self-invalidate).
"""

from fugue_tpu.stream.pipeline import PipelineSpec, StandingPipeline
from fugue_tpu.stream.progress import StreamProgress
from fugue_tpu.stream.source import ParquetTailSource, read_parquet_chunks
from fugue_tpu.stream.view import MaterializedView

__all__ = [
    "MaterializedView",
    "ParquetTailSource",
    "PipelineSpec",
    "StandingPipeline",
    "StreamProgress",
    "read_parquet_chunks",
]
