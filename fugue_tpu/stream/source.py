"""The tail source: discover newly-arrived parquet files through the fs
layer.

Discovery walks the source directory's direct children through
``fs.list_chronological`` — deterministic (mtime, name) order, dot/
underscore temps skipped — and subtracts the consumed-file ledger the
progress manifest carries. The ledger is a SET, not a high-watermark:
a file landing with an mtime OLDER than something already consumed (an
out-of-order copy onto shared storage) is still discovered on the next
poll, it just sorts earlier within its batch.

Files are treated as IMMUTABLE once consumed (the parquet convention:
writers land a complete file under a temp name and rename it in). A
consumed path whose recorded (size, mtime) changed is NOT re-folded —
re-folding would double-count every row the first fold already
committed — it is surfaced through ``mutated_files`` so the operator
sees the contract violation.
"""

from typing import Any, Dict, Iterator, List, Optional

import pandas as pd

from fugue_tpu.fs.base import FileInfo


def read_parquet_chunks(
    fs: Any, uri: str, batch_rows: int = 0
) -> Iterator[pd.DataFrame]:
    """Stream one parquet file as pandas chunks through the fs layer
    (``fs.open_input_stream`` keeps the fault sites and URI schemes in
    play). ``batch_rows`` bounds rows per chunk; 0 uses pyarrow's
    record-batch default."""
    import pyarrow.parquet as pq

    with fs.open_input_stream(uri) as fp:
        pf = pq.ParquetFile(fp)
        kwargs: Dict[str, Any] = {}
        if batch_rows > 0:
            kwargs["batch_size"] = batch_rows
        for batch in pf.iter_batches(**kwargs):
            yield batch.to_pandas()


class ParquetTailSource:
    """Tail a directory URI for new parquet files."""

    def __init__(self, fs: Any, path: str, pattern: str = "*.parquet"):
        self._fs = fs
        self.path = str(path).rstrip("/")
        self.pattern = pattern
        # consumed-but-changed paths observed by discover(): an operator
        # signal (immutability contract violation), never re-folded
        self.mutated_files: List[str] = []

    def discover(
        self,
        consumed: Dict[str, Dict[str, Any]],
        max_files: int = 0,
    ) -> List[FileInfo]:
        """New files in deterministic (mtime, name) order, minus the
        consumed ledger; at most ``max_files`` when > 0 (the rest stays
        for the next micro-batch — discovery is idempotent)."""
        out: List[FileInfo] = []
        for info in self._fs.list_chronological(self.path, self.pattern):
            rec = consumed.get(info.path)
            if rec is not None:
                changed = int(rec.get("size", -1)) != info.size or float(
                    rec.get("mtime", -1.0)
                ) != info.mtime
                if changed and info.path not in self.mutated_files:
                    self.mutated_files.append(info.path)
                continue
            out.append(info)
            if max_files > 0 and len(out) >= max_files:
                break
        return out

    def describe(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "pattern": self.pattern,
            "mutated_files": list(self.mutated_files),
        }


def schema_of_parquet(fs: Any, uri: str) -> Optional[Any]:
    """The fugue Schema of one parquet file's footer (None on failure) —
    how a standing pipeline types itself off the FIRST arriving file."""
    import pyarrow.parquet as pq

    from fugue_tpu.schema import Schema

    try:
        with fs.open_input_stream(uri) as fp:
            return Schema(pq.ParquetFile(fp).schema_arrow)
    except Exception:
        return None
