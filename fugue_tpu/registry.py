"""Builtin registrations: the native engine under names ``native``/``pandas``
and dataset display fallbacks (backend registration pattern parity:
reference fugue_spark/registry.py etc; the jax backend registers itself in
fugue_tpu/jax_backend/registry.py)."""

from typing import Any

from fugue_tpu.execution.factory import (
    register_default_execution_engine,
    register_execution_engine,
)
from fugue_tpu.execution.native_execution_engine import NativeExecutionEngine


def _register() -> None:
    register_execution_engine(
        "native", lambda conf, **kwargs: NativeExecutionEngine(conf)
    )
    register_execution_engine(
        "pandas", lambda conf, **kwargs: NativeExecutionEngine(conf)
    )
    register_default_execution_engine(
        lambda conf, **kwargs: NativeExecutionEngine(conf)
    )
    try:
        import fugue_tpu.jax_backend.registry  # noqa: F401
    except ImportError:  # pragma: no cover - jax backend is part of the pkg
        pass


_register()
