"""Engine registry + resolution (reference fugue/execution/factory.py:18-508).

Resolution order for ``make_execution_engine(None)``: contextual engine ->
global engine -> inferred from input objects -> registered default -> native.
"""

from typing import Any, Callable, Dict, List, Optional, Tuple, Type, Union

from fugue_tpu.execution.execution_engine import (
    _CONTEXT_ENGINE,
    _GLOBAL_ENGINE,
    ExecutionEngine,
    SQLEngine,
)
from fugue_tpu.plugins import fugue_plugin
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.params import ParamDict

_ENGINE_FACTORY: Dict[str, Callable[..., ExecutionEngine]] = {}
_SQL_ENGINE_FACTORY: Dict[str, Callable[..., SQLEngine]] = {}
_DEFAULT_FACTORY: List[Optional[Callable[..., ExecutionEngine]]] = [None]


def register_execution_engine(
    name_or_type: Union[str, Type], func: Callable[..., ExecutionEngine],
    on_dup: str = "overwrite",
) -> None:
    """Register an engine factory under a name (``func(conf, **kwargs)``)."""
    if isinstance(name_or_type, str):
        key = name_or_type.lower()
        assert_or_throw(
            on_dup in ("overwrite", "throw", "ignore"),
            ValueError(f"invalid on_dup {on_dup}"),
        )
        if key in _ENGINE_FACTORY:
            if on_dup == "throw":
                raise KeyError(f"engine {key} already registered")
            if on_dup == "ignore":
                return
        _ENGINE_FACTORY[key] = func
    else:
        # register by type: handled through the parse plugin
        t = name_or_type

        @parse_execution_engine.candidate(
            lambda engine, conf, **kwargs: isinstance(engine, t)
        )
        def _parse(engine: Any, conf: Any, **kwargs: Any) -> ExecutionEngine:
            return func(engine, conf, **kwargs)


def register_default_execution_engine(
    func: Callable[..., ExecutionEngine], on_dup: str = "overwrite"
) -> None:
    _DEFAULT_FACTORY[0] = func


def register_sql_engine(name: str, func: Callable[..., SQLEngine],
                        on_dup: str = "overwrite") -> None:
    key = name.lower()
    if key in _SQL_ENGINE_FACTORY:
        if on_dup == "throw":
            raise KeyError(f"sql engine {key} already registered")
        if on_dup == "ignore":
            return
    _SQL_ENGINE_FACTORY[key] = func


def register_default_sql_engine(func: Callable[..., SQLEngine]) -> None:
    _SQL_ENGINE_FACTORY[""] = func


@fugue_plugin
def parse_execution_engine(engine: Any, conf: Any, **kwargs: Any) -> ExecutionEngine:
    """Plugin: convert an arbitrary object (session, url, ...) to an engine."""
    raise NotImplementedError(f"can't parse execution engine from {engine!r}")


@fugue_plugin
def infer_execution_engine(objs: List[Any]) -> Any:
    """Plugin: infer the engine identifier from input dataframes (e.g. a jax
    block frame infers the jax engine)."""
    return None


@fugue_plugin
def parse_sql_engine(engine: Any, execution_engine: ExecutionEngine,
                     **kwargs: Any) -> SQLEngine:
    raise NotImplementedError(f"can't parse sql engine from {engine!r}")


def try_get_context_engine() -> Optional[ExecutionEngine]:
    eng = _CONTEXT_ENGINE.get()
    if eng is not None:
        return eng
    return _GLOBAL_ENGINE[0]


def make_sql_engine(
    engine: Any = None,
    execution_engine: Optional[ExecutionEngine] = None,
    **kwargs: Any,
) -> SQLEngine:
    if engine is None:
        assert_or_throw(execution_engine is not None, ValueError("no engine"))
        return execution_engine.sql_engine  # type: ignore
    if isinstance(engine, SQLEngine):
        return engine
    if isinstance(engine, type) and issubclass(engine, SQLEngine):
        return engine(execution_engine, **kwargs)
    if isinstance(engine, str) and engine.lower() in _SQL_ENGINE_FACTORY:
        return _SQL_ENGINE_FACTORY[engine.lower()](execution_engine, **kwargs)
    return parse_sql_engine(engine, execution_engine, **kwargs)


def make_execution_engine(
    engine: Any = None,
    conf: Any = None,
    infer_by: Optional[List[Any]] = None,
    **kwargs: Any,
) -> ExecutionEngine:
    """Resolve anything engine-like into a live ExecutionEngine (reference
    factory.py:237-339)."""
    conf = ParamDict(conf)
    if isinstance(engine, tuple):
        execution_engine = make_execution_engine(engine[0], conf, infer_by, **kwargs)
        execution_engine.sql_engine = make_sql_engine(engine[1], execution_engine)
        return execution_engine
    if isinstance(engine, ExecutionEngine):
        if len(conf) > 0:
            engine.conf.update(conf)
        return engine
    if engine is None:
        ctx = try_get_context_engine()
        if ctx is not None:
            if len(conf) > 0:
                ctx.conf.update(conf)
            return ctx
        if infer_by is not None:
            inferred = infer_execution_engine(infer_by)
            if inferred is not None:
                return make_execution_engine(inferred, conf, None, **kwargs)
        if _DEFAULT_FACTORY[0] is not None:
            return _DEFAULT_FACTORY[0](conf, **kwargs)
        engine = "native"
    if isinstance(engine, str):
        key = engine.lower()
        if ":" in key:  # "engine:sql_engine" shorthand
            parts = key.split(":", 1)
            return make_execution_engine((parts[0], parts[1]), conf, infer_by, **kwargs)
        if key in _ENGINE_FACTORY:
            return _ENGINE_FACTORY[key](conf, **kwargs)
        return parse_execution_engine(engine, conf, **kwargs)
    if isinstance(engine, type) and issubclass(engine, ExecutionEngine):
        return engine(conf, **kwargs)
    return parse_execution_engine(engine, conf, **kwargs)
