"""ExecutionEngine: THE backend contract, with MapEngine and SQLEngine facets.

Parity target: reference ``fugue/execution/execution_engine.py:339`` (engine
abstract ops :480-1181, MapEngine :278-316, SQLEngine :184-275, zip/comap
:969-1118, serialize-by-partition :1221-1360) — re-designed: the co-partition
(zip/comap) data plane carries arrow-IPC blobs instead of pickled pandas, and
``select/filter/assign/aggregate`` have engine-overridable defaults instead of
being hard-wired through a SQL engine.
"""

import logging
from abc import ABC, abstractmethod
from contextlib import contextmanager
from contextvars import ContextVar
from threading import local
from typing import TYPE_CHECKING, Any, Callable, Dict, Iterator, List, Optional, Union
from uuid import uuid4

if TYPE_CHECKING:  # pragma: no cover
    from fugue_tpu.fs import FileSystemRegistry

from fugue_tpu.collections.partition import PartitionCursor, PartitionSpec
from fugue_tpu.collections.sql import StructuredRawSQL
from fugue_tpu.collections.yielded import PhysicalYielded, Yielded
from fugue_tpu.column.expressions import ColumnExpr
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.constants import FUGUE_GLOBAL_CONF
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.dataframe import (
    ArrayDataFrame,
    DataFrame,
    DataFrames,
    LocalDataFrame,
)
from fugue_tpu.dataframe.utils import deserialize_df, serialize_df
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.params import ParamDict

AnyDataFrame = Any

_FUGUE_SER_KEY = "_fugue_ser_data"
_FUGUE_SER_NO = "_fugue_ser_no"
_ZIP_SCHEMAS_META = "serialized_schemas"
_ZIP_NAMES_META = "serialized_names"
_ZIP_HOW_META = "serialized_how"

_CONTEXT_ENGINE: ContextVar[Optional["ExecutionEngine"]] = ContextVar(
    "fugue_tpu_engine", default=None
)
_GLOBAL_LOCK = tracked_lock("execution.engine._GLOBAL_LOCK", reentrant=True)
_GLOBAL_ENGINE: List[Optional["ExecutionEngine"]] = [None]


class FugueEngineBase(ABC):
    @property
    @abstractmethod
    def is_distributed(self) -> bool:  # pragma: no cover - interface
        raise NotImplementedError

    @property
    def log(self) -> logging.Logger:
        return logging.getLogger(type(self).__name__)

    @property
    @abstractmethod
    def conf(self) -> ParamDict:  # pragma: no cover - interface
        raise NotImplementedError

    @abstractmethod
    def to_df(self, df: AnyDataFrame, schema: Any = None) -> DataFrame:
        """Convert an arbitrary acceptable object to this engine's DataFrame."""
        raise NotImplementedError  # pragma: no cover


class EngineFacet(FugueEngineBase):
    """A sub-engine sharing its parent's config/log (MapEngine, SQLEngine)."""

    def __init__(self, execution_engine: "ExecutionEngine"):
        self._execution_engine = execution_engine

    @property
    def execution_engine(self) -> "ExecutionEngine":
        return self._execution_engine

    @property
    def conf(self) -> ParamDict:
        return self._execution_engine.conf

    @property
    def log(self) -> logging.Logger:
        return self._execution_engine.log

    def to_df(self, df: AnyDataFrame, schema: Any = None) -> DataFrame:
        return self._execution_engine.to_df(df, schema)


class MapEngine(EngineFacet):
    """The single primitive every parallel op lowers to (reference
    execution_engine.py:278-316): apply ``map_func(cursor, local_df)`` to each
    logical partition."""

    @abstractmethod
    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:  # pragma: no cover - interface
        raise NotImplementedError

    def map_bag(
        self,
        bag: Any,
        map_func: Callable,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable] = None,
    ) -> Any:
        raise NotImplementedError(f"map_bag not supported by {type(self)}")


class SQLEngine(EngineFacet):
    """SQL facet: execute a raw SELECT over named dataframes (reference
    execution_engine.py:184-275)."""

    @property
    def dialect(self) -> Optional[str]:
        return None

    @abstractmethod
    def select(self, dfs: DataFrames, statement: StructuredRawSQL) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    def table_exists(self, table: str) -> bool:
        raise NotImplementedError(f"{type(self)} doesn't support tables")

    def save_table(
        self,
        df: DataFrame,
        table: str,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        **kwargs: Any,
    ) -> None:
        raise NotImplementedError(f"{type(self)} doesn't support tables")

    def load_table(self, table: str, **kwargs: Any) -> DataFrame:
        raise NotImplementedError(f"{type(self)} doesn't support tables")

    def drop_table(self, table: str) -> None:
        """Remove a table from the engine's catalog (no-op if absent)."""
        raise NotImplementedError(f"{type(self)} doesn't support tables")

    def encode_name(self, name: str) -> str:
        return name


class ExecutionEngine(FugueEngineBase):
    """The backend contract (reference execution_engine.py:339). Subclasses
    implement the abstract primitives; relational composites, the co-partition
    plane (zip/comap) and column-algebra ops have engine-agnostic defaults."""

    def __init__(self, conf: Any = None):
        self._conf = ParamDict(FUGUE_GLOBAL_CONF)
        self._conf.update(ParamDict(conf))
        self._map_engine: Optional[MapEngine] = None
        self._sql_engine: Optional[SQLEngine] = None
        self._fs: Optional[Any] = None
        self._metrics: Optional[Any] = None
        self._in_context_count = 0
        self._is_global = False
        # ContextVar tokens must be reset by the thread that created them,
        # so each thread keeps its own token stack — a long-lived engine
        # (the serving daemon) runs many workflows concurrently, each
        # entering/leaving the context on its own worker thread
        self._ctx_local = local()
        self._ctx_lock = tracked_lock(
            "execution.engine.ExecutionEngine._ctx_lock", reentrant=True
        )
        self._stop_lock = tracked_lock(
            "execution.engine.ExecutionEngine._stop_lock", reentrant=True
        )
        self._stopped = False

    # ---- lifecycle & context (reference :363-447) -----------------------
    @property
    def in_context(self) -> bool:
        return self._in_context_count > 0

    @property
    def is_global(self) -> bool:
        return self._is_global

    def as_context(self) -> "ExecutionEngine":
        """Push self as the contextual engine: ``with engine.as_context():``"""
        with self._ctx_lock:
            self._in_context_count += 1
        stack = getattr(self._ctx_local, "tokens", None)
        if stack is None:
            stack = self._ctx_local.tokens = []
        stack.append(_CONTEXT_ENGINE.set(self))
        self.on_enter_context()
        return self

    def __enter__(self) -> "ExecutionEngine":
        return self

    def __exit__(self, *args: Any) -> None:
        self.stop_context()

    def stop_context(self) -> None:
        stack = getattr(self._ctx_local, "tokens", None)
        if stack:
            _CONTEXT_ENGINE.reset(stack.pop())
        with self._ctx_lock:
            if self._in_context_count == 0:
                return
            self._in_context_count -= 1
            should_stop = self._in_context_count == 0 and not self._is_global
        self.on_exit_context()
        if should_stop:
            self.stop()

    def retain(self) -> "ExecutionEngine":
        """Hold the engine alive across context exits WITHOUT becoming
        the ambient context engine. Unlike ``as_context`` this is
        thread-agnostic: ``as_context``'s ContextVar token stack is
        per-thread, so a ``stop_context`` from a different thread (a
        drain thread, a signal handler) would decrement the count but
        leave the starting thread's ambient engine pointing at a stopped
        engine. Long-lived owners that never want ambient resolution —
        the serving daemon — pair ``retain()`` with ``release()``."""
        with self._ctx_lock:
            self._in_context_count += 1
        self.on_enter_context()
        return self

    def release(self) -> None:
        """Drop a ``retain`` hold; stops the engine when the last
        context/hold is gone (and it is not the global engine). Safe
        from any thread."""
        with self._ctx_lock:
            if self._in_context_count == 0:
                return
            self._in_context_count -= 1
            should_stop = self._in_context_count == 0 and not self._is_global
        self.on_exit_context()
        if should_stop:
            self.stop()

    def set_global(self) -> "ExecutionEngine":
        with _GLOBAL_LOCK:
            old = _GLOBAL_ENGINE[0]
            if old is not None and old is not self:
                old._is_global = False
                if not old.in_context:
                    old.stop()
            self._is_global = True
            _GLOBAL_ENGINE[0] = self
        return self

    def unset_global(self) -> None:
        with _GLOBAL_LOCK:
            if _GLOBAL_ENGINE[0] is self:
                _GLOBAL_ENGINE[0] = None
            self._is_global = False

    def on_enter_context(self) -> None:  # pragma: no cover - hook
        pass

    def on_exit_context(self) -> None:  # pragma: no cover - hook
        pass

    def stop(self) -> None:
        with self._stop_lock:
            if not self._stopped:
                self._stopped = True
                self.stop_engine()

    @property
    def task_execution_lock(self) -> Optional[Any]:
        """An engine-wide reentrant lock the workflow layer holds around
        each task's EXECUTION when concurrent workflows share this
        engine, or None when concurrent dispatch is safe (the default).
        Engines whose device runtime cannot take concurrent multi-device
        program dispatch (XLA CPU collectives rendezvous across
        executions and can deadlock when two programs interleave) return
        a real lock: host-side work — SQL compile, planning, queueing,
        result serialization — still overlaps; device programs
        serialize at task granularity."""
        return None

    def stop_engine(self) -> None:  # pragma: no cover - hook
        pass

    # ---- facets ----------------------------------------------------------
    @property
    def conf(self) -> ParamDict:
        return self._conf

    @property
    def map_engine(self) -> MapEngine:
        if self._map_engine is None:
            self._map_engine = self.create_default_map_engine()
        return self._map_engine

    @map_engine.setter
    def map_engine(self, engine: MapEngine) -> None:
        self._map_engine = engine

    @property
    def sql_engine(self) -> SQLEngine:
        if self._sql_engine is None:
            self._sql_engine = self.create_default_sql_engine()
        return self._sql_engine

    @sql_engine.setter
    def sql_engine(self, engine: SQLEngine) -> None:
        self._sql_engine = engine

    @property
    def fs(self) -> "FileSystemRegistry":
        """The engine's URI-routing filesystem (part of the contract,
        reference execution_engine.py:476): every persistence path —
        save/load targets, checkpoint dirs, yield files — resolves
        through it, so ``memory://`` / ``gs://`` URIs work anywhere a
        local path does."""
        if self._fs is None:
            self._fs = self.create_default_fs()
        return self._fs

    @fs.setter
    def fs(self, fs: "FileSystemRegistry") -> None:
        self._fs = fs

    def create_default_fs(self) -> "FileSystemRegistry":
        from fugue_tpu.fs import make_default_registry

        return make_default_registry()

    # ---- observability ---------------------------------------------------
    @property
    def metrics(self) -> Any:
        """The engine's :class:`~fugue_tpu.obs.metrics.MetricsRegistry`
        — the ONE registry every counter surface of this engine (and of
        a serving daemon built on it) registers into. Per-engine by
        design: two engines in one process never share counters. Lazily
        created; always available regardless of ``fugue.obs.enabled``
        (the back-compat dict accessors read through it)."""
        if self._metrics is None:
            from fugue_tpu.obs.metrics import MetricsRegistry

            self._metrics = MetricsRegistry()
        return self._metrics

    # ---- fault tolerance -------------------------------------------------
    @property
    def supports_host_degrade(self) -> bool:
        """True when the engine has a cheaper capacity tier a device-OOM
        task can re-run on (the jax engine's host mesh). The workflow's
        retry executor consults this before counting an OOM as a retry."""
        return False

    def degraded_to_host(self) -> Any:
        """Context manager forcing this THREAD's work onto the host tier.
        Default engines have one tier: a no-op context."""
        from contextlib import nullcontext

        return nullcontext()

    @abstractmethod
    def create_default_map_engine(self) -> MapEngine:  # pragma: no cover
        raise NotImplementedError

    @abstractmethod
    def create_default_sql_engine(self) -> SQLEngine:  # pragma: no cover
        raise NotImplementedError

    @abstractmethod
    def get_current_parallelism(self) -> int:  # pragma: no cover
        raise NotImplementedError

    # ---- abstract primitives (reference :480-1181) ----------------------
    @abstractmethod
    def repartition(self, df: DataFrame, partition_spec: PartitionSpec) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def broadcast(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def persist(
        self,
        df: DataFrame,
        lazy: bool = False,
        **kwargs: Any,
    ) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def join(
        self,
        df1: DataFrame,
        df2: DataFrame,
        how: str,
        on: Optional[List[str]] = None,
    ) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def union(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def subtract(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def intersect(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def distinct(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def dropna(
        self,
        df: DataFrame,
        how: str = "any",
        thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def fillna(self, df: DataFrame, value: Any, subset: Optional[List[str]] = None) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def sample(
        self,
        df: DataFrame,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        seed: Optional[int] = None,
    ) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def take(
        self,
        df: DataFrame,
        n: int,
        presort: str,
        na_position: str = "last",
        partition_spec: Optional[PartitionSpec] = None,
    ) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def load_df(
        self,
        path: Union[str, List[str]],
        format_hint: Any = None,
        columns: Any = None,
        **kwargs: Any,
    ) -> DataFrame:
        raise NotImplementedError  # pragma: no cover

    @abstractmethod
    def save_df(
        self,
        df: DataFrame,
        path: str,
        format_hint: Any = None,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        force_single: bool = False,
        **kwargs: Any,
    ) -> None:
        raise NotImplementedError  # pragma: no cover

    # ---- column-algebra composites (engine-overridable defaults) --------
    def select(
        self,
        df: DataFrame,
        cols: SelectColumns,
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
    ) -> DataFrame:
        """SELECT via the column algebra (reference :743). Default: local
        pandas evaluation; distributed engines should override/push down."""
        from fugue_tpu.column.pandas_eval import eval_select
        from fugue_tpu.dataframe import PandasDataFrame

        out_schema = cols.infer_schema(df.schema)
        pdf = eval_select(df.as_local().as_pandas(), cols, where, having)
        return self.to_df(PandasDataFrame(pdf, out_schema))

    def filter(self, df: DataFrame, condition: ColumnExpr) -> DataFrame:
        from fugue_tpu.column.pandas_eval import eval_filter
        from fugue_tpu.dataframe import PandasDataFrame

        pdf = eval_filter(df.as_local().as_pandas(), condition)
        return self.to_df(PandasDataFrame(pdf, df.schema))

    def assign(self, df: DataFrame, columns: List[ColumnExpr]) -> DataFrame:
        from fugue_tpu.column.pandas_eval import eval_assign
        from fugue_tpu.dataframe import PandasDataFrame

        named = {}
        for c in columns:
            assert_or_throw(c.output_name != "", ValueError(f"{c} has no name"))
            named[c.output_name] = c
        schema = df.schema
        new_fields = []
        for name, expr in named.items():
            tp = expr.infer_type(schema)
            if name in schema:
                if tp is None:
                    tp = schema[name].type
            assert_or_throw(tp is not None, ValueError(f"can't infer type of {expr}"))
            if name in schema:
                schema = schema.alter(Schema([(name, tp)]))
            else:
                new_fields.append((name, tp))
        out_schema = schema + Schema(new_fields)
        pdf = eval_assign(df.as_local().as_pandas(), **named)
        return self.to_df(PandasDataFrame(pdf, out_schema))

    def aggregate(
        self,
        df: DataFrame,
        partition_spec: Optional[PartitionSpec],
        agg_cols: List[ColumnExpr],
    ) -> DataFrame:
        from fugue_tpu.column.pandas_eval import eval_aggregate
        from fugue_tpu.dataframe import PandasDataFrame

        assert_or_throw(len(agg_cols) > 0, ValueError("no aggregations"))
        keys = partition_spec.partition_by if partition_spec is not None else []
        named = {}
        for c in agg_cols:
            assert_or_throw(c.output_name != "", ValueError(f"{c} has no name"))
            named[c.output_name] = c
        fields = [df.schema[k] for k in keys]
        for name, expr in named.items():
            tp = expr.infer_type(df.schema)
            assert_or_throw(tp is not None, ValueError(f"can't infer type of {expr}"))
            fields.append((name, tp))  # type: ignore
        out_schema = Schema(fields)
        pdf = eval_aggregate(df.as_local().as_pandas(), keys, named)
        return self.to_df(PandasDataFrame(pdf[out_schema.names], out_schema))

    # ---- co-partition plane: zip / comap (reference :969-1360) ----------
    def zip(
        self,
        dfs: DataFrames,
        how: str = "inner",
        partition_spec: Optional[PartitionSpec] = None,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> DataFrame:
        """Co-partition multiple dataframes by key: each input becomes rows of
        ``(keys..., serialized_blob, df_no)``; union of all inputs grouped by
        keys is the zipped frame consumed by :meth:`comap`."""
        assert_or_throw(len(dfs) > 0, ValueError("can't zip 0 dataframes"))
        how = how.lower().replace(" ", "_")
        assert_or_throw(
            how in ("inner", "left_outer", "right_outer", "full_outer", "cross"),
            ValueError(f"invalid zip type {how}"),
        )
        partition_spec = partition_spec or PartitionSpec()
        keys: List[str] = partition_spec.partition_by
        if len(keys) == 0 and how != "cross":
            # infer keys: intersection of all schemas
            keys = [
                n
                for n in dfs[0].schema.names
                if all(n in df.schema for df in dfs.values())
            ]
            assert_or_throw(
                len(keys) > 0, ValueError("no common keys to zip by")
            )
        if how == "cross":
            assert_or_throw(
                len(keys) == 0, ValueError("cross zip can't have keys")
            )
        serialized: List[DataFrame] = []
        schemas: List[str] = []
        names: List[str] = list(dfs.keys()) if dfs.has_dict else [""] * len(dfs)
        for no, df in enumerate(dfs.values()):
            schemas.append(str(df.schema))
            serialized.append(
                self._serialize_by_partition(
                    df,
                    PartitionSpec(partition_spec, by=[k for k in keys if k in df.schema]),
                    no,
                    temp_path,
                    to_file_threshold,
                )
            )
        res = serialized[0]
        for s in serialized[1:]:
            res = self.union(res, s, distinct=False)
        res.reset_metadata(
            {
                "serialized": True,
                _ZIP_SCHEMAS_META: schemas,
                _ZIP_NAMES_META: names,
                _ZIP_HOW_META: how,
            }
        )
        return res

    def zip_all(
        self,
        dfs: DataFrames,
        how: str = "inner",
        partition_spec: Optional[PartitionSpec] = None,
    ) -> DataFrame:
        return self.zip(dfs, how=how, partition_spec=partition_spec)

    def _serialize_by_partition(
        self,
        df: DataFrame,
        partition_spec: PartitionSpec,
        df_no: int,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> DataFrame:
        keys = [k for k in partition_spec.partition_by if k in df.schema]
        # presort columns are filtered PER FRAME (reference :1232: a zip
        # presort may reference columns that exist in only some members)
        presort = [
            (c, asc)
            for c, asc in partition_spec.presort.items()
            if c in df.schema
        ]
        partition_spec = PartitionSpec(
            partition_spec, by=keys, presort=presort
        )
        output_schema = Schema(
            [df.schema[k] for k in keys]
            + [(_FUGUE_SER_NO, "int"), (_FUGUE_SER_KEY, "bytes")]  # type: ignore
        )

        engine_fs = self.fs if temp_path is not None else None

        def _serialize(cursor: PartitionCursor, data: LocalDataFrame) -> LocalDataFrame:
            blob = serialize_df(
                data,
                threshold=to_file_threshold,
                file_path=None
                if temp_path is None
                else f"{temp_path}/{uuid4()}.parquet",
                fs=engine_fs,
            )
            row = [cursor.key_value_dict[k] for k in keys] + [df_no, blob]
            return ArrayDataFrame([row], output_schema)

        return self.map_engine.map_dataframe(
            df, _serialize, output_schema, partition_spec
        )

    def comap(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, DataFrames], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrames], Any]] = None,
    ) -> DataFrame:
        """Apply ``map_func(cursor, DataFrames)`` to each co-partitioned key
        group of a zipped dataframe."""
        assert_or_throw(
            df.metadata.get("serialized", False), ValueError("df is not zipped")
        )
        schemas = [Schema(s) for s in df.metadata[_ZIP_SCHEMAS_META]]
        names = df.metadata[_ZIP_NAMES_META]
        how = df.metadata.get(_ZIP_HOW_META, "inner")
        key_names = [
            n for n in df.schema.names if n not in (_FUGUE_SER_NO, _FUGUE_SER_KEY)
        ]
        runner = _Comap(schemas, names, how, map_func, on_init, fs=self.fs)
        spec = PartitionSpec(partition_spec, by=key_names) if key_names else \
            PartitionSpec(num=1)
        return self.map_engine.map_dataframe(
            df, runner.run, output_schema, spec, on_init=runner.on_init
        )

    # ---- misc ------------------------------------------------------------
    def convert_yield_dataframe(self, df: DataFrame, as_local: bool) -> DataFrame:
        """Prepare a dataframe for yielding across workflows; engines whose
        frames die with the engine must localize (reference :449-466)."""
        return df.as_local() if as_local else df

    def load_yielded(self, df: Yielded) -> DataFrame:
        from fugue_tpu.dataframe.dataframe import YieldedDataFrame

        if isinstance(df, YieldedDataFrame):
            return self.to_df(df.result)
        if isinstance(df, PhysicalYielded):
            if df.storage_type == "file":
                return self.load_df(df.name)
            return self.sql_engine.load_table(df.name)
        raise ValueError(f"can't load {df}")

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    def __uuid__(self) -> str:
        from fugue_tpu.utils.hash import to_uuid

        return to_uuid(type(self).__name__, dict(self.conf))


class _Comap:
    def __init__(
        self,
        schemas: List[Schema],
        names: List[str],
        how: str,
        func: Callable,
        on_init: Optional[Callable],
        fs: Any = None,
    ):
        self.schemas = schemas
        self.names = names
        self.how = how
        self.func = func
        self._on_init = on_init
        # spill blobs were written through the engine's fs: read back
        # through the SAME registry, not the process default
        self._fs = fs

    def on_init(self, partition_no: int, df: DataFrame) -> None:
        if self._on_init is not None:
            self._on_init(partition_no, self._empty_dfs())

    def _empty_dfs(self) -> DataFrames:
        if any(n != "" for n in self.names):
            return DataFrames(
                {
                    n: ArrayDataFrame([], s)
                    for n, s in zip(self.names, self.schemas)
                }
            )
        return DataFrames([ArrayDataFrame([], s) for s in self.schemas])

    def run(self, cursor: PartitionCursor, data: LocalDataFrame) -> LocalDataFrame:
        by_no: Dict[int, List[Any]] = {}
        no_idx = data.schema.index_of_key(_FUGUE_SER_NO)
        blob_idx = data.schema.index_of_key(_FUGUE_SER_KEY)
        for row in data.as_array_iterable(type_safe=False):
            by_no.setdefault(row[no_idx], []).append(row[blob_idx])
        # presence rules by zip type
        n = len(self.schemas)
        present = set(by_no.keys())
        if self.how == "inner" and len(present) < n:
            return ArrayDataFrame([], self.func_output_schema(cursor))
        if self.how == "left_outer" and 0 not in present:
            return ArrayDataFrame([], self.func_output_schema(cursor))
        if self.how == "right_outer" and (n - 1) not in present:
            return ArrayDataFrame([], self.func_output_schema(cursor))
        frames: List[DataFrame] = []
        for no in range(n):
            blobs = by_no.get(no, [])
            if len(blobs) == 0:
                frames.append(ArrayDataFrame([], self.schemas[no]))
            elif len(blobs) == 1:
                frames.append(deserialize_df(blobs[0], fs=self._fs))  # type: ignore
            else:
                sub = [deserialize_df(b, fs=self._fs) for b in blobs]
                merged = sub[0].as_arrow()  # type: ignore
                import pyarrow as pa

                merged = pa.concat_tables(
                    [merged] + [s.as_arrow() for s in sub[1:]]  # type: ignore
                )
                from fugue_tpu.dataframe import ArrowDataFrame

                frames.append(ArrowDataFrame(merged))
        if any(x != "" for x in self.names):
            dfs = DataFrames(dict(zip(self.names, frames)))
        else:
            dfs = DataFrames(frames)
        return self.func(cursor, dfs)

    def func_output_schema(self, cursor: PartitionCursor) -> Any:
        # used only for empty results; the map engine replaces with real schema
        return self.schemas[0]
