"""NativeExecutionEngine: the single-process pandas engine — reference
semantics for every conformance suite (parity target: reference
fugue/execution/native_execution_engine.py; SQL-on-pandas comes from our own
column-algebra/SQL interpreter instead of qpd)."""

import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from fugue_tpu.collections.partition import PartitionCursor, PartitionSpec
from fugue_tpu.collections.sql import StructuredRawSQL
from fugue_tpu.constants import KEYWORD_PARALLELISM, KEYWORD_ROWCOUNT
from fugue_tpu.dataframe import (
    ArrayDataFrame,
    DataFrame,
    DataFrames,
    LocalBoundedDataFrame,
    LocalDataFrame,
    PandasDataFrame,
    as_fugue_df,
)
from fugue_tpu.dataframe.pandas_dataframe import PandasDataFrame as _PDF
from fugue_tpu.dataframe.utils import get_join_schemas
from fugue_tpu.execution.execution_engine import (
    ExecutionEngine,
    MapEngine,
    SQLEngine,
)
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils import io as _io


def _sort_pandas(
    pdf: pd.DataFrame, sorts: Dict[str, bool], na_position: str = "first"
) -> pd.DataFrame:
    if len(sorts) == 0 or len(pdf) == 0:
        return pdf
    return pdf.sort_values(
        list(sorts.keys()),
        ascending=list(sorts.values()),
        na_position=na_position,
        kind="stable",
    )


class PandasMapEngine(MapEngine):
    """Per-partition map on pandas: presort + even split or stable groupby
    (reference native_execution_engine.py:68-168)."""

    @property
    def is_distributed(self) -> bool:
        return False

    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:
        output_schema = Schema(output_schema)
        input_schema = df.schema
        pdf = self.to_df(df).as_pandas()
        cursor = partition_spec.get_cursor(input_schema, 0)
        if on_init is not None:
            on_init(0, self.to_df(df))
        results: List[pd.DataFrame] = []
        partition_no = 0
        for chunk in self._split(pdf, partition_spec, input_schema):
            if len(chunk) == 0:
                continue
            chunk = chunk.reset_index(drop=True)
            first_row = chunk.iloc[0].tolist()
            cursor.set(first_row, partition_no, 0)
            local = _PDF._wrap(chunk, input_schema)
            out = map_func(cursor, local)
            partition_no += 1
            if out is not None and not out.empty:
                results.append(out.as_pandas())
        if len(results) == 0:
            return PandasDataFrame(None, output_schema)
        res = pd.concat(results, ignore_index=True)
        return PandasDataFrame(res, output_schema)

    def _split(
        self, pdf: pd.DataFrame, spec: PartitionSpec, schema: Schema
    ) -> Iterator[pd.DataFrame]:
        sorts = spec.get_sorts(schema)
        if len(spec.partition_by) == 0:
            num = spec.get_num_partitions(
                **{
                    KEYWORD_ROWCOUNT: lambda: len(pdf),
                    KEYWORD_PARALLELISM: lambda: 1,
                }
            )
            pdf = _sort_pandas(pdf, sorts)
            if num <= 1 or spec.algo == "coarse" or len(pdf) == 0:
                yield pdf
            elif spec.algo == "hash":
                # stable row-hash partitioning (reference
                # fugue_spark/_utils/partition.py:14 hash_repartition)
                ids = (
                    pd.util.hash_pandas_object(pdf, index=False).to_numpy()
                    % num
                )
                # one O(n) groupby pass, not num full-length mask scans
                for _, sub in pdf.groupby(ids, sort=True):
                    yield sub
            elif spec.algo == "rand":
                # seeded shuffle then even chunks (reference :26
                # rand_repartition); deterministic per run for testability
                rng = np.random.default_rng(42)
                pdf = pdf.iloc[rng.permutation(len(pdf))]
                yield from self._even_chunks(pdf, num)
            else:
                yield from self._even_chunks(pdf, num)
        else:
            pdf = _sort_pandas(pdf, spec.get_sorts(schema))
            if len(pdf) == 0:
                yield pdf
                return
            grouped = pdf.groupby(
                spec.partition_by, dropna=False, sort=False, group_keys=False
            )
            for _, sub in grouped:
                yield sub

    @staticmethod
    def _even_ranges(n: int, num: int) -> Iterator[Tuple[int, int]]:
        """Exact balanced contiguous (start, end) ranges (reference :38
        even_repartition: sizes differ by at most one row)."""
        parts = min(num, n)
        base, extra = divmod(n, parts)
        start = 0
        for i in range(parts):
            end = start + base + (1 if i < extra else 0)
            yield start, end
            start = end

    def _even_chunks(
        self, pdf: pd.DataFrame, num: int
    ) -> Iterator[pd.DataFrame]:
        for start, end in self._even_ranges(len(pdf), num):
            yield pdf.iloc[start:end]

    def map_bag(
        self,
        bag: Any,
        map_func: Callable,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable] = None,
    ) -> Any:
        """Partitioned bag map: split per the spec's ``num`` (even chunks;
        rand = seeded shuffle first), apply ``map_func(no, bag)`` per
        physical partition, concatenate."""
        from fugue_tpu.bag import ArrayBag

        if on_init is not None:
            on_init(0, bag)
        data = list(bag.as_array())
        num = partition_spec.get_num_partitions(
            **{
                KEYWORD_ROWCOUNT: lambda: len(data),
                KEYWORD_PARALLELISM: lambda: 1,
            }
        )
        if num <= 1 or len(data) == 0 or partition_spec.algo == "coarse":
            return map_func(0, ArrayBag(data))
        if partition_spec.algo == "rand":
            rng = np.random.default_rng(42)
            data = [data[i] for i in rng.permutation(len(data))]
        out: List[Any] = []
        for i, (start, end) in enumerate(
            self._even_ranges(len(data), num)
        ):
            res = map_func(i, ArrayBag(data[start:end]))
            out.extend(res.as_array())
        return ArrayBag(out)


# process-wide table catalog: the role of the duckdb connection / spark
# session catalog in the reference backends. Single-controller engines all
# share it, so table yields cross workflows and engine instances. Long-lived
# processes reclaim memory with drop_table / clear_table_catalog.
_TABLE_CATALOG: Dict[str, Any] = {}


def drop_table(name: str) -> None:
    "Remove one table from the in-memory catalog (no-op if absent)."
    _TABLE_CATALOG.pop(name, None)


def clear_table_catalog() -> None:
    "Drop every table in the in-memory catalog."
    _TABLE_CATALOG.clear()


class PandasSQLEngine(SQLEngine):
    """SQL over pandas via the built-in SQL front end (the qpd role,
    reference native_execution_engine.py:41-65) + an in-memory table
    catalog for save_table/load_table/table yields."""

    @property
    def is_distributed(self) -> bool:
        return False

    @property
    def dialect(self) -> Optional[str]:
        return "spark"

    def select(self, dfs: DataFrames, statement: StructuredRawSQL) -> DataFrame:
        from fugue_tpu.sql_frontend.executor import run_sql_on_dataframes

        return run_sql_on_dataframes(
            statement.construct(dialect=self.dialect), dfs
        )

    def table_exists(self, table: str) -> bool:
        return table in _TABLE_CATALOG

    def save_table(
        self,
        df: DataFrame,
        table: str,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        **kwargs: Any,
    ) -> None:
        assert_or_throw(
            mode in ("overwrite", "error"),
            NotImplementedError(f"save mode {mode}"),
        )
        if mode == "error":
            assert_or_throw(
                table not in _TABLE_CATALOG,
                ValueError(f"table {table} exists"),
            )
        local = self.execution_engine.to_df(df).as_local_bounded()
        _TABLE_CATALOG[table] = (
            local.as_arrow(type_safe=True),
            local.schema,
        )

    def drop_table(self, table: str) -> None:
        drop_table(table)

    def load_table(self, table: str, **kwargs: Any) -> DataFrame:
        assert_or_throw(
            table in _TABLE_CATALOG, ValueError(f"table {table} not found")
        )
        data, schema = _TABLE_CATALOG[table]
        from fugue_tpu.dataframe import ArrowDataFrame

        return self.execution_engine.to_df(ArrowDataFrame(data, schema))


class NativeExecutionEngine(ExecutionEngine):
    """Single-process engine on pandas (reference
    native_execution_engine.py:171-419)."""

    def __init__(self, conf: Any = None):
        super().__init__(conf)

    @property
    def is_distributed(self) -> bool:
        return False

    def create_default_map_engine(self) -> MapEngine:
        return PandasMapEngine(self)

    def create_default_sql_engine(self) -> SQLEngine:
        return PandasSQLEngine(self)

    def get_current_parallelism(self) -> int:
        return 1

    def to_df(self, df: Any, schema: Any = None) -> LocalBoundedDataFrame:
        if isinstance(df, DataFrame):
            assert_or_throw(
                schema is None,
                ValueError("schema must be None when df is a DataFrame"),
            )
            res = df.as_local_bounded()
            if df.has_metadata:
                res.reset_metadata(df.metadata)
            return res  # type: ignore
        if isinstance(df, pd.DataFrame):
            return PandasDataFrame(df, schema)
        if isinstance(df, (list, tuple)) or (
            hasattr(df, "__iter__") and not isinstance(df, str)
        ):
            return ArrayDataFrame(df, schema)
        from fugue_tpu.collections.yielded import Yielded

        if isinstance(df, Yielded):
            return self.load_yielded(df)  # type: ignore
        raise ValueError(f"can't convert {type(df)} to DataFrame")

    def repartition(self, df: DataFrame, partition_spec: PartitionSpec) -> DataFrame:
        return df

    def broadcast(self, df: DataFrame) -> DataFrame:
        return df

    def persist(self, df: DataFrame, lazy: bool = False, **kwargs: Any) -> DataFrame:
        return self.to_df(df)

    def join(
        self,
        df1: DataFrame,
        df2: DataFrame,
        how: str,
        on: Optional[List[str]] = None,
    ) -> DataFrame:
        how = how.lower().replace("_", "").replace(" ", "")
        key_schema, output_schema = get_join_schemas(df1, df2, how, on)
        keys = key_schema.names
        a = self.to_df(df1).as_pandas()
        b = self.to_df(df2).as_pandas()
        res = _pandas_join(a, b, how, keys)
        return PandasDataFrame(res[output_schema.names], output_schema)

    def union(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        assert_or_throw(
            df1.schema == df2.schema,
            ValueError(f"union schema mismatch {df1.schema} vs {df2.schema}"),
        )
        a = self.to_df(df1).as_pandas()
        b = self.to_df(df2).as_pandas()
        res = pd.concat([a, b], ignore_index=True)
        if distinct:
            res = _pandas_distinct(res)
        return PandasDataFrame(res, df1.schema)

    def subtract(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        assert_or_throw(
            df1.schema == df2.schema,
            ValueError(f"subtract schema mismatch {df1.schema} vs {df2.schema}"),
        )
        if not distinct:  # multiset: pair off occurrences
            return PandasDataFrame(
                _pandas_multiset_op(
                    self.to_df(df1).as_pandas(),
                    self.to_df(df2).as_pandas(),
                    subtract=True,
                ),
                df1.schema,
            )
        a = _pandas_distinct(self.to_df(df1).as_pandas())
        b = self.to_df(df2).as_pandas()
        cols = list(a.columns)
        merged = a.merge(b.drop_duplicates(), on=cols, how="left", indicator=True)
        res = merged[merged["_merge"] == "left_only"][cols]
        return PandasDataFrame(res.reset_index(drop=True), df1.schema)

    def intersect(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        assert_or_throw(
            df1.schema == df2.schema,
            ValueError(f"intersect schema mismatch {df1.schema} vs {df2.schema}"),
        )
        if not distinct:  # multiset: pair off occurrences
            return PandasDataFrame(
                _pandas_multiset_op(
                    self.to_df(df1).as_pandas(),
                    self.to_df(df2).as_pandas(),
                    subtract=False,
                ),
                df1.schema,
            )
        a = _pandas_distinct(self.to_df(df1).as_pandas())
        b = self.to_df(df2).as_pandas()
        cols = list(a.columns)
        merged = a.merge(b.drop_duplicates(), on=cols, how="inner")
        return PandasDataFrame(merged.reset_index(drop=True), df1.schema)

    def distinct(self, df: DataFrame) -> DataFrame:
        res = _pandas_distinct(self.to_df(df).as_pandas())
        return PandasDataFrame(res, df.schema)

    def dropna(
        self,
        df: DataFrame,
        how: str = "any",
        thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> DataFrame:
        kw: Dict[str, Any] = dict(subset=subset)
        if thresh is not None:
            kw["thresh"] = thresh
        else:
            kw["how"] = how
        res = self.to_df(df).as_pandas().dropna(**kw)
        return PandasDataFrame(res.reset_index(drop=True), df.schema)

    def fillna(
        self, df: DataFrame, value: Any, subset: Optional[List[str]] = None
    ) -> DataFrame:
        assert_or_throw(
            (not isinstance(value, dict)) or all(v is not None for v in value.values()),
            ValueError("fillna dict can't contain None"),
        )
        assert_or_throw(value is not None, ValueError("fillna value can't be None"))
        pdf = self.to_df(df).as_pandas()
        if isinstance(value, dict):
            res = pdf.fillna(value)
        elif subset is not None:
            res = pdf.fillna({c: value for c in subset})
        else:
            res = pdf.fillna(value)
        return PandasDataFrame(res, df.schema)

    def sample(
        self,
        df: DataFrame,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        seed: Optional[int] = None,
    ) -> DataFrame:
        assert_or_throw(
            (n is None) != (frac is None),
            ValueError("one and only one of n and frac must be set"),
        )
        res = (
            self.to_df(df)
            .as_pandas()
            .sample(n=n, frac=frac, replace=replace, random_state=seed)
        )
        return PandasDataFrame(res.reset_index(drop=True), df.schema)

    def take(
        self,
        df: DataFrame,
        n: int,
        presort: str,
        na_position: str = "last",
        partition_spec: Optional[PartitionSpec] = None,
    ) -> DataFrame:
        assert_or_throw(
            isinstance(n, int) and n >= 0, ValueError("n must be a non-negative int")
        )
        assert_or_throw(
            na_position in ("first", "last"), ValueError("invalid na_position")
        )
        partition_spec = partition_spec or PartitionSpec()
        from fugue_tpu.collections.partition import parse_presort_exp

        sorts = parse_presort_exp(presort) if presort else partition_spec.presort
        pdf = self.to_df(df).as_pandas()
        if len(partition_spec.partition_by) == 0:
            res = _sort_pandas(pdf, sorts, na_position).head(n)
        else:
            pdf = _sort_pandas(pdf, sorts, na_position)
            res = (
                pdf.groupby(
                    partition_spec.partition_by, dropna=False, sort=False,
                    group_keys=False,
                )
                .head(n)
            )
        return PandasDataFrame(res.reset_index(drop=True), df.schema)

    def load_df(
        self,
        path: Union[str, List[str]],
        format_hint: Any = None,
        columns: Any = None,
        **kwargs: Any,
    ) -> LocalBoundedDataFrame:
        # optimizer-attached row-group pruning is a jax-ingest hint; the
        # native path ignores it (the downstream filter re-applies the
        # predicate, so dropping the hint is always correct) — EXCEPT on
        # lake:// paths, where the triples prune WHOLE FILES from
        # manifest stats before any footer is read, which is free on any
        # engine
        pruning = kwargs.pop("pruning", None)
        first = path if isinstance(path, str) else path[0]
        from fugue_tpu.lake.format import is_lake_uri

        if pruning and is_lake_uri(first):
            kwargs["pruning"] = pruning
        return _io.load_df(path, format_hint, columns, fs=self.fs, **kwargs)

    def save_df(
        self,
        df: DataFrame,
        path: str,
        format_hint: Any = None,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        force_single: bool = False,
        **kwargs: Any,
    ) -> None:
        _io.save_df(
            df, path, format_hint, mode,
            partition_cols=_io.spec_partition_cols(partition_spec, force_single),
            fs=self.fs, **kwargs,
        )


def _pandas_distinct(pdf: pd.DataFrame) -> pd.DataFrame:
    try:
        return pdf.drop_duplicates(ignore_index=True)
    except TypeError:
        # unhashable cells (lists/dicts): fall back to a string projection
        key = pdf.astype(str).apply(lambda r: "\0".join(r), axis=1)
        return pdf[~key.duplicated()].reset_index(drop=True)


def _pandas_multiset_op(
    a: pd.DataFrame, b: pd.DataFrame, subtract: bool
) -> pd.DataFrame:
    """EXCEPT/INTERSECT ALL (standard SQL multiset semantics): each left
    row pairs off against right-side occurrences of the same full-row
    key — EXCEPT ALL keeps occurrences past the right count, INTERSECT
    ALL those within it. NULL keys compare equal (merge factorization)."""
    cols = list(a.columns)
    occ_l = "_occ"
    while occ_l in cols:  # user columns can shadow the temp names
        occ_l += "_"
    rc_l = "_rc"
    while rc_l in cols:
        rc_l += "_"
    lo = a.assign(**{occ_l: a.groupby(cols, dropna=False).cumcount()})
    rcnt = (
        b.groupby(cols, dropna=False).size().rename(rc_l).reset_index()
    )
    merged = lo.merge(rcnt, on=cols, how="left")
    rc = merged[rc_l].fillna(0)
    keep = merged[occ_l] >= rc if subtract else merged[occ_l] < rc
    return merged[keep][cols].reset_index(drop=True)


def _pandas_join(
    a: pd.DataFrame, b: pd.DataFrame, how: str, keys: List[str]
) -> pd.DataFrame:
    """SQL-semantics join on pandas: null keys never match (pd.merge would
    match NaN == NaN, so null-keyed rows are handled explicitly)."""
    if how == "cross":
        return a.merge(b, how="cross")
    a_null = a[keys].isna().any(axis=1) if len(a) else pd.Series([], dtype=bool)
    b_null = b[keys].isna().any(axis=1) if len(b) else pd.Series([], dtype=bool)
    a_ok, a_bad = (a[~a_null], a[a_null]) if len(a) else (a, a)
    b_ok, b_bad = (b[~b_null], b[b_null]) if len(b) else (b, b)
    if how == "inner":
        return a_ok.merge(b_ok, on=keys, how="inner")
    if how in ("semi", "leftsemi"):
        right = b_ok[keys].drop_duplicates()
        return a_ok.merge(right, on=keys, how="inner")
    if how in ("anti", "leftanti"):
        right = b_ok[keys].drop_duplicates()
        merged = a.merge(right, on=keys, how="left", indicator=True)
        return merged[merged["_merge"] == "left_only"].drop(columns=["_merge"])
    if how == "leftouter":
        res = a.merge(b_ok, on=keys, how="left")
        return res
    if how == "rightouter":
        res = a_ok.merge(b, on=keys, how="right")
        return res
    if how == "fullouter":
        core = a_ok.merge(b_ok, on=keys, how="outer")
        extras = []
        if len(a_bad) > 0:
            extras.append(a_bad.merge(b_ok.head(0), on=keys, how="left"))
        if len(b_bad) > 0:
            extras.append(a_ok.head(0).merge(b_bad, on=keys, how="right"))
        if extras:
            core = pd.concat([core] + extras, ignore_index=True)
        return core
    raise NotImplementedError(f"join type {how}")
