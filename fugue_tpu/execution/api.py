"""Eager functional engine API (reference fugue/execution/api.py): context
managers + one-shot engine ops over any dataframe-like input."""

from contextlib import contextmanager
from typing import Any, Callable, Iterator, List, Optional, Union

from fugue_tpu.collections.partition import PartitionSpec
from fugue_tpu.column.expressions import ColumnExpr
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.dataframe import DataFrame
from fugue_tpu.dataframe.api import as_fugue_df, get_native_as_df
from fugue_tpu.execution.execution_engine import (
    _GLOBAL_ENGINE,
    ExecutionEngine,
)
from fugue_tpu.execution.factory import make_execution_engine, try_get_context_engine
from fugue_tpu.utils.assertion import assert_or_throw

AnyDataFrame = Any


@contextmanager
def engine_context(
    engine: Any = None, conf: Any = None, infer_by: Optional[List[Any]] = None
) -> Iterator[ExecutionEngine]:
    """``with engine_context("jax"):`` — all fugue_tpu calls inside use this
    engine by default."""
    e = make_execution_engine(engine, conf, infer_by)
    e.as_context()
    try:
        yield e
    finally:
        e.stop_context()


def set_global_engine(engine: Any = None, conf: Any = None) -> ExecutionEngine:
    assert_or_throw(engine is not None, ValueError("engine can't be None"))
    return make_execution_engine(engine, conf).set_global()


def clear_global_engine() -> None:
    old = _GLOBAL_ENGINE[0]
    if old is not None:
        old.unset_global()
        if not old.in_context:
            old.stop()


def get_context_engine() -> ExecutionEngine:
    engine = try_get_context_engine()
    assert_or_throw(engine is not None, ValueError("no contextual/global engine"))
    return engine  # type: ignore


def get_current_parallelism(engine: Any = None, conf: Any = None) -> int:
    return make_execution_engine(engine, conf).get_current_parallelism()


def get_current_conf() -> Any:
    engine = try_get_context_engine()
    if engine is not None:
        return engine.conf
    from fugue_tpu.constants import FUGUE_GLOBAL_CONF

    return FUGUE_GLOBAL_CONF


def run_engine_function(
    func: Callable[[ExecutionEngine], DataFrame],
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
    infer_by: Optional[List[Any]] = None,
) -> Any:
    """Resolve engine, run ``func(engine)`` inside its context, adapt result."""
    e = make_execution_engine(engine, engine_conf, infer_by)
    e.as_context()
    try:
        res = func(e)
        if as_local:
            res = res.as_local()
        if as_fugue:
            return res
        return res.native if res.is_local else get_native_as_df(res)
    finally:
        e.stop_context()


def _to_engine_df(engine: ExecutionEngine, df: AnyDataFrame) -> DataFrame:
    if isinstance(df, DataFrame):
        return engine.to_df(df)
    return engine.to_df(as_fugue_df(df))


def repartition(
    df: AnyDataFrame,
    partition: Any,
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.repartition(_to_engine_df(e, df), PartitionSpec(partition)),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def broadcast(
    df: AnyDataFrame, engine: Any = None, engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.broadcast(_to_engine_df(e, df)),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def persist(
    df: AnyDataFrame, lazy: bool = False, engine: Any = None,
    engine_conf: Any = None, as_fugue: bool = False, **kwargs: Any,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.persist(_to_engine_df(e, df), lazy=lazy, **kwargs),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def join(
    df1: AnyDataFrame,
    df2: AnyDataFrame,
    *dfs: AnyDataFrame,
    how: str,
    on: Optional[List[str]] = None,
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    def _join(e: ExecutionEngine) -> DataFrame:
        res = e.join(_to_engine_df(e, df1), _to_engine_df(e, df2), how=how, on=on)
        for df in dfs:
            res = e.join(res, _to_engine_df(e, df), how=how, on=on)
        return res

    return run_engine_function(
        _join, engine, engine_conf, as_fugue, infer_by=[df1, df2, *dfs]
    )


def _make_join(how: str) -> Callable:
    def _join(
        df1: AnyDataFrame, df2: AnyDataFrame, *dfs: AnyDataFrame,
        on: Optional[List[str]] = None,
        engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
    ) -> AnyDataFrame:
        return join(df1, df2, *dfs, how=how, on=on, engine=engine,
                    engine_conf=engine_conf, as_fugue=as_fugue)

    _join.__name__ = how.replace(" ", "_") + "_join"
    return _join


inner_join = _make_join("inner")
semi_join = _make_join("semi")
anti_join = _make_join("anti")
left_outer_join = _make_join("left_outer")
right_outer_join = _make_join("right_outer")
full_outer_join = _make_join("full_outer")
cross_join = _make_join("cross")


def union(
    df1: AnyDataFrame, df2: AnyDataFrame, *dfs: AnyDataFrame,
    distinct: bool = True,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    def _union(e: ExecutionEngine) -> DataFrame:
        res = e.union(_to_engine_df(e, df1), _to_engine_df(e, df2), distinct=distinct)
        for df in dfs:
            res = e.union(res, _to_engine_df(e, df), distinct=distinct)
        return res

    return run_engine_function(
        _union, engine, engine_conf, as_fugue, infer_by=[df1, df2, *dfs]
    )


def subtract(
    df1: AnyDataFrame, df2: AnyDataFrame, *dfs: AnyDataFrame,
    distinct: bool = True,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    def _subtract(e: ExecutionEngine) -> DataFrame:
        res = e.subtract(_to_engine_df(e, df1), _to_engine_df(e, df2), distinct=distinct)
        for df in dfs:
            res = e.subtract(res, _to_engine_df(e, df), distinct=distinct)
        return res

    return run_engine_function(
        _subtract, engine, engine_conf, as_fugue, infer_by=[df1, df2, *dfs]
    )


def intersect(
    df1: AnyDataFrame, df2: AnyDataFrame, *dfs: AnyDataFrame,
    distinct: bool = True,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    def _intersect(e: ExecutionEngine) -> DataFrame:
        res = e.intersect(_to_engine_df(e, df1), _to_engine_df(e, df2),
                          distinct=distinct)
        for df in dfs:
            res = e.intersect(res, _to_engine_df(e, df), distinct=distinct)
        return res

    return run_engine_function(
        _intersect, engine, engine_conf, as_fugue, infer_by=[df1, df2, *dfs]
    )


def distinct(
    df: AnyDataFrame, engine: Any = None, engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.distinct(_to_engine_df(e, df)),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def dropna(
    df: AnyDataFrame, how: str = "any", thresh: Optional[int] = None,
    subset: Optional[List[str]] = None,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.dropna(_to_engine_df(e, df), how=how, thresh=thresh, subset=subset),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def fillna(
    df: AnyDataFrame, value: Any, subset: Optional[List[str]] = None,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.fillna(_to_engine_df(e, df), value=value, subset=subset),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def sample(
    df: AnyDataFrame, n: Optional[int] = None, frac: Optional[float] = None,
    replace: bool = False, seed: Optional[int] = None,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.sample(_to_engine_df(e, df), n=n, frac=frac, replace=replace,
                           seed=seed),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def take(
    df: AnyDataFrame, n: int, presort: str = "", na_position: str = "last",
    partition: Any = None,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.take(
            _to_engine_df(e, df), n=n, presort=presort, na_position=na_position,
            partition_spec=None if partition is None else PartitionSpec(partition),
        ),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def load(
    path: Union[str, List[str]], format_hint: Any = None, columns: Any = None,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
    **kwargs: Any,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.load_df(path, format_hint=format_hint, columns=columns, **kwargs),
        engine, engine_conf, as_fugue,
    )


def save(
    df: AnyDataFrame, path: str, format_hint: Any = None, mode: str = "overwrite",
    partition: Any = None, force_single: bool = False,
    engine: Any = None, engine_conf: Any = None, **kwargs: Any,
) -> None:
    e = make_execution_engine(engine, engine_conf, infer_by=[df])
    e.as_context()
    try:
        e.save_df(
            _to_engine_df(e, df), path, format_hint=format_hint, mode=mode,
            partition_spec=None if partition is None else PartitionSpec(partition),
            force_single=force_single, **kwargs,
        )
    finally:
        e.stop_context()


def select(
    df: AnyDataFrame, *columns: Union[str, ColumnExpr],
    where: Optional[ColumnExpr] = None, having: Optional[ColumnExpr] = None,
    distinct: bool = False,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    from fugue_tpu.column.expressions import col as _col

    cols = SelectColumns(
        *[_col(c) if isinstance(c, str) else c for c in columns],
        arg_distinct=distinct,
    )
    return run_engine_function(
        lambda e: e.select(_to_engine_df(e, df), cols, where=where, having=having),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def filter(  # noqa: A001
    df: AnyDataFrame, condition: ColumnExpr,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.filter(_to_engine_df(e, df), condition),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def assign(
    df: AnyDataFrame,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
    **columns: Any,
) -> AnyDataFrame:
    from fugue_tpu.column.expressions import lit

    cols = [
        (v if isinstance(v, ColumnExpr) else lit(v)).alias(k)
        for k, v in columns.items()
    ]
    return run_engine_function(
        lambda e: e.assign(_to_engine_df(e, df), cols),
        engine, engine_conf, as_fugue, infer_by=[df],
    )


def aggregate(
    df: AnyDataFrame, partition_by: Any = None,
    engine: Any = None, engine_conf: Any = None, as_fugue: bool = False,
    **agg_kwcols: ColumnExpr,
) -> AnyDataFrame:
    cols = [v.alias(k) for k, v in agg_kwcols.items()]
    spec = None if partition_by is None else PartitionSpec(by=(
        [partition_by] if isinstance(partition_by, str) else list(partition_by)
    ))
    return run_engine_function(
        lambda e: e.aggregate(_to_engine_df(e, df), spec, cols),
        engine, engine_conf, as_fugue, infer_by=[df],
    )
