from fugue_tpu.execution.execution_engine import (
    AnyDataFrame,
    EngineFacet,
    ExecutionEngine,
    MapEngine,
    SQLEngine,
)
from fugue_tpu.execution.native_execution_engine import (
    NativeExecutionEngine,
    PandasMapEngine,
    PandasSQLEngine,
)
from fugue_tpu.execution.factory import (
    make_execution_engine,
    make_sql_engine,
    register_default_execution_engine,
    register_default_sql_engine,
    register_execution_engine,
    register_sql_engine,
)
from fugue_tpu.execution.api import (
    clear_global_engine,
    engine_context,
    get_context_engine,
    get_current_parallelism,
    set_global_engine,
)
