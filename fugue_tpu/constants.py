"""Global configuration registry and well-known config keys.

Layered config model (parity with reference fugue/constants.py:35-51):
global conf (this module) <- engine conf at construction <- per-run overrides.
"""

from typing import Any, Dict

from fugue_tpu.utils.params import ParamDict

KEYWORD_ROWCOUNT = "ROWCOUNT"
KEYWORD_PARALLELISM = "CONCURRENCY"

FUGUE_CONF_WORKFLOW_CONCURRENCY = "fugue.workflow.concurrency"
FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH = "fugue.workflow.checkpoint.path"
FUGUE_CONF_WORKFLOW_RETRY_MAX_ATTEMPTS = "fugue.workflow.retry.max_attempts"
FUGUE_CONF_WORKFLOW_RETRY_BACKOFF = "fugue.workflow.retry.backoff"
FUGUE_CONF_WORKFLOW_RETRY_JITTER = "fugue.workflow.retry.jitter"
FUGUE_CONF_WORKFLOW_TIMEOUT = "fugue.workflow.timeout"
FUGUE_CONF_WORKFLOW_RESUME = "fugue.workflow.resume"
FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE = "fugue.workflow.exception.hide"
FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT = "fugue.workflow.exception.inject"
FUGUE_CONF_WORKFLOW_EXCEPTION_OPTIMIZE = "fugue.workflow.exception.optimize"
FUGUE_CONF_SQL_IGNORE_CASE = "fugue.sql.compile.ignore_case"
FUGUE_CONF_SQL_DIALECT = "fugue.sql.compile.dialect"
FUGUE_CONF_RPC_SERVER = "fugue.rpc.server"
FUGUE_CONF_JAX_PARTITIONS = "fugue.jax.default.partitions"
FUGUE_CONF_JAX_COMPILE = "fugue.jax.compile"
FUGUE_CONF_JAX_ROW_BUCKET = "fugue.jax.row_bucket"
FUGUE_CONF_JAX_DEVICE_ZIP = "fugue.jax.device_zip"
FUGUE_CONF_JAX_PLACEMENT = "fugue.jax.placement"
FUGUE_CONF_JAX_MIN_DEVICE_BYTES = "fugue.jax.placement.min_device_bytes"
FUGUE_CONF_JAX_COMPILE_CACHE = "fugue.jax.compile.cache"
FUGUE_CONF_JAX_IO_BATCH_ROWS = "fugue.jax.io.batch_rows"
FUGUE_CONF_JAX_GROUPBY_MATMUL = "fugue.jax.groupby.matmul"
FUGUE_CONF_JAX_GROUPBY_STRATEGY = "fugue.jax.groupby.strategy"
FUGUE_CONF_JAX_GROUPBY_AUTOTUNE = "fugue.jax.groupby.autotune"
FUGUE_CONF_JAX_MEMORY_BUDGET_BYTES = "fugue.jax.memory.budget_bytes"
FUGUE_CONF_JAX_MEMORY_BUDGET_FRACTION = "fugue.jax.memory.budget_fraction"
FUGUE_CONF_JAX_MEMORY_HIGH_WATERMARK = "fugue.jax.memory.high_watermark"
FUGUE_CONF_JAX_MEMORY_LOW_WATERMARK = "fugue.jax.memory.low_watermark"
FUGUE_CONF_RPC_HTTP_RETRIES = "fugue.rpc.http_server.retries"

FUGUE_COMPILE_TIME_CONFIGS = {
    FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE,
    FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT,
    FUGUE_CONF_WORKFLOW_EXCEPTION_OPTIMIZE,
    FUGUE_CONF_SQL_IGNORE_CASE,
    FUGUE_CONF_SQL_DIALECT,
}

_DEFAULT_CONF: Dict[str, Any] = {
    FUGUE_CONF_WORKFLOW_CONCURRENCY: 1,
    # fault tolerance: attempts = 1 means no retry; backoff is the base
    # exponential delay in seconds (delay = backoff * 2**(attempt-1)),
    # jitter a multiplicative fraction added on top. Only TRANSIENT error
    # classes retry (fs/IO, RPC transport, jax RESOURCE_EXHAUSTED) — see
    # fugue_tpu/workflow/fault.py:classify_error. timeout is the per-task
    # wall clock in seconds (0 = unlimited), enforced by the parallel
    # runner. resume=True keeps a run manifest of completed task uuids so
    # re-running an identical DAG after a crash restarts at the frontier.
    FUGUE_CONF_WORKFLOW_RETRY_MAX_ATTEMPTS: 1,
    FUGUE_CONF_WORKFLOW_RETRY_BACKOFF: 0.1,
    FUGUE_CONF_WORKFLOW_RETRY_JITTER: 0.1,
    FUGUE_CONF_WORKFLOW_TIMEOUT: 0.0,
    FUGUE_CONF_WORKFLOW_RESUME: False,
    FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE: "fugue_tpu.",
    FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT: 3,
    FUGUE_CONF_WORKFLOW_EXCEPTION_OPTIMIZE: True,
    FUGUE_CONF_SQL_IGNORE_CASE: False,
    FUGUE_CONF_SQL_DIALECT: "spark",
    FUGUE_CONF_JAX_ROW_BUCKET: 0,
    FUGUE_CONF_JAX_DEVICE_ZIP: True,
    # Two-tier placement (see JaxExecutionEngine): frames below the byte
    # threshold ingest onto the host (CPU-XLA) mesh; at/above it they go to
    # the accelerator mesh. The default is tuned for network-attached
    # accelerators where per-query host<->device transfer costs seconds per
    # GB; on PCIe-local TPU hosts set a lower threshold or placement=device.
    FUGUE_CONF_JAX_PLACEMENT: "auto",
    FUGUE_CONF_JAX_MIN_DEVICE_BYTES: 256 * 1024 * 1024,
    # streamed parquet ingest/save: 0 = eager (whole-table). > 0 pipelines
    # arrow record-batch decode with per-shard device_put staging on load
    # (each mesh shard ships as soon as its rows are decoded, while the
    # next batches decode) and bounds parquet row groups on save. The
    # ingest stays LAZY: host-only chains never pay a device round trip.
    FUGUE_CONF_JAX_IO_BATCH_ROWS: 0,
    # group-by reduction algorithm (legacy knob, kept for back-compat):
    # "always"/"never" pin the strategy below to matmul/scatter; "auto"
    # defers to fugue.jax.groupby.strategy.
    FUGUE_CONF_JAX_GROUPBY_MATMUL: "auto",
    # segment-reduction strategy: "auto" consults the measured crossover
    # table in jax_backend/segtune.py (scatter on CPU meshes, one-hot
    # matmul on accelerators below the segment cap, sorted scatter above
    # it), sharpened by a one-shot on-device autotune; or pin one of
    # "matmul" | "matmul_bf16" | "scatter" | "sort". matmul_bf16 trades
    # ~8 mantissa bits for speed and is PIN-ONLY — auto never picks it.
    FUGUE_CONF_JAX_GROUPBY_STRATEGY: "auto",
    # autotune policy: "auto" probes on accelerator meshes for large
    # frames only; True/False force it on/off.
    FUGUE_CONF_JAX_GROUPBY_AUTOTUNE: "auto",
    # device-memory governance (jax_backend/memory.py): budget_bytes > 0
    # (or budget_fraction > 0 of the detected per-device memory) turns on
    # the HBM byte ledger + admission controller. An ingest/persist that
    # would push the device tier past high_watermark * budget first
    # spills LRU persisted frames to the host tier down to low_watermark;
    # a frame whose estimated footprint alone exceeds the budget is
    # placed on the host tier directly. 0/0.0 = ungoverned (default).
    FUGUE_CONF_JAX_MEMORY_BUDGET_BYTES: 0,
    FUGUE_CONF_JAX_MEMORY_BUDGET_FRACTION: 0.0,
    FUGUE_CONF_JAX_MEMORY_HIGH_WATERMARK: 0.9,
    FUGUE_CONF_JAX_MEMORY_LOW_WATERMARK: 0.75,
    # bounded exponential-backoff retries for the HTTP RPC client on
    # transient transport failures (connection refused/reset, HTTP 503);
    # non-transient HTTP errors always fail fast
    FUGUE_CONF_RPC_HTTP_RETRIES: 2,
}

_GLOBAL_CONF = ParamDict(_DEFAULT_CONF)


def register_global_conf(conf: Dict[str, Any], on_dup: int = ParamDict.OVERWRITE) -> None:
    """Register global configs readable by every engine/workflow created after."""
    _GLOBAL_CONF.update(conf, on_dup=on_dup)


FUGUE_GLOBAL_CONF = _GLOBAL_CONF
