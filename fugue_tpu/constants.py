"""Global configuration registry and well-known config keys.

Layered config model (parity with reference fugue/constants.py:35-51):
global conf (this module) <- engine conf at construction <- per-run overrides.

Every ``FUGUE_CONF_*`` key is DECLARED in :data:`_CONF_REGISTRY` below with
its value type, default, and a one-line description; ``_DEFAULT_CONF`` (the
seed of the global conf every engine/workflow inherits) is derived from that
table, so the registry is the single source of truth shared by the engine
conf getters and the static analyzer's conf pass
(:mod:`fugue_tpu.analysis`), which flags unknown ``fugue.*`` keys with a
did-you-mean suggestion and values not convertible to the declared type.
"""

from typing import Any, Dict, NamedTuple

from fugue_tpu.utils.params import ParamDict, _convert

KEYWORD_ROWCOUNT = "ROWCOUNT"
KEYWORD_PARALLELISM = "CONCURRENCY"

FUGUE_CONF_ANALYSIS = "fugue.analysis"
FUGUE_CONF_WORKFLOW_CONCURRENCY = "fugue.workflow.concurrency"
FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH = "fugue.workflow.checkpoint.path"
FUGUE_CONF_WORKFLOW_RETRY_MAX_ATTEMPTS = "fugue.workflow.retry.max_attempts"
FUGUE_CONF_WORKFLOW_RETRY_BACKOFF = "fugue.workflow.retry.backoff"
FUGUE_CONF_WORKFLOW_RETRY_JITTER = "fugue.workflow.retry.jitter"
FUGUE_CONF_WORKFLOW_TIMEOUT = "fugue.workflow.timeout"
FUGUE_CONF_WORKFLOW_RESUME = "fugue.workflow.resume"
FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE = "fugue.workflow.exception.hide"
FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT = "fugue.workflow.exception.inject"
FUGUE_CONF_WORKFLOW_EXCEPTION_OPTIMIZE = "fugue.workflow.exception.optimize"
FUGUE_CONF_SQL_IGNORE_CASE = "fugue.sql.compile.ignore_case"
FUGUE_CONF_SQL_DIALECT = "fugue.sql.compile.dialect"
FUGUE_CONF_RPC_SERVER = "fugue.rpc.server"
FUGUE_CONF_JAX_PARTITIONS = "fugue.jax.default.partitions"
FUGUE_CONF_JAX_COMPILE = "fugue.jax.compile"
FUGUE_CONF_JAX_ROW_BUCKET = "fugue.jax.row_bucket"
FUGUE_CONF_JAX_DEVICE_ZIP = "fugue.jax.device_zip"
FUGUE_CONF_JAX_PLACEMENT = "fugue.jax.placement"
FUGUE_CONF_JAX_MIN_DEVICE_BYTES = "fugue.jax.placement.min_device_bytes"
FUGUE_CONF_JAX_COMPILE_CACHE = "fugue.jax.compile.cache"
FUGUE_CONF_JAX_IO_BATCH_ROWS = "fugue.jax.io.batch_rows"
FUGUE_CONF_JAX_IO_PIPELINE = "fugue.jax.io.pipeline"
FUGUE_CONF_JAX_GROUPBY_MATMUL = "fugue.jax.groupby.matmul"
FUGUE_CONF_JAX_GROUPBY_STRATEGY = "fugue.jax.groupby.strategy"
FUGUE_CONF_JAX_GROUPBY_AUTOTUNE = "fugue.jax.groupby.autotune"
FUGUE_CONF_JAX_SHUFFLE = "fugue.jax.shuffle"
FUGUE_CONF_JAX_SHUFFLE_OVERLAP = "fugue.jax.shuffle.overlap"
FUGUE_CONF_JAX_DEVICES = "fugue.jax.devices"
FUGUE_CONF_JAX_MEMORY_BUDGET_BYTES = "fugue.jax.memory.budget_bytes"
FUGUE_CONF_JAX_MEMORY_BUDGET_FRACTION = "fugue.jax.memory.budget_fraction"
FUGUE_CONF_JAX_MEMORY_HIGH_WATERMARK = "fugue.jax.memory.high_watermark"
FUGUE_CONF_JAX_MEMORY_LOW_WATERMARK = "fugue.jax.memory.low_watermark"
FUGUE_CONF_JAX_RECOVERY_ENABLED = "fugue.jax.recovery.enabled"
FUGUE_CONF_JAX_RECOVERY_MAX_LOSSES = "fugue.jax.recovery.max_losses"
FUGUE_CONF_RPC_HTTP_RETRIES = "fugue.rpc.http_server.retries"
FUGUE_CONF_RPC_HTTP_MAX_BODY = "fugue.rpc.http_server.max_body_bytes"
FUGUE_CONF_RPC_HTTP_READ_TIMEOUT = "fugue.rpc.http_server.read_timeout"
FUGUE_CONF_SERVE_HOST = "fugue.serve.host"
FUGUE_CONF_SERVE_PORT = "fugue.serve.port"
FUGUE_CONF_SERVE_MAX_CONCURRENT = "fugue.serve.max_concurrent"
FUGUE_CONF_SERVE_SESSION_TTL = "fugue.serve.session_ttl"
FUGUE_CONF_SERVE_SYNC_WAIT = "fugue.serve.sync_wait"
FUGUE_CONF_SERVE_TENANT_BUDGET_FRACTION = "fugue.serve.tenant_budget_fraction"
FUGUE_CONF_SERVE_STATE_PATH = "fugue.serve.state_path"
FUGUE_CONF_SERVE_DRAIN_TIMEOUT = "fugue.serve.drain_timeout"
FUGUE_CONF_SERVE_MAX_QUEUE = "fugue.serve.max_queue"
FUGUE_CONF_SERVE_SESSION_MAX_JOBS = "fugue.serve.session_max_jobs"
FUGUE_CONF_SERVE_MEMORY_REJECT = "fugue.serve.memory_reject_fraction"
FUGUE_CONF_SERVE_SYNC_DEGRADE_DEPTH = "fugue.serve.sync_degrade_depth"
FUGUE_CONF_SERVE_BREAKER_THRESHOLD = "fugue.serve.breaker.threshold"
FUGUE_CONF_SERVE_BREAKER_COOLDOWN = "fugue.serve.breaker.cooldown"
FUGUE_CONF_SERVE_HEARTBEAT_TIMEOUT = "fugue.serve.heartbeat_timeout"
FUGUE_CONF_SERVE_JOB_TTL = "fugue.serve.job_ttl"
FUGUE_CONF_SERVE_CLIENT_RETRIES = "fugue.serve.client.retries"
FUGUE_CONF_SERVE_PREWARM = "fugue.serve.prewarm"
FUGUE_CONF_SERVE_FLEET_REPLICAS = "fugue.serve.fleet.replicas"
FUGUE_CONF_SERVE_FLEET_HOST = "fugue.serve.fleet.host"
FUGUE_CONF_SERVE_FLEET_PORT = "fugue.serve.fleet.port"
FUGUE_CONF_SERVE_FLEET_HEALTH_INTERVAL = "fugue.serve.fleet.health_interval"
FUGUE_CONF_SERVE_FLEET_DEATH_THRESHOLD = "fugue.serve.fleet.death_threshold"
FUGUE_CONF_SERVE_FLEET_RESULT_CACHE_DIR = "fugue.serve.fleet.result_cache_dir"
FUGUE_CONF_SERVE_FLEET_DEVICE_SLICES = "fugue.serve.fleet.device_slices"
FUGUE_CONF_SERVE_SCHEDULER = "fugue.serve.scheduler"
FUGUE_CONF_SERVE_ADMISSION_MEMORY_FRACTION = (
    "fugue.serve.admission.memory_fraction"
)
FUGUE_CONF_SERVE_ADMISSION_MAX_WAIT = "fugue.serve.admission.max_predicted_wait"
FUGUE_CONF_SERVE_ADMISSION_DEFAULT_MS = "fugue.serve.admission.default_cost_ms"
FUGUE_CONF_SERVE_ADMISSION_DEFAULT_BYTES = (
    "fugue.serve.admission.default_cost_bytes"
)
FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS = "fugue.serve.autoscale.max_replicas"
FUGUE_CONF_SERVE_AUTOSCALE_MIN_REPLICAS = "fugue.serve.autoscale.min_replicas"
FUGUE_CONF_SERVE_AUTOSCALE_INTERVAL = "fugue.serve.autoscale.interval"
FUGUE_CONF_SERVE_AUTOSCALE_UP_QUEUE = "fugue.serve.autoscale.scale_up_queue"
FUGUE_CONF_SERVE_AUTOSCALE_UP_P99_MS = "fugue.serve.autoscale.scale_up_p99_ms"
FUGUE_CONF_SERVE_AUTOSCALE_SUSTAIN_TICKS = "fugue.serve.autoscale.sustain_ticks"
FUGUE_CONF_SERVE_AUTOSCALE_IDLE_TICKS = "fugue.serve.autoscale.idle_ticks"
FUGUE_CONF_SERVE_AUTOSCALE_COOLDOWN = "fugue.serve.autoscale.cooldown"
FUGUE_CONF_OPTIMIZE = "fugue.optimize"
FUGUE_CONF_OPTIMIZE_CSE = "fugue.optimize.cse"
FUGUE_CONF_OPTIMIZE_FILTER = "fugue.optimize.filter_pushdown"
FUGUE_CONF_OPTIMIZE_FUSION = "fugue.optimize.fusion"
FUGUE_CONF_OPTIMIZE_PROJECTION = "fugue.optimize.projection_pushdown"
FUGUE_CONF_OPTIMIZE_RESULT_CACHE = "fugue.optimize.result_cache"
FUGUE_CONF_OPTIMIZE_CACHE_MAX_ENTRIES = "fugue.optimize.cache.max_entries"
FUGUE_CONF_OPTIMIZE_CACHE_MAX_PROGRAMS = "fugue.optimize.cache.max_programs"
FUGUE_CONF_OPTIMIZE_CACHE_MAX_RESULT_BYTES = (
    "fugue.optimize.cache.max_result_bytes"
)
FUGUE_CONF_OPTIMIZE_CACHE_DIR = "fugue.optimize.cache.dir"
FUGUE_CONF_SERVE_RESULT_CACHE = "fugue.serve.result_cache"
FUGUE_CONF_DEBUG_LOCK_SANITIZER = "fugue.debug.lock_sanitizer"
FUGUE_CONF_DEBUG_RETRACE_SENTINEL = "fugue.debug.retrace_sentinel"
FUGUE_CONF_DEBUG_RETRACE_SENTINEL_MAX_TRACES = (
    "fugue.debug.retrace_sentinel.max_traces"
)
FUGUE_CONF_DEBUG_RETRACE_SENTINEL_RAISE = "fugue.debug.retrace_sentinel.raise"
FUGUE_CONF_OBS_ENABLED = "fugue.obs.enabled"
FUGUE_CONF_OBS_TRACE_PATH = "fugue.obs.trace_path"
FUGUE_CONF_OBS_SLOW_QUERY_MS = "fugue.obs.slow_query_ms"
FUGUE_CONF_OBS_SAMPLE_RATE = "fugue.obs.sample_rate"
FUGUE_CONF_OBS_PROFILE = "fugue.obs.profile"
FUGUE_CONF_STATS_PATH = "fugue.stats.path"
FUGUE_CONF_STATS_HISTORY = "fugue.stats.history"
FUGUE_CONF_STREAM_SOURCE = "fugue.stream.source"
FUGUE_CONF_STREAM_PATTERN = "fugue.stream.pattern"
FUGUE_CONF_STREAM_INTERVAL = "fugue.stream.interval"
FUGUE_CONF_STREAM_WATERMARK_DELAY = "fugue.stream.watermark.delay"
FUGUE_CONF_STREAM_MAX_FILES = "fugue.stream.max_files_per_batch"
FUGUE_CONF_STREAM_BATCH_ROWS = "fugue.stream.batch_rows"
FUGUE_CONF_LAKE_COMMIT_RETRIES = "fugue.lake.commit.retries"
FUGUE_CONF_LAKE_COMMIT_BACKOFF = "fugue.lake.commit.backoff"
FUGUE_CONF_LAKE_COMPACT_TARGET_ROWS = "fugue.lake.compact.target_rows"
FUGUE_CONF_LAKE_SERVE_PATH = "fugue.lake.serve.path"
FUGUE_CONF_LAKE_VERIFY = "fugue.lake.verify"

FUGUE_COMPILE_TIME_CONFIGS = {
    FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE,
    FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT,
    FUGUE_CONF_WORKFLOW_EXCEPTION_OPTIMIZE,
    FUGUE_CONF_SQL_IGNORE_CASE,
    FUGUE_CONF_SQL_DIALECT,
}

class ConfKeyInfo(NamedTuple):
    """One declared conf key: its value type (``object`` = unchecked),
    default, and description. ``in_defaults=False`` keys are declared (the
    analyzer knows them) but deliberately NOT seeded into the global conf
    (e.g. legacy/no-op knobs)."""

    key: str
    type: type
    default: Any
    description: str
    in_defaults: bool = True


_CONF_REGISTRY: Dict[str, ConfKeyInfo] = {}


def register_conf_key(
    key: str,
    type_: type,
    default: Any,
    description: str,
    in_defaults: bool = True,
) -> None:
    """Declare a conf key (type + default + description). Backends and
    plugins may call this for their own ``fugue.*`` keys so the static
    analyzer recognizes them; keys registered after import time extend the
    live registry but not the already-built global defaults."""
    _CONF_REGISTRY[key] = ConfKeyInfo(key, type_, default, description, in_defaults)


def declared_conf_keys() -> Dict[str, ConfKeyInfo]:
    """Snapshot of every declared conf key (key -> ConfKeyInfo). Shared by
    the engine conf getters (via :func:`conf_default`) and the analyzer's
    conf pass."""
    return dict(_CONF_REGISTRY)


def conf_default(key: str) -> Any:
    """The registered default of a declared conf key."""
    return _CONF_REGISTRY[key].default


def typed_conf_get(conf: Any, key: str) -> Any:
    """Read a declared key from a conf mapping: missing keys return the
    registered default, present values coerce to the key's DECLARED type
    (the same ``_convert`` semantics the analyzer's FWF202 rule checks;
    ``object``-typed keys pass through untouched)."""
    info = _CONF_REGISTRY[key]
    if key not in conf:
        return info.default
    value = conf[key]
    if info.type is object:
        return value
    return _convert(value, info.type)


def _declare_defaults() -> None:
    r = register_conf_key
    r(
        FUGUE_CONF_ANALYSIS,
        str,
        "warn",
        "pre-execution static analysis of the workflow DAG: 'off' skips it, "
        "'warn' (default) logs diagnostics and proceeds, 'error' raises "
        "before any task executes when error-level diagnostics exist",
    )
    r(FUGUE_CONF_WORKFLOW_CONCURRENCY, int, 1, "parallel task slots of the DAG runner")
    # fault tolerance: attempts = 1 means no retry; backoff is the base
    # exponential delay in seconds (delay = backoff * 2**(attempt-1)),
    # jitter a multiplicative fraction added on top. Only TRANSIENT error
    # classes retry (fs/IO, RPC transport, jax RESOURCE_EXHAUSTED) — see
    # fugue_tpu/workflow/fault.py:classify_error. timeout is the per-task
    # wall clock in seconds (0 = unlimited), enforced by the parallel
    # runner. resume=True keeps a run manifest of completed task uuids so
    # re-running an identical DAG after a crash restarts at the frontier.
    r(FUGUE_CONF_WORKFLOW_RETRY_MAX_ATTEMPTS, int, 1, "task attempts (1 = no retry)")
    r(FUGUE_CONF_WORKFLOW_RETRY_BACKOFF, float, 0.1, "base exponential retry delay (s)")
    r(FUGUE_CONF_WORKFLOW_RETRY_JITTER, float, 0.1, "multiplicative retry jitter fraction")
    r(FUGUE_CONF_WORKFLOW_TIMEOUT, float, 0.0, "per-task wall clock (s, 0 = unlimited)")
    r(FUGUE_CONF_WORKFLOW_RESUME, bool, False, "manifest-backed resume of crashed runs")
    r(
        FUGUE_CONF_WORKFLOW_CHECKPOINT_PATH,
        str,
        "",
        "durable dir/URI for strong checkpoints, yields and run manifests",
    )
    r(FUGUE_CONF_WORKFLOW_EXCEPTION_HIDE, str, "fugue_tpu.", "module prefix hidden from tracebacks")
    r(FUGUE_CONF_WORKFLOW_EXCEPTION_INJECT, int, 3, "user stack frames attached to task errors")
    r(FUGUE_CONF_WORKFLOW_EXCEPTION_OPTIMIZE, bool, True, "prune framework frames from tracebacks")
    r(FUGUE_CONF_SQL_IGNORE_CASE, bool, False, "case-insensitive FugueSQL keywords")
    r(FUGUE_CONF_SQL_DIALECT, str, "spark", "SQL dialect of raw SELECT statements")
    r(FUGUE_CONF_RPC_SERVER, str, "native", "driver<->worker RPC server ('native' or 'http')")
    r(
        FUGUE_CONF_RPC_HTTP_RETRIES,
        int,
        2,
        # bounded exponential-backoff retries for the HTTP RPC client on
        # transient transport failures (connection refused/reset, HTTP 503);
        # non-transient HTTP errors always fail fast
        "HTTP RPC client retries on transient transport failures",
    )
    r(
        FUGUE_CONF_JAX_PARTITIONS,
        int,
        0,
        "logical split count for host-fallback maps (0 = mesh size)",
    )
    # legacy/no-op: compilation is always on; declared so old confs lint clean
    r(FUGUE_CONF_JAX_COMPILE, bool, True, "legacy no-op (compilation is always on)", in_defaults=False)
    r(
        FUGUE_CONF_JAX_ROW_BUCKET,
        int,
        0,
        "round row counts up to multiples of this before compile so nearby "
        "shapes share programs (0 = exact shapes; every distinct row count "
        "compiles its own program)",
    )
    r(FUGUE_CONF_JAX_DEVICE_ZIP, bool, True, "device-side zip of co-partitioned frames")
    # Two-tier placement (see JaxExecutionEngine): frames below the byte
    # threshold ingest onto the host (CPU-XLA) mesh; at/above it they go to
    # the accelerator mesh. The default is tuned for network-attached
    # accelerators where per-query host<->device transfer costs seconds per
    # GB; on PCIe-local TPU hosts set a lower threshold or placement=device.
    r(FUGUE_CONF_JAX_PLACEMENT, str, "auto", "ingest tier: auto | device | host")
    r(
        FUGUE_CONF_JAX_MIN_DEVICE_BYTES,
        int,
        256 * 1024 * 1024,
        "auto-placement threshold: smaller frames stay on the host tier",
    )
    # DEPRECATED alias of fugue.optimize.cache.dir (the persistent
    # executable cache that replaced jax's own compilation cache here).
    # Precedence: fugue.optimize.cache.dir wins when both are set; a
    # value arriving only through this key (or the FUGUE_JAX_COMPILE_CACHE
    # env var) still enables the SAME disk tier, with a deprecation note
    # logged — two divergent caches never run side by side.
    r(
        FUGUE_CONF_JAX_COMPILE_CACHE,
        str,
        "",
        "DEPRECATED alias of fugue.optimize.cache.dir (persistent "
        "executable cache dir); the new key wins when both are set",
    )
    # streamed parquet ingest/save: 0 = eager (whole-table). > 0 pipelines
    # arrow record-batch decode with per-shard device_put staging on load
    # (each mesh shard ships as soon as its rows are decoded, while the
    # next batches decode) and bounds parquet row groups on save. The
    # ingest stays LAZY: host-only chains never pay a device round trip.
    r(FUGUE_CONF_JAX_IO_BATCH_ROWS, int, 0, "streamed parquet ingest batch rows (0 = eager)")
    # end-to-end IO pipelining over the streamed paths (requires
    # batch_rows > 0): on load, the first batches kick a background warm
    # of the persistent-executable cache so the first dispatch after
    # assembly is execute-only; on save, row-group encode/write of chunk
    # k overlaps the device->host fetch of chunk k+1. Results and row
    # order are identical to the unpipelined stream (parity-tested).
    r(
        FUGUE_CONF_JAX_IO_PIPELINE,
        bool,
        True,
        "overlap streamed-IO decode/staging with executable warm (load) "
        "and row-group writes with result fetch (save)",
    )
    # group-by reduction algorithm (legacy knob, kept for back-compat):
    # "always"/"never" pin the strategy below to matmul/scatter; "auto"
    # defers to fugue.jax.groupby.strategy.
    r(FUGUE_CONF_JAX_GROUPBY_MATMUL, str, "auto", "legacy matmul pin: auto | always | never")
    # segment-reduction strategy: "auto" consults the measured crossover
    # table in jax_backend/segtune.py (scatter on CPU meshes, one-hot
    # matmul on accelerators below the segment cap, sorted scatter above
    # it), sharpened by a one-shot on-device autotune; or pin one of
    # "matmul" | "matmul_bf16" | "scatter" | "sort". matmul_bf16 trades
    # ~8 mantissa bits for speed and is PIN-ONLY — auto never picks it.
    r(
        FUGUE_CONF_JAX_GROUPBY_STRATEGY,
        str,
        "auto",
        "segment-reduction kernel: auto | matmul | matmul_bf16 | scatter | sort",
    )
    # autotune policy: "auto" probes on accelerator meshes for large
    # frames only; True/False force it on/off. Mixed-type by design.
    r(FUGUE_CONF_JAX_GROUPBY_AUTOTUNE, object, "auto", "one-shot strategy autotune: auto | bool")
    # all-to-all shuffle repartition (jax_backend/shuffle.py): co-locate
    # matching keys per device shard before segment reductions (group-by,
    # join match counts). "auto" shuffles only on multi-device meshes for
    # frames large enough to amortize the padded receive; "on"/"off" pin
    # it. Single-device meshes never shuffle regardless.
    r(FUGUE_CONF_JAX_SHUFFLE, str, "auto", "key-shuffle repartition: auto | on | off")
    # collective/compute overlap: double-buffer the next key-range's
    # all-to-all behind the current range's local reduction. "auto"
    # enables it on accelerator meshes only (CPU collectives are
    # synchronous, so the split is pure overhead there).
    r(FUGUE_CONF_JAX_SHUFFLE_OVERLAP, str, "auto", "shuffle/compute overlap: auto | on | off")
    # device slice for the engine's mesh: a comma-separated list of
    # indices into jax.devices() (e.g. "0,1"). Empty = all devices. How
    # a serve fleet gives each replica its own slice of the pod.
    r(FUGUE_CONF_JAX_DEVICES, str, "", "engine device slice: comma-separated jax.devices() indices")
    # device-memory governance (jax_backend/memory.py): budget_bytes > 0
    # (or budget_fraction > 0 of the detected per-device memory) turns on
    # the HBM byte ledger + admission controller. An ingest/persist that
    # would push the device tier past high_watermark * budget first
    # spills LRU persisted frames to the host tier down to low_watermark;
    # a frame whose estimated footprint alone exceeds the budget is
    # placed on the host tier directly. 0/0.0 = ungoverned (default).
    r(FUGUE_CONF_JAX_MEMORY_BUDGET_BYTES, int, 0, "device-memory budget bytes (0 = ungoverned)")
    r(
        FUGUE_CONF_JAX_MEMORY_BUDGET_FRACTION,
        float,
        0.0,
        "budget as a fraction of detected per-device memory",
    )
    r(FUGUE_CONF_JAX_MEMORY_HIGH_WATERMARK, float, 0.9, "admission spill trigger fraction")
    r(FUGUE_CONF_JAX_MEMORY_LOW_WATERMARK, float, 0.75, "spill-down target fraction")
    # device-fault resilience (engine.recover_from_device_loss): on a
    # DEVICE_LOST-classified XLA error the engine rebuilds its mesh from
    # the surviving devices, evacuates/re-reads live frames, and retries
    # the task under the normal backoff budget. Frames without
    # recoverable lineage fail their owning query with DeviceLostError —
    # never the process. Needs >1 device to have survivors (FWF509 warns
    # when fugue.jax.devices pins a single device). Read with a local
    # default-on fallback by the engine rather than seeded into every
    # conf (in_defaults=False), so FWF509 only fires on EXPLICIT keys.
    r(
        FUGUE_CONF_JAX_RECOVERY_ENABLED,
        bool,
        True,
        "degraded-mesh rebuild + block evacuation on device loss",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_JAX_RECOVERY_MAX_LOSSES,
        int,
        0,
        "cumulative device losses an engine absorbs before failing fast "
        "(0 = unlimited; each loss shrinks the mesh by the dead devices)",
        in_defaults=False,
    )
    # consumed with local fallbacks by their owning modules (multi-process
    # init in jax_backend/distributed.py, HTTP RPC in rpc/http.py) rather
    # than through the global defaults table — declared here so the
    # analyzer's conf pass recognizes them, NOT seeded (in_defaults=False)
    r(
        "fugue.jax.dist.coordinator",
        str,
        "",
        "host:port of process 0 for multi-process jax init",
        in_defaults=False,
    )
    r(
        "fugue.jax.dist.num_processes",
        int,
        1,
        "total process count of the multi-process mesh",
        in_defaults=False,
    )
    r(
        "fugue.jax.dist.process_id",
        int,
        0,
        "this process's index in the multi-process mesh",
        in_defaults=False,
    )
    r(
        "fugue.rpc.http_server.host",
        str,
        "127.0.0.1",
        "bind/connect host of the HTTP RPC server",
        in_defaults=False,
    )
    r(
        "fugue.rpc.http_server.port",
        int,
        0,
        "HTTP RPC server port (0 = ephemeral)",
        in_defaults=False,
    )
    r(
        "fugue.rpc.http_server.timeout",
        float,
        30.0,
        "HTTP RPC request timeout (s)",
        in_defaults=False,
    )
    # daemon-hardening knobs of the HTTP server (rpc/http.py): a request
    # body over the cap is rejected with 413 before it is read into
    # memory; read_timeout bounds how long one request may keep a handler
    # thread blocked on a slow/stalled client socket
    r(
        FUGUE_CONF_RPC_HTTP_MAX_BODY,
        int,
        64 * 1024 * 1024,
        "max HTTP request body bytes (413 above; 0 = unlimited)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_RPC_HTTP_READ_TIMEOUT,
        float,
        30.0,
        "per-request socket read timeout of the HTTP server (s)",
        in_defaults=False,
    )
    # multi-tenant serving daemon (fugue_tpu/serve/): consumed by the
    # daemon via typed_conf_get with these registered defaults — declared
    # module-owned (not seeded) like the other fugue.rpc.http_server keys
    r(
        FUGUE_CONF_SERVE_HOST,
        str,
        "127.0.0.1",
        "bind host of the serving daemon's HTTP API",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_PORT,
        int,
        0,
        "serving daemon HTTP port (0 = ephemeral)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_MAX_CONCURRENT,
        int,
        4,
        "workflow submissions the daemon runs concurrently",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_SESSION_TTL,
        float,
        3600.0,
        "idle seconds before a serve session expires (0 = never)",
        in_defaults=False,
    )
    # sync submissions park an HTTP handler thread while they wait: the
    # cap bounds how long a wedged job can pin it — on expiry the call
    # returns the live job snapshot (still queued/running) and the
    # client polls /v1/jobs/<id> like an async submission
    r(
        FUGUE_CONF_SERVE_SYNC_WAIT,
        float,
        600.0,
        "max seconds a sync submit blocks before returning the job "
        "snapshot for polling (0 = unbounded)",
        in_defaults=False,
    )
    # per-tenant fair share of the device-memory budget: > 0 makes the
    # governor's spill ordering FAIR (the tenant most over
    # fraction * budget spills first, LRU within it) so one heavy serve
    # session cannot evict everyone else's persisted tables; 0 keeps the
    # original global LRU order
    r(
        FUGUE_CONF_SERVE_TENANT_BUDGET_FRACTION,
        float,
        0.0,
        "per-tenant fair share of the memory budget (0 = global LRU)",
        in_defaults=False,
    )
    # serving resilience (serve/state.py, serve/supervisor.py): a durable
    # state_path turns on the daemon's crash journal — the session
    # registry, per-session saved-table catalog (parquet artifacts with
    # sha256 fingerprints) and the async job journal are atomically
    # rewritten through engine.fs on every mutation, so a restarted
    # daemon rehydrates sessions, lazily reloads integrity-verified hot
    # tables, and resumes interrupted async jobs
    r(
        FUGUE_CONF_SERVE_STATE_PATH,
        str,
        "",
        "durable dir/URI for the daemon's state journal + hot-table "
        "artifacts ('' = ephemeral daemon, no crash recovery)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_DRAIN_TIMEOUT,
        float,
        30.0,
        "seconds in-flight jobs get to finish on stop(drain=True) before "
        "their tokens are cancelled and they are abandoned",
        in_defaults=False,
    )
    # backpressure & admission: overload answers 503/429 WITH Retry-After
    # instead of queueing unboundedly or blocking HTTP handler threads
    r(
        FUGUE_CONF_SERVE_MAX_QUEUE,
        int,
        256,
        "queued-job backlog over which new submissions get 503 + "
        "Retry-After (0 = unbounded queue)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_SESSION_MAX_JOBS,
        int,
        0,
        "per-session queued+running job cap; over it submissions get "
        "429 + Retry-After (0 = uncapped)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_MEMORY_REJECT,
        float,
        0.0,
        "device-tier fill fraction of the memory budget over which new "
        "submissions get 503 (0 = no memory-pressure rejection)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_SYNC_DEGRADE_DEPTH,
        int,
        0,
        "queued-job backlog at which sync submits degrade to async "
        "202 + job-id instead of parking an HTTP worker (0 = never)",
        in_defaults=False,
    )
    # engine supervisor: consecutive-failure circuit breakers per session
    # and per query fingerprint (deterministic workflow uuid) quarantine
    # poison queries with a structured error instead of burning retries;
    # a tripped breaker half-opens after the cooldown to probe recovery
    r(
        FUGUE_CONF_SERVE_BREAKER_THRESHOLD,
        int,
        5,
        "consecutive job failures that trip a session/query circuit "
        "breaker (0 = breakers off)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_BREAKER_COOLDOWN,
        float,
        30.0,
        "seconds a tripped breaker stays open before half-opening for "
        "one probe submission",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_HEARTBEAT_TIMEOUT,
        float,
        0.0,
        "seconds without a heartbeat before the supervisor cancels a "
        "running job as wedged (0 = runner timeouts only)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_JOB_TTL,
        float,
        600.0,
        "seconds a finished job keeps its result payload before TTL "
        "eviction drops it (status survives; 0 = keep until the record "
        "cap evicts the job)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_CLIENT_RETRIES,
        int,
        2,
        "ServeClient retries on transient transport failures and "
        "503/429 backpressure answers (honors server Retry-After)",
        in_defaults=False,
    )
    # daemon pre-warm (cold-start recovery): with a persistent
    # executable cache dir configured, a starting daemon loads the
    # cached executables matching its engine signature in the
    # background and /v1/health answers 503 state="warming" until the
    # warm finishes — so an LB routes the first query only when its
    # dispatch is compile-free (time_to_first_query becomes IO-bound)
    r(
        FUGUE_CONF_SERVE_PREWARM,
        bool,
        True,
        "pre-load persistent-cached executables at daemon start before "
        "/v1/health reports ready",
        in_defaults=False,
    )
    # serving fleet (fugue_tpu/serve/fleet.py): a front-tier router
    # spreading sessions across N daemon replicas with journal-based
    # migration — on replica death (or a planned drain for a rolling
    # restart) a survivor adopts the dead replica's journal, so sessions
    # and fingerprint-verified hot tables move without losing committed
    # saves. Replicas must share fugue.serve.state_path (and ideally the
    # fugue.optimize.cache.dir executable cache) — FWF504 warns when a
    # multi-replica conf lacks either.
    r(
        FUGUE_CONF_SERVE_FLEET_REPLICAS,
        int,
        0,
        "daemon replicas a ServeFleet runs behind the router (0/1 = "
        "single-daemon serving, no fleet)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_FLEET_HOST,
        str,
        "127.0.0.1",
        "bind host of the fleet router's HTTP front tier",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_FLEET_PORT,
        int,
        0,
        "fleet router HTTP port (0 = ephemeral)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_FLEET_HEALTH_INTERVAL,
        float,
        1.0,
        "seconds between the router's /v1/health polls of each replica",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_FLEET_DEATH_THRESHOLD,
        int,
        3,
        "consecutive health-poll/forward transport failures before the "
        "router declares a replica dead and fails its sessions over",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_FLEET_RESULT_CACHE_DIR,
        str,
        "",
        "dir/URI (via engine.fs) of the fleet's cross-replica result "
        "cache for pure queries, keyed by DAG fingerprint + table "
        "artifact sha256s ('' = off; ServeFleet defaults it under the "
        "shared state path)",
        in_defaults=False,
    )
    # per-replica device slices: when on and the pod has at least one
    # device per replica, the fleet partitions jax.devices() evenly and
    # sets each replica's fugue.jax.devices so every engine owns its own
    # sub-mesh (capacity model: qps x devices) instead of all replicas
    # sharing one global mesh.
    r(
        FUGUE_CONF_SERVE_FLEET_DEVICE_SLICES,
        bool,
        False,
        "give each fleet replica its own slice of jax.devices() via "
        "fugue.jax.devices (needs >= 1 device per replica)",
        in_defaults=False,
    )
    # overload-survival plane (ISSUE 18): the predictive scheduler
    # replaces FIFO job pickup with shortest-predicted-job-first inside
    # per-tenant fairness, costs each query from its fingerprint's
    # stats-store history (fugue.stats.path), and admits-or-queues on
    # PREDICTED device bytes against the governed memory budget instead
    # of rejecting on observed fill. fugue.serve.admission.* tune the
    # predictions; fugue.serve.autoscale.* drive the fleet autoscaler
    # (scale up on sustained queue/latency pressure, drain-then-retire
    # on idle via the same journal-adoption move as a rolling restart).
    r(
        FUGUE_CONF_SERVE_SCHEDULER,
        str,
        "fifo",
        "job scheduling policy: fifo | predictive (stats-store cost "
        "model, shortest-job-first within per-tenant fairness, "
        "priority/deadline submission fields)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_ADMISSION_MEMORY_FRACTION,
        float,
        0.8,
        "fraction of the governed device-memory budget the predictive "
        "scheduler plans into: a queued job whose predicted bytes would "
        "push the in-flight prediction over it waits for headroom "
        "instead of starting (0 = predicted-memory gating off)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_ADMISSION_MAX_WAIT,
        float,
        0.0,
        "seconds of predicted queue drain beyond which new submissions "
        "are shed in priority order with 503 + Retry-After sized from "
        "the predicted drain (0 = predictive shedding off)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_ADMISSION_DEFAULT_MS,
        float,
        250.0,
        "assumed wall milliseconds for a query fingerprint with no "
        "stats-store history",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_ADMISSION_DEFAULT_BYTES,
        int,
        32 * 1024 * 1024,
        "assumed peak device bytes for a query fingerprint with no "
        "stats-store history",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS,
        int,
        0,
        "replica ceiling the fleet autoscaler may grow to (0 = "
        "autoscaler off; must exceed fugue.serve.fleet.replicas to "
        "ever scale up)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_AUTOSCALE_MIN_REPLICAS,
        int,
        1,
        "replica floor scale-down must never drain below",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_AUTOSCALE_INTERVAL,
        float,
        2.0,
        "seconds between autoscaler pressure samples",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_AUTOSCALE_UP_QUEUE,
        int,
        4,
        "mean queued jobs per replica that counts one sample as "
        "pressured",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_AUTOSCALE_UP_P99_MS,
        float,
        0.0,
        "fleet p99 job milliseconds that counts one sample as "
        "pressured (0 = queue-depth signal only)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_AUTOSCALE_SUSTAIN_TICKS,
        int,
        3,
        "consecutive pressured samples before a scale-up (one spike "
        "must not spawn a replica)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_AUTOSCALE_IDLE_TICKS,
        int,
        10,
        "consecutive idle samples (no queue, no running jobs) before "
        "drain-then-retire of the newest surplus replica",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_SERVE_AUTOSCALE_COOLDOWN,
        float,
        10.0,
        "seconds after any scale action during which the autoscaler "
        "only observes",
        in_defaults=False,
    )
    # cost-based DAG optimizer (fugue_tpu/optimize): the rewrite phase
    # running between schema propagation and execution. "auto" (default)
    # enables it for jax engines only; per-rule keys disable individual
    # rewrites. Rewrites NEVER change task uuids (clones pin them), so
    # deterministic checkpoints and manifest resume are unaffected.
    r(
        FUGUE_CONF_OPTIMIZE,
        str,
        "auto",
        "DAG rewrite phase: off | on | auto (jax engines only)",
    )
    r(FUGUE_CONF_OPTIMIZE_CSE, bool, True, "common-subplan elimination rule")
    r(
        FUGUE_CONF_OPTIMIZE_FILTER,
        bool,
        True,
        "filter pushdown past select/rename + parquet row-group pruning",
    )
    r(
        FUGUE_CONF_OPTIMIZE_FUSION,
        bool,
        True,
        "select/rename/filter chain fusion into one compiled program",
    )
    r(
        FUGUE_CONF_OPTIMIZE_PROJECTION,
        bool,
        True,
        "projection pushdown into the parquet load's narrow-load planner",
    )
    # process-wide plan & result cache (fugue_tpu/optimize/cache.py):
    # compiled jit program handles are ALWAYS shared across same-conf
    # engine instances; result_cache additionally serves
    # deterministically-checkpointed task artifacts from memory while
    # the artifact exists (opt-in: the artifact already gives cross-run
    # reuse, the memory tier is for hot repeated pipelines)
    r(
        FUGUE_CONF_OPTIMIZE_RESULT_CACHE,
        bool,
        False,
        "in-memory reuse of deterministically-checkpointed task results",
    )
    r(
        FUGUE_CONF_OPTIMIZE_CACHE_MAX_PROGRAMS,
        int,
        512,
        "LRU bound on process-wide cached compiled program handles",
    )
    r(
        FUGUE_CONF_OPTIMIZE_CACHE_MAX_ENTRIES,
        int,
        256,
        "LRU bound on process-wide cached result entries",
    )
    r(
        FUGUE_CONF_OPTIMIZE_CACHE_MAX_RESULT_BYTES,
        int,
        256 * 1024 * 1024,
        "byte bound on cached results (governed engines additionally "
        "clamp to a fraction of the HBM ledger budget)",
    )
    # the plan cache's DISK tier (fugue_tpu/optimize/exec_cache.py):
    # compiled executables are AOT-serialized through engine.fs under
    # this dir/URI, keyed by the plan signature (platform + mesh devices
    # + fugue.jax.* conf) plus the program key, fn source hash and
    # argument avals — so a FRESH PROCESS skips XLA compilation
    # entirely, and URI-capable storage lets fleet replicas share one
    # cache. Entries are version-stamped (jax/jaxlib/format rev); stale
    # or corrupt entries evict to a recompile, never an error. Takes
    # precedence over the deprecated fugue.jax.compile.cache alias.
    r(
        FUGUE_CONF_OPTIMIZE_CACHE_DIR,
        str,
        "",
        "dir/URI (via engine.fs) of the persistent compiled-executable "
        "cache ('' = disk tier off; overrides the deprecated "
        "fugue.jax.compile.cache alias)",
    )
    # serving daemon's cross-request query result cache: a resubmitted
    # identical pure query (same session, same table-catalog epoch, same
    # DAG uuid) answers from the cached payload with zero execution —
    # the "millions of users running similar queries" fast path
    r(
        FUGUE_CONF_SERVE_RESULT_CACHE,
        bool,
        True,
        "serving daemon cross-request result cache for pure queries",
        in_defaults=False,
    )
    # unified observability plane (fugue_tpu/obs): request-scoped span
    # tracing + metrics registry + Perfetto/Prometheus export. With
    # enabled=False every instrumentation site is an allocation-free
    # no-op (the hot-path contract the zero-overhead test enforces).
    # Module-owned like the serve keys: read via typed_conf_get, not
    # seeded into the global defaults.
    r(
        FUGUE_CONF_OBS_ENABLED,
        bool,
        False,
        "request-scoped span tracing: off = every instrumentation site "
        "is an allocation-free no-op",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_OBS_TRACE_PATH,
        str,
        "",
        "dir/URI (via engine.fs) for per-trace Chrome-trace JSON files "
        "loadable in Perfetto ('' = no trace files)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_OBS_SLOW_QUERY_MS,
        float,
        0.0,
        "jobs/runs slower than this log one structured record with "
        "their span breakdown (0 = off)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_OBS_SAMPLE_RATE,
        float,
        1.0,
        "fraction of eligible requests/runs that open a trace",
        in_defaults=False,
    )
    # per-task profiler (ISSUE 14): rows in/out, device bytes, compile/
    # execute/transfer split, queue wait, retries and cache events per
    # DAG task, surfaced as FugueWorkflowResult.profile() (EXPLAIN
    # ANALYZE). Needs fugue.obs.enabled for the span-derived phase
    # split — FWF505 warns about the silently inert combination.
    r(
        FUGUE_CONF_OBS_PROFILE,
        bool,
        False,
        "per-task runtime profiler (EXPLAIN ANALYZE); inert unless "
        "fugue.obs.enabled is also on",
        in_defaults=False,
    )
    # persisted runtime-statistics store (fugue_tpu/obs/stats_store.py):
    # profiled runs append per-task-uuid observed rows/bytes/timings
    # into a bounded ring per query fingerprint under this dir/URI via
    # engine.fs — the statistics the phase-2 cost model / adaptive
    # re-planning (ROADMAP item 1) will read. The serving daemon
    # defaults it to <fugue.serve.state_path>/stats.
    r(
        FUGUE_CONF_STATS_PATH,
        str,
        "",
        "dir/URI (via engine.fs) of the persisted runtime-statistics "
        "store ('' = off; serving defaults to <state_path>/stats)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_STATS_HISTORY,
        int,
        32,
        "observations kept per query fingerprint in the runtime-"
        "statistics store (bounded ring)",
        in_defaults=False,
    )
    # continuous execution (fugue_tpu/stream): a standing pipeline tails
    # new parquet files under fugue.stream.source through the fs layer
    # (mtime-then-name discovery order), folds each micro-batch into
    # device-resident accumulators carried ACROSS batches, and commits
    # an exactly-once progress manifest (consumed files + accumulator
    # snapshot) per batch. Module-owned (read via typed_conf_get, not
    # seeded); FWF506 warns about inert fugue.stream.* keys (no source)
    # and a standing pipeline without fugue.workflow.resume (no durable
    # progress manifest -> a restart refolds from scratch).
    r(
        FUGUE_CONF_STREAM_SOURCE,
        str,
        "",
        "dir/URI (via engine.fs) a standing pipeline tails for arriving "
        "parquet files ('' = no streaming source)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_STREAM_PATTERN,
        str,
        "*.parquet",
        "basename glob the tail source matches new files against",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_STREAM_INTERVAL,
        float,
        1.0,
        "seconds between a standing pipeline's discovery polls "
        "(0 = manual stepping only, no ticker thread)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_STREAM_WATERMARK_DELAY,
        float,
        0.0,
        "event-time lateness allowance: watermark = max event time seen "
        "- delay; a window emits only once the watermark passes its end",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_STREAM_MAX_FILES,
        int,
        0,
        "cap on files folded per micro-batch (0 = all newly discovered)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_STREAM_BATCH_ROWS,
        int,
        0,
        "rows per host chunk when folding one parquet file "
        "(0 = pyarrow's record-batch default)",
        in_defaults=False,
    )
    # versioned table storage (fugue_tpu/lake): snapshot-isolated tables
    # of immutable parquet data files + a _meta/ manifest log, committed
    # through an optimistic CAS on the next manifest slot. Module-owned
    # (read via typed_conf_get, not seeded); FWF507 warns about inert
    # fugue.lake.* keys and AS OF reads against non-lake paths.
    r(
        FUGUE_CONF_LAKE_COMMIT_RETRIES,
        int,
        10,
        "optimistic-commit attempts before a LakeCommitConflict "
        "propagates (each retry rebases on the new table head)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_LAKE_COMMIT_BACKOFF,
        float,
        0.05,
        "base seconds of linear backoff between lake commit retries "
        "(attempt k sleeps ~k*backoff with jitter)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_LAKE_COMPACT_TARGET_ROWS,
        int,
        1_000_000,
        "rows per rewritten data file when compaction coalesces "
        "streamed micro-batch files into larger ones",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_LAKE_SERVE_PATH,
        str,
        "",
        "base dir/URI for lake-backed serve tables: session save_table "
        "commits each materialized view as a shared versioned table "
        "under <path>/<name> any replica can query ('' = per-session "
        "parquet artifacts, the pre-lake behavior)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_LAKE_VERIFY,
        bool,
        False,
        "verify each data file's manifest-recorded sha256 on scan; a "
        "mismatch fails the read with LakeIntegrityError and counts "
        "fugue_lake_integrity_rejected (files committed before the "
        "checksum field skip verification)",
        in_defaults=False,
    )
    # runtime lock-order sanitizer (testing/locktrace.py): debug-only.
    # Off (the default), every tracked_lock() call returns a plain
    # threading lock — no wrapper, zero overhead. On, locks created
    # afterwards are name-registered and every acquisition is checked
    # for ordering inversions/potential deadlock cycles. Consumed by
    # the serving daemon at start and by tests; module-owned, not seeded.
    r(
        FUGUE_CONF_DEBUG_LOCK_SANITIZER,
        bool,
        False,
        "debug lock-order sanitizer: wrap locks created after arming and "
        "report acquisition-order inversions (off = zero overhead)",
        in_defaults=False,
    )
    # runtime retrace sentinel (testing/retrace.py): debug-only twin of
    # the static FJX jit-hazard lint plane (analysis/jitlint). Off (the
    # default), every dispatch pays one module-global read. On, each
    # ACTUAL XLA trace of an engine program is counted per program key;
    # exceeding the budget logs (or raises) a report carrying the Python
    # callsite and the differing argument aval. Consumed by the serving
    # daemon at start and by tests; module-owned, not seeded.
    r(
        FUGUE_CONF_DEBUG_RETRACE_SENTINEL,
        bool,
        False,
        "debug retrace sentinel: count XLA traces per jitted program key "
        "and report programs exceeding the trace budget with callsite + "
        "differing aval (off = zero overhead)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_DEBUG_RETRACE_SENTINEL_MAX_TRACES,
        int,
        4,
        "trace budget per jitted program key before the retrace sentinel "
        "reports a violation (only read when the sentinel is armed)",
        in_defaults=False,
    )
    r(
        FUGUE_CONF_DEBUG_RETRACE_SENTINEL_RAISE,
        bool,
        False,
        "raise RetraceBudgetExceeded on a retrace-sentinel violation "
        "instead of logging it (CI benches die at the first unstable "
        "program)",
        in_defaults=False,
    )


_declare_defaults()

_DEFAULT_CONF: Dict[str, Any] = {
    info.key: info.default
    for info in _CONF_REGISTRY.values()
    if info.in_defaults
}

_GLOBAL_CONF = ParamDict(_DEFAULT_CONF)


def register_global_conf(conf: Dict[str, Any], on_dup: int = ParamDict.OVERWRITE) -> None:
    """Register global configs readable by every engine/workflow created after."""
    _GLOBAL_CONF.update(conf, on_dup=on_dup)


FUGUE_GLOBAL_CONF = _GLOBAL_CONF
