"""Aggregation and scalar function constructors over the column algebra
(reference fugue/column/functions.py:40-346)."""

import builtins
from typing import Any

from fugue_tpu.column.expressions import ColumnExpr, _FuncExpr, _to_col
from fugue_tpu.utils.assertion import assert_or_throw


# the variance family — shared by the device segment programs, the
# engine gates, the SQL bridge and both host evaluators (one constant
# so a new member can't be added to some layers and not others)
VARIANCE_FUNCS = (
    "stddev", "stddev_samp", "stddev_pop",
    "variance", "var_samp", "var_pop",
)


def variance_ddof(name: str) -> int:
    return 0 if name.endswith("_pop") else 1


def variance_stat(name: str) -> str:
    return "std" if name.startswith("stddev") else "var"


def _agg(name: str, col: Any, arg_distinct: bool = False) -> ColumnExpr:
    return _FuncExpr(name, _to_col(col), arg_distinct=arg_distinct, is_aggregation=True)


def min(col: Any) -> ColumnExpr:  # noqa: A001
    return _agg("min", col)


def max(col: Any) -> ColumnExpr:  # noqa: A001
    return _agg("max", col)


def sum(col: Any) -> ColumnExpr:  # noqa: A001
    return _agg("sum", col)


def avg(col: Any) -> ColumnExpr:
    return _agg("avg", col)


mean = avg


def first(col: Any) -> ColumnExpr:
    return _agg("first", col)


def last(col: Any) -> ColumnExpr:
    return _agg("last", col)


def count(col: Any) -> ColumnExpr:
    return _agg("count", col)


def count_distinct(col: Any) -> ColumnExpr:
    return _agg("count", col, arg_distinct=True)


def like(col: Any, pattern: str, negated: bool = False) -> ColumnExpr:
    """SQL ``LIKE`` with a literal pattern (``%``/``_`` wildcards)."""
    assert_or_throw(
        isinstance(pattern, str), ValueError("LIKE pattern must be a string")
    )
    return _FuncExpr("like", _to_col(col), pattern, bool(negated))


def case_when(*args: Any) -> ColumnExpr:
    """``CASE WHEN c1 THEN v1 [WHEN c2 THEN v2 ...] ELSE d END`` —
    arguments are condition/value pairs followed by the default (odd
    argument count required)."""
    assert_or_throw(
        len(args) >= 3 and len(args) % 2 == 1,
        ValueError("case_when takes cond/value pairs plus a default"),
    )
    return _FuncExpr("case_when", *[_to_col(a) for a in args])


def coalesce(*args: Any) -> ColumnExpr:
    assert_or_throw(len(args) > 0, ValueError("coalesce requires at least one arg"))
    return _FuncExpr("coalesce", *[_to_col(a) for a in args])


def is_agg(column: Any) -> bool:
    """Whether the expression contains an aggregation at any level."""
    if isinstance(column, _FuncExpr) and column.is_aggregation:
        return True
    if isinstance(column, ColumnExpr):
        from fugue_tpu.column.expressions import _BinaryOpExpr, _UnaryOpExpr

        if isinstance(column, _BinaryOpExpr):
            return is_agg(column.left) or is_agg(column.right)
        if isinstance(column, _UnaryOpExpr):
            return is_agg(column.col)
        if isinstance(column, _FuncExpr):
            return builtins.any(is_agg(a) for a in column.args)
    return False
