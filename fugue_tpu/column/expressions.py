"""Engine-agnostic column expression tree (reference
fugue/column/expressions.py:452-860 re-designed): the single algebra consumed
by the SQL text generator, the pandas evaluator, and the JAX device lowering.
"""

from typing import Any, Dict, Iterable, List, Optional, Union

import pyarrow as pa

from fugue_tpu.schema import Schema, parse_type
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.hash import to_uuid


class ColumnExpr:
    """Base of all column expressions."""

    def __init__(self):
        self._as_name = ""
        self._as_type: Optional[pa.DataType] = None

    # ---- naming / casting ------------------------------------------------
    @property
    def name(self) -> str:
        """The inherent name ('' when the expression has none)."""
        return ""

    @property
    def as_name(self) -> str:
        return self._as_name

    @property
    def as_type(self) -> Optional[pa.DataType]:
        return self._as_type

    @property
    def output_name(self) -> str:
        return self._as_name if self._as_name != "" else self.name

    def alias(self, as_name: str) -> "ColumnExpr":
        res = self._copy()
        res._as_name = as_name
        res._as_type = self._as_type
        return res

    def cast(self, data_type: Any) -> "ColumnExpr":
        res = self._copy()
        res._as_name = self._as_name
        if data_type is None:
            res._as_type = None
        elif isinstance(data_type, pa.DataType):
            res._as_type = data_type
        elif isinstance(data_type, str):
            res._as_type = parse_type(data_type)
        else:
            assert_or_throw(
                data_type in _PY_TYPES,
                ValueError(f"can't cast to {data_type!r}"),
            )
            res._as_type = _PY_TYPES[data_type]
        return res

    def _copy(self) -> "ColumnExpr":  # pragma: no cover - overridden
        raise NotImplementedError

    # ---- type inference --------------------------------------------------
    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        """Output type against an input schema; None when not inferrable."""
        return self._as_type

    def infer_schema_field(self, schema: Schema) -> pa.Field:
        name = self.output_name
        assert_or_throw(name != "", ValueError(f"{self} has no output name"))
        tp = self.infer_type(schema)
        assert_or_throw(tp is not None, ValueError(f"can't infer type of {self}"))
        return pa.field(name, tp)

    # ---- operators -------------------------------------------------------
    def __eq__(self, other: Any) -> "ColumnExpr":  # type: ignore[override]
        return _BinaryOpExpr("==", self, _to_col(other))

    def __ne__(self, other: Any) -> "ColumnExpr":  # type: ignore[override]
        return _BinaryOpExpr("!=", self, _to_col(other))

    def __lt__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("<", self, _to_col(other))

    def __le__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("<=", self, _to_col(other))

    def __gt__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr(">", self, _to_col(other))

    def __ge__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr(">=", self, _to_col(other))

    def __add__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("+", self, _to_col(other))

    def __radd__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("+", _to_col(other), self)

    def __sub__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("-", self, _to_col(other))

    def __rsub__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("-", _to_col(other), self)

    def __mul__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("*", self, _to_col(other))

    def __rmul__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("*", _to_col(other), self)

    def __truediv__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("/", self, _to_col(other))

    def __rtruediv__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("/", _to_col(other), self)

    def __and__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("&", self, _to_col(other))

    def __rand__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("&", _to_col(other), self)

    def __or__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("|", self, _to_col(other))

    def __ror__(self, other: Any) -> "ColumnExpr":
        return _BinaryOpExpr("|", _to_col(other), self)

    def __invert__(self) -> "ColumnExpr":
        return _UnaryOpExpr("~", self)

    def __neg__(self) -> "ColumnExpr":
        return _UnaryOpExpr("-", self)

    def is_null(self) -> "ColumnExpr":
        return _UnaryOpExpr("IS_NULL", self)

    def not_null(self) -> "ColumnExpr":
        return _UnaryOpExpr("NOT_NULL", self)

    # ---- identity --------------------------------------------------------
    def __uuid__(self) -> str:
        return to_uuid(
            type(self).__name__,
            self._as_name,
            str(self._as_type),
            self._uuid_keys(),
        )

    def _uuid_keys(self) -> List[Any]:  # pragma: no cover - overridden
        return []

    def __hash__(self) -> int:
        return hash(self.__uuid__())

    def __bool__(self) -> bool:
        raise ValueError("ColumnExpr can't be used as a boolean")

    def __str__(self) -> str:  # pragma: no cover - overridden
        raise NotImplementedError

    def __repr__(self) -> str:
        return str(self)


_PY_TYPES: Dict[Any, pa.DataType] = {
    int: pa.int64(),
    float: pa.float64(),
    str: pa.string(),
    bool: pa.bool_(),
    bytes: pa.binary(),
}


def _to_col(obj: Any) -> ColumnExpr:
    if isinstance(obj, ColumnExpr):
        return obj
    return lit(obj)


class _NamedColumnExpr(ColumnExpr):
    def __init__(self, name: str):
        super().__init__()
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    @property
    def wildcard(self) -> bool:
        return self._name == "*"

    def _copy(self) -> ColumnExpr:
        return _NamedColumnExpr(self._name)

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self._as_type is not None:
            return self._as_type
        if self.wildcard:
            return None
        return schema[self._name].type if self._name in schema else None

    def _uuid_keys(self) -> List[Any]:
        return [self._name]

    def __str__(self) -> str:
        res = self._name
        if self._as_type is not None:
            from fugue_tpu.schema import type_to_expr

            res = f"CAST({res} AS {type_to_expr(self._as_type)})"
        if self._as_name != "":
            res = f"{res} AS {self._as_name}"
        return res


class _LitColumnExpr(ColumnExpr):
    def __init__(self, value: Any):
        super().__init__()
        assert_or_throw(
            value is None or isinstance(value, (int, float, str, bool)),
            NotImplementedError(f"{value} is not a valid literal"),
        )
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    def _copy(self) -> ColumnExpr:
        return _LitColumnExpr(self._value)

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self._as_type is not None:
            return self._as_type
        if self._value is None:
            return pa.null()
        if isinstance(self._value, bool):
            return pa.bool_()
        if isinstance(self._value, int):
            return pa.int64()
        if isinstance(self._value, float):
            return pa.float64()
        return pa.string()

    def _uuid_keys(self) -> List[Any]:
        return [self._value]

    def __str__(self) -> str:
        if self._value is None:
            body = "NULL"
        elif isinstance(self._value, bool):
            body = "TRUE" if self._value else "FALSE"
        elif isinstance(self._value, str):
            body = "'" + self._value.replace("'", "''") + "'"
        else:
            body = str(self._value)
        if self._as_name != "":
            return f"{body} AS {self._as_name}"
        return body


class _UnaryOpExpr(ColumnExpr):
    def __init__(self, op: str, col: ColumnExpr):
        super().__init__()
        self._op = op
        self._col = col

    @property
    def op(self) -> str:
        return self._op

    @property
    def col(self) -> ColumnExpr:
        return self._col

    def _copy(self) -> ColumnExpr:
        return _UnaryOpExpr(self._op, self._col)

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self._as_type is not None:
            return self._as_type
        if self._op in ("IS_NULL", "NOT_NULL"):
            return pa.bool_()
        return self._col.infer_type(schema)

    def _uuid_keys(self) -> List[Any]:
        return [self._op, self._col.__uuid__()]

    def __str__(self) -> str:
        if self._op == "IS_NULL":
            body = f"{self._col} IS NULL"
        elif self._op == "NOT_NULL":
            body = f"{self._col} IS NOT NULL"
        elif self._op == "~":
            body = f"(NOT {self._col})"
        else:
            body = f"{self._op}({self._col})"
        if self._as_name != "":
            return f"{body} AS {self._as_name}"
        return body


_COMPARISON_OPS = {"==", "!=", "<", "<=", ">", ">="}
_LOGICAL_OPS = {"&", "|"}


class _BinaryOpExpr(ColumnExpr):
    def __init__(self, op: str, left: ColumnExpr, right: ColumnExpr):
        super().__init__()
        self._op = op
        self._left = left
        self._right = right

    @property
    def op(self) -> str:
        return self._op

    @property
    def left(self) -> ColumnExpr:
        return self._left

    @property
    def right(self) -> ColumnExpr:
        return self._right

    def _copy(self) -> ColumnExpr:
        return _BinaryOpExpr(self._op, self._left, self._right)

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self._as_type is not None:
            return self._as_type
        if self._op in _COMPARISON_OPS or self._op in _LOGICAL_OPS:
            return pa.bool_()
        lt = self._left.infer_type(schema)
        rt = self._right.infer_type(schema)
        if lt is None or rt is None:
            return None
        return _promote(lt, rt, self._op)

    def _uuid_keys(self) -> List[Any]:
        return [self._op, self._left.__uuid__(), self._right.__uuid__()]

    def __str__(self) -> str:
        op = {"==": "=", "&": "AND", "|": "OR"}.get(self._op, self._op)
        body = f"({self._left} {op} {self._right})"
        if self._as_name != "":
            return f"{body} AS {self._as_name}"
        return body


class _FuncExpr(ColumnExpr):
    def __init__(
        self,
        func: str,
        *args: Any,
        arg_distinct: bool = False,
        is_aggregation: bool = False,
    ):
        super().__init__()
        self._func = func
        self._args: List[ColumnExpr] = [_to_col(a) for a in args]
        self._arg_distinct = arg_distinct
        self._is_agg = is_aggregation

    @property
    def func(self) -> str:
        return self._func

    @property
    def args(self) -> List[ColumnExpr]:
        return self._args

    @property
    def arg_distinct(self) -> bool:
        return self._arg_distinct

    @property
    def is_aggregation(self) -> bool:
        return self._is_agg

    def _copy(self) -> ColumnExpr:
        return _FuncExpr(
            self._func,
            *self._args,
            arg_distinct=self._arg_distinct,
            is_aggregation=self._is_agg,
        )

    def infer_type(self, schema: Schema) -> Optional[pa.DataType]:
        if self._as_type is not None:
            return self._as_type
        f = self._func.lower()
        if f in ("count", "count_distinct"):
            return pa.int64()
        if f in ("avg", "mean"):
            return pa.float64()
        if f in ("min", "max", "sum", "first", "last") and len(self._args) == 1:
            t = self._args[0].infer_type(schema)
            if f == "sum" and t is not None and pa.types.is_integer(t):
                return pa.int64()
            return t
        if f == "coalesce":
            types = [a.infer_type(schema) for a in self._args]
            types = [t for t in types if t is not None and not pa.types.is_null(t)]
            return types[0] if types else None
        if f == "like":
            return pa.bool_()
        if f in ("abs", "nullif"):
            return self._args[0].infer_type(schema)
        if f in (
            "round", "sqrt", "exp", "ln", "log", "log2", "log10",
            "sin", "cos", "tan", "power", "pow",
            "stddev", "stddev_samp", "stddev_pop",
            "variance", "var_samp", "var_pop", "median",
        ):
            return pa.float64()
        if f in ("floor", "ceil", "ceiling", "sign", "length", "len"):
            return pa.int64()
        if f == "mod":
            t = self._args[0].infer_type(schema)
            return t if t is not None else pa.int64()
        if f in ("if", "iif") and len(self._args) == 3:
            return self._args[1].infer_type(schema) or self._args[
                2
            ].infer_type(schema)
        if f in (
            "upper", "ucase", "lower", "lcase", "trim", "ltrim", "rtrim",
            "reverse", "substring", "substr", "concat", "replace",
        ):
            return pa.string()
        if f == "case_when":
            # value branches: args 1, 3, ... and the trailing default
            vals = [
                a
                for i, a in enumerate(self._args)
                if i % 2 == 1 or i == len(self._args) - 1
            ]
            types = [a.infer_type(schema) for a in vals]
            types = [t for t in types if t is not None and not pa.types.is_null(t)]
            if not types:
                return None
            out = types[0]
            for t in types[1:]:
                if t == out:
                    continue
                p = _promote(out, t, "+")
                if p is None:
                    return None
                out = p
            return out
        return None

    def _uuid_keys(self) -> List[Any]:
        return [
            self._func,
            self._arg_distinct,
            self._is_agg,
            [a.__uuid__() for a in self._args],
        ]

    def __str__(self) -> str:
        distinct = "DISTINCT " if self._arg_distinct else ""
        body = f"{self._func.upper()}({distinct}{','.join(str(a) for a in self._args)})"
        if self._as_type is not None:
            from fugue_tpu.schema import type_to_expr

            body = f"CAST({body} AS {type_to_expr(self._as_type)})"
        if self._as_name != "":
            return f"{body} AS {self._as_name}"
        return body


def _promote(lt: pa.DataType, rt: pa.DataType, op: str) -> Optional[pa.DataType]:
    if op == "/":
        return pa.float64()
    if lt == rt:
        return lt
    numeric_rank = [pa.bool_(), pa.int8(), pa.int16(), pa.int32(), pa.int64(),
                    pa.float16(), pa.float32(), pa.float64()]
    if lt in numeric_rank and rt in numeric_rank:
        return numeric_rank[max(numeric_rank.index(lt), numeric_rank.index(rt))]
    if pa.types.is_string(lt) or pa.types.is_string(rt):
        return pa.string()
    return None


# ---- public constructors --------------------------------------------------
def col(obj: Union[str, ColumnExpr], alias: str = "") -> ColumnExpr:
    """Reference a column by name (``col("*")`` is the wildcard)."""
    if isinstance(obj, ColumnExpr):
        return obj.alias(alias) if alias != "" else obj
    if isinstance(obj, str):
        res: ColumnExpr = _NamedColumnExpr(obj)
        return res.alias(alias) if alias != "" else res
    raise ValueError(f"invalid column reference {obj!r}")


def lit(obj: Any, alias: str = "") -> ColumnExpr:
    res: ColumnExpr = _LitColumnExpr(obj)
    return res.alias(alias) if alias != "" else res


def null() -> ColumnExpr:
    return lit(None)


def all_cols() -> ColumnExpr:
    return col("*")


def function(name: str, *args: Any, arg_distinct: bool = False) -> ColumnExpr:
    """A generic (engine-interpreted) function call expression."""
    return _FuncExpr(name, *args, arg_distinct=arg_distinct)
