"""SelectColumns validation + SQL text generation from the column algebra
(reference fugue/column/sql.py:38,233)."""

from typing import Any, Callable, Iterable, List, Optional

import pyarrow as pa

from fugue_tpu.column.expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)
from fugue_tpu.column.functions import is_agg
from fugue_tpu.schema import Schema, type_to_expr
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.hash import to_uuid


class SelectColumns:
    """A validated projection list (possibly with aggregations)."""

    def __init__(self, *cols: ColumnExpr, arg_distinct: bool = False):
        self._cols = list(cols)
        self._distinct = arg_distinct
        assert_or_throw(len(self._cols) > 0, ValueError("empty select"))
        self._agg = [c for c in self._cols if is_agg(c)]
        self._non_agg = [c for c in self._cols if not is_agg(c)]
        if self.has_agg:
            assert_or_throw(
                not any(
                    isinstance(c, _NamedColumnExpr) and c.wildcard
                    for c in self._cols
                ),
                ValueError("wildcard can't be used with aggregations"),
            )

    @property
    def is_distinct(self) -> bool:
        return self._distinct

    def distinct(self) -> "SelectColumns":
        return SelectColumns(*self._cols, arg_distinct=True)

    @property
    def all_cols(self) -> List[ColumnExpr]:
        return self._cols

    @property
    def has_agg(self) -> bool:
        return len(self._agg) > 0

    @property
    def agg_funcs(self) -> List[ColumnExpr]:
        return self._agg

    @property
    def group_keys(self) -> List[ColumnExpr]:
        """Non-aggregation expressions = implicit GROUP BY keys."""
        return self._non_agg

    @property
    def simple(self) -> bool:
        """All plain column references (no computation)."""
        return all(
            isinstance(c, _NamedColumnExpr) and c.as_type is None for c in self._cols
        )

    def assert_all_with_names(self) -> "SelectColumns":
        names: List[str] = []
        for c in self._cols:
            if isinstance(c, _NamedColumnExpr) and c.wildcard and c.as_name == "":
                continue
            name = c.output_name
            assert_or_throw(name != "", ValueError(f"{c} has no output name"))
            names.append(name)
        assert_or_throw(
            len(set(names)) == len(names),
            ValueError(f"duplicated output names in {names}"),
        )
        return self

    def assert_no_wildcard(self) -> "SelectColumns":
        assert_or_throw(
            not any(
                isinstance(c, _NamedColumnExpr) and c.wildcard for c in self._cols
            ),
            ValueError("wildcard not allowed here"),
        )
        return self

    def assert_no_agg(self) -> "SelectColumns":
        assert_or_throw(not self.has_agg, ValueError("aggregation not allowed here"))
        return self

    def replace_wildcard(self, schema: Schema) -> "SelectColumns":
        cols: List[ColumnExpr] = []
        for c in self._cols:
            if isinstance(c, _NamedColumnExpr) and c.wildcard and c.as_name == "":
                explicit = set(
                    x.output_name for x in self._cols
                    if not (isinstance(x, _NamedColumnExpr) and x.wildcard)
                )
                for n in schema.names:
                    if n not in explicit:
                        cols.append(_NamedColumnExpr(n))
            else:
                cols.append(c)
        return SelectColumns(*cols, arg_distinct=self._distinct)

    def infer_schema(self, schema: Schema) -> Schema:
        resolved = self.replace_wildcard(schema).assert_all_with_names()
        return Schema([c.infer_schema_field(schema) for c in resolved.all_cols])

    def __uuid__(self) -> str:
        return to_uuid([c.__uuid__() for c in self._cols], self._distinct)


class SQLExpressionGenerator:
    """Render expressions / SELECT statements as SQL text for engines with a
    SQL surface. ``enable_cast=False`` lets engines that handle typing
    themselves skip CAST generation."""

    def __init__(self, enable_cast: bool = True):
        self._enable_cast = enable_cast
        self._func_handlers: dict = {}

    def add_func_handler(
        self, name: str, handler: Callable[["_FuncExpr"], str]
    ) -> "SQLExpressionGenerator":
        self._func_handlers[name.lower()] = handler
        return self

    def generate(self, expr: ColumnExpr) -> str:
        """Expression (without alias) to SQL text."""
        return self._gen(expr, with_alias=False)

    def generate_select_expr(self, expr: ColumnExpr) -> str:
        return self._gen(expr, with_alias=True)

    def select(
        self,
        columns: SelectColumns,
        table: str,
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
    ) -> str:
        columns.assert_all_with_names()
        distinct = "DISTINCT " if columns.is_distinct else ""
        proj = ", ".join(self.generate_select_expr(c) for c in columns.all_cols)
        sql = f"SELECT {distinct}{proj} FROM {table}"
        if where is not None:
            sql += f" WHERE {self.generate(where)}"
        if columns.has_agg and len(columns.group_keys) > 0:
            keys = ", ".join(self._gen(k, with_alias=False) for k in columns.group_keys)
            sql += f" GROUP BY {keys}"
        if having is not None:
            assert_or_throw(
                columns.has_agg, ValueError("HAVING requires aggregation")
            )
            sql += f" HAVING {self.generate(having)}"
        return sql

    def where(self, condition: ColumnExpr, table: str) -> str:
        assert_or_throw(
            not is_agg(condition), ValueError("WHERE can't contain aggregation")
        )
        return f"SELECT * FROM {table} WHERE {self.generate(condition)}"

    def _gen(self, expr: ColumnExpr, with_alias: bool) -> str:
        body = self._gen_body(expr)
        if self._enable_cast and expr.as_type is not None:
            body = f"CAST({body} AS {self.type_to_sql(expr.as_type)})"
        if with_alias and expr.as_name != "":
            body = f"{body} AS {expr.as_name}"
        elif with_alias and expr.name == "" and expr.output_name == "":
            pass
        return body

    def _gen_body(self, expr: ColumnExpr) -> str:
        if isinstance(expr, _NamedColumnExpr):
            return expr.name
        if isinstance(expr, _LitColumnExpr):
            v = expr.value
            if v is None:
                return "NULL"
            if isinstance(v, bool):
                return "TRUE" if v else "FALSE"
            if isinstance(v, str):
                return "'" + v.replace("'", "''") + "'"
            return str(v)
        if isinstance(expr, _UnaryOpExpr):
            inner = self._gen(expr.col, with_alias=False)
            if expr.op == "IS_NULL":
                return f"({inner} IS NULL)"
            if expr.op == "NOT_NULL":
                return f"({inner} IS NOT NULL)"
            if expr.op == "~":
                return f"(NOT {inner})"
            return f"({expr.op}{inner})"
        if isinstance(expr, _BinaryOpExpr):
            op = {"==": "=", "&": "AND", "|": "OR"}.get(expr.op, expr.op)
            left = self._gen(expr.left, with_alias=False)
            right = self._gen(expr.right, with_alias=False)
            # SQL null-safe: = NULL must become IS NULL
            if isinstance(expr.right, _LitColumnExpr) and expr.right.value is None:
                if expr.op == "==":
                    return f"({left} IS NULL)"
                if expr.op == "!=":
                    return f"({left} IS NOT NULL)"
            return f"({left} {op} {right})"
        if isinstance(expr, _FuncExpr):
            handler = self._func_handlers.get(expr.func.lower())
            if handler is not None:
                return handler(expr)
            distinct = "DISTINCT " if expr.arg_distinct else ""
            args = ", ".join(self._gen(a, with_alias=False) for a in expr.args)
            return f"{expr.func.upper()}({distinct}{args})"
        raise NotImplementedError(f"can't generate SQL for {expr}")

    def type_to_sql(self, tp: pa.DataType) -> str:
        if pa.types.is_int64(tp):
            return "BIGINT"
        if pa.types.is_int32(tp):
            return "INT"
        if pa.types.is_int16(tp):
            return "SMALLINT"
        if pa.types.is_int8(tp):
            return "TINYINT"
        if pa.types.is_float64(tp):
            return "DOUBLE"
        if pa.types.is_float32(tp):
            return "FLOAT"
        if pa.types.is_string(tp):
            return "VARCHAR"
        if pa.types.is_boolean(tp):
            return "BOOLEAN"
        if pa.types.is_timestamp(tp):
            return "TIMESTAMP"
        if pa.types.is_date(tp):
            return "DATE"
        if pa.types.is_binary(tp):
            return "BINARY"
        if pa.types.is_decimal(tp):
            return f"DECIMAL({tp.precision},{tp.scale})"
        return type_to_expr(tp).upper()
