from fugue_tpu.column.expressions import (
    ColumnExpr,
    all_cols,
    col,
    function,
    lit,
    null,
)
from fugue_tpu.column.functions import (
    avg,
    coalesce,
    count,
    count_distinct,
    first,
    is_agg,
    last,
    max,  # noqa: A004
    mean,
    min,  # noqa: A004
    sum,  # noqa: A004
)
from fugue_tpu.column.sql import SelectColumns, SQLExpressionGenerator
