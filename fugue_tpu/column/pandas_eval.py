"""Evaluate the column algebra directly on pandas — the native engine's
compute path for select/filter/assign/aggregate (replaces the reference's
qpd-SQL-on-pandas dependency with a direct expression interpreter; SQL
semantics: Kleene logic via pandas nullable booleans, nulls ignored by aggs).
"""

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from fugue_tpu.column.expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
)
from fugue_tpu.column.functions import (
    VARIANCE_FUNCS,
    is_agg,
    variance_ddof,
    variance_stat,
)
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


def sql_fmod(a: pd.Series, b: pd.Series) -> pd.Series:
    """SQL modulo: truncated (sign of dividend, MOD(-7, 3) = -1), NULL on
    a zero divisor, with numpy's out-of-domain chatter suppressed. Shared
    by every host evaluator so the semantics cannot drift apart."""
    with np.errstate(invalid="ignore", divide="ignore"):
        return np.fmod(a, b).where(b != 0)


def eval_expr(df: pd.DataFrame, expr: ColumnExpr) -> pd.Series:
    """Evaluate a non-aggregation expression to a Series aligned with df."""
    s = _eval(df, expr)
    if expr.as_type is not None:
        s = _cast_series(s, expr.as_type)
    return s


def _bool_series(s: pd.Series) -> pd.Series:
    """To pandas nullable boolean (Kleene logic for &/|)."""
    if s.dtype == "boolean":
        return s
    return s.astype("boolean")


def _eval(df: pd.DataFrame, expr: ColumnExpr) -> pd.Series:
    if isinstance(expr, _NamedColumnExpr):
        assert_or_throw(not expr.wildcard, ValueError("can't evaluate wildcard"))
        return df[expr.name]
    if isinstance(expr, _LitColumnExpr):
        v = expr.value
        return pd.Series([v] * len(df), index=df.index)
    if isinstance(expr, _UnaryOpExpr):
        inner = _eval(df, expr.col)
        if expr.op == "IS_NULL":
            return inner.isna().astype("boolean")
        if expr.op == "NOT_NULL":
            return (~inner.isna()).astype("boolean")
        if expr.op == "-":
            return -inner
        if expr.op == "~":
            return ~_bool_series(inner)
        raise NotImplementedError(f"unary op {expr.op}")
    if isinstance(expr, _BinaryOpExpr):
        left = _eval(df, expr.left)
        right = _eval(df, expr.right)
        op = expr.op
        if op in ("&", "|"):
            lb, rb = _bool_series(left), _bool_series(right)
            return lb & rb if op == "&" else lb | rb
        if op in ("==", "!=", "<", "<=", ">", ">="):
            # SQL: comparison with NULL yields NULL
            nulls = left.isna() | right.isna()
            func = {
                "==": lambda a, b: a == b,
                "!=": lambda a, b: a != b,
                "<": lambda a, b: a < b,
                "<=": lambda a, b: a <= b,
                ">": lambda a, b: a > b,
                ">=": lambda a, b: a >= b,
            }[op]
            with np.errstate(invalid="ignore"):
                res = func(left, right)
            res = res.astype("boolean")
            res[nulls] = pd.NA
            return res
        if op == "+":
            return left + right
        if op == "-":
            return left - right
        if op == "*":
            return left * right
        if op == "/":
            return left.astype("float64") / right
        raise NotImplementedError(f"binary op {op}")
    if isinstance(expr, _FuncExpr) and not expr.is_aggregation:
        f = expr.func.lower()
        if f == "coalesce":
            args = [_eval(df, a) for a in expr.args]
            res = args[0]
            for a in args[1:]:
                res = res.combine_first(a)
            return res
        if f == "like":
            operand = _eval(df, expr.args[0])
            pattern = expr.args[1]
            negated = expr.args[2]
            assert_or_throw(
                isinstance(negated, _LitColumnExpr),
                ValueError("LIKE negation must be a literal"),
            )
            if isinstance(pattern, _LitColumnExpr) and isinstance(
                pattern.value, str
            ):
                rx = compile_like_regex(pattern.value)
                res = operand.astype("string").str.fullmatch(rx).astype(
                    "boolean"
                )
                if negated.value:
                    res = ~res
                res[operand.isna()] = pd.NA  # NULL LIKE anything -> NULL
                return res
            # dynamic pattern: compile per DISTINCT pattern value;
            # NULL on either side -> NULL
            p = _eval(df, pattern)
            cache: Dict[Any, Any] = {}
            vals: List[Any] = []
            for v, pv in zip(operand, p):
                if pd.isna(v) or pd.isna(pv):
                    vals.append(None)
                    continue
                crx = cache.get(pv)
                if crx is None:
                    crx = compile_like_regex(str(pv))
                    cache[pv] = crx
                vals.append(crx.fullmatch(str(v)) is not None)
            res = pd.Series(vals, index=df.index, dtype=object).astype(
                "boolean"
            )
            return ~res if negated.value else res
        if f == "case_when":
            # cond/value pairs + default; NULL conditions don't match —
            # fill NA up front so one NULL condition can't poison the
            # matched accumulator for later branches (review finding)
            default = _eval(df, expr.args[-1])
            res = default.copy()
            matched = pd.Series(False, index=df.index)
            for i in range(0, len(expr.args) - 1, 2):
                cond = (
                    _bool_series(_eval(df, expr.args[i]))
                    .fillna(False)
                    .astype(bool)
                )
                val = _eval(df, expr.args[i + 1])
                take = cond & ~matched
                if take.any():
                    res = val.where(take, res)
                matched = matched | cond
            return res
        if f in _NUM_UNARY:
            s = pd.to_numeric(_eval(df, expr.args[0]), errors="coerce")
            # out-of-domain inputs (SQRT(-4), LN(0)) yield NaN by SQL
            # intent, not as a numpy anomaly — keep -W error runs clean
            with np.errstate(invalid="ignore", divide="ignore"):
                res = _NUM_UNARY[f](s)
            return pd.Series(res, index=df.index)
        if f == "round":
            s = pd.to_numeric(_eval(df, expr.args[0]), errors="coerce")
            digits = _scalar_arg(df, expr.args, 1, 0)
            return s.round(int(digits))
        if f in ("power", "pow"):
            a = pd.to_numeric(_eval(df, expr.args[0]), errors="coerce")
            b = pd.to_numeric(_eval(df, expr.args[1]), errors="coerce")
            return a**b
        if f == "mod":
            a = pd.to_numeric(_eval(df, expr.args[0]), errors="coerce")
            b = pd.to_numeric(_eval(df, expr.args[1]), errors="coerce")
            return sql_fmod(a, b)
        if f == "nullif":
            a = _eval(df, expr.args[0])
            b = _eval(df, expr.args[1])
            eq = pd.Series(False, index=df.index)
            with np.errstate(invalid="ignore"):
                eq = (a == b) & a.notna() & b.notna()
            return a.astype(object).where(~eq, None)
        if f in ("if", "iif"):
            cond = _bool_series(_eval(df, expr.args[0])).fillna(False)
            yes = _eval(df, expr.args[1])
            no = _eval(df, expr.args[2])
            return yes.astype(object).where(
                cond.astype(bool), no.astype(object)
            )
        if f in _STR_UNARY:
            s = _eval(df, expr.args[0])
            nulls = s.isna()
            res = _STR_UNARY[f](s.astype(object).astype(str)).astype(object)
            res[nulls.to_numpy(dtype=bool)] = None
            return res
        if f in ("length", "len"):
            s = _eval(df, expr.args[0])
            res = s.astype(object).astype(str).str.len().astype(object)
            res[s.isna().to_numpy(dtype=bool)] = None
            return res
        if f in ("substring", "substr"):
            s = _eval(df, expr.args[0])
            starts = pd.to_numeric(_eval(df, expr.args[1]), errors="coerce")
            lens = (
                pd.to_numeric(_eval(df, expr.args[2]), errors="coerce")
                if len(expr.args) > 2
                else None
            )
            return sql_substring(s, starts, lens)
        if f == "concat":
            res: Optional[pd.Series] = None
            nulls: Optional[pd.Series] = None
            for a in expr.args:
                s = _eval(df, a)
                nulls = s.isna() if nulls is None else (nulls | s.isna())
                part = s.astype(object).astype(str)
                res = part if res is None else res + part
            assert res is not None and nulls is not None
            res = res.astype(object)
            res[nulls.to_numpy(dtype=bool)] = None
            return res
        if f == "replace":
            s = _eval(df, expr.args[0])
            nulls = s.isna()
            old = str(_scalar_arg(df, expr.args, 1, ""))
            new = str(_scalar_arg(df, expr.args, 2, ""))
            res = s.astype(object).astype(str).str.replace(
                old, new, regex=False
            ).astype(object)
            res[nulls.to_numpy(dtype=bool)] = None
            return res
        raise NotImplementedError(f"function {expr.func} not supported on pandas")
    raise NotImplementedError(f"can't evaluate {expr}")


_NUM_UNARY: Dict[str, Any] = {
    "abs": lambda s: s.abs(),
    "floor": np.floor,
    "ceil": np.ceil,
    "ceiling": np.ceil,
    "sqrt": np.sqrt,
    "exp": np.exp,
    "ln": np.log,
    "log": np.log,
    "log2": np.log2,
    "log10": np.log10,
    "sin": np.sin,
    "cos": np.cos,
    "tan": np.tan,
    "sign": np.sign,
}

_STR_UNARY: Dict[str, Any] = {
    "upper": lambda s: s.str.upper(),
    "ucase": lambda s: s.str.upper(),
    "lower": lambda s: s.str.lower(),
    "lcase": lambda s: s.str.lower(),
    "trim": lambda s: s.str.strip(),
    "ltrim": lambda s: s.str.lstrip(),
    "rtrim": lambda s: s.str.rstrip(),
    "reverse": lambda s: s.str[::-1],
}


def _scalar_arg(df: pd.DataFrame, args: List[Any], i: int, default: Any) -> Any:
    """A scalar parameter (round digits, substring bounds, ...): the
    first value of the evaluated argument — same convention as the SQL
    runner's scalar functions."""
    if i >= len(args):
        return default
    s = _eval(df, args[i])
    return s.iloc[0] if len(s) else default


def sql_substring(
    s: pd.Series,
    starts: pd.Series,
    lens: Optional[pd.Series],
) -> pd.Series:
    """SQL SUBSTRING over object-typed strings: per-row 1-based start and
    optional length, NULL operand/start/length -> NULL. Shared by the SQL
    runner and the column-algebra evaluator so the two host paths cannot
    diverge. Constant parameters (the common, literal case) take the
    vectorized ``str.slice`` path."""
    nulls = s.isna() | starts.isna()
    if lens is not None:
        nulls = nulls | lens.isna()
    nl = nulls.to_numpy(dtype=bool)
    sv = s.astype(object).astype(str)
    su = starts[~nulls].unique()
    lu = None if lens is None else lens[~nulls].unique()
    if len(su) <= 1 and (lu is None or len(lu) <= 1):
        st0 = max(int(su[0]) - 1, 0) if len(su) else 0
        if lens is not None:
            n = int(lu[0]) if lu is not None and len(lu) else 0
            res = sv.str.slice(st0, st0 + n)
        else:
            res = sv.str.slice(st0)
        res = res.astype(object)
        res[nl] = None
        return res
    out: List[Any] = []
    for i in range(len(sv)):
        if nl[i]:
            out.append(None)
            continue
        x = sv.iloc[i]
        st0 = max(int(starts.iloc[i]) - 1, 0)
        if lens is not None:
            out.append(x[st0:st0 + int(lens.iloc[i])])
        else:
            out.append(x[st0:])
    res = pd.Series(out, index=s.index, dtype=object)
    res[nl] = None
    return res


def like_pattern_to_regex(pattern: str) -> str:
    """SQL LIKE pattern -> an equivalent regex (``%`` -> ``.*``,
    ``_`` -> ``.``, everything else literal). Unanchored — use
    :func:`compile_like_regex` for matching."""
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)


def compile_like_regex(pattern: str) -> "re.Pattern":
    r"""THE compiled regex every LIKE evaluator (host select runner,
    device dictionary LUTs, pandas column algebra) matches with. Anchored
    with ``\A...\Z`` — ``$`` would also match just before a trailing
    newline, so the three evaluators could diverge on values like
    ``"red\n"`` (ADVICE r5 #3). DOTALL because SQL's ``%``/``_`` match
    any character INCLUDING newlines (``'a\nb' LIKE 'a%'`` is TRUE)."""
    return re.compile(
        r"\A" + like_pattern_to_regex(pattern) + r"\Z", re.DOTALL
    )


def _cast_series(s: pd.Series, tp: pa.DataType) -> pd.Series:
    from fugue_tpu.dataframe.arrow_utils import cast_table

    arr = pa.Array.from_pandas(s)
    table = pa.Table.from_arrays([arr], names=["_c"])
    out = cast_table(table, Schema([pa.field("_c", tp)]))
    return out.column(0).to_pandas()


def eval_filter(df: pd.DataFrame, condition: ColumnExpr) -> pd.DataFrame:
    assert_or_throw(not is_agg(condition), ValueError("WHERE can't aggregate"))
    if len(df) == 0:
        return df
    mask = _bool_series(eval_expr(df, condition)).fillna(False).astype(bool)
    return df[mask.to_numpy()]


def eval_assign(df: pd.DataFrame, **columns: ColumnExpr) -> pd.DataFrame:
    out = df.copy(deep=False)
    for name, expr in columns.items():
        assert_or_throw(not is_agg(expr), ValueError("assign can't aggregate"))
        out[name] = eval_expr(df, expr) if len(df) > 0 else \
            _empty_typed_series(expr, df)
    return out

def _empty_typed_series(expr: ColumnExpr, df: pd.DataFrame) -> pd.Series:
    return pd.Series([], dtype=object)



def _apply_agg(
    grouped: Any, func: str, col: str, distinct: bool
) -> pd.Series:
    f = func.lower()
    if f == "count":
        if distinct:
            return grouped[col].nunique(dropna=True)
        return grouped[col].count()
    if f in ("avg", "mean"):
        if distinct:
            return grouped[col].agg(lambda s: s.drop_duplicates().mean())
        return grouped[col].mean()
    if f == "sum":
        if distinct:
            return grouped[col].agg(
                lambda s: s.drop_duplicates().sum(min_count=1)
            )
        return grouped[col].sum(min_count=1)  # all-null -> NULL like SQL
    if f == "min":
        return grouped[col].min()
    if f == "max":
        return grouped[col].max()
    if f in VARIANCE_FUNCS:
        ddof, fn2 = variance_ddof(f), variance_stat(f)
        if distinct:
            return grouped[col].agg(
                lambda s: getattr(s.drop_duplicates(), fn2)(ddof=ddof)
            )
        return getattr(grouped[col], fn2)(ddof=ddof)
    if f == "median":
        if distinct:
            return grouped[col].agg(lambda s: s.drop_duplicates().median())
        return grouped[col].median()
    if f == "first":
        # .first() would skip nulls; we want the literal first row value
        return grouped[col].agg(lambda s: s.iloc[0] if len(s) > 0 else None)
    if f == "last":
        return grouped[col].agg(lambda s: s.iloc[-1] if len(s) > 0 else None)
    raise NotImplementedError(f"aggregation {func} not supported")


def _global_agg(df: pd.DataFrame, func: str, col: str, distinct: bool) -> Any:
    f = func.lower()
    s = df[col]
    if f == "count":
        return s.nunique(dropna=True) if distinct else s.count()
    if f in ("avg", "mean"):
        return s.drop_duplicates().mean() if distinct else s.mean()
    if f == "sum":
        if distinct:
            return s.drop_duplicates().sum(min_count=1)
        return s.sum(min_count=1)
    if f == "min":
        return s.min()
    if f == "max":
        return s.max()
    if f in VARIANCE_FUNCS:
        vals = s.drop_duplicates() if distinct else s
        return getattr(vals, variance_stat(f))(ddof=variance_ddof(f))
    if f == "median":
        vals = s.drop_duplicates() if distinct else s
        return vals.median()
    if f == "first":
        return s.iloc[0] if len(s) > 0 else None
    if f == "last":
        return s.iloc[-1] if len(s) > 0 else None
    raise NotImplementedError(f"aggregation {func} not supported")


def eval_aggregate(
    df: pd.DataFrame,
    group_names: List[str],
    aggs: Dict[str, ColumnExpr],
) -> pd.DataFrame:
    """Group by ``group_names`` (empty = global) and compute named
    aggregations. Each agg expression must be a single aggregation function
    whose argument is any non-agg expression."""
    work = df.copy(deep=False)
    plans: List[Tuple[str, str, str, bool]] = []  # (out_name, func, tmp_col, distinct)
    for i, (out_name, expr) in enumerate(aggs.items()):
        assert_or_throw(
            isinstance(expr, _FuncExpr) and expr.is_aggregation and len(expr.args) == 1,
            ValueError(f"{expr} is not a simple aggregation"),
        )
        arg = expr.args[0]
        tmp = f"_agg_arg_{i}"
        if isinstance(arg, _NamedColumnExpr) and arg.wildcard:
            # count(*): count rows — use a constant column
            work[tmp] = 1
        else:
            work[tmp] = eval_expr(df, arg) if len(df) > 0 else None
        plans.append((out_name, expr.func, tmp, expr.arg_distinct))
    if len(group_names) == 0:
        data = {
            out: [_global_agg(work, func, tmp, distinct)]
            for out, func, tmp, distinct in plans
        }
        return pd.DataFrame(data)
    grouped = work.groupby(group_names, dropna=False, sort=False)
    pieces = {
        out: _apply_agg(grouped, func, tmp, distinct)
        for out, func, tmp, distinct in plans
    }
    res = pd.DataFrame(pieces)
    return res.reset_index()


def _rewrite_having(
    expr: ColumnExpr,
    computed: Dict[str, str],
    extra: Dict[str, ColumnExpr],
) -> ColumnExpr:
    """Replace aggregation subtrees with references to aggregated columns."""
    from fugue_tpu.column.expressions import col as _col

    if isinstance(expr, _FuncExpr) and expr.is_aggregation:
        key = expr.alias("").__uuid__()
        if key in computed:
            return _col(computed[key])
        name = f"_having_{len(extra)}"
        extra[name] = expr.alias(name)
        computed[key] = name
        return _col(name)
    if isinstance(expr, _BinaryOpExpr):
        return _BinaryOpExpr(
            expr.op,
            _rewrite_having(expr.left, computed, extra),
            _rewrite_having(expr.right, computed, extra),
        )
    if isinstance(expr, _UnaryOpExpr):
        return _UnaryOpExpr(expr.op, _rewrite_having(expr.col, computed, extra))
    return expr


def eval_select(
    df: pd.DataFrame,
    columns: SelectColumns,
    where: Optional[ColumnExpr] = None,
    having: Optional[ColumnExpr] = None,
) -> pd.DataFrame:
    """Full SELECT semantics on pandas: WHERE -> projection/aggregation ->
    HAVING -> DISTINCT."""
    # wildcard expansion only needs column NAMES; declare string to avoid an
    # O(rows*cols) arrow conversion here
    cols = columns.replace_wildcard(
        Schema([pa.field(str(c), pa.string()) for c in df.columns])
    ).assert_all_with_names()
    if where is not None:
        df = eval_filter(df, where)
    if not cols.has_agg:
        out = pd.DataFrame(
            {
                c.output_name: (eval_expr(df, c) if len(df) > 0 else
                                pd.Series([], dtype=object))
                for c in cols.all_cols
            }
        )
        if cols.is_distinct:
            out = out.drop_duplicates()
        return out.reset_index(drop=True)
    # aggregation path: group keys are the non-agg output columns.
    # Computed keys materialize under TEMP names so an alias shadowing a
    # source column (SELECT x % 10 AS x, SUM(x) ...) cannot corrupt the
    # aggregate arguments (review-adjacent finding)
    key_names: List[str] = []
    key_rename: Dict[str, str] = {}
    work = df.copy(deep=False)
    for i, k in enumerate(cols.group_keys):
        name = k.output_name
        if (
            isinstance(k, _NamedColumnExpr)
            and k.as_type is None
            and k.name == name
            and name in work.columns
        ):
            key_names.append(name)  # plain passthrough key
            continue
        tmp = f"_gk_{i}"
        while tmp in work.columns:  # never clobber a real input column
            tmp += "_"
        work[tmp] = eval_expr(df, k) if len(df) > 0 else None
        key_rename[tmp] = name
        key_names.append(tmp)
    aggs = {c.output_name: c for c in cols.agg_funcs}
    having_rewritten: Optional[ColumnExpr] = None
    if having is not None:
        # HAVING refers to aggregations: rewrite agg subtrees into column refs
        # over the aggregated output, computing hidden agg columns as needed
        # key by alias-stripped uuid so HAVING's bare agg nodes match
        computed = {c.alias("").__uuid__(): c.output_name for c in cols.agg_funcs}
        extra: Dict[str, ColumnExpr] = {}
        having_rewritten = _rewrite_having(having, computed, extra)
        aggs = dict(aggs, **extra)
    res = eval_aggregate(work, key_names, aggs)
    if key_rename:
        res = res.rename(columns=key_rename)
    if having_rewritten is not None:
        res = eval_filter(res, having_rewritten)
    # order columns as requested
    res = res[[c.output_name for c in cols.all_cols]]
    if cols.is_distinct:
        res = res.drop_duplicates()
    return res.reset_index(drop=True)
