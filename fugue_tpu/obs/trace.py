"""Request-scoped span tracing (Dapper shape: ``trace_id`` / ``span_id``
/ parent links) with thread-local context propagation.

The contract that keeps instrumentation free when observability is off:

- A span is only ever recorded under an ACTIVE trace. The thread-local
  context holds the current :class:`Span`; :func:`start_span` with no
  current span returns the shared :data:`NULL_CM` singleton — one
  function call, one thread-local read, **zero allocation** — so the
  hundreds of instrumentation sites across the engine cost nothing on
  workloads that never opened a trace.
- Traces are OPENED only at the two entry points that own a request's
  lifecycle: the serving daemon (per HTTP request, trace id =
  ``X-Request-Id``) and ``FugueWorkflow.run`` (embedded use, when no
  ambient trace is already active). Everything below them just calls
  :func:`start_span`.
- Crossing threads is explicit: the DAG runner captures the caller's
  current span at ``run()`` and re-attaches it inside each worker via
  :func:`activate`, so task/attempt/engine spans land in the right tree
  no matter which pool thread executes them.

Spans carry ``time.time_ns`` wall-clock bounds (exported as Chrome
trace-event microseconds) plus the executing thread id, so a Perfetto
load shows queue/compile/execute/transfer lanes per thread.
"""

import itertools
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from fugue_tpu.testing.locktrace import tracked_lock

_TLS = threading.local()


def current_span() -> Optional["Span"]:
    """This thread's active span (None = no trace → no-op sites)."""
    return getattr(_TLS, "span", None)


class Span:
    __slots__ = (
        "trace",
        "name",
        "span_id",
        "parent_id",
        "start_ns",
        "end_ns",
        "thread_id",
        "attrs",
    )

    def __init__(
        self,
        trace: "Trace",
        name: str,
        span_id: int,
        parent_id: Optional[int],
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.trace = trace
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = time.time_ns()
        self.end_ns: Optional[int] = None
        self.thread_id = threading.get_ident()
        self.attrs: Dict[str, Any] = attrs or {}

    def set_attr(self, **kv: Any) -> None:
        self.attrs.update(kv)

    def finish(self) -> None:
        """Idempotent end; the trace's open-span count drops on the
        first call only."""
        if self.end_ns is None:
            self.end_ns = time.time_ns()
            self.trace._note_end()

    @property
    def duration_ms(self) -> float:
        end = self.end_ns if self.end_ns is not None else time.time_ns()
        return (end - self.start_ns) / 1e6

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"Span({self.name}, id={self.span_id}, "
            f"parent={self.parent_id}, {self.duration_ms:.3f}ms)"
        )


class _NullSpan:
    """The span-shaped no-op sites receive when tracing is off: every
    method swallows its arguments; truthiness is False so guards can
    branch on a real span cheaply."""

    __slots__ = ()

    def set_attr(self, **kv: Any) -> None:
        pass

    def finish(self) -> None:
        pass

    def __bool__(self) -> bool:
        return False


NULL_SPAN = _NullSpan()


class _NullCM:
    """Allocation-free ``with`` target for obs-off instrumentation."""

    __slots__ = ()

    def __enter__(self) -> _NullSpan:
        return NULL_SPAN

    def __exit__(self, *args: Any) -> bool:
        return False


NULL_CM = _NullCM()


class Trace:
    """One request's span collection. Spans register at START (so a
    crashed run still exports what it saw); ``complete`` flips when the
    root ended and no span remains open — the exporter's trigger when
    two threads (HTTP handler, job worker) race to finish last."""

    __slots__ = (
        "trace_id",
        "spans",
        "root_span",
        "_lock",
        "_ids",
        "_open",
        "_exported",
    )

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.spans: List[Span] = []
        self.root_span: Optional[Span] = None
        self._lock = tracked_lock("obs.trace.Trace._lock")
        self._ids = itertools.count(1)
        self._open = 0
        self._exported = False

    def start_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Span:
        span = Span(
            self,
            name,
            next(self._ids),
            parent.span_id if parent is not None else None,
            attrs,
        )
        with self._lock:
            self.spans.append(span)
            self._open += 1
            if self.root_span is None:
                self.root_span = span
        return span

    def root(self, name: str, **attrs: Any) -> Span:
        return self.start_span(name, None, attrs)

    def _note_end(self) -> None:
        with self._lock:
            self._open -= 1

    @property
    def complete(self) -> bool:
        with self._lock:
            return (
                self.root_span is not None
                and self.root_span.end_ns is not None
                and self._open <= 0
            )

    def mark_exported(self) -> bool:
        """True exactly once — the exporter's claim when multiple
        threads observe completion concurrently."""
        with self._lock:
            if self._exported:
                return False
            self._exported = True
            return True

    def find(self, name: str) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.name == name]

    def children_of(self, span: Span) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.parent_id == span.span_id]


class _SpanCM:
    """``with start_span("x") as sp:`` — pushes the child as the
    thread's current span, restores the parent on exit, marks the span
    errored when the body raises."""

    __slots__ = ("_parent", "_name", "_attrs", "_span")

    def __init__(self, parent: Span, name: str, attrs: Dict[str, Any]):
        self._parent = parent
        self._name = name
        self._attrs = attrs
        self._span: Optional[Span] = None

    def __enter__(self) -> Span:
        self._span = self._parent.trace.start_span(
            self._name, self._parent, self._attrs or None
        )
        _TLS.span = self._span
        return self._span

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        span = self._span
        if span is not None:
            if exc_type is not None:
                span.attrs.setdefault("error", exc_type.__name__)
            span.finish()
        _TLS.span = self._parent
        return False


class _ActivateCM:
    """Attach an EXISTING span as this thread's current context (cross-
    thread propagation); restores whatever was current before."""

    __slots__ = ("_span", "_prev")

    def __init__(self, span: Span):
        self._span = span
        self._prev: Optional[Span] = None

    def __enter__(self) -> Span:
        self._prev = current_span()
        _TLS.span = self._span
        return self._span

    def __exit__(self, *args: Any) -> bool:
        _TLS.span = self._prev
        return False


def start_span(name: str, **attrs: Any) -> Any:
    """Context manager for one child span of the thread's current span.
    No active trace → the shared no-op singleton (nothing allocated)."""
    cur = getattr(_TLS, "span", None)
    if cur is None:
        return NULL_CM
    return _SpanCM(cur, name, attrs)


def begin_span(name: str, **attrs: Any) -> Any:
    """Manual (non-context-manager) child span for windows whose start
    and end live in different functions (the memory gate's
    ``before()``/``after()``); caller owns ``finish()``. The span is NOT
    pushed as the thread's current context. Returns :data:`NULL_SPAN`
    when no trace is active."""
    cur = getattr(_TLS, "span", None)
    if cur is None:
        return NULL_SPAN
    return cur.trace.start_span(name, cur, attrs or None)


def activate(span: Optional[Span]) -> Any:
    """Context manager attaching ``span`` to this thread; ``None`` (the
    obs-off carry) is the shared no-op."""
    if span is None or isinstance(span, _NullSpan):
        return NULL_CM
    return _ActivateCM(span)


class _SuppressCM:
    """Marks this thread as sampled-OUT: trace owners downstream
    (``FugueWorkflow.run``) must not open a trace of their own."""

    __slots__ = ("_prev",)

    def __enter__(self) -> None:
        self._prev = getattr(_TLS, "suppress", False)
        _TLS.suppress = True
        return None

    def __exit__(self, *args: Any) -> bool:
        _TLS.suppress = self._prev
        return False


def suppress_tracing() -> Any:
    """Scope in which downstream trace OWNERS stay quiet. The serving
    daemon wraps a job whose request lost the sampling draw in this, so
    the workflow layer does not re-enter sampling and export an
    uncorrelated trace at ~double the configured rate."""
    return _SuppressCM()


def tracing_suppressed() -> bool:
    return getattr(_TLS, "suppress", False)
