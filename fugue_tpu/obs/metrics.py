"""The ONE metrics registry: counters, gauges and histograms with label
sets, Prometheus text exposition, and a plain-dict ``snapshot()`` for
embedded use.

Before this module every subsystem counted into its own ad-hoc dict
(``engine.fallbacks``, serve ``backpressure.rejections``, ``RunStats``,
breaker describes). Those dicts remain the *public read shapes* — their
owners now keep them as thin views over metric families registered here,
so one Prometheus scrape covers everything and the back-compat accessors
stay byte-identical.

Design notes:

- A :class:`MetricsRegistry` is an ordinary object, not a process
  global: each engine owns one (``engine.metrics``), so two engines in
  one process (tests, benches) never share counters. The serving daemon
  exposes its engine's registry at ``GET /v1/metrics``.
- Families are created idempotently (``registry.counter(name, ...)``
  returns the existing family on repeat) so independent modules can
  attach to the same family without import-order coupling.
- Children (one per label-value tuple) are cached on the family;
  callers on hot paths should pre-resolve children once
  (``family.labels(op="x")``) and call ``inc()`` on the child — the
  cost is then one lock + add, the same as the dict increments these
  replace.
- ``collectors`` are callables run right before ``snapshot()`` /
  ``render()``: pull-model metrics (breaker states, queue depth,
  memory pressure, uptime) are computed at scrape time instead of being
  pushed on every mutation.
"""

import math
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from fugue_tpu.testing.locktrace import tracked_lock

# the metric-NAME vocabulary: every family registered with a literal
# name must fall under one of these component prefixes. The source
# linter's FLN107 enforces it statically (a free-form name would fork
# the dashboard namespace silently); new subsystems extend the tuple in
# the same PR that introduces their metrics.
METRIC_NAME_PREFIXES = (
    "fugue_engine_",
    "fugue_serve_",
    "fugue_fleet_",
    "fugue_autoscale_",
    "fugue_obs_",
    "fugue_stats_",
    "fugue_stream_",
    "fugue_workflow_",
    "fugue_shuffle_",
    "fugue_lake_",
)

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# latency-oriented default buckets (seconds), Prometheus-style
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class _Child:
    """One (family, label-values) time series."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Child):
    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n


class Gauge(_Child):
    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n


class Histogram:
    """Cumulative-bucket histogram child (Prometheus semantics: the
    rendered ``le`` buckets are cumulative, ``+Inf`` == ``_count``)."""

    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * len(buckets)  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    break

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            cum: List[int] = []
            acc = 0
            for c in self.counts:
                acc += c
                cum.append(acc)
            return {
                "buckets": dict(zip(self.buckets, cum)),
                "sum": self.sum,
                "count": self.count,
            }


class MetricFamily:
    """A named metric with a fixed label-name tuple and one child per
    label-value combination."""

    def __init__(
        self,
        name: str,
        kind: str,
        help: str,
        labelnames: Tuple[str, ...],
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = labelnames
        self._buckets = buckets
        self._lock = tracked_lock("obs.metrics.MetricFamily._lock")
        self._children: Dict[Tuple[str, ...], Any] = {}

    def _make_child(self) -> Any:
        if self.kind == COUNTER:
            return Counter()
        if self.kind == GAUGE:
            return Gauge()
        return Histogram(self._buckets)

    def labels(self, **kv: Any) -> Any:
        """The child for one label-value set (created on first use).
        With no labels declared, ``labels()`` is the single child."""
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got "
                f"{tuple(kv)}"
            )
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def clear(self) -> None:
        """Drop every child — the reset idiom of the ad-hoc dicts this
        registry replaced (``engine.reset_fallbacks``)."""
        with self._lock:
            self._children.clear()

    def children(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return list(self._children.items())

    def as_dict(self) -> Dict[Any, float]:
        """Back-compat view: single-label families map label value ->
        value; label-free families map ``""`` -> value; multi-label
        families map the label tuple -> value."""
        out: Dict[Any, float] = {}
        for key, child in self.children():
            if isinstance(child, Histogram):
                continue
            if len(self.labelnames) == 1:
                out[key[0]] = child.value
            elif len(self.labelnames) == 0:
                out[""] = child.value
            else:
                out[key] = child.value
        return out

    def as_int_dict(self) -> Dict[Any, int]:
        return {k: int(v) for k, v in self.as_dict().items()}


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _labels_text(names: Tuple[str, ...], values: Tuple[str, ...],
                 extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape(extra[1])}"')
    if not pairs:
        return ""
    return "{" + ",".join(pairs) + "}"


class MetricsRegistry:
    """Create/lookup metric families, snapshot them, render them as
    Prometheus text exposition (format version 0.0.4)."""

    def __init__(self) -> None:
        self._lock = tracked_lock("obs.metrics.MetricsRegistry._lock")
        self._families: Dict[str, MetricFamily] = {}
        self._collectors: List[Callable[[], None]] = []

    # ---- family constructors (idempotent) --------------------------------
    def _family(
        self,
        name: str,
        kind: str,
        help: str,
        labelnames: Iterable[str],
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> MetricFamily:
        names = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != names:
                    raise ValueError(
                        f"metric {name} already registered as {fam.kind}"
                        f"{fam.labelnames}, not {kind}{names}"
                    )
                return fam
            fam = self._families[name] = MetricFamily(
                name, kind, help, names, buckets
            )
            return fam

    def counter(
        self, name: str, help: str, labelnames: Iterable[str] = ()
    ) -> MetricFamily:
        return self._family(name, COUNTER, help, labelnames)

    def gauge(
        self, name: str, help: str, labelnames: Iterable[str] = ()
    ) -> MetricFamily:
        return self._family(name, GAUGE, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str,
        labelnames: Iterable[str] = (),
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> MetricFamily:
        return self._family(name, HISTOGRAM, help, labelnames, buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    # ---- scrape-time collectors ------------------------------------------
    def add_collector(self, fn: Callable[[], None]) -> None:
        """Register a callable run before every snapshot/render — the
        place to SET pull-model gauges (queue depth, breaker states)."""
        with self._lock:
            self._collectors.append(fn)

    def remove_collector(self, fn: Callable[[], None]) -> None:
        """Deregister a collector (idempotent). Owners with a lifecycle
        shorter than the registry's — a serving daemon on a caller-owned
        engine — must remove their collectors on stop, or every later
        scrape would keep reading the stopped owner's stale gauges."""
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def _collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # a bad collector must not break a scrape
                pass

    # ---- export ----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict snapshot for embedded use (no HTTP scrape)."""
        self._collect()
        with self._lock:
            families = list(self._families.values())
        out: Dict[str, Any] = {}
        for fam in families:
            samples: List[Dict[str, Any]] = []
            for key, child in fam.children():
                labels = dict(zip(fam.labelnames, key))
                if isinstance(child, Histogram):
                    samples.append({"labels": labels, **child.snapshot()})
                else:
                    samples.append({"labels": labels, "value": child.value})
            out[fam.name] = {
                "type": fam.kind,
                "help": fam.help,
                "samples": samples,
            }
        return out

    def render(self) -> str:
        """Prometheus text exposition. Families with no children still
        emit their HELP/TYPE header so scrapers learn the full schema."""
        self._collect()
        with self._lock:
            families = list(self._families.values())
        lines: List[str] = []
        for fam in families:
            lines.append(f"# HELP {fam.name} {_escape(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in sorted(fam.children(), key=lambda kv: kv[0]):
                if isinstance(child, Histogram):
                    snap = child.snapshot()
                    for le, cum in snap["buckets"].items():
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_labels_text(fam.labelnames, key, ('le', _fmt(le)))}"
                            f" {cum}"
                        )
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_labels_text(fam.labelnames, key, ('le', '+Inf'))}"
                        f" {snap['count']}"
                    )
                    lines.append(
                        f"{fam.name}_sum{_labels_text(fam.labelnames, key)}"
                        f" {_fmt(snap['sum'])}"
                    )
                    lines.append(
                        f"{fam.name}_count{_labels_text(fam.labelnames, key)}"
                        f" {snap['count']}"
                    )
                else:
                    lines.append(
                        f"{fam.name}{_labels_text(fam.labelnames, key)}"
                        f" {_fmt(child.value)}"
                    )
        return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Minimal exposition-format parser for round-trip tests and scrape
    consumers: ``{metric_name: {((label, value), ...): sample_value}}``.
    Handles the subset :meth:`MetricsRegistry.render` emits (escaped
    label values, ``+Inf``, histogram ``_bucket``/``_sum``/``_count``)."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_raw, value_raw = rest.rsplit("}", 1)
            labels: List[Tuple[str, str]] = []
            i = 0
            while i < len(labels_raw):
                eq = labels_raw.index("=", i)
                lname = labels_raw[i:eq]
                assert labels_raw[eq + 1] == '"'
                j = eq + 2
                buf: List[str] = []
                while labels_raw[j] != '"':
                    if labels_raw[j] == "\\":
                        nxt = labels_raw[j + 1]
                        buf.append(
                            {"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt)
                        )
                        j += 2
                    else:
                        buf.append(labels_raw[j])
                        j += 1
                labels.append((lname, "".join(buf)))
                i = j + 1
                if i < len(labels_raw) and labels_raw[i] == ",":
                    i += 1
        else:
            name, value_raw = line.rsplit(None, 1)
            labels = []
            value_raw = " " + value_raw
        value_str = value_raw.strip()
        value = math.inf if value_str == "+Inf" else float(value_str)
        out.setdefault(name, {})[tuple(labels)] = value
    return out
