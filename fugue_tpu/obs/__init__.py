"""The unified observability plane (ISSUE 8).

Three coordinated pieces, all conf-gated and free when off:

- **span tracer** (:mod:`fugue_tpu.obs.trace`): request-scoped spans
  with ``trace_id``/``span_id``/parent links in a thread-local context
  that propagates HTTP request → serve job → workflow run → task
  attempt → engine compile/execute/transfer. Instrumentation sites are
  allocation-free no-ops without an active trace.
- **metrics registry** (:mod:`fugue_tpu.obs.metrics`): counters /
  gauges / histograms with label sets, one per engine
  (``engine.metrics``). The pre-existing ad-hoc dicts
  (``engine.fallbacks``, serve backpressure counters, ``RunStats``,
  breaker states) are views over families registered here; the serving
  daemon renders the registry as Prometheus text at ``GET /v1/metrics``
  and ``registry.snapshot()`` serves embedded use.
- **exporters** (:mod:`fugue_tpu.obs.export`): per-run Chrome-trace
  JSON (Perfetto-loadable) written through ``engine.fs`` under
  ``fugue.obs.trace_path``, plus the structured slow-query log
  (``fugue.obs.slow_query_ms``).

Conf keys (registry-declared in :mod:`fugue_tpu.constants`):

- ``fugue.obs.enabled`` (bool, default False): master switch. Off, no
  trace is ever opened and every span site is a shared no-op singleton.
- ``fugue.obs.trace_path`` (str, ""): dir/URI for per-trace Chrome
  trace JSON files ("" = traces stay in memory for their owner only).
- ``fugue.obs.slow_query_ms`` (float, 0): jobs/runs slower than this
  log one structured record with their span breakdown (0 = off).
- ``fugue.obs.sample_rate`` (float, 1.0): fraction of eligible
  requests/runs that open a trace.
"""

import random
from typing import Any, Optional, Tuple

from fugue_tpu.obs.export import (  # noqa: F401
    chrome_trace_events,
    export_trace,
    maybe_log_slow_query,
    span_breakdown,
)
from fugue_tpu.obs.metrics import (  # noqa: F401
    MetricsRegistry,
    parse_prometheus_text,
)
from fugue_tpu.obs.profile import (  # noqa: F401
    Profiler,
    RunProfile,
    TaskProfile,
    current_task_profile,
    force_profiling,
    note_cache_event,
    profiling_forced,
    profiling_requested,
)
from fugue_tpu.obs.trace import (  # noqa: F401
    NULL_CM,
    NULL_SPAN,
    Span,
    Trace,
    activate,
    begin_span,
    current_span,
    start_span,
    suppress_tracing,
    tracing_suppressed,
)

__all__ = [
    "MetricsRegistry",
    "ObsOptions",
    "Profiler",
    "RunProfile",
    "Span",
    "TaskProfile",
    "Trace",
    "activate",
    "begin_span",
    "chrome_trace_events",
    "current_span",
    "current_task_profile",
    "export_trace",
    "finalize_trace",
    "force_profiling",
    "maybe_log_slow_query",
    "note_cache_event",
    "obs_options",
    "open_trace",
    "parse_prometheus_text",
    "profiling_forced",
    "profiling_requested",
    "span_breakdown",
    "start_span",
]


class ObsOptions:
    """Parsed ``fugue.obs.*`` conf, resolved once per owner."""

    __slots__ = ("enabled", "trace_path", "slow_query_ms", "sample_rate")

    def __init__(
        self,
        enabled: bool = False,
        trace_path: str = "",
        slow_query_ms: float = 0.0,
        sample_rate: float = 1.0,
    ):
        self.enabled = bool(enabled)
        self.trace_path = str(trace_path or "").strip()
        self.slow_query_ms = max(0.0, float(slow_query_ms))
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))


def obs_options(conf: Any) -> ObsOptions:
    """Read the declared ``fugue.obs.*`` keys from a conf mapping."""
    from fugue_tpu.constants import (
        FUGUE_CONF_OBS_ENABLED,
        FUGUE_CONF_OBS_SAMPLE_RATE,
        FUGUE_CONF_OBS_SLOW_QUERY_MS,
        FUGUE_CONF_OBS_TRACE_PATH,
        typed_conf_get,
    )

    return ObsOptions(
        enabled=typed_conf_get(conf, FUGUE_CONF_OBS_ENABLED),
        trace_path=typed_conf_get(conf, FUGUE_CONF_OBS_TRACE_PATH),
        slow_query_ms=typed_conf_get(conf, FUGUE_CONF_OBS_SLOW_QUERY_MS),
        sample_rate=typed_conf_get(conf, FUGUE_CONF_OBS_SAMPLE_RATE),
    )


def open_trace(
    opts: ObsOptions,
    name: str,
    trace_id: Optional[str] = None,
    **attrs: Any,
) -> Tuple[Optional[Trace], Optional[Span]]:
    """Open a new trace with one root span when observability is on and
    the request wins the sampling draw; ``(None, None)`` otherwise. The
    caller owns finalization (:func:`finalize_trace`)."""
    if not opts.enabled:
        return None, None
    if opts.sample_rate < 1.0 and random.random() >= opts.sample_rate:
        return None, None
    trace = Trace(trace_id)
    return trace, trace.root(name, **attrs)


def finalize_trace(
    trace: Optional[Trace],
    opts: ObsOptions,
    fs: Any = None,
    log: Any = None,
    registry: Any = None,
    finish_root: bool = True,
    profile: Any = None,
    **slow_detail: Any,
) -> Optional[str]:
    """Finish an OWNED trace: end the root span (idempotent; pass
    ``finish_root=False`` from co-owners that must not cut a root still
    serving elsewhere — the daemon's job-finish path), export the Chrome
    trace JSON when ``fugue.obs.trace_path`` is set, and emit the
    slow-query record when the root crossed ``fugue.obs.slow_query_ms``.
    Safe to call from racing threads — only the call that observes the
    trace complete and claims it exports. Returns the trace file URI
    when one was written."""
    if trace is None:
        return None
    root = trace.root_span
    if finish_root and root is not None and root.end_ns is None:
        root.finish()
    if not trace.complete or not trace.mark_exported():
        return None
    if finish_root and root is not None:
        # the slow-query record rides root ownership: co-owner callers
        # (finish_root=False) time and report their own unit instead
        maybe_log_slow_query(
            trace,
            root.duration_ms,
            opts.slow_query_ms,
            log=log,
            registry=registry,
            profile=profile,
            **slow_detail,
        )
    if opts.trace_path and fs is not None:
        return export_trace(
            trace, fs, opts.trace_path, log=log, registry=registry
        )
    return None
