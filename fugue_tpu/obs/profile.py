"""Per-task execution profiler (EXPLAIN ANALYZE's runtime half).

The profiler rides the machinery PR 8 already put in place — the span
tracer and the workflow runner's task hooks — and attributes runtime
cost back to individual DAG tasks:

- **rows in/out** of every task (metadata-cheap ``count()`` on bounded
  frames; opaque/iterable frames record ``None`` rather than consume);
- **device bytes** of each task's output — the REAL ledger bytes
  (:func:`fugue_tpu.jax_backend.blocks.device_nbytes`) for materialized
  jax frames, the PR 4 dtype-widening estimator otherwise;
- the **wall / compile / execute / transfer split** from the engine
  spans nested under the task's span (``engine.compile`` /
  ``engine.execute`` / ``engine.transfer``), plus attempt counts from
  the ``task.attempt`` spans;
- **queue wait vs execution**: how long the task sat READY (every
  dependency finished) before its worker actually started it;
- **retries / degradations / fallbacks / cache events** — retry and
  host-degrade counts from the run's :class:`RunStats`, engine
  plan/exec-cache and fallback counter deltas sampled around the task,
  and exact checkpoint / result-cache hits noted by the task layer
  through the thread-local task scope.

The off contract matches the tracer's: ``fugue.obs.profile`` off means
``FugueWorkflow.run`` never constructs a profiler, the task wrapper
takes the pre-existing code path (one ``is None`` check), and the task
layer's cache-event hook is a single thread-local read returning None —
no wrapper objects, no allocation (the bench's ``detail.profiler`` block
holds the on/off ratio at ≤ ~1.05, same bar as ``detail.observability``).

Phase attribution needs spans, so the profiler only activates through
conf when ``fugue.obs.enabled`` is also on (FWF505 warns about the
silently inert combination, mirroring FWF404); a per-request
:func:`force_profiling` scope (the serving daemon's ``profile`` flag)
activates it regardless and simply records empty phases when no trace
is live.
"""

import threading
import time
from typing import Any, Dict, List, Optional

# span names that make up a task's phase split
_PHASE_SPANS = ("engine.compile", "engine.execute", "engine.transfer")

_TLS = threading.local()


def current_task_profile() -> Optional["TaskProfile"]:
    """The record of the task executing on THIS thread, or None when
    profiling is off (the allocation-free fast path: one thread-local
    read)."""
    return getattr(_TLS, "task", None)


def note_cache_event(tier: str, result: str) -> None:
    """Attribute one cache event (``tier`` in checkpoint/result/...,
    ``result`` in hit/miss/store) to the task executing on this thread.
    A no-op single thread-local read when profiling is off."""
    rec = getattr(_TLS, "task", None)
    if rec is not None:
        rec.note_cache(tier, result)


class _TaskScope:
    """Attaches one task's record as this thread's current profile
    target for the duration of the task body (paired set/restore — the
    FLN103 contract); the deep layers' :func:`note_cache_event` reads
    it through the thread-local."""

    __slots__ = ("_rec", "_prev")

    def __init__(self, rec: "TaskProfile"):
        self._rec = rec
        self._prev: Optional["TaskProfile"] = None

    def __enter__(self) -> "TaskProfile":
        self._prev = getattr(_TLS, "task", None)
        _TLS.task = self._rec
        return self._rec

    def __exit__(self, *args: Any) -> bool:
        # restore (not clear): an extension that runs a nested profiled
        # workflow on this thread hands attribution back to the OUTER
        # task when the inner one finishes
        _TLS.task = self._prev
        return False


def task_scope(rec: "TaskProfile") -> _TaskScope:
    return _TaskScope(rec)


class _ForceCM:
    """Thread-scoped per-request profiling override (the serving
    daemon's ``profile: true`` submission flag)."""

    __slots__ = ("_prev",)

    def __enter__(self) -> None:
        self._prev = getattr(_TLS, "force", False)
        _TLS.force = True
        return None

    def __exit__(self, *args: Any) -> bool:
        _TLS.force = self._prev
        return False


def force_profiling() -> Any:
    """Scope in which ``FugueWorkflow.run`` profiles regardless of conf
    (phases stay empty when no trace is live)."""
    return _ForceCM()


def profiling_forced() -> bool:
    return getattr(_TLS, "force", False)


def profiling_requested(conf: Any) -> bool:
    """The conf gate: ``fugue.obs.profile`` AND ``fugue.obs.enabled``
    (without the tracer the phase split has no source — FWF505 flags the
    inert combination)."""
    from fugue_tpu.constants import (
        FUGUE_CONF_OBS_ENABLED,
        FUGUE_CONF_OBS_PROFILE,
        typed_conf_get,
    )

    return bool(typed_conf_get(conf, FUGUE_CONF_OBS_PROFILE)) and bool(
        typed_conf_get(conf, FUGUE_CONF_OBS_ENABLED)
    )


def _safe_count(df: Any) -> Optional[int]:
    """Row count when it is metadata-cheap and safe: bounded DataFrames
    only (iterable frames raise instead of consuming; anything else
    records None — the profiler must never change execution)."""
    try:
        if df is None or not getattr(df, "is_bounded", False):
            return None
        return int(df.count())
    except Exception:
        return None


def _device_bytes(df: Any) -> Optional[int]:
    """Output device footprint: REAL ledger bytes for a materialized
    jax frame, the PR 4 widening estimate from (schema, rows) otherwise,
    None when rows are unknowable."""
    try:
        blocks = getattr(df, "_blocks", None)
        if blocks is not None and hasattr(blocks, "columns"):
            from fugue_tpu.jax_backend.blocks import device_nbytes

            return int(device_nbytes(blocks))
        rows = _safe_count(df)
        if rows is None:
            return None
        schema = getattr(df, "schema", None)
        if schema is None:
            return None
        from fugue_tpu.jax_backend.memory import estimate_schema_device_bytes

        return int(estimate_schema_device_bytes(schema, rows))
    except Exception:
        return None


class TaskProfile:
    """One task's runtime observation (built only while profiling)."""

    __slots__ = (
        "uuid",
        "name",
        "task_type",
        "callsite",
        "dep_uuids",
        "rows_in",
        "rows_out",
        "device_bytes",
        "started_at",
        "ended_at",
        "queue_wait_ms",
        "phases",
        "attempts",
        "retries",
        "degradations",
        "cache",
        "counters",
        "error",
        "span",
    )

    def __init__(self, task: Any, span: Any = None):
        self.uuid = task.__uuid__()
        self.name = task.name
        self.task_type = task.task_type
        self.callsite = list(task.callsite or [])
        self.dep_uuids = [t.__uuid__() for t in task.inputs]
        self.rows_in: List[Optional[int]] = []
        self.rows_out: Optional[int] = None
        self.device_bytes: Optional[int] = None
        self.started_at = time.monotonic()
        self.ended_at: Optional[float] = None
        self.queue_wait_ms = 0.0
        self.phases: Dict[str, float] = {}
        self.attempts = 1
        self.retries = 0
        self.degradations = 0
        self.cache: Dict[str, Dict[str, int]] = {}
        self.counters: Dict[str, Dict[str, int]] = {}
        self.error: Optional[str] = None
        # the task's real Span (or None): phase attribution walks its
        # subtree once at finalize
        self.span = span if getattr(span, "span_id", None) is not None else None

    @property
    def wall_ms(self) -> float:
        end = self.ended_at if self.ended_at is not None else time.monotonic()
        return (end - self.started_at) * 1000.0

    def note_cache(self, tier: str, result: str) -> None:
        slot = self.cache.setdefault(tier, {})
        slot[result] = slot.get(result, 0) + 1

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "uuid": self.uuid,
            "name": self.name,
            "type": self.task_type,
            "callsite": list(self.callsite),
            "rows_in": list(self.rows_in),
            "rows_out": self.rows_out,
            "device_bytes": self.device_bytes,
            "wall_ms": round(self.wall_ms, 3),
            "queue_wait_ms": round(self.queue_wait_ms, 3),
            "phases": {k: round(v, 3) for k, v in self.phases.items()},
            "attempts": self.attempts,
            "retries": self.retries,
            "degradations": self.degradations,
            "cache": {k: dict(v) for k, v in self.cache.items()},
        }
        if self.counters:
            out["counters"] = {k: dict(v) for k, v in self.counters.items()}
        if self.error is not None:
            out["error"] = self.error
        return out


# engine counter surfaces sampled around each task (delta attribution);
# each maps a profile key to the engine property carrying the dict
_COUNTER_SURFACES = (
    ("plan_cache", "plan_cache_stats"),
    ("compile_cache", "compile_cache_stats"),
    ("fallbacks", "fallbacks"),
    ("shuffle", "shuffle_counts"),
)


class RunProfile:
    """One run's profile: per-task records in execution order plus the
    merged EXPLAIN tree (set by the workflow when available)."""

    def __init__(self, workflow_uuid: str, concurrency: int = 1):
        self.workflow_uuid = workflow_uuid
        self.concurrency = int(concurrency)
        self.records: Dict[str, TaskProfile] = {}
        self.order: List[str] = []
        self.started_at = time.monotonic()
        self.total_ms = 0.0
        self.report: Any = None  # ExplainReport, attached by the workflow
        self._lock = threading.Lock()

    # counter-delta attribution is exact only when tasks run serially
    # (the default inner concurrency); concurrent tasks overlap on the
    # shared engine counters, so the profile says so instead of lying
    @property
    def exact_attribution(self) -> bool:
        return self.concurrency <= 1

    def add(self, rec: TaskProfile) -> None:
        # task uuids are CONTENT hashes: two spec-identical tasks (CSE
        # off, or user duplicates) legitimately share one. Store every
        # instance under a unique key (uuid, then uuid#2, uuid#3 …) so
        # no observation is lost; uuid lookups resolve to the first
        # instance — the same dedup the explain tree applies.
        with self._lock:
            key = rec.uuid
            n = 2
            while key in self.records:
                key = f"{rec.uuid}#{n}"
                n += 1
            self.records[key] = rec
            self.order.append(key)

    def task(self, uuid: str) -> Optional[TaskProfile]:
        return self.records.get(uuid)

    def by_name(self, name: str) -> Optional[TaskProfile]:
        for rec in self.records.values():
            if rec.name == name:
                return rec
        return None

    def finalize(
        self, trace: Any = None, stats: Any = None
    ) -> "RunProfile":
        """Settle the run: total wall, queue waits from dependency end
        times, phase splits from one walk of the trace's span forest,
        retry/degrade counts from :class:`RunStats`."""
        self.total_ms = (time.monotonic() - self.started_at) * 1000.0
        # queue wait: time between READY (all deps ended; run start for
        # roots) and the worker actually starting the task
        for rec in self.records.values():
            ready = self.started_at
            for dep in rec.dep_uuids:
                d = self.records.get(dep)
                if d is not None and d.ended_at is not None:
                    ready = max(ready, d.ended_at)
            rec.queue_wait_ms = max(0.0, (rec.started_at - ready) * 1000.0)
        if stats is not None:
            retries = getattr(stats, "retries", None) or {}
            degrades = getattr(stats, "degradations", None) or {}
            for rec in self.records.values():
                rec.retries = int(retries.get(rec.name, 0))
                rec.degradations = int(degrades.get(rec.name, 0))
        if trace is not None:
            self._attach_spans(trace)
        return self

    def _attach_spans(self, trace: Any) -> None:
        """One pass over the trace: group spans under each task's span
        subtree and roll their durations up into the phase split."""
        try:
            with trace._lock:
                spans = list(trace.spans)
        except Exception:
            return
        children: Dict[int, List[Any]] = {}
        for s in spans:
            if s.parent_id is not None:
                children.setdefault(s.parent_id, []).append(s)
        for rec in self.records.values():
            if rec.span is None:
                continue
            attempts = 0
            stack = list(children.get(rec.span.span_id, []))
            while stack:
                s = stack.pop()
                stack.extend(children.get(s.span_id, []))
                if s.name in _PHASE_SPANS:
                    key = s.name.split(".", 1)[1] + "_ms"
                    rec.phases[key] = rec.phases.get(key, 0.0) + s.duration_ms
                elif s.name == "task.attempt":
                    attempts += 1
            if attempts > 0:
                rec.attempts = attempts

    def top_tasks(self, n: int = 3) -> List[Dict[str, Any]]:
        """The run's ``n`` most expensive tasks by wall clock — what the
        slow-query log carries beyond the per-phase span breakdown."""
        ranked = sorted(
            self.records.values(), key=lambda r: r.wall_ms, reverse=True
        )
        out: List[Dict[str, Any]] = []
        for rec in ranked[: max(0, n)]:
            out.append(
                {
                    "name": rec.name,
                    "callsite": rec.callsite[0] if rec.callsite else "",
                    "wall_ms": round(rec.wall_ms, 3),
                    "phases": {k: round(v, 3) for k, v in rec.phases.items()},
                }
            )
        return out

    def observation(self) -> Dict[str, Any]:
        """The statistics-store payload: per-task-uuid observed rows /
        bytes / timings for this run of this query fingerprint."""
        return {
            "workflow": self.workflow_uuid,
            "total_ms": round(self.total_ms, 3),
            "tasks": {
                uuid: {
                    "name": rec.name,
                    "rows_in": list(rec.rows_in),
                    "rows_out": rec.rows_out,
                    "device_bytes": rec.device_bytes,
                    "wall_ms": round(rec.wall_ms, 3),
                    "phases": {
                        k: round(v, 3) for k, v in rec.phases.items()
                    },
                }
                for uuid, rec in self.records.items()
            },
        }

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "workflow": self.workflow_uuid,
            "concurrency": self.concurrency,
            "exact_attribution": self.exact_attribution,
            "total_ms": round(self.total_ms, 3),
            "tasks": [self.records[u].as_dict() for u in self.order],
        }
        if self.report is not None:
            out["plan"] = self.report.to_dict()
        return out

    def to_text(self) -> str:
        """EXPLAIN ANALYZE rendering: the plan tree annotated with this
        run's per-task observations (falls back to a flat listing when
        no plan report is attached)."""
        if self.report is not None:
            self.report.attach_profile(self)
            return self.report.to_text()
        lines = [f"RunProfile {self.workflow_uuid[:12]} "
                 f"total={self.total_ms:.1f}ms"]
        for uuid in self.order:
            rec = self.records[uuid]
            lines.append(
                f"  {rec.name}: rows={rec.rows_out} "
                f"wall={rec.wall_ms:.1f}ms phases={rec.phases}"
            )
        return "\n".join(lines)


class Profiler:
    """The per-run collector ``FugueWorkflow.run`` owns while profiling
    is active. ``begin``/``finish`` bracket each task on its worker
    thread; the thread-local task scope is what lets deep layers
    (checkpoint short-circuits, result caches) attribute events without
    plumbing."""

    def __init__(self, workflow_uuid: str, engine: Any, concurrency: int = 1):
        self._engine = engine
        self.profile = RunProfile(workflow_uuid, concurrency=concurrency)

    def _sample(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for key, attr in _COUNTER_SURFACES:
            try:
                val = getattr(self._engine, attr, None)
                if isinstance(val, dict):
                    out[key] = {
                        k: int(v)
                        for k, v in val.items()
                        if isinstance(v, (int, float))
                    }
            except Exception:
                pass
        return out

    def begin(self, task: Any, span: Any = None) -> TaskProfile:
        """The task's record; the caller enters :func:`task_scope` with
        it so the thread-local attach/detach stays a paired scope."""
        rec = TaskProfile(task, span=span)
        rec.counters = self._sample()  # baselines; finish() turns to deltas
        return rec

    def finish(
        self,
        rec: TaskProfile,
        inputs: Any = None,
        result: Any = None,
        error: Any = None,
    ) -> TaskProfile:
        rec.ended_at = time.monotonic()
        if error is not None:
            rec.error = type(error).__name__
        after = self._sample()
        deltas: Dict[str, Dict[str, int]] = {}
        for key, base in rec.counters.items():
            cur = after.get(key, {})
            d = {
                k: cur.get(k, 0) - v
                for k, v in base.items()
                if cur.get(k, 0) - v != 0
            }
            for k, v in cur.items():
                if k not in base and v != 0:
                    d[k] = v
            if d:
                deltas[key] = d
        rec.counters = deltas
        if inputs is not None:
            rec.rows_in = [_safe_count(i) for i in inputs]
        if result is not None:
            rec.rows_out = _safe_count(result)
            rec.device_bytes = _device_bytes(result)
        self.profile.add(rec)
        return rec

    def finalize(self, trace: Any = None, stats: Any = None) -> RunProfile:
        return self.profile.finalize(trace=trace, stats=stats)
