"""Trace exporters: per-run Chrome-trace-event JSON (loads directly in
Perfetto / ``chrome://tracing``) and the structured slow-query log.

Export is strictly best-effort: a failing trace-file write (chaos site
``obs.trace``) is counted on the registry and logged — it degrades
observability, never the job that produced the trace.
"""

import json
from typing import Any, Dict, List, Optional

from fugue_tpu.obs.trace import Trace
from fugue_tpu.testing.faults import fault_point

# registry family names shared by the exporters and their tests
TRACE_EXPORT_FAILURES = "fugue_obs_trace_export_failures_total"
TRACES_EXPORTED = "fugue_obs_traces_exported_total"
SLOW_QUERIES = "fugue_obs_slow_queries_total"


def chrome_trace_events(trace: Trace) -> Dict[str, Any]:
    """The trace as a Chrome trace-event JSON object: one complete
    (``"ph": "X"``) event per span, on its executing thread's lane, with
    the span/parent/trace ids in ``args`` so the tree survives tools
    that only render time-nesting."""
    import os

    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    with trace._lock:
        spans = list(trace.spans)
    # an unfinished span (crashed run) renders up to the latest end seen
    latest = max(
        (s.end_ns for s in spans if s.end_ns is not None),
        default=None,
    )
    for s in spans:
        end = s.end_ns if s.end_ns is not None else (latest or s.start_ns)
        args: Dict[str, Any] = {
            "trace_id": trace.trace_id,
            "span_id": s.span_id,
        }
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        args.update(s.attrs)
        events.append(
            {
                "name": s.name,
                "cat": "fugue_tpu",
                "ph": "X",
                "ts": s.start_ns / 1000.0,  # microseconds
                "dur": max(0.0, (end - s.start_ns) / 1000.0),
                "pid": pid,
                "tid": s.thread_id,
                "args": args,
            }
        )
    return {"displayTimeUnit": "ms", "traceEvents": events}


def span_breakdown(trace: Trace) -> Dict[str, Any]:
    """Per-span-name time rollup of one trace — the slow-query log's
    payload: how much wall clock each phase (queue/compile/execute/
    transfer/...) consumed, with counts."""
    phases: Dict[str, Dict[str, float]] = {}
    with trace._lock:
        spans = list(trace.spans)
    for s in spans:
        slot = phases.setdefault(s.name, {"ms": 0.0, "count": 0})
        slot["ms"] = round(slot["ms"] + s.duration_ms, 3)
        slot["count"] += 1
    root = trace.root_span
    return {
        "trace_id": trace.trace_id,
        "total_ms": round(root.duration_ms, 3) if root is not None else 0.0,
        "spans": len(spans),
        "phases": phases,
    }


def export_trace(
    trace: Trace,
    fs: Any,
    base_uri: str,
    log: Any = None,
    registry: Any = None,
) -> Optional[str]:
    """Write the trace as ``<base_uri>/trace-<trace_id>.json`` through
    the engine's virtual filesystem (atomic, like the run manifest).
    Returns the URI, or None when the write failed — counted on
    ``fugue_obs_trace_export_failures_total`` and logged, never raised."""
    base = str(base_uri).rstrip("/")
    uri = fs.join(base, f"trace-{trace.trace_id}.json")
    try:
        fault_point("obs.trace", uri)
        fs.makedirs(base, exist_ok=True)
        # compact separators, no indent: a big run's trace carries
        # thousands of spans, and the export cost is the one obs cost
        # paid per run even when nobody reads the file — keep it minimal
        # (same atomic-write primitive as the run manifest)
        data = json.dumps(
            chrome_trace_events(trace), separators=(",", ":")
        ).encode("utf-8")
        fs.write_file_atomic(uri, lambda fp: fp.write(data))
    except Exception as ex:
        if registry is not None:
            registry.counter(
                TRACE_EXPORT_FAILURES,
                "trace-file writes that failed (observability degraded, "
                "the traced job was not affected)",
            ).labels().inc()
        if log is not None:
            log.warning(
                "fugue_tpu obs: trace export to %s failed (%s: %s); "
                "observability degraded, the job is unaffected",
                uri,
                type(ex).__name__,
                ex,
            )
        return None
    if registry is not None:
        registry.counter(
            TRACES_EXPORTED, "trace files written to fugue.obs.trace_path"
        ).labels().inc()
    return uri


def maybe_log_slow_query(
    trace: Optional[Trace],
    duration_ms: float,
    slow_query_ms: float,
    log: Any = None,
    registry: Any = None,
    profile: Any = None,
    **detail: Any,
) -> Optional[Dict[str, Any]]:
    """Emit one structured slow-query record when ``duration_ms``
    crosses the configured threshold: a single JSON log line carrying
    the span breakdown (phases of the offending job) plus caller detail
    (job id, session, sql hash). With a run profile available, the
    record also names the top-3 most expensive TASKS (name, user
    callsite, phase split) — the "which line of my workflow is slow"
    answer the per-phase rollup can't give. Returns the record (tests
    introspect it); None when under threshold or the threshold is off."""
    if slow_query_ms <= 0 or duration_ms <= slow_query_ms:
        return None
    record: Dict[str, Any] = {
        "slow_query_ms": slow_query_ms,
        "duration_ms": round(duration_ms, 3),
        **detail,
    }
    if trace is not None:
        record["breakdown"] = span_breakdown(trace)
    if profile is not None:
        try:
            record["top_tasks"] = profile.top_tasks(3)
        except Exception:  # pragma: no cover - enrichment is best-effort
            pass
    if registry is not None:
        registry.counter(
            SLOW_QUERIES,
            "jobs/runs whose wall clock crossed fugue.obs.slow_query_ms",
        ).labels().inc()
    if log is not None:
        log.warning("fugue_tpu obs slow query: %s", json.dumps(record))
    return record
