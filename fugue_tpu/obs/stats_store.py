"""The persisted runtime-statistics store: per query-fingerprint rolling
history of per-task-uuid observed rows / bytes / timings.

This is the durable half of the profiler — the statistics plane ROADMAP
item 1 (cost model + adaptive re-planning) will read. Layout, all
through ``engine.fs`` (URI-capable: local dirs, ``memory://``, object
stores):

    <base>/<fingerprint>.json
        {"fingerprint": ..., "observations": [obs, ...]}   # bounded ring

where ``fingerprint`` is the deterministic workflow uuid (the same key
the serve circuit breakers and result caches use — stable across
processes and replicas) and each observation is
:meth:`~fugue_tpu.obs.profile.RunProfile.observation`: per-task-uuid
rows in/out, device bytes, wall/phase timings.

Write discipline matches the serve journal (FLN104-clean): the in-memory
ring mutates under the store lock, the filesystem write runs OUTSIDE it
through a per-fingerprint :class:`~fugue_tpu.serve.state.SnapshotWriter`
(ordered tickets, superseded snapshots dropped, failures counted and
logged — durability degrades, the run that produced the profile never
fails). The store survives daemon restarts by construction (it IS
files), and :meth:`adopt` merges a dead replica's fingerprint files into
the survivor's store during fleet failover.

Conf (registry-declared):

- ``fugue.stats.path`` — dir/URI of the store; the serving daemon
  defaults it to ``<fugue.serve.state_path>/stats``. '' = off.
- ``fugue.stats.history`` — ring length per fingerprint (default 32).
"""

import copy
import time
from typing import Any, Dict, List, Optional

from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.workflow.manifest import read_json

# bound on in-memory cached rings/writers; the files themselves are the
# durable store, the cache only avoids re-reading hot fingerprints
_MAX_CACHED = 256

STATS_WRITES = "fugue_stats_store_writes_total"


class RuntimeStatsStore:
    """Rolling per-fingerprint observation rings on the fs layer."""

    def __init__(
        self,
        fs: Any,
        base_uri: str,
        history: int = 32,
        log: Any = None,
        registry: Any = None,
    ):
        self._fs = fs
        self._base = str(base_uri).rstrip("/")
        self._history = max(1, int(history))
        self._log = log
        self._lock = tracked_lock("obs.stats_store.RuntimeStatsStore._lock")
        self._rings: Dict[str, List[Dict[str, Any]]] = {}
        self._writers: Dict[str, Any] = {}
        self._m_writes = (
            None
            if registry is None
            else registry.counter(
                STATS_WRITES,
                "runtime-statistics store snapshot writes by result",
                ["result"],
            )
        )
        try:
            fs.makedirs(self._base, exist_ok=True)
        except Exception:  # pragma: no cover - store is best-effort
            pass

    @property
    def base_uri(self) -> str:
        return self._base

    def rebind(
        self,
        fs: Any,
        history: int,
        log: Any = None,
        registry: Any = None,
    ) -> None:
        """Re-point a process-cached store at a NEW owner (a restarted
        daemon's engine): fresh fs/log, the CURRENT conf's ring length,
        and the live engine's metrics registry — a stopped engine's
        registry must not keep receiving this store's counters."""
        m_writes = (
            None
            if registry is None
            else registry.counter(
                STATS_WRITES,
                "runtime-statistics store snapshot writes by result",
                ["result"],
            )
        )
        with self._lock:
            self._fs = fs
            self._history = max(1, int(history))
            self._log = log
            self._m_writes = m_writes

    def uri(self, fingerprint: str) -> str:
        return self._fs.join(self._base, f"{fingerprint}.json")

    # ---- ring access -----------------------------------------------------
    def _load_ring(self, fingerprint: str) -> List[Dict[str, Any]]:
        """The in-memory ring for one fingerprint, loading the file on a
        cache miss. The fs read runs OUTSIDE the store lock."""
        with self._lock:
            ring = self._rings.get(fingerprint)
        if ring is not None:
            return ring
        data = (
            read_json(
                self._fs, self.uri(fingerprint),
                log=self._log, what="runtime stats",
            )
            or {}
        )
        loaded = [
            o for o in (data.get("observations") or []) if isinstance(o, dict)
        ][-self._history:]
        with self._lock:
            # double-checked install: a racing loader's ring wins
            ring = self._rings.setdefault(fingerprint, loaded)
            self._evict_locked()
        return ring

    def _writer(self, fingerprint: str) -> Any:
        from fugue_tpu.serve.state import SnapshotWriter

        with self._lock:
            w = self._writers.get(fingerprint)
            if w is None:
                w = self._writers[fingerprint] = SnapshotWriter(
                    self._fs, self.uri(fingerprint), log=self._log
                )
            return w

    def _evict_locked(self) -> None:
        # rings only: they reload from disk on the next touch. Writers
        # are NEVER evicted — the superseded-ticket ordering guarantee
        # only holds within one SnapshotWriter instance per URI, and a
        # writer is just a mutex + two ints, bounded by the distinct
        # fingerprints this process ever recorded.
        while len(self._rings) > _MAX_CACHED:
            self._rings.pop(next(iter(self._rings)))

    # ---- public API ------------------------------------------------------
    def record(self, fingerprint: str, observation: Dict[str, Any]) -> bool:
        """Append one observation to the fingerprint's ring and persist
        the snapshot. Best-effort: returns False (counted + logged) when
        the write failed; never raises into the profiled run."""
        fingerprint = str(fingerprint)
        try:
            ring = self._load_ring(fingerprint)
            writer = self._writer(fingerprint)
            obs = dict(observation)
            obs.setdefault("recorded_at", time.time())
            with self._lock:
                ring.append(obs)
                del ring[: max(0, len(ring) - self._history)]
                payload = {
                    "fingerprint": fingerprint,
                    "history": self._history,
                    "observations": copy.deepcopy(ring),
                }
                ticket = writer.ticket()
            before = writer.failures
            writer.write(ticket, payload)
            ok = writer.failures == before
        except Exception as ex:
            ok = False
            if self._log is not None:
                self._log.warning(
                    "fugue_tpu stats store: recording fingerprint %s "
                    "failed (%s: %s); statistics degraded, the run is "
                    "unaffected",
                    fingerprint[:12], type(ex).__name__, ex,
                )
        if self._m_writes is not None:
            self._m_writes.labels(result="ok" if ok else "error").inc()
        return ok

    def history(self, fingerprint: str) -> List[Dict[str, Any]]:
        """The fingerprint's observation ring, oldest first (empty when
        never recorded)."""
        ring = self._load_ring(str(fingerprint))
        with self._lock:
            return copy.deepcopy(ring)

    def latest(self, fingerprint: str) -> Optional[Dict[str, Any]]:
        hist = self.history(fingerprint)
        return hist[-1] if hist else None

    def observed_rows(self, fingerprint: str) -> Dict[str, Optional[int]]:
        """Per-task-uuid ``rows_out`` of the LATEST observation — the
        replay surface the cost model (and EXPLAIN's ``observed`` block)
        reads."""
        obs = self.latest(fingerprint)
        if obs is None:
            return {}
        return {
            uuid: rec.get("rows_out")
            for uuid, rec in (obs.get("tasks") or {}).items()
        }

    def fingerprints(self) -> List[str]:
        """Every fingerprint with a persisted ring (scans the store
        dir — startup/diagnostic use, not the hot path)."""
        out: List[str] = []
        try:
            for uri in self._fs.glob(self._fs.join(self._base, "*.json")):
                name = uri.rsplit("/", 1)[-1]
                if name.endswith(".json"):
                    out.append(name[: -len(".json")])
        except Exception:  # pragma: no cover - scan is best-effort
            pass
        return sorted(out)

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            cached = len(self._rings)
        return {
            "uri": self._base,
            "history": self._history,
            "cached_fingerprints": cached,
        }

    # ---- fleet adoption --------------------------------------------------
    def adopt(self, source_base: str) -> int:
        """Merge a dead/drained replica's store into this one (fleet
        failover rides along with the journal adoption): each source
        fingerprint's observations append into the survivor's ring,
        oldest first, bounded as usual. Returns fingerprints merged."""
        source = str(source_base or "").rstrip("/")
        if source == "" or source == self._base:
            return 0
        merged = 0
        try:
            uris = list(self._fs.glob(self._fs.join(source, "*.json")))
        except Exception:
            return 0
        for uri in uris:
            name = uri.rsplit("/", 1)[-1]
            if not name.endswith(".json"):
                continue
            fingerprint = name[: -len(".json")]
            data = (
                read_json(self._fs, uri, log=self._log, what="adopted stats")
                or {}
            )
            observations = [
                o
                for o in (data.get("observations") or [])
                if isinstance(o, dict)
            ]
            if not observations:
                continue
            ring = self._load_ring(fingerprint)
            writer = self._writer(fingerprint)
            with self._lock:
                seen = {
                    o.get("recorded_at") for o in ring
                }
                fresh = [
                    o
                    for o in observations
                    if o.get("recorded_at") not in seen
                ]
                # source observations are OLDER context: they go in
                # front so the survivor's own runs stay the latest
                ring[:0] = fresh
                del ring[: max(0, len(ring) - self._history)]
                payload = {
                    "fingerprint": fingerprint,
                    "history": self._history,
                    "observations": copy.deepcopy(ring),
                }
                ticket = writer.ticket()
            writer.write(ticket, payload)
            merged += 1
        return merged


def make_stats_store(
    engine: Any, path: str, history: int = 32
) -> Optional[RuntimeStatsStore]:
    """A store on the engine's fs when ``path`` is non-empty; None keeps
    statistics off (PR-8-and-earlier behavior)."""
    base = str(path or "").strip()
    if base == "":
        return None
    return RuntimeStatsStore(
        engine.fs,
        base,
        history=history,
        log=engine.log,
        registry=getattr(engine, "metrics", None),
    )


_STORES: Dict[str, RuntimeStatsStore] = {}
_STORES_LOCK = tracked_lock("obs.stats_store._STORES_LOCK")


def get_stats_store(
    engine: Any, path: str, history: int = 32
) -> RuntimeStatsStore:
    """Process-wide store cache keyed by base URI: every profiled run
    against the same store path shares one ring cache and one ordered
    writer per fingerprint, so concurrent same-fingerprint runs in one
    process append instead of clobbering each other's snapshots. A
    cache hit REBINDS the store to the calling engine (fs, log,
    metrics registry, current ring length) — a restarted daemon's
    counters must land on its live engine, not its predecessor's."""
    base = str(path).rstrip("/")
    with _STORES_LOCK:
        store = _STORES.get(base)
    if store is None:
        built = make_stats_store(engine, base, history=history)
        assert built is not None  # caller checked path non-empty
        with _STORES_LOCK:
            store = _STORES.setdefault(base, built)
        if store is built:
            return store
    store.rebind(
        engine.fs,
        history,
        log=engine.log,
        registry=getattr(engine, "metrics", None),
    )
    return store
