"""Typed exception hierarchy (parity contract:
``/root/reference/fugue/exceptions.py:1-66``): one root users can catch
(:class:`FugueError`), split into compile-time vs runtime vs validation
vs SQL branches so programs can distinguish "my workflow is malformed"
from "execution failed" without string-matching.

The framework's concrete errors subclass BOTH a branch here and their
historical base (``ValueError`` for the SQL front end's errors), so
pre-hierarchy code catching ``ValueError`` keeps working.
"""


class FugueError(Exception):
    """Base of every framework-raised error."""


class FugueBug(FugueError):
    """An internal invariant broke — not a user error."""


class FugueInvalidOperation(FugueError):
    """The requested operation is not valid on this object/state."""


class FuguePluginsRegistrationError(FugueError):
    """Loading or registering a plugin failed."""


class FugueDataFrameError(FugueError):
    """DataFrame-related errors."""


class FugueDataFrameInitError(FugueDataFrameError):
    """Constructing a DataFrame from the given object failed."""


class FugueDatasetEmptyError(FugueDataFrameError):
    """The dataframe is empty where a value was required (peek)."""


class FugueDataFrameOperationError(FugueDataFrameError):
    """An invalid DataFrame operation (bad rename/alter/select)."""


class FugueWorkflowError(FugueError):
    """Workflow-related errors."""


class FugueWorkflowCompileError(FugueWorkflowError):
    """Raised while BUILDING a workflow DAG (before execution)."""


class FugueWorkflowCompileValidationError(FugueWorkflowCompileError):
    """A validation rule failed at compile time."""


class WorkflowAnalysisError(FugueWorkflowCompileError):
    """The pre-execution static analyzer found error-level diagnostics and
    ``fugue.analysis`` is set to ``error``: the run is rejected BEFORE any
    task executes. ``diagnostics`` holds every finding of the analysis
    (not only the error-level ones), most severe first."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        # compared by NAME to stay import-free of the analysis package
        # without hardcoding the severity enum's integer layout
        errors = [d for d in self.diagnostics if str(d.severity) == "error"]
        msg = (
            f"static analysis rejected the workflow with {len(errors)} "
            "error-level diagnostic(s):\n"
            + "\n".join(d.describe() for d in errors)
        )
        super().__init__(msg)


class FugueInterfacelessError(FugueWorkflowCompileError):
    """A function couldn't be adapted into an extension (bad signature
    or missing schema hint)."""


class FugueWorkflowRuntimeError(FugueWorkflowError):
    """Raised while EXECUTING a workflow."""


class FugueWorkflowRuntimeValidationError(FugueWorkflowRuntimeError):
    """A validation rule failed at runtime (partition/input checks)."""


class TaskFailure:
    """One task's failure inside a workflow run: the task's display name,
    the user callsite where it was defined, and the error itself."""

    def __init__(
        self,
        task_id: str,
        task_name: str,
        error: BaseException,
        callsite=None,
    ):
        self.task_id = task_id
        self.task_name = task_name
        self.error = error
        self.callsite = list(callsite or [])

    def describe(self) -> str:
        lines = [
            f"[task {self.task_name}] "
            f"{type(self.error).__name__}: {self.error}"
        ]
        if self.callsite:
            lines.append("  defined at:")
            lines.extend("  " + c for c in self.callsite)
        return "\n".join(lines)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TaskFailure({self.task_name}, {type(self.error).__name__})"


class WorkflowRuntimeError(FugueWorkflowRuntimeError):
    """The parallel runner's aggregated failure: EVERY task that failed
    during the run (not just the first), each carrying its task name and
    the user callsite that defined it. ``failures`` holds the structured
    :class:`TaskFailure` list; the first failure is chained as
    ``__cause__`` so ``raise ... from`` semantics and traceback tools
    keep working."""

    def __init__(self, failures):
        self.failures = list(failures)
        msg = f"{len(self.failures)} task(s) failed:\n" + "\n".join(
            f.describe() for f in self.failures
        )
        super().__init__(msg)
        if self.failures:
            self.__cause__ = self.failures[0].error

    @property
    def errors(self):
        return [f.error for f in self.failures]


class TaskTimeoutError(FugueWorkflowRuntimeError):
    """A task exceeded its wall-clock timeout (``fugue.workflow.timeout``
    or a per-task override) and was abandoned by the runner."""

    def __init__(self, task_name: str, timeout: float):
        super().__init__(
            f"task {task_name} timed out after {timeout:g}s"
        )
        self.task_name = task_name
        self.timeout = timeout


class TaskCancelledError(FugueWorkflowRuntimeError):
    """A task was cooperatively cancelled because a sibling failed or
    timed out; it never ran (or aborted at a cancellation point)."""


class DeviceLostError(FugueWorkflowRuntimeError):
    """A device in the engine's mesh died and the data this query needs
    could not be recovered onto the survivors: no lazy ingest plan, no
    checkpoint artifact, no pinned ``lake://`` version to rebuild from.
    The error fails the OWNING query only — the engine keeps serving on
    the degraded mesh and the process never dies. ``lost_devices`` holds
    the dead device ids; ``frames`` the unrecoverable frame keys."""

    def __init__(self, message: str, lost_devices=(), frames=()):
        super().__init__(message)
        self.lost_devices = tuple(lost_devices)
        self.frames = tuple(frames)


class FugueSQLError(FugueWorkflowCompileError):
    """FugueSQL-related compile error."""


class FugueSQLSyntaxError(FugueSQLError):
    """FugueSQL/SELECT text failed to parse."""


class FugueSQLRuntimeError(FugueWorkflowRuntimeError):
    """A SQL statement failed during execution."""
