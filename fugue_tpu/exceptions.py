"""Typed exception hierarchy (parity contract:
``/root/reference/fugue/exceptions.py:1-66``): one root users can catch
(:class:`FugueError`), split into compile-time vs runtime vs validation
vs SQL branches so programs can distinguish "my workflow is malformed"
from "execution failed" without string-matching.

The framework's concrete errors subclass BOTH a branch here and their
historical base (``ValueError`` for the SQL front end's errors), so
pre-hierarchy code catching ``ValueError`` keeps working.
"""


class FugueError(Exception):
    """Base of every framework-raised error."""


class FugueBug(FugueError):
    """An internal invariant broke — not a user error."""


class FugueInvalidOperation(FugueError):
    """The requested operation is not valid on this object/state."""


class FuguePluginsRegistrationError(FugueError):
    """Loading or registering a plugin failed."""


class FugueDataFrameError(FugueError):
    """DataFrame-related errors."""


class FugueDataFrameInitError(FugueDataFrameError):
    """Constructing a DataFrame from the given object failed."""


class FugueDatasetEmptyError(FugueDataFrameError):
    """The dataframe is empty where a value was required (peek)."""


class FugueDataFrameOperationError(FugueDataFrameError):
    """An invalid DataFrame operation (bad rename/alter/select)."""


class FugueWorkflowError(FugueError):
    """Workflow-related errors."""


class FugueWorkflowCompileError(FugueWorkflowError):
    """Raised while BUILDING a workflow DAG (before execution)."""


class FugueWorkflowCompileValidationError(FugueWorkflowCompileError):
    """A validation rule failed at compile time."""


class FugueInterfacelessError(FugueWorkflowCompileError):
    """A function couldn't be adapted into an extension (bad signature
    or missing schema hint)."""


class FugueWorkflowRuntimeError(FugueWorkflowError):
    """Raised while EXECUTING a workflow."""


class FugueWorkflowRuntimeValidationError(FugueWorkflowRuntimeError):
    """A validation rule failed at runtime (partition/input checks)."""


class FugueSQLError(FugueWorkflowCompileError):
    """FugueSQL-related compile error."""


class FugueSQLSyntaxError(FugueSQLError):
    """FugueSQL/SELECT text failed to parse."""


class FugueSQLRuntimeError(FugueWorkflowRuntimeError):
    """A SQL statement failed during execution."""
