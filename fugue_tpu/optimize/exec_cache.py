"""The plan cache's DISK tier: AOT-serialized compiled executables.

The PR 9 :class:`~fugue_tpu.optimize.cache.PlanCache` shares compiled
``jax.jit`` handles across engines, but only within one process — a
restarted daemon or a fresh bench process re-pays the full trace + XLA
compile + first dispatch (~2-9 s on this container, the cold-start
residual ROADMAP item 5 names). This module persists the compiled
executables themselves:

- **what is stored** — for every ``_jit_cached`` program whose key is
  process-stable (see :func:`canonical_key_token`), the per-shape
  compiled executable (``jitted.lower(avals).compile()`` serialized via
  :mod:`jax.experimental.serialize_executable`), written through
  ``engine.fs`` under ``fugue.optimize.cache.dir`` — so ``memory://``,
  local dirs and object-store URIs all work, and fleet replicas can
  share one cache;
- **how it is keyed** — the entry id folds the engine's plan signature
  (platform + mesh device ids + every ``fugue.jax.*`` conf value), the
  logical program key, a hash of the program function's source, and the
  argument avals (tree structure + shape/dtype/sharding per leaf).
  Anything that could change the compiled artifact changes the id;
- **how it is invalidated** — every entry carries a header stamped with
  the cache format rev and the jax/jaxlib/python versions. A version
  mismatch or an unreadable (truncated, corrupt) entry is EVICTED — the
  file is removed, the engine recompiles, and a fresh entry replaces it;
  a cache problem is never an execution error;
- **when it is written** — persistence runs on a single background
  worker (miss → compile → dispatch → persist off the critical path).
  The worker re-lowers from avals, so no array data is retained. Writes
  run under the chaos site ``cache.persist`` and a ``cache.persist``
  span; failures are counted (``fugue_engine_exec_cache_persist_total``)
  and logged, never raised.

Hit/miss/evict/corrupt counters ride the existing
``fugue_engine_plan_cache_total`` family under ``tier="disk"`` (the
in-memory handle tier is ``tier="memory"``), with a deserialize-time
histogram (``fugue_engine_exec_cache_deserialize_seconds``).
"""

import logging
import os
import pickle
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, NamedTuple, Optional, Tuple

import numpy as np

from fugue_tpu.testing.locktrace import tracked_lock

# bump when the on-disk layout or the keying scheme changes: old entries
# then evict to a recompile instead of deserializing garbage
FORMAT_REV = 1
_MAGIC = b"FGXC1\n"
_SUFFIX = ".jxc"

_log = logging.getLogger("fugue_tpu.optimize.exec_cache")

# ---- conf resolution --------------------------------------------------------
_DEPRECATION_LOGGED = False


def resolve_cache_dir(conf: Any, log: Any = None) -> str:
    """The persistent executable cache dir in effect: the new
    ``fugue.optimize.cache.dir`` key wins; the legacy
    ``fugue.jax.compile.cache`` key (and its ``FUGUE_JAX_COMPILE_CACHE``
    env var) remains an ALIAS that feeds the same disk tier with a
    deprecation note — the two keys can never run divergent caches.
    Empty string = disk tier off."""
    global _DEPRECATION_LOGGED
    from fugue_tpu.constants import (
        FUGUE_CONF_JAX_COMPILE_CACHE,
        FUGUE_CONF_OPTIMIZE_CACHE_DIR,
    )

    try:
        new = str(conf.get(FUGUE_CONF_OPTIMIZE_CACHE_DIR, "") or "").strip()
    except Exception:  # pragma: no cover - conf-less stub
        new = ""
    if new != "":
        return new
    try:
        legacy = str(conf.get(FUGUE_CONF_JAX_COMPILE_CACHE, "") or "").strip()
    except Exception:  # pragma: no cover
        legacy = ""
    if legacy == "":
        legacy = os.environ.get("FUGUE_JAX_COMPILE_CACHE", "").strip()
    if legacy != "" and not _DEPRECATION_LOGGED:
        _DEPRECATION_LOGGED = True
        (log or _log).warning(
            "fugue_tpu: fugue.jax.compile.cache is deprecated — it now "
            "aliases fugue.optimize.cache.dir (the persistent "
            "compiled-executable cache at %s); set "
            "fugue.optimize.cache.dir directly",
            legacy,
        )
    return legacy


# ---- stable key encoding ----------------------------------------------------
def canonical_key_token(obj: Any) -> Optional[str]:
    """A deterministic, process-stable string for a program key, or None
    when any component is not a stable primitive (such programs simply
    skip the disk tier — the in-memory tiers still serve them)."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return repr(obj)
    if isinstance(obj, float):
        return repr(obj)
    if isinstance(obj, bytes):
        return "b" + obj.hex()
    if isinstance(obj, np.dtype):
        return f"dt:{obj.str}"
    if isinstance(obj, (tuple, list)):
        parts = [canonical_key_token(x) for x in obj]
        if any(p is None for p in parts):
            return None
        return "(" + ",".join(parts) + ")"  # type: ignore[arg-type]
    if isinstance(obj, frozenset):
        parts = [canonical_key_token(x) for x in obj]
        if any(p is None for p in parts):
            return None
        return "{" + ",".join(sorted(parts)) + "}"  # type: ignore[arg-type]
    return None


_FN_HASHES: "Any" = None
_FN_HASH_LOCK = tracked_lock("optimize.exec_cache._FN_HASH_LOCK")


def fn_source_hash(fn: Callable) -> str:
    """Hash of the program function's source (falls back to bytecode):
    a code change that would produce a different program under the same
    logical key invalidates the entry. Memoized per function object
    (weakly — the jit handles keep live programs' fns alive anyway) so
    the ``inspect.getsource`` file I/O runs once per program, not per
    probe/persist."""
    global _FN_HASHES
    import weakref

    table = _FN_HASHES
    if table is not None:
        # lock-free fast path (dict read under the GIL): the steady
        # state of every dispatch must not serialize on a global lock
        try:
            cached = table.get(fn)
        except TypeError:  # unweakrefable callable: compute uncached
            cached = None
        if cached is not None:
            return cached
    with _FN_HASH_LOCK:
        if _FN_HASHES is None:
            _FN_HASHES = weakref.WeakKeyDictionary()
    import hashlib
    import inspect

    try:
        src = inspect.getsource(fn)
    except Exception:
        code = getattr(fn, "__code__", None)
        src = code.co_code.hex() if code is not None else repr(fn)
    digest = hashlib.blake2b(src.encode(), digest_size=16).hexdigest()
    with _FN_HASH_LOCK:
        try:
            _FN_HASHES[fn] = digest
        except TypeError:
            pass
    return digest


_SHARDING_TOKENS: "Any" = None


def _sharding_token(s: Any) -> str:
    # memoized per sharding object: meshes are long-lived and shared by
    # every column of every frame, and repr-ing the device list per
    # LEAF per DISPATCH would dominate the signature cost
    global _SHARDING_TOKENS
    import weakref

    table = _SHARDING_TOKENS
    if table is not None:
        try:
            tok = table.get(s)
            if tok is not None:
                return tok
        except TypeError:
            pass
    try:
        from jax.sharding import NamedSharding

        if isinstance(s, NamedSharding):
            devs = ",".join(str(d) for d in s.mesh.devices.flat)
            tok = f"ns[{devs}]{s.spec}:{s.memory_kind}"
        else:
            tok = repr(s)
    except Exception:  # pragma: no cover - jax API drift
        tok = repr(s)
    try:
        if _SHARDING_TOKENS is None:
            _SHARDING_TOKENS = weakref.WeakKeyDictionary()
        _SHARDING_TOKENS[s] = tok
    except TypeError:  # pragma: no cover - unweakrefable sharding
        pass
    return tok


class ArgsSignature(NamedTuple):
    """One dispatch's argument signature: a stable token (tree structure
    + per-leaf shape/dtype/sharding) and the abstract args a background
    persist can re-lower from without holding any data."""

    token: str
    lower_args: Tuple[Any, ...]


def args_signature(args: Tuple[Any, ...]) -> Optional[ArgsSignature]:
    """Signature of a program's concrete arguments, or None when a leaf
    is not a committed jax array / numpy scalar / python scalar (the
    disk tier then skips this dispatch — correctness never depends on
    it)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts: List[str] = [str(treedef)]
    abstract: List[Any] = []
    for x in leaves:
        if isinstance(x, jax.Array):
            parts.append(
                f"a:{x.shape}:{x.dtype}:{_sharding_token(x.sharding)}"
            )
            abstract.append(
                jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            )
        elif isinstance(x, np.generic):
            arr = np.asarray(x)
            parts.append(f"n:{arr.shape}:{arr.dtype}")
            # value-independent: scalars are dynamic (traced) args
            abstract.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
        elif isinstance(x, (bool, int, float)):
            # python scalars trace weak-typed: keep the live value (it
            # is tiny) so re-lowering reproduces the exact weak dtype
            parts.append(f"p:{type(x).__name__}")
            abstract.append(x)
        else:
            return None
    abstract_args = jax.tree_util.tree_unflatten(treedef, abstract)
    return ArgsSignature("|".join(parts), tuple(abstract_args))


# ---- background warm threads ------------------------------------------------
_WARM_THREADS: List[threading.Thread] = []
_WARM_LOCK = tracked_lock("optimize.exec_cache._WARM_LOCK")


def _join_warm_threads() -> None:
    """atexit: a daemon warm thread frozen MID-DESERIALIZE by interpreter
    teardown aborts the process from XLA's C++ ("terminate called
    without an active exception") — join stragglers first, bounded."""
    with _WARM_LOCK:
        threads = list(_WARM_THREADS)
    for t in threads:
        if t.is_alive():
            t.join(timeout=10.0)


def spawn_warm_thread(target: Callable[[], Any]) -> threading.Thread:
    """Start a background executable-warm thread, registered for the
    bounded atexit join above."""
    import atexit

    t = threading.Thread(target=target, daemon=True, name="fugue-exec-warm")
    with _WARM_LOCK:
        if not _WARM_THREADS:
            atexit.register(_join_warm_threads)
        _WARM_THREADS[:] = [x for x in _WARM_THREADS if x.is_alive()]
        _WARM_THREADS.append(t)
    t.start()
    return t


# ---- background persist worker ----------------------------------------------
_WORKER_LOCK = tracked_lock("optimize.exec_cache._WORKER_LOCK")
_WORKER: Optional[ThreadPoolExecutor] = None
_PENDING: List[Any] = []


def _worker() -> ThreadPoolExecutor:
    global _WORKER
    with _WORKER_LOCK:
        if _WORKER is None:
            _WORKER = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="fugue-exec-cache"
            )
        return _WORKER


def flush_persists(timeout: Optional[float] = 60.0) -> None:
    """Block until every scheduled executable persist finished — the
    test/bench synchronization point (a process about to be measured
    cold must not exit before its cache entries are durable)."""
    while True:
        with _WORKER_LOCK:
            pending = [f for f in _PENDING if not f.done()]
            _PENDING[:] = pending
        if not pending:
            return
        for f in pending:
            f.result(timeout=timeout)


class ExecutableDiskCache:
    """One engine's view of the disk tier (the engine supplies fs,
    metrics, obs spans and its plan signature; entries themselves are
    engine-agnostic and shared through the filesystem)."""

    def __init__(self, engine: Any, base_uri: str):
        self._engine = engine
        self._base = str(base_uri or "").strip().rstrip("/")
        self._dir_ready = False
        # per-program key-token memo (fn hashes memoize module-wide in
        # fn_source_hash): computed once per program, not per dispatch
        self._key_tokens: dict = {}

    @property
    def enabled(self) -> bool:
        return self._base != ""

    @property
    def base_uri(self) -> str:
        return self._base

    # ---- keying ----------------------------------------------------------
    def entry_id(
        self, plan_sig: str, key: Any, fn: Callable, aval_token: str
    ) -> Optional[str]:
        """Deterministic entry id, or None for disk-ineligible keys."""
        try:
            memo = self._key_tokens.get(key, False)
        except TypeError:  # unhashable key: certainly not disk-stable
            return None
        if memo is False:
            memo = canonical_key_token(key)
            self._key_tokens[key] = memo
        if memo is None:
            return None
        from fugue_tpu.utils.hash import to_uuid

        return to_uuid(plan_sig, memo, fn_source_hash(fn), aval_token)

    def entry_uri(self, plan_sig: str, eid: str) -> str:
        # the plan-signature prefix makes warm scans cheap: a daemon
        # pre-warm lists the dir and reads only its own engine's entries
        return self._engine.fs.join(
            self._base, f"{plan_sig[:8]}-{eid}{_SUFFIX}"
        )

    # ---- load ------------------------------------------------------------
    def load(self, uri: str) -> Tuple[str, Optional[Any], Optional[dict]]:
        """Deserialize one entry: ``("hit", compiled, meta)``, or
        ``("miss", None, None)`` when absent, ``("evict", ...)`` on a
        version mismatch, ``("corrupt", ...)`` on an unreadable entry —
        the latter two remove the file so the recompile's fresh persist
        replaces it."""
        import jax
        import jaxlib

        fs = self._engine.fs
        try:
            if not fs.exists(uri):
                return "miss", None, None
            blob = fs.read_bytes(uri)
        except Exception:
            return "miss", None, None
        try:
            if not blob.startswith(_MAGIC):
                raise ValueError("bad magic")
            entry = pickle.loads(blob[len(_MAGIC):])
            meta = entry["meta"]
        except Exception:
            self._evict(uri)
            return "corrupt", None, None
        py = f"{sys.version_info[0]}.{sys.version_info[1]}"
        if (
            meta.get("rev") != FORMAT_REV
            or meta.get("jax") != jax.__version__
            or meta.get("jaxlib") != jaxlib.__version__
            or meta.get("py") != py
        ):
            self._evict(uri)
            return "evict", None, None
        try:
            from jax.experimental import serialize_executable as se

            compiled = se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"]
            )
        except Exception:
            # serialized against a device topology / runtime this
            # process does not have: unusable here, remove it
            self._evict(uri)
            return "corrupt", None, None
        return "hit", compiled, meta

    def _evict(self, uri: str) -> None:
        try:
            self._engine.fs.rm(uri)
        except Exception:  # pragma: no cover - best-effort cleanup
            pass

    def scan(self, plan_sig: Optional[str] = None) -> List[str]:
        """Entry URIs on disk, optionally filtered to one engine
        signature via the filename prefix."""
        fs = self._engine.fs
        try:
            if not fs.exists(self._base):
                return []
            names = fs.listdir(self._base)
        except Exception:
            return []
        prefix = f"{plan_sig[:8]}-" if plan_sig else ""
        return [
            fs.join(self._base, n)
            for n in sorted(names)
            if n.endswith(_SUFFIX) and n.startswith(prefix)
        ]

    # ---- persist ---------------------------------------------------------
    def schedule_persist(
        self,
        jitted: Any,
        plan_sig: str,
        key: Any,
        fn: Callable,
        sig: ArgsSignature,
        name: str,
        on_done: Optional[Callable[[bool], None]] = None,
    ) -> bool:
        """Queue a background persist of the executable this dispatch
        just compiled. Returns False (nothing queued) for disk-ineligible
        keys. Holds only avals + the jit handle, never array data."""
        eid = self.entry_id(plan_sig, key, fn, sig.token)
        if eid is None:
            return False
        uri = self.entry_uri(plan_sig, eid)
        from fugue_tpu.obs import current_span

        parent = current_span()
        fut = _worker().submit(
            self._persist_now, jitted, plan_sig, key,
            fn_source_hash(fn), sig, name, uri, parent, on_done,
        )
        with _WORKER_LOCK:
            # prune settled futures on append: a long-lived daemon
            # schedules persists forever and nothing else may ever call
            # flush_persists
            _PENDING[:] = [f for f in _PENDING if not f.done()]
            _PENDING.append(fut)
        return True

    def _persist_now(
        self,
        jitted: Any,
        plan_sig: str,
        key: Any,
        fn_hash: str,
        sig: ArgsSignature,
        name: str,
        uri: str,
        parent_span: Any,
        on_done: Optional[Callable[[bool], None]],
    ) -> None:
        import jax
        import jaxlib

        from fugue_tpu.obs import activate, start_span
        from fugue_tpu.testing.faults import fault_point

        ok = False
        try:
            with activate(parent_span):
                with start_span("cache.persist", program=name, uri=uri):
                    # re-lower from avals: hits jax's in-memory lowering/
                    # compilation caches right after the jit dispatch
                    # compiled, so this is cheap and holds no data
                    compiled = jitted.lower(*sig.lower_args).compile()
                    from jax.experimental import serialize_executable as se

                    payload, in_tree, out_tree = se.serialize(compiled)
                    entry = {
                        "meta": {
                            "rev": FORMAT_REV,
                            "jax": jax.__version__,
                            "jaxlib": jaxlib.__version__,
                            "py": (
                                f"{sys.version_info[0]}."
                                f"{sys.version_info[1]}"
                            ),
                            "plan_sig": plan_sig,
                            "key": key,
                            # folded into the filename uuid AND stored
                            # here: the warm scan must register entries
                            # under the same fn-aware in-memory key the
                            # dispatch path computes, or a source change
                            # could serve a stale warm-loaded executable
                            "fn_hash": fn_hash,
                            "aval_token": sig.token,
                            "program": name,
                            "created_at": time.time(),
                        },
                        "payload": payload,
                        "in_tree": in_tree,
                        "out_tree": out_tree,
                    }
                    blob = _MAGIC + pickle.dumps(entry)
                    fs = self._engine.fs
                    if not self._dir_ready:
                        fs.makedirs(self._base, exist_ok=True)
                        self._dir_ready = True
                    fault_point("cache.persist", uri)
                    fs.write_file_atomic(uri, lambda fp: fp.write(blob))
                    ok = True
        except Exception as ex:
            # a failing persist degrades warm starts, never this run
            (getattr(self._engine, "log", None) or _log).warning(
                "fugue_tpu exec-cache: persisting %s to %s failed "
                "(%s: %s); execution unaffected",
                name, uri, type(ex).__name__, ex,
            )
        finally:
            if on_done is not None:
                try:
                    on_done(ok)
                except Exception:  # pragma: no cover - counter callback
                    pass
