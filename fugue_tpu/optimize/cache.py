"""The process-wide plan & result cache (ISSUE 10).

One :class:`PlanCache` instance outlives every engine in the process:

- **program handles** — ``JaxExecutionEngine._jit_cached`` keys every
  logical program by structure; the plan cache stores the underlying
  ``jax.jit`` handle under (engine signature, program key) so a FRESH
  engine (a new ``run()``, a restarted bench loop) reuses the already
  compiled executables instead of paying XLA compilation again. The
  engine signature folds the platform, the mesh's device ids and every
  ``fugue.jax.*`` conf value, so engines with different kernel-selection
  conf never share a slot.
- **result entries** — deterministically-checkpointed task artifacts
  (the loaded dataframe is served from memory while the artifact still
  exists, skipping the parquet decode) and serving-daemon query payloads
  (keyed by session id + catalog epoch + the DAG's deterministic uuid).

Eviction is LRU, bounded by entry count and by total result bytes; for
governed engines (PR 4 HBM ledger) the byte bound additionally clamps to
a fraction of the device-memory budget so cached device frames can never
crowd out live working sets. Hit/miss counters surface on the PR 8
metrics registry (``fugue_engine_plan_cache_total``,
``fugue_serve_result_cache_total``) and in ``/v1/status``.

Since ISSUE 11 the cache also fronts a DISK tier
(:mod:`fugue_tpu.optimize.exec_cache`): per-shape AOT-compiled
executables loaded from ``fugue.optimize.cache.dir`` live in
:meth:`PlanCache.get_executable`/``put_executable`` (LRU under the same
program bound), ``mark_compiled`` records shapes the jit path owns so
the disk is probed at most once per shape, and ``claim_warm`` makes the
per-plan-signature bulk warm (daemon pre-warm, streamed-ingest
first-batch warm) run once per process.
"""

from collections import OrderedDict
from typing import Any, Dict, Optional

from fugue_tpu.testing.locktrace import tracked_lock

_DEFAULT_MAX_PROGRAMS = 512
_DEFAULT_MAX_ENTRIES = 256
_DEFAULT_MAX_RESULT_BYTES = 256 * 1024 * 1024
# governed engines: cached results may pin at most this fraction of the
# device-memory budget (the PR 4 ledger's admission bound)
_GOVERNED_RESULT_FRACTION = 0.25


class PlanCache:
    """Thread-safe LRU cache of compiled program handles and result
    entries, shared process-wide (see :func:`get_plan_cache`)."""

    def __init__(
        self,
        max_programs: int = _DEFAULT_MAX_PROGRAMS,
        max_entries: int = _DEFAULT_MAX_ENTRIES,
        max_result_bytes: int = _DEFAULT_MAX_RESULT_BYTES,
    ):
        self._lock = tracked_lock(
            "optimize.cache.PlanCache._lock", reentrant=True
        )
        self._max_programs = max_programs
        self._max_entries = max_entries
        self._max_result_bytes = max_result_bytes
        self._programs: "OrderedDict[Any, Any]" = OrderedDict()
        # (global program key, aval token) -> AOT-compiled executable
        # loaded from the DISK tier (exec_cache.py); dispatches for these
        # shapes run the deserialized executable and never touch XLA
        self._executables: "OrderedDict[Any, Any]" = OrderedDict()
        # shapes this process compiled via the jit path (LRU-bounded):
        # no point probing the disk again — the jit handle owns them
        self._compiled_shapes: "OrderedDict[Any, None]" = OrderedDict()
        # plan signatures a full disk warm already ran for (daemon
        # pre-warm / streamed-ingest first-batch warm fire once each)
        self._warmed_sigs: set = set()
        # key -> (value, nbytes, tag)
        self._results: "OrderedDict[Any, Any]" = OrderedDict()
        self._result_bytes = 0
        self.program_hits = 0
        self.program_misses = 0
        self.result_hits = 0
        self.result_misses = 0
        self.evictions = 0

    def configure(self, conf: Any) -> None:
        """Adopt the cache bounds from a conf mapping (engines call this
        at construction; the tightest explicit setting wins last)."""
        from fugue_tpu.constants import (
            FUGUE_CONF_OPTIMIZE_CACHE_MAX_ENTRIES,
            FUGUE_CONF_OPTIMIZE_CACHE_MAX_PROGRAMS,
            FUGUE_CONF_OPTIMIZE_CACHE_MAX_RESULT_BYTES,
            typed_conf_get,
        )

        with self._lock:
            self._max_programs = int(
                typed_conf_get(conf, FUGUE_CONF_OPTIMIZE_CACHE_MAX_PROGRAMS)
            )
            self._max_entries = int(
                typed_conf_get(conf, FUGUE_CONF_OPTIMIZE_CACHE_MAX_ENTRIES)
            )
            self._max_result_bytes = int(
                typed_conf_get(conf, FUGUE_CONF_OPTIMIZE_CACHE_MAX_RESULT_BYTES)
            )

    # ---- program handles -------------------------------------------------
    def get_program(self, key: Any) -> Optional[Any]:
        with self._lock:
            handle = self._programs.get(key)
            if handle is None:
                self.program_misses += 1
                return None
            self._programs.move_to_end(key)
            self.program_hits += 1
            return handle

    def put_program(self, key: Any, handle: Any) -> None:
        with self._lock:
            self._programs[key] = handle
            self._programs.move_to_end(key)
            while len(self._programs) > max(1, self._max_programs):
                self._programs.popitem(last=False)
                self.evictions += 1

    # ---- AOT executables (disk-tier shapes) ------------------------------
    def get_executable(self, key: Any) -> Optional[Any]:
        with self._lock:
            c = self._executables.get(key)
            if c is not None:
                self._executables.move_to_end(key)
            return c

    def put_executable(self, key: Any, compiled: Any) -> None:
        with self._lock:
            self._executables[key] = compiled
            self._executables.move_to_end(key)
            while len(self._executables) > max(1, self._max_programs):
                self._executables.popitem(last=False)
                self.evictions += 1

    def drop_executable(self, key: Any) -> None:
        with self._lock:
            self._executables.pop(key, None)

    def mark_compiled(self, key: Any) -> None:
        """This process jit-compiled the shape: later dispatches skip
        the disk probe (the jit handle's own cache serves them)."""
        with self._lock:
            self._compiled_shapes[key] = None
            self._compiled_shapes.move_to_end(key)
            while len(self._compiled_shapes) > max(4, 4 * self._max_programs):
                self._compiled_shapes.popitem(last=False)

    def was_compiled(self, key: Any) -> bool:
        with self._lock:
            return key in self._compiled_shapes

    def claim_warm(self, claim_key: Any) -> bool:
        """True exactly once per (cache dir, plan signature) — the
        caller owning the claim runs the full disk warm for it."""
        with self._lock:
            if claim_key in self._warmed_sigs:
                return False
            self._warmed_sigs.add(claim_key)
            return True

    # ---- result entries --------------------------------------------------
    def get_result(self, key: Any) -> Optional[Any]:
        with self._lock:
            entry = self._results.get(key)
            if entry is None:
                self.result_misses += 1
                return None
            self._results.move_to_end(key)
            self.result_hits += 1
            return entry[0]

    def put_result(
        self,
        key: Any,
        value: Any,
        nbytes: int,
        tag: Optional[str] = None,
        byte_cap: Optional[int] = None,
    ) -> bool:
        """Insert a result entry, evicting LRU entries past the entry
        and byte bounds. An entry alone larger than the byte cap is
        refused (never cached) rather than evicting everything else."""
        nbytes = max(0, int(nbytes))
        cap = self._max_result_bytes if byte_cap is None else min(
            self._max_result_bytes, int(byte_cap)
        )
        if nbytes > cap > 0:
            return False
        with self._lock:
            old = self._results.pop(key, None)
            if old is not None:
                self._result_bytes -= old[1]
            self._results[key] = (value, nbytes, tag)
            self._result_bytes += nbytes
            while self._results and (
                len(self._results) > max(1, self._max_entries)
                or (cap > 0 and self._result_bytes > cap)
            ):
                _, (_, evicted_bytes, _) = self._results.popitem(last=False)
                self._result_bytes -= evicted_bytes
                self.evictions += 1
            return True

    def drop_result(self, key: Any) -> None:
        with self._lock:
            entry = self._results.pop(key, None)
            if entry is not None:
                self._result_bytes -= entry[1]

    def invalidate_tag(self, tag: str) -> int:
        """Drop every result entry carrying ``tag`` (a serving session
        closing drops its payload entries); returns the dropped count."""
        with self._lock:
            dead = [k for k, (_, _, t) in self._results.items() if t == tag]
            for k in dead:
                _, nbytes, _ = self._results.pop(k)
                self._result_bytes -= nbytes
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()
            self._executables.clear()
            self._compiled_shapes.clear()
            self._warmed_sigs.clear()
            self._results.clear()
            self._result_bytes = 0

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "programs": len(self._programs),
                "executables": len(self._executables),
                "program_hits": self.program_hits,
                "program_misses": self.program_misses,
                "results": len(self._results),
                "result_bytes": self._result_bytes,
                "result_hits": self.result_hits,
                "result_misses": self.result_misses,
                "evictions": self.evictions,
            }


_PLAN_CACHE = PlanCache()


def get_plan_cache() -> PlanCache:
    """The process-wide plan cache singleton."""
    return _PLAN_CACHE


# ---- engine signature -------------------------------------------------------
def engine_plan_signature(engine: Any) -> str:
    """Deterministic signature under which an engine's compiled programs
    may be shared process-wide: platform + mesh device ids + every
    ``fugue.jax.*`` conf value (kernel-selection conf changes programs,
    so differing conf must never share a slot)."""
    from fugue_tpu.constants import FUGUE_CONF_JAX_COMPILE_CACHE
    from fugue_tpu.utils.hash import to_uuid

    try:
        devices = tuple(
            str(d) for d in getattr(engine.mesh, "devices").flat
        )
    except Exception:  # pragma: no cover - defensive
        devices = ()
    conf_items = sorted(
        (k, str(v))
        for k, v in dict(engine.conf).items()
        if isinstance(k, str)
        and k.startswith("fugue.jax.")
        # the deprecated disk-cache ALIAS names where executables are
        # stored, not what they compute: folding it would split one
        # shared cache into disjoint per-spelling namespaces
        and k != FUGUE_CONF_JAX_COMPILE_CACHE
    )
    return to_uuid(type(engine).__name__, devices, conf_items)


# ---- deterministic-checkpoint task results ----------------------------------
def _estimate_frame_bytes(df: Any) -> int:
    try:
        blocks = getattr(df, "native", None)
        if blocks is not None:
            from fugue_tpu.jax_backend.blocks import device_nbytes

            return int(device_nbytes(blocks))
    except Exception:  # pragma: no cover - estimator best-effort
        pass
    try:
        n = int(df.count())
        return max(1, n) * max(1, len(df.schema)) * 16
    except Exception:  # pragma: no cover
        return 1 << 20


def _governed_byte_cap(engine: Any) -> Optional[int]:
    mem = getattr(engine, "memory_stats", None)
    if not isinstance(mem, dict) or not mem.get("enabled"):
        return None
    budget = int(mem.get("budget_bytes") or 0)
    if budget <= 0:
        return None
    return int(budget * _GOVERNED_RESULT_FRACTION)


def task_result_cache_enabled(engine: Any) -> bool:
    """The ``fugue.optimize.result_cache`` gate for in-memory reuse of
    deterministically-checkpointed task artifacts (default off: the
    artifact itself already provides cross-run reuse; the memory tier is
    an opt-in for hot repeated pipelines)."""
    from fugue_tpu.constants import (
        FUGUE_CONF_OPTIMIZE_RESULT_CACHE,
        typed_conf_get,
    )

    try:
        return bool(
            typed_conf_get(engine.conf, FUGUE_CONF_OPTIMIZE_RESULT_CACHE)
        )
    except Exception:  # pragma: no cover - conf-less engine stub
        return False


def _task_result_key(task: Any, ctx: Any, uri: str) -> Any:
    # fold the engine's plan signature (platform + mesh devices +
    # fugue.jax.* conf) like the program cache does: a cached frame's
    # blocks are sharded on a specific mesh, and serving them to a
    # different-mesh/conf engine would hand it misplaced device state
    engine = ctx.engine
    sig = getattr(engine, "_plan_sig", None) or type(engine).__name__
    return ("task", sig, task.__uuid__(), uri)


def get_task_result(task: Any, ctx: Any) -> Optional[Any]:
    """In-memory hit for a deterministically-checkpointed task: serves
    the previously loaded dataframe while the artifact still exists
    (existence is re-verified so a cleaned checkpoint dir invalidates
    the memory entry exactly like it invalidates the artifact)."""
    cp = task.checkpoint
    if not getattr(cp, "deterministic", False):
        return None
    uri = cp.artifact_uri(ctx.checkpoint_path)
    if uri is None:
        return None
    cache = get_plan_cache()
    key = _task_result_key(task, ctx, uri)
    df = cache.get_result(key)
    if df is None:
        return None
    try:
        exists = ctx.checkpoint_path.file_exists(uri)
    except Exception:  # pragma: no cover - fs hiccup: treat as gone
        exists = False
    if not exists:
        cache.drop_result(key)
        return None
    yielded = getattr(cp, "yielded", None)
    if yielded is not None:
        yielded.set_value(uri)
    return df


def put_task_result(task: Any, ctx: Any, df: Any) -> None:
    cp = task.checkpoint
    if not getattr(cp, "deterministic", False):
        return
    uri = cp.artifact_uri(ctx.checkpoint_path)
    if uri is None:
        return
    get_plan_cache().put_result(
        _task_result_key(task, ctx, uri),
        df,
        _estimate_frame_bytes(df),
        byte_cap=_governed_byte_cap(ctx.engine),
    )
