"""Cost-based DAG optimizer (ISSUE 10): the rewrite phase that runs
between ``schema_pass.propagate`` and execution, plus the process-wide
plan & result cache shared across workflow runs and serving-daemon
sessions.

- :mod:`fugue_tpu.optimize.rewrite` — rule-driven task-graph rewrites
  (projection pushdown, filter pushdown + parquet row-group pruning,
  select/rename/filter chain fusion, common-subplan elimination) over a
  CLONED task list whose uuids are pinned to the original tasks, so
  rewrites never change the task identities deterministic checkpoints
  and manifest resume key on.
- :mod:`fugue_tpu.optimize.cache` — the process-wide
  :class:`~fugue_tpu.optimize.cache.PlanCache`: compiled jit program
  handles keyed by (engine signature, program key) shared across engine
  instances, plus result entries (deterministically-checkpointed task
  artifacts, serving-daemon query payloads) with LRU eviction bounded
  by entry count and bytes.
"""

from fugue_tpu.optimize.cache import PlanCache, get_plan_cache
from fugue_tpu.optimize.rewrite import (
    OptimizedPlan,
    RewriteNote,
    optimize_enabled,
    optimize_tasks,
)

__all__ = [
    "OptimizedPlan",
    "PlanCache",
    "RewriteNote",
    "get_plan_cache",
    "optimize_enabled",
    "optimize_tasks",
]
