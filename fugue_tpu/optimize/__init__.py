"""Cost-based DAG optimizer (ISSUE 10): the rewrite phase that runs
between ``schema_pass.propagate`` and execution, plus the process-wide
plan & result cache shared across workflow runs and serving-daemon
sessions.

- :mod:`fugue_tpu.optimize.rewrite` — rule-driven task-graph rewrites
  (projection pushdown, filter pushdown + parquet row-group pruning,
  select/rename/filter chain fusion, common-subplan elimination) over a
  CLONED task list whose uuids are pinned to the original tasks, so
  rewrites never change the task identities deterministic checkpoints
  and manifest resume key on.
- :mod:`fugue_tpu.optimize.cache` — the process-wide
  :class:`~fugue_tpu.optimize.cache.PlanCache`: compiled jit program
  handles keyed by (engine signature, program key) shared across engine
  instances, plus result entries (deterministically-checkpointed task
  artifacts, serving-daemon query payloads) with LRU eviction bounded
  by entry count and bytes.
- :mod:`fugue_tpu.optimize.exec_cache` — the plan cache's DISK tier
  (ISSUE 11): AOT-serialized compiled executables persisted through
  ``engine.fs`` under ``fugue.optimize.cache.dir``, keyed by the plan
  signature + program key + fn source hash + argument avals, so a
  FRESH PROCESS skips XLA compilation entirely.
"""

from fugue_tpu.optimize.cache import PlanCache, get_plan_cache
from fugue_tpu.optimize.exec_cache import (
    ExecutableDiskCache,
    flush_persists,
    resolve_cache_dir,
)
from fugue_tpu.optimize.rewrite import (
    OptimizedPlan,
    RewriteNote,
    optimize_enabled,
    optimize_tasks,
)

__all__ = [
    "ExecutableDiskCache",
    "OptimizedPlan",
    "PlanCache",
    "RewriteNote",
    "flush_persists",
    "get_plan_cache",
    "optimize_enabled",
    "optimize_tasks",
    "resolve_cache_dir",
]
