"""Rule-driven rewrites of a built (unexecuted) workflow task graph.

The optimizer runs inside ``FugueWorkflow.run`` after the static
analysis gate and before the DAG runner, gated by conf ``fugue.optimize``
(``auto`` — the default — enables it for jax engines only). It never
mutates the user's workflow: the task list is CLONED and every clone's
uuid is pinned to its source task BEFORE any rewrite, so deterministic
checkpoints, manifest resume and the plan cache keep seeing the exact
identities the unoptimized DAG would produce.

Rules, in application order:

- **common-subplan elimination** (``fugue.optimize.cse``) — the
  deterministic task uuids already identify structurally identical
  subtrees; duplicates whose whole upstream cone is deterministic
  execute once and fan out.
- **filter pushdown** (``fugue.optimize.filter_pushdown``) — a filter
  sinks below select/rename/drop projections (with expression column
  remapping), and a predicate that lands directly on a parquet load
  attaches conjunctive ``(col, op, literal)`` pruning triples the
  streamed ingest checks against parquet row-group statistics (pruning
  is advisory: the filter still runs, so partial/ignored pruning is
  always correct).
- **chain fusion** (``fugue.optimize.fusion``) — maximal
  select/rename/filter/drop chains collapse into ONE select (projection
  + combined ``where``) so the engine dispatches one compiled program
  instead of N.
- **projection pushdown** (``fugue.optimize.projection_pushdown``) —
  each task's downstream-required column set is threaded backward
  through filter/select/rename/join/aggregate edges into the parquet
  load's ``columns`` spec, so the streamed ingest's narrow-load planner
  (and the eager reader) never decode or stage columns no consumer
  needs. Columns are only dropped when EVERY path to an externally
  observable point (output task, yield, deterministic checkpoint,
  opaque extension) provably ignores them.
"""

import copy
from typing import Any, Dict, Iterator, List, Optional, Tuple

from fugue_tpu.analysis.schema_pass import SchemaInfo, expr_columns, propagate
from fugue_tpu.collections.partition import parse_presort_exp
from fugue_tpu.column.expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
    col,
)
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.extensions import builtins as _b
from fugue_tpu.utils.hash import to_uuid
from fugue_tpu.utils.params import ParamDict
from fugue_tpu.workflow.checkpoint import WeakCheckpoint
from fugue_tpu.workflow.tasks import CreateTask, FugueTask, OutputTask, ProcessTask

# rule slugs (stable: conf keys, FWF501 messages and tests key on them)
RULE_CSE = "cse"
RULE_FILTER_PUSHDOWN = "filter_pushdown"
RULE_FUSION = "fusion"
RULE_PROJECTION = "projection_pushdown"

# builtins whose output is a pure function of their spec + inputs: safe
# to deduplicate (CSE) and to serve from a result cache. User
# transformers/processors/creators and writers are deliberately absent —
# uuid equality is SPEC equality, not value determinism, for user code.
_PURE_EXTENSIONS = (
    _b.CreateData,
    _b.Load,
    _b.RunJoin,
    _b.RunSetOperation,
    _b.Distinct,
    _b.Dropna,
    _b.Fillna,
    _b.RunSQLSelect,
    _b.Select,
    _b.Filter,
    _b.Assign,
    _b.Aggregate,
    _b.Rename,
    _b.AlterColumns,
    _b.DropColumns,
    _b.SelectColumnsP,
    _b.Take,
)


class RewriteNote:
    """One applied or declined rewrite, with the offending task's name
    and user callsite (the same attribution diagnostics carry)."""

    __slots__ = ("rule", "applied", "message", "task_name", "callsite")

    def __init__(self, rule: str, applied: bool, message: str, task: Any = None):
        self.rule = rule
        self.applied = applied
        self.message = message
        self.task_name = getattr(task, "name", "") if task is not None else ""
        self.callsite = list(getattr(task, "callsite", None) or [])

    def describe(self) -> str:
        verb = "applied" if self.applied else "declined"
        head = f"{self.rule} {verb}"
        if self.task_name:
            head += f" [task {self.task_name}]"
        return f"{head}: {self.message}"

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"RewriteNote({self.describe()})"


class OptimizedPlan:
    """The rewrite phase's output: the (possibly rewritten) task list in
    dependency order plus the notes of every rule decision."""

    __slots__ = ("tasks", "notes")

    def __init__(self, tasks: List[FugueTask], notes: List[RewriteNote]):
        self.tasks = tasks
        self.notes = notes

    @property
    def applied(self) -> List[RewriteNote]:
        return [n for n in self.notes if n.applied]


# the one vocabulary of disabling fugue.optimize values (FWF501 and the
# run() gate must never drift apart on what counts as "off")
OFF_VALUES = ("off", "false", "0", "none", "")


def optimize_enabled(conf: Any, engine: Any = None) -> bool:
    """The ``fugue.optimize`` gate: ``auto`` (default) enables the
    rewrite phase for jax engines only; ``on`` forces it for any engine,
    ``off`` disables it. Unknown values raise — a gate the user asked
    for must not silently degrade."""
    from fugue_tpu.analysis.analyzer import _is_jax_engine
    from fugue_tpu.constants import FUGUE_CONF_OPTIMIZE, conf_default

    raw = str(
        (conf or {}).get(FUGUE_CONF_OPTIMIZE, conf_default(FUGUE_CONF_OPTIMIZE))
        if conf is not None
        else conf_default(FUGUE_CONF_OPTIMIZE)
    ).strip().lower()
    if raw in OFF_VALUES:
        return False
    if raw in ("on", "true", "1"):
        return True
    if raw == "auto":
        return _is_jax_engine(engine)
    raise ValueError(
        f"invalid {FUGUE_CONF_OPTIMIZE} mode {raw!r}: expected off | on | auto"
    )


def _rule_enabled(conf: Any, rule: str) -> bool:
    from fugue_tpu.constants import typed_conf_get

    return bool(typed_conf_get(conf or {}, f"fugue.optimize.{rule}"))


def _value_hashable(obj: Any, depth: int = 0) -> bool:
    """Whether a raw CreateData payload hashes by VALUE through
    ``to_uuid`` (plain scalars and nested lists/tuples of them). Frame
    objects (and numpy arrays) hash by schema/truncated repr only, so
    two different datasets can share a uuid — never value-stable."""
    if obj is None or isinstance(obj, (str, int, float, bool, bytes)):
        return True
    if depth > 6:
        return False
    if isinstance(obj, (list, tuple)):
        return all(_value_hashable(x, depth + 1) for x in obj)
    return False


def is_pure_task(task: FugueTask, frame_inputs_stable: bool = False) -> bool:
    """True when the task's output is a pure, VALUE-deterministic
    function of its uuid + inputs (``Sample`` counts only when seeded).
    ``CreateData`` wrapping a dataframe object is excluded — dataframes
    hash by schema repr, so uuid equality does not imply equal data —
    unless the caller vouches for frame stability
    (``frame_inputs_stable``: the serving daemon's session tables only
    change through ``save_table``, which bumps the cache epoch in the
    key)."""
    ext = task.extension
    if ext is _b.Sample:
        return task.params.get("seed", None) is not None
    if ext is _b.CreateData:
        data = task.params.get("data", None)
        if _value_hashable(data):
            return True
        if not frame_inputs_stable:
            return False
        from fugue_tpu.dataframe import DataFrame

        return isinstance(data, DataFrame)
    return any(ext is p for p in _PURE_EXTENSIONS)


def _is_pinned_lake_load(task: FugueTask) -> bool:
    """A ``lake://`` load pinned to an explicit VERSION reads a
    write-once manifest: the snapshot can never change under the same
    key, so it is safe for a cross-request result cache. Timestamp pins
    stay uncacheable — their resolution depends on commit-clock
    monotonicity the format does not promise."""
    if task.extension is not _b.Load:
        return False
    path = task.params.get("path", None)
    if isinstance(path, (list, tuple)):
        path = path[0] if path else None
    if not isinstance(path, str):
        return False
    from fugue_tpu.lake.format import is_lake_uri, parse_lake_uri

    if not is_lake_uri(path):
        return False
    try:
        _, pin = parse_lake_uri(path)
    except Exception:
        return False
    params = dict(task.params.get("params", None) or {})
    if "timestamp" in params or "timestamp" in pin:
        return False
    return "version" in params or "version" in pin


def tasks_are_pure(
    tasks: List[FugueTask], frame_inputs_stable: bool = False
) -> bool:
    """True when EVERY task in the list is a pure builtin and none is an
    output task — the eligibility check the serving daemon's
    cross-request result cache uses (a cached payload must not skip side
    effects). ``Load`` is rejected here even though CSE treats it as
    pure WITHIN one run: a cross-request cache keyed by task uuid would
    keep serving stale rows after the external file changes on disk
    (file content is not epoch-tracked the way session tables are). The
    one exception is a version-pinned ``lake://`` load (``AS OF <v>``):
    the pinned snapshot is immutable by construction."""
    return all(
        is_pure_task(t, frame_inputs_stable)
        and not isinstance(t, OutputTask)
        and (t.extension is not _b.Load or _is_pinned_lake_load(t))
        for t in tasks
    )


def _observable(task: FugueTask) -> bool:
    """Whether the task's FULL output is externally observable: yields,
    durable (deterministic) checkpoint artifacts, or a broadcast handle.
    Rewrites must never change what an observable point sees."""
    if task.yields or task.broadcast_result:
        return True
    cp = task.checkpoint
    return not cp.is_null and not isinstance(cp, WeakCheckpoint)


def _rewirable(task: FugueTask) -> bool:
    """An intermediate node a rewrite may restructure: not observable,
    no checkpoint of any kind, no partition hints riding on it."""
    return (
        not _observable(task)
        and task.checkpoint.is_null
        and not task.partition_spec.partition_by
        and len(task.partition_spec.presort) == 0
    )


# ---- clone machinery --------------------------------------------------------
def _clone_tasks(tasks: List[FugueTask]) -> List[FugueTask]:
    """Shallow-clone the task graph with every clone's uuid PINNED to
    its source task's uuid (computed from the pristine spec) so no later
    param/input edit can change the identities checkpoints key on."""
    mapping: Dict[int, FugueTask] = {}
    out: List[FugueTask] = []
    for t in tasks:
        c = copy.copy(t)
        c._uuid = t.__uuid__()  # pin BEFORE any rewrite edits the spec
        c.params = ParamDict(dict(t.params))
        c.inputs = [mapping[id(i)] for i in t.inputs]
        mapping[id(t)] = c
        out.append(c)
    return out


def _synthetic(
    template_cls: type,
    extension: Any,
    params: Dict[str, Any],
    inputs: List[FugueTask],
    uuid: str,
    like: Optional[FugueTask] = None,
) -> FugueTask:
    """Build a rewrite-created task with an explicit (deterministic)
    uuid. ``like`` transfers the observable surface of the task the new
    node REPLACES: checkpoint, yields, broadcast, fault policy, callsite
    and partition spec — and its uuid wins, because the replacement
    produces the exact frame the replaced task would have."""
    task = template_cls(extension, params=params, input_tasks=inputs)
    task._uuid = uuid
    if like is not None:
        task._uuid = like.__uuid__()
        task.checkpoint = like.checkpoint
        task.yields = like.yields
        task.yield_as_local = like.yield_as_local
        task.broadcast_result = like.broadcast_result
        task.fault_override = like.fault_override
        task.callsite = like.callsite
        task.partition_spec = like.partition_spec
    return task


def _consumers(tasks: List[FugueTask]) -> Dict[int, List[FugueTask]]:
    out: Dict[int, List[FugueTask]] = {id(t): [] for t in tasks}
    for t in tasks:
        for i in t.inputs:
            out.setdefault(id(i), []).append(t)
    return out


def _rewire(tasks: List[FugueTask], old: FugueTask, new: FugueTask) -> None:
    for t in tasks:
        if any(i is old for i in t.inputs):
            t.inputs = [new if i is old else i for i in t.inputs]


# ---- expression helpers -----------------------------------------------------
def _conjuncts(expr: Any) -> Iterator[ColumnExpr]:
    """Top-level AND conjuncts of a condition tree."""
    if (
        isinstance(expr, _BinaryOpExpr)
        and expr.op == "&"
        and expr.as_type is None
        and expr.as_name == ""
    ):
        yield from _conjuncts(expr.left)
        yield from _conjuncts(expr.right)
    elif isinstance(expr, ColumnExpr):
        yield expr


def _and_all(conds: List[ColumnExpr]) -> ColumnExpr:
    out = conds[0]
    for c in conds[1:]:
        out = out & c
    return out


def rename_expr_columns(
    expr: Any, name_map: Dict[str, str]
) -> Optional[ColumnExpr]:
    """Rebuild an expression tree with every named column reference
    renamed through ``name_map`` (identity for unmapped names). Returns
    None when the tree holds nodes that can't be safely rebuilt
    (wildcards, unknown classes) — callers decline the rewrite then."""
    if not isinstance(expr, ColumnExpr):
        return None
    out: Optional[ColumnExpr]
    if isinstance(expr, _NamedColumnExpr):
        if expr.wildcard:
            return None
        out = col(name_map.get(expr.name, expr.name))
    elif isinstance(expr, _LitColumnExpr):
        return expr
    elif isinstance(expr, _UnaryOpExpr):
        c = rename_expr_columns(expr.col, name_map)
        if c is None:
            return None
        out = _UnaryOpExpr(expr.op, c)
    elif isinstance(expr, _BinaryOpExpr):
        left = rename_expr_columns(expr.left, name_map)
        right = rename_expr_columns(expr.right, name_map)
        if left is None or right is None:
            return None
        out = _BinaryOpExpr(expr.op, left, right)
    elif isinstance(expr, _FuncExpr):
        args = [rename_expr_columns(a, name_map) for a in expr.args]
        if any(a is None for a in args):
            return None
        out = _FuncExpr(
            expr.func,
            *args,
            arg_distinct=expr.arg_distinct,
            is_aggregation=expr.is_aggregation,
        )
    else:
        return None
    out._as_name = expr.as_name
    out._as_type = expr.as_type
    return out


def extract_pruning_triples(cond: Any) -> List[List[Any]]:
    """Conjunctive ``[col, op, literal]`` comparisons usable for parquet
    row-group pruning: pruning with ANY subset of a conjunction is
    sound, so non-comparison conjuncts are simply skipped."""
    triples: List[List[Any]] = []
    _FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}
    for c in _conjuncts(cond):
        if not isinstance(c, _BinaryOpExpr) or c.as_type is not None:
            continue
        if c.op not in ("<", "<=", ">", ">=", "=="):
            continue
        left, right = c.left, c.right

        def _named(e: Any) -> Optional[str]:
            if (
                isinstance(e, _NamedColumnExpr)
                and not e.wildcard
                and e.as_type is None
            ):
                return e.name
            return None

        def _num(e: Any) -> Optional[Any]:
            if isinstance(e, _LitColumnExpr) and isinstance(
                e.value, (int, float)
            ) and not isinstance(e.value, bool):
                return e.value
            return None

        name, value = _named(left), _num(right)
        if name is not None and value is not None:
            triples.append([name, c.op, value])
            continue
        name, value = _named(right), _num(left)
        if name is not None and value is not None:
            triples.append([name, _FLIP[c.op], value])
    return triples


# ---- rule: common-subplan elimination ---------------------------------------
def _cse(
    tasks: List[FugueTask], notes: List[RewriteNote]
) -> List[FugueTask]:
    kept_by_uuid: Dict[str, FugueTask] = {}
    replacement: Dict[int, FugueTask] = {}
    deterministic: Dict[int, bool] = {}
    out: List[FugueTask] = []
    for t in tasks:
        if replacement:
            t.inputs = [replacement.get(id(i), i) for i in t.inputs]
        det = is_pure_task(t) and all(
            deterministic.get(id(i), False) for i in t.inputs
        )
        deterministic[id(t)] = det
        if det and not isinstance(t, OutputTask):
            key = t.__uuid__()
            kept = kept_by_uuid.get(key)
            if (
                kept is not None
                and t.checkpoint.is_null
                and not t.yields
                and not t.broadcast_result
            ):
                replacement[id(t)] = kept
                notes.append(
                    RewriteNote(
                        RULE_CSE,
                        True,
                        f"duplicate subplan folded into task {kept.name} "
                        f"(identical uuid {key[:8]})",
                        t,
                    )
                )
                continue
            kept_by_uuid.setdefault(key, t)
        out.append(t)
    return out


# ---- rule: filter pushdown + row-group pruning ------------------------------
def _pure_projection_map(task: FugueTask) -> Optional[Dict[str, str]]:
    """output name -> input name for projections a filter can cross:
    Rename, DropColumns, SelectColumnsP and simple Selects whose
    entries are plain (un-cast) named columns. None = not crossable."""
    ext = task.extension
    p = task.params
    if ext is _b.Rename:
        columns = p.get("columns", None) or {}
        return {v: k for k, v in columns.items()}
    if ext is _b.DropColumns or ext is _b.SelectColumnsP:
        return {}  # names pass through unchanged
    if ext is _b.Select:
        cols = p.get("columns", None)
        if (
            cols is None
            or p.get("having", None) is not None
            or cols.is_distinct
            or cols.has_agg
        ):
            return None
        out: Dict[str, str] = {}
        for c in cols.all_cols:
            if (
                not isinstance(c, _NamedColumnExpr)
                or c.wildcard
                or c.as_type is not None
                or c.output_name == ""
            ):
                return None
            out[c.output_name] = c.name
        return out
    return None


def _filter_refs_survive(task: FugueTask, cond_cols: List[str]) -> bool:
    """After the swap the projection's REFERENCED inputs must still
    exist; for DropColumns the filter may not reference dropped
    columns, for SelectColumnsP the condition columns must be selected
    ones, and for Rename a condition column that is a rename KEY (an
    old name that is not also someone's new name) does not exist in the
    filter's input — the unoptimized run errors there, and the rewrite
    must not silently legitimize it."""
    ext = task.extension
    p = task.params
    if ext is _b.SelectColumnsP:
        names = set(p.get("columns", None) or [])
        return all(c in names for c in cond_cols)
    if ext is _b.DropColumns:
        dropped = set(p.get("columns", None) or [])
        return not any(c in dropped for c in cond_cols)
    if ext is _b.Rename:
        columns = p.get("columns", None) or {}
        shadowed = set(columns.keys()) - set(columns.values())
        return not any(c in shadowed for c in cond_cols)
    return True


def _filter_pushdown(
    tasks: List[FugueTask], conf: Any, notes: List[RewriteNote]
) -> List[FugueTask]:
    changed = True
    guard = 0
    noted: set = set()
    while changed and guard < len(tasks) + 8:
        changed = False
        guard += 1
        consumers = _consumers(tasks)
        for t in list(tasks):
            if t.extension is not _b.Filter or len(t.inputs) != 1:
                continue
            proj = t.inputs[0]
            if (
                proj not in tasks
                or len(proj.inputs) != 1
                or len(consumers.get(id(proj), [])) != 1
                or not _rewirable(proj)
                or not isinstance(proj, ProcessTask)
            ):
                continue
            name_map = _pure_projection_map(proj)
            if name_map is None:
                if (
                    proj.extension is _b.Select
                    and (id(t), id(proj)) not in noted
                ):
                    noted.add((id(t), id(proj)))
                    notes.append(
                        RewriteNote(
                            RULE_FILTER_PUSHDOWN,
                            False,
                            "select has computed/distinct/aggregate "
                            "columns; filter cannot cross the projection",
                            t,
                        )
                    )
                continue
            cond = t.params.get("condition", None)
            cond_cols = list(dict.fromkeys(expr_columns(cond)))
            if proj.extension is _b.Select and not all(
                c in name_map for c in cond_cols
            ):
                if (id(t), id(proj)) not in noted:
                    noted.add((id(t), id(proj)))
                    notes.append(
                        RewriteNote(
                            RULE_FILTER_PUSHDOWN,
                            False,
                            "filter references a computed select column; "
                            "cannot cross the projection",
                            t,
                        )
                    )
                continue
            if not _filter_refs_survive(proj, cond_cols):
                continue
            remapped = rename_expr_columns(cond, name_map)
            if remapped is None:
                if (id(t), id(proj)) not in noted:
                    noted.add((id(t), id(proj)))
                    notes.append(
                        RewriteNote(
                            RULE_FILTER_PUSHDOWN,
                            False,
                            "filter condition could not be rebuilt for "
                            "the projection's input columns",
                            t,
                        )
                    )
                continue
            inner = _synthetic(
                ProcessTask,
                _b.Filter,
                dict(condition=remapped),
                [proj.inputs[0]],
                to_uuid("opt.filter_pushdown", proj.__uuid__(), t.__uuid__()),
            )
            inner.callsite = t.callsite
            outer = _synthetic(
                ProcessTask,
                proj.extension,
                dict(proj.params),
                [inner],
                "",
                like=t,
            )
            # the outer projection replaces the FILTER's identity (same
            # output frame); keep the projection's own param spec
            outer.input_names = proj.input_names
            idx_proj = next(i for i, x in enumerate(tasks) if x is proj)
            idx_t = next(i for i, x in enumerate(tasks) if x is t)
            tasks[idx_proj] = inner
            tasks[idx_t] = outer
            _rewire(tasks, t, outer)
            notes.append(
                RewriteNote(
                    RULE_FILTER_PUSHDOWN,
                    True,
                    f"filter pushed below {proj.name} "
                    f"(condition columns remapped: {cond_cols})",
                    t,
                )
            )
            changed = True
            break
    _attach_rowgroup_pruning(tasks, notes)
    return tasks


def _is_parquet_load(task: FugueTask) -> bool:
    if not (isinstance(task, CreateTask) and task.extension is _b.Load):
        return False
    from fugue_tpu.utils.io import infer_format

    path = task.params.get("path", None)
    if isinstance(path, (list, tuple)):
        path = path[0] if path else None
    if not isinstance(path, str):
        return False
    from fugue_tpu.lake.format import is_lake_uri

    if is_lake_uri(path):
        # lake tables are parquet underneath, and the pruning triples
        # additionally skip WHOLE FILES from manifest stats
        return True
    fmt = task.params.get("fmt", "") or None
    try:
        return infer_format(path, fmt) == "parquet"
    except Exception:
        return False


def _attach_rowgroup_pruning(
    tasks: List[FugueTask], notes: List[RewriteNote]
) -> None:
    consumers = _consumers(tasks)
    for t in tasks:
        if not _is_parquet_load(t) or _observable(t):
            continue
        cons = consumers.get(id(t), [])
        if len(cons) != 1:
            continue
        c = cons[0]
        if c.extension is _b.Filter:
            cond = c.params.get("condition", None)
        elif c.extension is _b.Select:
            cond = c.params.get("where", None)
        else:
            continue
        if cond is None:
            continue
        kwargs = dict(t.params.get("params", None) or {})
        if "pruning" in kwargs:
            continue
        triples = extract_pruning_triples(cond)
        if not triples:
            notes.append(
                RewriteNote(
                    RULE_FILTER_PUSHDOWN,
                    False,
                    "predicate over the parquet load has no conjunctive "
                    "column-vs-literal comparison usable for row-group "
                    "pruning",
                    c,
                )
            )
            continue
        kwargs["pruning"] = triples
        t.params["params"] = kwargs
        notes.append(
            RewriteNote(
                RULE_FILTER_PUSHDOWN,
                True,
                f"row-group pruning triples {triples} attached to the "
                "parquet load (advisory: the filter still runs)",
                c,
            )
        )


# ---- rule: select/rename/filter chain fusion --------------------------------
class _ChainState:
    """Composed effect of a fusible chain in CHAIN-INPUT terms:
    ``outputs`` is the ordered projection (None = not yet explicit)
    where each entry is (output name, expression over the chain input);
    ``conds`` are the accumulated filter conditions. While ``outputs``
    is None the column SET is the (possibly unknown) chain input's, with
    ``fwd`` tracking composed renames (head name -> current name) so
    rename chains over schema-less inputs still fuse once an explicit
    projection terminates them."""

    def __init__(self) -> None:
        self.outputs: Optional[List[Tuple[str, ColumnExpr]]] = None
        self.fwd: Dict[str, str] = {}
        self.conds: List[ColumnExpr] = []

    def name_map(self) -> Optional[Dict[str, str]]:
        """current output name -> chain-input name, defined only while
        every current output is a plain un-cast named column (None =
        some are not; pure-rename state returns the inverse rename)."""
        if self.outputs is None:
            return {cur: head for head, cur in self.fwd.items()}
        out: Dict[str, str] = {}
        for name, e in self.outputs:
            if (
                not isinstance(e, _NamedColumnExpr)
                or e.wildcard
                or e.as_type is not None
            ):
                return None
            out[name] = e.name
        return out


_FUSIBLE = (
    _b.Filter,
    _b.Rename,
    _b.DropColumns,
    _b.SelectColumnsP,
    _b.Select,
)


def _compose_op(
    state: _ChainState, task: FugueTask, head_info: SchemaInfo
) -> bool:
    """Fold one chain op into the state; False = not composable (the
    chain is cut before this op)."""
    ext = task.extension
    p = task.params
    if ext is _b.Filter:
        nm = state.name_map()
        if nm is None:
            return False
        raw = p.get("condition", None)
        cond_cols = list(dict.fromkeys(expr_columns(raw)))
        if state.outputs is not None:
            # explicit projection: the filter's input has EXACTLY the
            # output names — an unknown reference errors unoptimized
            if any(c not in nm for c in cond_cols):
                return False
        else:
            # pure-rename state: a reference to a renamed-AWAY head
            # name does not exist post-rename; composing it would
            # silently legitimize an invalid plan
            shadowed = {
                head for head, cur in state.fwd.items() if head != cur
            } - set(state.fwd.values())
            if any(c in shadowed for c in cond_cols):
                return False
        cond = rename_expr_columns(raw, nm)
        if cond is None:
            return False
        state.conds.append(cond)
        return True
    if state.outputs is None and ext in (_b.Rename, _b.DropColumns):
        # materialize the implicit identity projection when the chain
        # input's columns are statically known (validations stay exact)
        if head_info.columns is not None and not state.fwd:
            state.outputs = [(n, col(n)) for n in head_info.columns]
    if ext is _b.Rename:
        columns = p.get("columns", None) or {}
        if state.outputs is None:
            # schema-less: compose the rename maps; an explicit
            # projection later resolves names through the composition
            fwd = dict(state.fwd)
            produced = set(fwd.values())
            for head, cur in list(fwd.items()):
                fwd[head] = columns.get(cur, cur)
            for old, new in columns.items():
                if old not in produced:
                    fwd[old] = new
            if len(set(fwd.values())) != len(fwd):
                return False  # rename collision: keep the runtime error
            state.fwd = fwd
            return True
        current = [n for n, _ in state.outputs]
        if any(k not in current for k in columns):
            return False  # runtime would reject: keep the error
        renamed = [(columns.get(n, n), e) for n, e in state.outputs]
        if len({n for n, _ in renamed}) != len(renamed):
            return False
        state.outputs = renamed
        return True
    if ext is _b.DropColumns:
        if state.outputs is None:
            # schema-less drop can't validate its column list and a
            # later projection referencing a dropped column would be
            # silently legitimized: not composable
            return False
        names = [c for c in p.get("columns", None) or [] if isinstance(c, str)]
        current = {n for n, _ in state.outputs}
        if not p.get("if_exists", False) and any(n not in current for n in names):
            return False
        kept = [(n, e) for n, e in state.outputs if n not in names]
        if not kept:
            return False
        state.outputs = kept
        return True
    if ext is _b.SelectColumnsP:
        names = p.get("columns", None) or []
        if not all(isinstance(n, str) for n in names) or not names:
            return False
        if state.outputs is None:
            nm = state.name_map() or {}
            state.outputs = [(n, col(nm.get(n, n))) for n in names]
            return True
        by_name = dict(state.outputs)
        if any(n not in by_name for n in names):
            return False
        state.outputs = [(n, by_name[n]) for n in names]
        return True
    if ext is _b.Select:
        cols = p.get("columns", None)
        if (
            cols is None
            or p.get("having", None) is not None
            or cols.is_distinct
            or cols.has_agg
        ):
            return False
        nm = state.name_map()
        if nm is None:
            return False
        where = p.get("where", None)
        if where is not None:
            cond = rename_expr_columns(where, nm)
            if cond is None:
                return False
            state.conds.append(cond)
        new_out: List[Tuple[str, ColumnExpr]] = []
        for c in cols.all_cols:
            if isinstance(c, _NamedColumnExpr) and c.wildcard:
                return False
            name = c.output_name
            if name == "":
                return False
            rebuilt = rename_expr_columns(c, nm)
            if rebuilt is None:
                return False
            new_out.append((name, rebuilt))
        if len({n for n, _ in new_out}) != len(new_out):
            return False
        state.outputs = new_out
        return True
    return False


def _fuse_chains(
    tasks: List[FugueTask], conf: Any, notes: List[RewriteNote]
) -> List[FugueTask]:
    infos, _ = propagate(tasks)
    consumers = _consumers(tasks)
    in_chain: set = set()

    def _fusible_link(t: FugueTask) -> bool:
        return (
            isinstance(t, ProcessTask)
            and len(t.inputs) == 1
            and any(t.extension is f for f in _FUSIBLE)
            and id(t) not in in_chain
        )

    for start in list(tasks):
        if not _fusible_link(start):
            continue
        # `start` must be the FIRST link: its input is not itself a
        # fusible intermediate (else the chain starts further up)
        inp = start.inputs[0]
        if (
            _fusible_link(inp)
            and len(consumers.get(id(inp), [])) == 1
            and _rewirable(inp)
        ):
            continue
        chain = [start]
        while True:
            last = chain[-1]
            outs = consumers.get(id(last), [])
            if (
                len(outs) == 1
                and _fusible_link(outs[0])
                and _rewirable(last)
            ):
                chain.append(outs[0])
            else:
                break
        if len(chain) < 2:
            continue
        head_info = infos.get(id(start.inputs[0]), SchemaInfo(reason="unknown"))
        state = _ChainState()
        composed: List[FugueTask] = []
        for link in chain:
            trial = _ChainState()
            trial.outputs = None if state.outputs is None else list(state.outputs)
            trial.fwd = dict(state.fwd)
            trial.conds = list(state.conds)
            if not _compose_op(trial, link, head_info):
                break
            state = trial
            composed.append(link)
        while composed and state.outputs is None and state.fwd:
            # a pure-rename tail without an explicit projection can't
            # build a single Select over an unknown schema: re-compose
            # the longest prefix that CAN build
            composed = composed[:-1]
            state = _ChainState()
            for link in composed:
                _compose_op(state, link, head_info)
        if len(composed) < 2:
            if len(chain) >= 2:
                notes.append(
                    RewriteNote(
                        RULE_FUSION,
                        False,
                        f"chain of {len(chain)} select/rename/filter tasks "
                        "not fusible (computed columns, wildcards or an "
                        "unknown input schema)",
                        chain[0],
                    )
                )
            continue
        last = composed[-1]
        head_input = composed[0].inputs[0]
        if state.outputs is None:
            fused = _synthetic(
                ProcessTask,
                _b.Filter,
                dict(condition=_and_all(state.conds)),
                [head_input],
                "",
                like=last,
            )
        else:
            entries = [
                e if e.output_name == name else e.alias(name)
                for name, e in state.outputs
            ]
            fused = _synthetic(
                ProcessTask,
                _b.Select,
                dict(
                    columns=SelectColumns(*entries),
                    where=_and_all(state.conds) if state.conds else None,
                    having=None,
                ),
                [head_input],
                "",
                like=last,
            )
        idx_last = next(i for i, x in enumerate(tasks) if x is last)
        tasks[idx_last] = fused
        for link in composed[:-1]:
            tasks.remove(link)
        _rewire(tasks, last, fused)
        for link in composed:
            in_chain.add(id(link))
        in_chain.add(id(fused))
        consumers = _consumers(tasks)
        notes.append(
            RewriteNote(
                RULE_FUSION,
                True,
                f"{len(composed)} chained select/rename/filter tasks fused "
                "into one compiled program",
                last,
            )
        )
    return tasks


# ---- rule: projection pushdown ----------------------------------------------
_ALL = None  # sentinel: the full output is required


def _ordered(names: Any) -> Dict[str, None]:
    return dict.fromkeys(n for n in names if isinstance(n, str))


def _merge_req(
    req: Dict[int, Any], task: FugueTask, add: Any
) -> None:
    if id(task) not in req:
        req[id(task)] = dict() if add is not _ALL else _ALL
    if add is _ALL:
        req[id(task)] = _ALL
        return
    if req[id(task)] is _ALL:
        return
    req[id(task)].update(add)


def _input_requirements(
    t: FugueTask, out_req: Any, infos: Dict[int, SchemaInfo]
) -> List[Any]:
    """Per-input required-column sets given the task's own required
    output (``_ALL`` = everything). Anything not provably narrowable
    answers ``_ALL`` — the sweep is safe by construction."""
    ext = t.extension
    p = t.params
    n = len(t.inputs)
    if n == 0:
        return []
    if isinstance(t, OutputTask) or not is_pure_task(t):
        return [_ALL] * n
    if ext is _b.Filter:
        cond_refs = _ordered(expr_columns(p.get("condition", None)))
        if out_req is _ALL:
            return [_ALL]
        return [{**out_req, **cond_refs}]
    if ext is _b.Select:
        cols = p.get("columns", None)
        entries = getattr(cols, "all_cols", None) or []
        refs: Dict[str, None] = {}
        for c in entries:
            if isinstance(c, _NamedColumnExpr) and c.wildcard:
                return [_ALL]
            refs.update(_ordered(expr_columns(c)))
        refs.update(_ordered(expr_columns(p.get("where", None))))
        return [refs]
    if ext is _b.Rename:
        columns = p.get("columns", None) or {}
        if out_req is _ALL:
            return [_ALL]
        inv = {v: k for k, v in columns.items()}
        req = _ordered(inv.get(c, c) for c in out_req)
        req.update(_ordered(columns.keys()))
        return [req]
    if ext is _b.AlterColumns:
        if out_req is _ALL:
            return [_ALL]
        from fugue_tpu.schema import Schema

        try:
            altered = Schema(p.get("columns", "")).names
        except Exception:
            return [_ALL]
        return [{**out_req, **_ordered(altered)}]
    if ext is _b.DropColumns:
        names = _ordered(p.get("columns", None) or [])
        if out_req is _ALL:
            return [_ALL]
        if p.get("if_exists", False):
            return [dict(out_req)]
        return [{**out_req, **names}]
    if ext is _b.SelectColumnsP:
        names = p.get("columns", None) or []
        if not all(isinstance(c, str) for c in names):
            return [_ALL]
        return [_ordered(names)]
    if ext is _b.Assign:
        cols = p.get("columns", None) or []
        if out_req is _ALL:
            return [_ALL]
        assigned = {getattr(c, "output_name", "") for c in cols}
        req = _ordered(c for c in out_req if c not in assigned)
        for c in cols:
            req.update(_ordered(expr_columns(c)))
        return [req]
    if ext is _b.Aggregate:
        req = _ordered(t.partition_spec.partition_by)
        for c in p.get("columns", None) or []:
            req.update(_ordered(expr_columns(c)))
        return [req]
    if ext is _b.Take:
        if out_req is _ALL:
            return [_ALL]
        req = dict(out_req)
        req.update(_ordered(t.partition_spec.partition_by))
        req.update(_ordered(t.partition_spec.presort.keys()))
        try:
            req.update(_ordered(parse_presort_exp(p.get("presort", "")).keys()))
        except Exception:
            return [_ALL]
        return [req]
    if ext is _b.Dropna:
        subset = p.get("subset", None)
        if subset and out_req is not _ALL:
            return [{**out_req, **_ordered(subset)}]
        return [_ALL]
    if ext is _b.Fillna:
        if out_req is _ALL:
            return [_ALL]
        req = dict(out_req)
        subset = p.get("subset", None)
        if subset:
            req.update(_ordered(subset))
        value = p.get("value", None)
        if isinstance(value, dict):
            req.update(_ordered(value.keys()))
        return [req]
    if ext is _b.Sample:
        return [out_req if out_req is _ALL else dict(out_req)]
    if ext is _b.RunJoin:
        how = str(p.get("how", "")).lower()
        on = [c for c in p.get("on", None) or [] if isinstance(c, str)]
        if out_req is _ALL:
            return [_ALL] * n
        sides = [infos.get(id(i), SchemaInfo(reason="unknown")) for i in t.inputs]
        if any(s.columns is None for s in sides):
            return [_ALL] * n
        if how in ("semi", "anti", "left_semi", "left_anti") and n == 2:
            first = {**out_req, **_ordered(on)}
            return [first, _ordered(on)]
        # a duplicate non-key column is a runtime error the optimizer
        # must not silently fix by narrowing it away
        seen: Dict[str, int] = {}
        for i, s in enumerate(sides):
            for name in s.columns or []:
                if name in seen and name not in on:
                    return [_ALL] * n
                seen.setdefault(name, i)
        out: List[Any] = []
        for s in sides:
            cols = set(s.columns or [])
            req = _ordered([c for c in out_req if c in cols] + on)
            out.append(req)
        return out
    # Distinct / set ops compare WHOLE rows; everything else is opaque
    return [_ALL] * n


def _required_columns(
    tasks: List[FugueTask], infos: Dict[int, SchemaInfo]
) -> Dict[int, Any]:
    consumers = _consumers(tasks)
    req: Dict[int, Any] = {}
    for t in reversed(tasks):
        out_req = req.get(id(t), _ALL if not consumers.get(id(t)) else dict())
        if _observable(t):
            out_req = _ALL
        req.setdefault(id(t), out_req)
        if req[id(t)] is not _ALL and out_req is _ALL:
            req[id(t)] = _ALL
        out_req = req[id(t)]
        for inp, r in zip(t.inputs, _input_requirements(t, out_req, infos)):
            _merge_req(req, inp, r)
    return req


def _projection_pushdown(
    tasks: List[FugueTask], conf: Any, notes: List[RewriteNote]
) -> List[FugueTask]:
    infos, _ = propagate(tasks)
    req = _required_columns(tasks, infos)
    for t in tasks:
        if not _is_parquet_load(t):
            continue
        r = req.get(id(t), _ALL)
        if r is _ALL or len(r) == 0:
            continue
        current = t.params.get("columns", None)
        if isinstance(current, str):
            notes.append(
                RewriteNote(
                    RULE_PROJECTION,
                    False,
                    "load declares a schema-expression column spec; narrow "
                    "load not applicable",
                    t,
                )
            )
            continue
        if current is None:
            narrowed = list(r)
        else:
            cur = [c for c in current if isinstance(c, str)]
            if any(c not in cur for c in r):
                # a consumer references a column outside the declared
                # load list: the unoptimized run errors there — keep it
                continue
            narrowed = [c for c in cur if c in r]
            if narrowed == cur:
                continue
        t.params["columns"] = narrowed
        notes.append(
            RewriteNote(
                RULE_PROJECTION,
                True,
                f"parquet load narrowed to {narrowed} (downstream "
                "consumers require no other column)",
                t,
            )
        )
    return tasks


# ---- the pipeline -----------------------------------------------------------
def optimize_tasks(
    tasks: List[FugueTask], conf: Any = None, engine: Any = None
) -> OptimizedPlan:
    """Clone the task graph (uuids pinned) and run the enabled rewrite
    rules over it. The input tasks are never mutated, so the same
    workflow object can be optimized repeatedly (or linted dry-run by
    FWF501) without drift."""
    notes: List[RewriteNote] = []
    out = _clone_tasks(tasks)
    if _rule_enabled(conf, RULE_CSE):
        out = _cse(out, notes)
    if _rule_enabled(conf, RULE_FILTER_PUSHDOWN):
        out = _filter_pushdown(out, conf, notes)
    if _rule_enabled(conf, RULE_FUSION):
        out = _fuse_chains(out, conf, notes)
    if _rule_enabled(conf, RULE_PROJECTION):
        out = _projection_pushdown(out, conf, notes)
    return OptimizedPlan(out, notes)
