"""Functional API over any dataset-like object (plugin dispatchers).

Backends register candidates so these work on raw pandas/arrow/jax objects
as well as fugue_tpu Datasets (parity: reference fugue/dataset/api.py)."""

from typing import Any

from fugue_tpu.dataset.dataset import Dataset
from fugue_tpu.plugins import fugue_plugin


@fugue_plugin
def as_fugue_dataset(data: Any, **kwargs: Any) -> Dataset:
    """Convert an arbitrary object to a fugue_tpu Dataset."""
    if isinstance(data, Dataset):
        return data
    raise NotImplementedError(f"can't convert {type(data)} to Dataset")


def show(data: Any, n: int = 10, with_count: bool = False, title: Any = None) -> None:
    as_fugue_dataset(data).show(n, with_count, title)


@fugue_plugin
def as_local(data: Any) -> Any:
    return as_fugue_dataset(data).native  # pragma: no cover - overridden


@fugue_plugin
def as_local_bounded(data: Any) -> Any:
    return as_fugue_dataset(data).native  # pragma: no cover - overridden


@fugue_plugin
def is_local(data: Any) -> bool:
    return as_fugue_dataset(data).is_local


@fugue_plugin
def is_bounded(data: Any) -> bool:
    return as_fugue_dataset(data).is_bounded


@fugue_plugin
def is_empty(data: Any) -> bool:
    return as_fugue_dataset(data).empty


@fugue_plugin
def count(data: Any) -> int:
    return as_fugue_dataset(data).count()


@fugue_plugin
def get_num_partitions(data: Any) -> int:
    return as_fugue_dataset(data).num_partitions
