from fugue_tpu.dataset.dataset import Dataset, DatasetDisplay, get_dataset_display
from fugue_tpu.dataset.api import (
    as_fugue_dataset,
    as_local,
    as_local_bounded,
    count,
    is_bounded,
    is_empty,
    is_local,
    show,
)
