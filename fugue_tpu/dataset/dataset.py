"""Dataset: the root abstraction over anything distributed-or-local with
metadata (DataFrames and Bags both derive from it). Parity target:
reference ``fugue/dataset/dataset.py:14``; rebuilt on our own ParamDict and
plugin registry."""

from abc import ABC, abstractmethod
from typing import Any, Optional

from fugue_tpu.plugins import fugue_plugin
from fugue_tpu.exceptions import FugueDatasetEmptyError
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.params import ParamDict


class DatasetEmptyError(FugueDatasetEmptyError, ValueError):
    """Peek on an empty dataset (ValueError kept for pre-hierarchy
    callers)."""


class Dataset(ABC):
    """A collection of data that may live locally or across a cluster/mesh."""

    def __init__(self):
        self._metadata: Optional[ParamDict] = None

    @property
    def metadata(self) -> ParamDict:
        if self._metadata is None:
            self._metadata = ParamDict()
        return self._metadata

    @property
    def has_metadata(self) -> bool:
        return self._metadata is not None and len(self._metadata) > 0

    def reset_metadata(self, metadata: Any) -> None:
        self._metadata = ParamDict(metadata) if metadata is not None else None

    @property
    @abstractmethod
    def is_local(self) -> bool:  # pragma: no cover - interface
        """Whether the full dataset lives in the driver process."""
        raise NotImplementedError

    @property
    @abstractmethod
    def is_bounded(self) -> bool:  # pragma: no cover - interface
        """Whether the dataset has finite size."""
        raise NotImplementedError

    @property
    @abstractmethod
    def num_partitions(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    @property
    @abstractmethod
    def empty(self) -> bool:  # pragma: no cover - interface
        raise NotImplementedError

    @abstractmethod
    def count(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def assert_not_empty(self) -> None:
        assert_or_throw(
            not self.empty, DatasetEmptyError("dataset is empty")
        )

    @property
    def native(self) -> Any:
        """The underlying object of the backend (self for pure-python impls)."""
        return self

    def show(
        self, n: int = 10, with_count: bool = False, title: Optional[str] = None
    ) -> None:
        get_dataset_display(self).show(n, with_count, title)


class DatasetDisplay(ABC):
    """Pluggable renderer for :meth:`Dataset.show` — notebook integrations
    override via the :func:`get_dataset_display` plugin."""

    def __init__(self, ds: Dataset):
        self._ds = ds

    @abstractmethod
    def show(
        self, n: int = 10, with_count: bool = False, title: Optional[str] = None
    ) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def repr(self) -> str:
        return str(type(self._ds).__name__)

    def repr_html(self) -> str:
        return self.repr()


@fugue_plugin
def get_dataset_display(ds: "Dataset") -> DatasetDisplay:
    """Get the display utility for a dataset; backends/notebooks register
    higher-priority candidates."""
    raise NotImplementedError(f"no display registered for {type(ds)}")
