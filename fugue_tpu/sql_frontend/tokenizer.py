"""SQL tokenizer for the built-in SQL front end.

Fills the lexer half of the reference's ANTLR dependency
(``fugue-sql-antlr``, see reference setup.py:49 and fugue/sql/workflow.py:16).
A C++ accelerated scanner (the ``[cpp]`` role) can replace ``_scan_py`` via
:func:`set_accelerated_scanner`; the Python scanner is always the fallback.
"""

from typing import Callable, List, NamedTuple, Optional

from fugue_tpu.exceptions import FugueSQLSyntaxError

__all__ = ["Token", "TokenError", "tokenize", "set_accelerated_scanner"]


class TokenError(FugueSQLSyntaxError, ValueError):
    """Lexing failure (ValueError kept for pre-hierarchy callers)."""


class Token(NamedTuple):
    kind: str  # IDENT | QIDENT | NUMBER | STRING | OP | END
    value: str
    pos: int  # character offset into the source

    @property
    def upper(self) -> str:
        return self.value.upper()


_OPERATORS = [
    "<>", "!=", "<=", ">=", "||", "==", "=>",
    "=", "<", ">", "+", "-", "*", "/", "%", "(", ")", ",", ".", ";", ":",
    "{", "}", "[", "]", "?",
]

_IDENT_START = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_IDENT_CONT = _IDENT_START | set("0123456789")
_DIGITS = set("0123456789")

# optional native scanner: fn(sql) -> List[Tuple[kind, value, pos]] or None
_ACCELERATED: List[Optional[Callable[[str], Optional[List[Token]]]]] = [None]


def set_accelerated_scanner(
    fn: Optional[Callable[[str], Optional[List[Token]]]]
) -> None:
    """Install a native (C++) scanner; ``None`` restores pure Python."""
    _ACCELERATED[0] = fn


def tokenize(sql: str) -> List[Token]:
    """Scan ``sql`` into a token list terminated by an END token."""
    if _ACCELERATED[0] is not None:
        res = _ACCELERATED[0](sql)
        if res is not None:
            return res
    return _scan_py(sql)


def _scan_py(sql: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise TokenError(f"unterminated block comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j, buf = i + 1, []
            while True:
                if j >= n:
                    raise TokenError(f"unterminated string at {i}")
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                if sql[j] == "\\" and j + 1 < n and sql[j + 1] in ("'", "\\"):
                    buf.append(sql[j + 1])
                    j += 2
                    continue
                buf.append(sql[j])
                j += 1
            out.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        if c == '"' or c == "`":
            close = c
            j, buf = i + 1, []
            while True:
                if j >= n:
                    raise TokenError(f"unterminated quoted identifier at {i}")
                if sql[j] == close:
                    if j + 1 < n and sql[j + 1] == close:
                        buf.append(close)
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            out.append(Token("QIDENT", "".join(buf), i))
            i = j + 1
            continue
        if c in _DIGITS or (
            c == "." and i + 1 < n and sql[i + 1] in _DIGITS
        ):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = sql[j]
                if ch in _DIGITS:
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    if j + 1 < n and (
                        sql[j + 1] in _DIGITS
                        or (
                            sql[j + 1] in "+-"
                            and j + 2 < n
                            and sql[j + 2] in _DIGITS
                        )
                    ):
                        seen_exp = True
                        j += 2 if sql[j + 1] in "+-" else 1
                    else:
                        break
                else:
                    break
            out.append(Token("NUMBER", sql[i:j], i))
            i = j
            continue
        if c in _IDENT_START:
            j = i + 1
            while j < n and sql[j] in _IDENT_CONT:
                j += 1
            out.append(Token("IDENT", sql[i:j], i))
            i = j
            continue
        for op in _OPERATORS:
            if sql.startswith(op, i):
                out.append(Token("OP", op, i))
                i += len(op)
                break
        else:
            raise TokenError(f"unexpected character {c!r} at {i}")
    out.append(Token("END", "", n))
    return out
