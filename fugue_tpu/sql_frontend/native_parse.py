"""C++ parser integration (the FULL-parse half of the reference's
``fugue-sql-antlr[cpp]`` role — reference README.md:162 "can be 50+
times faster"; the scanner half lives in native_build.py).

``native/cparser.cpp`` lexes AND parses in native code and returns a
generic tree of tuples; :func:`try_native_parse` rebuilds ast.* nodes
from it. Any construct the C++ side cannot handle identically makes it
return None and the pure-Python parser takes over, so behavior —
including error messages on bad SQL — never diverges. AST equality over
the corpus is enforced by tests/.../test_native_parser.py.

Set ``FUGUE_TPU_NO_NATIVE=1`` to skip entirely.
"""

import os
from typing import Any, Optional

from fugue_tpu.sql_frontend import ast
from fugue_tpu.sql_frontend.native_build import (
    build_extension,
    load_extension,
)

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO, "native", "cparser.cpp")
_STATE: dict = {"tried": False, "parse": None}


def enable_native_parser() -> bool:
    """Idempotent; returns True when the C++ parser is loaded."""
    if _STATE["tried"]:
        return _STATE["parse"] is not None
    _STATE["tried"] = True
    if os.environ.get("FUGUE_TPU_NO_NATIVE", "").lower() in ("1", "true"):
        return False
    so = build_extension(_SRC, "_fugue_tpu_cparser", timeout=180)
    if so is None:
        return False
    mod = load_extension(so, "_fugue_tpu_cparser")
    if mod is None:
        return False
    _STATE["parse"] = mod.parse  # type: ignore[attr-defined]
    return True


def native_parser_active() -> bool:
    return _STATE["parse"] is not None


def try_native_parse(sql: str) -> Optional[ast.Query]:
    """Parse with the C++ parser; None = use the Python parser."""
    fn = _STATE["parse"]
    if fn is None:
        return None
    try:
        tree = fn(sql)
        if tree is None:
            return None
        return _query(tree)
    except Exception:
        return None  # defensive: python path owns errors


# ---- generic tree -> ast -------------------------------------------------


def _query(t: Any) -> ast.Query:
    tag = t[0]
    if tag == "with":
        return ast.With(
            [(name, _query(sub)) for name, sub in t[1]], _query(t[2])
        )
    if tag == "setop_tail":
        inner = _query(t[1])
        assert isinstance(inner, ast.SetOp)
        inner.order_by = [_order(o) for o in t[2]]
        inner.limit = t[3]
        inner.offset = t[4]
        return inner
    if tag == "setop":
        return ast.SetOp(t[1], t[2], _query(t[3]), _query(t[4]))
    if tag == "select":
        (_, items, from_, where, group, having, order, limit, offset,
         distinct) = t
        return ast.Select(
            [_item(i) for i in items],
            None if from_ is None else _relation(from_),
            None if where is None else _expr(where),
            [_expr(g) for g in group],
            None if having is None else _expr(having),
            [_order(o) for o in order],
            limit,
            offset,
            distinct,
        )
    raise ValueError(f"bad query tag {tag}")


def _item(t: Any) -> ast.SelectItem:
    return ast.SelectItem(_expr(t[1]), t[2])


def _order(t: Any) -> ast.OrderItem:
    return ast.OrderItem(_expr(t[1]), t[2], t[3])


def _relation(t: Any) -> ast.Relation:
    tag = t[0]
    if tag == "table":
        return ast.TableRef(t[1], t[2])
    if tag == "subq":
        return ast.SubqueryRef(_query(t[1]), t[2])
    if tag == "join":
        return ast.JoinRel(
            _relation(t[1]),
            _relation(t[2]),
            t[3],
            None if t[4] is None else _expr(t[4]),
            None if t[5] is None else list(t[5]),
        )
    raise ValueError(f"bad relation tag {tag}")


def _expr(t: Any) -> ast.Expr:
    tag = t[0]
    if tag == "lit":
        return ast.Lit(t[1])
    if tag == "col":
        return ast.Col(t[1], t[2])
    if tag == "star":
        return ast.Star(t[1])
    if tag == "unary":
        return ast.Unary(t[1], _expr(t[2]))
    if tag == "bin":
        return ast.Binary(t[1], _expr(t[2]), _expr(t[3]))
    if tag == "func":
        return ast.Func(t[1], [_expr(a) for a in t[2]], t[3])
    if tag == "case":
        return ast.Case(
            None if t[1] is None else _expr(t[1]),
            [(_expr(c), _expr(v)) for c, v in t[2]],
            None if t[3] is None else _expr(t[3]),
        )
    if tag == "cast":
        return ast.Cast(_expr(t[1]), t[2])
    if tag == "inlist":
        return ast.InList(_expr(t[1]), [_expr(i) for i in t[2]], t[3])
    if tag == "between":
        return ast.Between(_expr(t[1]), _expr(t[2]), _expr(t[3]), t[4])
    if tag == "like":
        return ast.Like(_expr(t[1]), _expr(t[2]), t[3])
    if tag == "isnull":
        return ast.IsNull(_expr(t[1]), t[2])
    if tag == "window":
        frame = None
        if len(t) > 4 and t[4] is not None:
            frame = ast.Frame(t[4][1], tuple(t[4][2]), tuple(t[4][3]))
        return ast.Window(
            _expr(t[1]),  # type: ignore[arg-type]
            [_expr(p) for p in t[2]],
            [_order(o) for o in t[3]],
            frame,
        )
    if tag == "subquery":
        return ast.ScalarSubquery(_query(t[1]))
    if tag == "insub":
        return ast.InSubquery(_expr(t[1]), _query(t[2]), t[3])
    if tag == "exists":
        return ast.Exists(_query(t[1]))
    raise ValueError(f"bad expr tag {tag}")
