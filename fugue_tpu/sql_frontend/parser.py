"""Recursive-descent SQL parser (SELECT subset) — the parser half of the
reference's ANTLR dependency (fugue/sql/workflow.py:16, grammar from the
external ``fugue-sql-antlr`` package).

Supports: WITH CTEs; SELECT [DISTINCT] items; FROM with aliases, subqueries
and INNER/LEFT/RIGHT/FULL/CROSS/SEMI/ANTI joins (ON / USING); WHERE;
GROUP BY (exprs, ordinals or aliases); HAVING; ORDER BY with NULLS
FIRST/LAST; LIMIT/OFFSET; UNION/EXCEPT/INTERSECT [ALL|DISTINCT];
expressions with CASE, CAST, IN, BETWEEN, LIKE, IS NULL, arithmetic,
comparison, boolean logic and function calls (incl. DISTINCT aggregates).
"""

from typing import List, Optional, Tuple

from fugue_tpu.exceptions import FugueSQLSyntaxError
from fugue_tpu.sql_frontend.ast import (
    Between, Binary, Case, Cast, Col, Exists, Expr, Frame, Func, InList,
    InSubquery, IsNull, JoinRel, Like, Lit, OrderItem, Query, Relation,
    ScalarSubquery, Select, SelectItem, SetOp, Star, SubqueryRef,
    TableRef, Unary, Window, With,
)
from fugue_tpu.sql_frontend.tokenizer import Token, tokenize

__all__ = ["SQLParseError", "parse_select", "Cursor", "ExprParser"]


class SQLParseError(FugueSQLSyntaxError, ValueError):
    """Parse failure (ValueError kept for pre-hierarchy callers)."""


_RESERVED_AFTER_TABLE = {
    "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION",
    "EXCEPT", "INTERSECT", "JOIN", "INNER", "LEFT", "RIGHT", "FULL",
    "CROSS", "SEMI", "ANTI", "ON", "USING", "NATURAL", "BY", "AND", "OR",
    # FugueSQL statement keywords that may follow a table expression
    "PERSIST", "BROADCAST", "CHECKPOINT", "YIELD", "PREPARTITION",
    "TRANSFORM", "PROCESS", "OUTPUT", "PRINT", "SAVE", "LOAD", "TAKE",
    "SELECT", "WITH", "END", "DISTRIBUTE", "PRESORT", "SINGLE", "FROM",
    "OUTTRANSFORM", "CREATE", "ZIP", "RENAME", "ALTER", "FILL", "SAMPLE",
    "REPLACE", "SEED", "DETERMINISTIC", "LAZY", "WEAK", "STRONG",
    "CALLBACK", "ROWCOUNT", "ROWS", "TITLE", "HASH", "RAND", "EVEN",
    "COARSE", "DROP", "SCHEMA", "PARAMS", "COLUMNS", "OVERWRITE", "APPEND",
}


class Cursor:
    """Token cursor shared by the SELECT parser and the FugueSQL dialect
    parser."""

    def __init__(self, tokens: List[Token]):
        self.tokens = tokens
        self.i = 0

    @property
    def tok(self) -> Token:
        return self.tokens[self.i]

    def peek(self, n: int = 1) -> Token:
        j = min(self.i + n, len(self.tokens) - 1)
        return self.tokens[j]

    def at_end(self) -> bool:
        return self.tok.kind == "END"

    def advance(self) -> Token:
        t = self.tok
        if t.kind != "END":
            self.i += 1
        return t

    def is_kw(self, *words: str) -> bool:
        t = self.tok
        return t.kind == "IDENT" and t.upper in words

    def accept_kw(self, *words: str) -> bool:
        if self.is_kw(*words):
            self.advance()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.accept_kw(word):
            raise SQLParseError(f"expected {word}, got {self.tok.value!r}")

    def is_op(self, *ops: str) -> bool:
        t = self.tok
        return t.kind == "OP" and t.value in ops

    def accept_op(self, *ops: str) -> bool:
        if self.is_op(*ops):
            self.advance()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SQLParseError(f"expected {op!r}, got {self.tok.value!r}")

    def error(self, msg: str) -> SQLParseError:
        return SQLParseError(f"{msg} (at token {self.tok.value!r})")


def parse_select(sql: str) -> Query:
    from fugue_tpu.sql_frontend.native_build import enable_native_scanner
    from fugue_tpu.sql_frontend.native_parse import (
        enable_native_parser,
        try_native_parse,
    )

    # the C++ parser covers the FULL parse; on None (unsupported shape,
    # syntax error, no compiler) the pure-Python path below owns the
    # parse AND the error message
    enable_native_parser()
    q = try_native_parse(sql)
    if q is not None:
        return q
    enable_native_scanner()  # idempotent; falls back to python silently
    cur = Cursor(tokenize(sql))
    q = ExprParser(cur).query()
    cur.accept_op(";")
    if not cur.at_end():
        raise cur.error("unexpected trailing input")
    return q


class ExprParser:
    """Parses queries and expressions from a shared :class:`Cursor`."""

    def __init__(self, cursor: Cursor):
        self.cur = cursor

    # ---- queries --------------------------------------------------------

    def query(self) -> Query:
        cur = self.cur
        if cur.is_kw("WITH"):
            cur.advance()
            ctes: List[Tuple[str, Query]] = []
            while True:
                name = self._name("CTE name")
                cur.expect_kw("AS")
                cur.expect_op("(")
                sub = self.query()
                cur.expect_op(")")
                ctes.append((name, sub))
                if not cur.accept_op(","):
                    break
            return With(ctes, self.query())
        return self._set_expr()

    def _set_expr(self) -> Query:
        left = self._select_core()
        while self.cur.is_kw("UNION", "EXCEPT", "INTERSECT"):
            op = self.cur.advance().upper
            all_ = self.cur.accept_kw("ALL")
            if not all_:
                self.cur.accept_kw("DISTINCT")
            right = self._select_core()
            left = SetOp(op, all_, left, right)
        # trailing ORDER BY / LIMIT bind to the whole set expression
        if isinstance(left, SetOp):
            left.order_by = self._order_by_clause()
            left.limit, left.offset = self._limit_clause()
        return left

    def _select_core(self) -> Query:
        cur = self.cur
        if cur.accept_op("("):
            q = self.query()
            cur.expect_op(")")
            return q
        cur.expect_kw("SELECT")
        distinct = False
        if cur.accept_kw("DISTINCT"):
            distinct = True
        else:
            cur.accept_kw("ALL")
        items = [self._select_item()]
        while cur.accept_op(","):
            items.append(self._select_item())
        from_ = None
        if cur.accept_kw("FROM"):
            from_ = self._from_expr()
        where = self.expr() if cur.accept_kw("WHERE") else None
        group_by: List[Expr] = []
        if cur.accept_kw("GROUP"):
            cur.expect_kw("BY")
            group_by.append(self.expr())
            while cur.accept_op(","):
                group_by.append(self.expr())
        having = self.expr() if cur.accept_kw("HAVING") else None
        order_by = self._order_by_clause()
        limit, offset = self._limit_clause()
        return Select(
            items, from_, where, group_by, having, order_by, limit, offset,
            distinct,
        )

    def _order_by_clause(self) -> List[OrderItem]:
        cur = self.cur
        out: List[OrderItem] = []
        if cur.is_kw("ORDER"):
            cur.advance()
            cur.expect_kw("BY")
            while True:
                e = self.expr()
                asc = True
                if cur.accept_kw("DESC"):
                    asc = False
                else:
                    cur.accept_kw("ASC")
                nulls = None
                if cur.accept_kw("NULLS"):
                    if cur.accept_kw("FIRST"):
                        nulls = "FIRST"
                    else:
                        cur.expect_kw("LAST")
                        nulls = "LAST"
                out.append(OrderItem(e, asc, nulls))
                if not cur.accept_op(","):
                    break
        return out

    def _limit_clause(self) -> Tuple[Optional[int], Optional[int]]:
        cur = self.cur
        limit = offset = None
        if cur.accept_kw("LIMIT"):
            limit = self._int_lit("LIMIT")
        if cur.accept_kw("OFFSET"):
            offset = self._int_lit("OFFSET")
        return limit, offset

    def _int_lit(self, what: str) -> int:
        t = self.cur.tok
        if t.kind != "NUMBER":
            raise self.cur.error(f"{what} expects an integer")
        self.cur.advance()
        return int(t.value)

    def _select_item(self) -> SelectItem:
        cur = self.cur
        if cur.is_op("*"):
            cur.advance()
            return SelectItem(Star())
        # qualified star: t.*
        if (
            cur.tok.kind in ("IDENT", "QIDENT")
            and cur.peek(1).kind == "OP" and cur.peek(1).value == "."
            and cur.peek(2).kind == "OP" and cur.peek(2).value == "*"
        ):
            table = cur.advance().value
            cur.advance()
            cur.advance()
            return SelectItem(Star(table))
        e = self.expr()
        alias = None
        if cur.accept_kw("AS"):
            alias = self._name("alias")
        elif cur.tok.kind == "QIDENT" or (
            cur.tok.kind == "IDENT"
            and cur.tok.upper not in _RESERVED_AFTER_TABLE
        ):
            alias = cur.advance().value
        return SelectItem(e, alias)

    # ---- FROM -----------------------------------------------------------

    def _from_expr(self) -> Relation:
        rel = self._table_primary()
        while True:
            cur = self.cur
            how = None
            if cur.is_kw("CROSS"):
                cur.advance()
                cur.expect_kw("JOIN")
                how = "cross"
            elif cur.is_kw("INNER"):
                cur.advance()
                cur.expect_kw("JOIN")
                how = "inner"
            elif cur.is_kw("JOIN"):
                cur.advance()
                how = "inner"
            elif cur.is_kw("LEFT"):
                if cur.peek(1).upper in ("SEMI", "ANTI"):
                    cur.advance()
                    how = "semi" if cur.advance().upper == "SEMI" else "anti"
                    cur.expect_kw("JOIN")
                else:
                    cur.advance()
                    cur.accept_kw("OUTER")
                    cur.expect_kw("JOIN")
                    how = "left_outer"
            elif cur.is_kw("RIGHT"):
                cur.advance()
                cur.accept_kw("OUTER")
                cur.expect_kw("JOIN")
                how = "right_outer"
            elif cur.is_kw("FULL"):
                cur.advance()
                cur.accept_kw("OUTER")
                cur.expect_kw("JOIN")
                how = "full_outer"
            elif cur.is_kw("SEMI", "ANTI"):
                how = "semi" if cur.advance().upper == "SEMI" else "anti"
                cur.expect_kw("JOIN")
            elif cur.is_op(","):
                cur.advance()
                how = "cross"
                rel = JoinRel(rel, self._table_primary(), how)
                continue
            else:
                break
            right = self._table_primary()
            on = None
            using = None
            if how != "cross":
                if cur.accept_kw("ON"):
                    on = self.expr()
                elif cur.accept_kw("USING"):
                    cur.expect_op("(")
                    using = [self._name("USING column")]
                    while cur.accept_op(","):
                        using.append(self._name("USING column"))
                    cur.expect_op(")")
            rel = JoinRel(rel, right, how, on, using)
        return rel

    def _table_primary(self) -> Relation:
        cur = self.cur
        if cur.accept_op("("):
            q = self.query()
            cur.expect_op(")")
            alias = self._table_alias()
            if alias is None:
                raise cur.error("subquery in FROM requires an alias")
            return SubqueryRef(q, alias)
        name = self._name("table name")
        return TableRef(name, self._table_alias())

    def _table_alias(self) -> Optional[str]:
        cur = self.cur
        if cur.accept_kw("AS"):
            return self._name("alias")
        if cur.tok.kind == "QIDENT" or (
            cur.tok.kind == "IDENT"
            and cur.tok.upper not in _RESERVED_AFTER_TABLE
        ):
            return cur.advance().value
        return None

    def _name(self, what: str) -> str:
        t = self.cur.tok
        if t.kind not in ("IDENT", "QIDENT"):
            raise self.cur.error(f"expected {what}")
        self.cur.advance()
        return t.value

    # ---- expressions ----------------------------------------------------

    def expr(self) -> Expr:
        return self._or_expr()

    def _or_expr(self) -> Expr:
        left = self._and_expr()
        while self.cur.accept_kw("OR"):
            left = Binary("OR", left, self._and_expr())
        return left

    def _and_expr(self) -> Expr:
        left = self._not_expr()
        while self.cur.accept_kw("AND"):
            left = Binary("AND", left, self._not_expr())
        return left

    def _not_expr(self) -> Expr:
        if self.cur.accept_kw("NOT"):
            return Unary("NOT", self._not_expr())
        return self._predicate()

    def _predicate(self) -> Expr:
        cur = self.cur
        left = self._additive()
        while True:
            if cur.is_op("=", "==", "<>", "!=", "<", "<=", ">", ">="):
                op = cur.advance().value
                op = {"==": "=", "!=": "<>"}.get(op, op)
                left = Binary(op, left, self._additive())
                continue
            if cur.is_kw("IS"):
                cur.advance()
                negated = cur.accept_kw("NOT")
                cur.expect_kw("NULL")
                left = IsNull(left, negated)
                continue
            negated = False
            if cur.is_kw("NOT") and cur.peek(1).upper in (
                "IN", "BETWEEN", "LIKE",
            ):
                cur.advance()
                negated = True
            if cur.accept_kw("IN"):
                cur.expect_op("(")
                if cur.is_kw("SELECT", "WITH"):
                    q = self.query()
                    cur.expect_op(")")
                    left = InSubquery(left, q, negated)
                    continue
                items = [self.expr()]
                while cur.accept_op(","):
                    items.append(self.expr())
                cur.expect_op(")")
                left = InList(left, items, negated)
                continue
            if cur.accept_kw("BETWEEN"):
                low = self._additive()
                cur.expect_kw("AND")
                high = self._additive()
                left = Between(left, low, high, negated)
                continue
            if cur.accept_kw("LIKE"):
                left = Like(left, self._additive(), negated)
                continue
            if negated:
                raise cur.error("dangling NOT")
            return left

    def _additive(self) -> Expr:
        left = self._multiplicative()
        while True:
            if self.cur.is_op("+", "-", "||"):
                op = self.cur.advance().value
                left = Binary(op, left, self._multiplicative())
            else:
                return left

    def _multiplicative(self) -> Expr:
        left = self._unary()
        while True:
            if self.cur.is_op("*", "/", "%"):
                op = self.cur.advance().value
                left = Binary(op, left, self._unary())
            else:
                return left

    def _unary(self) -> Expr:
        if self.cur.is_op("-", "+"):
            op = self.cur.advance().value
            return Unary(op, self._unary())
        return self._primary()

    def _primary(self) -> Expr:
        cur = self.cur
        t = cur.tok
        if t.kind == "NUMBER":
            cur.advance()
            v = float(t.value) if ("." in t.value or "e" in t.value.lower()) \
                else int(t.value)
            return Lit(v)
        if t.kind == "STRING":
            cur.advance()
            return Lit(t.value)
        if cur.accept_op("("):
            if cur.is_kw("SELECT", "WITH"):
                q = self.query()
                cur.expect_op(")")
                return ScalarSubquery(q)
            e = self.expr()
            cur.expect_op(")")
            return e
        if t.kind == "QIDENT":
            cur.advance()
            return self._maybe_qualified(t.value)
        if t.kind != "IDENT":
            raise cur.error("expected expression")
        u = t.upper
        if u == "NULL":
            cur.advance()
            return Lit(None)
        if u == "TRUE":
            cur.advance()
            return Lit(True)
        if u == "FALSE":
            cur.advance()
            return Lit(False)
        if u == "CASE":
            return self._case()
        if (
            u == "EXISTS"
            and cur.peek(1).kind == "OP"
            and cur.peek(1).value == "("
            and cur.peek(2).kind == "IDENT"
            and cur.peek(2).upper in ("SELECT", "WITH")
        ):
            cur.advance()
            cur.advance()  # (
            q = self.query()
            cur.expect_op(")")
            return Exists(q)
        if u == "CAST":
            cur.advance()
            cur.expect_op("(")
            e = self.expr()
            cur.expect_kw("AS")
            tp = self._type_name()
            cur.expect_op(")")
            return Cast(e, tp)
        # function call?
        if cur.peek(1).kind == "OP" and cur.peek(1).value == "(":
            name = cur.advance().value
            cur.advance()  # (
            if cur.accept_op(")"):
                return self._maybe_over(Func(name, []))
            if cur.is_op("*"):
                cur.advance()
                cur.expect_op(")")
                return self._maybe_over(Func(name, [Star()]))
            distinct = cur.accept_kw("DISTINCT")
            args = [self.expr()]
            while cur.accept_op(","):
                args.append(self.expr())
            cur.expect_op(")")
            return self._maybe_over(Func(name, args, distinct))
        cur.advance()
        return self._maybe_qualified(t.value)

    def _maybe_over(self, func: Func) -> Expr:
        """``OVER (PARTITION BY ... ORDER BY ...)`` after a function call.
        OVER introduces a window only when followed by ``(`` — a bare
        ``over`` stays available as a select-item alias (review finding)."""
        cur = self.cur
        if not (
            cur.is_kw("OVER")
            and cur.peek(1).kind == "OP"
            and cur.peek(1).value == "("
        ):
            return func
        cur.advance()
        cur.expect_op("(")
        partition: List[Expr] = []
        if cur.accept_kw("PARTITION"):
            cur.expect_kw("BY")
            partition.append(self.expr())
            while cur.accept_op(","):
                partition.append(self.expr())
        order: List[OrderItem] = []
        if cur.is_kw("ORDER"):
            order = self._order_by_clause()
        frame = None
        if cur.is_kw("ROWS", "RANGE", "GROUPS"):
            frame = self._frame_clause()
        cur.expect_op(")")
        return Window(func, partition, order, frame)

    def _frame_clause(self) -> Frame:
        """``ROWS|RANGE|GROUPS BETWEEN <bound> AND <bound>`` (or the
        single-bound shorthand, whose end is CURRENT ROW)."""
        cur = self.cur
        unit = cur.advance().value.lower()
        if cur.accept_kw("BETWEEN"):
            start = self._frame_bound()
            cur.expect_kw("AND")
            end = self._frame_bound()
        else:
            start = self._frame_bound()
            end = ("c", None)
        if cur.is_kw("EXCLUDE"):
            raise cur.error("EXCLUDE in window frames is not supported")
        if start[0] == "uf" or end[0] == "up":
            raise cur.error("window frame start cannot follow its end")
        _rank = {"up": 0, "p": 1, "c": 2, "f": 3, "uf": 4}
        if _rank[start[0]] > _rank[end[0]]:
            raise cur.error("window frame start cannot follow its end")
        return Frame(unit, start, end)

    def _frame_bound(self) -> Tuple[str, Optional[object]]:
        cur = self.cur
        if cur.accept_kw("UNBOUNDED"):
            if cur.accept_kw("PRECEDING"):
                return ("up", None)
            cur.expect_kw("FOLLOWING")
            return ("uf", None)
        if cur.accept_kw("CURRENT"):
            cur.expect_kw("ROW")
            return ("c", None)
        t = cur.tok
        if t.kind != "NUMBER":
            raise cur.error("expected a numeric window frame offset")
        cur.advance()
        v = t.value
        n: object = float(v) if ("." in v or "e" in v.lower()) else int(v)
        if cur.accept_kw("PRECEDING"):
            return ("p", n)
        cur.expect_kw("FOLLOWING")
        return ("f", n)

    def _maybe_qualified(self, first: str) -> Expr:
        cur = self.cur
        if cur.is_op(".") and cur.peek(1).kind in ("IDENT", "QIDENT"):
            cur.advance()
            name = cur.advance().value
            return Col(name, table=first)
        return Col(first)

    def _case(self) -> Expr:
        cur = self.cur
        cur.expect_kw("CASE")
        operand = None
        if not cur.is_kw("WHEN"):
            operand = self.expr()
        whens: List[Tuple[Expr, Expr]] = []
        while cur.accept_kw("WHEN"):
            c = self.expr()
            cur.expect_kw("THEN")
            whens.append((c, self.expr()))
        default = self.expr() if cur.accept_kw("ELSE") else None
        cur.expect_kw("END")
        if len(whens) == 0:
            raise cur.error("CASE requires at least one WHEN")
        return Case(operand, whens, default)

    def _type_name(self) -> str:
        cur = self.cur
        base = self._name("type name").lower()
        # consume (p[,s]) for decimal-style types; ignored by our type map
        if cur.accept_op("("):
            self._int_lit("type parameter")
            if cur.accept_op(","):
                self._int_lit("type parameter")
            cur.expect_op(")")
        return base
