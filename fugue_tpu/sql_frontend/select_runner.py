"""SELECT executor over DataFrames on pandas — the role qpd plays for the
reference's native engine (reference fugue/execution/native_execution_engine.py:41-65)
and duckdb plays for its SQL backends.

Executes the AST from :mod:`fugue_tpu.sql_frontend.parser` with SQL
semantics: three-valued logic, null-ignoring aggregates, null keys never
joining, null-safe set operations.
"""

from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from fugue_tpu.exceptions import FugueSQLRuntimeError
from fugue_tpu.dataframe import DataFrame, DataFrames
from fugue_tpu.dataframe.arrow_dataframe import ArrowDataFrame
from fugue_tpu.dataframe.dataframe import LocalBoundedDataFrame
from fugue_tpu.column.functions import (
    VARIANCE_FUNCS,
    variance_ddof,
    variance_stat,
)
from fugue_tpu.column.pandas_eval import compile_like_regex, sql_fmod
from fugue_tpu.schema import Schema
from fugue_tpu.sql_frontend import ast
from fugue_tpu.sql_frontend.parser import parse_select

__all__ = ["run_select", "run_query", "SQLExecutionError"]


class SQLExecutionError(FugueSQLRuntimeError, ValueError):
    """SQL execution failure (ValueError kept for pre-hierarchy
    callers)."""


def run_select(sql: str, dfs: DataFrames) -> LocalBoundedDataFrame:
    """Parse and execute ``sql`` against the named dataframes in ``dfs``."""
    return run_query(parse_select(sql), dfs)


def run_query(query: ast.Query, dfs: DataFrames) -> LocalBoundedDataFrame:
    env: Dict[str, "_Table"] = {}
    for name, df in dfs.items():
        env[name.lower()] = _Table.from_fugue(df)
    res = _run(query, env)
    return res.to_fugue()


# ---- typed columnar intermediates ---------------------------------------


class _TS(NamedTuple):
    """A typed series: values aligned to the current scope index + the
    arrow output type (None = not yet determined)."""

    series: pd.Series
    dtype: Optional[pa.DataType]


class _Table:
    """An executed relation: pandas frame with output names + arrow types."""

    def __init__(self, frame: pd.DataFrame, names: List[str],
                 types: List[Optional[pa.DataType]]):
        self.frame = frame
        self.names = names
        self.types = types

    @staticmethod
    def from_fugue(df: DataFrame) -> "_Table":
        pdf = df.as_pandas().reset_index(drop=True)
        schema = df.schema
        pdf.columns = list(range(len(schema)))
        return _Table(pdf, list(schema.names), list(schema.types))

    def to_fugue(self) -> LocalBoundedDataFrame:
        arrays: List[pa.Array] = []
        fields: List[pa.Field] = []
        for i, (name, tp) in enumerate(zip(self.names, self.types)):
            s = self.frame.iloc[:, i] if self.frame.shape[1] > i else \
                pd.Series([], dtype=object)
            arr = _series_to_arrow(s, tp)
            arrays.append(arr)
            fields.append(pa.field(name, arr.type))
        table = pa.Table.from_arrays(arrays, schema=pa.schema(fields))
        return ArrowDataFrame(table)


def _series_to_arrow(s: pd.Series, tp: Optional[pa.DataType]) -> pa.Array:
    target = tp if tp is not None and not pa.types.is_null(tp) else None
    try:
        if target is not None:
            return pa.Array.from_pandas(s, type=target)
        arr = pa.Array.from_pandas(s)
        if pa.types.is_null(arr.type):
            return arr.cast(pa.string())
        return arr
    except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
        arr = pa.Array.from_pandas(s.astype(object).where(s.notna(), None))
        if target is not None:
            return arr.cast(target)
        return arr


# ---- scopes -------------------------------------------------------------


class _Entry(NamedTuple):
    qual: Optional[str]  # lower-cased table alias/name
    name: str
    label: Any  # column label in the scope frame
    dtype: Optional[pa.DataType]


class _Scope:
    def __init__(self, frame: pd.DataFrame, entries: List[_Entry]):
        self.frame = frame
        self.entries = entries

    @staticmethod
    def from_table(t: _Table, qual: Optional[str]) -> "_Scope":
        frame = t.frame.copy(deep=False)
        labels = [f"c{i}" for i in range(len(t.names))]
        frame.columns = labels
        q = qual.lower() if qual is not None else None
        entries = [
            _Entry(q, n, lb, tp)
            for n, lb, tp in zip(t.names, labels, t.types)
        ]
        return _Scope(frame, entries)

    def candidates(self, name: str, qual: Optional[str]) -> List[_Entry]:
        """Exact-name matches, else case-insensitive matches (SQL
        identifier folding). 0 = not found, >1 = ambiguous."""
        q = qual.lower() if qual is not None else None
        cands = [
            e for e in self.entries
            if e.name == name and (q is None or e.qual == q)
        ]
        if len(cands) == 0:  # case-insensitive fallback
            low = name.lower()
            cands = [
                e for e in self.entries
                if e.name.lower() == low and (q is None or e.qual == q)
            ]
        return cands

    def resolve(self, name: str, qual: Optional[str]) -> _Entry:
        cands = self.candidates(name, qual)
        if len(cands) == 0:
            raise SQLExecutionError(f"column not found: {_qname(name, qual)}")
        if len(cands) > 1:
            raise SQLExecutionError(f"ambiguous column: {_qname(name, qual)}")
        return cands[0]

    def star_entries(self, qual: Optional[str]) -> List[_Entry]:
        if qual is None:
            return list(self.entries)
        q = qual.lower()
        out = [e for e in self.entries if e.qual == q]
        if len(out) == 0:
            raise SQLExecutionError(f"unknown table {qual!r} in wildcard")
        return out


def _qname(name: str, qual: Optional[str]) -> str:
    return name if qual is None else f"{qual}.{name}"


# ---- query execution ----------------------------------------------------


def _run(query: ast.Query, env: Dict[str, _Table]) -> _Table:
    if isinstance(query, ast.With):
        scoped = dict(env)
        for name, sub in query.ctes:
            scoped[name.lower()] = _run(sub, scoped)
        return _run(query.body, scoped)
    if isinstance(query, ast.SetOp):
        return _run_setop(query, env)
    if isinstance(query, ast.Select):
        return _run_select(query, env)
    raise SQLExecutionError(f"unsupported query node {type(query).__name__}")


def _lookup_table(name: str, env: Dict[str, _Table]) -> _Table:
    t = env.get(name.lower())
    if t is None:
        raise SQLExecutionError(f"table not found: {name}")
    return t


def _build_scope(rel: ast.Relation, env: Dict[str, _Table]) -> _Scope:
    if isinstance(rel, ast.TableRef):
        t = _lookup_table(rel.name, env)
        return _Scope.from_table(t, rel.alias or rel.name)
    if isinstance(rel, ast.SubqueryRef):
        return _Scope.from_table(_run(rel.query, env), rel.alias)
    if isinstance(rel, ast.JoinRel):
        left = _build_scope(rel.left, env)
        right = _build_scope(rel.right, env)
        return _join_scopes(left, right, rel, env)
    raise SQLExecutionError(f"unsupported relation {type(rel).__name__}")


def _relabel(scope: _Scope, prefix: str) -> _Scope:
    mapping = {e.label: f"{prefix}{e.label}" for e in scope.entries}
    frame = scope.frame.rename(columns=mapping)
    entries = [e._replace(label=mapping[e.label]) for e in scope.entries]
    return _Scope(frame, entries)


def _join_scopes(
    left: _Scope, right: _Scope, rel: ast.JoinRel,
    env: Optional[Dict[str, _Table]] = None,
) -> _Scope:
    left = _relabel(left, "l_")
    right = _relabel(right, "r_")
    how = rel.how
    if how == "cross":
        frame = left.frame.merge(right.frame, how="cross")
        return _Scope(frame, left.entries + right.entries)
    # extract equi-join key expressions
    pairs: List[Tuple[_TS, _TS]] = []
    residual: Optional[ast.Expr] = None
    coalesce_pairs: List[Tuple[Any, Any]] = []  # (left label, right label)
    hidden_right: List[Any] = []
    if rel.using is not None:
        for name in rel.using:
            le = left.resolve(name, None)
            re_ = right.resolve(name, None)
            pairs.append((
                _TS(left.frame[le.label], le.dtype),
                _TS(right.frame[re_.label], re_.dtype),
            ))
            coalesce_pairs.append((le.label, re_.label))
            hidden_right.append(re_.label)
    elif rel.on is not None:
        conj = _split_conjunction(rel.on)
        ev_l, ev_r = _Evaluator(left, env=env), _Evaluator(right, env=env)
        for c in conj:
            sides = _equi_sides(c, ev_l, ev_r)
            if sides is None:
                residual = c if residual is None else \
                    ast.Binary("AND", residual, c)
            else:
                pairs.append(sides)
        if len(pairs) == 0:
            if how != "inner":
                raise SQLExecutionError(
                    f"{how} join requires at least one equi-join condition"
                )
            frame = left.frame.merge(right.frame, how="cross")
            scope = _Scope(frame, left.entries + right.entries)
            if rel.on is not None:
                mask = _to_bool_mask(
                    _Evaluator(scope, env=env).eval(rel.on).series
                )
                scope = _Scope(scope.frame[mask], scope.entries)
            return scope
    else:
        raise SQLExecutionError("join requires ON or USING")
    lf = left.frame.copy(deep=False)
    rf = right.frame.copy(deep=False)
    keys = []
    for i, (lts, rts) in enumerate(pairs):
        k = f"_jk{i}"
        lf[k] = lts.series
        rf[k] = rts.series
        keys.append(k)
    from fugue_tpu.execution.native_execution_engine import _pandas_join

    how_map = {
        "inner": "inner", "left_outer": "leftouter",
        "right_outer": "rightouter", "full_outer": "fullouter",
        "semi": "semi", "anti": "anti",
    }
    joined = _pandas_join(lf, rf, how_map[how], keys)
    entries = list(left.entries)
    if how in ("semi", "anti"):
        joined = joined[[e.label for e in left.entries]]
    else:
        for ll, rl in coalesce_pairs:
            # USING: expose one coalesced key column under the left label
            if how in ("right_outer", "full_outer"):
                joined[ll] = joined[ll].combine_first(joined[rl])
        entries = entries + [
            e for e in right.entries if e.label not in hidden_right
        ]
        joined = joined[[e.label for e in entries]]
    scope = _Scope(joined.reset_index(drop=True), entries)
    if residual is not None:
        mask = _to_bool_mask(
            _Evaluator(scope, env=env).eval(residual).series
        )
        scope = _Scope(scope.frame[mask].reset_index(drop=True), scope.entries)
    return scope


def _split_conjunction(e: ast.Expr) -> List[ast.Expr]:
    if isinstance(e, ast.Binary) and e.op == "AND":
        return _split_conjunction(e.left) + _split_conjunction(e.right)
    return [e]


def _equi_sides(
    e: ast.Expr, ev_l: "_Evaluator", ev_r: "_Evaluator"
) -> Optional[Tuple[_TS, _TS]]:
    """If ``e`` is ``left_expr = right_expr`` (each side evaluable on one
    scope), evaluate both; else None."""
    if not (isinstance(e, ast.Binary) and e.op == "="):
        return None
    for a, b in ((e.left, e.right), (e.right, e.left)):
        try:
            lts = ev_l.eval(a)
        except SQLExecutionError:
            continue
        try:
            rts = ev_r.eval(b)
        except SQLExecutionError:
            continue
        return lts, rts
    return None


def _to_bool_mask(s: pd.Series) -> np.ndarray:
    return s.astype("boolean").fillna(False).to_numpy(dtype=bool)


# ---- expression evaluation ----------------------------------------------

_NUMERIC = (pa.int64(), pa.float64())


def _is_float(tp: Optional[pa.DataType]) -> bool:
    return tp is not None and pa.types.is_floating(tp)


def _arith_type(
    op: str, lt: Optional[pa.DataType], rt: Optional[pa.DataType]
) -> pa.DataType:
    if op == "/":
        return pa.float64()
    if _is_float(lt) or _is_float(rt):
        return pa.float64()
    if lt is not None and rt is not None and \
            pa.types.is_integer(lt) and pa.types.is_integer(rt):
        return pa.int64()
    return pa.float64()


def _walk_nodes(n: ast.Node, fn: Callable[[ast.Node], None]) -> None:
    fn(n)
    for f in n._fields:
        _walk_val(getattr(n, f), fn)


def _walk_val(v: Any, fn: Callable[[ast.Node], None]) -> None:
    if isinstance(v, ast.Node):
        _walk_nodes(v, fn)
    elif isinstance(v, (list, tuple)):
        for x in v:
            _walk_val(x, fn)


def _transform(n: ast.Node, tr: Callable[[ast.Node], Optional[ast.Node]]) -> Any:
    r = tr(n)
    if r is not None:
        return r
    return type(n)(*[_transform_val(getattr(n, f), tr) for f in n._fields])


def _transform_val(v: Any, tr: Callable[[ast.Node], Optional[ast.Node]]) -> Any:
    if isinstance(v, ast.Node):
        return _transform(v, tr)
    if isinstance(v, list):
        return [_transform_val(x, tr) for x in v]
    if isinstance(v, tuple):
        return tuple(_transform_val(x, tr) for x in v)
    return v


def _static_output_names(
    q: ast.Query, env_names: Dict[str, List[str]], ctes: Dict[str, List[str]]
) -> List[str]:
    """Best-effort output column names of a query WITHOUT executing it
    (for correlation analysis; unknown pieces expand to nothing)."""
    if isinstance(q, ast.With):
        scoped = dict(ctes)
        for name, sub in q.ctes:
            scoped[name.lower()] = _static_output_names(sub, env_names, scoped)
        return _static_output_names(q.body, env_names, scoped)
    if isinstance(q, ast.SetOp):
        return _static_output_names(q.left, env_names, ctes)
    if not isinstance(q, ast.Select):
        return []
    out: List[str] = []
    for i, item in enumerate(q.items):
        if isinstance(item.expr, ast.Star):
            rel = q.from_
            if rel is not None:
                names: List[str] = []

                def visit(n: ast.Node) -> None:
                    if isinstance(n, ast.TableRef):
                        src = ctes.get(n.name.lower()) or env_names.get(
                            n.name.lower()
                        )
                        if src:
                            names.extend(src)
                    elif isinstance(n, ast.SubqueryRef):
                        names.extend(
                            _static_output_names(n.query, env_names, ctes)
                        )

                _walk_nodes(rel, visit)
                out.extend(names)
        else:
            out.append(_output_name(item, i))
    return out


def _outer_refs(
    q: ast.Query, env: Dict[str, "_Table"], outer_scope: "_Scope"
) -> List[ast.Col]:
    """Column references inside ``q`` that do not bind to ANY name
    visible inside the subquery subtree (union-of-subtree name sets;
    unqualified names prefer inner binding, matching SQL's
    innermost-first rule) but DO resolve in the enclosing scope."""
    env_names = {k: list(t.names) for k, t in env.items()}
    quals: Set[str] = set()
    cols: Set[str] = set()
    ctes: Dict[str, List[str]] = {}

    def gather(n: ast.Node) -> None:
        if isinstance(n, ast.With):
            for name, sub in n.ctes:
                ctes[name.lower()] = _static_output_names(
                    sub, env_names, ctes
                )
                quals.add(name.lower())
        elif isinstance(n, ast.TableRef):
            alias = (n.alias or n.name).lower()
            quals.add(alias)
            src = ctes.get(n.name.lower()) or env_names.get(
                n.name.lower()
            ) or []
            cols.update(x.lower() for x in src)
        elif isinstance(n, ast.SubqueryRef):
            quals.add(n.alias.lower())
            cols.update(
                x.lower()
                for x in _static_output_names(n.query, env_names, ctes)
            )
        elif isinstance(n, ast.SelectItem) and n.alias is not None:
            # select aliases count as inner names so ORDER BY/GROUP BY
            # alias refs inside the subquery are never substituted.
            # Known limit: an unqualified OUTER ref colliding with an
            # inner select alias binds nowhere and errors (this engine
            # never resolves aliases in WHERE, subquery or not)
            cols.add(n.alias.lower())

    _walk_nodes(q, gather)
    found: List[ast.Col] = []
    seen: Set[Tuple[Optional[str], str]] = set()

    def classify(n: ast.Node) -> None:
        if not isinstance(n, ast.Col):
            return
        tl = n.table.lower() if n.table is not None else None
        key = (tl, n.name.lower())
        if key in seen:
            return
        if tl is not None:
            if tl in quals:
                return
        elif n.name.lower() in cols:
            return
        try:
            outer_scope.resolve(n.name, n.table)
        except Exception:
            return
        seen.add(key)
        found.append(ast.Col(n.name, n.table))

    _walk_nodes(q, classify)
    return found


def _subst_outer(
    q: ast.Query, refs: List[ast.Col], values: Tuple[Any, ...]
) -> ast.Query:
    """Rebuild the subquery with every outer reference replaced by the
    current outer row's value as a literal."""
    mapping = {
        (
            r.table.lower() if r.table is not None else None,
            r.name.lower(),
        ): v
        for r, v in zip(refs, values)
    }

    def tr(n: ast.Node) -> Optional[ast.Node]:
        if isinstance(n, ast.Col):
            key = (
                n.table.lower() if n.table is not None else None,
                n.name.lower(),
            )
            if key in mapping:
                return ast.Lit(mapping[key])
        return None

    return _transform(q, tr)


class _Evaluator:
    """Evaluates expressions over a scope with SQL null semantics.
    ``env`` (the visible tables) enables subquery expressions; outer
    references inside them correlate to this evaluator's scope."""

    def __init__(
        self,
        scope: _Scope,
        allow_agg: bool = False,
        env: Optional[Dict[str, _Table]] = None,
    ):
        self.scope = scope
        self.allow_agg = allow_agg
        self.env = env

    @property
    def index(self) -> pd.Index:
        return self.scope.frame.index

    def const(self, value: Any, dtype: Optional[pa.DataType]) -> _TS:
        return _TS(pd.Series([value] * len(self.index), index=self.index,
                             dtype=object if value is None else None),
                   dtype)

    def eval(self, e: ast.Expr) -> _TS:
        if isinstance(e, ast.Lit):
            v = e.value
            if v is None:
                return self.const(None, None)
            if isinstance(v, bool):
                return self.const(v, pa.bool_())
            if isinstance(v, int):
                return self.const(v, pa.int64())
            if isinstance(v, float):
                return self.const(v, pa.float64())
            return self.const(v, pa.string())
        if isinstance(e, ast.Col):
            entry = self.scope.resolve(e.name, e.table)
            return _TS(self.scope.frame[entry.label], entry.dtype)
        if isinstance(e, ast.Unary):
            return self._unary(e)
        if isinstance(e, ast.Binary):
            return self._binary(e)
        if isinstance(e, ast.IsNull):
            ts = self.eval(e.operand)
            res = ts.series.isna()
            if e.negated:
                res = ~res
            return _TS(res.astype("boolean"), pa.bool_())
        if isinstance(e, ast.InList):
            return self._in_list(e)
        if isinstance(e, ast.Between):
            low = ast.Binary("<=", e.operand, e.high)
            high = ast.Binary(">=", e.operand, e.low)
            combined: ast.Expr = ast.Binary("AND", high, low)
            if e.negated:
                combined = ast.Unary("NOT", combined)
            return self.eval(combined)
        if isinstance(e, ast.Like):
            return self._like(e)
        if isinstance(e, ast.Case):
            return self._case(e)
        if isinstance(e, ast.Cast):
            return self._cast(e)
        if isinstance(e, ast.Func):
            return self._func(e)
        if isinstance(e, ast.Window):
            return _eval_window(self, e)
        if isinstance(e, ast.ScalarSubquery):
            return self._scalar_subquery(e)
        if isinstance(e, ast.InSubquery):
            return self._in_subquery(e)
        if isinstance(e, ast.Exists):
            return self._exists(e)
        if isinstance(e, ast.Star):
            raise SQLExecutionError("wildcard not allowed in this context")
        raise SQLExecutionError(f"unsupported expression {type(e).__name__}")

    def _subquery_tables(
        self, q: ast.Query
    ) -> Tuple[Optional[_Table], Optional[List[_Table]]]:
        """Execute a subquery: uncorrelated -> (table, None), executed
        once; correlated -> (None, per-row tables), executed once per
        DISTINCT outer-reference tuple."""
        env = self.env if self.env is not None else {}
        refs = _outer_refs(q, env, self.scope)
        if not refs:
            return _run(q, env), None
        series = [self.eval(c).series for c in refs]
        cache: Dict[Tuple[Any, ...], _Table] = {}
        per_row: List[_Table] = []
        for i in range(len(self.index)):
            vals = []
            for s in series:
                v = s.iloc[i]
                if pd.isna(v):
                    v = None
                elif hasattr(v, "item"):
                    v = v.item()
                vals.append(v)
            key = tuple(vals)
            if key not in cache:
                q2 = _subst_outer(q, refs, key)
                cache[key] = _run(q2, env)
            per_row.append(cache[key])
        return None, per_row

    def _scalar_subquery(self, e: ast.ScalarSubquery) -> _TS:
        once, per_row = self._subquery_tables(e.query)

        def _value(t: _Table) -> Any:
            if len(t.names) != 1:
                raise SQLExecutionError(
                    "scalar subquery must return exactly one column"
                )
            if len(t.frame) > 1:
                raise SQLExecutionError(
                    "scalar subquery returned more than one row"
                )
            if len(t.frame) == 0:
                return None
            v = t.frame.iloc[0, 0]
            return None if pd.isna(v) else v

        if once is not None:
            return self.const(_value(once), once.types[0])
        assert per_row is not None
        tp = per_row[0].types[0] if per_row else None
        vals = [_value(t) for t in per_row]
        ser = pd.Series(vals, index=self.index)  # infers; None -> NaN
        return _TS(ser, tp)

    def _in_subquery(self, e: ast.InSubquery) -> _TS:
        ots = self.eval(e.operand)
        once, per_row = self._subquery_tables(e.query)

        def _membership(v: Any, t: _Table) -> Any:
            """SQL 3VL: match -> True; no match but NULLs present ->
            NULL; empty set -> False; NULL operand -> NULL unless the
            set is empty."""
            if len(t.names) != 1:
                raise SQLExecutionError(
                    "IN subquery must return exactly one column"
                )
            col = t.frame.iloc[:, 0]
            if len(col) == 0:
                return False
            if pd.isna(v):
                return None
            nn = col.dropna()
            hit = bool((nn == v).any()) if len(nn) else False
            if hit:
                return True
            return None if len(nn) < len(col) else False

        if once is not None:
            # vectorized path: one isin over the precomputed value set
            if len(once.names) != 1:
                raise SQLExecutionError(
                    "IN subquery must return exactly one column"
                )
            col = once.frame.iloc[:, 0]
            nn = col.dropna()
            has_null = len(nn) < len(col)
            if len(col) == 0:
                res = pd.Series(False, index=self.index).astype("boolean")
            else:
                hit = ots.series.isin(nn).astype("boolean")
                if has_null:
                    hit[~hit.fillna(False).to_numpy(dtype=bool)] = pd.NA
                hit[ots.series.isna().to_numpy(dtype=bool)] = pd.NA
                res = hit
            if e.negated:
                res = ~res
            return _TS(res, pa.bool_())
        vals = []
        for i in range(len(self.index)):
            m = _membership(ots.series.iloc[i], per_row[i])  # type: ignore
            if e.negated and m is not None:
                m = not m
            vals.append(m)
        return _TS(
            pd.Series(vals, index=self.index, dtype=object).astype(
                "boolean"
            ),
            pa.bool_(),
        )

    def _exists(self, e: ast.Exists) -> _TS:
        once, per_row = self._subquery_tables(e.query)
        if once is not None:
            return self.const(len(once.frame) > 0, pa.bool_())
        assert per_row is not None
        vals = [len(t.frame) > 0 for t in per_row]
        return _TS(
            pd.Series(vals, index=self.index, dtype="boolean"), pa.bool_()
        )

    def _unary(self, e: ast.Unary) -> _TS:
        ts = self.eval(e.operand)
        if e.op == "NOT":
            return _TS(~ts.series.astype("boolean"), pa.bool_())
        if e.op == "-":
            return _TS(-pd.to_numeric(ts.series), ts.dtype or pa.float64())
        return ts  # unary +

    def _binary(self, e: ast.Binary) -> _TS:
        op = e.op
        if op in ("AND", "OR"):
            lb = self.eval(e.left).series.astype("boolean")
            rb = self.eval(e.right).series.astype("boolean")
            return _TS(lb & rb if op == "AND" else lb | rb, pa.bool_())
        lts = self.eval(e.left)
        rts = self.eval(e.right)
        if op in ("=", "<>", "<", "<=", ">", ">="):
            return self._compare(op, lts, rts)
        if op == "||":
            ls = lts.series.astype(object)
            rs = rts.series.astype(object)
            nulls = ls.isna() | rs.isna()
            res = ls.where(nulls, ls.astype(str) + rs.astype(str))
            res[nulls] = None
            return _TS(res, pa.string())
        left, right = lts.series, rts.series
        if op == "+":
            res = left + right
        elif op == "-":
            res = left - right
        elif op == "*":
            res = left * right
        elif op == "/":
            res = pd.to_numeric(left, errors="coerce").astype("float64") / \
                pd.to_numeric(right, errors="coerce")
        elif op == "%":
            res = sql_fmod(pd.to_numeric(left), pd.to_numeric(right))
        else:
            raise SQLExecutionError(f"unsupported operator {op}")
        return _TS(res, _arith_type(op, lts.dtype, rts.dtype))

    def _compare(self, op: str, lts: _TS, rts: _TS) -> _TS:
        left, right = lts.series, rts.series
        nulls = left.isna() | right.isna()
        func: Dict[str, Callable[[Any, Any], Any]] = {
            "=": lambda a, b: a == b,
            "<>": lambda a, b: a != b,
            "<": lambda a, b: a < b,
            "<=": lambda a, b: a <= b,
            ">": lambda a, b: a > b,
            ">=": lambda a, b: a >= b,
        }
        # compare only non-null positions: object-dtype series (e.g.
        # subquery results) would raise on None-vs-value otherwise
        res = pd.Series(pd.NA, index=left.index, dtype="boolean")
        m = (~nulls).to_numpy(dtype=bool)
        if m.any():
            with np.errstate(invalid="ignore"):
                r = func[op](left[m], right[m])
            res[m] = np.asarray(r, dtype=bool)
        return _TS(res, pa.bool_())

    def _in_list(self, e: ast.InList) -> _TS:
        ts = self.eval(e.operand)
        values = []
        for item in e.items:
            if not isinstance(item, ast.Lit):
                raise SQLExecutionError("IN list items must be literals")
            values.append(item.value)
        res = ts.series.isin([v for v in values if v is not None])
        res = res.astype("boolean")
        if e.negated:
            res = ~res
        res[ts.series.isna().to_numpy(dtype=bool)] = pd.NA
        return _TS(res, pa.bool_())

    def _like(self, e: ast.Like) -> _TS:
        ts = self.eval(e.operand)
        s = ts.series.astype(object)
        nulls = s.isna()
        if isinstance(e.pattern, ast.Lit):
            # the ONE anchored like->regex helper all three evaluators
            # share (device LUTs, pandas_eval, this runner): fullmatch
            # with \A...\Z — str.match + ^...$ would also accept a
            # trailing newline and silently diverge (ADVICE r5 #3)
            regex = compile_like_regex(str(e.pattern.value))
            matched = s.where(
                nulls, s.astype(str).str.fullmatch(regex, na=False)
            )
            res = matched.astype("boolean")
        else:
            # dynamic (column-valued) pattern: compile per DISTINCT
            # pattern value; NULL pattern -> NULL like any comparison
            p = self.eval(e.pattern).series
            nulls = nulls | p.isna()
            cache: Dict[Any, Any] = {}
            vals: List[Any] = []
            for v, pv in zip(s, p):
                if pd.isna(v) or pd.isna(pv):
                    vals.append(None)
                    continue
                rx = cache.get(pv)
                if rx is None:
                    rx = compile_like_regex(str(pv))
                    cache[pv] = rx
                vals.append(rx.fullmatch(str(v)) is not None)
            res = pd.Series(vals, index=s.index, dtype=object).astype(
                "boolean"
            )
        if e.negated:
            res = ~res
        res[nulls.to_numpy(dtype=bool)] = pd.NA
        return _TS(res, pa.bool_())

    def _case(self, e: ast.Case) -> _TS:
        whens = e.whens
        if e.operand is not None:
            whens = [
                (ast.Binary("=", e.operand, cond), val) for cond, val in whens
            ]
        default_ts = self.eval(e.default) if e.default is not None else \
            self.const(None, None)
        res = default_ts.series.astype(object)
        dtype = default_ts.dtype
        decided = pd.Series(False, index=self.index)
        for cond, val in whens:
            mask = _to_bool_mask(self.eval(cond).series) & ~decided.to_numpy()
            vts = self.eval(val)
            res = res.where(~mask, vts.series.astype(object))
            decided = decided | mask
            if dtype is None:
                dtype = vts.dtype
            elif vts.dtype is not None and not dtype.equals(vts.dtype):
                dtype = _arith_type("+", dtype, vts.dtype) \
                    if pa.types.is_integer(dtype) or pa.types.is_floating(dtype) \
                    else dtype
        return _TS(res, dtype)

    def _cast(self, e: ast.Cast) -> _TS:
        ts = self.eval(e.operand)
        tp = _SQL_TYPES.get(e.type_name)
        if tp is None:
            raise SQLExecutionError(f"unknown type {e.type_name}")
        s = ts.series
        try:
            if pa.types.is_integer(tp):
                num = pd.to_numeric(s, errors="raise")
                s = pd.Series(num, index=s.index).astype("Int64")
            elif pa.types.is_floating(tp):
                s = pd.to_numeric(s, errors="raise").astype("float64")
            elif pa.types.is_boolean(tp):
                s = s.map(_to_bool_scalar).astype("boolean")
            elif pa.types.is_string(tp):
                nulls = s.isna()
                s = s.astype(object)
                s = s.where(nulls, s.map(_to_str_scalar))
                s[nulls] = None
        except (ValueError, TypeError) as ex:
            raise SQLExecutionError(f"cast failed: {ex}") from ex
        return _TS(s, tp)

    def _func(self, e: ast.Func) -> _TS:
        name = e.name
        if name in _AGG_FUNCS:
            raise SQLExecutionError(
                f"aggregation {name} not allowed in this context"
            )
        impl = _SCALAR_FUNCS.get(name)
        if impl is None:
            raise SQLExecutionError(f"unsupported function {name}")
        args = [self.eval(a) for a in e.args]
        return impl(self, args)


def _to_bool_scalar(v: Any) -> Any:
    if v is None or (isinstance(v, float) and np.isnan(v)):
        return None
    if isinstance(v, str):
        return v.strip().lower() in ("true", "1", "t", "yes")
    return bool(v)


def _to_str_scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(v)
    return str(v)


_SQL_TYPES: Dict[str, pa.DataType] = {
    "int": pa.int32(), "integer": pa.int32(), "tinyint": pa.int8(),
    "smallint": pa.int16(), "bigint": pa.int64(), "long": pa.int64(),
    "float": pa.float32(), "real": pa.float32(),
    "double": pa.float64(), "decimal": pa.float64(), "numeric": pa.float64(),
    "string": pa.string(), "varchar": pa.string(), "char": pa.string(),
    "text": pa.string(),
    "boolean": pa.bool_(), "bool": pa.bool_(),
    "date": pa.date32(), "timestamp": pa.timestamp("us"),
    "datetime": pa.timestamp("us"),
    "binary": pa.binary(), "bytes": pa.binary(),
}


# ---- scalar function registry -------------------------------------------


def _fn_coalesce(ev: _Evaluator, args: List[_TS]) -> _TS:
    res = args[0].series
    dtype = args[0].dtype
    for a in args[1:]:
        res = res.combine_first(a.series)
        dtype = dtype or a.dtype
    return _TS(res, dtype)


def _fn_nullif(ev: _Evaluator, args: List[_TS]) -> _TS:
    a, b = args
    eq = _to_bool_mask(ev._compare("=", a, b).series)
    res = a.series.astype(object).where(~eq, None)
    return _TS(res, a.dtype)


def _fn_if(ev: _Evaluator, args: List[_TS]) -> _TS:
    cond, yes, no = args
    mask = _to_bool_mask(cond.series)
    res = yes.series.astype(object).where(mask, no.series.astype(object))
    return _TS(res, yes.dtype or no.dtype)


def _num_fn(f: Callable[[pd.Series], pd.Series],
            out: Optional[pa.DataType] = pa.float64()) -> Callable:
    def impl(ev: _Evaluator, args: List[_TS]) -> _TS:
        s = pd.to_numeric(args[0].series, errors="coerce")
        # out-of-domain inputs (SQRT(-4), LN(0)) yield NaN by SQL intent,
        # not as a numpy anomaly — keep -W error runs clean
        with np.errstate(invalid="ignore", divide="ignore"):
            res = f(s)
        return _TS(res, out if out is not None else args[0].dtype)
    return impl


def _fn_round(ev: _Evaluator, args: List[_TS]) -> _TS:
    s = pd.to_numeric(args[0].series, errors="coerce")
    digits = 0
    if len(args) > 1:
        digits = int(args[1].series.iloc[0]) if len(args[1].series) else 0
    return _TS(s.round(digits), pa.float64())


def _fn_power(ev: _Evaluator, args: List[_TS]) -> _TS:
    a = pd.to_numeric(args[0].series, errors="coerce")
    b = pd.to_numeric(args[1].series, errors="coerce")
    return _TS(a ** b, pa.float64())


def _fn_mod(ev: _Evaluator, args: List[_TS]) -> _TS:
    a = pd.to_numeric(args[0].series, errors="coerce")
    b = pd.to_numeric(args[1].series, errors="coerce")
    return _TS(sql_fmod(a, b), args[0].dtype or pa.int64())


def _str_fn(f: Callable[[pd.Series], pd.Series],
            out: pa.DataType = pa.string()) -> Callable:
    def impl(ev: _Evaluator, args: List[_TS]) -> _TS:
        s = args[0].series
        nulls = s.isna()
        res = f(s.astype(object).astype(str))
        res = pd.Series(res, index=s.index).astype(object)
        res[nulls.to_numpy(dtype=bool)] = None
        return _TS(res, out)
    return impl


def _fn_substring(ev: _Evaluator, args: List[_TS]) -> _TS:
    """Per-row 1-based start and optional length (standard SQL); NULL
    operand/start/length -> NULL. Shared helper with the column-algebra
    evaluator (``pandas_eval.sql_substring``)."""
    from fugue_tpu.column.pandas_eval import sql_substring

    starts = pd.to_numeric(args[1].series, errors="coerce")
    lens = (
        pd.to_numeric(args[2].series, errors="coerce")
        if len(args) > 2
        else None
    )
    return _TS(sql_substring(args[0].series, starts, lens), pa.string())


def _fn_concat(ev: _Evaluator, args: List[_TS]) -> _TS:
    res = None
    nulls = None
    for a in args:
        s = a.series
        nulls = s.isna() if nulls is None else (nulls | s.isna())
        part = s.astype(object).astype(str)
        res = part if res is None else res + part
    res = res.astype(object)
    res[nulls.to_numpy(dtype=bool)] = None
    return _TS(res, pa.string())


def _fn_replace(ev: _Evaluator, args: List[_TS]) -> _TS:
    s = args[0].series
    nulls = s.isna()
    old = str(args[1].series.iloc[0]) if len(args[1].series) else ""
    new = str(args[2].series.iloc[0]) if len(args[2].series) else ""
    res = s.astype(object).astype(str).str.replace(old, new, regex=False)
    res = res.astype(object)
    res[nulls.to_numpy(dtype=bool)] = None
    return _TS(res, pa.string())


_SCALAR_FUNCS: Dict[str, Callable[[ _Evaluator, List[_TS]], _TS]] = {
    "coalesce": _fn_coalesce,
    "nullif": _fn_nullif,
    "if": _fn_if,
    "iif": _fn_if,
    "abs": _num_fn(lambda s: s.abs(), None),
    "round": _fn_round,
    "floor": _num_fn(np.floor, pa.int64()),
    "ceil": _num_fn(np.ceil, pa.int64()),
    "ceiling": _num_fn(np.ceil, pa.int64()),
    "sqrt": _num_fn(np.sqrt),
    "exp": _num_fn(np.exp),
    "ln": _num_fn(np.log),
    "log": _num_fn(np.log),
    "log2": _num_fn(np.log2),
    "log10": _num_fn(np.log10),
    "sin": _num_fn(np.sin),
    "cos": _num_fn(np.cos),
    "tan": _num_fn(np.tan),
    "sign": _num_fn(np.sign, pa.int64()),
    "power": _fn_power,
    "pow": _fn_power,
    "mod": _fn_mod,
    "upper": _str_fn(lambda s: s.str.upper()),
    "ucase": _str_fn(lambda s: s.str.upper()),
    "lower": _str_fn(lambda s: s.str.lower()),
    "lcase": _str_fn(lambda s: s.str.lower()),
    "length": _str_fn(lambda s: s.str.len(), pa.int64()),
    "len": _str_fn(lambda s: s.str.len(), pa.int64()),
    "trim": _str_fn(lambda s: s.str.strip()),
    "ltrim": _str_fn(lambda s: s.str.lstrip()),
    "rtrim": _str_fn(lambda s: s.str.rstrip()),
    "reverse": _str_fn(lambda s: s.str[::-1]),
    "substring": _fn_substring,
    "substr": _fn_substring,
    "concat": _fn_concat,
    "replace": _fn_replace,
}


# ---- aggregation --------------------------------------------------------

_AGG_FUNCS = {
    "count", "sum", "avg", "mean", "min", "max", "first", "last",
    "first_value", "last_value", "stddev", "stddev_samp", "stddev_pop",
    "variance", "var_samp", "var_pop", "median",
}


def _contains_agg(e: ast.Expr) -> bool:
    if isinstance(e, ast.Window):
        # a window expression is row-level: its inner aggregate runs over
        # the window frame, not the GROUP BY
        return False
    if isinstance(e, ast.Func) and e.name in _AGG_FUNCS:
        return True
    return any(_contains_agg(c) for c in _children(e))


def _contains_window(e: Optional[ast.Expr]) -> bool:
    if e is None:
        return False
    if isinstance(e, ast.Window):
        return True
    return any(_contains_window(c) for c in _children(e))


def _children(e: ast.Expr) -> List[ast.Expr]:
    out: List[ast.Expr] = []
    if isinstance(e, ast.Window):
        return (
            list(e.partition_by)
            + [o.expr for o in e.order_by]
            + [a for a in e.func.args if not isinstance(a, ast.Star)]
        )
    if isinstance(e, ast.Unary):
        out = [e.operand]
    elif isinstance(e, ast.Binary):
        out = [e.left, e.right]
    elif isinstance(e, ast.Func):
        out = [a for a in e.args if not isinstance(a, ast.Star)]
    elif isinstance(e, ast.Case):
        out = [x for pair in e.whens for x in pair]
        if e.operand is not None:
            out.append(e.operand)
        if e.default is not None:
            out.append(e.default)
    elif isinstance(e, ast.Cast):
        out = [e.operand]
    elif isinstance(e, (ast.IsNull, ast.Like, ast.InList)):
        out = [e.operand]
        if isinstance(e, ast.Like):
            out.append(e.pattern)
    elif isinstance(e, ast.Between):
        out = [e.operand, e.low, e.high]
    elif isinstance(e, ast.InSubquery):
        # the subquery body is its OWN scope — only the operand belongs
        # to this one (ScalarSubquery/Exists contribute nothing)
        out = [e.operand]
    return out


_WINDOW_ONLY_FUNCS = {
    "row_number", "rank", "dense_rank", "lag", "lead",
    "ntile", "percent_rank", "cume_dist", "nth_value",
}

# aggregates that honor an explicit frame clause (ranking and lag/lead
# are frame-independent by the standard; the frame is ignored for them)
_FRAME_AGGS = {
    "count", "sum", "avg", "mean", "min", "max",
    "first", "first_value", "last", "last_value", "nth_value",
}

_NOT_LITERAL = object()


def _literal_value(e: ast.Expr) -> Any:
    """The python value of a (possibly sign-negated) literal expression."""
    if isinstance(e, ast.Lit):
        return e.value
    if (
        isinstance(e, ast.Unary)
        and e.op == "-"
        and isinstance(e.operand, ast.Lit)
        and isinstance(e.operand.value, (int, float))
        and not isinstance(e.operand.value, bool)
    ):
        return -e.operand.value
    return _NOT_LITERAL


def _eval_window(ev: "_Evaluator", e: ast.Window) -> _TS:
    """Window functions over the evaluator's scope rows.

    Semantics match the reference's DuckDB/SparkSQL backends
    (``/root/reference/fugue_duckdb/execution_engine.py:37``): ranking
    functions need ORDER BY; aggregates-without-ORDER BY see the whole
    partition; aggregates-with-ORDER BY use the SQL default frame (RANGE
    UNBOUNDED PRECEDING .. CURRENT ROW), so peers — rows tying on every
    ORDER BY key — share one value."""
    name = e.func.name
    if name not in _WINDOW_ONLY_FUNCS and name not in _AGG_FUNCS:
        raise SQLExecutionError(f"unsupported window function {name}")
    if e.func.distinct:
        raise SQLExecutionError("DISTINCT is not supported in windows")
    if name in (
        "row_number", "rank", "dense_rank", "percent_rank", "cume_dist"
    ) and e.func.args:
        raise SQLExecutionError(f"{name}() takes no arguments")
    idx = ev.index
    if not idx.is_unique:  # pragma: no cover - scopes use fresh indexes
        raise SQLExecutionError("window over non-unique row index")
    # several items commonly share one OVER clause: memoize the sorted
    # order / partition / peer machinery per (partition_by, order_by)
    # on the evaluator (review finding)
    wcache = getattr(ev, "_window_clause_cache", None)
    if wcache is None:
        wcache = ev._window_clause_cache = {}  # type: ignore[attr-defined]
    ckey = (tuple(e.partition_by), tuple(e.order_by))
    if ckey in wcache:
        order, same_part, part_id, is_peer, peer_id = wcache[ckey]
    else:
        work = pd.DataFrame(index=idx)
        pcols: List[str] = []
        for j, p in enumerate(e.partition_by):
            work[f"p{j}"] = ev.eval(p).series
            pcols.append(f"p{j}")
        # partition keys lead the sort: the shift-based partition/peer
        # detection below requires each partition to be CONTIGUOUS
        ocols: List[str] = []
        sort_cols: List[str] = list(pcols)
        sort_asc: List[bool] = [True] * len(pcols)
        for j, o in enumerate(e.order_by):
            c = f"s{j}"
            work[c] = ev.eval(o.expr).series
            ocols.append(c)
            nulls_first = (
                (o.nulls == "FIRST") if o.nulls is not None else False
            )
            work[f"n_{c}"] = (
                (~work[c].isna()) if nulls_first else work[c].isna()
            )
            sort_cols.extend([f"n_{c}", c])
            sort_asc.extend([True, o.asc])
        if sort_cols:
            order = work.sort_values(
                sort_cols, ascending=sort_asc, kind="stable"
            ).index
        else:
            order = idx
        sw0 = work.loc[order]

        def _same_as_prev(col: str) -> pd.Series:
            s = sw0[col]
            prev = s.shift()
            return (s == prev).fillna(False) | (s.isna() & prev.isna())

        if len(sw0) > 0:
            same_part = pd.Series(True, index=sw0.index)
            for c in pcols:
                same_part &= _same_as_prev(c)
            same_part.iloc[0] = False
            part_id = (~same_part).cumsum()
            same_order = pd.Series(True, index=sw0.index)
            for c in ocols:
                same_order &= _same_as_prev(c)
            is_peer = same_part & same_order
            peer_id = (~is_peer).cumsum()
        else:
            same_part = part_id = is_peer = peer_id = pd.Series(
                [], dtype="int64"
            )
        wcache[ckey] = (order, same_part, part_id, is_peer, peer_id)

    n = len(order)
    if n == 0:
        # empty input: keep the same output TYPE a non-empty input gives
        if name in ("row_number", "rank", "dense_rank", "count", "ntile"):
            tp0: Optional[pa.DataType] = pa.int64()
        elif name in ("avg", "mean", "percent_rank", "cume_dist"):
            tp0 = pa.float64()
        else:
            args0 = e.func.args
            if len(args0) >= 1 and not isinstance(args0[0], ast.Star):
                atp = ev.eval(args0[0]).dtype
            else:
                atp = pa.int64()
            if name == "sum":
                tp0 = (
                    pa.int64()
                    if atp is not None and pa.types.is_integer(atp)
                    else pa.float64()
                )
            else:  # min/max/lag/lead/first/last: the argument's type
                tp0 = atp
        return _TS(pd.Series([], index=idx, dtype=object), tp0)
    grp = part_id.groupby(part_id)
    rn = grp.cumcount() + 1

    def _back(s: pd.Series, tp: Optional[pa.DataType]) -> _TS:
        return _TS(s.reindex(idx), tp)

    if name == "row_number":
        if not e.order_by:
            raise SQLExecutionError("row_number() requires ORDER BY")
        return _back(rn.astype("int64"), pa.int64())
    if name in ("rank", "dense_rank"):
        if not e.order_by:
            raise SQLExecutionError(f"{name}() requires ORDER BY")
        if name == "rank":
            r = rn.where(~is_peer).groupby(part_id).ffill()
        else:
            r = (~is_peer).astype("int64").groupby(part_id).cumsum()
        return _back(r.astype("int64"), pa.int64())
    if name in ("ntile", "percent_rank", "cume_dist"):
        if not e.order_by:
            raise SQLExecutionError(f"{name}() requires ORDER BY")
        psize = grp.transform("size")
        if name == "ntile":
            if len(e.func.args) != 1:
                raise SQLExecutionError("ntile takes one int argument")
            buckets = _literal_value(e.func.args[0])
            if not isinstance(buckets, int) or isinstance(buckets, bool) \
                    or buckets < 1:
                raise SQLExecutionError(
                    "ntile argument must be a positive int literal"
                )
            # first (psize % n) buckets get one extra row (standard SQL)
            q_, rem = psize // buckets, psize % buckets
            cutoff = rem * (q_ + 1)
            in_head = rn <= cutoff
            head = (rn - 1) // (q_ + 1).clip(lower=1) + 1
            tail = rem + (rn - 1 - cutoff) // q_.clip(lower=1) + 1
            r = head.where(in_head, tail)
            return _back(r.astype("int64"), pa.int64())
        if name == "percent_rank":
            srank = rn.where(~is_peer).groupby(part_id).ffill()
            denom = (psize - 1).clip(lower=1)
            r = (srank - 1) / denom
            r = r.where(psize > 1, 0.0)
        else:  # cume_dist: rows <= current row's peer group, over psize
            last_rn = rn.groupby(peer_id).transform("max")
            r = last_rn / psize
        return _back(r.astype("float64"), pa.float64())
    if name in ("lag", "lead"):
        if len(e.func.args) < 1 or len(e.func.args) > 3 or isinstance(
            e.func.args[0], ast.Star
        ):
            raise SQLExecutionError(f"{name} takes (expr[, offset[, default]])")
        offset = 1
        default: Any = None
        if len(e.func.args) >= 2:
            ov = _literal_value(e.func.args[1])
            if not isinstance(ov, int) or isinstance(ov, bool):
                raise SQLExecutionError(f"{name} offset must be an int literal")
            offset = ov
        if len(e.func.args) == 3:
            default = _literal_value(e.func.args[2])
            if default is _NOT_LITERAL:
                raise SQLExecutionError(f"{name} default must be a literal")
        if offset < 0:
            raise SQLExecutionError(f"{name} offset must be >= 0")
        vts = ev.eval(e.func.args[0])
        vs = vts.series.loc[order]
        shifted = vs.groupby(part_id).shift(offset if name == "lag" else -offset)
        if default is not None:
            # the default fills only OUT-OF-PARTITION positions; a shifted-in
            # NULL source value stays NULL (review finding)
            if name == "lag":
                oob = rn <= offset
            else:
                psize = grp.transform("size")
                oob = rn > psize - offset
            shifted = shifted.where(~oob, default)
        tp = vts.dtype
        if (
            default is not None
            and isinstance(default, float)
            and tp is not None
            and pa.types.is_integer(tp)
        ):
            tp = pa.float64()  # a float fill widens an int column
        return _back(shifted, tp)

    if (name == "nth_value" or e.frame is not None) and name in _FRAME_AGGS:
        return _eval_frame_window(ev, e, name, order, part_id, peer_id, _back)

    # aggregates over the window
    star = len(e.func.args) == 1 and isinstance(e.func.args[0], ast.Star)
    if star:
        if name != "count":
            raise SQLExecutionError(f"{name}(*) is not valid")
        vs = pd.Series(1, index=order)
        vts_tp: Optional[pa.DataType] = pa.int64()
    else:
        if len(e.func.args) != 1:
            raise SQLExecutionError(f"window {name} takes one argument")
        vts = ev.eval(e.func.args[0])
        vs = vts.series.loc[order]
        vts_tp = vts.dtype
    sum_tp = (
        pa.int64()
        if vts_tp is not None and pa.types.is_integer(vts_tp)
        else pa.float64()
    )

    def _positional_pick(group_id: pd.Series, first: bool) -> pd.Series:
        """POSITIONAL first/last value per group — unlike pandas
        transform('first'/'last'), a NULL boundary row yields NULL
        (review finding; matches _agg_result's iloc semantics)."""
        new_group = group_id != group_id.shift()
        marker = new_group if first else new_group.shift(-1, fill_value=True)
        mapping = pd.Series(
            vs[marker].values, index=group_id[marker].values
        )
        return group_id.map(mapping)

    if not e.order_by:
        g = vs.groupby(part_id)
        if name == "count":
            r = (
                g.transform("size")
                if star
                else vs.notna().groupby(part_id).transform("sum")
            )
            return _back(r.astype("int64"), pa.int64())
        if name in ("sum", "avg", "mean"):
            cnt = vs.notna().groupby(part_id).transform("sum")
            tot = vs.fillna(0).groupby(part_id).transform("sum")
            if name == "sum":
                return _back(tot.where(cnt > 0), sum_tp)
            return _back(
                (tot / cnt).where(cnt > 0), pa.float64()
            )
        if name in ("min", "max"):
            r = g.transform(name)
            return _back(r, vts_tp)
        if name in ("first", "first_value", "last", "last_value"):
            r = _positional_pick(part_id, first=name.startswith("first"))
            return _back(r, vts_tp)
        raise SQLExecutionError(f"unsupported window aggregate {name}")
    # running (default-frame) aggregates; peers share the group's last value
    cnt = (
        grp.cumcount() + 1
        if star
        else vs.notna().astype("int64").groupby(part_id).cumsum()
    )
    if name == "count":
        r = cnt
    elif name in ("sum", "avg", "mean"):
        tot = vs.fillna(0).groupby(part_id).cumsum()
        r = tot.where(cnt > 0) if name == "sum" else (tot / cnt).where(cnt > 0)
    elif name in ("min", "max"):
        if vs.dtype.kind in "biufcmM":
            r = getattr(vs.groupby(part_id), f"cum{name}")()
            # cummin/cummax leave NaN AT null positions; SQL's
            # null-ignoring frame carries the prior extremum forward
            # (review finding)
            r = r.groupby(part_id).ffill()
        else:
            # strings/objects: pandas cummin rejects them — accumulate
            # per group (review finding)
            pick = min if name == "min" else max

            def _acc(s: pd.Series) -> pd.Series:
                best: Any = None
                out: List[Any] = []
                for v in s:
                    if not pd.isna(v):
                        best = v if best is None else pick(best, v)
                    out.append(best)
                return pd.Series(out, index=s.index, dtype=object)

            r = vs.groupby(part_id, group_keys=False).apply(_acc)
    elif name in ("first", "first_value"):
        r = _positional_pick(part_id, first=True)
    elif name in ("last", "last_value"):
        # frame ends at the current row's peer group: its last row's value
        r = _positional_pick(peer_id, first=False)
    else:
        raise SQLExecutionError(f"unsupported running window {name}")
    r = r.groupby(peer_id).transform("last")
    tp = (
        pa.int64()
        if name == "count"
        else (
            sum_tp
            if name == "sum"
            else (pa.float64() if name in ("avg", "mean") else vts_tp)
        )
    )
    return _back(r, tp)


def _frame_bound_check(b: Tuple[str, Any], unit: str) -> Tuple[str, Any]:
    kind, nv = b
    if kind in ("p", "f"):
        if unit in ("rows", "groups"):
            if not isinstance(nv, int) or isinstance(nv, bool) or nv < 0:
                raise SQLExecutionError(
                    f"{unit.upper()} frame offsets must be "
                    "non-negative integers"
                )
        else:
            if isinstance(nv, bool) or not isinstance(nv, (int, float)) \
                    or nv < 0:
                raise SQLExecutionError(
                    "RANGE frame offsets must be non-negative numbers"
                )
    return kind, nv


def _range_minmax(
    codes: np.ndarray, lo: np.ndarray, hi: np.ndarray, is_min: bool
) -> np.ndarray:
    """Vectorized range-min/max queries over ``codes`` via a sparse
    table: O(n log n) build, O(1) per query. ``lo``/``hi`` are inclusive
    and must satisfy ``0 <= lo <= hi < n`` (callers mask empty frames
    afterwards)."""
    n = len(codes)
    op = np.minimum if is_min else np.maximum
    st = [codes]
    w = 1
    while 2 * w <= n:
        prev = st[-1]
        m = n - 2 * w + 1
        st.append(op(prev[:m], prev[w:w + m]))
        w *= 2
    length = hi - lo + 1
    k = np.floor(np.log2(np.maximum(length, 1))).astype(np.int64)
    out = np.empty(len(lo), dtype=codes.dtype)
    for kk in range(len(st)):
        m = k == kk
        if not m.any():
            continue
        w = 1 << kk
        out[m] = op(st[kk][lo[m]], st[kk][hi[m] - w + 1])
    return out


def _eval_frame_window(
    ev: "_Evaluator",
    e: ast.Window,
    name: str,
    order: pd.Index,
    part_id: pd.Series,
    peer_id: pd.Series,
    _back: Callable[[pd.Series, Optional[pa.DataType]], _TS],
) -> _TS:
    """Aggregates (and first/last/nth_value) over an EXPLICIT frame
    clause — ROWS / RANGE / GROUPS, BETWEEN any pair of bounds — plus
    ``nth_value`` under the default frame. Semantics follow the
    standard as the reference's DuckDB backend executes it
    (``/root/reference/fugue_duckdb/execution_engine.py:37``):
    positional bounds clip to the partition, empty frames yield NULL
    (COUNT 0), RANGE offsets need exactly one numeric ORDER BY key and
    resolve to the null peer group on null keys."""
    frame = e.frame
    if frame is None:  # nth_value under the default frame
        if e.order_by:
            frame = ast.Frame("range", ("up", None), ("c", None))
        else:
            frame = ast.Frame("rows", ("up", None), ("uf", None))
    unit = frame.unit
    if unit == "groups" and not e.order_by:
        raise SQLExecutionError("GROUPS frames require ORDER BY")
    skind, sn = _frame_bound_check(frame.start, unit)
    ekind, en = _frame_bound_check(frame.end, unit)

    n = len(order)
    pos = np.arange(n, dtype=np.int64)
    pid = part_id.to_numpy()
    new_part = np.empty(n, dtype=bool)
    new_part[0] = True
    new_part[1:] = pid[1:] != pid[:-1]
    p_starts = np.flatnonzero(new_part)
    p_ends = np.append(p_starts[1:], n) - 1
    pidx = np.cumsum(new_part) - 1
    part_start = p_starts[pidx]
    part_end = p_ends[pidx]
    gid = peer_id.to_numpy()
    new_peer = np.empty(n, dtype=bool)
    new_peer[0] = True
    new_peer[1:] = gid[1:] != gid[:-1]
    g_starts = np.flatnonzero(new_peer)
    g_ends = np.append(g_starts[1:], n) - 1
    g_glob = np.cumsum(new_peer) - 1
    peer_start = g_starts[g_glob]
    peer_end = g_ends[g_glob]

    # ---- the argument ----------------------------------------------------
    star = len(e.func.args) >= 1 and isinstance(e.func.args[0], ast.Star)
    nth = 0
    if name == "nth_value":
        if len(e.func.args) != 2 or star:
            raise SQLExecutionError("nth_value takes (expr, n)")
        nv = _literal_value(e.func.args[1])
        if not isinstance(nv, int) or isinstance(nv, bool) or nv < 1:
            raise SQLExecutionError(
                "nth_value position must be a positive int literal"
            )
        nth = nv
    elif star:
        if name != "count" or len(e.func.args) != 1:
            raise SQLExecutionError(f"{name}(*) is not valid")
    elif len(e.func.args) != 1:
        raise SQLExecutionError(f"window {name} takes one argument")
    if star:
        vs = pd.Series(1, index=order)
        vts_tp: Optional[pa.DataType] = pa.int64()
    else:
        vts = ev.eval(e.func.args[0])
        vs = vts.series.loc[order]
        vts_tp = vts.dtype

    # ---- frame bounds as positions ---------------------------------------
    def _rows_bound(kind: str, nv: Any, is_start: bool) -> np.ndarray:
        if kind == "up":
            return part_start.copy()
        if kind == "uf":
            return part_end.copy()
        if kind == "c":
            return pos.copy()
        off = nv if kind == "f" else -nv
        return pos + off

    def _groups_bound(kind: str, nv: Any, is_start: bool) -> np.ndarray:
        if kind == "up":
            return part_start.copy()
        if kind == "uf":
            return part_end.copy()
        if kind == "c":
            return peer_start.copy() if is_start else peer_end.copy()
        g_first = g_glob[part_start]
        g_last = g_glob[part_end]
        tg = g_glob + (nv if kind == "f" else -nv)
        if is_start:
            # before the partition's first group -> clamp to it; past the
            # last group -> empty (one past partition end)
            out = g_starts[np.clip(tg, g_first, g_last)]
            return np.where(tg > g_last, part_end + 1, out)
        out = g_ends[np.clip(tg, g_first, g_last)]
        return np.where(tg < g_first, part_start - 1, out)

    _rk: Dict[str, Any] = {}

    def _range_key_state() -> Dict[str, Any]:
        """Order-key machinery for RANGE offsets — computed once and
        shared by the lo and hi bounds (the key expression can be
        arbitrarily expensive)."""
        if _rk:
            return _rk
        if len(e.order_by) != 1:
            raise SQLExecutionError(
                "RANGE frames with offsets require exactly one "
                "ORDER BY expression"
            )
        o = e.order_by[0]
        ks = ev.eval(o.expr).series.loc[order]
        if not pd.api.types.is_numeric_dtype(
            ks.dtype
        ) and not ks.map(
            lambda v: v is None or isinstance(v, (int, float))
        ).all():
            raise SQLExecutionError(
                "RANGE frame offsets require a numeric ORDER BY key"
            )
        kv = pd.to_numeric(ks).astype("float64").to_numpy()
        isna = np.isnan(kv)
        if not o.asc:
            kv = -kv
        nulls_first = (o.nulls == "FIRST") if o.nulls is not None else False
        spans = []  # (part first, part last, non-null first, non-null last)
        for t in range(len(p_starts)):
            s_, e_ = p_starts[t], p_ends[t]
            nn = int(isna[s_:e_ + 1].sum())
            a, b = (s_ + nn, e_) if nulls_first else (s_, e_ - nn)
            spans.append((s_, e_, a, b))
        _rk.update(kv=kv, isna=isna, spans=spans)
        return _rk

    def _range_bound(kind: str, nv: Any, is_start: bool) -> np.ndarray:
        if kind == "up":
            return part_start.copy()
        if kind == "uf":
            return part_end.copy()
        if kind == "c":
            return peer_start.copy() if is_start else peer_end.copy()
        st = _range_key_state()
        kv, isna = st["kv"], st["isna"]
        delta = float(nv) if kind == "f" else -float(nv)
        out = np.empty(n, dtype=np.int64)
        for s_, e_, a, b in st["spans"]:
            if a > b:  # all-null partition
                continue
            seg = kv[a:b + 1]
            tgt = kv[s_:e_ + 1] + delta
            if is_start:
                out[s_:e_ + 1] = a + np.searchsorted(seg, tgt, side="left")
            else:
                out[s_:e_ + 1] = (
                    a + np.searchsorted(seg, tgt, side="right") - 1
                )
        # null keys: the frame bound resolves to the null peer group
        out[isna] = peer_start[isna] if is_start else peer_end[isna]
        return out

    bound = {"rows": _rows_bound, "groups": _groups_bound,
             "range": _range_bound}[unit]
    lo = bound(skind, sn, True)
    hi = bound(ekind, en, False)
    lo = np.maximum(lo, part_start)
    hi = np.minimum(hi, part_end)
    empty = lo > hi
    lo_s = np.clip(lo, 0, n - 1)
    hi_s = np.clip(hi, 0, n - 1)

    # ---- aggregate over [lo, hi] -----------------------------------------
    def _ser(arr: np.ndarray) -> pd.Series:
        return pd.Series(arr, index=order)

    if name == "count":
        if star:
            r = _ser(np.where(empty, 0, hi - lo + 1))
        else:
            c = np.concatenate(
                [[0], np.cumsum(vs.notna().to_numpy(dtype="int64"))]
            )
            r = _ser(np.where(empty, 0, c[hi_s + 1] - c[lo_s]))
        return _back(r.astype("int64"), pa.int64())
    if name in ("sum", "avg", "mean"):
        fv = vs.fillna(0).to_numpy(dtype="float64")
        cs = np.concatenate([[0.0], np.cumsum(fv)])
        cn = np.concatenate(
            [[0], np.cumsum(vs.notna().to_numpy(dtype="int64"))]
        )
        cnt = np.where(empty, 0, cn[hi_s + 1] - cn[lo_s])
        tot = np.where(empty, 0.0, cs[hi_s + 1] - cs[lo_s])
        sum_tp = (
            pa.int64()
            if vts_tp is not None and pa.types.is_integer(vts_tp)
            else pa.float64()
        )
        if name == "sum":
            r = _ser(tot).where(cnt > 0)
            if sum_tp == pa.int64():
                # exact for the int64 range a float64 cumsum preserves
                r = r.round()
            return _back(r, sum_tp)
        return _back(
            _ser(np.where(cnt > 0, tot / np.maximum(cnt, 1), np.nan)).where(
                cnt > 0
            ),
            pa.float64(),
        )
    if name in ("min", "max"):
        codes, uniques = pd.factorize(vs, sort=True)
        cf = codes.astype(np.float64)
        cf[codes < 0] = np.inf if name == "min" else -np.inf
        res = _range_minmax(cf, lo_s, hi_s, name == "min")
        ok = np.isfinite(res) & ~empty
        vals = np.empty(n, dtype=object)
        vals[~ok] = None
        if ok.any():
            taken = np.asarray(uniques, dtype=object)[
                res[ok].astype(np.int64)
            ]
            vals[ok] = taken
        return _back(_ser(vals), vts_tp)
    if name in ("first", "first_value", "last", "last_value", "nth_value"):
        if name == "nth_value":
            at = lo + nth - 1
            bad = empty | (at > hi)
        elif name.startswith("first"):
            at = lo
            bad = empty
        else:
            at = hi
            bad = empty
        arr = vs.to_numpy()
        r = _ser(arr[np.clip(at, 0, n - 1)]).where(~_ser(bad))
        return _back(r, vts_tp)
    raise AssertionError(name)  # the _FRAME_AGGS gate owns the contract


def _collect_aggs(e: ast.Expr, out: List[ast.Func]) -> None:
    if isinstance(e, ast.Func) and e.name in _AGG_FUNCS:
        if e not in out:
            out.append(e)
        return
    for c in _children(e):
        _collect_aggs(c, out)


def _agg_result(
    grouped: Any, func: ast.Func, label: str, arg_type: Optional[pa.DataType]
) -> Tuple[pd.Series, Optional[pa.DataType]]:
    name = func.name
    if name == "count":
        if func.distinct:
            return grouped[label].nunique(dropna=True), pa.int64()
        if len(func.args) == 1 and isinstance(func.args[0], ast.Star):
            return grouped[label].size(), pa.int64()
        return grouped[label].count(), pa.int64()
    if name in ("avg", "mean"):
        if func.distinct:
            return (
                grouped[label].agg(lambda s: s.drop_duplicates().mean()),
                pa.float64(),
            )
        return grouped[label].mean(), pa.float64()
    if name == "sum":
        col = grouped[label]
        if func.distinct:
            res = col.agg(lambda s: s.dropna().drop_duplicates().sum()
                          if s.notna().any() else None)
        else:
            res = col.sum(min_count=1)
        tp = pa.int64() if arg_type is not None and \
            pa.types.is_integer(arg_type) else pa.float64()
        return res, tp
    if name == "min":
        return grouped[label].min(), arg_type
    if name == "max":
        return grouped[label].max(), arg_type
    if name in ("first", "first_value"):
        return grouped[label].agg(
            lambda s: s.iloc[0] if len(s) > 0 else None
        ), arg_type
    if name in ("last", "last_value"):
        return grouped[label].agg(
            lambda s: s.iloc[-1] if len(s) > 0 else None
        ), arg_type
    if name in VARIANCE_FUNCS:
        ddof, f2 = variance_ddof(name), variance_stat(name)
        if func.distinct:
            res = grouped[label].agg(
                lambda s: getattr(s.drop_duplicates(), f2)(ddof=ddof)
            )
        else:
            res = getattr(grouped[label], f2)(ddof=ddof)
        return res, pa.float64()
    if name == "median":
        if func.distinct:
            return grouped[label].agg(
                lambda s: s.drop_duplicates().median()
            ), pa.float64()
        return grouped[label].median(), pa.float64()
    raise SQLExecutionError(f"unsupported aggregation {name}")


def _global_agg_result(
    frame: pd.DataFrame, func: ast.Func, label: str,
    arg_type: Optional[pa.DataType],
) -> Tuple[Any, Optional[pa.DataType]]:
    s = frame[label]
    name = func.name
    if name == "count":
        if func.distinct:
            return s.nunique(dropna=True), pa.int64()
        if len(func.args) == 1 and isinstance(func.args[0], ast.Star):
            return len(s), pa.int64()
        return s.count(), pa.int64()
    if name in ("avg", "mean"):
        vals = s.drop_duplicates() if func.distinct else s
        return (vals.mean() if len(vals) else None), pa.float64()
    if name == "sum":
        vals = s.dropna().drop_duplicates() if func.distinct else s
        res = vals.sum(min_count=1) if len(vals) else None
        tp = pa.int64() if arg_type is not None and \
            pa.types.is_integer(arg_type) else pa.float64()
        return (None if res is None or pd.isna(res) else res), tp
    if name == "min":
        return (s.min() if s.notna().any() else None), arg_type
    if name == "max":
        return (s.max() if s.notna().any() else None), arg_type
    if name in ("first", "first_value"):
        return (s.iloc[0] if len(s) > 0 else None), arg_type
    if name in ("last", "last_value"):
        return (s.iloc[-1] if len(s) > 0 else None), arg_type
    if name in VARIANCE_FUNCS:
        vals = s.drop_duplicates() if func.distinct else s
        return (
            getattr(vals, variance_stat(name))(ddof=variance_ddof(name))
            if len(vals)
            else None
        ), pa.float64()
    if name == "median":
        vals = s.drop_duplicates() if func.distinct else s
        return (vals.median() if len(vals) else None), pa.float64()
    raise SQLExecutionError(f"unsupported aggregation {name}")


# ---- SELECT execution ---------------------------------------------------


def _run_select(q: ast.Select, env: Dict[str, _Table]) -> _Table:
    if q.from_ is None:
        scope = _Scope(pd.DataFrame({"_": [0]})[[]], [])
        scope.frame.index = pd.RangeIndex(1)
    else:
        scope = _build_scope(q.from_, env)
    if q.where is not None:
        if _contains_agg(q.where):
            raise SQLExecutionError("WHERE cannot contain aggregations")
        if _contains_window(q.where):
            raise SQLExecutionError("WHERE cannot contain window functions")
        mask = _to_bool_mask(
            _Evaluator(scope, env=env).eval(q.where).series
        )
        scope = _Scope(scope.frame[mask], scope.entries)

    has_agg = (
        len(q.group_by) > 0
        or any(
            not isinstance(it.expr, ast.Star) and _contains_agg(it.expr)
            for it in q.items
        )
        or (q.having is not None)
    )
    if has_agg and (
        _contains_window(q.having)
        or any(_contains_window(g) for g in q.group_by)
        or any(
            not isinstance(it.expr, ast.Star) and _contains_window(it.expr)
            for it in q.items
        )
    ):
        raise SQLExecutionError(
            "window functions over aggregated output are not supported"
        )
    resolver: Optional[Callable[[ast.Expr], _TS]]
    if has_agg:
        out, resolver = _run_agg_select(q, scope, env)
    else:
        out = _run_plain_select(q, scope, env)
        ev = _Evaluator(scope, env=env)
        resolver = ev.eval
    if q.distinct:
        # keep the original index so order keys can still be reindexed
        out = _Table(out.frame.drop_duplicates(), out.names, out.types)
    out = _apply_order_limit(out, q.order_by, q.limit, q.offset, resolver)
    return out


def _output_name(item: ast.SelectItem, i: int) -> str:
    if item.alias is not None:
        return item.alias
    if isinstance(item.expr, ast.Col):
        return item.expr.name
    return f"col_{i}"


def _run_plain_select(
    q: ast.Select, scope: _Scope, env: Optional[Dict[str, _Table]] = None
) -> _Table:
    ev = _Evaluator(scope, env=env)
    cols: List[Tuple[str, _TS]] = []
    for i, item in enumerate(q.items):
        if isinstance(item.expr, ast.Star):
            for e in scope.star_entries(item.expr.table):
                cols.append((e.name, _TS(scope.frame[e.label], e.dtype)))
        else:
            cols.append((_output_name(item, i), ev.eval(item.expr)))
    names = [c[0] for c in cols]
    _check_dup(names)
    frame = pd.DataFrame(
        {f"o{i}": ts.series for i, (_, ts) in enumerate(cols)},
        index=scope.frame.index,
    )
    if len(cols) > 0 and len(scope.frame.index) == 0:
        frame = frame.iloc[0:0]
    return _Table(frame, names, [ts.dtype for _, ts in cols])


def _check_dup(names: List[str]) -> None:
    seen = set()
    for n in names:
        if n in seen:
            raise SQLExecutionError(f"duplicated output column {n}")
        seen.add(n)


class _AggContext:
    """Post-aggregation scope: group keys + aggregated values by node."""

    def __init__(self, env: Optional[Dict[str, _Table]] = None) -> None:
        self.key_exprs: List[ast.Expr] = []
        self.key_labels: List[str] = []
        self.key_types: List[Optional[pa.DataType]] = []
        self.agg_nodes: List[ast.Func] = []
        self.agg_labels: List[str] = []
        self.agg_types: List[Optional[pa.DataType]] = []
        self.frame = pd.DataFrame()
        self.env = env

    def eval_post(self, e: ast.Expr, scope: _Scope) -> _TS:
        """Evaluate over the aggregated frame, mapping group-by exprs and
        agg funcs to their computed columns."""
        for k, lbl, tp in zip(self.key_exprs, self.key_labels, self.key_types):
            if e == k:
                return _TS(self.frame[lbl], tp)
            if isinstance(e, ast.Col) and isinstance(k, ast.Col) \
                    and e.name == k.name and e.table is None:
                return _TS(self.frame[lbl], tp)
        if isinstance(e, ast.Func) and e.name in _AGG_FUNCS:
            for node, lbl, tp in zip(
                self.agg_nodes, self.agg_labels, self.agg_types
            ):
                if e == node:
                    return _TS(self.frame[lbl], tp)
            raise SQLExecutionError(f"aggregation {e} was not computed")
        if isinstance(e, ast.Col):
            raise SQLExecutionError(
                f"column {_qname(e.name, e.table)} is not in GROUP BY"
            )
        # structural recursion via a shadow evaluator over the agg frame.
        # Plain-column group keys become scope entries (qualified with
        # their PRE-aggregation qualifier) so qualified refs — notably
        # correlated subqueries' outer references like ``a.k`` in HAVING
        # — resolve to the grouped key columns (review finding)
        entries: List[_Entry] = []
        for k, lbl, tp in zip(
            self.key_exprs, self.key_labels, self.key_types
        ):
            if isinstance(k, ast.Col):
                try:
                    src = scope.resolve(k.name, k.table)
                except SQLExecutionError:
                    continue
                entries.append(_Entry(src.qual, src.name, lbl, tp))
        sub = _Evaluator(_Scope(self.frame, entries), env=self.env)
        return _eval_with_hook(sub, e, lambda x: self._hook(x, scope))

    def _hook(self, e: ast.Expr, scope: _Scope) -> Optional[_TS]:
        for k, lbl, tp in zip(self.key_exprs, self.key_labels, self.key_types):
            if e == k or (
                isinstance(e, ast.Col) and isinstance(k, ast.Col)
                and e.name == k.name and e.table is None
            ):
                return _TS(self.frame[lbl], tp)
        if isinstance(e, ast.Func) and e.name in _AGG_FUNCS:
            for node, lbl, tp in zip(
                self.agg_nodes, self.agg_labels, self.agg_types
            ):
                if e == node:
                    return _TS(self.frame[lbl], tp)
        return None


def _eval_with_hook(
    ev: _Evaluator, e: ast.Expr, hook: Callable[[ast.Expr], Optional[_TS]]
) -> _TS:
    hooked = hook(e)
    if hooked is not None:
        return hooked
    orig = ev.eval

    def patched(x: ast.Expr) -> _TS:
        h = hook(x)
        if h is not None:
            return h
        return orig(x)

    ev.eval = patched  # type: ignore[method-assign]
    try:
        return orig(e)
    finally:
        ev.eval = orig  # type: ignore[method-assign]


def _resolve_groupby_expr(
    g: ast.Expr, q: ast.Select, scope: _Scope
) -> ast.Expr:
    """GROUP BY ordinal or select alias resolves to the item's expression.

    A real input column takes precedence over a select alias of the same
    (case-folded) name — Postgres/DuckDB resolution order."""
    if isinstance(g, ast.Lit) and isinstance(g.value, int) \
            and not isinstance(g.value, bool):
        idx = g.value - 1
        if idx < 0 or idx >= len(q.items):
            raise SQLExecutionError(f"GROUP BY ordinal {g.value} out of range")
        return q.items[idx].expr
    if isinstance(g, ast.Col) and g.table is None:
        cands = scope.candidates(g.name, None)
        if len(cands) > 1:
            raise SQLExecutionError(f"ambiguous column: {_qname(g.name, None)}")
        if len(cands) == 1:
            return g  # input column wins over any same-named alias
        for it in q.items:
            if it.alias is not None and it.alias.lower() == g.name.lower():
                return it.expr
    return g


def _run_agg_select(
    q: ast.Select, scope: _Scope, env: Optional[Dict[str, _Table]] = None
) -> Tuple[_Table, Callable[[ast.Expr], _TS]]:
    ctx = _AggContext(env)
    ctx.key_exprs = [_resolve_groupby_expr(g, q, scope) for g in q.group_by]
    for k in ctx.key_exprs:
        if _contains_agg(k):
            raise SQLExecutionError("GROUP BY cannot contain aggregations")
    aggs: List[ast.Func] = []
    for it in q.items:
        if isinstance(it.expr, ast.Star):
            raise SQLExecutionError("SELECT * cannot be used with GROUP BY")
        _collect_aggs(it.expr, aggs)
    if q.having is not None:
        _collect_aggs(q.having, aggs)
    for o in q.order_by:
        _collect_aggs(o.expr, aggs)
    ctx.agg_nodes = aggs

    ev = _Evaluator(scope, env=env)
    work = pd.DataFrame(index=scope.frame.index)
    key_labels = []
    for i, k in enumerate(ctx.key_exprs):
        ts = ev.eval(k)
        lbl = f"k{i}"
        work[lbl] = ts.series
        key_labels.append(lbl)
        ctx.key_labels.append(lbl)
        ctx.key_types.append(ts.dtype)
    arg_types: List[Optional[pa.DataType]] = []
    for i, node in enumerate(aggs):
        lbl = f"a{i}"
        if len(node.args) == 1 and isinstance(node.args[0], ast.Star):
            work[lbl] = 1
            arg_types.append(pa.int64())
        else:
            if len(node.args) != 1:
                raise SQLExecutionError(
                    f"aggregation {node.name} takes one argument"
                )
            ts = ev.eval(node.args[0])
            work[lbl] = ts.series
            arg_types.append(ts.dtype)
        ctx.agg_labels.append(lbl)

    if len(key_labels) == 0:
        data: Dict[str, Any] = {}
        for node, lbl, atp in zip(aggs, ctx.agg_labels, arg_types):
            val, tp = _global_agg_result(work, node, lbl, atp)
            data[lbl] = [val]
            ctx.agg_types.append(tp)
        ctx.frame = pd.DataFrame(data) if data else pd.DataFrame(index=[0])
    else:
        grouped = work.groupby(key_labels, dropna=False, sort=False)
        pieces: Dict[str, pd.Series] = {}
        for node, lbl, atp in zip(aggs, ctx.agg_labels, arg_types):
            res, tp = _agg_result(grouped, node, lbl, atp)
            pieces[lbl] = res
            ctx.agg_types.append(tp)
        if pieces:
            agg_frame = pd.DataFrame(pieces).reset_index()
        else:
            agg_frame = grouped.size().reset_index(name="_sz") \
                .drop(columns=["_sz"])
        ctx.frame = agg_frame

    if q.having is not None:
        mask = _to_bool_mask(ctx.eval_post(q.having, scope).series)
        ctx.frame = ctx.frame[mask]

    cols: List[Tuple[str, _TS]] = []
    for i, it in enumerate(q.items):
        cols.append((_output_name(it, i), ctx.eval_post(it.expr, scope)))
    names = [c[0] for c in cols]
    _check_dup(names)
    frame = pd.DataFrame(
        {f"o{i}": ts.series for i, (_, ts) in enumerate(cols)},
        index=ctx.frame.index,
    )
    out = _Table(frame, names, [ts.dtype for _, ts in cols])
    return out, (lambda e: ctx.eval_post(e, scope))


def _apply_order_limit(
    t: _Table,
    order_by: List[ast.OrderItem],
    limit: Optional[int],
    offset: Optional[int],
    resolver: Optional[Callable[[ast.Expr], _TS]],
) -> _Table:
    if order_by:
        keys = []
        for j, o in enumerate(order_by):
            ts = _order_key(t, o, resolver)
            keys.append((f"s{j}", ts.series, o))
        t = _sort_table(t, keys, t.frame.index)
    t = _Table(t.frame.reset_index(drop=True), t.names, t.types)
    return _apply_limit(t, limit, offset)


def _order_key(
    t: _Table, o: ast.OrderItem,
    resolver: Optional[Callable[[ast.Expr], _TS]],
) -> _TS:
    e = o.expr
    if isinstance(e, ast.Lit) and isinstance(e.value, int) \
            and not isinstance(e.value, bool):
        idx = e.value - 1
        if 0 <= idx < len(t.names):
            return _TS(t.frame.iloc[:, idx], t.types[idx])
    if isinstance(e, ast.Col) and e.table is None:
        if e.name in t.names:
            idx = t.names.index(e.name)
            return _TS(t.frame.iloc[:, idx], t.types[idx])
        # SQL identifiers fold case: ORDER BY k matches output column K
        folded = [n.lower() for n in t.names]
        if folded.count(e.name.lower()) == 1:
            idx = folded.index(e.name.lower())
            return _TS(t.frame.iloc[:, idx], t.types[idx])
    if resolver is not None:
        ts = resolver(e)
        return _TS(ts.series.reindex(t.frame.index), ts.dtype)
    raise SQLExecutionError(f"cannot resolve ORDER BY expression {e}")


def _sort_table(
    t: _Table, keys: List[Tuple[str, pd.Series, ast.OrderItem]],
    index: pd.Index,
) -> _Table:
    sorter = pd.DataFrame(
        {lbl: s.reindex(index) for lbl, s, _ in keys}, index=index
    )
    by = [lbl for lbl, _, _ in keys]
    ascending = [o.asc for _, _, o in keys]
    # pandas supports one na_position for all keys; emulate per-key NULLS
    # FIRST/LAST via a null-rank column per key
    frames = []
    for lbl, _, o in keys:
        nulls_first = (o.nulls == "FIRST") if o.nulls is not None else False
        nf = sorter[lbl].isna()
        frames.append((f"n_{lbl}", (~nf) if nulls_first else nf))
    for lbl, s in frames:
        sorter[lbl] = s
    interleaved = []
    asc2 = []
    for (lbl, _, o), (nlbl, _s) in zip(keys, frames):
        interleaved.extend([nlbl, lbl])
        asc2.extend([True, o.asc])
    del by, ascending
    order = sorter.sort_values(interleaved, ascending=asc2, kind="stable").index
    return _Table(t.frame.loc[order], t.names, t.types)


def _apply_limit(
    t: _Table, limit: Optional[int], offset: Optional[int]
) -> _Table:
    if offset is not None:
        t = _Table(t.frame.iloc[offset:], t.names, t.types)
    if limit is not None:
        t = _Table(t.frame.iloc[:limit], t.names, t.types)
    return _Table(t.frame.reset_index(drop=True), t.names, t.types)


# ---- set operations -----------------------------------------------------


def _unify_types(
    a: Optional[pa.DataType], b: Optional[pa.DataType]
) -> Optional[pa.DataType]:
    if a is None:
        return b
    if b is None or a.equals(b):
        return a
    numeric = (pa.types.is_integer, pa.types.is_floating)
    if any(f(a) for f in numeric) and any(f(b) for f in numeric):
        if pa.types.is_floating(a) or pa.types.is_floating(b):
            return pa.float64()
        return pa.int64()
    return pa.string()


def _run_setop(q: ast.SetOp, env: Dict[str, _Table]) -> _Table:
    left = _run(q.left, env)
    right = _run(q.right, env)
    if len(left.names) != len(right.names):
        raise SQLExecutionError(
            f"{q.op} requires equal column counts "
            f"({len(left.names)} vs {len(right.names)})"
        )
    lf = left.frame.copy(deep=False)
    rf = right.frame.copy(deep=False)
    labels = [f"u{i}" for i in range(len(left.names))]
    lf.columns = labels
    rf.columns = labels
    types = [
        _unify_types(a, b) for a, b in zip(left.types, right.types)
    ]
    # coerce BOTH sides to the unified column types up front: dedup and
    # the multiset merges below compare values, and pandas refuses to
    # merge int64 against str outright (review finding)
    for lbl, tp, ltp, rtp in zip(labels, types, left.types, right.types):
        if ltp is None or rtp is None:
            # NULL-literal side: compare in object space — concat handles
            # it natively, but the merge-based ops need matching dtypes
            # (review finding); set-op NULLs compare equal, which pandas'
            # merge factorization gives for None keys
            if str(lf[lbl].dtype) != str(rf[lbl].dtype):
                lf[lbl] = lf[lbl].astype(object)
                rf[lbl] = rf[lbl].astype(object)
            continue
        if str(lf[lbl].dtype) == str(rf[lbl].dtype):
            continue
        if tp is not None and pa.types.is_string(tp):
            for f in (lf, rf):
                s = f[lbl]
                nulls = s.isna()
                o = s.astype(object)
                o[~nulls] = s[~nulls].map(_to_str_scalar)
                o[nulls.to_numpy(dtype=bool)] = None
                f[lbl] = o
        else:
            try:
                dt = tp.to_pandas_dtype() if tp is not None else float
                lf[lbl] = lf[lbl].astype(dt)
                rf[lbl] = rf[lbl].astype(dt)
            except Exception:
                raise SQLExecutionError(
                    f"incompatible column types in {q.op}"
                )
    if q.op == "UNION":
        res = pd.concat([lf, rf], ignore_index=True)
        if not q.all:
            res = res.drop_duplicates().reset_index(drop=True)
    elif q.op in ("EXCEPT", "INTERSECT") and q.all:
        # multiset semantics (standard SQL ... ALL): pair off occurrences
        # — EXCEPT ALL keeps each left row whose occurrence index exceeds
        # the right-side count; INTERSECT ALL keeps those within it
        lo = lf.assign(
            _occ=lf.groupby(labels, dropna=False).cumcount()
        )
        rcnt = (
            rf.groupby(labels, dropna=False)
            .size()
            .rename("_rc")
            .reset_index()
        )
        merged = lo.merge(rcnt, on=labels, how="left")
        rc = merged["_rc"].fillna(0)
        keep = merged["_occ"] >= rc if q.op == "EXCEPT" else (
            merged["_occ"] < rc
        )
        res = merged[keep].drop(columns=["_occ", "_rc"]).reset_index(
            drop=True
        )
    elif q.op == "EXCEPT":
        ld = lf.drop_duplicates()
        rd = rf.drop_duplicates()
        merged = ld.merge(rd, on=labels, how="left", indicator=True)
        res = merged[merged["_merge"] == "left_only"] \
            .drop(columns=["_merge"]).reset_index(drop=True)
    elif q.op == "INTERSECT":
        ld = lf.drop_duplicates()
        rd = rf.drop_duplicates()
        res = ld.merge(rd, on=labels, how="inner").reset_index(drop=True)
    else:
        raise SQLExecutionError(f"unsupported set op {q.op}")
    out = _Table(res, list(left.names), types)
    return _apply_order_limit(out, q.order_by, q.limit, q.offset, None)
