"""fugue_sql / fugue_sql_flow entry points (reference fugue/sql/api.py:18,111).
Full implementation arrives with the parser module."""

from typing import Any


def fugue_sql(query: str, *args: Any, **kwargs: Any) -> Any:
    from fugue_tpu.sql_frontend.workflow_sql import run_fugue_sql

    return run_fugue_sql(query, *args, **kwargs)


def fugue_sql_flow(query: str, *args: Any, **kwargs: Any) -> Any:
    from fugue_tpu.sql_frontend.workflow_sql import build_fugue_sql_flow

    return build_fugue_sql_flow(query, *args, **kwargs)
