"""fugue_sql / fugue_sql_flow entry points (reference fugue/sql/api.py:18,111)."""

from typing import Any

from fugue_tpu.sql_frontend.workflow_sql import (
    FugueSQLWorkflow,
    _caller_vars,
    fill_sql_template,
)

__all__ = ["fugue_sql", "fugue_sql_flow", "FugueSQLWorkflow", "fill_sql_template"]


def fugue_sql(
    query: str,
    *args: Any,
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
    **kwargs: Any,
) -> Any:
    """Run a FugueSQL script and return its last dataframe."""
    from fugue_tpu.sql_frontend.workflow_sql import _fugue_sql_impl

    return _fugue_sql_impl(
        query, _caller_vars(2), args, kwargs,
        engine=engine, engine_conf=engine_conf,
        as_fugue=as_fugue, as_local=as_local,
    )


def fugue_sql_flow(query: str, *args: Any, **kwargs: Any) -> FugueSQLWorkflow:
    """Build (not run) a FugueSQLWorkflow; use YIELD for outputs."""
    dag = FugueSQLWorkflow()
    dag._sql(query, _caller_vars(2), *args, **kwargs)
    return dag
