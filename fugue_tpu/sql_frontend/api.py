"""fugue_sql / fugue_sql_flow entry points (reference fugue/sql/api.py:18,111).

The implementations live in :mod:`fugue_tpu.sql_frontend.workflow_sql`
(their ``_caller_vars`` frame depth is relative to the functions
themselves, so a plain re-export preserves caller-local dataframe
resolution)."""

from fugue_tpu.sql_frontend.workflow_sql import (  # noqa: F401
    FugueSQLWorkflow,
    explain_sql,
    fill_sql_template,
    fugue_sql,
    fugue_sql_flow,
    lint_sql,
)

__all__ = [
    "fugue_sql",
    "fugue_sql_flow",
    "FugueSQLWorkflow",
    "explain_sql",
    "fill_sql_template",
    "lint_sql",
]
