"""AST nodes for the SQL front end (the role of the ANTLR parse tree in the
reference, fugue/sql/_visitors.py — but as a typed logical AST rather than a
raw grammar tree)."""

from typing import Any, List, Optional, Tuple

__all__ = [
    "Expr", "Lit", "Col", "Star", "Unary", "Binary", "Func", "Case", "Cast",
    "InList", "Between", "Like", "IsNull", "Window", "Frame",
    "ScalarSubquery", "InSubquery", "Exists",
    "Relation", "TableRef", "SubqueryRef", "JoinRel",
    "SelectItem", "OrderItem", "Select", "SetOp", "With", "Query",
]


class Node:
    _fields: Tuple[str, ...] = ()

    def __repr__(self) -> str:
        inner = ", ".join(f"{f}={getattr(self, f)!r}" for f in self._fields)
        return f"{type(self).__name__}({inner})"

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and all(
            getattr(self, f) == getattr(other, f) for f in self._fields
        )

    def __hash__(self) -> int:  # structural, for agg dedup
        return hash((type(self).__name__,) + tuple(
            tuple(v) if isinstance(v := getattr(self, f), list) else v
            for f in self._fields
        ))


class Expr(Node):
    pass


class Lit(Expr):
    _fields = ("value",)

    def __init__(self, value: Any):
        self.value = value  # None | bool | int | float | str


class Col(Expr):
    _fields = ("name", "table")

    def __init__(self, name: str, table: Optional[str] = None):
        self.name = name
        self.table = table


class Star(Expr):
    _fields = ("table",)

    def __init__(self, table: Optional[str] = None):
        self.table = table


class Unary(Expr):
    _fields = ("op", "operand")

    def __init__(self, op: str, operand: Expr):
        self.op = op  # '-' | '+' | 'NOT'
        self.operand = operand


class Binary(Expr):
    _fields = ("op", "left", "right")

    def __init__(self, op: str, left: Expr, right: Expr):
        self.op = op  # = <> < <= > >= + - * / % || AND OR
        self.left = left
        self.right = right


class Func(Expr):
    _fields = ("name", "args", "distinct")

    def __init__(self, name: str, args: List[Expr], distinct: bool = False):
        self.name = name.lower()
        self.args = args
        self.distinct = distinct


class Case(Expr):
    _fields = ("operand", "whens", "default")

    def __init__(
        self,
        operand: Optional[Expr],
        whens: List[Tuple[Expr, Expr]],
        default: Optional[Expr],
    ):
        self.operand = operand
        self.whens = whens
        self.default = default

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.operand, tuple(self.whens), self.default))


class Cast(Expr):
    _fields = ("operand", "type_name")

    def __init__(self, operand: Expr, type_name: str):
        self.operand = operand
        self.type_name = type_name.lower()


class InList(Expr):
    _fields = ("operand", "items", "negated")

    def __init__(self, operand: Expr, items: List[Expr], negated: bool):
        self.operand = operand
        self.items = items
        self.negated = negated


class Between(Expr):
    _fields = ("operand", "low", "high", "negated")

    def __init__(self, operand: Expr, low: Expr, high: Expr, negated: bool):
        self.operand = operand
        self.low = low
        self.high = high
        self.negated = negated


class Like(Expr):
    _fields = ("operand", "pattern", "negated")

    def __init__(self, operand: Expr, pattern: Expr, negated: bool):
        self.operand = operand
        self.pattern = pattern
        self.negated = negated


class IsNull(Expr):
    _fields = ("operand", "negated")

    def __init__(self, operand: Expr, negated: bool):
        self.operand = operand
        self.negated = negated


class ScalarSubquery(Expr):
    """``(SELECT ...)`` as a value: exactly one output column; one row
    gives its value, zero rows NULL, more is an error. Columns that do
    not bind inside the subquery correlate to the enclosing scope."""

    _fields = ("query",)

    def __init__(self, query: "Query"):
        self.query = query


class InSubquery(Expr):
    """``operand [NOT] IN (SELECT ...)`` with SQL three-valued logic."""

    _fields = ("operand", "query", "negated")

    def __init__(self, operand: "Expr", query: "Query", negated: bool):
        self.operand = operand
        self.query = query
        self.negated = negated


class Exists(Expr):
    """``EXISTS (SELECT ...)`` — true iff the subquery returns rows."""

    _fields = ("query",)

    def __init__(self, query: "Query"):
        self.query = query


class Frame(Node):
    """Explicit window frame clause: ``ROWS|RANGE|GROUPS BETWEEN <bound>
    AND <bound>``. Bounds are ``(kind, n)`` pairs with kind one of
    ``"up"`` (UNBOUNDED PRECEDING), ``"p"`` (n PRECEDING), ``"c"``
    (CURRENT ROW), ``"f"`` (n FOLLOWING), ``"uf"`` (UNBOUNDED
    FOLLOWING); ``n`` is None except for "p"/"f"."""

    _fields = ("unit", "start", "end")

    def __init__(
        self,
        unit: str,  # "rows" | "range" | "groups"
        start: Tuple[str, Optional[Any]],
        end: Tuple[str, Optional[Any]],
    ):
        self.unit = unit
        self.start = start
        self.end = end


class Window(Expr):
    """``func(...) OVER (PARTITION BY ... ORDER BY ... [frame])``. With
    no explicit frame clause and an ORDER BY, aggregates use the SQL
    default frame (RANGE UNBOUNDED PRECEDING .. CURRENT ROW — running
    totals where peers share a value); without ORDER BY, the whole
    partition."""

    _fields = ("func", "partition_by", "order_by", "frame")

    def __init__(
        self,
        func: "Func",
        partition_by: List["Expr"],
        order_by: List["OrderItem"],
        frame: Optional["Frame"] = None,
    ):
        self.func = func
        self.partition_by = partition_by
        self.order_by = order_by
        self.frame = frame


# ---- relations ----------------------------------------------------------


class Relation(Node):
    pass


class TableRef(Relation):
    _fields = ("name", "alias")

    def __init__(self, name: str, alias: Optional[str] = None):
        self.name = name
        self.alias = alias


class SubqueryRef(Relation):
    _fields = ("query", "alias")

    def __init__(self, query: "Query", alias: str):
        self.query = query
        self.alias = alias


class JoinRel(Relation):
    _fields = ("left", "right", "how", "on", "using")

    def __init__(
        self,
        left: Relation,
        right: Relation,
        how: str,  # inner|cross|left_outer|right_outer|full_outer|semi|anti
        on: Optional[Expr] = None,
        using: Optional[List[str]] = None,
    ):
        self.left = left
        self.right = right
        self.how = how
        self.on = on
        self.using = using


# ---- queries ------------------------------------------------------------


class SelectItem(Node):
    _fields = ("expr", "alias")

    def __init__(self, expr: Expr, alias: Optional[str] = None):
        self.expr = expr
        self.alias = alias


class OrderItem(Node):
    _fields = ("expr", "asc", "nulls")

    def __init__(self, expr: Expr, asc: bool = True, nulls: Optional[str] = None):
        self.expr = expr
        self.asc = asc
        self.nulls = nulls  # None | 'FIRST' | 'LAST'


class Query(Node):
    pass


class Select(Query):
    _fields = (
        "items", "from_", "where", "group_by", "having",
        "order_by", "limit", "offset", "distinct",
    )

    def __init__(
        self,
        items: List[SelectItem],
        from_: Optional[Relation] = None,
        where: Optional[Expr] = None,
        group_by: Optional[List[Expr]] = None,
        having: Optional[Expr] = None,
        order_by: Optional[List[OrderItem]] = None,
        limit: Optional[int] = None,
        offset: Optional[int] = None,
        distinct: bool = False,
    ):
        self.items = items
        self.from_ = from_
        self.where = where
        self.group_by = group_by or []
        self.having = having
        self.order_by = order_by or []
        self.limit = limit
        self.offset = offset
        self.distinct = distinct


class SetOp(Query):
    _fields = ("op", "all", "left", "right", "order_by", "limit", "offset")

    def __init__(
        self,
        op: str,  # UNION | EXCEPT | INTERSECT
        all: bool,
        left: Query,
        right: Query,
        order_by: Optional[List[OrderItem]] = None,
        limit: Optional[int] = None,
        offset: Optional[int] = None,
    ):
        self.op = op
        self.all = all
        self.left = left
        self.right = right
        self.order_by = order_by or []
        self.limit = limit
        self.offset = offset


class With(Query):
    _fields = ("ctes", "body")

    def __init__(self, ctes: List[Tuple[str, Query]], body: Query):
        self.ctes = ctes
        self.body = body
