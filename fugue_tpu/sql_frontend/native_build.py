"""Build & install the C++ SQL scanner (the role of the reference's
``fugue-sql-antlr[cpp]`` accelerated parser, reference README.md:162).

``enable_native_scanner()`` compiles ``native/ctokenizer.cpp`` with g++ at
first use (cached as a .so next to a source-hash marker, so rebuilds only
happen when the source changes), loads it, and installs it via
:func:`fugue_tpu.sql_frontend.tokenizer.set_accelerated_scanner`. Every
failure path (no compiler, load error) leaves the pure-Python scanner in
place — acceleration is strictly opt-out-able and never changes behavior
(the C scanner defers to Python on anything it can't lex identically).

Set ``FUGUE_TPU_NO_NATIVE=1`` to skip entirely.
"""

import hashlib
import importlib.util
import os
import subprocess
import sysconfig
from typing import Optional

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO, "native", "ctokenizer.cpp")
_BUILD_DIR = os.path.join(_REPO, "native", "_build")
_STATE = {"tried": False, "ok": False}


def build_extension(
    src: str, stem: str, timeout: int = 120
) -> Optional[str]:
    """Compile ``src`` into a content-hashed .so under the shared build
    dir and return its path (shared by the C++ scanner and parser).
    EVERY failure (no source, read-only fs, no compiler) returns None so
    the pure-Python path silently takes over — never crash a SQL call.
    pid-unique temp + atomic rename: concurrent first-use builds (e.g.
    parallel test workers) must not install a half-written .so that the
    hash-existence check would then trust forever."""
    try:
        with open(src, "rb") as fp:
            src_hash = hashlib.sha256(fp.read()).hexdigest()[:16]
        so = os.path.join(_BUILD_DIR, f"{stem}_{src_hash}.so")
        if os.path.exists(so):
            return so
        os.makedirs(_BUILD_DIR, exist_ok=True)
        include = sysconfig.get_path("include")
        tmp = f"{so}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", f"-I{include}", src, "-o",
            tmp,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=timeout)
        os.replace(tmp, so)
        return so
    except Exception:
        return None


def load_extension(so: str, module_name: str) -> Optional[object]:
    try:
        spec = importlib.util.spec_from_file_location(module_name, so)
        mod = importlib.util.module_from_spec(spec)  # type: ignore[arg-type]
        spec.loader.exec_module(mod)  # type: ignore[union-attr]
        return mod
    except Exception:
        return None


def _build() -> Optional[str]:
    return build_extension(_SRC, "_fugue_tpu_ctokenizer", timeout=120)


def _load(so: str) -> Optional[object]:
    return load_extension(so, "_fugue_tpu_ctokenizer")


def enable_native_scanner() -> bool:
    """Idempotent; returns True when the C++ scanner is active."""
    if _STATE["tried"]:
        return _STATE["ok"]
    _STATE["tried"] = True
    if os.environ.get("FUGUE_TPU_NO_NATIVE", "").lower() in ("1", "true"):
        return False
    so = _build()
    if so is None:
        return False
    mod = _load(so)
    if mod is None:
        return False
    from itertools import starmap

    from fugue_tpu.sql_frontend.tokenizer import (
        Token,
        set_accelerated_scanner,
    )

    scan = mod.scan  # type: ignore[attr-defined]

    def _native_scan(sql: str):
        raw = scan(sql)
        if raw is None:  # non-ASCII or lexical error: python path decides
            return None
        return list(starmap(Token, raw))

    set_accelerated_scanner(_native_scan)
    _STATE["ok"] = True
    return True


def native_scanner_active() -> bool:
    return _STATE["ok"]
