"""Build & install the C++ SQL scanner (the role of the reference's
``fugue-sql-antlr[cpp]`` accelerated parser, reference README.md:162).

``enable_native_scanner()`` compiles ``native/ctokenizer.cpp`` with g++ at
first use (cached as a .so next to a source-hash marker, so rebuilds only
happen when the source changes), loads it, and installs it via
:func:`fugue_tpu.sql_frontend.tokenizer.set_accelerated_scanner`. Every
failure path (no compiler, load error) leaves the pure-Python scanner in
place — acceleration is strictly opt-out-able and never changes behavior
(the C scanner defers to Python on anything it can't lex identically).

Set ``FUGUE_TPU_NO_NATIVE=1`` to skip entirely.
"""

import hashlib
import importlib.util
import os
import subprocess
import sysconfig
from typing import Optional

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO, "native", "ctokenizer.cpp")
_BUILD_DIR = os.path.join(_REPO, "native", "_build")
_STATE = {"tried": False, "ok": False}


def _so_path(src_hash: str) -> str:
    return os.path.join(_BUILD_DIR, f"_fugue_tpu_ctokenizer_{src_hash}.so")


def _build() -> Optional[str]:
    # EVERY failure (no source, read-only fs, no compiler) returns None so
    # the pure-Python scanner silently takes over — never crash a SQL call
    try:
        with open(_SRC, "rb") as fp:
            src_hash = hashlib.sha256(fp.read()).hexdigest()[:16]
        so = _so_path(src_hash)
        if os.path.exists(so):
            return so
        os.makedirs(_BUILD_DIR, exist_ok=True)
        include = sysconfig.get_path("include")
        # pid-unique temp + atomic rename (see native_parse._build)
        tmp = f"{so}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", f"-I{include}", _SRC, "-o",
            tmp,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return so
    except Exception:
        return None


def _load(so: str) -> Optional[object]:
    try:
        spec = importlib.util.spec_from_file_location(
            "_fugue_tpu_ctokenizer", so
        )
        mod = importlib.util.module_from_spec(spec)  # type: ignore[arg-type]
        spec.loader.exec_module(mod)  # type: ignore[union-attr]
        return mod
    except Exception:
        return None


def enable_native_scanner() -> bool:
    """Idempotent; returns True when the C++ scanner is active."""
    if _STATE["tried"]:
        return _STATE["ok"]
    _STATE["tried"] = True
    if os.environ.get("FUGUE_TPU_NO_NATIVE", "").lower() in ("1", "true"):
        return False
    so = _build()
    if so is None:
        return False
    mod = _load(so)
    if mod is None:
        return False
    from itertools import starmap

    from fugue_tpu.sql_frontend.tokenizer import (
        Token,
        set_accelerated_scanner,
    )

    scan = mod.scan  # type: ignore[attr-defined]

    def _native_scan(sql: str):
        raw = scan(sql)
        if raw is None:  # non-ASCII or lexical error: python path decides
            return None
        return list(starmap(Token, raw))

    set_accelerated_scanner(_native_scan)
    _STATE["ok"] = True
    return True


def native_scanner_active() -> bool:
    return _STATE["ok"]
