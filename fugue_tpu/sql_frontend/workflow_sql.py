"""FugueSQLWorkflow and the ``fugue_sql`` / ``fugue_sql_flow`` entry points
(reference fugue/sql/workflow.py:16-68, fugue/sql/api.py:18,111)."""

import sys
from typing import Any, Dict, Optional, Tuple

from fugue_tpu.constants import FUGUE_CONF_SQL_DIALECT
from fugue_tpu.dataframe import DataFrame
from fugue_tpu.execution.factory import make_execution_engine
from fugue_tpu.sql_frontend.fugue_parser import FugueSQLCompiler
from fugue_tpu.workflow.workflow import FugueWorkflow, WorkflowDataFrame

__all__ = [
    "FugueSQLWorkflow", "fugue_sql", "fugue_sql_flow", "fill_sql_template",
    "explain_sql", "lint_sql",
]


def fill_sql_template(template: str, params: Dict[str, Any]) -> str:
    """Jinja-fill ``{{var}}`` references in a FugueSQL script."""
    if "{{" not in template and "{%" not in template:
        return template
    try:
        from jinja2 import Template
    except ImportError:  # pragma: no cover - jinja2 is in the base image
        return template
    return Template(template).render(**params)


def _caller_vars(depth: int) -> Dict[str, Any]:
    frame = sys._getframe(depth)
    out: Dict[str, Any] = {}
    out.update(frame.f_globals)
    out.update(frame.f_locals)
    return out


def _split_params(kwargs: Dict[str, Any]) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Split kwargs into template params and dataframe sources."""
    params: Dict[str, Any] = {}
    dfs: Dict[str, Any] = {}
    for k, v in kwargs.items():
        if isinstance(v, (DataFrame, WorkflowDataFrame)) or \
                FugueSQLCompiler._is_dataframe_like(v):
            dfs[k] = v
        else:
            params[k] = v
    return params, dfs


class FugueSQLWorkflow(FugueWorkflow):
    """A workflow whose DAG can be built from FugueSQL scripts; usable
    incrementally::

        dag = FugueSQLWorkflow()
        dag("a = CREATE [[0]] SCHEMA x:long")
        dag("SELECT x+1 AS x FROM a PRINT")
        dag.run()
    """

    def __init__(self, compile_conf: Any = None):
        super().__init__(compile_conf)
        self._sql_vars: Dict[str, WorkflowDataFrame] = {}

    @property
    def sql_vars(self) -> Dict[str, WorkflowDataFrame]:
        return self._sql_vars

    def __call__(self, code: str, *args: Any, **kwargs: Any) -> None:
        self._sql(code, _caller_vars(2), *args, **kwargs)

    def _sql(
        self,
        code: str,
        caller_vars: Optional[Dict[str, Any]],
        *args: Any,
        **kwargs: Any,
    ) -> Dict[str, WorkflowDataFrame]:
        params: Dict[str, Any] = {}
        for a in args:
            if not isinstance(a, dict):
                raise ValueError(f"args can only contain dicts: {a}")
            params.update(a)
        params.update(kwargs)
        params, sources = _split_params(params)
        local_vars = dict(caller_vars or {})
        local_vars.update(params)
        code = fill_sql_template(code, params)
        compiler = FugueSQLCompiler(
            workflow=self,
            variables=self._sql_vars,
            sources=sources,
            local_vars=local_vars,
            dialect=self._conf.get(FUGUE_CONF_SQL_DIALECT, "spark"),
            last=self.last_df,
        )
        variables = compiler.compile(code)
        for k, v in variables.items():
            if isinstance(v, WorkflowDataFrame) and v.workflow is self:
                self._sql_vars[k] = v
        if compiler.last is not None:
            self._last_df = compiler.last
        return variables


def fugue_sql_flow(query: str, *args: Any, **kwargs: Any) -> FugueSQLWorkflow:
    """Build (but don't run) a FugueSQLWorkflow from a full FugueSQL script;
    use YIELD inside the script to expose results."""
    dag = FugueSQLWorkflow()
    dag._sql(query, _caller_vars(2), *args, **kwargs)
    return dag


def _fugue_sql_impl(
    query: str,
    caller_vars: Dict[str, Any],
    args: Any,
    kwargs: Dict[str, Any],
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
) -> Any:
    dag = FugueSQLWorkflow()
    dag._sql(query, caller_vars, *args, **kwargs)
    if dag.last_df is None:
        raise ValueError(f"no dataframe to output from\n{query}")
    dag.last_df.yield_dataframe_as("result", as_local=as_local)
    e = make_execution_engine(engine, engine_conf)
    dag.run(e)
    result = dag.yields["result"].result  # type: ignore
    if as_fugue:
        return result
    from fugue_tpu.dataframe.api import get_native_as_df

    return result.native if result.is_local else get_native_as_df(result)


def lint_sql(query: str, *args: Any, conf: Any = None, **kwargs: Any) -> Any:
    """Compile a FugueSQL script into a DAG and statically analyze it
    WITHOUT executing anything: returns the list of
    :class:`~fugue_tpu.analysis.Diagnostic` findings (most severe first).
    The same compilation path as :func:`fugue_sql_flow`, so FugueSQL
    syntax errors surface as usual; column/partition/conf problems come
    back as stable-coded diagnostics instead of mid-run failures. Also
    available from the shell: ``python -m fugue_tpu.analysis script.fsql``."""
    dag = FugueSQLWorkflow(conf)
    dag._sql(query, _caller_vars(2), *args, **kwargs)
    return dag.analyze(conf=conf)


def explain_sql(
    query: str,
    *args: Any,
    conf: Any = None,
    engine: Any = None,
    **kwargs: Any,
) -> Any:
    """EXPLAIN a FugueSQL script WITHOUT executing it: compile the DAG
    (same path as :func:`fugue_sql_flow`, so caller-local dataframes
    resolve as usual) and return the
    :class:`~fugue_tpu.analysis.explain.ExplainReport` — the
    optimizer-rewritten task tree with applied rewrites, propagated
    schemas and estimated device bytes (``.to_text()`` /
    ``.to_dict()``). Pair with ``fugue.obs.profile`` and
    ``FugueWorkflowResult.profile()`` for EXPLAIN ANALYZE."""
    dag = FugueSQLWorkflow(conf)
    dag._sql(query, _caller_vars(2), *args, **kwargs)
    return dag.explain(conf=conf, engine=engine)


def fugue_sql(
    query: str,
    *args: Any,
    engine: Any = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
    **kwargs: Any,
) -> Any:
    """Run a FugueSQL script and return its LAST dataframe (use
    :func:`fugue_sql_flow` + YIELD for multiple outputs)."""
    return _fugue_sql_impl(
        query, _caller_vars(2), args, kwargs,
        engine=engine, engine_conf=engine_conf,
        as_fugue=as_fugue, as_local=as_local,
    )
