"""FugueSQL-equivalent front end: tokenizer, parser, DAG compiler and the
SQL-on-dataframes executor (reference fugue/sql + fugue-sql-antlr + qpd,
rebuilt from scratch — see fugue_tpu/sql_frontend/parser.py)."""
