"""SQL AST -> device plan bridge.

Lowers SELECT queries into a small tree of engine primitives —
``engine.join`` / ``engine.union`` / ``engine.select`` / device sort —
so that on the jax engine, joins, set ops, GROUP BY and ORDER BY all run
on device (the role the reference's SQL backends play natively:
``/root/reference/fugue_duckdb/execution_engine.py:238-483`` builds its
relational ops as DuckDB SQL; here the bridge builds them as device
relational ops), including windows (``WindowPlan``): the ranking
family, whole-partition / running / framed aggregates over the FULL
frame matrix (ROWS, GROUPS, RANGE incl. numeric offsets), LAG/LEAD and
FIRST/LAST/NTH_VALUE; multiset set ops; DISTINCT and variance/median
aggregates; HAVING; string predicates, LIKE (literal AND dynamic
column-valued patterns via pairwise-dictionary LUTs), CASE and the
scalar function library incl. multi-column CONCAT (composed
cross-product dictionaries); uncorrelated ``col [NOT] IN (SELECT ...)``
WHERE conjuncts as device SEMI / 3VL-anti joins, equi-correlated
``[NOT] EXISTS`` as device SEMI/ANTI joins, and uncorrelated scalar
subqueries inlined as device-computed literals
(:func:`inline_scalar_subqueries`). Returns ``None`` for anything
outside the supported shape (non-equi joins and correlations,
oversized frame offsets, over-cap dictionary compositions) so callers
fall back to the host SELECT runner.

Name scoping is tracked per relation (each plan node knows its output
column names), so a qualified reference to a column the relation does
not own is a translation failure — the host runner then raises the
proper SQL error instead of the bridge silently mis-binding it.
"""

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from fugue_tpu.column import functions as ff
from fugue_tpu.column.expressions import ColumnExpr, col, lit, null
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.sql_frontend import ast

__all__ = [
    "translate_query",
    "inline_scalar_subqueries",
    "Plan",
    "ScanPlan",
    "JoinPlan",
    "NotInJoinPlan",
    "SetPlan",
    "SelectPlan",
    "WindowPlan",
    "WindowSpec",
]

from fugue_tpu.column.functions import VARIANCE_FUNCS

_AGG_FUNCS = {
    "sum", "min", "max", "avg", "mean", "count", "first", "last",
    "median", *VARIANCE_FUNCS,
}

_JOIN_HOW = {
    "inner": "inner",
    "cross": "cross",
    "left_outer": "left_outer",
    "right_outer": "right_outer",
    "full_outer": "full_outer",
    "semi": "semi",
    "anti": "anti",
}


class _GiveUp(Exception):
    pass


class Plan:
    """A device-executable relational plan node.

    ``out_names`` is the node's PHYSICAL output column list (what the
    engine frame will hold); executors walk the tree with engine
    primitives. ``sql_row_names`` is the SQL-visible namespace, which can
    differ: an ON equi-join keeps BOTH key columns visible (referencing
    the bare key is ambiguous, per the host oracle) even though the
    engine output collapses them, while USING merges them in SQL too."""

    out_names: List[str]

    @property
    def sql_row_names(self) -> List[str]:
        return self.out_names


class ScanPlan(Plan):
    def __init__(self, table: str, out_names: List[str]):
        self.table = table
        self.out_names = out_names


class JoinPlan(Plan):
    def __init__(
        self,
        left: Plan,
        right: Plan,
        how: str,
        on: List[str],
        using: bool = False,
    ):
        self.left = left
        self.right = right
        self.how = how
        self.on = on
        self.using = using
        if how in ("semi", "anti"):
            self.out_names = list(left.out_names)
            self._sql_names = list(left.sql_row_names)
        else:
            keyset = {k.lower() for k in on}
            self.out_names = list(left.out_names) + [
                n for n in right.out_names if n.lower() not in keyset
            ]
            if using:
                self._sql_names = list(self.out_names)
            else:
                # ON join: both key columns stay SQL-visible, so a bare
                # reference to the key is ambiguous — exactly what the
                # host oracle enforces
                self._sql_names = list(left.sql_row_names) + list(
                    right.sql_row_names
                )

    @property
    def sql_row_names(self) -> List[str]:
        return self._sql_names


class NotInJoinPlan(Plan):
    """``WHERE x NOT IN (SELECT ...)`` — an anti-join variant with SQL's
    three-valued NOT IN semantics (relational.not_in_join). Keeps the
    left frame's columns/visibility like semi/anti."""

    def __init__(self, left: Plan, right: Plan, key: str):
        self.left = left
        self.right = right
        self.key = key
        self.out_names = list(left.out_names)
        self._sql_names = list(left.sql_row_names)

    @property
    def sql_row_names(self) -> List[str]:
        return self._sql_names


class SetPlan(Plan):
    def __init__(self, op: str, distinct: bool, left: Plan, right: Plan):
        self.op = op  # union | except | intersect
        self.distinct = distinct
        self.left = left
        self.right = right
        self.out_names = list(left.out_names)


class SelectPlan(Plan):
    """Project/filter/aggregate over ``source`` plus post-ops.

    ``cols is None`` means pass the source through unchanged (used to
    hang ORDER BY / LIMIT off a set-op result)."""

    def __init__(
        self,
        source: Plan,
        cols: Optional[SelectColumns],
        where: Optional[ColumnExpr],
        having: Optional[ColumnExpr],
        order_by: List[Tuple[str, bool, Optional[str]]],
        limit: Optional[int],
        offset: Optional[int],
        distinct: bool,
        out_names: List[str],
    ):
        self.source = source
        self.cols = cols
        self.where = where
        self.having = having
        self.order_by = order_by  # (output column, asc, nulls)
        self.limit = limit
        self.offset = offset
        self.distinct = distinct
        self.out_names = out_names


class WindowSpec:
    """One device-lowerable window item: the ranking family
    (row_number/rank/dense_rank/ntile/percent_rank/cume_dist, needing
    ORDER BY), a whole-partition aggregate (sum/count/avg/min/max, no
    ORDER BY), a running or ROWS-framed aggregate/positional
    (sum/count/avg/min/max/first_value/last_value/nth_value with ORDER
    BY), or lag/lead. ``param`` holds ntile's bucket count, nth_value's
    position or lag/lead's offset; ``default`` lag/lead's fill literal.
    ``frame`` is a normalized frame ``(unit, lo_kind, lo_n, hi_kind,
    hi_n)`` — unit 'rows'/'groups'/'range', kinds 'up'/'p'/'c'/'f'/'uf'
    — or None for the default frame (running when ``order_by`` is
    non-empty; RANGE offsets require exactly one ORDER BY key)."""

    def __init__(
        self,
        name: str,
        func: str,
        arg: Optional[str],
        partition_by: List[str],
        order_by: List[Tuple[str, bool, Optional[bool]]],
        param: Optional[int] = None,
        frame: Optional[
            Tuple[str, str, Optional[float], str, Optional[float]]
        ] = None,
        default: Optional[object] = None,
    ):
        self.name = name
        self.func = func
        self.arg = arg
        self.partition_by = partition_by
        self.order_by = order_by  # (column, asc, nulls_first)
        self.param = param
        self.frame = frame
        self.default = default


class WindowPlan(Plan):
    """Window items + passthrough columns over ``source``; executed by
    ``relational.device_window``."""

    def __init__(
        self,
        source: Plan,
        items: List[Tuple[str, object]],
        where: Optional[ColumnExpr],
        out_names: List[str],
    ):
        self.source = source
        self.items = items  # ("col", (out, src)) | ("win", WindowSpec)
        self.where = where
        self.out_names = out_names


class _Scope:
    """Visible relations: alias -> that relation's output column names.
    ``row_names`` is the FROM clause's final (join-deduped) column list —
    unqualified references resolve against it, so a join key appearing on
    both sides is unambiguous exactly when the join collapsed it."""

    def __init__(self) -> None:
        self.relations: Dict[str, List[str]] = {}
        self.row_names: List[str] = []
        # (alias, column) pairs whose SQL value diverges from the surviving
        # joined column — e.g. ``b.k`` after ``a LEFT JOIN b`` is NULL on
        # unmatched rows while the surviving ``k`` is a's value
        self.tainted: Set[Tuple[str, str]] = set()

    def add(self, alias: str, names: List[str]) -> None:
        if alias.lower() in self.relations:
            raise _GiveUp()  # duplicate alias: let the host runner error
        self.relations[alias.lower()] = names

    def taint(self, alias: str, name: str) -> None:
        self.tainted.add((alias.lower(), name.lower()))

    def resolve(self, name: str, table: Optional[str]) -> str:
        """Return the bound column name, or give up on a bad/ambiguous
        reference (the host runner owns the error message)."""
        if table is not None:
            if (table.lower(), name.lower()) in self.tainted:
                raise _GiveUp()
            names = self.relations.get(table.lower())
            if names is None:
                raise _GiveUp()
            for n in names:
                if n.lower() == name.lower():
                    return n
            raise _GiveUp()
        hits = [n for n in self.row_names if n.lower() == name.lower()]
        if len(hits) != 1:
            raise _GiveUp()
        return hits[0]


def inline_scalar_subqueries(
    q: ast.Node,
    df_schemas: Dict[str, Sequence[str]],
    run_plan: Any,  # Callable[[Plan], DataFrame-like]
) -> None:
    """Pre-pass: replace each UNCORRELATED scalar subquery whose body
    lowers to a device plan with the literal value computed on device
    (one scalar readback — the data never leaves the device). The
    rewritten outer query then lowers as usual, so e.g.
    ``WHERE v > (SELECT AVG(v) FROM t)`` runs entirely in-engine (the
    reference executes all SQL in-engine,
    /root/reference/fugue_duckdb/execution_engine.py:37-135).

    Non-lowerable, correlated, multi-row or exotic-typed subqueries stay
    in the tree — the host runner owns those (including the proper
    "more than one row" error). Mutates ``q`` in place (the ast is
    parsed fresh per statement).

    Guards (review findings): a subquery referencing a name any CTE
    shadows is never inlined (the base-table value would silently
    diverge from the host's CTE-scoped one), and nothing executes until
    a cheap placeholder probe shows the OUTER query would lower — a
    host-destined statement must not pay device subquery runs it will
    redo on the host."""
    import copy

    cte_names: Set[str] = set()
    subq_count = 0

    def _scan(node: Any) -> None:
        nonlocal subq_count
        if isinstance(node, ast.With):
            cte_names.update(name.lower() for name, _ in node.ctes)
        if isinstance(node, ast.ScalarSubquery):
            subq_count += 1
        if isinstance(node, ast.Node):
            for f in node._fields:
                _scan_val(getattr(node, f))

    def _scan_val(v: Any) -> None:
        if isinstance(v, ast.Node):
            _scan(v)
        elif isinstance(v, (list, tuple)):
            for x in v:
                _scan_val(x)

    _scan(q)
    if subq_count == 0:
        return
    # probe: would the outer query lower with the subqueries replaced by
    # placeholder literals? (numeric and string shapes both tried — the
    # value's kind can decide lowerability)
    probe_ok = False
    for ph in (ast.Lit(0), ast.Lit("")):
        qc = copy.deepcopy(q)

        def _stub(node: Any) -> Any:
            if isinstance(node, ast.ScalarSubquery):
                return copy.deepcopy(ph)
            if isinstance(node, ast.Node):
                for f in node._fields:
                    setattr(node, f, _stub_val(getattr(node, f)))
            return node

        def _stub_val(v: Any) -> Any:
            if isinstance(v, ast.Node):
                return _stub(v)
            if isinstance(v, list):
                return [_stub_val(x) for x in v]
            if isinstance(v, tuple):
                return tuple(_stub_val(x) for x in v)
            return v

        if translate_query(_stub(qc), df_schemas) is not None:
            probe_ok = True
            break
    if not probe_ok:
        return

    def _references_cte(sub: ast.Node) -> bool:
        found = False

        def _walk_refs(node: Any) -> None:
            nonlocal found
            if isinstance(node, ast.TableRef):
                if node.name.lower() in cte_names:
                    found = True
            if isinstance(node, ast.Node):
                for f in node._fields:
                    _walk_refs_val(getattr(node, f))

        def _walk_refs_val(v: Any) -> None:
            if isinstance(v, ast.Node):
                _walk_refs(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    _walk_refs_val(x)

        _walk_refs(sub)
        return found

    def _rewrite(node: Any) -> Any:
        if isinstance(node, ast.ScalarSubquery):
            if cte_names and _references_cte(node.query):
                return node  # a CTE shadows the name: host scoping wins
            # translate a COPY: when this pass declines to inline (plan
            # not lowerable, >1 row, exotic value), the original tree must
            # come out untouched — the host runner reuses it, and a
            # synthetic __scalar__ alias left behind would leak into its
            # scoping (ADVICE r5 #4)
            query = node.query
            if (
                isinstance(node.query, ast.Select)
                and len(node.query.items) == 1
                and node.query.items[0].alias is None
                and not isinstance(node.query.items[0].expr, ast.Star)
            ):
                # the bridge needs named computed columns; the name is
                # never visible to the outer query
                query = copy.deepcopy(node.query)
                query.items[0].alias = "__scalar__"
            plan = translate_query(query, df_schemas)
            if plan is None or len(plan.out_names) != 1:
                return node
            try:
                res = run_plan(plan)
                n = res.count()
                if n > 1:
                    return node  # host raises the >1-row error
                v = None if n == 0 else res.as_array()[0][0]
                tp = res.schema.fields[0].type
            except Exception:
                return node
            if v is not None and hasattr(v, "item"):
                v = v.item()
            if isinstance(v, float) and v != v:
                v = None  # NaN payload -> SQL NULL
            if v is None:
                # a bare NULL literal is typeless; the host's scalar
                # subquery carries the subquery's dtype — cast to match
                tn = _sql_type_name(tp)
                return (
                    ast.Cast(ast.Lit(None), tn) if tn is not None else node
                )
            if isinstance(v, (bool, int, float, str)):
                return ast.Lit(v)
            return node  # exotic value type: host owns it
        if isinstance(node, ast.Node):
            for f in node._fields:
                setattr(node, f, _walk(getattr(node, f)))
        return node

    def _walk(v: Any) -> Any:
        if isinstance(v, ast.Node):
            return _rewrite(v)
        if isinstance(v, list):
            return [_walk(x) for x in v]
        if isinstance(v, tuple):
            return tuple(_walk(x) for x in v)
        return v

    _rewrite(q)


def _sql_type_name(tp: Any) -> Optional[str]:
    """SQL type name for a pyarrow type (inverse of the parsers'
    _SQL_TYPES for the types a scalar subquery can produce)."""
    import pyarrow as pa

    if pa.types.is_float64(tp):
        return "double"
    if pa.types.is_float32(tp):
        return "float"
    if pa.types.is_int64(tp):
        return "long"
    if pa.types.is_int32(tp):
        return "int"
    if pa.types.is_int16(tp):
        return "smallint"
    if pa.types.is_int8(tp):
        return "tinyint"
    if pa.types.is_boolean(tp):
        return "boolean"
    if pa.types.is_string(tp) or pa.types.is_large_string(tp):
        return "string"
    return None


def translate_query(
    query: ast.Query, df_schemas: Dict[str, Sequence[str]]
) -> Optional[Plan]:
    """Translate a full query (CTEs, set ops, joins, nested SELECTs) into
    a device plan, or ``None`` when any part falls outside the supported
    shape."""
    try:
        return _query(
            {n.lower(): list(v) for n, v in df_schemas.items()}, query
        )
    except _GiveUp:
        return None


def _query(env: Dict[str, object], q: ast.Query) -> Plan:
    if isinstance(q, ast.With):
        inner = dict(env)
        for name, sub in q.ctes:
            inner[name.lower()] = _query(inner, sub)
        return _query(inner, q.body)
    if isinstance(q, ast.SetOp):
        op = q.op.lower()
        if op not in ("union", "except", "intersect"):
            raise _GiveUp()
        left = _query(env, q.left)
        right = _query(env, q.right)
        plan: Plan = SetPlan(op, not q.all, left, right)
        if q.order_by or q.limit is not None or q.offset is not None:
            order = _order_items(q.order_by, plan.out_names)
            plan = SelectPlan(
                plan, None, None, None, order, q.limit, q.offset,
                False, list(plan.out_names),
            )
        return plan
    if isinstance(q, ast.Select):
        return _select(env, q)
    raise _GiveUp()


def _relation(env: Dict[str, object], rel: ast.Relation, scope: _Scope) -> Plan:
    if isinstance(rel, ast.TableRef):
        target = env.get(rel.name.lower())
        if target is None:
            raise _GiveUp()
        alias = rel.alias or rel.name
        if isinstance(target, Plan):  # CTE body
            plan: Plan = target
            names = list(target.out_names)
        else:
            names = list(target)  # type: ignore[arg-type]
            plan = ScanPlan(rel.name.lower(), names)
        scope.add(alias, names)
        return plan
    if isinstance(rel, ast.SubqueryRef):
        sub = _query(env, rel.query)
        scope.add(rel.alias, list(sub.out_names))
        return sub
    if isinstance(rel, ast.JoinRel):
        left = _relation(env, rel.left, scope)
        left_aliases = set(scope.relations)
        right_scope = _Scope()
        right = _relation(env, rel.right, right_scope)
        for alias, names in right_scope.relations.items():
            scope.add(alias, names)
        scope.tainted |= right_scope.tainted
        how = _JOIN_HOW.get(rel.how.lower().replace(" ", "_"))
        if how is None:
            raise _GiveUp()
        keys = _join_keys(rel, left, right)
        if how != "cross" and len(keys) == 0:
            raise _GiveUp()
        # a qualified key reference on an outer join's null-filled side is
        # NOT the surviving joined key — decline those bindings
        if how in ("left_outer", "full_outer"):
            for alias in set(scope.relations) - left_aliases:
                for k in keys:
                    scope.taint(alias, k)
        if how in ("right_outer", "full_outer"):
            for alias in left_aliases:
                for k in keys:
                    scope.taint(alias, k)
        plan = JoinPlan(left, right, how, keys, using=bool(rel.using))
        lowered_names = [n.lower() for n in plan.out_names]
        if len(set(lowered_names)) != len(lowered_names):
            raise _GiveUp()  # shared non-key columns: engine.join can't
        return plan
    raise _GiveUp()


def _join_keys(rel: ast.JoinRel, left: Plan, right: Plan) -> List[str]:
    """Equi-join keys: USING(...) or an ON conjunction of same-name
    column equalities across the two sides. Keys resolve
    case-insensitively against BOTH sides' actual column names."""
    lnames = {n.lower(): n for n in left.out_names}
    rnames = {n.lower(): n for n in right.out_names}
    if rel.using:
        out = []
        for u in rel.using:
            nl = u.lower()
            if nl not in lnames or nl not in rnames:
                raise _GiveUp()
            out.append(lnames[nl])
        return out
    if rel.on is None:
        return []

    def _conj(e: ast.Expr) -> List[str]:
        if isinstance(e, ast.Binary) and e.op.upper() == "AND":
            return _conj(e.left) + _conj(e.right)
        if (
            isinstance(e, ast.Binary)
            and e.op == "="
            and isinstance(e.left, ast.Col)
            and isinstance(e.right, ast.Col)
        ):
            a, b = e.left, e.right
            if a.name.lower() != b.name.lower():
                raise _GiveUp()  # differently-named equi keys: host only
            nl = a.name.lower()
            if nl not in lnames or nl not in rnames:
                raise _GiveUp()
            return [lnames[nl]]
        raise _GiveUp()

    return _conj(rel.on)


def _select(env: Dict[str, object], q: ast.Select) -> Plan:
    if q.from_ is None:
        raise _GiveUp()  # FROM-less SELECT: host evaluates it fine
    scope = _Scope()
    source = _relation(env, q.from_, scope)
    scope.row_names = list(source.sql_row_names)
    if any(isinstance(it.expr, ast.Window) for it in q.items):
        return _window_select(q, scope, source)

    exprs: List[ColumnExpr] = []
    out_names: List[str] = []
    implicit_star = False
    for item in q.items:
        if isinstance(item.expr, ast.Star):
            if (
                item.expr.table is not None
                and item.expr.table.lower() not in scope.relations
            ):
                raise _GiveUp()
            if item.expr.table is not None and len(scope.relations) > 1:
                raise _GiveUp()  # per-table star over a join: host only
            visible = [n.lower() for n in source.sql_row_names]
            if len(set(visible)) != len(visible):
                # SELECT * over an ON join duplicates the key column —
                # the host oracle rejects that; don't silently dedup
                raise _GiveUp()
            exprs.append(col("*"))
            out_names.extend(source.out_names)
            implicit_star = True
            continue
        e = _expr(item.expr, scope)
        if item.alias:
            e = e.alias(item.alias)
        elif e.output_name == "":
            raise _GiveUp()  # unnamed computed column
        exprs.append(e)
        out_names.append(e.output_name)

    cols = SelectColumns(*exprs)
    if cols.has_agg and implicit_star:
        raise _GiveUp()
    if q.group_by:
        # each GROUP BY entry — ordinal, select alias, plain column or
        # expression — must cover a non-agg select item, and every
        # non-agg item must be covered (extra keys: host runner)
        na_pairs = [
            (item, e)
            for item, e in zip(q.items, exprs)
            if any(e is k for k in cols.group_keys)
        ]
        covered = [False] * len(na_pairs)

        def _cover(pred) -> bool:
            hit = False
            for j, (item, e2) in enumerate(na_pairs):
                if pred(item, e2):
                    covered[j] = True
                    hit = True
            return hit

        for g in q.group_by:
            if (
                isinstance(g, ast.Lit)
                and isinstance(g.value, int)
                and not isinstance(g.value, bool)
            ):
                idx = g.value - 1
                if not (0 <= idx < len(q.items)) or not _cover(
                    lambda item, _e, t=q.items[idx]: item is t
                ):
                    raise _GiveUp()
                continue
            if isinstance(g, ast.Col):
                # a real input column takes precedence over a select
                # alias of the same folded name (host runner agrees);
                # an ambiguous reference gives up so the host owns the
                # error message
                if g.table is None and not any(
                    n.lower() == g.name.lower() for n in scope.row_names
                ):
                    if _cover(
                        lambda item, _e: item.alias is not None
                        and item.alias.lower() == g.name.lower()
                    ):
                        continue
                    raise _GiveUp()
                resolved = scope.resolve(g.name, g.table).lower()

                def _same_col(item: ast.SelectItem, _e: ColumnExpr) -> bool:
                    if not isinstance(item.expr, ast.Col):
                        return False
                    try:
                        return (
                            scope.resolve(
                                item.expr.name, item.expr.table
                            ).lower()
                            == resolved
                        )
                    except Exception:
                        return False

                if _cover(_same_col):
                    continue
                raise _GiveUp()
            if not _cover(lambda item, _e: item.expr == g):
                raise _GiveUp()
        if not all(covered) or not cols.has_agg:
            raise _GiveUp()
    elif cols.has_agg and len(cols.group_keys) > 0:
        raise _GiveUp()  # non-agg cols without GROUP BY is invalid SQL

    where_ast = q.where
    if where_ast is not None:
        source, where_ast = _lower_in_subqueries(
            env, source, scope, where_ast
        )
    where = _expr(where_ast, scope) if where_ast is not None else None
    having = _expr(q.having, scope) if q.having is not None else None
    order = _order_items(q.order_by, out_names)
    return SelectPlan(
        source, cols, where, having, order, q.limit, q.offset,
        q.distinct, out_names,
    )


def _lower_in_subqueries(
    env: Dict[str, object],
    source: Plan,
    scope: _Scope,
    where: ast.Expr,
) -> Tuple[Plan, Optional[ast.Expr]]:
    """Uncorrelated ``col IN (SELECT ...)`` WHERE conjuncts become
    device SEMI joins against the translated subquery; ``col NOT IN
    (SELECT ...)`` becomes a :class:`NotInJoinPlan` — an anti-join
    variant carrying SQL's three-valued NOT IN semantics (any NULL on
    the right keeps nothing; an empty right keeps everything). NULL
    semantics of the IN form match exactly: in a WHERE context a
    no-match NULL filters the row just like FALSE, and null keys never
    join."""

    remaining: List[ast.Expr] = []
    for c in _split_conjuncts(where):
        if isinstance(c, ast.InSubquery) and isinstance(c.operand, ast.Col):
            sub = _query(env, c.query)  # correlated refs -> _GiveUp
            if len(sub.out_names) != 1:
                raise _GiveUp()  # the host owns the arity error
            keyname = scope.resolve(c.operand.name, c.operand.table)
            inner = sub.out_names[0]
            if inner.lower() != keyname.lower():
                sub = SelectPlan(
                    sub,
                    SelectColumns(col(inner).alias(keyname)),
                    None, None, [], None, None, False, [keyname],
                )
            if c.negated:
                source = NotInJoinPlan(source, sub, keyname)
            else:
                source = JoinPlan(source, sub, "semi", [keyname])
            continue
        ex = _exists_form(c)
        if ex is not None:
            source = _decorrelate_exists(env, source, scope, *ex)
            continue
        remaining.append(c)
    out: Optional[ast.Expr] = None
    for c in remaining:
        out = c if out is None else ast.Binary("AND", out, c)
    return source, out


def _split_conjuncts(e: ast.Expr) -> List[ast.Expr]:
    if isinstance(e, ast.Binary) and e.op.upper() == "AND":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _has_aggregate(e: Any) -> bool:
    """Any aggregate call anywhere in the expression subtree (nested
    queries included — conservative: callers give up to the host)."""
    if isinstance(e, ast.Func) and e.name.lower() in _AGG_FUNCS:
        return True
    if isinstance(e, ast.Node):
        return any(
            _has_aggregate(getattr(e, f)) for f in e._fields
        )
    if isinstance(e, (list, tuple)):
        return any(_has_aggregate(x) for x in e)
    return False


def _exists_form(c: ast.Expr) -> Optional[Tuple[ast.Query, bool]]:
    if isinstance(c, ast.Exists):
        return (c.query, False)
    if (
        isinstance(c, ast.Unary)
        and c.op.upper() == "NOT"
        and isinstance(c.operand, ast.Exists)
    ):
        return (c.operand.query, True)
    return None


def _decorrelate_exists(
    env: Dict[str, object],
    source: Plan,
    scope: _Scope,
    q: ast.Query,
    negated: bool,
) -> Plan:
    """The classic decorrelation: ``[NOT] EXISTS (SELECT ... WHERE
    inner.k = outer.k AND <inner-only residuals>)`` is exactly a device
    SEMI (resp. ANTI) join on the equality pairs — NULL outer keys never
    join, which matches EXISTS evaluating the correlation to NULL.
    Anything beyond equi-correlation + inner residuals gives up (the
    host runner owns the general case)."""
    if not isinstance(q, ast.Select) or q.from_ is None:
        raise _GiveUp()
    if (
        q.group_by
        or q.having is not None
        or q.distinct
        or q.order_by
        or q.limit is not None
        or q.offset is not None
    ):
        raise _GiveUp()
    if _has_aggregate(list(q.items)) or _has_aggregate(q.where):
        # a scalar-aggregate subquery ALWAYS returns one row, so EXISTS
        # is unconditionally true — not a semi join (review finding)
        raise _GiveUp()
    inner_scope = _Scope()
    inner_src = _relation(env, q.from_, inner_scope)
    inner_scope.row_names = list(inner_src.sql_row_names)
    for item in q.items:  # EXISTS ignores items, but bad refs must fall
        if isinstance(item.expr, ast.Star):
            tbl = item.expr.table
            if tbl is not None and tbl.lower() not in inner_scope.relations:
                raise _GiveUp()  # unknown alias: the host raises it
            continue
        _expr(item.expr, inner_scope)

    def _bind(ref: ast.Col) -> Tuple[str, str]:
        # standard scoping: unqualified names prefer the INNER scope.
        # Only a name genuinely ABSENT from the inner scope may bind
        # outer — taint/ambiguity failures must not silently rebind
        # (review finding)
        if ref.table is not None:
            if ref.table.lower() in inner_scope.relations:
                return (
                    "inner", inner_scope.resolve(ref.name, ref.table)
                )
            return ("outer", scope.resolve(ref.name, ref.table))
        hits = [
            n
            for n in inner_scope.row_names
            if n.lower() == ref.name.lower()
        ]
        if hits:
            return ("inner", inner_scope.resolve(ref.name, None))
        return ("outer", scope.resolve(ref.name, None))

    pairs: List[Tuple[str, str]] = []  # (outer name, inner name)
    residual: Optional[ColumnExpr] = None
    for cj in _split_conjuncts(q.where) if q.where is not None else []:
        if (
            isinstance(cj, ast.Binary)
            and cj.op == "="
            and isinstance(cj.left, ast.Col)
            and isinstance(cj.right, ast.Col)
        ):
            (ka, na), (kb, nb) = _bind(cj.left), _bind(cj.right)
            if {ka, kb} == {"inner", "outer"}:
                outer_n = na if ka == "outer" else nb
                inner_n = na if ka == "inner" else nb
                pairs.append((outer_n, inner_n))
                continue
            if ka == "outer":  # outer = outer: host handles
                raise _GiveUp()
        # anything else must be INNER-only (resolve raises otherwise)
        term = _expr(cj, inner_scope)
        residual = term if residual is None else (residual & term)
    if not pairs:
        raise _GiveUp()  # uncorrelated EXISTS: host owns it
    outer_names = [o for o, _ in pairs]
    if len({o.lower() for o in outer_names}) != len(outer_names):
        raise _GiveUp()
    sub = SelectPlan(
        inner_src,
        SelectColumns(*[col(i).alias(o) for o, i in pairs]),
        residual, None, [], None, None, False, list(outer_names),
    )
    return JoinPlan(
        source, sub, "anti" if negated else "semi", list(outer_names)
    )


_DEVICE_WINDOW_AGGS = {"sum", "count", "avg", "mean", "min", "max"}

# scalar functions the bridge forwards into the column algebra (device
# evaluation or the pandas evaluator; anything else is a host fallback)
_SCALAR_FN_NAMES = {
    "abs", "round", "floor", "ceil", "ceiling", "sqrt", "exp", "ln",
    "log", "log2", "log10", "sin", "cos", "tan", "sign", "power", "pow",
    "mod", "nullif", "if", "iif", "upper", "ucase", "lower", "lcase",
    "length", "len", "trim", "ltrim", "rtrim", "reverse", "substring",
    "substr", "concat", "replace",
}

# device frame/offset arithmetic runs in int32 sorted-space positions;
# anything larger stays on the host runner (which handles it exactly)
_DEVICE_OFFSET_MAX = 1 << 30


def _device_int(nv: object, lo: int = 0) -> bool:
    return (
        isinstance(nv, int)
        and not isinstance(nv, bool)
        and lo <= nv <= _DEVICE_OFFSET_MAX
    )


def _window_select(q: ast.Select, scope: _Scope, source: Plan) -> Plan:
    """SELECT with window items -> WindowPlan (verdict r3 item 4's device
    lowering). Shapes beyond the device set — running frames, rank/lag/
    lead, expression args — give up to the host runner."""
    if q.group_by or q.having is not None or q.distinct:
        raise _GiveUp()
    items: List[Tuple[str, object]] = []
    out_names: List[str] = []
    for item in q.items:
        e = item.expr
        if isinstance(e, ast.Col):
            name = scope.resolve(e.name, e.table)
            out = item.alias or name
            items.append(("col", (out, name)))
            out_names.append(out)
            continue
        if not isinstance(e, ast.Window) or item.alias is None:
            raise _GiveUp()
        if e.func.distinct:
            raise _GiveUp()
        part: List[str] = []
        for pexpr in e.partition_by:
            if not isinstance(pexpr, ast.Col):
                raise _GiveUp()
            part.append(scope.resolve(pexpr.name, pexpr.table))
        order: List[Tuple[str, bool, Optional[bool]]] = []
        for o in e.order_by:
            if not isinstance(o.expr, ast.Col):
                raise _GiveUp()
            order.append(
                (
                    scope.resolve(o.expr.name, o.expr.table),
                    o.asc,
                    None if o.nulls is None else o.nulls == "FIRST",
                )
            )
        fn = e.func.name
        arg: Optional[str] = None
        param: Optional[int] = None
        default: Optional[object] = None
        # normalize the frame clause: None = the SQL default frame.
        # ROWS, GROUPS and single-key RANGE frames (incl. numeric
        # offsets) all lower to device; only oversized offsets and
        # multi-key RANGE stay on the host runner.
        frame: Optional[
            Tuple[str, str, Optional[float], str, Optional[float]]
        ]
        frame = None
        whole_partition = False
        fr = e.frame
        is_ranking = fn in (
            "row_number", "rank", "dense_rank", "percent_rank",
            "cume_dist", "ntile", "lag", "lead",
        )
        if fr is not None and not is_ranking:  # ranking ignores frames
            sk, sn = fr.start
            ek, en = fr.end
            if fr.unit == "groups" and not order:
                raise _GiveUp()  # the host runner owns this error
            if (sk, ek) == ("up", "uf"):
                whole_partition = True
            elif fr.unit == "range":
                if (sk, ek) == ("up", "c"):
                    pass  # the default running frame
                elif len(order) == 1:
                    # numeric RANGE offsets: one ORDER BY key required
                    for kd, nv in ((sk, sn), (ek, en)):
                        if kd in ("p", "f") and (
                            isinstance(nv, bool)
                            or not isinstance(nv, (int, float))
                            or not (0 <= nv <= _DEVICE_OFFSET_MAX)
                        ):
                            raise _GiveUp()  # host runner owns the error
                    frame = ("range", sk, sn, ek, en)
                else:
                    raise _GiveUp()
            elif fr.unit == "rows":
                for kd, nv in ((sk, sn), (ek, en)):
                    if kd in ("p", "f") and not _device_int(nv):
                        raise _GiveUp()  # host runner owns the error
                frame = ("rows", sk, sn, ek, en)
            else:  # groups
                for kd, nv in ((sk, sn), (ek, en)):
                    if kd in ("p", "f") and not _device_int(nv):
                        raise _GiveUp()  # host runner owns the error
                frame = ("groups", sk, sn, ek, en)
        if fn in ("row_number", "rank", "dense_rank", "percent_rank",
                  "cume_dist"):
            if not order or e.func.args:
                raise _GiveUp()
        elif fn == "ntile":
            if not order or len(e.func.args) != 1:
                raise _GiveUp()
            a0 = e.func.args[0]
            if not isinstance(a0, ast.Lit) or not _device_int(a0.value, 1):
                raise _GiveUp()  # host runner owns the error message
            param = a0.value
        elif fn in _DEVICE_WINDOW_AGGS:
            if len(e.func.args) != 1:
                raise _GiveUp()
            a = e.func.args[0]
            if isinstance(a, ast.Star):
                if fn != "count":
                    raise _GiveUp()
            elif isinstance(a, ast.Col):
                arg = scope.resolve(a.name, a.table)
            else:
                raise _GiveUp()
            if whole_partition or (not order and fr is None):
                # order-insensitive over the whole partition: the plain
                # segment aggregate
                order = []
                frame = None
            elif not order:
                raise _GiveUp()  # framed but unordered: host runner
        elif fn in ("first_value", "last_value", "nth_value"):
            nargs = 2 if fn == "nth_value" else 1
            if not order or len(e.func.args) != nargs:
                raise _GiveUp()
            a = e.func.args[0]
            if not isinstance(a, ast.Col):
                raise _GiveUp()
            arg = scope.resolve(a.name, a.table)
            if fn == "nth_value":
                a1 = e.func.args[1]
                if not isinstance(a1, ast.Lit) or not _device_int(
                    a1.value, 1
                ):
                    raise _GiveUp()
                param = a1.value
            if whole_partition:
                frame = ("rows", "up", None, "uf", None)
        elif fn in ("lag", "lead"):
            if not order or not (1 <= len(e.func.args) <= 3):
                raise _GiveUp()
            a = e.func.args[0]
            if not isinstance(a, ast.Col):
                raise _GiveUp()
            arg = scope.resolve(a.name, a.table)
            param = 1
            if len(e.func.args) >= 2:
                a1 = e.func.args[1]
                if not isinstance(a1, ast.Lit) or not _device_int(a1.value):
                    raise _GiveUp()
                param = a1.value
            if len(e.func.args) == 3:
                a2 = e.func.args[2]
                dv: object = None
                if isinstance(a2, ast.Lit):
                    dv = a2.value
                elif (
                    isinstance(a2, ast.Unary)
                    and a2.op == "-"
                    and isinstance(a2.operand, ast.Lit)
                    and isinstance(a2.operand.value, (int, float))
                    and not isinstance(a2.operand.value, bool)
                ):
                    dv = -a2.operand.value
                if dv is None or isinstance(dv, (str, bool)):
                    raise _GiveUp()  # non-numeric defaults: host runner
                default = dv
        else:
            raise _GiveUp()  # expression args / exotic funcs: host runner
        items.append(
            (
                "win",
                WindowSpec(
                    item.alias, fn, arg, part, order, param,
                    frame=frame, default=default,
                ),
            )
        )
        out_names.append(item.alias)
    lowered = [n.lower() for n in out_names]
    if len(set(lowered)) != len(lowered):
        raise _GiveUp()
    where = _expr(q.where, scope) if q.where is not None else None
    plan: Plan = WindowPlan(source, items, where, out_names)
    if q.order_by or q.limit is not None or q.offset is not None:
        order2 = _order_items(q.order_by, out_names)
        plan = SelectPlan(
            plan, None, None, None, order2, q.limit, q.offset, False,
            list(out_names),
        )
    return plan


def _order_items(
    items: List[ast.OrderItem],
    out_names: List[str],
) -> List[Tuple[str, bool, Optional[str]]]:
    """ORDER BY entries resolved against the SELECT's OUTPUT columns
    (unqualified references and 1-based positions only — expression and
    qualified sort keys stay on the host runner)."""
    out: List[Tuple[str, bool, Optional[str]]] = []
    for o in items:
        e = o.expr
        if (
            isinstance(e, ast.Lit)
            and isinstance(e.value, int)
            and not isinstance(e.value, bool)
            and 1 <= e.value <= len(out_names)
        ):
            name = out_names[e.value - 1]
        elif isinstance(e, ast.Col):
            if e.table is not None:
                # a QUALIFIED ref names the source column, which an output
                # alias of the same name may shadow with different values —
                # sorting by the output here would silently diverge from
                # SQL semantics (review finding), so the host runner keeps
                # this shape
                raise _GiveUp()
            if e.name in out_names:  # exact name wins, like the host
                name = e.name
            else:
                folded = [n for n in out_names if n.lower() == e.name.lower()]
                if len(folded) != 1:  # missing or case-ambiguous: host
                    raise _GiveUp()
                name = folded[0]
        else:
            raise _GiveUp()
        out.append((name, o.asc, o.nulls))
    return out


_BIN_OPS = {"=", "<>", "!=", "<", "<=", ">", ">=", "+", "-", "*", "/",
            "AND", "OR"}


def _expr(e: ast.Expr, scope: _Scope) -> ColumnExpr:
    if isinstance(e, ast.Lit):
        return null() if e.value is None else lit(e.value)
    if isinstance(e, ast.Col):
        return col(scope.resolve(e.name, e.table))
    if isinstance(e, ast.Unary):
        op = e.op.upper()
        v = _expr(e.operand, scope)
        if op == "-":
            return -v
        if op == "+":
            return v
        if op == "NOT":
            return ~v
        raise _GiveUp()
    if isinstance(e, ast.Binary):
        op = e.op.upper()
        if op == "%":
            from fugue_tpu.column.expressions import function

            return function(
                "mod", _expr(e.left, scope), _expr(e.right, scope)
            )
        if op not in _BIN_OPS:
            raise _GiveUp()
        lv, rv = _expr(e.left, scope), _expr(e.right, scope)
        return {
            "=": lambda: lv == rv,
            "<>": lambda: lv != rv,
            "!=": lambda: lv != rv,
            "<": lambda: lv < rv,
            "<=": lambda: lv <= rv,
            ">": lambda: lv > rv,
            ">=": lambda: lv >= rv,
            "+": lambda: lv + rv,
            "-": lambda: lv - rv,
            "*": lambda: lv * rv,
            "/": lambda: lv / rv,
            "AND": lambda: lv & rv,
            "OR": lambda: lv | rv,
        }[op]()
    if isinstance(e, ast.Func):
        name = e.name.lower()
        if e.distinct and name not in _AGG_FUNCS:
            raise _GiveUp()
        if name in _AGG_FUNCS:
            if len(e.args) != 1:
                raise _GiveUp()
            a = e.args[0]
            arg = col("*") if isinstance(a, ast.Star) else _expr(a, scope)
            if name == "mean":
                name = "avg"
            if e.distinct:
                if isinstance(a, ast.Star):
                    raise _GiveUp()  # COUNT(DISTINCT *): host owns error
                from fugue_tpu.column.functions import _agg

                return _agg(name, arg, arg_distinct=True)
            if not hasattr(ff, name):  # variance family etc.
                from fugue_tpu.column.functions import _agg

                return _agg(name, arg)
            # the ff constructors mark is_aggregation (function() does not)
            return getattr(ff, name)(arg)
        if name == "coalesce":
            return ff.coalesce(*[_expr(a, scope) for a in e.args])
        if name in _SCALAR_FN_NAMES:
            from fugue_tpu.column.expressions import function

            return function(name, *[_expr(a, scope) for a in e.args])
        raise _GiveUp()
    if isinstance(e, ast.Cast):
        return _expr(e.operand, scope).cast(e.type_name)
    if isinstance(e, ast.IsNull):
        v = _expr(e.operand, scope)
        return v.not_null() if e.negated else v.is_null()
    if isinstance(e, ast.Between):
        v = _expr(e.operand, scope)
        res = (v >= _expr(e.low, scope)) & (v <= _expr(e.high, scope))
        return ~res if e.negated else res
    if isinstance(e, ast.InList):
        v = _expr(e.operand, scope)
        res: Optional[ColumnExpr] = None
        for item in e.items:
            term = v == _expr(item, scope)
            res = term if res is None else (res | term)
        if res is None:
            raise _GiveUp()
        return ~res if e.negated else res
    if isinstance(e, ast.Like):
        if isinstance(e.pattern, ast.Lit) and isinstance(
            e.pattern.value, str
        ):
            return ff.like(
                _expr(e.operand, scope), e.pattern.value, negated=e.negated
            )
        # dynamic (column-valued) pattern: engine-interpreted LIKE over
        # two expressions — on device a (value-dict x pattern-dict) LUT
        from fugue_tpu.column.expressions import function

        return function(
            "like",
            _expr(e.operand, scope),
            _expr(e.pattern, scope),
            lit(bool(e.negated)),
        )
    if isinstance(e, ast.Case):
        args: List[ColumnExpr] = []
        operand = (
            None if e.operand is None else _expr(e.operand, scope)
        )
        for cond, val in e.whens:
            c = _expr(cond, scope)
            if operand is not None:
                c = operand == c
            args.append(c)
            args.append(_expr(val, scope))
        args.append(
            null() if e.default is None else _expr(e.default, scope)
        )
        return ff.case_when(*args)
    raise _GiveUp()  # subqueries / windows
