"""SQL AST -> column algebra bridge.

Lets SQL engines lower simple single-table SELECT [WHERE] [GROUP BY]
queries into :meth:`ExecutionEngine.select` (the column-algebra path) —
on the jax engine that means device projections and segment-reduction
aggregates instead of the host SELECT runner. The reference gets this for
free from its SQL backends (Spark SQL, DuckDB); here the bridge plays
that role for expressions the device evaluator understands, and returns
``None`` for anything else (joins, subqueries, CTEs, set ops, ORDER BY,
window functions) so callers fall back to the host runner.
"""

from typing import Dict, List, Optional, Tuple

from fugue_tpu.column import functions as ff
from fugue_tpu.column.expressions import ColumnExpr, col, lit, null
from fugue_tpu.column.sql import SelectColumns
from fugue_tpu.sql_frontend import ast

__all__ = ["translate_simple_select", "SimplePlan"]

_AGG_FUNCS = {"sum", "min", "max", "avg", "mean", "count", "first", "last"}


class SimplePlan:
    """A single-table plan: run ``engine.select(dfs[table], cols, where,
    having)``."""

    def __init__(
        self,
        table: str,
        cols: SelectColumns,
        where: Optional[ColumnExpr],
        having: Optional[ColumnExpr],
    ):
        self.table = table
        self.cols = cols
        self.where = where
        self.having = having


class _GiveUp(Exception):
    pass


def translate_simple_select(
    query: ast.Query, df_names: List[str]
) -> Optional[SimplePlan]:
    """Translate, or None when the query doesn't fit the simple shape."""
    try:
        return _translate(query, df_names)
    except _GiveUp:
        return None


def _translate(query: ast.Query, df_names: List[str]) -> SimplePlan:
    if not isinstance(query, ast.Select):
        raise _GiveUp()
    if query.order_by or query.limit is not None or query.offset is not None:
        raise _GiveUp()
    if query.distinct:
        raise _GiveUp()
    if not isinstance(query.from_, ast.TableRef):
        raise _GiveUp()
    lowered = {n.lower(): n for n in df_names}
    tname = query.from_.name.lower()
    if tname not in lowered:
        raise _GiveUp()
    alias = (query.from_.alias or query.from_.name).lower()

    exprs: List[ColumnExpr] = []
    implicit_star = False
    for item in query.items:
        if isinstance(item.expr, ast.Star):
            if item.expr.table is not None and item.expr.table.lower() != alias:
                raise _GiveUp()
            exprs.append(col("*"))
            implicit_star = True
            continue
        e = _expr(item.expr, alias)
        if item.alias:
            e = e.alias(item.alias)
        elif e.output_name == "":
            raise _GiveUp()  # unnamed computed column
        exprs.append(e)

    cols = SelectColumns(*exprs)
    if cols.has_agg and implicit_star:
        raise _GiveUp()
    # GROUP BY keys must coincide with the non-agg select items
    if query.group_by:
        keys = set()
        for g in query.group_by:
            if not isinstance(g, ast.Col):
                raise _GiveUp()
            keys.add(g.name.lower())
        non_agg = {c.output_name.lower() for c in cols.group_keys}
        if keys != non_agg or not cols.has_agg:
            raise _GiveUp()
    elif cols.has_agg and len(cols.group_keys) > 0:
        raise _GiveUp()  # non-agg cols without GROUP BY is invalid SQL

    where = _expr(query.where, alias) if query.where is not None else None
    having = _expr(query.having, alias) if query.having is not None else None
    return SimplePlan(lowered[tname], cols, where, having)


_BIN_OPS = {"=", "<>", "!=", "<", "<=", ">", ">=", "+", "-", "*", "/",
            "AND", "OR"}


def _expr(e: ast.Expr, alias: str) -> ColumnExpr:
    if isinstance(e, ast.Lit):
        return null() if e.value is None else lit(e.value)
    if isinstance(e, ast.Col):
        if e.table is not None and e.table.lower() != alias:
            raise _GiveUp()
        return col(e.name)
    if isinstance(e, ast.Unary):
        op = e.op.upper()
        v = _expr(e.operand, alias)
        if op == "-":
            return -v
        if op == "+":
            return v
        if op == "NOT":
            return ~v
        raise _GiveUp()
    if isinstance(e, ast.Binary):
        op = e.op.upper()
        if op not in _BIN_OPS:
            raise _GiveUp()
        lv, rv = _expr(e.left, alias), _expr(e.right, alias)
        return {
            "=": lambda: lv == rv,
            "<>": lambda: lv != rv,
            "!=": lambda: lv != rv,
            "<": lambda: lv < rv,
            "<=": lambda: lv <= rv,
            ">": lambda: lv > rv,
            ">=": lambda: lv >= rv,
            "+": lambda: lv + rv,
            "-": lambda: lv - rv,
            "*": lambda: lv * rv,
            "/": lambda: lv / rv,
            "AND": lambda: lv & rv,
            "OR": lambda: lv | rv,
        }[op]()
    if isinstance(e, ast.Func):
        name = e.name.lower()
        if e.distinct:
            raise _GiveUp()
        if name in _AGG_FUNCS:
            if len(e.args) != 1:
                raise _GiveUp()
            a = e.args[0]
            arg = col("*") if isinstance(a, ast.Star) else _expr(a, alias)
            if name == "mean":
                name = "avg"
            # the ff constructors mark is_aggregation (function() does not)
            return getattr(ff, name)(arg)
        if name == "coalesce":
            return ff.coalesce(*[_expr(a, alias) for a in e.args])
        raise _GiveUp()
    if isinstance(e, ast.Cast):
        return _expr(e.operand, alias).cast(e.type_name)
    if isinstance(e, ast.IsNull):
        v = _expr(e.operand, alias)
        return v.not_null() if e.negated else v.is_null()
    if isinstance(e, ast.Between):
        v = _expr(e.operand, alias)
        res = (v >= _expr(e.low, alias)) & (v <= _expr(e.high, alias))
        return ~res if e.negated else res
    if isinstance(e, ast.InList):
        v = _expr(e.operand, alias)
        res: Optional[ColumnExpr] = None
        for item in e.items:
            term = v == _expr(item, alias)
            res = term if res is None else (res | term)
        if res is None:
            raise _GiveUp()
        return ~res if e.negated else res
    raise _GiveUp()  # Case / Like / subqueries / windows
