"""FugueSQL dialect compiler: parses a FugueSQL script and emits workflow
DAG operations — the role of the ANTLR grammar + ``_Extensions`` visitor in
the reference (fugue/sql/_visitors.py:305-743).

Statement forms (subset of the reference grammar, same semantics):

- ``[var =] SELECT ...`` / ``WITH ... SELECT ...`` — standard SQL routed to
  the engine's SQLEngine; a missing FROM uses the previous statement's result
- ``CREATE [[...], ...] SCHEMA s`` / ``CREATE USING ext [(params)]``
- ``TRANSFORM [dfs] [prepartition] USING ext [(params)] [SCHEMA s]
  [CALLBACK cb]`` (multiple dfs are zipped → cotransform)
- ``OUTTRANSFORM [dfs] [prepartition] USING ext [(params)] [CALLBACK cb]``
- ``PROCESS [dfs] [prepartition] USING ext [(params)] [SCHEMA s]``
- ``OUTPUT [dfs] [prepartition] USING ext [(params)]``
- ``PRINT [n ROWS] [FROM dfs] [ROWCOUNT] [TITLE "t"]``
- ``SAVE [df] [prepartition] OVERWRITE|APPEND|TO [SINGLE] [fmt] "path"
  [(params)]`` / ``SAVE AND USE ...``
- ``LOAD [fmt] "path" [(params)] [COLUMNS cols|schema]``
- ``ZIP dfs [INNER|LEFT OUTER|...] [BY cols] [PRESORT ...]``
- ``RENAME COLUMNS a:b[,...] [FROM df]`` / ``ALTER COLUMNS a:t[,...]
  [FROM df]`` / ``DROP COLUMNS a[,...] [IF EXISTS] [FROM df]``
- ``DROP ROWS IF ANY|ALL NULL[S] [ON cols] [FROM df]``
- ``FILL NULLS [PARAMS] k:v[,...] [FROM df]``
- ``SAMPLE [REPLACE] n ROWS | p PERCENT [SEED n] [FROM df]``
- ``TAKE n ROW[S] [FROM df] [prepartition] [PRESORT ...] [NULLS
  FIRST|LAST]``
- postfix modifiers on any assignable statement: ``PERSIST``, ``BROADCAST``,
  ``[LAZY] WEAK CHECKPOINT``, ``[LAZY] [STRONG] CHECKPOINT``, ``[LAZY]
  DETERMINISTIC CHECKPOINT [(params)]``, ``YIELD [LOCAL] DATAFRAME|FILE|
  TABLE AS name``
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

from fugue_tpu.exceptions import (
    FugueSQLSyntaxError as _BaseFugueSQLSyntaxError,
)
from fugue_tpu.collections.partition import PartitionSpec
from fugue_tpu.collections.sql import StructuredRawSQL
from fugue_tpu.sql_frontend import ast
from fugue_tpu.sql_frontend.parser import Cursor, ExprParser, SQLParseError
from fugue_tpu.sql_frontend.sqlgen import generate_parts
from fugue_tpu.sql_frontend.tokenizer import tokenize

__all__ = [
    "FugueSQLDialectSyntaxError",
    "FugueSQLSyntaxError",
    "FugueSQLCompiler",
]


class FugueSQLDialectSyntaxError(_BaseFugueSQLSyntaxError, ValueError):
    """FugueSQL DIALECT syntax error (catchable as the canonical
    fugue_tpu.exceptions.FugueSQLSyntaxError; ValueError kept for
    pre-hierarchy callers). The historical module-local name
    ``FugueSQLSyntaxError`` stays as an alias — import the canonical
    class from fugue_tpu.exceptions to catch EVERY SQL syntax error."""


FugueSQLSyntaxError = FugueSQLDialectSyntaxError


_STATEMENT_KEYWORDS = {
    "SELECT", "WITH", "CREATE", "TRANSFORM", "OUTTRANSFORM", "PROCESS",
    "OUTPUT", "PRINT", "SAVE", "LOAD", "ZIP", "RENAME", "ALTER", "DROP",
    "FILL", "SAMPLE", "TAKE",
}
_MODIFIER_KEYWORDS = {
    "PERSIST", "BROADCAST", "CHECKPOINT", "WEAK", "STRONG", "DETERMINISTIC",
    "LAZY", "YIELD",
}
_SCHEMA_OPS = {":", ",", "*", "+", "-", "~", "[", "]", "{", "}", "<", ">", "."}


class FugueSQLCompiler:
    """Compiles one FugueSQL script onto a FugueWorkflow."""

    def __init__(
        self,
        workflow: Any,
        variables: Optional[Dict[str, Any]] = None,
        sources: Optional[Dict[str, Any]] = None,
        local_vars: Optional[Dict[str, Any]] = None,
        dialect: Optional[str] = None,
        last: Any = None,
    ):
        self.workflow = workflow
        self.variables: Dict[str, Any] = dict(variables or {})
        self.sources = dict(sources or {})  # raw dataframes from the caller
        self.local_vars = dict(local_vars or {})
        self.dialect = dialect
        self.last = last

    def compile(self, code: str) -> Dict[str, Any]:
        from fugue_tpu.sql_frontend.native_build import enable_native_scanner

        enable_native_scanner()  # idempotent; falls back to python silently
        cur = Cursor(tokenize(code))
        while not cur.at_end():
            if cur.accept_op(";"):
                continue
            self._statement(cur)
        return self.variables

    # ---- statement dispatch ---------------------------------------------

    def _statement(self, cur: Cursor) -> None:
        varname = None
        if (
            cur.tok.kind == "IDENT"
            and cur.peek(1).kind == "OP"
            and cur.peek(1).value == "="
        ):
            varname = cur.advance().value
            cur.advance()
        tdf = self._task(cur)
        tdf = self._modifiers(cur, tdf, varname)
        if varname is not None:
            if tdf is None:
                raise FugueSQLSyntaxError(
                    f"cannot assign an output statement to {varname}"
                )
            self.variables[varname] = tdf
        if tdf is not None:
            self.last = tdf

    def _task(self, cur: Cursor) -> Any:
        t = cur.tok
        if t.kind != "IDENT":
            raise FugueSQLSyntaxError(f"unexpected token {t.value!r}")
        u = t.upper
        if u in ("SELECT", "WITH"):
            return self._select_stmt(cur)
        if u == "CREATE":
            return self._create_stmt(cur)
        if u in ("TRANSFORM", "OUTTRANSFORM"):
            return self._transform_stmt(cur, out=(u == "OUTTRANSFORM"))
        if u == "PROCESS":
            return self._process_stmt(cur)
        if u == "OUTPUT":
            return self._output_stmt(cur)
        if u == "PRINT":
            return self._print_stmt(cur)
        if u == "SAVE":
            return self._save_stmt(cur)
        if u == "LOAD":
            return self._load_stmt(cur)
        if u == "ZIP":
            return self._zip_stmt(cur)
        if u == "RENAME":
            return self._rename_stmt(cur)
        if u == "ALTER":
            return self._alter_stmt(cur)
        if u == "DROP":
            return self._drop_stmt(cur)
        if u == "FILL":
            return self._fillna_stmt(cur)
        if u == "SAMPLE":
            return self._sample_stmt(cur)
        if u == "TAKE":
            return self._take_stmt(cur)
        raise FugueSQLSyntaxError(f"unknown statement {t.value!r}")

    # ---- SELECT ---------------------------------------------------------

    def _select_stmt(self, cur: Cursor) -> Any:
        q = ExprParser(cur).query()
        if isinstance(q, ast.Select) and q.from_ is None and \
                self.last is not None:
            q.from_ = ast.TableRef("__fugue_last__")
        dfs: Dict[str, Any] = {}

        def resolve(name: str) -> str:
            if name == "__fugue_last__":
                dfs[name] = self.last
                return name
            df = self._find_df(name)
            if df is None:
                raise FugueSQLSyntaxError(f"{name} is not defined")
            dfs[name] = df
            return name

        parts = generate_parts(q, resolve)
        return self.workflow.select(
            StructuredRawSQL(parts, dialect=self.dialect),
            dfs=dfs if len(dfs) > 0 else None,
        )

    # ---- CREATE / LOAD --------------------------------------------------

    def _create_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("CREATE")
        if cur.is_kw("USING"):
            cur.advance()
            using = self._using_ref(cur)
            params = self._opt_params(cur)
            schema = self._opt_schema(cur)
            return self.workflow.create(
                using=using, schema=schema, params=params
            )
        data = self._json_value(cur)
        cur.expect_kw("SCHEMA")
        schema = self._schema_expr(cur)
        return self.workflow.df(data, schema=schema)

    def _load_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("LOAD")
        fmt = ""
        if cur.is_kw("PARQUET", "CSV", "JSON"):
            fmt = cur.advance().value.lower()
        path = self._path(cur)
        params = self._opt_paren_params(cur) or {}
        if cur.accept_kw("AS"):
            cur.expect_kw("OF")
            params.update(self._as_of_target(cur))
        columns: Any = None
        if cur.accept_kw("COLUMNS"):
            columns = self._schema_or_cols(cur)
        return self.workflow.load(path, fmt=fmt, columns=columns, **params)

    def _as_of_target(self, cur: Cursor) -> Dict[str, Any]:
        """``LOAD "lake://..." AS OF <target>`` — time travel against a
        versioned lake table. A bare integer pins a snapshot VERSION; a
        float or a quoted ISO datetime pins a TIMESTAMP (resolved to the
        newest snapshot committed at or before it). Both land in the
        load params, so ``AS OF`` against a non-lake path is statically
        flaggable (FWF507) and fails at run time."""
        v = self._json_value(cur)
        if isinstance(v, bool):
            raise FugueSQLSyntaxError("AS OF expects a version or timestamp")
        if isinstance(v, int):
            return {"version": v}
        if isinstance(v, float):
            return {"timestamp": v}
        if isinstance(v, str):
            try:
                return {"version": int(v)}
            except ValueError:
                pass
            try:
                return {"timestamp": float(v)}
            except ValueError:
                pass
            from datetime import datetime

            try:
                return {"timestamp": datetime.fromisoformat(v).timestamp()}
            except ValueError:
                raise FugueSQLSyntaxError(
                    f"invalid AS OF target {v!r} (expected a version "
                    "number, an epoch timestamp or an ISO datetime)"
                )
        raise FugueSQLSyntaxError("AS OF expects a version or timestamp")

    # ---- extension statements -------------------------------------------

    def _transform_stmt(self, cur: Cursor, out: bool) -> Any:
        cur.advance()  # TRANSFORM / OUTTRANSFORM
        dfs = self._opt_dfs(cur)
        partition = self._opt_prepartition(cur)
        cur.expect_kw("USING")
        using = self._using_ref(cur)
        params = self._opt_params(cur)
        schema = self._opt_schema(cur)
        callback = None
        if cur.accept_kw("CALLBACK"):
            callback = self._using_ref(cur)
        src = self._dfs_to_single(dfs, partition)
        pre = None if self._was_zipped(dfs) else partition
        if out:
            if schema is not None:
                raise FugueSQLSyntaxError("OUTTRANSFORM cannot have SCHEMA")
            src.out_transform(
                using, params=params, pre_partition=pre, callback=callback
            )
            return None
        return src.transform(
            using, schema=schema, params=params, pre_partition=pre,
            callback=callback,
        )

    def _was_zipped(self, dfs: Any) -> bool:
        return isinstance(dfs, (list, dict)) and len(dfs) > 1

    def _dfs_to_single(self, dfs: Any, partition: Any) -> Any:
        """One df passes through; many dfs are zipped by the prepartition
        keys (cotransform input)."""
        if isinstance(dfs, list) and len(dfs) > 1:
            return self.workflow.zip(*dfs, partition=partition)
        if isinstance(dfs, dict) and len(dfs) > 1:
            # pass the dict itself: zip keeps the names so cotransformers
            # can address inputs as dfs["name"]
            return self.workflow.zip(dfs, partition=partition)
        if isinstance(dfs, list):
            return dfs[0]
        if isinstance(dfs, dict):
            return next(iter(dfs.values()))
        return self._last_df()

    def _last_df(self) -> Any:
        if self.last is None:
            raise FugueSQLSyntaxError("no previous dataframe in this script")
        return self.last

    def _process_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("PROCESS")
        dfs = self._opt_dfs(cur)
        partition = self._opt_prepartition(cur)
        cur.expect_kw("USING")
        using = self._using_ref(cur)
        params = self._opt_params(cur)
        schema = self._opt_schema(cur)
        args = self._dfs_to_args(dfs)
        return self.workflow.process(
            *args, using=using, schema=schema, params=params,
            pre_partition=partition,
        )

    def _output_stmt(self, cur: Cursor) -> None:
        cur.expect_kw("OUTPUT")
        dfs = self._opt_dfs(cur)
        partition = self._opt_prepartition(cur)
        cur.expect_kw("USING")
        using = self._using_ref(cur)
        params = self._opt_params(cur)
        args = self._dfs_to_args(dfs)
        self.workflow.output(
            *args, using=using, params=params, pre_partition=partition
        )
        return None

    def _dfs_to_args(self, dfs: Any) -> List[Any]:
        if dfs is None:
            return [self._last_df()]
        if isinstance(dfs, dict):
            return [dfs]
        return list(dfs)

    # ---- simple df statements -------------------------------------------

    def _print_stmt(self, cur: Cursor) -> None:
        cur.expect_kw("PRINT")
        n = 10
        if cur.tok.kind == "NUMBER":
            n = int(cur.advance().value)
            cur.accept_kw("ROWS") or cur.accept_kw("ROW")
        dfs = None
        if cur.accept_kw("FROM"):
            dfs = self._dfs_clause(cur)
        elif (
            cur.tok.kind == "IDENT"
            and cur.tok.upper not in ("ROWCOUNT", "TITLE")
            and not (cur.peek(1).kind == "OP" and cur.peek(1).value == "=")
            and self._find_df(cur.tok.value) is not None
        ):
            dfs = self._dfs_clause(cur)
        with_count = False
        if cur.accept_kw("ROWCOUNT"):
            with_count = True
        title = None
        if cur.accept_kw("TITLE"):
            if cur.tok.kind not in ("STRING", "QIDENT"):
                raise FugueSQLSyntaxError("TITLE expects a string")
            title = cur.advance().value
        args = self._dfs_to_args(dfs)
        self.workflow.show(*args, n=n, with_count=with_count, title=title)
        return None

    def _save_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("SAVE")
        and_use = False
        if cur.accept_kw("AND"):
            cur.expect_kw("USE")
            and_use = True
        df = None
        if (
            cur.tok.kind == "IDENT"
            and not (cur.peek(1).kind == "OP" and cur.peek(1).value == "=")
            and self._find_df(cur.tok.value) is not None
        ):
            df = self._df_ref(cur)
        partition = self._opt_prepartition(cur)
        if cur.accept_kw("OVERWRITE"):
            mode = "overwrite"
        elif cur.accept_kw("APPEND"):
            mode = "append"
        elif cur.accept_kw("TO"):
            mode = "error"
        else:
            raise FugueSQLSyntaxError("SAVE requires OVERWRITE|APPEND|TO")
        single = cur.accept_kw("SINGLE")
        fmt = ""
        if cur.is_kw("PARQUET", "CSV", "JSON"):
            fmt = cur.advance().value.lower()
        path = self._path(cur)
        params = self._opt_paren_params(cur) or {}
        src = df if df is not None else self._last_df()
        if and_use:
            return src.save_and_use(
                path, fmt=fmt, mode=mode, partition=partition, single=single,
                **params,
            )
        src.save(
            path, fmt=fmt, mode=mode, partition=partition, single=single,
            **params,
        )
        return None

    def _zip_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("ZIP")
        dfs = self._dfs_clause(cur)
        how = "inner"
        if cur.is_kw("INNER", "CROSS"):
            how = cur.advance().value.lower()
        elif cur.is_kw("LEFT", "RIGHT", "FULL"):
            side = cur.advance().value.lower()
            cur.expect_kw("OUTER")
            how = f"{side}_outer"
        by: List[str] = []
        if cur.accept_kw("BY"):
            by = self._name_list(cur)
        presort = ""
        if cur.accept_kw("PRESORT"):
            presort = self._presort_expr(cur)
        partition = PartitionSpec(by=by, presort=presort)
        if isinstance(dfs, dict):
            return self.workflow.zip(dfs, how=how, partition=partition)
        return self.workflow.zip(*dfs, how=how, partition=partition)

    def _rename_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("RENAME")
        cur.expect_kw("COLUMNS")
        pairs = {}
        while True:
            old = self._ident(cur, "column name")
            cur.expect_op(":")
            new = self._ident(cur, "column name")
            pairs[old] = new
            if not cur.accept_op(","):
                break
        df = self._opt_from_df(cur)
        return df.rename(pairs)

    def _alter_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("ALTER")
        cur.expect_kw("COLUMNS")
        schema = self._schema_expr(cur)
        df = self._opt_from_df(cur)
        return df.alter_columns(schema)

    def _drop_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("DROP")
        if cur.accept_kw("COLUMNS"):
            cols = self._name_list(cur)
            if_exists = False
            if cur.accept_kw("IF"):
                cur.expect_kw("EXISTS")
                if_exists = True
            df = self._opt_from_df(cur)
            return df.drop(cols, if_exists=if_exists)
        cur.expect_kw("ROWS")
        cur.expect_kw("IF")
        if cur.accept_kw("ANY"):
            how = "any"
        else:
            cur.expect_kw("ALL")
            how = "all"
        if not cur.accept_kw("NULLS"):
            cur.expect_kw("NULL")
        subset = None
        if cur.accept_kw("ON"):
            subset = self._name_list(cur)
        df = self._opt_from_df(cur)
        return df.dropna(how=how, subset=subset)

    def _fillna_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("FILL")
        cur.expect_kw("NULLS")
        value = self._params(cur)
        df = self._opt_from_df(cur)
        return df.fillna(value)

    def _sample_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("SAMPLE")
        replace = cur.accept_kw("REPLACE")
        n = frac = None
        if cur.tok.kind != "NUMBER":
            raise FugueSQLSyntaxError("SAMPLE expects n ROWS or p PERCENT")
        num = cur.advance().value
        if cur.accept_kw("ROWS") or cur.accept_kw("ROW"):
            n = int(num)
        elif cur.accept_kw("PERCENT"):
            frac = float(num) / 100.0
        else:
            raise FugueSQLSyntaxError("SAMPLE expects ROWS or PERCENT")
        seed = None
        if cur.accept_kw("SEED"):
            if cur.tok.kind != "NUMBER":
                raise FugueSQLSyntaxError("SEED expects an integer")
            seed = int(cur.advance().value)
        df = self._opt_from_df(cur)
        return df.sample(n=n, frac=frac, replace=replace, seed=seed)

    def _take_stmt(self, cur: Cursor) -> Any:
        cur.expect_kw("TAKE")
        if cur.tok.kind != "NUMBER":
            raise FugueSQLSyntaxError("TAKE expects a row count")
        n = int(cur.advance().value)
        cur.accept_kw("ROWS") or cur.accept_kw("ROW")
        df = self._opt_from_df(cur)
        partition = self._opt_prepartition(cur)
        presort = ""
        if cur.accept_kw("PRESORT"):
            presort = self._presort_expr(cur)
        na_position = "last"
        if cur.accept_kw("NULLS") or cur.accept_kw("NULL"):
            if cur.accept_kw("FIRST"):
                na_position = "first"
            else:
                cur.expect_kw("LAST")
        if partition is not None:
            df = df.partition(partition)
        if presort:
            return df.take(n, presort=presort, na_position=na_position)
        return df.take(n, na_position=na_position)

    # ---- modifiers ------------------------------------------------------

    def _modifiers(self, cur: Cursor, tdf: Any, varname: Optional[str]) -> Any:
        while True:
            lazy = False
            if cur.is_kw("LAZY"):
                lazy = True
                cur.advance()
            if cur.accept_kw("PERSIST"):
                # LAZY PERSIST = lazy weak checkpoint
                t = self._req(tdf, "PERSIST")
                tdf = t.weak_checkpoint(lazy=True) if lazy else t.persist()
            elif cur.accept_kw("BROADCAST"):
                if lazy:
                    raise FugueSQLSyntaxError("LAZY cannot prefix BROADCAST")
                tdf = self._req(tdf, "BROADCAST").broadcast()
            elif cur.accept_kw("WEAK"):
                cur.expect_kw("CHECKPOINT")
                params = self._opt_paren_params(cur) or {}
                tdf = self._req(tdf, "WEAK CHECKPOINT").weak_checkpoint(
                    lazy=lazy, **params
                )
            elif cur.accept_kw("DETERMINISTIC"):
                cur.expect_kw("CHECKPOINT")
                ns = None
                if cur.tok.kind == "STRING":
                    ns = cur.advance().value
                partition = self._opt_prepartition(cur)
                single = cur.accept_kw("SINGLE")
                params = self._opt_paren_params(cur) or {}
                if partition is not None:
                    params["partition"] = partition
                if single:
                    params["single"] = True
                # lazy strong checkpoints surface NotImplementedError from
                # StrongCheckpoint rather than silently running eagerly
                tdf = self._req(tdf, "DETERMINISTIC CHECKPOINT") \
                    .deterministic_checkpoint(namespace=ns, lazy=lazy, **params)
            elif cur.is_kw("STRONG", "CHECKPOINT"):
                cur.accept_kw("STRONG")
                cur.expect_kw("CHECKPOINT")
                params = self._opt_paren_params(cur) or {}
                tdf = self._req(tdf, "CHECKPOINT").strong_checkpoint(
                    lazy=lazy, **params
                )
            elif cur.accept_kw("YIELD"):
                local = cur.accept_kw("LOCAL")
                target = "dataframe"
                if cur.accept_kw("DATAFRAME"):
                    target = "dataframe"
                elif cur.accept_kw("FILE"):
                    target = "file"
                elif cur.accept_kw("TABLE"):
                    target = "table"
                name = varname
                if cur.accept_kw("AS"):
                    name = self._ident(cur, "yield name")
                if name is None:
                    raise FugueSQLSyntaxError("yield name is not specified")
                t = self._req(tdf, "YIELD")
                if target == "dataframe":
                    t.yield_dataframe_as(name, as_local=local)
                elif target == "file":
                    t.yield_file_as(name)
                else:
                    t.yield_table_as(name)
            else:
                if lazy:
                    raise FugueSQLSyntaxError("LAZY must prefix a checkpoint")
                return tdf

    def _req(self, tdf: Any, what: str) -> Any:
        if tdf is None:
            raise FugueSQLSyntaxError(f"{what} requires a dataframe result")
        return tdf

    # ---- shared clause parsers ------------------------------------------

    def _find_df(self, name: str) -> Any:
        if name in self.variables:
            return self.variables[name]
        if name in self.sources:
            df = self.workflow.create_data(self.sources.pop(name))
            self.variables[name] = df
            return df
        if name in self.local_vars and self._is_dataframe_like(
            self.local_vars[name]
        ):
            df = self.workflow.create_data(self.local_vars[name])
            self.variables[name] = df
            return df
        return None

    @staticmethod
    def _is_dataframe_like(obj: Any) -> bool:
        from fugue_tpu.dataframe import DataFrame

        if isinstance(obj, DataFrame):
            return True
        mod = type(obj).__module__ or ""
        return mod.startswith("pandas") or mod.startswith("pyarrow")

    def _df_ref(self, cur: Cursor) -> Any:
        name = self._ident(cur, "dataframe name")
        df = self._find_df(name)
        if df is None:
            raise FugueSQLSyntaxError(f"{name} is not defined")
        return df

    def _opt_dfs(self, cur: Cursor) -> Any:
        """Optional dataframe list before PREPARTITION/USING."""
        if cur.tok.kind == "IDENT" and not cur.is_kw(
            "USING", "PREPARTITION", "HASH", "RAND", "EVEN", "COARSE",
        ):
            return self._dfs_clause(cur)
        return None

    def _dfs_clause(self, cur: Cursor) -> Any:
        """``a, b`` (list) or ``x: a, y: b`` (dict) of dataframe refs."""
        named: Dict[str, Any] = {}
        unnamed: List[Any] = []
        while True:
            if (
                cur.tok.kind == "IDENT"
                and cur.peek(1).kind == "OP"
                and cur.peek(1).value == ":"
            ):
                key = cur.advance().value
                cur.advance()
                named[key] = self._df_ref(cur)
            else:
                unnamed.append(self._df_ref(cur))
            if not cur.accept_op(","):
                break
        if named and unnamed:
            raise FugueSQLSyntaxError("cannot mix named and unnamed dfs")
        return named if named else unnamed

    def _opt_from_df(self, cur: Cursor) -> Any:
        if cur.accept_kw("FROM"):
            return self._df_ref(cur)
        if (
            cur.tok.kind == "IDENT"
            and not (cur.peek(1).kind == "OP" and cur.peek(1).value == "=")
            and self._find_df(cur.tok.value) is not None
        ):
            return self._df_ref(cur)
        return self._last_df()

    def _opt_prepartition(self, cur: Cursor) -> Optional[PartitionSpec]:
        algo = ""
        if cur.is_kw("HASH", "RAND", "EVEN", "COARSE") and \
                cur.peek(1).upper == "PREPARTITION":
            algo = cur.advance().value.lower()
        if not cur.accept_kw("PREPARTITION"):
            return None
        num = "0"
        if cur.tok.kind == "NUMBER":
            num = cur.advance().value
        elif cur.is_kw("ROWCOUNT", "CONCURRENCY"):
            # expression like ROWCOUNT/4
            parts = [cur.advance().value]
            while cur.is_op("/", "*", "+", "-") or cur.tok.kind == "NUMBER":
                parts.append(cur.advance().value)
            num = "".join(parts)
        by: List[str] = []
        if cur.accept_kw("BY"):
            by = self._name_list(cur)
        presort = ""
        if cur.accept_kw("PRESORT"):
            presort = self._presort_expr(cur)
        return PartitionSpec(algo=algo, num=num, by=by, presort=presort)

    def _presort_expr(self, cur: Cursor) -> str:
        parts = []
        while True:
            name = self._ident(cur, "presort column")
            direction = ""
            if cur.accept_kw("ASC"):
                direction = " asc"
            elif cur.accept_kw("DESC"):
                direction = " desc"
            parts.append(name + direction)
            if not cur.accept_op(","):
                break
        return ",".join(parts)

    def _name_list(self, cur: Cursor) -> List[str]:
        out = [self._ident(cur, "column name")]
        while cur.accept_op(","):
            out.append(self._ident(cur, "column name"))
        return out

    def _ident(self, cur: Cursor, what: str) -> str:
        t = cur.tok
        if t.kind not in ("IDENT", "QIDENT"):
            raise FugueSQLSyntaxError(f"expected {what}, got {t.value!r}")
        cur.advance()
        return t.value

    def _using_ref(self, cur: Cursor) -> Any:
        parts = [self._ident(cur, "extension name")]
        while cur.is_op(".") and cur.peek(1).kind == "IDENT":
            cur.advance()
            parts.append(cur.advance().value)
        name = ".".join(parts)
        if len(parts) == 1 and name in self.local_vars:
            return self.local_vars[name]
        if len(parts) > 1:
            head = parts[0]
            if head in self.local_vars:
                obj = self.local_vars[head]
                try:
                    for p in parts[1:]:
                        obj = getattr(obj, p)
                    return obj
                except AttributeError:
                    pass
            try:
                import importlib

                mod = importlib.import_module(".".join(parts[:-1]))
                return getattr(mod, parts[-1])
            except (ImportError, AttributeError):
                pass
        return name  # registered alias

    def _opt_params(self, cur: Cursor) -> Optional[Dict[str, Any]]:
        if cur.accept_kw("PARAMS"):
            return self._json_pairs(cur)
        return self._opt_paren_params(cur)

    def _opt_paren_params(self, cur: Cursor) -> Optional[Dict[str, Any]]:
        if cur.is_op("(") or cur.is_op("{"):
            return self._params(cur)
        return None

    def _params(self, cur: Cursor) -> Dict[str, Any]:
        if cur.accept_op("("):
            out = self._json_pairs(cur)
            cur.expect_op(")")
            return out
        if cur.is_op("{"):
            v = self._json_value(cur)
            if not isinstance(v, dict):
                raise FugueSQLSyntaxError("expected a params object")
            return v
        if cur.accept_kw("PARAMS"):
            return self._json_pairs(cur)
        return self._json_pairs(cur)

    def _opt_schema(self, cur: Cursor) -> Optional[str]:
        if cur.accept_kw("SCHEMA"):
            return self._schema_expr(cur)
        return None

    def _schema_expr(self, cur: Cursor) -> str:
        """Consume schema tokens (``a:int,b:[str]`` or ``*,c:int``) until a
        statement/modifier boundary."""
        parts: List[str] = []
        while True:
            t = cur.tok
            if t.kind == "END":
                break
            if t.kind == "OP" and t.value in _SCHEMA_OPS:
                # a comma only continues the schema if a pair follows
                if t.value == ",":
                    nxt = cur.peek(1)
                    if nxt.kind != "IDENT" and nxt.kind != "QIDENT" and \
                            not (nxt.kind == "OP" and nxt.value in
                                 ("*", "-", "+", "~")):
                        break
                parts.append(t.value)
                cur.advance()
                continue
            if t.kind in ("IDENT", "QIDENT"):
                if t.upper in _STATEMENT_KEYWORDS or \
                        t.upper in _MODIFIER_KEYWORDS or \
                        t.upper in ("USING", "FROM", "CALLBACK", "PARAMS"):
                    break
                # assignment lookahead: `name = ...`
                if cur.peek(1).kind == "OP" and cur.peek(1).value == "=":
                    break
                parts.append(t.value)
                cur.advance()
                continue
            if t.kind == "NUMBER":
                parts.append(t.value)
                cur.advance()
                continue
            break
        if len(parts) == 0:
            raise FugueSQLSyntaxError("expected a schema expression")
        return "".join(parts)

    def _schema_or_cols(self, cur: Cursor) -> Any:
        """COLUMNS a,b (names) or a:int,b:str (schema string)."""
        start = cur.i
        names = []
        is_schema = False
        while True:
            t = cur.tok
            if t.kind not in ("IDENT", "QIDENT"):
                break
            names.append(t.value)
            cur.advance()
            if cur.is_op(":"):
                is_schema = True
                break
            if not cur.accept_op(","):
                break
        if is_schema:
            cur.i = start
            return self._schema_expr(cur)
        return names

    def _path(self, cur: Cursor) -> str:
        # single- or double-quoted paths are both accepted
        if cur.tok.kind not in ("STRING", "QIDENT"):
            raise FugueSQLSyntaxError("expected a quoted path")
        return cur.advance().value

    # ---- fugue-json -----------------------------------------------------

    def _json_pairs(self, cur: Cursor) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        while True:
            t = cur.tok
            if t.kind not in ("IDENT", "QIDENT", "STRING"):
                break
            key = cur.advance().value
            if not cur.accept_op(":"):
                cur.expect_op("=")
            out[key] = self._json_value(cur)
            if not cur.accept_op(","):
                break
        return out

    def _json_value(self, cur: Cursor) -> Any:
        t = cur.tok
        if t.kind == "NUMBER":
            cur.advance()
            return float(t.value) if "." in t.value or \
                "e" in t.value.lower() else int(t.value)
        if t.kind in ("STRING", "QIDENT"):  # double quotes = string here
            cur.advance()
            return t.value
        if t.kind == "IDENT":
            u = t.upper
            if u == "TRUE":
                cur.advance()
                return True
            if u == "FALSE":
                cur.advance()
                return False
            if u in ("NULL", "NONE"):
                cur.advance()
                return None
            cur.advance()
            return t.value  # bare word = string
        if cur.accept_op("-") :
            v = self._json_value(cur)
            return -v
        if cur.accept_op("["):
            items = []
            if not cur.accept_op("]"):
                while True:
                    items.append(self._json_value(cur))
                    if not cur.accept_op(","):
                        break
                cur.expect_op("]")
            return items
        if cur.accept_op("{"):
            obj: Dict[str, Any] = {}
            if not cur.accept_op("}"):
                obj = self._json_pairs(cur)
                cur.expect_op("}")
            return obj
        raise FugueSQLSyntaxError(f"expected a value, got {t.value!r}")
