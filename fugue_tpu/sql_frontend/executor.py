"""SQL SELECT executor over DataFrames (qpd/duckdb replacement).

Wired up by fugue_tpu.sql_frontend.parser; this placeholder raises until the
parser module lands (SURVEY §7 step 9)."""

from typing import Any

from fugue_tpu.dataframe import DataFrame, DataFrames


def run_sql_on_dataframes(sql: str, dfs: DataFrames) -> DataFrame:
    from fugue_tpu.sql_frontend.select_runner import run_select

    return run_select(sql, dfs)
