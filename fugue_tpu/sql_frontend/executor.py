"""SQL SELECT executor over DataFrames — the qpd/duckdb role for the native
engine (reference fugue/execution/native_execution_engine.py:41-65)."""

from fugue_tpu.dataframe import DataFrame, DataFrames
from fugue_tpu.sql_frontend.select_runner import run_select


def run_sql_on_dataframes(sql: str, dfs: DataFrames) -> DataFrame:
    return run_select(sql, dfs)
