"""Serialize the SQL AST back to text with dataframe-reference parts —
produces the ``StructuredRawSQL`` fragments that :class:`FugueSQLWorkflow`
feeds to ``dag.select`` (the role of ``_beautify_sql`` + placeholder
re-encoding in reference fugue/sql/_visitors.py:640-686)."""

from typing import Any, Callable, List, Optional, Set, Tuple

from fugue_tpu.sql_frontend import ast

__all__ = ["generate_parts"]


def generate_parts(
    q: ast.Query,
    resolve_df: Callable[[str], Optional[str]],
) -> List[Tuple[bool, str]]:
    """Render ``q`` as ``(is_dataframe, text)`` parts. Table names are passed
    through ``resolve_df``: a non-None return marks the name as a dataframe
    reference part; None keeps it as plain SQL text (e.g. a CTE name)."""
    gen = _Gen(resolve_df)
    gen.query(q, set())
    return gen.parts


class _Gen:
    def __init__(self, resolve_df: Callable[[str], Optional[str]]):
        self.parts: List[Tuple[bool, str]] = []
        self.resolve_df = resolve_df

    def emit(self, text: str) -> None:
        if self.parts and not self.parts[-1][0]:
            self.parts[-1] = (False, self.parts[-1][1] + text)
        else:
            self.parts.append((False, text))

    def emit_df(self, key: str) -> None:
        self.parts.append((True, key))

    # ---- queries --------------------------------------------------------

    def query(self, q: ast.Query, ctes: Set[str]) -> None:
        if isinstance(q, ast.With):
            scoped = set(ctes)
            self.emit("WITH ")
            for i, (name, sub) in enumerate(q.ctes):
                if i > 0:
                    self.emit(", ")
                self.emit(f"{name} AS (")
                self.query(sub, scoped)
                self.emit(")")
                scoped.add(name.lower())
            self.emit(" ")
            self.query(q.body, scoped)
            return
        if isinstance(q, ast.SetOp):
            self.query(q.left, ctes)
            self.emit(f" {q.op}{' ALL' if q.all else ''} ")
            self.query(q.right, ctes)
            self._order_limit(q.order_by, q.limit, q.offset, ctes)
            return
        assert isinstance(q, ast.Select)
        self.emit("SELECT ")
        if q.distinct:
            self.emit("DISTINCT ")
        for i, item in enumerate(q.items):
            if i > 0:
                self.emit(", ")
            if isinstance(item.expr, ast.Star):
                self.emit(
                    "*" if item.expr.table is None else f"{item.expr.table}.*"
                )
            else:
                self.expr(item.expr, ctes)
                if item.alias is not None:
                    self.emit(f' AS "{item.alias}"')
        if q.from_ is not None:
            self.emit(" FROM ")
            self.relation(q.from_, ctes)
        if q.where is not None:
            self.emit(" WHERE ")
            self.expr(q.where, ctes)
        if q.group_by:
            self.emit(" GROUP BY ")
            for i, g in enumerate(q.group_by):
                if i > 0:
                    self.emit(", ")
                self.expr(g, ctes)
        if q.having is not None:
            self.emit(" HAVING ")
            self.expr(q.having, ctes)
        self._order_limit(q.order_by, q.limit, q.offset, ctes)

    def _order_limit(
        self,
        order_by: List[ast.OrderItem],
        limit: Optional[int],
        offset: Optional[int],
        ctes: Set[str],
    ) -> None:
        if order_by:
            self.emit(" ORDER BY ")
            for i, o in enumerate(order_by):
                if i > 0:
                    self.emit(", ")
                self.expr(o.expr, ctes)
                if not o.asc:
                    self.emit(" DESC")
                if o.nulls is not None:
                    self.emit(f" NULLS {o.nulls}")
        if limit is not None:
            self.emit(f" LIMIT {limit}")
        if offset is not None:
            self.emit(f" OFFSET {offset}")

    # ---- relations ------------------------------------------------------

    def relation(self, rel: ast.Relation, ctes: Set[str]) -> None:
        if isinstance(rel, ast.TableRef):
            key = None if rel.name.lower() in ctes else \
                self.resolve_df(rel.name)
            if key is None:
                self.emit(rel.name)
            else:
                self.emit_df(key)
            alias = rel.alias or rel.name
            self.emit(f' AS "{alias}"')
            return
        if isinstance(rel, ast.SubqueryRef):
            self.emit("(")
            self.query(rel.query, ctes)
            self.emit(f') AS "{rel.alias}"')
            return
        assert isinstance(rel, ast.JoinRel)
        self.relation(rel.left, ctes)
        kw = {
            "inner": "INNER JOIN", "cross": "CROSS JOIN",
            "left_outer": "LEFT OUTER JOIN", "right_outer": "RIGHT OUTER JOIN",
            "full_outer": "FULL OUTER JOIN", "semi": "LEFT SEMI JOIN",
            "anti": "LEFT ANTI JOIN",
        }[rel.how]
        self.emit(f" {kw} ")
        self.relation(rel.right, ctes)
        if rel.on is not None:
            self.emit(" ON ")
            self.expr(rel.on, ctes)
        elif rel.using is not None:
            self.emit(" USING (" + ", ".join(rel.using) + ")")

    # ---- expressions ----------------------------------------------------

    def expr(self, e: ast.Expr, ctes: Set[str]) -> None:
        if isinstance(e, ast.Lit):
            v = e.value
            if v is None:
                self.emit("NULL")
            elif isinstance(v, bool):
                self.emit("TRUE" if v else "FALSE")
            elif isinstance(v, str):
                self.emit("'" + v.replace("'", "''") + "'")
            else:
                self.emit(repr(v))
            return
        if isinstance(e, ast.Col):
            name = f'"{e.name}"' if not e.name.isidentifier() else e.name
            self.emit(name if e.table is None else f"{e.table}.{name}")
            return
        if isinstance(e, ast.Star):
            self.emit("*" if e.table is None else f"{e.table}.*")
            return
        if isinstance(e, ast.Unary):
            if e.op == "NOT":
                self.emit("NOT (")
                self.expr(e.operand, ctes)
                self.emit(")")
            else:
                self.emit(f"{e.op}(")
                self.expr(e.operand, ctes)
                self.emit(")")
            return
        if isinstance(e, ast.Binary):
            self.emit("(")
            self.expr(e.left, ctes)
            self.emit(f" {e.op} ")
            self.expr(e.right, ctes)
            self.emit(")")
            return
        if isinstance(e, ast.Func):
            self.emit(e.name.upper() + "(")
            if e.distinct:
                self.emit("DISTINCT ")
            for i, a in enumerate(e.args):
                if i > 0:
                    self.emit(", ")
                self.expr(a, ctes)
            self.emit(")")
            return
        if isinstance(e, ast.Case):
            self.emit("CASE")
            if e.operand is not None:
                self.emit(" ")
                self.expr(e.operand, ctes)
            for cond, val in e.whens:
                self.emit(" WHEN ")
                self.expr(cond, ctes)
                self.emit(" THEN ")
                self.expr(val, ctes)
            if e.default is not None:
                self.emit(" ELSE ")
                self.expr(e.default, ctes)
            self.emit(" END")
            return
        if isinstance(e, ast.Cast):
            self.emit("CAST(")
            self.expr(e.operand, ctes)
            self.emit(f" AS {e.type_name})")
            return
        if isinstance(e, ast.InList):
            self.emit("(")
            self.expr(e.operand, ctes)
            self.emit(" NOT IN (" if e.negated else " IN (")
            for i, item in enumerate(e.items):
                if i > 0:
                    self.emit(", ")
                self.expr(item, ctes)
            self.emit("))")
            return
        if isinstance(e, ast.Between):
            self.emit("(")
            self.expr(e.operand, ctes)
            self.emit(" NOT BETWEEN " if e.negated else " BETWEEN ")
            self.expr(e.low, ctes)
            self.emit(" AND ")
            self.expr(e.high, ctes)
            self.emit(")")
            return
        if isinstance(e, ast.Like):
            self.emit("(")
            self.expr(e.operand, ctes)
            self.emit(" NOT LIKE " if e.negated else " LIKE ")
            self.expr(e.pattern, ctes)
            self.emit(")")
            return
        if isinstance(e, ast.IsNull):
            self.emit("(")
            self.expr(e.operand, ctes)
            self.emit(" IS NOT NULL)" if e.negated else " IS NULL)")
            return
        if isinstance(e, ast.Window):
            self.expr(e.func, ctes)
            self.emit(" OVER (")
            if e.partition_by:
                self.emit("PARTITION BY ")
                for i, p in enumerate(e.partition_by):
                    if i > 0:
                        self.emit(", ")
                    self.expr(p, ctes)
            if e.order_by:
                if e.partition_by:
                    self.emit(" ")
                self.emit("ORDER BY ")
                for i, o in enumerate(e.order_by):
                    if i > 0:
                        self.emit(", ")
                    self.expr(o.expr, ctes)
                    if not o.asc:
                        self.emit(" DESC")
                    if o.nulls is not None:
                        self.emit(f" NULLS {o.nulls}")
            if e.frame is not None:
                if e.partition_by or e.order_by:
                    self.emit(" ")
                fb = {
                    "up": "UNBOUNDED PRECEDING",
                    "uf": "UNBOUNDED FOLLOWING",
                    "c": "CURRENT ROW",
                }

                def _bound(b: Any) -> str:
                    kind, nv = b
                    if kind in fb:
                        return fb[kind]
                    word = "PRECEDING" if kind == "p" else "FOLLOWING"
                    return f"{nv} {word}"

                self.emit(
                    f"{e.frame.unit.upper()} BETWEEN "
                    f"{_bound(e.frame.start)} AND {_bound(e.frame.end)}"
                )
            self.emit(")")
            return
        if isinstance(e, ast.ScalarSubquery):
            self.emit("(")
            self.query(e.query, ctes)
            self.emit(")")
            return
        if isinstance(e, ast.InSubquery):
            self.emit("(")
            self.expr(e.operand, ctes)
            self.emit(" NOT IN (" if e.negated else " IN (")
            self.query(e.query, ctes)
            self.emit("))")
            return
        if isinstance(e, ast.Exists):
            self.emit("EXISTS (")
            self.query(e.query, ctes)
            self.emit(")")
            return
        raise ValueError(f"cannot serialize {type(e).__name__}")
