"""The functional API: ``import fugue_tpu.api as fa`` (reference
fugue/api.py:1-71 — one flat namespace over the whole framework)."""

# dataframe/dataset functional ops
from fugue_tpu.dataset.api import (
    as_fugue_dataset,
    count,
    is_bounded,
    is_empty,
    is_local,
    show,
)
from fugue_tpu.dataframe.api import (
    alter_columns,
    as_array,
    as_array_iterable,
    as_arrow,
    as_dict_iterable,
    as_pandas,
    drop_columns,
    get_column_names,
    get_native_as_df,
    get_schema,
    head,
    is_df,
    normalize_dataframes,
    peek_array,
    peek_dict,
    rename,
    select_columns,
)
from fugue_tpu.dataframe.dataframe import as_fugue_df

# engine management + eager ops
from fugue_tpu.execution.api import (
    aggregate,
    anti_join,
    assign,
    broadcast,
    clear_global_engine,
    cross_join,
    distinct,
    dropna,
    engine_context,
    fillna,
    filter,  # noqa: A004
    full_outer_join,
    get_context_engine,
    get_current_conf,
    get_current_parallelism,
    inner_join,
    intersect,
    join,
    left_outer_join,
    load,
    persist,
    repartition,
    right_outer_join,
    sample,
    save,
    select,
    semi_join,
    set_global_engine,
    subtract,
    take,
    union,
)

# workflow-level entry points
from fugue_tpu.workflow.api import explain, out_transform, raw_sql, transform

# sql entry points
from fugue_tpu.sql_frontend.api import (
    explain_sql,
    fugue_sql,
    fugue_sql_flow,
    lint_sql,
)

# column algebra re-exports (fa.col, fa.lit usable in select/filter)
from fugue_tpu.column import all_cols, col, lit, null
