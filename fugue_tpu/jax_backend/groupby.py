"""Device group-by: key factorization + segment reductions.

The TPU lowering of SQL GROUP BY (BASELINE: "group-by aggregates lower to
segment_sum/segment_max scans on device"). Two factorization strategies:

- **Static binning (the hot path, zero host syncs):** when every key is
  integer-like with host-known bounds (column ``stats`` captured at ingest
  and propagated through the pipeline), segment ids are a mixed-radix
  combination of ``key - min`` — one fused O(n) pass, no sort, and the
  segment COUNT is the static bin count, so downstream segment ops and
  output shapes need no device readback. Empty bins are dropped lazily via
  an occupancy mask (the frame's ``row_valid``).

- **Sort-based (general fallback):** lexicographic factorization via
  stable sorts for float/wide/unbounded keys. Costs two host syncs (group
  count) — acceptable off the hot path.

Everything computes in int32: int64 is EMULATED on TPU (~10x slower), and
row positions/bin codes fit int32 by construction.
"""

from functools import partial
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from fugue_tpu.column.functions import VARIANCE_FUNCS
from fugue_tpu.jax_backend.blocks import JaxBlocks, JaxColumn
from fugue_tpu.utils.assertion import assert_or_throw


def row_validity(blocks: JaxBlocks) -> jnp.ndarray:
    """True for real rows, False for mesh padding / filtered-out rows."""
    return blocks.validity()


def materialize_validity(
    row_valid: Optional[jnp.ndarray], pad_n: int, nrows_s: Any
) -> jnp.ndarray:
    """Traced helper: the one validity convention, shared by every device
    program — a masked frame passes its mask; a prefix frame materializes
    ``arange < nrows`` in-program (int32: int64 is emulated on TPU)."""
    if row_valid is not None:
        return row_valid
    return jnp.arange(pad_n, dtype=jnp.int32) < nrows_s


class BinSpec(NamedTuple):
    """Static description of a mixed-radix key binning: everything needed
    to compute segment ids INSIDE a traced program (no separate factorize
    dispatch) and to DECODE key values arithmetically from bin indices
    (no representative-row gather, no segment_min scatter)."""

    names: Tuple[str, ...]
    mins: Tuple[int, ...]
    spans: Tuple[int, ...]  # includes the +1 null bucket where masked
    masked: Tuple[bool, ...]
    total: int


def bin_spec(blocks: JaxBlocks, keys: List[str]) -> Optional[BinSpec]:
    """BinSpec for `keys` when all are integer-like with host-known bounds
    (stats from ingest / propagation, else ONE device min/max readback,
    cached on the column); None for float/unbounded keys."""
    missing: List[str] = []
    for k in keys:
        col = blocks.columns.get(k)
        if col is None or not col.on_device:
            return None
        if jnp.issubdtype(col.data.dtype, jnp.floating):
            return None
        if col.stats is None:
            missing.append(k)
    if missing:
        _fill_stats_from_device(blocks, missing)
    spans: List[int] = []
    mins: List[int] = []
    masked: List[bool] = []
    for k in keys:
        col = blocks.columns[k]
        lo, hi = col.stats  # type: ignore[misc]
        span = int(hi) - int(lo) + 1
        if span <= 0 or span > _MAX_BINS:
            return None
        has_mask = col.mask is not None
        if has_mask:
            span += 1  # null bucket
        spans.append(span)
        mins.append(int(lo))
        masked.append(has_mask)
    total = 1
    for r in spans:
        total *= r
        if total > _MAX_BINS:
            return None
    return BinSpec(tuple(keys), tuple(mins), tuple(spans), tuple(masked), total)


@jax.jit
def _minmax_prog(datas: Tuple[jnp.ndarray, ...]) -> Tuple[jnp.ndarray, ...]:
    return tuple(
        jnp.stack([jnp.min(d), jnp.max(d)]).astype(jnp.int64) for d in datas
    )


def _fill_stats_from_device(blocks: JaxBlocks, names: List[str]) -> None:
    """Backfill missing int-key stats with one jitted min/max program and a
    single batched readback, cached on the columns (a one-sync fallback so
    computed keys — e.g. from assign() — still reach the binned fast path
    instead of the ~10x sort factorization)."""
    datas = tuple(blocks.columns[k].data for k in names)
    bounds = jax.device_get(_minmax_prog(datas))
    for k, b in zip(names, bounds):
        blocks.columns[k].stats = (int(b[0]), int(b[1]))


def inline_seg(
    spec: BinSpec,
    key_data: Dict[str, jnp.ndarray],
    key_masks: Dict[str, Optional[jnp.ndarray]],
    valid_rows: jnp.ndarray,
) -> jnp.ndarray:
    """Traced helper: mixed-radix segment ids per row; invalid rows get the
    out-of-range sentinel ``spec.total`` (dropped by one-hot and segment
    ops alike)."""
    n = valid_rows.shape[0]
    combined = jnp.zeros((n,), dtype=jnp.int32)
    for name, kmin, span, has_mask in zip(
        spec.names, spec.mins, spec.spans, spec.masked
    ):
        code = (key_data[name] - kmin).astype(jnp.int32)
        if has_mask:
            code = jnp.where(key_masks[name], code, span - 1)
        combined = combined * jnp.int32(span) + code
    return jnp.where(valid_rows, combined, jnp.int32(spec.total))


def decode_bin_keys(
    spec: BinSpec, dtypes: Dict[str, Any]
) -> Dict[str, Tuple[jnp.ndarray, Optional[jnp.ndarray]]]:
    """Traced helper: key (values, mask) per bin index — pure arithmetic
    over ``arange(total)``, replacing the representative-row gather."""
    b = jnp.arange(spec.total, dtype=jnp.int32)
    out: Dict[str, Tuple[jnp.ndarray, Optional[jnp.ndarray]]] = {}
    stride = spec.total
    for name, kmin, span, has_mask in zip(
        spec.names, spec.mins, spec.spans, spec.masked
    ):
        stride //= span
        code = (b // stride) % span
        if has_mask:
            mask = code != span - 1
            value = jnp.where(mask, code, 0) + kmin
        else:
            mask = None
            value = code + kmin
        out[name] = (value.astype(dtypes[name]), mask)
    return out


# ---------------------------------------------------------------------------
# segment-reduction STRATEGY KERNELS
#
# All sum-type reductions (sum/avg/count payloads for every aggregated
# column) are packed into one multi-row operand so the per-row segment
# work — one-hot materialization, scatter index handling, or the sort —
# is amortized across every output. Four interchangeable strategies
# compute the identical contract; the engine picks one per (rows,
# num_segments, n_payload, placement tier) via a measured table + a
# one-shot on-device autotune (see segtune.py):
#
# - "matmul": chunked one-hot matmul over the MXU. The fastest measured
#   on TPU for small segment counts. Benchmarked at 100M rows x 1024
#   segments, f32, honest device_get endpoint (r3):
#     one-hot matmul (this design)            ~204ms  (~490M rows/s)
#     hierarchical (OH_hi*v)^T @ OH_lo split  ~490ms  (2.4x worse: two
#         one-hots materialize; XLA fuses the flat pattern better)
#     sort + segment_sum                      ~3.7s   (18x worse)
#     jax.ops.segment_sum (scatter)           ~10.0s  (50x worse: scatter
#         serializes on TPU; the MXU does not)
#   Chunk-size sweeps (2^16..2^20) move the time <15%, so the cost is
#   the inherent n*num_segments one-hot work, not scan-step overhead — a
#   pallas kernel was evaluated and offers no algorithmic advantage here
#   (VPU compare-accumulate is the same n*S work at lower throughput).
# - "matmul_bf16": the same one-hot matmul with the one-hot in bf16 and
#   each f32 payload split into hi+lo bf16 halves (two exact-0/1-weighted
#   products, f32 MXU accumulation) — halves the one-hot transient
#   traffic and rides the MXU's native bf16 rate at ~16 effective
#   mantissa bits. Only eligible when every float payload is f32.
# - "scatter": ONE packed (rows, n_payload) jax.ops.segment_sum. On CPU
#   meshes (the host placement tier) the one-hot transient is pure
#   memory-bandwidth waste while scatter-adds are cheap — measured
#   10M rows x 256 segments = 1.28s matmul vs 0.048s scatter — so the
#   table routes CPU meshes here. Exact integer accumulation (the matmul
#   family would lose low bits in its float accumulator).
# - "sort": argsort by segment id, then the packed scatter with
#   ``indices_are_sorted=True`` — XLA lowers sorted scatters to a far
#   cheaper kernel, trading the n*S one-hot work for an n*log(n) sort.
#   The crossover candidate for LARGE segment counts where the one-hot
#   work dominates.
# ---------------------------------------------------------------------------

STRATEGIES = ("matmul", "matmul_bf16", "scatter", "sort")

_MATMUL_MAX_SEGMENTS = 8192
# scatter/sort have no one-hot transient: the packed path stays viable up
# to the bin cap itself (output is (num_segments, n_payload))
_PACKED_MAX_SEGMENTS = 1 << 20
_MATMUL_CHUNK = 1 << 17
# cap on chunk*num_segments: the (chunk, num_segments) one-hot is the
# scan-step transient; 2^26 elements = 256MB f32 (1/2 that in bf16), safe
# on 16GB parts even if XLA fails to fuse it into the matmul (advisor r2)
_MATMUL_ONEHOT_BUDGET = 1 << 26


def segment_sums(
    float_payloads: List[jnp.ndarray],
    count_payloads: List[jnp.ndarray],
    seg: jnp.ndarray,
    num_segments: int,
    strategy: str = "matmul",
    int_payloads: Optional[List[jnp.ndarray]] = None,
) -> Tuple[List[jnp.ndarray], List[jnp.ndarray], List[jnp.ndarray]]:
    """Traced helper: every sum-type reduction through ONE strategy kernel.

    ``float_payloads`` accumulate in the widest float dtype present;
    ``count_payloads`` (bool/0-1 valued) accumulate exactly in int32;
    ``int_payloads`` accumulate exactly in int64 (scatter/sort only — the
    matmul family's float accumulator would drop low bits, callers gate).
    ``seg`` values >= num_segments contribute nothing on every strategy.
    Returns (float_sums, count_sums, int_sums) as per-payload lists."""
    ints = int_payloads or []
    assert_or_throw(
        strategy in STRATEGIES,
        ValueError(f"unknown segment-reduction strategy {strategy!r}"),
    )
    if strategy in ("matmul", "matmul_bf16"):
        assert_or_throw(
            len(ints) == 0,
            ValueError("matmul strategies cannot sum integer payloads"),
        )
        f, c = matmul_segment_sums(
            float_payloads,
            count_payloads,
            seg,
            num_segments,
            bf16=strategy == "matmul_bf16",
        )
        return f, c, []
    return _packed_scatter_sums(
        float_payloads, count_payloads, ints, seg, num_segments,
        presort=strategy == "sort",
    )


def segment_count(
    vec: jnp.ndarray,
    seg: jnp.ndarray,
    num_segments: int,
    strategy: str = "scatter",
) -> jnp.ndarray:
    """Traced helper: ONE 0/1-valued int32 count reduction routed through
    the strategy layer — the join-side/window count shape. ``vec`` must be
    bool or 0/1 (matmul accumulates chunk partials in f32; 0/1 sums below
    the chunk size are exact)."""
    if strategy != "scatter" and num_segments > 0:
        _, c, _ = segment_sums([], [vec], seg, num_segments, strategy)
        return c[0]
    return jax.ops.segment_sum(
        vec.astype(jnp.int32), seg, num_segments=num_segments
    )


def _float_acc_dtype(float_payloads: List[jnp.ndarray]) -> Any:
    """The accumulation dtype the strategy kernels share: the widest float
    dtype present (f64 stays f64 for CPU fidelity; pure-f32 TPU pipelines
    ride the fast path), f32 when there are no float payloads."""
    acc_dtype = (
        jnp.result_type(*[p.dtype for p in float_payloads])
        if len(float_payloads) > 0
        else jnp.float32
    )
    if not jnp.issubdtype(acc_dtype, jnp.floating):
        acc_dtype = jnp.float32
    return acc_dtype


def _packed_scatter_sums(
    float_payloads: List[jnp.ndarray],
    count_payloads: List[jnp.ndarray],
    int_payloads: List[jnp.ndarray],
    seg: jnp.ndarray,
    num_segments: int,
    presort: bool,
) -> Tuple[List[jnp.ndarray], List[jnp.ndarray], List[jnp.ndarray]]:
    """The scatter/sort strategies: same-kind payloads packed into one
    (rows, n_payload) operand per accumulator dtype, ONE segment_sum per
    pack (index handling amortized across every output). ``presort``
    reorders rows by segment id first so XLA lowers the scatter with
    ``indices_are_sorted=True``."""
    acc_dtype = _float_acc_dtype(float_payloads)
    if presort:
        order = jnp.argsort(seg).astype(jnp.int32)
        seg = seg[order]

        def _g(p: jnp.ndarray) -> jnp.ndarray:
            return p[order]
    else:

        def _g(p: jnp.ndarray) -> jnp.ndarray:
            return p

    def _reduce(payloads: List[jnp.ndarray], dtype: Any) -> List[jnp.ndarray]:
        if not payloads:
            return []
        pack = jnp.stack([_g(p.astype(dtype)) for p in payloads], axis=1)
        sums = jax.ops.segment_sum(
            pack, seg, num_segments=num_segments, indices_are_sorted=presort
        )
        return [sums[:, i] for i in range(len(payloads))]

    return (
        _reduce(float_payloads, acc_dtype),
        _reduce(count_payloads, jnp.int32),
        _reduce(int_payloads, jnp.int64),
    )


def matmul_segment_sums(
    float_payloads: List[jnp.ndarray],
    count_payloads: List[jnp.ndarray],
    seg: jnp.ndarray,
    num_segments: int,
    bf16: bool = False,
) -> Tuple[List[jnp.ndarray], List[jnp.ndarray]]:
    """Traced helper: all sum-type reductions in ONE chunked one-hot matmul
    over the MXU. ``float_payloads`` accumulate in f32/f64; ``count_payloads``
    (bool/0-1 valued) accumulate exactly in int32 (f32 partials per chunk
    are exact below the chunk size). ``seg`` values >= num_segments
    contribute nothing (their one-hot row is all zeros).

    ``bf16``: one-hot and payloads in bf16 with f32 MXU accumulation; each
    f32 payload is split hi+lo so ~16 mantissa bits survive. Callers must
    guarantee every float payload is f32 (gated by the strategy selector)."""
    n = seg.shape[0]
    ch = min(
        _MATMUL_CHUNK,
        max(256, _MATMUL_ONEHOT_BUDGET // max(1, num_segments)),
        n,
    )
    pad = (-n) % ch
    acc_dtype = _float_acc_dtype(float_payloads)
    nf = len(float_payloads)
    if bf16:
        # split each f32 payload into exact-sum bf16 halves: hi = bf16(v),
        # lo = bf16(v - hi); one-hot weights (0/1) are bf16-exact, so the
        # two f32-accumulated products recover ~16 mantissa bits
        op_dtype: Any = jnp.bfloat16
        acc_dtype = jnp.float32
        his = [p.astype(jnp.bfloat16) for p in float_payloads]
        los = [
            (p.astype(jnp.float32) - h.astype(jnp.float32)).astype(
                jnp.bfloat16
            )
            for p, h in zip(float_payloads, his)
        ]
        payloads = his + los + [p.astype(jnp.bfloat16) for p in count_payloads]
    else:
        op_dtype = acc_dtype
        payloads = [p.astype(acc_dtype) for p in float_payloads] + [
            p.astype(acc_dtype) for p in count_payloads
        ]
    if pad:
        seg = jnp.concatenate(
            [seg, jnp.full((pad,), num_segments, dtype=seg.dtype)]
        )
        payloads = [
            jnp.concatenate([p, jnp.zeros((pad,), dtype=p.dtype)])
            for p in payloads
        ]
    a = len(payloads)
    nsplit = 2 * nf if bf16 else nf
    kc = seg.reshape(-1, ch)
    pc = jnp.stack(payloads, axis=0).reshape(a, -1, ch)
    iota = jnp.arange(num_segments, dtype=seg.dtype)

    def body(carry: Tuple[Any, Any], kv: Any) -> Tuple[Tuple[Any, Any], None]:
        f_acc, c_acc = carry
        kk, vv = kv
        oh = (kk[:, None] == iota[None, :]).astype(op_dtype)
        part = jax.lax.dot_general(
            vv, oh, (((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
        )  # (a, num_segments), accumulated in acc_dtype
        if bf16:
            f_acc = f_acc + part[:nf] + part[nf:nsplit]
        else:
            f_acc = f_acc + part[:nf]
        c_acc = c_acc + part[nsplit:].astype(jnp.int32)
        return (f_acc, c_acc), None

    init = (
        jnp.zeros((nf, num_segments), acc_dtype),
        jnp.zeros((a - nsplit, num_segments), jnp.int32),
    )
    (f_acc, c_acc), _ = jax.lax.scan(
        body, init, (kc, jnp.moveaxis(pc, 0, 1))
    )
    return list(f_acc), list(c_acc)


class Factorized(NamedTuple):
    """Result of key factorization over a frame's padded rows.

    - ``seg``: int32 segment id per padded row; invalid rows carry the
      out-of-range sentinel ``num_segments`` (dropped by segment ops).
    - ``num_segments``: STATIC segment-id space size (bin count on the
      binned path; exact group count on the sort path). Some segments may
      be empty on the binned path.
    - ``first_idx``: representative (first valid) row index per segment,
      shape (num_segments,); garbage where a segment is empty.
    - ``occupied``: bool (num_segments,) marking non-empty segments, or
      None when every segment is occupied (sort path).
    - ``num_groups_dev``: device int32 scalar = true group count (lazy).
    """

    seg: jnp.ndarray
    num_segments: int
    first_idx: jnp.ndarray
    occupied: Optional[jnp.ndarray]
    num_groups_dev: Any


def factorize_keys(blocks: JaxBlocks, keys: List[str]) -> Factorized:
    """Factorize `keys` into segment ids. Null keys form their own groups
    (SQL GROUP BY semantics). Results are cached per frame (repeated ops
    on the same keys — transform then aggregate — pay once)."""
    cache_key = tuple(keys)
    if cache_key in blocks.factorize_cache:
        return blocks.factorize_cache[cache_key]
    res = _try_bin_factorize(blocks, keys)
    if res is None:
        res = _sort_factorize(blocks, keys)
    blocks.factorize_cache[cache_key] = res
    return res


_MAX_BINS = 1 << 22  # static-binning cap (16MB of int32 per scratch array)


def _try_bin_factorize(
    blocks: JaxBlocks, keys: List[str]
) -> Optional[Factorized]:
    """Sort-free, sync-free factorization for integer-like keys with
    host-known bounds."""
    spec = bin_spec(blocks, keys)
    if spec is None:
        return None
    seg, first_idx, occupied, num_dev = _bin_core(
        tuple(blocks.columns[k].data for k in keys),
        tuple(blocks.columns[k].mask for k in keys),
        blocks.row_valid,
        _nrows_scalar_arg(blocks),
        spec,
    )
    return Factorized(seg, spec.total, first_idx, occupied, num_dev)


def _nrows_scalar_arg(blocks: JaxBlocks) -> Any:
    """Known row count as a traced-arg scalar (np, so no eager dispatch);
    -1 when the frame is mask-layout (programs then use row_valid)."""
    if blocks._nrows is not None:
        return np.int32(blocks._nrows)
    return np.int32(-1)


@partial(jax.jit, static_argnames=("spec",))
def _bin_core(
    datas: Tuple[jnp.ndarray, ...],
    masks: Tuple[Optional[jnp.ndarray], ...],
    valid_rows: Optional[jnp.ndarray],
    nrows_s: Any,
    spec: "BinSpec",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    n = datas[0].shape[0]
    total = spec.total
    valid_rows = materialize_validity(valid_rows, n, nrows_s)
    seg = inline_seg(
        spec,
        dict(zip(spec.names, datas)),
        dict(zip(spec.names, masks)),
        valid_rows,
    )
    pos = jnp.arange(n, dtype=jnp.int32)
    # first valid row index per bin (n = "empty bin" sentinel)
    first_pos = jax.ops.segment_min(
        jnp.where(valid_rows, pos, n), seg, num_segments=total
    )
    occupied = first_pos < n
    first_idx = jnp.clip(first_pos, 0, n - 1)
    return seg, first_idx, occupied, occupied.sum().astype(jnp.int32)


def _sort_factorize(blocks: JaxBlocks, keys: List[str]) -> Factorized:
    """Lexicographic factorization via repeated stable sorts (general keys:
    floats, wide ints). One host sync for the group count."""
    codes: List[jnp.ndarray] = []
    for k in keys:
        col = blocks.columns[k]
        assert_or_throw(col.on_device, ValueError(f"key {k} not on device"))
        v = col.data
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int32)
        if jnp.issubdtype(v.dtype, jnp.floating):
            # Floats are their OWN sort codes: argsort orders them and the
            # equality-based boundary detection below works once the two
            # identity-hostile values are canonicalized — -0.0 -> +0.0
            # (groups with +0.0, host parity) and NaN -> 0.0 with a
            # separate isnan flag code (NaN != NaN would otherwise split
            # every NaN row into its own group). No bitcast anywhere: any
            # 64-bit bitcast-convert operand trips XLA's TPU x64 rewriter
            # (INTERNAL: bitcast-convert not implemented) regardless of
            # the target word shape (advisor r2, high).
            isnan = jnp.isnan(v)
            v = jnp.where(v == 0, jnp.zeros_like(v), v)
            v = jnp.where(isnan, jnp.zeros_like(v), v)
            pair = [isnan.astype(jnp.int32), v]
        elif v.dtype in (jnp.int64, jnp.uint64):
            words = jax.lax.bitcast_convert_type(v, jnp.uint32)
            pair = [words[:, 0].astype(jnp.int32),
                    words[:, 1].astype(jnp.int32)]
        else:
            pair = [v.astype(jnp.int32)]
        if col.mask is not None:
            # a separate null-flag key avoids any sentinel collision with
            # legitimate values: (is_null, value...) is the composite key
            codes.append((~col.mask).astype(jnp.int32))
            pair = [jnp.where(col.mask, p, 0) for p in pair]
        codes.extend(pair)
    seg_sorted, order, valid_rows, num_arr = _sort_factorize_core(
        tuple(codes), blocks.row_valid, _nrows_scalar_arg(blocks)
    )
    num = int(num_arr)  # host sync (general path only)
    seg, first_idx = _sort_factorize_finish(
        seg_sorted, order, valid_rows, num
    )
    return Factorized(seg, num, first_idx, None, jnp.int32(num))


@jax.jit
def _sort_factorize_core(
    codes: Tuple[jnp.ndarray, ...],
    valid_in: Optional[jnp.ndarray],
    nrows_s: Any,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    n = codes[0].shape[0]
    valid_rows = materialize_validity(valid_in, n, nrows_s)
    order = jnp.arange(n, dtype=jnp.int32)
    for c in reversed(codes):
        order = order[jnp.argsort(c[order], stable=True)]
    # validity as the final primary key (stable: preserves code order);
    # invalid rows sort last
    order = order[jnp.argsort(~valid_rows[order], stable=True)]
    sorted_valid = valid_rows[order]
    boundary = jnp.zeros((n,), dtype=jnp.bool_)
    for c in codes:
        sc = c[order]
        boundary = boundary | jnp.concatenate(
            [jnp.ones((1,), dtype=jnp.bool_), sc[1:] != sc[:-1]]
        )
    # only valid rows open groups; invalid rows (all trailing) get sentinel
    boundary = boundary & sorted_valid
    seg_sorted = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    num = jnp.max(jnp.where(sorted_valid, seg_sorted, -1)) + 1
    return seg_sorted, order, valid_rows, num


@partial(jax.jit, static_argnames=("num",))
def _sort_factorize_finish(
    seg_sorted: jnp.ndarray,
    order: jnp.ndarray,
    valid_rows: jnp.ndarray,
    num: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    n = order.shape[0]
    sorted_valid = valid_rows[order]
    seg_sorted = jnp.where(sorted_valid, seg_sorted, num)
    seg = (
        jnp.zeros((n,), dtype=jnp.int32).at[order].set(seg_sorted)
    )
    pos = jnp.arange(n, dtype=jnp.int32)
    first_pos = jax.ops.segment_min(
        jnp.where(sorted_valid, pos, n), seg_sorted, num_segments=num
    )
    first_idx = order[jnp.clip(first_pos, 0, n - 1)]
    return seg, first_idx


def _segment_agg_impl(
    func: str,
    values: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    seg: jnp.ndarray,
    num_segments: int,
    valid_rows: jnp.ndarray,
    strategy: str = "scatter",
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """One aggregation as a segment reduction (trace-time building block);
    returns (values[num_segments], mask[num_segments]). Sum-type reductions
    (count/sum/avg) route through the strategy layer; order-based ones
    (min/max/median/...) are scatter-native on every platform."""
    effective = valid_rows if mask is None else (mask & valid_rows)
    f = func.lower()
    if f == "count":
        return segment_count(effective, seg, num_segments, strategy), None
    if f == "sum" or f in ("avg", "mean"):
        filled = jnp.where(effective, values, 0)
        use = strategy
        if use == "matmul_bf16" and filled.dtype != jnp.float32:
            use = "matmul"  # the hi/lo split assumes f32 payloads
        if not jnp.issubdtype(filled.dtype, jnp.floating):
            if use in ("matmul", "matmul_bf16"):
                use = "scatter"  # exact int sums can't ride a float acc
            _, cs, is_ = segment_sums(
                [], [effective], seg, num_segments, use,
                int_payloads=[filled],
            )
            total, count = is_[0], cs[0]
        else:
            fs, cs, _ = segment_sums(
                [filled], [effective], seg, num_segments, use
            )
            total, count = fs[0], cs[0]
        if f == "sum":
            return total, count > 0  # all-null group -> NULL (SQL)
        avg = total / jnp.maximum(count, 1)
        return avg.astype(jnp.float64 if values.dtype == jnp.float64 else
                          jnp.float32), count > 0
    # int32 accumulation: int64 is emulated on TPU; counts fit int32 (<2B
    # rows); callers cast the output to the schema type
    count = jax.ops.segment_sum(
        effective.astype(jnp.int32), seg, num_segments=num_segments
    )
    if f == "min":
        big = _type_max(values.dtype)
        filled = jnp.where(effective, values, big)
        res = jax.ops.segment_min(filled, seg, num_segments=num_segments)
        return res, count > 0
    if f == "max":
        small = _type_min(values.dtype)
        filled = jnp.where(effective, values, small)
        res = jax.ops.segment_max(filled, seg, num_segments=num_segments)
        return res, count > 0
    if f in VARIANCE_FUNCS:
        if num_segments == 0:  # empty factorization: no groups at all
            z = jnp.zeros((0,), dtype=jnp.float64)
            return z, jnp.zeros((0,), dtype=jnp.bool_)
        # stable two-pass: mean per segment, then squared deviations.
        # NaN payloads (non-null computed NaNs, e.g. SQRT of a negative)
        # are skipped like pandas std/var skips them (review finding)
        eff = effective
        if jnp.issubdtype(values.dtype, jnp.floating):
            eff = eff & ~jnp.isnan(values)
        vcnt = jax.ops.segment_sum(
            eff.astype(jnp.int32), seg, num_segments=num_segments
        )
        fv = jnp.where(eff, values.astype(jnp.float64), 0.0)
        tot = jax.ops.segment_sum(fv, seg, num_segments=num_segments)
        cnt = vcnt.astype(jnp.float64)
        mean = tot / jnp.maximum(cnt, 1.0)
        segc = jnp.clip(seg, 0, num_segments - 1)
        dev = jnp.where(
            eff, values.astype(jnp.float64) - mean[segc], 0.0
        )
        ss = jax.ops.segment_sum(dev * dev, seg, num_segments=num_segments)
        pop = f in ("stddev_pop", "var_pop")
        denom = jnp.maximum(cnt if pop else cnt - 1.0, 1.0)
        var = ss / denom
        res = jnp.sqrt(var) if f.startswith("stddev") else var
        # sample forms need >= 2 rows (pandas ddof=1 gives NaN on one)
        return res, vcnt > (0 if pop else 1)
    if f == "median":
        if num_segments == 0:  # empty factorization: no groups at all
            z = jnp.zeros((0,), dtype=jnp.float64)
            return z, jnp.zeros((0,), dtype=jnp.bool_)
        # sorted-space selection: stable sort by value, re-sort by
        # segment (stability keeps the value order inside each segment),
        # then pick the middle position(s) per segment
        n = values.shape[0]
        fv = values.astype(jnp.float64)
        eff = effective
        if jnp.issubdtype(values.dtype, jnp.floating):
            eff = eff & ~jnp.isnan(values)
        mcount = jax.ops.segment_sum(
            eff.astype(jnp.int32), seg, num_segments=num_segments
        )
        keyv = jnp.where(eff, fv, jnp.inf)
        order = jnp.argsort(keyv, stable=True)
        segv = jnp.where(eff, seg, num_segments)
        order = order[jnp.argsort(segv[order], stable=True)]
        starts = jnp.cumsum(mcount) - mcount
        sortedv = fv[order]
        lo = starts + (mcount - 1) // 2
        hi = starts + mcount // 2
        med = (
            sortedv[jnp.clip(lo, 0, n - 1)]
            + sortedv[jnp.clip(hi, 0, n - 1)]
        ) * 0.5
        return med, mcount > 0
    if f in ("first", "last"):
        n = values.shape[0]
        idx = jnp.arange(n, dtype=jnp.int32)
        if f == "first":
            pick = jnp.where(valid_rows, idx, n)
            best = jax.ops.segment_min(pick, seg, num_segments=num_segments)
        else:
            pick = jnp.where(valid_rows, idx, -1)
            best = jax.ops.segment_max(pick, seg, num_segments=num_segments)
        best = jnp.clip(best, 0, n - 1)
        out_v = values[best]
        out_m = None if mask is None else mask[best]
        return out_v, out_m
    raise NotImplementedError(f"aggregation {func} on device")


def _type_max(dtype: Any) -> Any:
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf
    if dtype == jnp.bool_:
        return True
    return jnp.iinfo(dtype).max


def _type_min(dtype: Any) -> Any:
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    if dtype == jnp.bool_:
        return False
    return jnp.iinfo(dtype).min
