"""Device group-by aggregation: factorize keys, then segment reductions.

The TPU lowering of SQL GROUP BY (BASELINE: "group-by aggregates lower to
segment_sum/segment_max scans on device"): key columns (ints, dict-encoded
string codes, bools, dates) are packed into a single code, factorized with a
sort, and every aggregation becomes one ``jax.ops.segment_*`` scan — O(n log n)
once for the sort, O(n) per agg, all on the MXU-adjacent vector units with
XLA-inserted psums over ICI when sharded."""

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from fugue_tpu.jax_backend.blocks import JaxBlocks, JaxColumn
from fugue_tpu.utils.assertion import assert_or_throw


def row_validity(blocks: JaxBlocks) -> jnp.ndarray:
    """True for real rows, False for mesh padding."""
    pad_n = blocks.padded_nrows
    return jnp.arange(pad_n) < blocks.nrows


def factorize_keys(
    blocks: JaxBlocks, keys: List[str]
) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Return (segment_ids [padded_n], representative row index per group [G],
    num_groups). Null keys form their own groups (SQL GROUP BY semantics).
    Padding rows are routed to a trash segment dropped by the caller.

    Fast path — direct binning: when the combined key range is small (dict
    codes, int categories, bools, dates) segment ids are computed WITHOUT a
    global sort (seg = mixed-radix(k - kmin)); a distributed sort across the
    mesh costs ~10x one binning pass. Wide/float keys fall back to the
    sort-based path. Results are cached per frame (repeated ops on the same
    keys — transform then aggregate — pay once)."""
    cache_key = tuple(keys)
    if cache_key in blocks.factorize_cache:
        return blocks.factorize_cache[cache_key]
    res = _factorize_keys_impl(blocks, keys)
    blocks.factorize_cache[cache_key] = res
    return res


def _factorize_keys_impl(
    blocks: JaxBlocks, keys: List[str]
) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    binned = _try_bin_factorize(blocks, keys)
    if binned is not None:
        return binned
    valid_rows = row_validity(blocks)
    # pack each key into an int64 code with null flag
    codes: List[jnp.ndarray] = []
    for k in keys:
        col = blocks.columns[k]
        assert_or_throw(col.on_device, ValueError(f"key {k} not on device"))
        v = col.data
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.int32)
        if jnp.issubdtype(v.dtype, jnp.floating):
            # normalize -0.0 to +0.0 so both group together (host parity),
            # then use the bit pattern as a stable grouping identity
            v = jnp.where(v == 0, jnp.zeros_like(v), v)
            if v.dtype == jnp.float64:
                v = jax.lax.bitcast_convert_type(v, jnp.int64)
            else:
                v = jax.lax.bitcast_convert_type(
                    v.astype(jnp.float32), jnp.int32
                ).astype(jnp.int64)
        else:
            v = v.astype(jnp.int64)
        if col.mask is not None:
            # a separate null-flag key avoids any sentinel collision with
            # legitimate values: (is_null, value) is the composite key
            codes.append((~col.mask).astype(jnp.int64))
            v = jnp.where(col.mask, v, 0)
        codes.append(v)
    # lexicographic factorization via repeated stable sorts
    n = codes[0].shape[0]
    order = jnp.arange(n)
    for c in reversed(codes):
        order = order[jnp.argsort(c[order], stable=True)]
    # after composite sort, detect boundaries
    sorted_cols = [c[order] for c in codes]
    boundary = jnp.zeros((n,), dtype=jnp.bool_)
    for c in sorted_cols:
        boundary = boundary | jnp.concatenate(
            [jnp.ones((1,), dtype=jnp.bool_), c[1:] != c[:-1]]
        )
    # padding rows: force to the end by sorting validity first is not done;
    # instead mark them as their own trailing group and drop later
    sorted_valid = valid_rows[order]
    seg_sorted = jnp.cumsum(boundary) - 1
    # segment ids in original row order
    seg = jnp.zeros((n,), dtype=jnp.int64).at[order].set(seg_sorted)
    num_segments = int(seg_sorted[-1]) + 1 if n > 0 else 0
    # representative row per group: first VALID occurrence in sorted order
    # (deterministic segment_min; padding rows must never represent a group)
    pos = jnp.arange(n)
    first_valid_pos = jax.ops.segment_min(
        jnp.where(sorted_valid, pos, n), seg_sorted, num_segments=num_segments
    )
    group_has_valid = first_valid_pos < n
    first_idx = order[jnp.clip(first_valid_pos, 0, n - 1)]
    keep = group_has_valid
    # remap segment ids to the kept groups
    new_ids = jnp.cumsum(keep.astype(jnp.int64)) - 1
    seg = new_ids[seg]
    kept_first = first_idx[keep]
    return seg, kept_first, int(keep.sum())


_MAX_BINS = 1 << 22  # direct-binning cap (16MB of int32 per scratch array)


def _try_bin_factorize(
    blocks: JaxBlocks, keys: List[str]
) -> Optional[Tuple[jnp.ndarray, jnp.ndarray, int]]:
    """Sort-free factorization for small-range integer-like keys.

    Dispatch-frugal (the TPU may be network-tunneled, so every eager op is a
    round trip): ONE jitted min/max pass + ONE host sync for spans, ONE
    jitted binning program + ONE sync for the group count, ONE jitted gather.
    """
    datas: List[jnp.ndarray] = []
    masks: List[Optional[jnp.ndarray]] = []
    for k in keys:
        col = blocks.columns[k]
        if not col.on_device:
            return None
        if jnp.issubdtype(col.data.dtype, jnp.floating):
            return None
        datas.append(col.data)
        masks.append(col.mask)
    # one fused min/max for all keys -> single host transfer
    bounds = np.asarray(_minmax_jit(tuple(datas)))
    spans: List[int] = []
    for i in range(len(datas)):
        span = int(bounds[i, 1]) - int(bounds[i, 0]) + 1
        if span <= 0 or span > _MAX_BINS:
            return None
        if masks[i] is not None:
            span += 1  # null bucket
        spans.append(span)
    total = 1
    for r in spans:
        total *= r
        if total > _MAX_BINS:
            return None
    mins = tuple(int(bounds[i, 0]) for i in range(len(datas)))
    seg, first_pos, occupied, num_arr = _bin_core(
        tuple(datas),
        tuple(masks),
        mins,
        tuple(spans),
        blocks.nrows,
        total,
    )
    num = int(num_arr)
    first_idx = _gather_occupied(first_pos, occupied, num)
    return seg, first_idx, num


@jax.jit
def _minmax_jit(datas: Tuple[jnp.ndarray, ...]) -> jnp.ndarray:
    return jnp.stack(
        [
            jnp.stack([jnp.min(d).astype(jnp.int64), jnp.max(d).astype(jnp.int64)])
            for d in datas
        ]
    )


@partial(jax.jit, static_argnames=("mins", "spans", "nrows", "total"))
def _bin_core(
    datas: Tuple[jnp.ndarray, ...],
    masks: Tuple[Optional[jnp.ndarray], ...],
    mins: Tuple[int, ...],
    spans: Tuple[int, ...],
    nrows: int,
    total: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    # int32 throughout: int64 is EMULATED on TPU (~10x slower); bin codes
    # fit int32 by construction (total <= _MAX_BINS) and row positions fit
    # int32 up to 2B rows per frame
    n = datas[0].shape[0]
    valid_rows = jnp.arange(n, dtype=jnp.int32) < nrows
    # mixed-radix combine (single fused program; XLA auto-partitions)
    combined = jnp.zeros((n,), dtype=jnp.int32)
    for d, mask, kmin, span in zip(datas, masks, mins, spans):
        code = (d - kmin).astype(jnp.int32)
        if mask is not None:
            code = jnp.where(mask, code, span - 1)  # null -> top bucket
        combined = combined * jnp.int32(span) + code
    pos = jnp.arange(n, dtype=jnp.int32)
    # first valid row index per bin (n = "no valid row" sentinel)
    first_pos = jax.ops.segment_min(
        jnp.where(valid_rows, pos, n), combined, num_segments=total
    )
    occupied = first_pos < n
    # dense remap of occupied bins; group output order is unspecified,
    # like any SQL engine
    dense_ids = jnp.cumsum(occupied.astype(jnp.int32)) - 1
    seg = dense_ids[combined]
    return seg, first_pos, occupied, occupied.sum()


@partial(jax.jit, static_argnames=("num",))
def _gather_occupied(
    first_pos: jnp.ndarray, occupied: jnp.ndarray, num: int
) -> jnp.ndarray:
    idx = jnp.nonzero(occupied, size=num, fill_value=0)[0]
    return first_pos[idx]


@partial(jax.jit, static_argnames=("func", "num_segments", "has_mask"))
def _segment_agg_jit(
    func: str,
    values: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    seg: jnp.ndarray,
    num_segments: int,
    valid_rows: jnp.ndarray,
    has_mask: bool,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    return _segment_agg_impl(func, values, mask, seg, num_segments, valid_rows)


def segment_agg(
    func: str,
    values: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    seg: jnp.ndarray,
    num_segments: int,
    valid_rows: jnp.ndarray,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """One aggregation as a jit-compiled segment reduction; returns
    (values[G], mask[G])."""
    return _segment_agg_jit(
        func, values, mask, seg, num_segments, valid_rows, mask is not None
    )


def _segment_agg_impl(
    func: str,
    values: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    seg: jnp.ndarray,
    num_segments: int,
    valid_rows: jnp.ndarray,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    effective = valid_rows if mask is None else (mask & valid_rows)
    # int32 accumulation: int64 is emulated on TPU; counts fit int32 (<2B
    # rows); callers cast the output to the schema type
    count = jax.ops.segment_sum(
        effective.astype(jnp.int32), seg, num_segments=num_segments
    )
    f = func.lower()
    if f == "count":
        return count, None
    if f == "sum" or f in ("avg", "mean"):
        filled = jnp.where(effective, values, 0)
        total = jax.ops.segment_sum(filled, seg, num_segments=num_segments)
        if f == "sum":
            return total, count > 0  # all-null group -> NULL (SQL)
        avg = total / jnp.maximum(count, 1)
        return avg.astype(jnp.float64 if values.dtype == jnp.float64 else
                          jnp.float32), count > 0
    if f == "min":
        big = _type_max(values.dtype)
        filled = jnp.where(effective, values, big)
        res = jax.ops.segment_min(filled, seg, num_segments=num_segments)
        return res, count > 0
    if f == "max":
        small = _type_min(values.dtype)
        filled = jnp.where(effective, values, small)
        res = jax.ops.segment_max(filled, seg, num_segments=num_segments)
        return res, count > 0
    if f in ("first", "last"):
        n = values.shape[0]
        idx = jnp.arange(n, dtype=jnp.int32)
        if f == "first":
            pick = jnp.where(valid_rows, idx, n)
            best = jax.ops.segment_min(pick, seg, num_segments=num_segments)
        else:
            pick = jnp.where(valid_rows, idx, -1)
            best = jax.ops.segment_max(pick, seg, num_segments=num_segments)
        best = jnp.clip(best, 0, n - 1)
        out_v = values[best]
        out_m = None if mask is None else mask[best]
        return out_v, out_m
    raise NotImplementedError(f"aggregation {func} on device")


def _type_max(dtype: Any) -> Any:
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf
    if dtype == jnp.bool_:
        return True
    return jnp.iinfo(dtype).max


def _type_min(dtype: Any) -> Any:
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    if dtype == jnp.bool_:
        return False
    return jnp.iinfo(dtype).min
