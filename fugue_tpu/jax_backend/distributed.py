"""Multi-host support: DCN-coordinated meshes + in-program callbacks.

The reference's distributed story is its backends' (Spark/Dask/Ray)
cluster runtimes plus a Flask RPC channel (SURVEY §2.11). The TPU-native
equivalents here:

- :func:`init_distributed` — ``jax.distributed.initialize`` from conf
  keys (``fugue.jax.dist.*``); after it, ``jax.devices()`` spans every
  host and ``make_mesh()`` builds a global mesh whose collectives ride
  ICI within a slice and DCN across slices. The driver program is SPMD:
  every host runs the same engine code (single-controller per host,
  XLA owns the transport — no NCCL analog needed).
- :func:`make_device_callback` — the ``io_callback`` bridge: wraps an
  RPC client (in-process or HTTP) so a COMPILED jax transformer can
  invoke driver-side handlers from inside traced code — the TPU analog
  of calling the callback from a Spark UDF (reference
  fugue_test/builtin_suite.py:1552). Pinned to one device (SPMD rejects
  replicated side-effecting calls; one invocation per logical call is
  also the semantic an RPC notification wants).

- :func:`parse_lost_devices` / :func:`surviving_devices` /
  :func:`probe_devices` — the degraded-mesh recovery primitives: parse
  the dead device ids out of an XLA DATA_LOSS error, or probe every
  mesh device with a tiny transfer when the error names none, and hand
  the execution engine the surviving device list to rebuild from.

Conf keys:

- ``fugue.jax.dist.coordinator`` — ``host:port`` of process 0
- ``fugue.jax.dist.num_processes`` / ``fugue.jax.dist.process_id``
"""

import re
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp

from fugue_tpu.utils.params import ParamDict

CONF_COORDINATOR = "fugue.jax.dist.coordinator"
CONF_NUM_PROCESSES = "fugue.jax.dist.num_processes"
CONF_PROCESS_ID = "fugue.jax.dist.process_id"

_STATE = {"initialized": False}

# the id spellings XLA device errors use: "device 2", "device: 2",
# "TPU_3", "participant 1" (collective timeouts name ranks)
_LOST_DEVICE_RE = re.compile(
    r"(?:device[:\s]+|TPU_|participant[:\s]+)(\d+)", re.IGNORECASE
)


def parse_lost_devices(text: str) -> List[int]:
    """Dead device ids named by an XLA device-loss error message, in
    first-mention order, deduplicated. Empty when the error names none
    (the caller falls back to :func:`probe_devices`)."""
    seen: List[int] = []
    for m in _LOST_DEVICE_RE.finditer(str(text)):
        i = int(m.group(1))
        if i not in seen:
            seen.append(i)
    return seen


def surviving_devices(mesh: Any, lost_ids: Any) -> List[Any]:
    """The mesh's devices minus the lost ids, in mesh order. Ids match
    on ``device.id`` — the stable process-wide index ``fugue.jax.devices``
    also speaks."""
    lost = set(int(i) for i in lost_ids)
    return [d for d in mesh.devices.flat if int(d.id) not in lost]


def probe_devices(mesh: Any) -> List[Any]:
    """Probe every device in the mesh with a tiny round-trip transfer;
    return the ones that still answer. The fallback identification path
    when a device-loss error does not name the corpse."""
    ok: List[Any] = []
    for d in mesh.devices.flat:
        try:
            arr = jax.device_put(jnp.zeros((1,), jnp.int32), d)
            jax.block_until_ready(arr)
            ok.append(d)
        except Exception:
            continue
    return ok


def init_distributed(conf: Any = None) -> bool:
    """Initialize multi-host jax from conf; returns True when a
    multi-process setup was configured (False = single-host, no-op).
    Idempotent."""
    if _STATE["initialized"]:
        return True
    conf = ParamDict(conf)
    coordinator = conf.get(CONF_COORDINATOR, "")
    if coordinator == "":
        return False
    num = int(conf.get(CONF_NUM_PROCESSES, 1))
    pid = int(conf.get(CONF_PROCESS_ID, 0))
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num,
        process_id=pid,
    )
    _STATE["initialized"] = True
    return True


def make_device_callback(
    client: Callable[..., Any],
    result_shape: Optional[Any] = None,
    mesh: Optional[Any] = None,
) -> Callable[..., Any]:
    """Wrap an RPC client (or any host callable) for use INSIDE jitted
    code via ``jax.experimental.io_callback``.

    The wrapped function takes jax arrays, ships them to the host, calls
    ``client`` with numpy values, and returns arrays matching
    ``result_shape`` (a ``jax.ShapeDtypeStruct`` pytree; None = no
    result — pure notification). Example, inside a jax transformer::

        notify = make_device_callback(arrs_cb)  # from ctx callback
        def step(arrs):
            ...
            notify(jnp.sum(arrs["_row_valid"]))
            return {...}

    Pass the OWNING mesh when the caller's program runs on a device
    slice: the pin must land on a device that program actually uses —
    ``jax.devices()[0]`` belongs to a different replica's slice when
    engines carve up the pod via ``fugue.jax.devices``, and a cross
    slice pin both breaks the partitioner's placement and ships the
    callback operands over a link the program otherwise never touches.
    Without a mesh the process default device is kept for back-compat.
    """
    from jax.experimental import io_callback

    def _host(*args: Any) -> Any:
        import numpy as np

        res = client(*[np.asarray(a) for a in args])
        if result_shape is None:
            return None
        return res

    # under SPMD the callback is pinned to one device: the partitioner
    # rejects replicated side-effecting custom-calls, and a single
    # invocation per logical call is the semantic the RPC channel wants
    pin_dev = (
        mesh.devices.flat[0] if mesh is not None else jax.devices()[0]
    )
    pin = jax.sharding.SingleDeviceSharding(pin_dev)

    if result_shape is None:
        # io_callback requires a result; use a dummy int32 scalar
        shape = jax.ShapeDtypeStruct((), jnp.int32)

        def _host_dummy(*args: Any) -> Any:
            _host(*args)
            import numpy as np

            return np.int32(0)

        def _call(*args: Any) -> Any:
            return io_callback(
                _host_dummy, shape, *args, ordered=False, sharding=pin
            )

        return _call

    def _call_res(*args: Any) -> Any:
        return io_callback(
            _host, result_shape, *args, ordered=False, sharding=pin
        )

    return _call_res
