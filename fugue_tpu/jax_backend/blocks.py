"""Device block layer: dataframe columns as sharded jax.Arrays on a mesh.

The TPU-native columnar format (BASELINE north star: "partitions live as
sharded jax.Array blocks on a TPU pod mesh"):

- numeric/bool columns  -> jax.Array (+ bool validity mask when nulls exist)
- timestamp             -> int64 microseconds since epoch
- date                  -> int32 days since epoch
- string                -> dictionary-encoded: int32 codes on device, the
                           dictionary (np object array) on host
- anything else (nested, binary, decimal) -> host arrow column

Rows are padded to a multiple of the mesh size; a frame-level row validity
count tracks the true length. All device arrays are placed with
``NamedSharding(mesh, P("p"))`` over the leading (row) axis so jit-compiled
ops auto-partition and XLA inserts ICI collectives (scaling-book recipe:
pick a mesh, annotate shardings, let XLA do the rest).
"""

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw

_EPOCH = np.datetime64(0, "us")


class JaxColumn:
    """One column: device data + optional mask, or a host arrow fallback."""

    def __init__(
        self,
        pa_type: pa.DataType,
        data: Any,  # jax.Array (device kinds) or pa.ChunkedArray (host kind)
        mask: Optional[Any] = None,  # jax bool array, True = valid
        dictionary: Optional[np.ndarray] = None,  # for string kind
    ):
        self.pa_type = pa_type
        self.data = data
        self.mask = mask
        self.dictionary = dictionary

    @property
    def on_device(self) -> bool:
        return not isinstance(self.data, (pa.ChunkedArray, pa.Array))

    @property
    def is_string(self) -> bool:
        return self.dictionary is not None


def is_device_type(tp: pa.DataType) -> bool:
    return (
        pa.types.is_integer(tp)
        or pa.types.is_floating(tp)
        or pa.types.is_boolean(tp)
        or pa.types.is_timestamp(tp)
        or pa.types.is_date32(tp)
        or pa.types.is_string(tp)
        or pa.types.is_large_string(tp)
    )


def _np_dtype_for(tp: pa.DataType) -> Any:
    if pa.types.is_timestamp(tp):
        return np.int64
    if pa.types.is_date32(tp):
        return np.int32
    if pa.types.is_boolean(tp):
        return np.bool_
    return tp.to_pandas_dtype()


def make_mesh(devices: Optional[List[Any]] = None) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    return Mesh(np.array(devs), axis_names=("p",))


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("p"))


def padded_len(n: int, ndev: int) -> int:
    if n == 0:
        return ndev
    return ((n + ndev - 1) // ndev) * ndev


class JaxBlocks:
    """All columns of a frame + true row count (device rows may be padded)."""

    def __init__(self, nrows: int, columns: Dict[str, JaxColumn], mesh: Mesh):
        self.nrows = nrows
        self.columns = columns
        self.mesh = mesh
        # per-frame cache of key factorizations: (keys...) -> (seg, first, num)
        self.factorize_cache: Dict[Any, Any] = {}

    @property
    def all_on_device(self) -> bool:
        return all(c.on_device for c in self.columns.values())

    @property
    def padded_nrows(self) -> int:
        for c in self.columns.values():
            if c.on_device:
                return int(c.data.shape[0])
        return self.nrows


def from_arrow(table: pa.Table, schema: Schema, mesh: Mesh) -> JaxBlocks:
    """Arrow -> device blocks (pads rows, encodes strings, builds masks)."""
    ndev = mesh.devices.size
    n = table.num_rows
    pad_n = padded_len(n, ndev)
    sharding = row_sharding(mesh)
    cols: Dict[str, JaxColumn] = {}
    for field in schema.fields:
        arr = table.column(field.name)
        tp = field.type
        if not is_device_type(tp):
            cols[field.name] = JaxColumn(tp, arr.combine_chunks())
            continue
        if pa.types.is_string(tp) or pa.types.is_large_string(tp):
            enc = arr.combine_chunks().dictionary_encode()
            codes_np = enc.indices.to_numpy(zero_copy_only=False)
            valid = ~pd.isna(codes_np)
            codes = np.where(valid, np.nan_to_num(codes_np, nan=0), 0).astype(
                np.int32
            )
            dictionary = np.asarray(enc.dictionary.to_pylist(), dtype=object)
            data = _pad(codes, pad_n, 0)
            mask = _pad(valid.astype(np.bool_), pad_n, False)
            cols[field.name] = JaxColumn(
                tp,
                jax.device_put(data, sharding),
                jax.device_put(mask, sharding),
                dictionary,
            )
            continue
        np_dtype = _np_dtype_for(tp)
        combined = arr.combine_chunks()
        null_count = combined.null_count
        if pa.types.is_timestamp(tp):
            values = combined.cast(pa.timestamp("us")).to_numpy(
                zero_copy_only=False
            )
            values = (values.astype("datetime64[us]") - _EPOCH).astype(np.int64)
        elif pa.types.is_date32(tp):
            values = combined.to_numpy(zero_copy_only=False)
            values = (
                values.astype("datetime64[D]").astype("datetime64[us]") - _EPOCH
            ).astype(np.int64) // 86_400_000_000
            values = values.astype(np.int32)
        else:
            values = combined.to_numpy(zero_copy_only=False)
        if null_count > 0:
            import pyarrow.compute as pc

            valid = pc.is_valid(combined).to_numpy(zero_copy_only=False)
            # int columns with nulls surface as float+NaN from to_numpy
            if values.dtype.kind == "f" and not np.issubdtype(
                np_dtype, np.floating
            ):
                values = np.nan_to_num(values)
            filled = np.where(valid, values, 0).astype(np_dtype)
            mask_arr: Optional[Any] = jax.device_put(
                _pad(valid.astype(np.bool_), pad_n, False), sharding
            )
            data = _pad(filled, pad_n, 0)
        else:
            mask_arr = None
            data = _pad(np.ascontiguousarray(values, dtype=np_dtype), pad_n, 0)
        cols[field.name] = JaxColumn(
            tp, jax.device_put(data, sharding), mask_arr
        )
    return JaxBlocks(n, cols, mesh)


def _pad(arr: np.ndarray, target: int, fill: Any) -> np.ndarray:
    if arr.shape[0] == target:
        return arr
    out = np.full((target,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def to_arrow(blocks: JaxBlocks, schema: Schema) -> pa.Table:
    """Device blocks -> arrow (host gather, mask->null, dict decode)."""
    n = blocks.nrows
    arrays = []
    for field in schema.fields:
        col = blocks.columns[field.name]
        tp = field.type
        if not col.on_device:
            arrays.append(col.data.slice(0, n) if hasattr(col.data, "slice")
                          else col.data)
            continue
        values = np.asarray(col.data)[:n]
        mask_np = None if col.mask is None else ~np.asarray(col.mask)[:n]
        if col.is_string:
            decoded = np.empty(n, dtype=object)
            codes = values
            valid = np.ones(n, dtype=bool) if mask_np is None else ~mask_np
            decoded[valid] = col.dictionary[codes[valid]]
            decoded[~valid] = None
            arrays.append(pa.array(decoded, type=tp))
            continue
        if pa.types.is_timestamp(tp):
            ts = (values.astype(np.int64)).astype("datetime64[us]")
            arrays.append(
                pa.array(ts, type=pa.timestamp("us"), from_pandas=True).cast(tp)
                if mask_np is None
                else pa.array(
                    np.ma.masked_array(ts, mask=mask_np)  # type: ignore
                ).cast(tp)
            )
            continue
        if pa.types.is_date32(tp):
            days = values.astype(np.int32)
            arrays.append(
                pa.array(days, type=pa.int32()).cast(pa.date32())
                if mask_np is None
                else pa.Array.from_pandas(
                    pd.Series(days).mask(mask_np), type=pa.int32()
                ).cast(pa.date32())
            )
            continue
        if mask_np is None:
            arrays.append(pa.array(values, type=tp))
        else:
            arrays.append(
                pa.Array.from_pandas(
                    pd.Series(values).mask(mask_np), type=tp
                )
            )
    return pa.Table.from_arrays(arrays, schema=schema.pa_schema)


def gather_indices(blocks: JaxBlocks, idx: Any, schema: Schema) -> JaxBlocks:
    """Row-gather every device column (host columns via arrow take)."""
    mesh = blocks.mesh
    ndev = mesh.devices.size
    new_n = int(idx.shape[0])
    pad_n = padded_len(new_n, ndev)
    sharding = row_sharding(mesh)
    # padding rows beyond new_n are garbage by convention: every consumer
    # respects blocks.nrows (to_arrow slices, aggs build a row-validity mask)
    idx_padded = jnp.concatenate(
        [idx, jnp.zeros((pad_n - new_n,), dtype=idx.dtype)]
    ) if pad_n != new_n else idx
    cols: Dict[str, JaxColumn] = {}
    for name, col in blocks.columns.items():
        if not col.on_device:
            taken = col.data.take(pa.array(np.asarray(idx)))
            cols[name] = JaxColumn(col.pa_type, taken)
            continue
        data = jax.device_put(col.data[idx_padded], sharding)
        mask = (
            None
            if col.mask is None
            else jax.device_put(col.mask[idx_padded], sharding)
        )
        cols[name] = JaxColumn(col.pa_type, data, mask, col.dictionary)
    return JaxBlocks(new_n, cols, mesh)
