"""Device block layer: dataframe columns as sharded jax.Arrays on a mesh.

The TPU-native columnar format (BASELINE north star: "partitions live as
sharded jax.Array blocks on a TPU pod mesh"):

- numeric/bool columns  -> jax.Array (+ bool validity mask when nulls exist)
- timestamp             -> int64 microseconds since epoch
- date                  -> int32 days since epoch
- string                -> dictionary-encoded: int32 codes on device, the
                           dictionary (np object array) on host
- anything else (nested, binary, decimal) -> host arrow column

Rows are padded to a multiple of the mesh size. Row membership has TWO
layouts: *prefix* (rows [0, nrows) are real — the ingest layout) and
*masked* (a device bool ``row_valid`` marks real rows — produced by filter/
dropna/distinct/aggregate so those ops never synchronize with the host).
A frame's true row count may therefore be LAZY: a device scalar that is
only read back when the host actually needs the number (count(), arrow
export). This is the core of the engine's latency design: on a
network-tunneled TPU every host sync costs ~70ms, so the whole pipeline
must compile to a chain of async dispatches with a single sync at the
host boundary.

Integer-like columns carry host-known (min, max) ``stats`` captured at
ingest and propagated through gathers/passthroughs; they let group-by key
factorization choose static bin counts without reading bounds back from
the device (see groupby.py).

All device arrays are placed with ``NamedSharding(mesh, P("p"))`` over the
leading (row) axis so jit-compiled ops auto-partition and XLA inserts ICI
collectives (scaling-book recipe: pick a mesh, annotate shardings, let XLA
do the rest).
"""

import logging
import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pyarrow as pa
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fugue_tpu.schema import Schema
from fugue_tpu.testing.retrace import active_retrace_sentinel
from fugue_tpu.utils.assertion import assert_or_throw

_EPOCH = np.datetime64(0, "us")
_LOG = logging.getLogger("fugue_tpu.jax")


def ensure_x64() -> None:
    """Enable 64-bit dtypes (required for long/timestamp column fidelity:
    without x64, device_put silently truncates int64 -> int32).

    Called from engine/mesh/ingest entry points rather than at import time
    so importing fugue_tpu.jax_backend does not mutate global jax config
    for unrelated code (advisor finding r1). Opt out with
    FUGUE_TPU_DISABLE_X64=1 if every column fits 32 bits."""
    import os

    if os.environ.get("FUGUE_TPU_DISABLE_X64", "").lower() in ("1", "true"):
        return
    if not jax.config.jax_enable_x64:
        _LOG.info(
            "fugue_tpu: enabling jax_enable_x64 for 64-bit column fidelity"
        )
        jax.config.update("jax_enable_x64", True)


class JaxColumn:
    """One column: device data + optional mask, or a host arrow fallback.

    ``stats`` is an optional host-known (min, max) int pair bounding the
    VALID values of an integer-like column (a superset bound is fine);
    ``dictionary`` holds the decode table for string columns. ``unique``
    is a host-known guarantee that the column's VALID values are
    pairwise distinct (captured at ingest for strictly monotonic integer
    keys — the dimension-table surrogate-key pattern); it stays sound
    under row filtering (a subset of distinct values is distinct) and is
    dropped by every transformation that could duplicate values."""

    def __init__(
        self,
        pa_type: pa.DataType,
        data: Any,  # jax.Array (device kinds) or pa.ChunkedArray (host kind)
        mask: Optional[Any] = None,  # jax bool array, True = valid
        dictionary: Optional[np.ndarray] = None,  # for string kind
        stats: Optional[Tuple[int, int]] = None,  # host-known (min, max)
        unique: bool = False,
    ):
        self.pa_type = pa_type
        self.data = data
        self.mask = mask
        self.dictionary = dictionary
        self.stats = stats
        self.unique = unique

    @property
    def on_device(self) -> bool:
        return not isinstance(self.data, (pa.ChunkedArray, pa.Array))

    @property
    def is_string(self) -> bool:
        return self.dictionary is not None

    def with_data(
        self, data: Any, mask: Optional[Any], keep_stats: bool = True
    ) -> "JaxColumn":
        """Same logical column, new storage (e.g. after a row gather —
        a subset of rows keeps the same value bounds and dictionary)."""
        return JaxColumn(
            self.pa_type,
            data,
            mask,
            self.dictionary,
            self.stats if keep_stats else None,
        )


def is_device_type(tp: pa.DataType) -> bool:
    return (
        pa.types.is_integer(tp)
        or pa.types.is_floating(tp)
        or pa.types.is_boolean(tp)
        or pa.types.is_timestamp(tp)
        or pa.types.is_date32(tp)
        or pa.types.is_string(tp)
        or pa.types.is_large_string(tp)
    )


def _np_dtype_for(tp: pa.DataType) -> Any:
    if pa.types.is_timestamp(tp):
        return np.int64
    if pa.types.is_date32(tp):
        return np.int32
    if pa.types.is_boolean(tp):
        return np.bool_
    return tp.to_pandas_dtype()


def make_mesh(devices: Optional[List[Any]] = None) -> Mesh:
    ensure_x64()
    devs = devices if devices is not None else jax.devices()
    return Mesh(np.array(devs), axis_names=("p",))


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("p"))


# Compiled row-sharded programs are cached ON their mesh object (one
# dict per mesh) instead of in a module-global keyed by the mesh: each
# cached program's out_sharding holds a strong reference back to its
# mesh, so a global would root every mesh it ever saw — dead meshes
# (fleet replica churn, per-test engines) would leak their compiled
# programs forever, and no finalizer could fire to stop it. Attached to
# the mesh, cache + programs + mesh form one cycle the ordinary GC
# reclaims the moment the last outside reference drops. The weak
# registry below only observes which meshes currently carry a cache
# (tests assert it stays weak and that no module global here strongly
# roots a mesh or its programs).
_JIT_ROW_SHARDED_ATTR = "_fugue_jit_row_sharded_cache"
_JIT_ROW_SHARDED_MESHES: Any = weakref.WeakSet()


def jit_row_sharded(mesh: Mesh, key: Any, fn: Any) -> Any:
    """Jit ``fn`` with every output constrained to the mesh's row
    sharding, cached per (mesh, key). This is the multihost-safe way to
    CREATE row-axis arrays outside engine programs: eager jnp creations
    commit to one process-local device, and ``device_put`` onto a
    process-spanning sharding is a cross-host reshard jax refuses on CPU
    meshes. Callers must pass HOST scalars (np.int32, not jnp) so inputs
    never carry a single-device commitment either."""
    per_mesh = getattr(mesh, _JIT_ROW_SHARDED_ATTR, None)
    if per_mesh is None:
        per_mesh = {}
        setattr(mesh, _JIT_ROW_SHARDED_ATTR, per_mesh)
        _JIT_ROW_SHARDED_MESHES.add(mesh)
    prog = per_mesh.get(key)
    if prog is None:
        prog = jax.jit(fn, out_shardings=row_sharding(mesh))
        per_mesh[key] = prog
    san = active_retrace_sentinel()
    if san is None:
        return prog
    return _sentineled_dispatch(san, key, prog)


def _sentineled_dispatch(san: Any, key: Any, prog: Any) -> Any:
    """Retrace-sentinel shim over one row-sharded program: a dispatch
    that grew jax's per-shape cache was an actual XLA trace, counted
    against the program key's budget. Only ever constructed while the
    debug sentinel is armed — the disarmed path returns the raw jitted
    handle untouched."""
    name = "row_sharded:" + (
        str(key[0]) if isinstance(key, tuple) and key else str(key)
    )

    def _watched(*args: Any, **kwargs: Any) -> Any:
        sizer = getattr(prog, "_cache_size", None)
        before = -1
        if sizer is not None:
            try:
                before = sizer()
            except Exception:  # pragma: no cover - jax version drift
                sizer = None
        out = prog(*args, **kwargs)
        if sizer is not None:
            try:
                traced = sizer() > before
            except Exception:  # pragma: no cover
                traced = False
            if traced:
                ev = san.note_trace(name, key, args)
                san.raise_if_armed(ev)
        return out

    return _watched


def on_mesh(mesh: Mesh) -> Any:
    """Context manager pinning EAGER jnp array creation to the mesh's
    backend. Without it, eager ``jnp.arange``/``ones``/``concatenate``
    land on the process default device — on a TPU process operating a
    HOST-tier frame that silently bounces arrays through the accelerator
    link (measured: a 5M-row eager validity() cost 123ms over the tunnel
    vs <5ms local). Jitted programs don't need this: they follow their
    inputs' placement."""
    return jax.default_device(mesh.devices.flat[0])


def padded_len(n: int, ndev: int) -> int:
    if n == 0:
        return ndev
    return ((n + ndev - 1) // ndev) * ndev


def put_sharded(arr: np.ndarray, sharding: NamedSharding) -> Any:
    """Host numpy -> sharded device array. Single-process: a plain
    ``device_put``. Multi-process (after ``init_distributed``): every
    process holds the same host array (SPMD ingest) and contributes only
    its ADDRESSABLE shards via ``make_array_from_callback`` — device_put
    cannot place onto non-addressable devices."""
    if jax.process_count() > 1:
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )
    return jax.device_put(arr, sharding)


class JaxBlocks:
    """All columns of a frame + row membership.

    Invariant: either ``row_valid`` is a device bool array over the padded
    rows (masked layout; ``nrows`` may be lazy — a pending device scalar),
    or ``row_valid`` is None and ``nrows`` is a known int with prefix
    layout (rows [0, nrows) real)."""

    def __init__(
        self,
        nrows: Optional[int],
        columns: Dict[str, JaxColumn],
        mesh: Mesh,
        row_valid: Optional[Any] = None,
        nrows_dev: Optional[Any] = None,
    ):
        assert_or_throw(
            nrows is not None or row_valid is not None,
            ValueError("lazy nrows requires a row_valid mask"),
        )
        self._nrows = nrows
        self._nrows_dev = nrows_dev
        self.columns = columns
        self.mesh = mesh
        self.row_valid = row_valid
        # per-frame cache of key factorizations (see groupby.factorize_keys)
        self.factorize_cache: Dict[Any, Any] = {}
        # device-loss bookkeeping: ``lost`` marks a frame whose shards
        # died with a device and could not be rebuilt (touching it fails
        # the owning query with DeviceLostError); ``lineage`` optionally
        # holds a zero-arg loader returning a fresh arrow table — the
        # recoverable provenance (lazy ingest plan, checkpoint artifact,
        # pinned lake:// version) recovery re-materializes from
        self.lost = False
        self.lineage: Optional[Any] = None

    @property
    def nrows(self) -> int:
        """True row count; synchronizes with the device if lazy."""
        if self._nrows is None:
            if self._nrows_dev is not None:
                self._nrows = int(self._nrows_dev)
            else:
                self._nrows = int(jnp.sum(self.row_valid))
        return self._nrows

    @property
    def nrows_known(self) -> bool:
        return self._nrows is not None

    @property
    def nrows_scalar(self) -> Any:
        """Row count usable inside traced programs without a host sync."""
        if self._nrows is not None:
            return jnp.int32(self._nrows)
        if self._nrows_dev is not None:
            return self._nrows_dev.astype(jnp.int32)
        return jnp.sum(self.row_valid).astype(jnp.int32)

    @property
    def all_on_device(self) -> bool:
        return all(c.on_device for c in self.columns.values())

    @property
    def padded_nrows(self) -> int:
        for c in self.columns.values():
            if c.on_device:
                return int(c.data.shape[0])
        return self.nrows

    def validity(self) -> jnp.ndarray:
        """Device bool array over padded rows: True = real row. Built by
        a row-sharded jitted program so the mask is a GLOBAL array on
        multi-process meshes (an eager arange commits to one local
        device, and device_put cannot reshard across hosts)."""
        if self.row_valid is not None:
            return self.row_valid
        pad_n = self.padded_nrows
        prog = jit_row_sharded(
            self.mesh,
            ("validity", pad_n),
            lambda n: jnp.arange(pad_n, dtype=jnp.int32) < n,
        )
        return prog(np.int32(self._nrows))

    @property
    def is_prefix_layout(self) -> bool:
        return self.row_valid is None


def residency_arrays(blocks: JaxBlocks) -> List[Any]:
    """EVERY device array a frame owns: column data, column validity
    masks, and the row_valid mask. This is the set a residency-forcing
    fetch (persist) or an honest bench endpoint must drain — on relayed
    TPU backends any array left out can lazily stage over the link later
    (ADVICE r5 #1: masks staged inside the first timed run)."""
    arrs: List[Any] = []
    for c in blocks.columns.values():
        if c.on_device:
            arrs.append(c.data)
            if c.mask is not None:
                arrs.append(c.mask)
    if blocks.row_valid is not None:
        arrs.append(blocks.row_valid)
    return arrs


def device_nbytes(blocks: JaxBlocks) -> int:
    """A frame's REAL device-tier footprint: the byte sum over every
    device array it owns (column data, validity masks, row_valid). This
    is the number the memory governor's ledger registers — tests assert
    ledger parity against it, so it must stay in lockstep with
    :func:`residency_arrays`."""
    return sum(int(a.nbytes) for a in residency_arrays(blocks))


def _int_like_stats(
    values: np.ndarray, tp: pa.DataType
) -> Optional[Tuple[int, int]]:
    """Host-side (min, max) bound for integer-like ingest data. The array
    is already null-filled with 0, so the bound is a superset of the valid
    values — exactly what bin factorization needs."""
    if values.size == 0:
        return (0, 0)
    if values.dtype == np.bool_:
        return (0, 1)
    if values.dtype.kind in "iu":
        return (int(values.min()), int(values.max()))
    return None


def decode_device_values(arr: Any, tp: pa.DataType) -> np.ndarray:
    """Arrow array/chunked-array -> raw numpy values for a device-kind
    non-string column (timestamps to int64 us-since-epoch, date32 to
    int32 days; null positions arrive as NaN/NaT and are filled by the
    caller). THE canonical decode — the eager ingest (:func:`from_arrow`)
    and the streamed per-batch ingest (ingest._decode_into) must stay
    value-identical, so both call this."""
    if pa.types.is_timestamp(tp):
        values = arr.cast(pa.timestamp("us")).to_numpy(zero_copy_only=False)
        return (values.astype("datetime64[us]") - _EPOCH).astype(np.int64)
    if pa.types.is_date32(tp):
        values = arr.to_numpy(zero_copy_only=False)
        values = (
            values.astype("datetime64[D]").astype("datetime64[us]") - _EPOCH
        ).astype(np.int64) // 86_400_000_000
        return values.astype(np.int32)
    return arr.to_numpy(zero_copy_only=False)


def from_arrow(table: pa.Table, schema: Schema, mesh: Mesh) -> JaxBlocks:
    """Arrow -> device blocks (pads rows, encodes strings, builds masks,
    captures host-side key stats)."""
    ensure_x64()
    ndev = mesh.devices.size
    n = table.num_rows
    pad_n = padded_len(n, ndev)
    sharding = row_sharding(mesh)
    cols: Dict[str, JaxColumn] = {}
    for field in schema.fields:
        arr = table.column(field.name)
        tp = field.type
        if not is_device_type(tp):
            cols[field.name] = JaxColumn(tp, arr.combine_chunks())
            continue
        if pa.types.is_string(tp) or pa.types.is_large_string(tp):
            enc = arr.combine_chunks().dictionary_encode()
            codes_np = enc.indices.to_numpy(zero_copy_only=False)
            valid = ~pd.isna(codes_np)
            codes = np.where(valid, np.nan_to_num(codes_np, nan=0), 0).astype(
                np.int32
            )
            dictionary = np.asarray(enc.dictionary.to_pylist(), dtype=object)
            data = _pad(codes, pad_n, 0)
            mask = _pad(valid.astype(np.bool_), pad_n, False)
            cols[field.name] = JaxColumn(
                tp,
                put_sharded(data, sharding),
                put_sharded(mask, sharding),
                dictionary,
                stats=(0, max(len(dictionary) - 1, 0)),
            )
            continue
        np_dtype = _np_dtype_for(tp)
        combined = arr.combine_chunks()
        null_count = combined.null_count
        values = decode_device_values(combined, tp)
        if null_count > 0:
            import pyarrow.compute as pc

            valid = pc.is_valid(combined).to_numpy(zero_copy_only=False)
            # int columns with nulls surface as float+NaN from to_numpy
            if values.dtype.kind == "f" and not np.issubdtype(
                np_dtype, np.floating
            ):
                values = np.nan_to_num(values)
            filled = np.where(valid, values, 0).astype(np_dtype)
            mask_arr: Optional[Any] = put_sharded(
                _pad(valid.astype(np.bool_), pad_n, False), sharding
            )
            data = _pad(filled, pad_n, 0)
            stats = _int_like_stats(filled, tp)
        else:
            mask_arr = None
            data = _pad(np.ascontiguousarray(values, dtype=np_dtype), pad_n, 0)
            stats = _int_like_stats(data[:n] if n > 0 else data[:0], tp)
        unique = False
        if (
            mask_arr is None
            and pa.types.is_integer(tp)
            and 0 < n <= _UNIQUE_CHECK_MAX
        ):
            # strictly monotonic integer keys (the dim-table surrogate-key
            # pattern) are provably unique — unlocks the sync-free
            # unique-right join fast path (relational.expand_join).
            # element-wise comparison, NOT np.diff: subtraction wraps for
            # unsigned/extreme values and would falsely prove uniqueness
            unique = bool((data[1:n] > data[: n - 1]).all())
        cols[field.name] = JaxColumn(
            tp, put_sharded(data, sharding), mask_arr, stats=stats,
            unique=unique,
        )
    return JaxBlocks(n, cols, mesh)


_UNIQUE_CHECK_MAX = 4_000_000  # O(n) host check only for dim-table sizes


def _pad(arr: np.ndarray, target: int, fill: Any) -> np.ndarray:
    if arr.shape[0] == target:
        return arr
    out = np.full((target,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def to_arrow(blocks: JaxBlocks, schema: Schema) -> pa.Table:
    """Device blocks -> arrow (host gather, mask->null, dict decode).

    This is THE host boundary: masked-layout frames are compacted here with
    one readback of the validity mask; all lazy row counts materialize.
    All device columns transfer in ONE async wave (per-array readbacks cost
    a full relay round trip each on tunneled TPUs)."""
    for col in blocks.columns.values():
        if col.on_device:
            col.data.copy_to_host_async()
            if col.mask is not None:
                col.mask.copy_to_host_async()
    take: Optional[np.ndarray] = None
    if blocks.row_valid is not None:
        blocks.row_valid.copy_to_host_async()
        valid_np = np.asarray(blocks.row_valid)
        take = np.nonzero(valid_np)[0]
        n = int(take.shape[0])
        blocks._nrows = n  # materialized for free
    else:
        n = blocks.nrows
    arrays = []
    for field in schema.fields:
        col = blocks.columns[field.name]
        tp = field.type
        if not col.on_device:
            host = col.data
            if take is not None:
                host = host.take(pa.array(take))
            elif hasattr(host, "slice"):
                host = host.slice(0, n)
            arrays.append(host)
            continue
        full = np.asarray(col.data)
        values = full[take] if take is not None else full[:n]
        if col.mask is not None:
            m_full = ~np.asarray(col.mask)
            mask_np = m_full[take] if take is not None else m_full[:n]
        else:
            mask_np = None
        if col.is_string:
            # dictionary fast path: wrap the codes in an arrow
            # DictionaryArray and cast — arrow's C++ expand is ~8x faster
            # than numpy object-space decode (12ms vs 98ms at 2M rows)
            indices = pa.array(
                values.astype(np.int32, copy=False), mask=mask_np
            )
            da = pa.DictionaryArray.from_arrays(
                indices, pa.array(col.dictionary, type=pa.string())
            )
            arrays.append(da.cast(tp))
            continue
        if pa.types.is_timestamp(tp):
            ts = (values.astype(np.int64)).astype("datetime64[us]")
            arrays.append(
                pa.array(ts, type=pa.timestamp("us"), from_pandas=True).cast(tp)
                if mask_np is None
                else pa.array(
                    np.ma.masked_array(ts, mask=mask_np)  # type: ignore
                ).cast(tp)
            )
            continue
        if pa.types.is_date32(tp):
            days = values.astype(np.int32)
            arrays.append(
                pa.array(days, type=pa.int32()).cast(pa.date32())
                if mask_np is None
                else pa.Array.from_pandas(
                    pd.Series(days).mask(mask_np), type=pa.int32()
                ).cast(pa.date32())
            )
            continue
        if mask_np is None:
            arrays.append(pa.array(values, type=tp))
        else:
            arrays.append(
                pa.Array.from_pandas(
                    pd.Series(values).mask(mask_np), type=tp
                )
            )
    return pa.Table.from_arrays(arrays, schema=schema.pa_schema)


def blocks_schema(blocks: JaxBlocks) -> Schema:
    """A frame's schema as derived from its own columns (arrow types are
    authoritative on every JaxColumn). Used when no external Schema is
    at hand — e.g. device-loss evacuation of an anonymous frame."""
    return Schema(
        pa.schema(
            [pa.field(n, c.pa_type) for n, c in blocks.columns.items()]
        )
    )


def evacuate_blocks(
    blocks: JaxBlocks, mesh: Mesh, schema: Optional[Schema] = None
) -> None:
    """Rebuild a frame's storage onto ``mesh`` IN PLACE via an arrow
    round trip, preserving logical content exactly (row membership
    compacts, strings re-encode). In place because callers across the
    engine (catalog tables, session views, in-flight queries) hold
    references to THIS JaxBlocks object — recovery must heal them all,
    not just ones it can find.

    An arrow round trip rather than a device-to-device resharding:
    the old padding (a multiple of the dead mesh's size) is generally
    not divisible by the survivor count, and the source sharding spans
    a device that no longer answers — the host is the only safe relay.
    Raises if the dead device's shards are already unreadable; the
    caller then falls back to the frame's lineage."""
    sch = schema if schema is not None else blocks_schema(blocks)
    table = to_arrow(blocks, sch)
    fresh = from_arrow(table, sch, mesh)
    replace_blocks(blocks, fresh)


def replace_blocks(blocks: JaxBlocks, fresh: JaxBlocks) -> None:
    """Swap ``blocks``'s storage for ``fresh``'s in place (same logical
    frame, new arrays/mesh). Derived caches reset; the ``lost`` flag
    clears — the frame is healthy again."""
    blocks.columns = fresh.columns
    blocks.mesh = fresh.mesh
    blocks.row_valid = fresh.row_valid
    blocks._nrows = fresh._nrows
    blocks._nrows_dev = fresh._nrows_dev
    blocks.factorize_cache.clear()
    blocks.lost = False


def gather_indices(blocks: JaxBlocks, idx: Any, schema: Schema) -> JaxBlocks:
    """Row-gather every device column in ONE jitted dispatch (host columns
    via arrow take). ``idx`` must index real rows only."""
    mesh = blocks.mesh
    ndev = mesh.devices.size
    new_n = int(idx.shape[0])
    pad_n = padded_len(new_n, ndev)
    sharding = row_sharding(mesh)
    device_cols = {n: c for n, c in blocks.columns.items() if c.on_device}
    datas = {n: c.data for n, c in device_cols.items()}
    masks = {n: c.mask for n, c in device_cols.items() if c.mask is not None}
    with on_mesh(mesh):
        idx_dev = jnp.asarray(idx)
    out_d, out_m = _gather_program(pad_n)(datas, masks, idx_dev)
    cols: Dict[str, JaxColumn] = {}
    for name, col in blocks.columns.items():
        if not col.on_device:
            taken = col.data.take(pa.array(np.asarray(idx)))
            cols[name] = JaxColumn(col.pa_type, taken)
            continue
        cols[name] = col.with_data(
            jax.device_put(out_d[name], sharding),
            None
            if name not in out_m
            else jax.device_put(out_m[name], sharding),
        )
    return JaxBlocks(new_n, cols, mesh)


_GATHER_CACHE: Dict[int, Any] = {}


def _gather_program(pad_n: int) -> Any:
    """Jitted multi-column gather; padding rows repeat index 0 (garbage by
    convention — consumers respect the frame's row membership)."""
    if pad_n not in _GATHER_CACHE:

        @jax.jit
        def _gather(
            datas: Dict[str, jnp.ndarray],
            masks: Dict[str, jnp.ndarray],
            idx: jnp.ndarray,
        ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
            n = idx.shape[0]
            if n != pad_n:
                idx = jnp.concatenate(
                    [idx, jnp.zeros((pad_n - n,), dtype=idx.dtype)]
                )
            return (
                {k: v[idx] for k, v in datas.items()},
                {k: v[idx] for k, v in masks.items()},
            )

        _GATHER_CACHE[pad_n] = _gather
    return _GATHER_CACHE[pad_n]
