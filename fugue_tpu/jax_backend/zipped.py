"""Device co-partitioning: zip/comap without serialization.

The reference's zip path (fugue/execution/execution_engine.py:969-1360)
pickles every logical partition into a blob column, unions the blobs, and
re-groups — two shuffles plus (de)serialization per group; SURVEY §3.5
calls it "the main perf cliff of the design, and the piece to re-architect
on TPU". Here, zipping device frames just RECORDS the co-partition intent:
``JaxZippedDataFrame`` holds the member frames as-is. ``comap`` then makes
ONE columnar host export per member (the same boundary any host
cotransformer needs anyway) and assembles each key group by dataframe
slicing — no pickle, no blob union, no second shuffle.
"""

from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import pandas as pd
import pyarrow as pa

from fugue_tpu.collections.partition import PartitionSpec
from fugue_tpu.dataframe import (
    ArrayDataFrame,
    ArrowDataFrame,
    DataFrame,
    DataFrames,
    LocalBoundedDataFrame,
    PandasDataFrame,
)
from fugue_tpu.execution.execution_engine import (
    _FUGUE_SER_NO,
    _ZIP_HOW_META,
    _ZIP_NAMES_META,
    _ZIP_SCHEMAS_META,
)
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class JaxZippedDataFrame(DataFrame):
    """A co-partition handle over device frames (not a materializable
    dataframe: its only consumer is :meth:`JaxExecutionEngine.comap`)."""

    def __init__(
        self,
        frames: List[DataFrame],
        names: List[str],
        how: str,
        keys: List[str],
        key_schema: Schema,
        zip_spec: PartitionSpec,
    ):
        # cross zip has no keys; DataFrame refuses an empty schema, so use
        # the serialized path's marker column as a placeholder (the schema
        # of a zipped frame is only ever read for its key columns)
        super().__init__(
            key_schema
            if len(key_schema) > 0
            else Schema([(_FUGUE_SER_NO, "int")])
        )
        self.key_schema = key_schema
        self.frames = frames
        self.names = names
        self.how = how
        self.keys = keys
        self.zip_spec = zip_spec
        self.reset_metadata(
            {
                "serialized": True,
                "device_zipped": True,
                _ZIP_SCHEMAS_META: [str(f.schema) for f in frames],
                _ZIP_NAMES_META: names,
                _ZIP_HOW_META: how,
            }
        )

    @property
    def is_local(self) -> bool:
        return False

    @property
    def is_bounded(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return self.frames[0].num_partitions

    @property
    def empty(self) -> bool:
        return all(f.empty for f in self.frames)

    def count(self) -> int:
        raise NotImplementedError(_ONLY_COMAP)

    def peek_array(self) -> List[Any]:
        raise NotImplementedError(_ONLY_COMAP)

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        raise NotImplementedError(_ONLY_COMAP)

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        raise NotImplementedError(_ONLY_COMAP)

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        raise NotImplementedError(_ONLY_COMAP)

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        raise NotImplementedError(_ONLY_COMAP)

    def _select_cols(self, cols: List[Any]) -> DataFrame:
        raise NotImplementedError(_ONLY_COMAP)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        raise NotImplementedError(_ONLY_COMAP)

    def alter_columns(self, columns: Any) -> DataFrame:
        raise NotImplementedError(_ONLY_COMAP)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        raise NotImplementedError(_ONLY_COMAP)


_ONLY_COMAP = (
    "a device-zipped dataframe only supports comap/cotransform; "
    "set fugue.jax.device_zip=false for the serialized zip path"
)


def _canon_key(vals: Tuple[Any, ...]) -> Tuple[Any, ...]:
    return tuple(None if pd.isna(v) else v for v in vals)


def device_comap(
    engine: Any,
    zdf: JaxZippedDataFrame,
    map_func: Callable,
    output_schema: Any,
    partition_spec: PartitionSpec,
    on_init: Optional[Callable] = None,
) -> DataFrame:
    """Assemble key groups from one columnar export per member and apply
    the cotransformer. Presence rules per zip type mirror the serialized
    runner (execution_engine.py _Comap)."""
    out_schema = Schema(output_schema)
    keys = zdf.keys
    how = zdf.how
    n_members = len(zdf.frames)
    schemas = [f.schema for f in zdf.frames]
    sorts = zdf.zip_spec.presort
    pdfs: List[pd.DataFrame] = []
    for f in zdf.frames:
        pdf = f.as_pandas()
        if sorts:
            cols = [c for c in sorts if c in pdf.columns]
            if cols:
                pdf = pdf.sort_values(
                    cols,
                    ascending=[sorts[c] for c in cols],
                    kind="stable",
                    na_position="first",
                ).reset_index(drop=True)
        pdfs.append(pdf)

    if on_init is not None:
        empty = [ArrayDataFrame([], s) for s in schemas]
        on_init(0, _make_dfs(zdf.names, empty))

    if len(keys) == 0:  # cross zip: one group, whole frames
        frames: List[DataFrame] = [
            PandasDataFrame(pdf, s) for pdf, s in zip(pdfs, schemas)
        ]
        cursor = PartitionSpec().get_cursor(Schema(), 0)
        res = map_func(cursor, _make_dfs(zdf.names, frames))
        return engine.to_df(res)

    groups: List[Dict[Tuple[Any, ...], pd.DataFrame]] = []
    key_order: List[Tuple[Any, ...]] = []
    seen = set()
    for pdf in pdfs:
        g: Dict[Tuple[Any, ...], pd.DataFrame] = {}
        if len(pdf) > 0:
            for kv, sub in pdf.groupby(keys, dropna=False, sort=False):
                ck = _canon_key(kv if isinstance(kv, tuple) else (kv,))
                g[ck] = sub.reset_index(drop=True)
        groups.append(g)
        for ck in g:
            if ck not in seen:
                seen.add(ck)
                key_order.append(ck)

    key_schema = zdf.key_schema
    spec = PartitionSpec(partition_spec, by=keys)
    cursor = spec.get_cursor(key_schema, 0)
    outputs: List[pa.Table] = []
    part_no = 0
    for ck in key_order:
        present = [i for i in range(n_members) if ck in groups[i]]
        if how == "inner" and len(present) < n_members:
            continue
        if how == "left_outer" and 0 not in present:
            continue
        if how == "right_outer" and (n_members - 1) not in present:
            continue
        frames = [
            PandasDataFrame(groups[i][ck], schemas[i])
            if ck in groups[i]
            else ArrayDataFrame([], schemas[i])
            for i in range(n_members)
        ]
        cursor.set(list(ck), part_no, 0)
        part_no += 1
        res = map_func(cursor, _make_dfs(zdf.names, frames))
        table = res.as_arrow() if res.schema == out_schema else None
        if table is None:
            from fugue_tpu.dataframe.arrow_utils import cast_table

            table = cast_table(res.as_arrow(), out_schema)
        outputs.append(table)
    if not outputs:
        return engine.to_df(ArrayDataFrame([], out_schema))
    merged = pa.concat_tables(outputs)
    return engine.to_df(ArrowDataFrame(merged, out_schema))


def _make_dfs(names: List[str], frames: List[DataFrame]) -> DataFrames:
    if any(n != "" for n in names):
        return DataFrames(dict(zip(names, frames)))
    return DataFrames(frames)
