"""Proactive device-memory governance: HBM budget ledger, admission
control, and LRU spill-to-host.

PR 3 made the workflow layer survive a device OOM *after* it happens
(classify ``RESOURCE_EXHAUSTED``, degrade the whole task to the host
tier, retry). This module makes the jax engine avoid the crash in the
first place, the way production dataframe/array runtimes govern memory
(Spark's unified memory manager with storage eviction, Ray's
object-store spilling):

- **Byte ledger** (:class:`MemoryGovernor`): every ingested, persisted,
  or checkpoint-loaded frame's device blocks are registered with their
  REAL footprint (``sum(arr.nbytes)`` over :func:`blocks.residency_arrays`)
  against a per-tier budget. Registration is weakref-based: a dropped
  frame returns its budget the moment its blocks are collected — no
  explicit free calls, no leak on exception paths.
- **Admission control**: placement (``JaxExecutionEngine._place``) asks
  the governor before a frame lands on the device tier. A newcomer whose
  estimated footprint alone exceeds the budget is placed on the host
  tier directly — XLA never gets the chance to throw.
- **Watermark spill**: when an admission would push the device tier past
  ``high_watermark * budget``, the governor first spills LRU *persisted*
  frames to the host tier (their blocks are re-placed on the host mesh
  IN PLACE, so every live reference follows) until usage falls to the
  low watermark, then admits. Only persisted frames spill: transient
  intermediates die with their task and return budget via weakref.
- **Per-device pools**: the device-tier ledger additionally splits every
  frame's bytes evenly over the devices its arrays span. Admission and
  watermark decisions look at the MINIMUM free pool (equivalently, the
  fullest device scaled to mesh-total bytes): HBM is a per-chip
  resource, and one saturated device OOMs the whole mesh-spanning
  allocation no matter how empty its siblings are. While every frame
  spans the full engine mesh the pools stay balanced and the decisions
  reduce byte-identically to the global ledger arithmetic;
  ``snapshot()["device_pools"]`` exposes the split.
- **OOM feedback**: a real ``RESOURCE_EXHAUSTED`` that still slips
  through (engine under-estimate, foreign allocations in the same
  process) feeds the measured allocation size back into the ledger —
  the budget clamps to the observed capacity and pressure is relieved —
  before PR 3's reactive degrade path runs.

Conf keys (see ``constants.py``):

- ``fugue.jax.memory.budget_bytes``: absolute device-tier budget
  (0 = governance off, the default).
- ``fugue.jax.memory.budget_fraction``: fraction of the detected
  per-device memory (``device.memory_stats()['bytes_limit']``) summed
  over the mesh; on backends without memory stats (CPU test meshes) a
  2 GiB/device default applies so fraction-configured tests behave
  deterministically.
- ``fugue.jax.memory.high_watermark`` / ``.low_watermark``: admission
  trigger and spill target as fractions of the budget.

- **Per-tenant accounting** (the serving daemon's fairness plane):
  ledger entries carry an optional *tenant* tag — set for a whole scope
  with :meth:`MemoryGovernor.tenant_scope` (thread-local, so concurrent
  jobs against one shared engine tag independently) or explicitly with
  :meth:`MemoryGovernor.assign_tenant` (how a serve session claims its
  saved tables). When ``fugue.serve.tenant_budget_fraction`` > 0 each
  tenant's fair share is that fraction of the budget and the spiller
  becomes *fair*: victims come first from the tenant currently MOST
  over its share (proportional), LRU within that tenant (recency-aware)
  — so one heavy tenant's persisted tables spill before a light
  tenant's ever do, instead of global LRU letting the heavy newcomer
  evict everyone else. With no tenants recorded (or fraction 0) the
  spiller reduces exactly to the original global LRU order.

Every governance event is observable: ``engine.fallbacks`` counts
``mem_admit_host`` / ``mem_pressure`` / ``mem_spill`` /
``mem_oom_feedback`` (the strategy/fallback counter idiom), and
``engine.memory_stats`` snapshots the full ledger (including the
per-tenant tier breakdown); workflow runs copy the snapshot into
``FugueWorkflowResult.fault_stats["memory"]``.

The ``device.alloc`` fault-injection site (:mod:`fugue_tpu.testing.faults`)
fires in :meth:`MemoryGovernor.pre_alloc` with the placement tier as its
key, so tests simulate a device allocation failure deterministically on
CPU: a spec matching ``"device"`` raises on accelerator-tier staging and
stays silent after the degrade override re-places onto the host tier.
"""

import re
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax
import pyarrow as pa

from fugue_tpu.constants import (
    FUGUE_CONF_JAX_MEMORY_BUDGET_BYTES,
    FUGUE_CONF_JAX_MEMORY_BUDGET_FRACTION,
    FUGUE_CONF_JAX_MEMORY_HIGH_WATERMARK,
    FUGUE_CONF_JAX_MEMORY_LOW_WATERMARK,
    FUGUE_CONF_SERVE_TENANT_BUDGET_FRACTION,
)
from fugue_tpu.jax_backend.blocks import (
    JaxBlocks,
    device_nbytes,
    row_sharding,
)
from fugue_tpu.obs.trace import begin_span, current_span
from fugue_tpu.testing.faults import fault_point
from fugue_tpu.testing.locktrace import tracked_lock

# CPU-backend default when the platform reports no memory stats: tests
# configure budget_fraction against a deterministic synthetic capacity
_DEFAULT_TIER_CAPACITY_PER_DEVICE = 2 * 1024 * 1024 * 1024

_OOM_BYTES_RE = re.compile(r"(?:allocat\w*|of)\s+(\d+)\s*(?:bytes|B)\b", re.I)


def detect_devices_capacity(devices: Any) -> int:
    """Total memory over an iterable of jax devices: each device's
    ``memory_stats()['bytes_limit']`` where the backend reports it
    (TPU/GPU), else the synthetic CPU default per device. Shared by the
    mesh-level detection below and the static analyzer's lint-mode
    ``budget_fraction`` resolution (no engine/mesh exists there)."""
    total = 0
    ndev = 0
    for d in devices:
        ndev += 1
        limit = 0
        try:
            stats = d.memory_stats()
            if stats:
                limit = int(stats.get("bytes_limit", 0))
        except Exception:  # pragma: no cover - backend w/o memory stats
            limit = 0
        total += (
            limit if limit > 0 else _DEFAULT_TIER_CAPACITY_PER_DEVICE
        )
    return total if ndev > 0 else _DEFAULT_TIER_CAPACITY_PER_DEVICE


def detect_tier_capacity(mesh: Any) -> int:
    """Total device-tier memory over the mesh."""
    return detect_devices_capacity(mesh.devices.flat)


def _field_device_width(tp: pa.DataType) -> int:
    """Per-row device bytes of one column after ingest widening: strings
    dictionary-encode to int32 codes, timestamps widen to int64
    microseconds, date32 to int32 days, bool to one byte (arrow packs
    bools 8/byte — an 8x widening), numerics keep their width."""
    if pa.types.is_string(tp) or pa.types.is_large_string(tp):
        return 4
    if pa.types.is_timestamp(tp):
        return 8
    if pa.types.is_date32(tp):
        return 4
    if pa.types.is_boolean(tp):
        return 1
    if pa.types.is_integer(tp) or pa.types.is_floating(tp):
        return tp.bit_width // 8
    return 0  # nested/binary/decimal stay host arrow columns


def estimate_table_device_bytes(table: pa.Table) -> int:
    """Estimated device footprint of ingesting ``table``: per-column
    dtype-widened row widths plus a one-byte validity mask for columns
    that actually carry nulls. A superset-ish bound over the real
    ``device_nbytes`` (exact up to mesh padding), cheap enough to run on
    every admission decision."""
    n = table.num_rows
    total = 0
    for i, field in enumerate(table.schema):
        w = _field_device_width(field.type)
        if w == 0:
            continue
        total += n * w
        if table.column(i).null_count > 0:
            total += n  # bool validity mask
    return total


def estimate_schema_device_bytes(schema: Any, rows: int) -> int:
    """Schema-only variant of :func:`estimate_table_device_bytes` for the
    static analyzer's cost pass: the same dtype-widened per-row widths,
    but from a schema + row count alone (no data, so no per-column null
    masks — a slight under-bound relative to the table estimator)."""
    fields = schema if isinstance(schema, pa.Schema) else getattr(schema, "fields", schema)
    return sum(_field_device_width(f.type) for f in fields) * int(rows)


def move_blocks_to_mesh(blocks: JaxBlocks, mesh: Any) -> bool:
    """Re-place a frame's device arrays onto ``mesh`` IN PLACE (columns
    are shared across derived frames, so every live reference follows
    the move). Returns False when the move is not representable (row
    padding not divisible by the target mesh); when source and target
    mesh are the same object the move is ledger-only.

    The spiller also moves every REGISTERED sibling sharing a column so
    ledger tiers and mesh labels stay consistent; an unregistered
    transient frame derived from a spilled one keeps a stale mesh label
    on a real two-tier engine and may pay one implicit transfer on its
    next op — registered (ingested/persisted) frames never do."""
    if blocks.mesh is mesh:
        return True
    ndev = int(mesh.devices.size)
    for col in blocks.columns.values():
        if col.on_device and int(col.data.shape[0]) % ndev != 0:
            return False
    sharding = row_sharding(mesh)
    for col in blocks.columns.values():
        if not col.on_device:
            continue
        col.data = jax.device_put(col.data, sharding)
        if col.mask is not None:
            col.mask = jax.device_put(col.mask, sharding)
    if blocks.row_valid is not None:
        blocks.row_valid = jax.device_put(blocks.row_valid, sharding)
    if blocks._nrows_dev is not None:
        blocks._nrows_dev = jax.device_put(
            blocks._nrows_dev, mesh.devices.flat[0]
        )
    blocks.mesh = mesh
    # cached factorizations hold old-mesh arrays
    blocks.factorize_cache.clear()
    return True


def parse_oom_bytes(text: str) -> int:
    """Requested allocation size out of an XLA RESOURCE_EXHAUSTED message
    (``... while trying to allocate 123456 bytes ...``), 0 if absent."""
    m = _OOM_BYTES_RE.search(text)
    return int(m.group(1)) if m else 0


class _LedgerEntry:
    __slots__ = (
        "ref", "tier", "nbytes", "seq", "spillable", "tenant", "devices",
    )

    def __init__(
        self,
        ref: Any,
        tier: str,
        nbytes: int,
        seq: int,
        spillable: bool,
        tenant: Optional[str] = None,
        devices: Tuple[int, ...] = (),
    ):
        self.ref = ref
        self.tier = tier
        self.nbytes = nbytes
        self.seq = seq
        self.spillable = spillable
        self.tenant = tenant
        # device ids the frame's row-sharded arrays span: its bytes are
        # charged evenly across these per-device pools while on the
        # device tier
        self.devices = devices


class AllocationGate:
    """One admission ticket for one frame materialization: ``before()``
    runs right before the device arrays are allocated (watermark spill +
    the ``device.alloc`` fault site), ``after(blocks)`` registers the
    REAL footprint in the ledger. Attached by the engine to pending
    frames (``JaxDataFrame._mem_gate``) so lazy ingest pays admission at
    materialization time, when the ledger state is current."""

    __slots__ = ("_gov", "tier", "est", "_t0", "_obs_parent")

    def __init__(self, gov: "MemoryGovernor", tier: str, est: int):
        self._gov = gov
        self.tier = tier
        self.est = est
        self._t0: Any = None
        self._obs_parent: Any = None

    def before(self) -> None:
        # the before→after window IS the host→device (or host-tier)
        # staging of one frame. The span is NOT opened here: gates are
        # shared across derived frames and stay armed after a raised
        # alloc failure (see jax_backend/dataframe.py), so an open span
        # with no guaranteed after() would leak and pin its trace
        # incomplete. Instead the window's start and the ambient span
        # are stamped, and after() emits one BACKDATED engine.transfer
        # span — begin/clobber/abandon all degrade to "no span".
        self._obs_parent = current_span()
        if self._obs_parent is not None:
            self._t0 = time.time_ns()
        self._gov.pre_alloc(self.tier, self.est)

    def after(self, blocks: JaxBlocks) -> None:
        nbytes = self._gov.register(blocks, self.tier)
        # the ledger's real footprint everywhere — the counter and the
        # span must agree with each other and with the spill phase
        measured = (
            int(nbytes) if nbytes is not None else int(device_nbytes(blocks))
        )
        self._gov.note_transfer("ingest", self.tier, measured)
        parent, self._obs_parent = self._obs_parent, None
        t0, self._t0 = self._t0, None
        if parent is not None:
            span = parent.trace.start_span(
                "engine.transfer",
                parent,
                {
                    "phase": "ingest",
                    "tier": self.tier,
                    "est_bytes": int(self.est),
                    "bytes": measured,
                },
            )
            if t0 is not None:
                span.start_ns = t0
            span.finish()


class MemoryGovernor:
    """Per-engine byte ledger + admission controller + LRU spiller.

    Owned by :class:`JaxExecutionEngine`; reads conf lazily at first use
    so engines constructed before conf settles still govern correctly.
    Disabled (the default: no budget configured) every method is a cheap
    no-op except :meth:`pre_alloc`, which always runs the
    ``device.alloc`` fault site so OOM-injection tests work ungoverned.
    """

    def __init__(self, engine: Any):
        self._engine = engine
        self._lock = tracked_lock(
            "jax.memory.MemoryGovernor._lock", reentrant=True
        )
        self._entries: Dict[int, _LedgerEntry] = {}
        self._seq = 0
        self._resolved = False
        self._budget = 0
        self._high = 0.9
        self._low = 0.75
        self._tenant_fraction = 0.0
        # thread-local so concurrent jobs on one shared engine each tag
        # their own registrations (serving daemon: one thread per job)
        self._tenant_local = threading.local()
        self._tier_bytes: Dict[str, int] = {"device": 0, "host": 0}
        self._tier_peak: Dict[str, int] = {"device": 0, "host": 0}
        # per-device pools (device tier only): device id -> charged bytes.
        # A row-sharded frame's footprint splits evenly over the devices
        # it spans; governance decisions look at the FULLEST pool (i.e.
        # the minimum free pool), which reduces exactly to the global
        # ledger arithmetic while every frame spans the whole mesh.
        self._device_bytes: Dict[int, float] = {}
        # cached metric children for the transfer accounting, one per
        # (phase, tier) — see note_transfer
        self._transfer_children: Dict[Tuple[str, str], Any] = {}
        self.counters: Dict[str, int] = {
            "admissions_device": 0,
            "admissions_host": 0,
            "pressure_events": 0,
            "spills": 0,
            "spilled_bytes": 0,
            "oom_feedback": 0,
            "overcommit": 0,
            "devices_retired": 0,
            "frames_marked_lost": 0,
        }

    # ---- configuration ---------------------------------------------------
    def _resolve(self) -> None:
        if self._resolved:
            return
        conf = self._engine.conf
        budget = int(conf.get(FUGUE_CONF_JAX_MEMORY_BUDGET_BYTES, 0))
        if budget <= 0:
            frac = float(
                conf.get(FUGUE_CONF_JAX_MEMORY_BUDGET_FRACTION, 0.0)
            )
            if frac > 0:
                budget = int(frac * detect_tier_capacity(self._engine.mesh))
        self._budget = max(0, budget)
        high = float(conf.get(FUGUE_CONF_JAX_MEMORY_HIGH_WATERMARK, 0.9))
        low = float(conf.get(FUGUE_CONF_JAX_MEMORY_LOW_WATERMARK, 0.75))
        self._high = min(max(high, 0.0), 1.0)
        self._low = min(max(low, 0.0), self._high)
        frac = float(conf.get(FUGUE_CONF_SERVE_TENANT_BUDGET_FRACTION, 0.0))
        self._tenant_fraction = min(max(frac, 0.0), 1.0)
        self._resolved = True

    @property
    def enabled(self) -> bool:
        self._resolve()
        return self._budget > 0

    @property
    def budget_bytes(self) -> int:
        self._resolve()
        return self._budget

    def _count(self, name: str, detail: str = "") -> None:
        counter = getattr(self._engine, "_count_memory_event", None)
        if counter is not None:
            counter(name, detail)

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    # ---- per-device pools ------------------------------------------------
    def _engine_pool_ids(self) -> Tuple[int, ...]:
        """Device ids of the engine's own mesh — the pools admission and
        watermark decisions range over (a frame parked on some other
        mesh's devices still charges ITS devices' pools, but cannot
        relieve pressure here)."""
        mesh = getattr(self._engine, "mesh", None)
        if mesh is None:
            return ()
        return tuple(int(d.id) for d in mesh.devices.flat)

    def _frame_device_ids(self, blocks: JaxBlocks) -> Tuple[int, ...]:
        return tuple(int(d.id) for d in blocks.mesh.devices.flat)

    def _charge_pools_locked(
        self, entry: _LedgerEntry, nbytes: int
    ) -> None:
        """Add (or, negative, remove) one entry's even per-device split."""
        if not entry.devices:
            return
        share = nbytes / len(entry.devices)
        for d in entry.devices:
            self._device_bytes[d] = self._device_bytes.get(d, 0.0) + share

    def _effective_device_usage_locked(self) -> float:
        """Device-tier usage as governance sees it: the fullest pool
        scaled back to mesh-total bytes — i.e. the budget headroom is the
        MINIMUM free pool, so one saturated device gates admission even
        while its siblings sit empty. While every frame spans the whole
        engine mesh the pools are balanced and this returns the exact
        integer global ledger sum (byte-identical legacy decisions)."""
        ids = self._engine_pool_ids()
        if not ids:
            return float(self._tier_bytes["device"])
        pools = [self._device_bytes.get(d, 0.0) for d in ids]
        hi, lo = max(pools), min(pools)
        if hi - lo <= 0.5:  # balanced (float split noise only)
            return float(self._tier_bytes["device"])
        return hi * len(ids)

    # ---- tenants ---------------------------------------------------------
    def tenant_scope(self, tenant: Optional[str]) -> Any:
        """Context manager: registrations on THIS thread inside the scope
        are tagged with ``tenant`` (the serving daemon wraps each job's
        execution so a session's ingests charge its own account).
        Thread-local by design: a parallel inner runner's worker threads
        are NOT covered — durable ownership of anything that outlives a
        job comes from :meth:`assign_tenant` at save time, and untagged
        transients die with the job and return budget via weakref."""
        import contextlib

        @contextlib.contextmanager
        def _scope() -> Any:
            prev = getattr(self._tenant_local, "tenant", None)
            self._tenant_local.tenant = tenant
            try:
                yield self
            finally:
                self._tenant_local.tenant = prev

        return _scope()

    def current_tenant(self) -> Optional[str]:
        return getattr(self._tenant_local, "tenant", None)

    def assign_tenant(self, blocks: JaxBlocks, tenant: Optional[str]) -> None:
        """Claim a REGISTERED frame's bytes for ``tenant`` — how a serve
        session takes ownership of a table it saved. No-op when the frame
        is unregistered (governance off or transient)."""
        if not self.enabled:
            return
        with self._lock:
            e = self._entries.get(id(blocks))
            if e is not None and e.ref() is blocks:
                e.tenant = tenant

    def tenant_usage(self, tenant: Optional[str]) -> Dict[str, int]:
        """Live ledger bytes of one tenant per tier (zeros when absent)."""
        out = {"device": 0, "host": 0}
        with self._lock:
            for e in self._entries.values():
                if e.tenant == tenant and e.ref() is not None:
                    out[e.tier] += e.nbytes
        return out

    def _tenant_share_locked(self) -> int:
        """Each tenant's fair-share bytes (0 = per-tenant fairness off)."""
        return int(self._tenant_fraction * self._budget)

    # ---- admission -------------------------------------------------------
    def gate(self, tier: str, est: int) -> AllocationGate:
        return AllocationGate(self, tier, max(0, int(est)))

    def note_transfer(self, phase: str, tier: str, nbytes: int) -> None:
        """Account one host↔device transfer window on the engine's
        metrics registry (``fugue_engine_transfer_bytes_total``). The
        child is resolved lazily once per (phase, tier) and cached —
        the hot-path cost is one lock + add."""
        key = (phase, tier)
        child = self._transfer_children.get(key)
        if child is None:
            child = self._transfer_children[key] = self._engine.metrics.counter(
                "fugue_engine_transfer_bytes_total",
                "bytes moved through ingest staging and spill windows "
                "per phase and destination tier",
                ["phase", "tier"],
            ).labels(phase=phase, tier=tier)
        child.inc(max(0, int(nbytes)))

    def admit(self, est: int, default_tier: str) -> str:
        """The admission decision for a new frame of estimated footprint
        ``est`` whose placement policy chose ``default_tier``: a
        newcomer that alone exceeds the whole budget goes to the host
        tier directly instead of ever letting XLA throw. (A new frame
        row-shards evenly over the engine mesh, so its per-device share
        vs the per-device pool budget is exactly this comparison scaled
        by the device count; usage-dependent pressure is pre_alloc's
        job, evaluated against the minimum free pool.)"""
        if default_tier != "device" or not self.enabled:
            return default_tier
        with self._lock:
            if est > self._budget:
                self.counters["admissions_host"] += 1
                self._count(
                    "mem_admit_host",
                    f"{est}B exceeds budget {self._budget}B",
                )
                return "host"
            self.counters["admissions_device"] += 1
        return "device"

    def pre_alloc(self, tier: str, est: int) -> None:
        """Right before device arrays are allocated for an admitted
        frame: run the ``device.alloc`` fault site (keyed by tier), then
        spill LRU persisted frames down to the low watermark if this
        allocation would cross the high watermark."""
        fault_point("device.alloc", tier)
        if tier != "device" or not self.enabled:
            return
        with self._lock:
            high = self._high * self._budget
            # the minimum free pool gates admission: usage is the fullest
            # device's pool scaled to mesh-total bytes (== the global sum
            # while every frame spans the whole mesh)
            used = self._effective_device_usage_locked()
            if used + est <= high:
                return
            self.counters["pressure_events"] += 1
            self._count(
                "mem_pressure",
                f"{int(used + est)}B > "
                f"high watermark {int(high)}B",
            )
            target = max(self._low * self._budget - est, 0.0)
            self._spill_down_to_locked(target)
            if self._effective_device_usage_locked() + est > self._budget:
                # nothing left to spill: the allocation proceeds anyway
                # (the reactive OOM path still backstops it) but the
                # overcommit is on the record
                self.counters["overcommit"] += 1

    # ---- ledger ----------------------------------------------------------
    def register(
        self, blocks: JaxBlocks, tier: str, persisted: bool = False
    ) -> Optional[int]:
        """Enter a frame's blocks into the ledger with their REAL device
        footprint. Idempotent: re-registering refreshes recency, the
        persisted flag, and the byte count. Returns the measured bytes
        (None when governance is off — nothing was measured)."""
        if not self.enabled:
            return None
        nbytes = device_nbytes(blocks)
        key = id(blocks)
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None and existing.ref() is blocks:
                existing.seq = self._next_seq()
                existing.spillable = existing.spillable or persisted
                new_devices = self._frame_device_ids(blocks)
                if (
                    existing.nbytes != nbytes
                    or existing.devices != new_devices
                ):
                    # uncharge the old per-device split BEFORE the
                    # devices tuple changes: a frame rebuilt onto a
                    # degraded mesh must stop charging the dead pools
                    if existing.tier == "device":
                        self._charge_pools_locked(existing, -existing.nbytes)
                    self._tier_bytes[existing.tier] += (
                        nbytes - existing.nbytes
                    )
                    existing.nbytes = nbytes
                    existing.devices = new_devices
                    if existing.tier == "device":
                        self._charge_pools_locked(existing, nbytes)
                    self._bump_peak(existing.tier)
                return nbytes
            entry = _LedgerEntry(
                weakref.ref(blocks), tier, nbytes, self._next_seq(),
                persisted, tenant=self.current_tenant(),
                devices=self._frame_device_ids(blocks),
            )
            self._entries[key] = entry
            self._tier_bytes[tier] += nbytes
            if tier == "device":
                self._charge_pools_locked(entry, nbytes)
            self._bump_peak(tier)
        weakref.finalize(blocks, self._release, key, entry)
        return nbytes

    def _bump_peak(self, tier: str) -> None:
        if self._tier_bytes[tier] > self._tier_peak[tier]:
            self._tier_peak[tier] = self._tier_bytes[tier]

    def _release(self, key: int, entry: _LedgerEntry) -> None:
        """Weakref finalizer: a collected frame returns its budget."""
        with self._lock:
            if self._entries.get(key) is entry:
                del self._entries[key]
                self._tier_bytes[entry.tier] -= entry.nbytes
                if entry.tier == "device":
                    self._charge_pools_locked(entry, -entry.nbytes)

    def touch(self, blocks: Optional[JaxBlocks]) -> None:
        """LRU recency update for a frame flowing through an engine op."""
        if blocks is None or not self.enabled:
            return
        with self._lock:
            e = self._entries.get(id(blocks))
            if e is not None and e.ref() is blocks:
                e.seq = self._next_seq()

    def mark_persisted(self, blocks: JaxBlocks) -> None:
        """A persisted frame is pinned in memory by the user on purpose —
        exactly the population the LRU spiller may move to the host tier
        under pressure. Registers the blocks if ingest didn't."""
        if not self.enabled:
            return
        with self._lock:
            e = self._entries.get(id(blocks))
            if e is not None and e.ref() is blocks:
                e.spillable = True
                e.seq = self._next_seq()
                return
        self.register(blocks, self._infer_tier(blocks), persisted=True)

    def _infer_tier(self, blocks: JaxBlocks) -> str:
        host = getattr(self._engine, "host_mesh", None)
        dev = getattr(self._engine, "mesh", None)
        if host is not None and host is not dev and blocks.mesh is host:
            return "host"
        return "device"

    def tier_of(self, blocks: JaxBlocks) -> Optional[str]:
        """The ledger tier of a registered frame's blocks, or None."""
        with self._lock:
            e = self._entries.get(id(blocks))
            return e.tier if e is not None and e.ref() is blocks else None

    def ledger_entries(self) -> List[Tuple[str, int, bool]]:
        """Debug/testing view: (tier, nbytes, spillable) per live entry."""
        with self._lock:
            return [
                (e.tier, e.nbytes, e.spillable)
                for e in self._entries.values()
                if e.ref() is not None
            ]

    def ledger_entries_by_tenant(
        self,
    ) -> List[Tuple[Optional[str], str, int, bool]]:
        """Debug/testing view including the tenant tag:
        (tenant, tier, nbytes, spillable) per live entry."""
        with self._lock:
            return [
                (e.tenant, e.tier, e.nbytes, e.spillable)
                for e in self._entries.values()
                if e.ref() is not None
            ]

    # ---- spill -----------------------------------------------------------
    def _spill_down_to_locked(self, target_bytes: float) -> None:
        """Spill persisted device-tier frames until device usage is at or
        below ``target_bytes`` (or nothing spillable remains). Victim
        order is FAIR when per-tenant shares are configured — the tenant
        currently most over its share pays first, LRU within it — and
        plain global LRU otherwise. Caller holds the lock."""
        host_mesh = getattr(self._engine, "host_mesh", None)
        skipped: set = set()
        while self._effective_device_usage_locked() > target_bytes:
            v = self._pick_victim_locked(skipped)
            if v is None:
                break
            blocks = v.ref()
            if blocks is None or host_mesh is None:  # finalizer reclaims
                skipped.add(id(v))
                continue
            # the span wraps the ACTUAL device→host move: a multi-GB
            # spill's wall clock must land on the transfer phase in the
            # slow-query breakdown, not on whatever span encloses the
            # allocation that triggered it
            sp = begin_span("engine.transfer", phase="spill", tier="host")
            moved = False
            try:
                moved = move_blocks_to_mesh(blocks, host_mesh)
            finally:
                # a raising device_put must not leak the span open (a
                # leaked span pins the whole trace un-exportable)
                if sp:
                    sp.set_attr(bytes=int(v.nbytes), moved=moved)
                    sp.finish()
            if not moved:
                skipped.add(id(v))
                continue
            self._move_entry_locked(v, "host")
            self.counters["spills"] += 1
            self.counters["spilled_bytes"] += v.nbytes
            self.note_transfer("spill", "host", v.nbytes)
            self._count(
                "mem_spill",
                f"{v.nbytes}B to host tier"
                + (f" (tenant {v.tenant})" if v.tenant else ""),
            )
            # derived frames SHARE JaxColumn objects with their source
            # (select/rename/filter build new JaxBlocks over the same
            # columns): their arrays just moved with the spill, so move
            # their remaining arrays (row_valid), mesh label and ledger
            # bytes too — otherwise a sibling keeps a stale device-mesh
            # label over host-resident data and the device tier
            # over-reports forever
            vcols = {id(c) for c in blocks.columns.values()}
            for e in self._entries.values():
                if e is v or e.tier != "device":
                    continue
                sib = e.ref()
                if sib is None or not any(
                    id(c) in vcols for c in sib.columns.values()
                ):
                    continue
                if move_blocks_to_mesh(sib, host_mesh):
                    self._move_entry_locked(e, "host")

    def _pick_victim_locked(self, skipped: set) -> Optional[_LedgerEntry]:
        """Next spill victim. Proportional fairness: while any tenant's
        device usage exceeds its fair share, the MOST-over tenant's LRU
        frame goes first; once every tenant is within its share (or no
        shares are configured) the order is global LRU — identical to the
        pre-tenant behavior."""
        cands = [
            e
            for e in self._entries.values()
            if e.tier == "device" and e.spillable and id(e) not in skipped
        ]
        if not cands:
            return None
        share = self._tenant_share_locked()
        if share > 0:
            usage: Dict[Optional[str], int] = {}
            for e in self._entries.values():
                if e.tier == "device" and e.ref() is not None:
                    usage[e.tenant] = usage.get(e.tenant, 0) + e.nbytes
            over = {
                t: usage[t] / share
                for t in {e.tenant for e in cands}
                if t is not None and usage.get(t, 0) > share
            }
            if over:
                worst = max(over, key=lambda t: over[t])  # type: ignore[arg-type]
                pool = [e for e in cands if e.tenant == worst]
                return min(pool, key=lambda e: e.seq)
        return min(cands, key=lambda e: e.seq)

    def _move_entry_locked(self, entry: _LedgerEntry, tier: str) -> None:
        if entry.tier == tier:
            return
        if entry.tier == "device":
            self._charge_pools_locked(entry, -entry.nbytes)
        self._tier_bytes[entry.tier] -= entry.nbytes
        self._tier_bytes[tier] += entry.nbytes
        entry.tier = tier
        if tier == "device":
            self._charge_pools_locked(entry, entry.nbytes)
        self._bump_peak(tier)

    # ---- device loss -----------------------------------------------------
    def retire_devices(self, lost_ids: Any) -> Dict[str, Any]:
        """A device (or several) died: drop its pool from the ledger and
        mark every device-tier entry spanning it LOST — the frame's
        bytes return to the budget now (its arrays are unreadable, and
        recovery re-registers whatever it rebuilds with the survivors'
        split). Frames still reachable get ``blocks.lost = True`` so a
        later touch fails the owning query instead of dereferencing a
        dead shard. Runs even ungoverned: the lost flag is load-bearing
        for correctness, not just accounting."""
        lost = set(int(i) for i in lost_ids)
        out: Dict[str, Any] = {
            "entries_lost": 0, "bytes_lost": 0, "pools_retired": [],
        }
        with self._lock:
            for e in self._entries.values():
                if e.tier != "device" or not e.devices:
                    continue
                if not lost.intersection(e.devices):
                    continue
                self._charge_pools_locked(e, -e.nbytes)
                self._tier_bytes["device"] -= e.nbytes
                out["entries_lost"] += 1
                out["bytes_lost"] += e.nbytes
                e.nbytes = 0
                e.devices = ()
                blocks = e.ref()
                if blocks is not None:
                    blocks.lost = True
                    self.counters["frames_marked_lost"] += 1
            for d in sorted(lost):
                if d in self._device_bytes:
                    del self._device_bytes[d]
                    out["pools_retired"].append(d)
            self.counters["devices_retired"] += len(lost)
            self._count(
                "mem_device_retired",
                f"devices {sorted(lost)}: {out['entries_lost']} ledger "
                f"entries ({out['bytes_lost']}B) marked lost",
            )
        return out

    # ---- OOM feedback ----------------------------------------------------
    def note_oom(self, ex: BaseException) -> None:
        """A real RESOURCE_EXHAUSTED reached the fault layer: clamp the
        budget to the observed capacity (ledger bytes + the failed
        request) and relieve pressure, so the ledger learns what the
        estimate missed before the reactive degrade/retry re-runs."""
        measured = parse_oom_bytes(str(ex))
        with self._lock:
            self.counters["oom_feedback"] += 1
            self._count(
                "mem_oom_feedback", f"measured {measured}B" if measured else ""
            )
            if not self.enabled:
                return
            observed = self._tier_bytes["device"] + measured
            if 0 < observed < self._budget:
                self._budget = observed
            self._spill_down_to_locked(self._low * self._budget)

    # ---- observability ---------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        self._resolve()
        with self._lock:
            tenants: Dict[str, Dict[str, int]] = {}
            for e in self._entries.values():
                if e.tenant is None or e.ref() is None:
                    continue
                slot = tenants.setdefault(e.tenant, {"device": 0, "host": 0})
                slot[e.tier] += e.nbytes
            ids = self._engine_pool_ids()
            return {
                "enabled": self._budget > 0,
                "budget_bytes": self._budget,
                "per_device_budget_bytes": (
                    self._budget // len(ids) if ids else self._budget
                ),
                "high_watermark": self._high,
                "low_watermark": self._low,
                "tiers": dict(self._tier_bytes),
                "device_pools": {
                    int(d): int(self._device_bytes.get(d, 0.0)) for d in ids
                },
                "peak": dict(self._tier_peak),
                "counters": dict(self.counters),
                "live_frames": sum(
                    1 for e in self._entries.values() if e.ref() is not None
                ),
                "tenant_share_bytes": self._tenant_share_locked(),
                "tenants": tenants,
            }
