"""Adaptive segment-reduction strategy selection.

The engine's group-by (and the join/window count reductions that share
the segment machinery) can run on any of the interchangeable kernels in
``groupby.STRATEGIES``. Which one wins depends on the placement tier and
the shape — measured crossovers (r3/r6):

- CPU meshes (the host placement tier): packed scatter-add, always. The
  (chunk, segments) one-hot transient is pure memory-bandwidth waste on
  CPU (10M rows x 256 segments: 1.28s matmul vs 0.048s scatter).
- Accelerator meshes, small segment counts: one-hot matmul on the MXU
  (scatter serializes there; measured 50x worse at 1024 segments).
- Accelerator meshes, large segment counts: the n*num_segments one-hot
  work dominates; sorting by segment id and scattering with
  ``indices_are_sorted=True`` crosses over.

``choose_strategy`` encodes that table as the prior and sharpens it with
a ONE-SHOT on-device autotune: the first time a (platform, rows-bucket,
segments-bucket, payload-bucket) shape is seen on a mesh, each candidate
kernel runs on a small synthetic probe placed on that mesh's first
device, and the measured winner is cached for the life of the process.
The choice is empirical per mesh, not guessed — a v5e, a v4 and a CPU
relay will each converge to their own table. Autotune is off on CPU
meshes by default (the prior is unambiguous and tier-1 tests run there).
"""

import math
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fugue_tpu.jax_backend.groupby import (
    _MATMUL_MAX_SEGMENTS,
    STRATEGIES,
    segment_sums,
)

# (platform, rows_bucket, segments_bucket, payload_bucket, candidates)
# -> measured winner. Process-lifetime cache: autotune is one-shot per
# mesh shape class, mirroring the persistent XLA compile cache's role.
_TUNE_CACHE: Dict[Tuple, str] = {}
# observability: how many probe sweeps actually ran (tests pin one-shot)
_TUNE_RUNS = {"count": 0}

_PROBE_MAX_ROWS = 1 << 20
_PROBE_MIN_ROWS = 1 << 14
# below this many rows a probe sweep costs more than the op it tunes
_AUTOTUNE_MIN_ROWS = 1 << 22
# below this many padded rows the all-to-all shuffle's ndev-fold padded
# receive costs more than the cross-device combine it removes
_SHUFFLE_MIN_ROWS = 1 << 15


def clear_cache() -> None:
    _TUNE_CACHE.clear()


def _bucket(x: int) -> int:
    """Power-of-two bucket: shapes within 2x share one tuning entry."""
    return 0 if x <= 1 else int(math.ceil(math.log2(x)))


def heuristic_strategy(
    platform: str, num_segments: int, n_payload: int
) -> str:
    """The measured-table prior (used directly when autotune is off or the
    shape is too small to be worth probing)."""
    if platform == "cpu":
        return "scatter"
    if num_segments <= _MATMUL_MAX_SEGMENTS:
        return "matmul"
    return "sort"


def autotune_enabled(
    conf_value: Any, platform: str, rows: int
) -> bool:
    """``fugue.jax.groupby.autotune``: True/False pin it; "auto" (default)
    probes only on accelerator meshes and only for frames large enough
    that one probe sweep amortizes (the CPU prior is unambiguous, and
    tier-1 tests must not pay probe compiles). Unrecognized values raise
    — a misspelled opt-out must not silently keep probing."""
    v = conf_value
    if isinstance(v, str):
        s = v.strip().lower()
        if s in ("true", "1", "always", "on"):
            return True
        if s in ("false", "0", "never", "off"):
            return False
        if s != "auto":
            raise ValueError(
                f"fugue.jax.groupby.autotune={conf_value!r} is not one of "
                "auto/true/false/on/off/always/never"
            )
    elif isinstance(v, (bool, int)):
        return bool(v)
    elif v is not None:
        raise ValueError(
            f"fugue.jax.groupby.autotune={conf_value!r} is not a "
            "bool or auto/true/false string"
        )
    return platform != "cpu" and rows >= _AUTOTUNE_MIN_ROWS


def shuffle_mode(conf_value: Any, conf_key: str) -> str:
    """Normalize a shuffle conf value to ``auto`` / ``on`` / ``off``.
    Shared by ``fugue.jax.shuffle`` and ``fugue.jax.shuffle.overlap``;
    a misspelled opt-out must not silently keep shuffling."""
    v = conf_value
    if isinstance(v, bool):
        return "on" if v else "off"
    if v is None:
        return "auto"
    s = str(v).strip().lower()
    if s in ("true", "1", "always", "on"):
        return "on"
    if s in ("false", "0", "never", "off"):
        return "off"
    if s == "auto":
        return "auto"
    raise ValueError(
        f"{conf_key}={conf_value!r} is not one of auto/on/off"
    )


def choose_shuffle(
    mode: str, mesh: Any, rows: int, num_segments: int
) -> bool:
    """The devices-aware strategy column: should this segment reduction
    repartition rows by key (all-to-all shuffle, shuffle.py) so each
    device reduces only its own segments?

    Single-device meshes never shuffle (there is nothing to co-locate).
    ``on`` forces it on any multi-device mesh; ``auto`` additionally
    requires the frame to be large enough to amortize the padded
    receive and enough segments that every device owns some."""
    ndev = int(mesh.devices.size)
    if mode == "off" or ndev <= 1 or num_segments < 1:
        return False
    if mode == "on":
        return True
    return rows >= _SHUFFLE_MIN_ROWS and num_segments >= 2 * ndev


def choose_overlap(mode: str, mesh: Any, num_segments: int) -> bool:
    """Collective/compute overlap: double-buffer the next key-range's
    all-to-all behind the current range's local reduction. Worth it
    only where collectives are asynchronous (accelerator meshes — CPU
    runs them inline, so the second pass is pure overhead) and when the
    segment space splits into two non-trivial ranges."""
    ndev = int(mesh.devices.size)
    if mode == "off" or ndev <= 1 or num_segments < 2 * ndev:
        return False
    if mode == "on":
        return True
    return mesh.devices.flat[0].platform != "cpu"


def choose_strategy(
    mesh: Any,
    rows: int,
    num_segments: int,
    n_payload: int,
    candidates: Sequence[str],
    autotune_conf: Any = "auto",
    log: Optional[Any] = None,
) -> str:
    """Pick the segment-reduction strategy for one reduction shape.

    ``candidates`` is the caller-filtered eligible subset of STRATEGIES
    (e.g. matmul family removed when exact integer sums are present)."""
    assert len(candidates) > 0
    platform = mesh.devices.flat[0].platform
    prior = heuristic_strategy(platform, num_segments, n_payload)
    if prior not in candidates:
        prior = candidates[0]
    if len(candidates) == 1 or not autotune_enabled(
        autotune_conf, platform, rows
    ):
        return prior
    # the probe row count IS the cache key: probes saturate at
    # _PROBE_MAX_ROWS, so every larger frame shares one entry instead of
    # re-running a byte-identical sweep per rows bucket (review finding).
    # The saturation is a deliberate tradeoff — a 100M-row probe would
    # cost more than the op it tunes; kernel cost is ~linear in rows at
    # fixed (segments, payloads), so the 1M-row ranking carries.
    probe_n = int(min(max(rows, _PROBE_MIN_ROWS), _PROBE_MAX_ROWS))
    key = (
        platform,
        _bucket(probe_n),
        _bucket(num_segments),
        _bucket(n_payload),
        tuple(candidates),
    )
    hit = _TUNE_CACHE.get(key)
    if hit is not None:
        return hit
    winner = _measure(
        mesh, probe_n, num_segments, n_payload, list(candidates), prior, log
    )
    _TUNE_CACHE[key] = winner
    return winner


def _measure(
    mesh: Any,
    n: int,
    num_segments: int,
    n_payload: int,
    candidates: List[str],
    prior: str,
    log: Optional[Any],
) -> str:
    """Time each candidate kernel on an ``n``-row synthetic probe on the
    mesh's first device; best-of-2 after a compile/warm run. Any failure
    (OOM, missing dtype support) falls back to the prior — tuning must
    never break the query."""
    import jax
    import jax.numpy as jnp

    _TUNE_RUNS["count"] += 1
    nf = max(1, n_payload - 1)
    dev = mesh.devices.flat[0]
    try:
        rng = np.random.default_rng(0)
        seg_np = rng.integers(0, max(num_segments, 1), n).astype(np.int32)
        with jax.default_device(dev):
            seg = jnp.asarray(seg_np)
            fpay = [
                jnp.asarray(rng.random(n).astype(np.float32))
                for _ in range(nf)
            ]
            cpay = [jnp.ones((n,), jnp.bool_)]
        best, best_t = prior, float("inf")

        # payloads are jit ARGUMENTS, exactly like the production call
        # sites — closure-captured constants would let XLA fold casts and
        # hoist layouts the real kernels can't, skewing the ranking
        # (review finding)
        def _run(seg_: Any, fpay_: Any, cpay_: Any, strat: str) -> Any:
            f, c, _ = segment_sums(
                fpay_, cpay_, seg_, num_segments, strategy=strat
            )
            return f, c

        for strat in candidates:
            try:
                fn = jax.jit(partial(_run, strat=strat))
                jax.block_until_ready(fn(seg, fpay, cpay))  # compile + warm
                t = float("inf")
                for _ in range(2):
                    t0 = time.perf_counter()
                    jax.block_until_ready(fn(seg, fpay, cpay))
                    t = min(t, time.perf_counter() - t0)
            except Exception:  # pragma: no cover - kernel unsupported
                continue
            if t < best_t:
                best, best_t = strat, t
        if log is not None:
            log.info(
                "fugue_tpu.jax segment-reduction autotune: %s wins at "
                "rows~%d segments=%d payloads=%d on %s (%.2fms)",
                best, n, num_segments, n_payload, dev.platform,
                best_t * 1e3,
            )
        return best
    except Exception:  # pragma: no cover - probe setup failed
        return prior


__all__ = [
    "STRATEGIES",
    "autotune_enabled",
    "choose_overlap",
    "choose_shuffle",
    "choose_strategy",
    "clear_cache",
    "heuristic_strategy",
    "shuffle_mode",
]
