"""Device relational ops: joins and set operations on mesh-sharded blocks.

TPU-first design (replaces the reference's engine-delegated joins,
fugue/execution/execution_engine.py:547-741, which lower to Spark/Dask
shuffles): both sides' key columns are factorized into ONE shared segment
space using the group-by machinery (groupby.py), then

- **semi / anti** are mask-only: flip the left frame's row validity by a
  per-segment occupancy test — no gather, no shuffle, zero host syncs.
- **inner / left / right / full / cross** expand matches with a
  counts -> exclusive-cumsum -> searchsorted enumeration entirely on
  device; ONE host sync reads the output row count (joins change
  cardinality, so a static output shape needs exactly one readback).
- **union** concatenates padded blocks (validity masks make the seam
  invisible); **intersect / subtract** are mask-only occupancy tests over
  a full-row factorization (SQL set-op semantics: NULLs compare equal,
  which the factorizer's null buckets give for free).

String keys join by dictionary code after re-encoding both sides into a
shared dictionary (host work proportional to the dictionaries, not the
data). Null JOIN keys never match (SQL): rows with any null key get the
out-of-range sentinel segment, so every occupancy/count test skips them.
"""

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from fugue_tpu.jax_backend import groupby, shuffle
from fugue_tpu.jax_backend.blocks import (
    JaxBlocks,
    JaxColumn,
    jit_row_sharded,
    on_mesh,
    padded_len,
    row_sharding,
)
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


def _common_dtype(d1: Any, d2: Any) -> Any:
    return jnp.result_type(d1, d2)


def _mesh_scoped(pos: int) -> Any:
    """Run the decorated function under ``on_mesh(args[pos].mesh)`` so its
    EAGER jnp creations (zeros/arange/asarray fed into jitted programs)
    stay on the frame's backend instead of the process default device —
    on a TPU process with host-tier frames the default device is across
    a network link (see blocks.on_mesh)."""
    import functools

    def deco(fn: Any) -> Any:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with on_mesh(args[pos].mesh):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def harmonize_string_keys(
    c1: JaxColumn, c2: JaxColumn, mesh: Any
) -> Tuple[JaxColumn, JaxColumn, np.ndarray]:
    """Re-encode two dictionary columns into one shared dictionary.
    Side 1 keeps its codes (the union dictionary extends side 1's);
    side 2's codes are remapped with one device table-gather (a
    row-sharded jitted program: multihost-safe)."""
    d1, d2 = c1.dictionary, c2.dictionary
    if d1 is d2 or (len(d1) == len(d2) and (d1 == d2).all()):
        return c1, c2, d1
    index1 = {v: i for i, v in enumerate(d1)}
    map2 = np.empty(max(len(d2), 1), dtype=np.int32)
    extra: List[Any] = []
    for i, v in enumerate(d2):
        j = index1.get(v)
        if j is None:
            j = len(d1) + len(extra)
            extra.append(v)
        map2[i] = j
    union = (
        np.concatenate([d1, np.asarray(extra, dtype=object)])
        if extra
        else d1
    )
    p2 = int(c2.data.shape[0])
    hi2 = max(len(d2) - 1, 0)
    remap = jit_row_sharded(
        mesh,
        ("dict_remap", p2, len(map2), hi2),
        lambda m, c: m[jnp.clip(c, 0, hi2)],
    )
    new_codes2 = remap(map2, c2.data)
    hi = max(len(union) - 1, 0)
    out1 = JaxColumn(c1.pa_type, c1.data, c1.mask, union, (0, hi))
    out2 = JaxColumn(c2.pa_type, new_codes2, c2.mask, union, (0, hi))
    return out1, out2, union


def _merged_stats(
    c1: JaxColumn, c2: JaxColumn
) -> Optional[Tuple[int, int]]:
    if c1.stats is None or c2.stats is None:
        return None
    return (min(c1.stats[0], c2.stats[0]), max(c1.stats[1], c2.stats[1]))


@_mesh_scoped(0)
def concat_key_blocks(
    b1: JaxBlocks, b2: JaxBlocks, keys: List[str]
) -> Tuple[JaxBlocks, int, int]:
    """A combined frame holding both sides' key columns stacked along the
    row axis (side 1 rows first). Padding rows of each side stay invalid,
    so no compaction is needed — factorization sees them as non-rows.
    Returns (combined, p1, p2) where p1/p2 are each side's padded length.

    All arrays are built inside ONE row-sharded jitted program
    (multihost-safe: eager concatenates would commit to a process-local
    device and device_put can't reshard across hosts)."""
    mesh = b1.mesh
    p1, p2 = b1.padded_nrows, b2.padded_nrows
    pairs: Dict[str, Tuple[JaxColumn, JaxColumn]] = {}
    for k in keys:
        c1, c2 = b1.columns[k], b2.columns[k]
        if c1.is_string:
            c1, c2, _ = harmonize_string_keys(c1, c2, mesh)
        pairs[k] = (c1, c2)
    dts = {
        k: _common_dtype(c1.data.dtype, c2.data.dtype)
        for k, (c1, c2) in pairs.items()
    }
    masked = tuple(
        sorted(
            k
            for k, (c1, c2) in pairs.items()
            if c1.mask is not None or c2.mask is not None
        )
    )

    def _prog(
        d1: Dict[str, Any],
        d2: Dict[str, Any],
        m1: Dict[str, Any],
        m2: Dict[str, Any],
        rv1: Optional[Any],
        n1: Any,
        rv2: Optional[Any],
        n2: Any,
    ) -> Tuple[Dict[str, Any], Dict[str, Any], Any]:
        data = {
            k: jnp.concatenate(
                [d1[k].astype(dts[k]), d2[k].astype(dts[k])]
            )
            for k in d1
        }
        mask = {
            k: jnp.concatenate(
                [
                    m1.get(k, jnp.ones((p1,), dtype=bool)),
                    m2.get(k, jnp.ones((p2,), dtype=bool)),
                ]
            )
            for k in masked
        }
        v1 = groupby.materialize_validity(rv1, p1, n1)
        v2 = groupby.materialize_validity(rv2, p2, n2)
        return data, mask, jnp.concatenate([v1, v2])

    prog = jit_row_sharded(
        mesh,
        (
            "concat_keys", p1, p2, tuple(sorted(pairs)), masked,
            tuple(str(dts[k]) for k in sorted(dts)),
        ),
        _prog,
    )
    data, mask, row_valid = prog(
        {k: c1.data for k, (c1, _) in pairs.items()},
        {k: c2.data for k, (_, c2) in pairs.items()},
        {k: c1.mask for k, (c1, _) in pairs.items() if c1.mask is not None},
        {k: c2.mask for k, (_, c2) in pairs.items() if c2.mask is not None},
        b1.row_valid,
        _nrows_arg(b1),
        b2.row_valid,
        _nrows_arg(b2),
    )
    cols: Dict[str, JaxColumn] = {}
    for k, (c1, c2) in pairs.items():
        cols[k] = JaxColumn(
            c1.pa_type,
            data[k],
            mask.get(k),
            c1.dictionary,
            _merged_stats(c1, c2),
        )
    combined = JaxBlocks(None, cols, mesh, row_valid=row_valid)
    return combined, p1, p2


class SharedFactorization:
    """Both sides' keys in one segment space."""

    def __init__(
        self,
        seg1: Any,
        seg2: Any,
        num_segments: int,
        b1: JaxBlocks,
        b2: JaxBlocks,
        keys: List[str],
    ):
        self.seg1 = seg1  # int32[p1], sentinel num_segments for non-rows
        self.seg2 = seg2
        self.num_segments = num_segments
        self.b1 = b1
        self.b2 = b2
        self.keys = keys


def shared_factorize(
    b1: JaxBlocks, b2: JaxBlocks, keys: List[str]
) -> SharedFactorization:
    combined, p1, p2 = concat_key_blocks(b1, b2, keys)
    fr = groupby.factorize_keys(combined, keys)
    # split through a row-sharded program: an eager slice of a
    # process-spanning array is not multihost-safe
    split = jit_row_sharded(
        b1.mesh,
        ("seg_split", p1, p2),
        lambda s: (
            jax.lax.slice(s, (0,), (p1,)),
            jax.lax.slice(s, (p1,), (p1 + p2,)),
        ),
    )
    seg1, seg2 = split(fr.seg)
    return SharedFactorization(
        seg1, seg2, fr.num_segments, b1, b2, keys
    )


def _null_any_mask(b: JaxBlocks, keys: List[str]) -> Optional[Any]:
    """True where ANY key is null (such rows never match in a JOIN)."""
    masks = [
        b.columns[k].mask for k in keys if b.columns[k].mask is not None
    ]
    if not masks:
        return None
    nn = masks[0]
    for m in masks[1:]:
        nn = nn & m
    return ~nn


def device_joinable(
    b1: JaxBlocks, b2: JaxBlocks, names1: List[str], names2: List[str]
) -> bool:
    return all(
        n in b1.columns and b1.columns[n].on_device for n in names1
    ) and all(n in b2.columns and b2.columns[n].on_device for n in names2)


# ---------------------------------------------------------------------------
# semi / anti: mask-only
# ---------------------------------------------------------------------------


@_mesh_scoped(1)
def semi_anti_join(
    engine: Any, b1: JaxBlocks, b2: JaxBlocks, keys: List[str], anti: bool
) -> JaxBlocks:
    sf = shared_factorize(b1, b2, keys)
    S = max(sf.num_segments, 1)
    null1 = _null_any_mask(b1, keys)
    null2 = _null_any_mask(b2, keys)
    p1 = b1.padded_nrows
    # join-side count reductions share the group-by strategy layer
    strat = engine._count_reduce_strategy(b1, S)

    def _prog(
        seg1: Any,
        seg2: Any,
        v2: Any,
        n2m: Optional[Any],
        rv1: Optional[Any],
        n1m: Optional[Any],
        nrows1: Any,
    ) -> Tuple[Any, Any]:
        valid1 = groupby.materialize_validity(rv1, p1, nrows1)
        match2 = v2 if n2m is None else (v2 & ~n2m)
        # out-of-range seg ids contribute nothing on any strategy
        c2 = groupby.segment_count(
            match2, jnp.where(match2, seg2, S), S, strat
        )
        hit = c2[jnp.clip(seg1, 0, S - 1)] > 0
        matchable1 = valid1 if n1m is None else (valid1 & ~n1m)
        if anti:
            keep = valid1 & (~matchable1 | ~hit)
        else:
            keep = matchable1 & hit
        return keep, jnp.sum(keep).astype(jnp.int32)

    keep, cnt = engine._jit_cached(
        ("semi_anti", anti, S, p1, b2.padded_nrows, tuple(keys), strat),
        _prog,
    )(
        sf.seg1,
        sf.seg2,
        b2.validity(),
        null2,
        b1.row_valid,
        null1,
        _nrows_arg(b1),
    )
    return JaxBlocks(
        None, dict(b1.columns), b1.mesh, row_valid=keep, nrows_dev=cnt
    )


@_mesh_scoped(1)
def not_in_join(
    engine: Any, b1: JaxBlocks, b2: JaxBlocks, keys: List[str]
) -> JaxBlocks:
    """``WHERE x NOT IN (SELECT y ...)`` as a mask-only device op with
    SQL's three-valued semantics (the host oracle:
    select_runner._in_subquery): an EMPTY right side keeps every row
    (even a NULL x); ANY null right value keeps none (the comparison is
    never TRUE); otherwise keep non-null, non-matching rows. Zero host
    syncs — the count stays lazy like semi/anti."""
    sf = shared_factorize(b1, b2, keys)
    S = max(sf.num_segments, 1)
    null1 = _null_any_mask(b1, keys)
    null2 = _null_any_mask(b2, keys)
    p1 = b1.padded_nrows
    strat = engine._count_reduce_strategy(b1, S)

    def _prog(
        seg1: Any,
        seg2: Any,
        v2: Any,
        n2m: Optional[Any],
        rv1: Optional[Any],
        n1m: Optional[Any],
        nrows1: Any,
    ) -> Tuple[Any, Any]:
        valid1 = groupby.materialize_validity(rv1, p1, nrows1)
        empty2 = jnp.sum(v2.astype(jnp.int32)) == 0
        if n2m is None:
            any_null2 = jnp.asarray(False)
            match2 = v2
        else:
            any_null2 = jnp.sum((v2 & n2m).astype(jnp.int32)) > 0
            match2 = v2 & ~n2m
        c2 = groupby.segment_count(
            match2, jnp.where(match2, seg2, S), S, strat
        )
        hit = c2[jnp.clip(seg1, 0, S - 1)] > 0
        notnull1 = valid1 if n1m is None else (valid1 & ~n1m)
        keep = valid1 & (empty2 | (notnull1 & ~any_null2 & ~hit))
        return keep, jnp.sum(keep).astype(jnp.int32)

    keep, cnt = engine._jit_cached(
        ("not_in", S, p1, b2.padded_nrows, tuple(keys), strat), _prog
    )(
        sf.seg1,
        sf.seg2,
        b2.validity(),
        null2,
        b1.row_valid,
        null1,
        _nrows_arg(b1),
    )
    return JaxBlocks(
        None, dict(b1.columns), b1.mesh, row_valid=keep, nrows_dev=cnt
    )


# ---------------------------------------------------------------------------
# inner / left_outer (right/full build on these)
# ---------------------------------------------------------------------------


@_mesh_scoped(1)
def expand_join(
    engine: Any,
    b1: JaxBlocks,
    b2: JaxBlocks,
    keys: List[str],
    how: str,  # "inner" | "leftouter" | "fullouter" | "cross"
    schema1: Schema,
    schema2: Schema,
    out_schema: Schema,
) -> JaxBlocks:
    """Match-enumerating join. Phase 1 (device): per-left-row match counts
    and the sorted-by-segment ordering of the right side. One host sync
    reads the output size(s). Phase 2 (device): enumerate output rows by
    searchsorted over the exclusive cumsum, gather both sides."""
    mesh = b1.mesh
    p1, p2 = b1.padded_nrows, b2.padded_nrows
    is_cross = how == "cross"
    if is_cross:
        S = 1
        seg1 = jnp.zeros((p1,), dtype=jnp.int32)
        seg2 = jnp.zeros((p2,), dtype=jnp.int32)
        null1 = null2 = None
    else:
        sf = shared_factorize(b1, b2, keys)
        S, seg1, seg2 = sf.num_segments, sf.seg1, sf.seg2
        null1 = _null_any_mask(b1, keys)
        null2 = _null_any_mask(b2, keys)
    S = max(S, 1)
    outer_left = how in ("leftouter", "fullouter")
    if (
        how in ("inner", "leftouter")
        and len(keys) == 1
        and b2.columns[keys[0]].unique
    ):
        # each left row matches AT MOST ONE right row (host-proven at
        # ingest): no expansion, no output-cardinality readback — the
        # output is the left frame with right columns gathered in and a
        # validity mask. ZERO host syncs (the general path's one count
        # sync costs a full relay round trip on network-attached TPUs).
        return _unique_right_join(
            engine, b1, b2, how, S, seg1, seg2, null1, null2,
            schema1, schema2, out_schema,
        )

    # per-side match counts share the group-by strategy layer (matmul on
    # accelerator tiers below the segment cap, scatter otherwise); on
    # multi-device meshes the shuffle column of the strategy decision
    # runs them as a map-side combine: each device counts its own rows
    # and one reduce-scatter-layout all-to-all of partial counts gives
    # every device its own segment range
    strat = engine._count_reduce_strategy(b1, S)
    shuf = not is_cross and engine._join_shuffle(mesh, max(p1, p2), S)

    def _count_prog(
        seg1_: Any,
        seg2_: Any,
        rv1: Optional[Any],
        n1: Any,
        v2: Any,
        n1m: Optional[Any],
        n2m: Optional[Any],
    ) -> Tuple[Any, Any, Any, Any, Any, Any, Any]:
        valid1 = groupby.materialize_validity(rv1, p1, n1)
        match2 = v2 if n2m is None else (v2 & ~n2m)
        seg2s = jnp.where(match2, seg2_, S)
        # right-side metadata (per-segment counts, exclusive starts,
        # grouped order: stable, non-rows last). Multi-device shuffle:
        # GSPMD replicates a global argsort onto every device; the fused
        # local-sort + one-all-gather construction yields the identical
        # enumeration with only local sorts and ONE partial-counts
        # exchange feeding counts, starts and order alike
        if shuf:
            c2, cstart2, order2 = shuffle.sharded_grouped_order(
                mesh, seg2s, S
            )
        else:
            c2 = groupby.segment_count(match2, seg2s, S, strat)
            cstart2 = shuffle.sharded_cumsum(mesh, c2) - c2
            order2, _ = shuffle.grouped_sort(seg2s, S, p2)
        matchable1 = valid1 if n1m is None else (valid1 & ~n1m)
        m = jnp.where(matchable1, c2[jnp.clip(seg1_, 0, S - 1)], 0)
        reps = jnp.where(
            valid1, jnp.maximum(m, 1) if outer_left else m, 0
        )
        total = jnp.sum(reps)
        # sharded-axis prefix sum rides the two-level scan: GSPMD's own
        # cumsum partitioning serializes across devices (see
        # shuffle.sharded_cumsum)
        start = shuffle.sharded_cumsum(mesh, reps) - reps
        if how != "fullouter":
            # the right-unmatched tail exists only for full outer — an
            # O(p1) segment_sum the other join types shouldn't pay
            zero = jnp.zeros((), jnp.int32)
            return m, start, order2, cstart2, total, zero, order2
        seg1s = jnp.where(matchable1, seg1_, S)
        c1 = (
            shuffle.preagg_segment_count(mesh, matchable1, seg1s, S, strat)
            if shuf
            else groupby.segment_count(matchable1, seg1s, S, strat)
        )
        un2 = v2 & (
            ~match2 | (c1[jnp.clip(seg2_, 0, S - 1)] == 0)
        )
        r_total = jnp.sum(un2.astype(jnp.int32))
        order_un2 = jnp.argsort(~un2, stable=True).astype(jnp.int32)
        return m, start, order2, cstart2, total, r_total, order_un2

    t0 = time.perf_counter() if shuf else 0.0
    m, start, order2, cstart2, total, r_total, order_un2 = engine._jit_cached(
        ("join_count", how, S, p1, p2, tuple(keys), strat, shuf), _count_prog
    )(
        seg1,
        seg2,
        b1.row_valid,
        _nrows_arg(b1),
        b2.validity(),
        null1,
        null2,
    )
    if shuf:
        # join counts are combinable: they ride the map-side-combine
        # exchange (i32 partial counts), not the row shuffle
        ndev_ = int(mesh.devices.size)
        nbytes = shuffle.estimate_preagg_bytes(S, ndev_, 4)
        if how == "fullouter":
            nbytes *= 2
        engine._count_shuffle("join", nbytes, time.perf_counter() - t0, False)
    # THE one host sync of the join: output cardinality
    M = int(total)
    R = int(r_total) if how == "fullouter" else 0
    ndev = int(mesh.devices.size)
    out_pad = padded_len(M, ndev)
    sharding = row_sharding(mesh)

    d1 = {n: b1.columns[n] for n in schema1.names}
    other2 = [n for n in schema2.names if n not in schema1.names]
    d2 = {n: b2.columns[n] for n in other2}
    # harmonize output string columns BEFORE gathering so full-outer's
    # appended right rows share dictionaries (keys only; non-key columns
    # come from exactly one side)
    key_cols2: Dict[str, JaxColumn] = {}
    if how == "fullouter":
        for k in keys:
            c1h, c2h, _ = (
                harmonize_string_keys(d1[k], b2.columns[k], mesh)
                if d1[k].is_string
                else (d1[k], b2.columns[k], None)
            )
            d1[k] = c1h
            key_cols2[k] = c2h

    # expansion index algorithm: scatter marks at each left row's start
    # offset, then cumsum. This beats searchsorted ~7x on BOTH backends
    # (CPU: 417ms vs 57ms at 5M; TPU: 69ms vs 492ms — binary search over
    # 5M boundaries serializes into log(n) dependent gather passes, while
    # scatter+scan is two streaming sweeps)

    def _gather_prog(
        datas1: Dict[str, Any],
        masks1: Dict[str, Any],
        datas2: Dict[str, Any],
        masks2: Dict[str, Any],
        m_: Any,
        start_: Any,
        order2_: Any,
        cstart2_: Any,
        seg1_: Any,
    ) -> Tuple[Dict[str, Any], Dict[str, Any], Dict[str, Any], Dict[str, Any], Any]:
        t = jnp.arange(out_pad, dtype=jnp.int32)
        if int(mesh.devices.size) > 1:
            # the scatter+scan's GSPMD partitioning all-reduces full
            # output copies; per-shard binary search is collective-free
            i = shuffle.sharded_expand_rows(mesh, start_, out_pad)
        else:
            # rows with zero matches scatter onto the NEXT row's start
            # (same offset), so the duplicate marks accumulate and
            # cumsum skips them — "drop" discards starts beyond the
            # output (tail rows with zero matches)
            marks = jnp.zeros((out_pad,), jnp.int32).at[start_].add(
                1, mode="drop"
            )
            i = jnp.cumsum(marks) - 1
        i = jnp.clip(i, 0, p1 - 1)
        j_local = t - start_[i]
        matched = j_local < m_[i]
        s = jnp.clip(seg1_[i], 0, S - 1)
        rpos = jnp.clip(cstart2_[s] + j_local, 0, p2 - 1)
        ridx = order2_[rpos]
        out1 = {k: v[i] for k, v in datas1.items()}
        om1 = {k: v[i] for k, v in masks1.items()}
        out2 = {k: v[ridx] for k, v in datas2.items()}
        om2 = {k: v[ridx] & matched for k, v in masks2.items()}
        for k in datas2:
            if k not in om2:
                om2[k] = matched
        return out1, om1, out2, om2, matched

    g1, gm1, g2, gm2, _matched = engine._jit_cached(
        (
            "join_gather",
            how,
            S,
            p1,
            p2,
            out_pad,
            tuple(sorted(d1)),
            tuple(sorted(d2)),
            tuple(sorted(n for n, c in d1.items() if c.mask is not None)),
            tuple(sorted(n for n, c in d2.items() if c.mask is not None)),
        ),
        _gather_prog,
    )(
        {n: c.data for n, c in d1.items()},
        {n: c.mask for n, c in d1.items() if c.mask is not None},
        {n: c.data for n, c in d2.items()},
        {n: c.mask for n, c in d2.items() if c.mask is not None},
        m,
        start,
        order2,
        cstart2,
        seg1,
    )
    cols: Dict[str, JaxColumn] = {}
    for f in out_schema.fields:
        n = f.name
        if n in g1:
            src, data, mask = d1[n], g1[n], gm1.get(n)
        else:
            src, data, mask = d2[n], g2[n], gm2.get(n)
        cols[n] = JaxColumn(
            f.type,
            jax.device_put(data, sharding),
            None if mask is None else jax.device_put(mask, sharding),
            src.dictionary,
            src.stats,
        )
    out = JaxBlocks(M, cols, mesh)
    if how == "fullouter" and R > 0:
        right_part = _gather_right_unmatched(
            engine, b1, b2, keys, key_cols2, order_un2, R, out_schema
        )
        out = union_all_blocks(out, right_part)
    return out


def _unique_right_join(
    engine: Any,
    b1: JaxBlocks,
    b2: JaxBlocks,
    how: str,  # "inner" | "leftouter"
    S: int,
    seg1: Any,
    seg2: Any,
    null1: Optional[Any],
    null2: Optional[Any],
    schema1: Schema,
    schema2: Schema,
    out_schema: Schema,
) -> JaxBlocks:
    """Join against a right side whose (single) key is host-proven
    unique: one program scatters each right row's position into its
    segment slot, gathers right columns by the left rows' segments, and
    flips validity — left columns pass through UNTOUCHED (stats, dicts
    and uniqueness intact), the row count stays lazy."""
    mesh = b1.mesh
    p1, p2 = b1.padded_nrows, b2.padded_nrows
    sharding = row_sharding(mesh)
    other2 = [n for n in schema2.names if n not in schema1.names]
    d2 = {n: b2.columns[n] for n in other2}
    inner = how == "inner"

    def _prog(
        seg1_: Any,
        seg2_: Any,
        rv1: Optional[Any],
        n1: Any,
        v2: Any,
        n1m: Optional[Any],
        n2m: Optional[Any],
        datas2: Dict[str, Any],
        masks2: Dict[str, Any],
    ) -> Tuple[Dict[str, Any], Dict[str, Any], Any, Any]:
        valid1 = groupby.materialize_validity(rv1, p1, n1)
        match2 = v2 if n2m is None else (v2 & ~n2m)
        pos2 = (
            jnp.full((S,), -1, dtype=jnp.int32)
            .at[jnp.where(match2, seg2_, S)]
            .max(jnp.arange(p2, dtype=jnp.int32), mode="drop")
        )
        matchable1 = valid1 if n1m is None else (valid1 & ~n1m)
        r = pos2[jnp.clip(seg1_, 0, S - 1)]
        matched = matchable1 & (r >= 0)
        ridx = jnp.clip(r, 0, p2 - 1)
        out2 = {k: v[ridx] for k, v in datas2.items()}
        om2 = {k: v[ridx] & matched for k, v in masks2.items()}
        for k in datas2:
            if k not in om2:
                om2[k] = matched
        keep = matched if inner else valid1
        return out2, om2, keep, jnp.sum(keep).astype(jnp.int32)

    g2, gm2, keep, cnt = engine._jit_cached(
        (
            "join_unique_right",
            how,
            S,
            p1,
            p2,
            tuple(sorted(d2)),
            tuple(sorted(n for n, c in d2.items() if c.mask is not None)),
        ),
        _prog,
    )(
        seg1,
        seg2,
        b1.row_valid,
        _nrows_arg(b1),
        b2.validity(),
        null1,
        null2,
        {n: c.data for n, c in d2.items()},
        {n: c.mask for n, c in d2.items() if c.mask is not None},
    )
    cols: Dict[str, JaxColumn] = {}
    for f in out_schema.fields:
        n = f.name
        if n in g2:
            src = d2[n]
            cols[n] = JaxColumn(
                f.type,
                jax.device_put(g2[n], sharding),
                jax.device_put(gm2[n], sharding),
                src.dictionary,
                src.stats,
            )
        else:
            src = b1.columns[n]
            cols[n] = JaxColumn(
                f.type, src.data, src.mask, src.dictionary, src.stats,
                unique=src.unique,
            )
    return JaxBlocks(
        None, cols, mesh, row_valid=keep, nrows_dev=cnt
    )


@_mesh_scoped(1)
def _gather_right_unmatched(
    engine: Any,
    b1: JaxBlocks,
    b2: JaxBlocks,
    keys: List[str],
    key_cols2: Dict[str, JaxColumn],
    order_un2: Any,
    R: int,
    out_schema: Schema,
) -> JaxBlocks:
    """Full-outer tail: df2 rows with no df1 match; df1-only columns NULL.
    Key columns take df2's values (already dictionary-harmonized)."""
    mesh = b2.mesh
    ndev = int(mesh.devices.size)
    out_pad = padded_len(R, ndev)
    sharding = row_sharding(mesh)
    src_cols: Dict[str, JaxColumn] = {}
    left_only: List[str] = []
    for f in out_schema.fields:
        n = f.name
        if n in keys:
            src_cols[n] = key_cols2.get(n, b2.columns[n])
        elif n in b2.columns and n not in b1.columns:
            src_cols[n] = b2.columns[n]
        else:
            left_only.append(n)

    def _prog(
        datas: Dict[str, Any], masks: Dict[str, Any], order_: Any
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        idx = order_[
            jnp.clip(
                jnp.arange(out_pad, dtype=jnp.int32),
                0,
                order_.shape[0] - 1,
            )
        ]
        return (
            {k: v[idx] for k, v in datas.items()},
            {k: v[idx] for k, v in masks.items()},
        )

    g, gm = engine._jit_cached(
        (
            "join_right_tail",
            out_pad,
            b2.padded_nrows,
            tuple(sorted(src_cols)),
            tuple(
                sorted(
                    n for n, c in src_cols.items() if c.mask is not None
                )
            ),
        ),
        _prog,
    )(
        {n: c.data for n, c in src_cols.items()},
        {n: c.mask for n, c in src_cols.items() if c.mask is not None},
        order_un2,
    )
    cols: Dict[str, JaxColumn] = {}
    for f in out_schema.fields:
        n = f.name
        if n in src_cols:
            src = src_cols[n]
            cols[n] = JaxColumn(
                f.type,
                jax.device_put(g[n], sharding),
                None if n not in gm else jax.device_put(gm[n], sharding),
                src.dictionary,
                src.stats,
            )
        else:
            # left-only column: all NULL
            dt = _null_device_dtype(f.type)
            cols[n] = JaxColumn(
                f.type,
                jax.device_put(jnp.zeros((out_pad,), dtype=dt), sharding),
                jax.device_put(
                    jnp.zeros((out_pad,), dtype=bool), sharding
                ),
                np.asarray([], dtype=object) if _is_str(f.type) else None,
                None,
            )
    return JaxBlocks(R, cols, mesh)


def _is_str(tp: pa.DataType) -> bool:
    return pa.types.is_string(tp) or pa.types.is_large_string(tp)


def _null_device_dtype(tp: pa.DataType) -> Any:
    if _is_str(tp):
        return jnp.int32
    if pa.types.is_timestamp(tp):
        return jnp.int64
    if pa.types.is_date32(tp):
        return jnp.int32
    if pa.types.is_boolean(tp):
        return jnp.bool_
    return tp.to_pandas_dtype()


# ---------------------------------------------------------------------------
# set operations
# ---------------------------------------------------------------------------


@_mesh_scoped(0)
def repartition_by_key(
    engine: Any, blocks: JaxBlocks, keys: List[str]
) -> Optional[JaxBlocks]:
    """Explicit shuffle repartition: materialize a copy of ``blocks``
    where every valid row lives on device ``segment(keys) % ndev``, via
    ONE padded all-to-all (shuffle.shuffle_rows). Joins, group-bys and
    distincts on the same keys then reduce purely device-locally —
    matching keys are co-located per shard.

    Row count, column dtypes, dictionaries and stats are preserved; only
    placement and padded length change (the receive is padded to
    ``ndev * padded_nrows``). Returns None when there is nothing to
    co-locate (single-device mesh) or the frame is not fully on device —
    callers fall back to the unshuffled frame."""
    mesh = blocks.mesh
    ndev = int(mesh.devices.size)
    if ndev <= 1 or not blocks.all_on_device:
        return None
    for k in keys:
        if k not in blocks.columns:
            return None
    fr = groupby.factorize_keys(blocks, keys)
    pad_n = blocks.padded_nrows
    names = sorted(blocks.columns)
    mask_names = tuple(
        n for n in names if blocks.columns[n].mask is not None
    )

    def _prog(
        seg_: Any,
        row_valid: Optional[Any],
        nrows_s: Any,
        datas_: Dict[str, Any],
        masks_: Dict[str, Any],
    ) -> Dict[str, Any]:
        valid_ = groupby.materialize_validity(row_valid, pad_n, nrows_s)
        arrays: Dict[str, Any] = {}
        for n in names:
            arrays[f"d:{n}"] = datas_[n]
        for n in mask_names:
            arrays[f"m:{n}"] = masks_[n]
        _, marker, out = shuffle.shuffle_rows(mesh, seg_, valid_, arrays)
        out["_valid"] = marker
        return out

    dtypes = tuple(str(blocks.columns[n].data.dtype) for n in names)
    t0 = time.perf_counter()
    outs = engine._jit_cached(
        ("repartition", tuple(names), mask_names, dtypes, tuple(keys),
         pad_n, ndev),
        _prog,
    )(
        fr.seg,
        blocks.row_valid,
        _nrows_arg(blocks),
        {n: blocks.columns[n].data for n in names},
        {n: blocks.columns[n].mask for n in mask_names},
    )
    widths = sum(
        blocks.columns[n].data.dtype.itemsize for n in names
    ) + len(mask_names)
    engine._count_shuffle(
        "repartition",
        shuffle.estimate_shuffle_bytes(pad_n, ndev, widths),
        time.perf_counter() - t0,
        False,
    )
    sharding = row_sharding(mesh)
    out_cols: Dict[str, JaxColumn] = {}
    for n in names:
        src = blocks.columns[n]
        out_cols[n] = JaxColumn(
            src.pa_type,
            jax.device_put(outs[f"d:{n}"], sharding),
            jax.device_put(outs[f"m:{n}"], sharding)
            if n in mask_names
            else None,
            src.dictionary,
            src.stats,
        )
    return JaxBlocks(
        blocks._nrows,
        out_cols,
        mesh,
        row_valid=jax.device_put(outs["_valid"], sharding),
        nrows_dev=blocks._nrows_dev,
    )


def union_all_blocks(b1: JaxBlocks, b2: JaxBlocks) -> JaxBlocks:
    """Concatenate two frames along the row axis. Padding rows of each side
    remain invalid under the combined mask — no compaction, no sync. All
    arrays come from one row-sharded jitted program (multihost-safe —
    see concat_key_blocks)."""
    mesh = b1.mesh
    p1, p2 = b1.padded_nrows, b2.padded_nrows
    pairs: Dict[str, Tuple[JaxColumn, JaxColumn]] = {}
    for n, c1 in b1.columns.items():
        c2 = b2.columns[n]
        if c1.is_string:
            c1, c2, _ = harmonize_string_keys(c1, c2, mesh)
        pairs[n] = (c1, c2)
    dts = {
        n: _common_dtype(c1.data.dtype, c2.data.dtype)
        for n, (c1, c2) in pairs.items()
    }
    masked = tuple(
        sorted(
            n
            for n, (c1, c2) in pairs.items()
            if c1.mask is not None or c2.mask is not None
        )
    )

    def _prog(
        d1: Dict[str, Any],
        d2: Dict[str, Any],
        m1: Dict[str, Any],
        m2: Dict[str, Any],
        rv1: Optional[Any],
        n1: Any,
        rv2: Optional[Any],
        n2: Any,
    ) -> Tuple[Dict[str, Any], Dict[str, Any], Any]:
        data = {
            n: jnp.concatenate(
                [d1[n].astype(dts[n]), d2[n].astype(dts[n])]
            )
            for n in d1
        }
        mask = {
            n: jnp.concatenate(
                [
                    m1.get(n, jnp.ones((p1,), dtype=bool)),
                    m2.get(n, jnp.ones((p2,), dtype=bool)),
                ]
            )
            for n in masked
        }
        v1 = groupby.materialize_validity(rv1, p1, n1)
        v2 = groupby.materialize_validity(rv2, p2, n2)
        return data, mask, jnp.concatenate([v1, v2])

    prog = jit_row_sharded(
        mesh,
        (
            "union_all", p1, p2, tuple(sorted(pairs)), masked,
            tuple(str(dts[n]) for n in sorted(dts)),
        ),
        _prog,
    )
    data, mask, row_valid = prog(
        {n: c1.data for n, (c1, _) in pairs.items()},
        {n: c2.data for n, (_, c2) in pairs.items()},
        {n: c1.mask for n, (c1, _) in pairs.items() if c1.mask is not None},
        {n: c2.mask for n, (_, c2) in pairs.items() if c2.mask is not None},
        b1.row_valid,
        _nrows_arg(b1),
        b2.row_valid,
        _nrows_arg(b2),
    )
    cols: Dict[str, JaxColumn] = {}
    for n, (c1, c2) in pairs.items():
        cols[n] = JaxColumn(
            c1.pa_type,
            data[n],
            mask.get(n),
            c1.dictionary,
            _merged_stats(c1, c2),
        )
    nrows = (
        b1._nrows + b2._nrows
        if b1.nrows_known and b2.nrows_known
        else None
    )
    nrows_dev = None
    if nrows is None:
        nrows_dev = b1.nrows_scalar + b2.nrows_scalar
    return JaxBlocks(
        nrows, cols, mesh, row_valid=row_valid, nrows_dev=nrows_dev
    )


@_mesh_scoped(1)
def intersect_subtract(
    engine: Any,
    b1: JaxBlocks,
    b2: JaxBlocks,
    names: List[str],
    subtract: bool,
    distinct: bool = True,
) -> JaxBlocks:
    """INTERSECT / EXCEPT: keep df1 rows whose full-row key {is, is not}
    present in df2 — first occurrence only when ``distinct``; multiset
    (... ALL) semantics otherwise: EXCEPT ALL keeps each row whose
    occurrence ordinal within its key is >= df2's count of that key,
    INTERSECT ALL those below it. Mask-only; NULLs compare equal (null
    buckets)."""
    sf = shared_factorize(b1, b2, names)
    S = max(sf.num_segments, 1)
    p1 = b1.padded_nrows
    # S + 1: the multiset branch reduces over the sentinel bucket too —
    # the selector must see the LARGEST segment count the program uses
    strat = engine._count_reduce_strategy(b1, S + 1)

    def _prog(
        seg1: Any,
        seg2: Any,
        rv1: Optional[Any],
        n1: Any,
        v2: Any,
    ) -> Tuple[Any, Any]:
        valid1 = groupby.materialize_validity(rv1, p1, n1)
        c2 = groupby.segment_count(v2, jnp.where(v2, seg2, S), S, strat)
        pos = jnp.arange(p1, dtype=jnp.int32)
        if distinct:
            hit = c2[jnp.clip(seg1, 0, S - 1)] > 0
            present = valid1 & (~hit if subtract else hit)
            # first occurrence among the kept df1 rows
            firsts = jax.ops.segment_min(
                jnp.where(present, pos, p1),
                jnp.where(present, seg1, S),
                num_segments=S,
            )
            keep = present & (firsts[jnp.clip(seg1, 0, S - 1)] == pos)
            return keep, jnp.sum(keep).astype(jnp.int32)
        # multiset: occurrence ordinal per key via a segment-sorted scan
        segv1 = jnp.where(valid1, seg1, S)
        order = jnp.argsort(segv1, stable=True)
        c1 = groupby.segment_count(valid1, segv1, S + 1, strat)[:S]
        starts = shuffle.sharded_cumsum(b1.mesh, c1) - c1
        sseg = segv1[order]
        ordinal_sorted = pos - starts[jnp.clip(sseg, 0, S - 1)]
        ordinal = jnp.zeros((p1,), dtype=jnp.int32).at[order].set(
            ordinal_sorted
        )
        rc = c2[jnp.clip(seg1, 0, S - 1)]
        keep = valid1 & (ordinal >= rc if subtract else ordinal < rc)
        return keep, jnp.sum(keep).astype(jnp.int32)

    keep, cnt = engine._jit_cached(
        (
            "intersect_subtract",
            subtract,
            distinct,
            S,
            p1,
            b2.padded_nrows,
            tuple(names),
            strat,
        ),
        _prog,
    )(sf.seg1, sf.seg2, b1.row_valid, _nrows_arg(b1), b2.validity())
    return JaxBlocks(
        None, dict(b1.columns), b1.mesh, row_valid=keep, nrows_dev=cnt
    )


def _nrows_arg(blocks: JaxBlocks) -> Any:
    if blocks._nrows is not None:
        return np.int32(blocks._nrows)
    if blocks._nrows_dev is not None:
        return blocks._nrows_dev
    return np.int32(-1)


# ---------------------------------------------------------------------------
# fillna / take / sample (mask-only where possible)
# ---------------------------------------------------------------------------


def _encode_fill_value(col: JaxColumn, value: Any) -> Optional[Any]:
    """The fill value in the column's device representation, or None if it
    cannot be represented (caller falls back)."""
    tp = col.pa_type
    try:
        if col.is_string:
            if not isinstance(value, str):
                return None
            hits = np.nonzero(col.dictionary == value)[0]
            if len(hits) > 0:
                return np.int32(hits[0])
            # append to the dictionary (host-side, small)
            col.dictionary = np.concatenate(
                [col.dictionary, np.asarray([value], dtype=object)]
            )
            if col.stats is not None:
                col.stats = (col.stats[0], len(col.dictionary) - 1)
            return np.int32(len(col.dictionary) - 1)
        if pa.types.is_timestamp(tp):
            ts = np.datetime64(value, "us")
            return np.int64((ts - np.datetime64(0, "us")).astype(np.int64))
        if pa.types.is_date32(tp):
            d = np.datetime64(value, "D")
            return np.int32(
                (d - np.datetime64(0, "D")).astype(np.int64)
            )
        v = np.asarray(value, dtype=col.data.dtype)[()]
        # the host oracle REJECTS inexact fills (e.g. 2.5 into int64);
        # a silently truncating device path would diverge from it
        if not np.issubdtype(col.data.dtype, np.floating) and v != value:
            return None
        return v
    except (ValueError, TypeError):
        return None


@_mesh_scoped(1)
def device_fillna(
    engine: Any,
    blocks: JaxBlocks,
    schema: Schema,
    targets: Dict[str, Any],
) -> Optional[JaxBlocks]:
    """Fill nulls in `targets` columns in ONE jitted dispatch; the filled
    columns drop their masks. Returns None when any target column is
    host-resident or the value can't be encoded."""
    enc: Dict[str, Any] = {}
    float_cols: List[str] = []
    for name, value in targets.items():
        col = blocks.columns[name]
        if not col.on_device:
            return None
        is_float = jnp.issubdtype(col.data.dtype, jnp.floating)
        if col.mask is None and not is_float:
            continue  # nothing to fill
        v = _encode_fill_value(col, value)
        if v is None:
            return None
        enc[name] = v
        if is_float:
            float_cols.append(name)
    if not enc:
        return blocks
    names = sorted(enc)

    def _prog(
        datas: Dict[str, Any], masks: Dict[str, Any], fills: Dict[str, Any]
    ) -> Dict[str, Any]:
        outs: Dict[str, Any] = {}
        for nm in names:
            d = datas[nm]
            m = masks.get(nm)
            eff_null = jnp.zeros(d.shape, dtype=bool) if m is None else ~m
            if nm in float_cols:
                eff_null = eff_null | jnp.isnan(d)
            outs[nm] = jnp.where(eff_null, fills[nm].astype(d.dtype), d)
        return outs

    outs = engine._jit_cached(
        (
            "fillna",
            blocks.padded_nrows,
            tuple(names),
            tuple(sorted(float_cols)),
            tuple(nm for nm in names if blocks.columns[nm].mask is not None),
        ),
        _prog,
    )(
        {nm: blocks.columns[nm].data for nm in names},
        {
            nm: blocks.columns[nm].mask
            for nm in names
            if blocks.columns[nm].mask is not None
        },
        {nm: jnp.asarray(enc[nm]) for nm in names},
    )
    sharding = row_sharding(blocks.mesh)
    new_cols = dict(blocks.columns)
    for nm in names:
        src = blocks.columns[nm]
        new_cols[nm] = JaxColumn(
            src.pa_type,
            jax.device_put(outs[nm], sharding),
            None,
            src.dictionary,
            src.stats,
        )
    return JaxBlocks(
        blocks._nrows,
        new_cols,
        blocks.mesh,
        row_valid=blocks.row_valid,
        nrows_dev=blocks._nrows_dev,
    )


@_mesh_scoped(0)
def _sort_code_columns(
    blocks: JaxBlocks, sorts: List[Tuple[str, bool]]
) -> Optional[List[Tuple[Any, Optional[Any], bool]]]:
    """Per sort item IN ORDER (duplicates kept): (device code array,
    effective-null mask or None, ascending). String columns sort by
    LEXICOGRAPHIC rank (a host argsort of the small dictionary builds the
    rank table), not by code order."""
    out: List[Tuple[Any, Optional[Any], bool]] = []
    for name, asc in sorts:
        col = blocks.columns.get(name)
        if col is None or not col.on_device:
            return None
        data = col.data
        if col.is_string:
            order = np.argsort(col.dictionary.astype(str), kind="stable")
            rank = np.empty(max(len(order), 1), dtype=np.int32)
            rank[order] = np.arange(len(order), dtype=np.int32)
            data = jnp.asarray(rank)[
                jnp.clip(col.data, 0, max(len(order) - 1, 0))
            ]
        elif data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        null = None if col.mask is None else ~col.mask
        if jnp.issubdtype(data.dtype, jnp.floating):
            nan = jnp.isnan(data)
            null = nan if null is None else (null | nan)
            data = jnp.where(nan, jnp.zeros_like(data), data)
        out.append((data, null, bool(asc)))
    return out


def _stable_sort_order(
    code_arrs: Tuple[Any, ...],
    null_arrs: Dict[int, Any],
    ascs: List[bool],
    na_first: List[bool],
    valid: Any,
    invalid_last: bool = True,
) -> Any:
    """Traced helper shared by device_take/device_sort: row order under a
    stable multi-key sort (keys applied least-significant outward), per-key
    NULLS FIRST/LAST, then (unless the caller re-sorts, e.g. by segment)
    invalid rows last. ``descending=True`` (not value negation) because
    negating unsigned or INT_MIN values wraps and silently misorders
    (review finding)."""
    p = valid.shape[0]
    order = jnp.arange(p, dtype=jnp.int32)
    for i in reversed(range(len(code_arrs))):
        sc = code_arrs[i]
        if i in null_arrs:
            # null slots hold fill garbage (join gathers especially):
            # neutralize them so null rows TIE on the value key and keep
            # the less-significant key order (review finding)
            sc = jnp.where(null_arrs[i], jnp.zeros_like(sc), sc)
        sc = sc[order]
        order = order[jnp.argsort(sc, stable=True, descending=not ascs[i])]
        if i in null_arrs:
            nf = null_arrs[i][order]
            # nulls first -> sort by NOT-null; nulls last -> by null
            flag = ~nf if na_first[i] else nf
            order = order[jnp.argsort(flag, stable=True)]
    if invalid_last:
        order = order[jnp.argsort(~valid[order], stable=True)]
    return order


@_mesh_scoped(1)
def device_take(
    engine: Any,
    blocks: JaxBlocks,
    schema: Schema,
    n: int,
    sorts: Dict[str, bool],
    na_position: str,
    partition_by: List[str],
) -> Optional[JaxBlocks]:
    """Mask-only take: rows keep their storage order; validity flips to
    the first `n` rows per partition (or globally) under the presort
    order. Zero host syncs; the row count becomes a lazy device scalar."""
    codes = _sort_code_columns(blocks, list(sorts.items()))
    if codes is None:
        return None
    for k in partition_by:
        col = blocks.columns.get(k)
        if col is None or not col.on_device:
            return None
    p = blocks.padded_nrows
    if partition_by:
        fr = groupby.factorize_keys(blocks, partition_by)
        seg, S = fr.seg, max(fr.num_segments, 1)
    else:
        seg, S = None, 1
    na_first = na_position == "first"

    def _prog(
        code_arrs: Tuple[Any, ...],
        null_arrs: Dict[int, Any],
        seg_: Optional[Any],
        row_valid: Optional[Any],
        nrows_s: Any,
    ) -> Tuple[Any, Any]:
        valid = groupby.materialize_validity(row_valid, p, nrows_s)
        order = _stable_sort_order(
            code_arrs, null_arrs,
            [asc for _, _, asc in codes],
            [na_first] * len(codes),
            valid,
            invalid_last=seg_ is None,
        )
        if seg_ is not None:
            order = order[jnp.argsort(seg_[order], stable=True)]
            # invalid rows last (their sentinel seg already sorts high,
            # but keep the explicit guarantee)
            order = order[jnp.argsort(~valid[order], stable=True)]
        invrank = jnp.zeros((p,), dtype=jnp.int32).at[order].set(
            jnp.arange(p, dtype=jnp.int32)
        )
        if seg_ is not None:
            cnt = jax.ops.segment_sum(
                valid.astype(jnp.int32),
                jnp.where(valid, seg_, S),
                num_segments=S,
            )
            starts = jnp.cumsum(cnt) - cnt
            local = invrank - starts[jnp.clip(seg_, 0, S - 1)]
            keep = valid & (local < n)
        else:
            keep = valid & (invrank < n)
        return keep, jnp.sum(keep).astype(jnp.int32)

    keep, cnt = engine._jit_cached(
        (
            "take",
            n,
            p,
            S,
            tuple(partition_by),
            tuple((nm, asc) for nm, asc in sorts.items()),
            tuple(i for i in range(len(codes)) if codes[i][1] is not None),
            na_position,
        ),
        _prog,
    )(
        tuple(c for c, _, _ in codes),
        {i: nl for i, (_, nl, _) in enumerate(codes) if nl is not None},
        seg,
        blocks.row_valid,
        _nrows_arg(blocks),
    )
    return JaxBlocks(
        None, dict(blocks.columns), blocks.mesh, row_valid=keep, nrows_dev=cnt
    )


@_mesh_scoped(1)
def device_sort(
    engine: Any,
    blocks: JaxBlocks,
    schema: Schema,
    sorts: List[Tuple[str, bool, Optional[bool]]],
    limit: Optional[int] = None,
    offset: Optional[int] = None,
) -> Optional[JaxBlocks]:
    """ORDER BY [LIMIT/OFFSET] as a device ROW REORDER: stable multi-key
    argsort on device (per-key NULLS FIRST/LAST; default LAST to match the
    host SELECT runner), then one gather of the surviving window. Pays one
    host sync for the row count — ORDER BY sits at a query's export
    boundary, where that sync happens anyway. With ``sorts == []`` this is
    plain LIMIT/OFFSET in storage order."""
    code_cols = _sort_code_columns(
        blocks, [(name, asc) for name, asc, _ in sorts]
    )
    if code_cols is None:
        return None
    if not all(c.on_device for c in blocks.columns.values()):
        return None
    p = blocks.padded_nrows
    na_first = [
        (nulls if nulls is not None else False) for _, _, nulls in sorts
    ]

    def _prog(
        code_arrs: Tuple[Any, ...],
        null_arrs: Dict[int, Any],
        row_valid: Optional[Any],
        nrows_s: Any,
    ) -> Any:
        valid = groupby.materialize_validity(row_valid, p, nrows_s)
        return _stable_sort_order(
            code_arrs, null_arrs,
            [asc for _, _, asc in code_cols],
            na_first,
            valid,
        )

    order = engine._jit_cached(
        (
            "sort",
            p,
            tuple(
                (nm, asc, nf) for (nm, asc, _), nf in zip(sorts, na_first)
            ),
            tuple(
                i for i in range(len(code_cols))
                if code_cols[i][1] is not None
            ),
        ),
        _prog,
    )(
        tuple(c for c, _, _ in code_cols),
        {i: nl for i, (_, nl, _) in enumerate(code_cols) if nl is not None},
        blocks.row_valid,
        _nrows_arg(blocks),
    )
    n = blocks.nrows  # the one host sync
    start = min(offset or 0, n)
    stop = n if limit is None else min(n, start + limit)
    from fugue_tpu.jax_backend.blocks import gather_indices

    return gather_indices(blocks, order[start:stop], schema)


@_mesh_scoped(1)
def device_window(
    engine: Any,
    blocks: JaxBlocks,
    schema: Schema,
    items: List[Any],
) -> Optional[Tuple[JaxBlocks, Schema]]:
    """Window functions as device programs (verdict r3 item 4's device
    lowering): whole-partition aggregates gather segment reductions back
    per row; the ranking family (row_number/rank/dense_rank/ntile/
    percent_rank/cume_dist) runs through _window_rank_family's sorted-
    space program (stable sort + per-segment start offsets + adjacent-
    row peer detection). ``items`` mixes
    ``("col", (out_name, src_name))`` passthroughs with ``("win", spec)``
    entries (see ``algebra_bridge.WindowSpec``). Returns None when any
    referenced column is host-resident."""
    if not all(c.on_device for c in blocks.columns.values()):
        return None
    p = blocks.padded_nrows
    out_cols: Dict[str, JaxColumn] = {}
    fields: List[Any] = []
    for kind, payload in items:
        if kind == "col":
            out_name, src_name = payload
            src = blocks.columns.get(src_name)
            if src is None:
                return None
            out_cols[out_name] = src
            fields.append(
                pa.field(out_name, schema[src_name].type)
            )
            continue
        spec = payload
        if spec.partition_by:
            fr = groupby.factorize_keys(blocks, list(spec.partition_by))
            seg, S = fr.seg, max(fr.num_segments, 1)
        else:
            seg, S = jnp.zeros((p,), dtype=jnp.int32), 1
        if spec.func in (
            "row_number", "rank", "dense_rank", "ntile", "percent_rank",
            "cume_dist",
        ):
            col, tp = _window_rank_family(engine, blocks, spec, seg, S, p)
        elif spec.order_by:
            res = _window_frame_agg(engine, blocks, spec, seg, S, p)
            if res is None:
                return None
            col, tp = res
        else:
            res = _window_segment_agg(engine, blocks, spec, seg, S, p)
            if res is None:
                return None
            col, tp = res
        out_cols[spec.name] = col
        fields.append(pa.field(spec.name, tp))
    out_schema = Schema(fields)
    return (
        JaxBlocks(
            blocks._nrows,
            out_cols,
            blocks.mesh,
            row_valid=blocks.row_valid,
            nrows_dev=blocks._nrows_dev,
        ),
        out_schema,
    )


def _window_rank_family(
    engine: Any, blocks: JaxBlocks, spec: Any, seg: Any, S: int, p: int
) -> Tuple[JaxColumn, pa.DataType]:
    """The ranking family (row_number / rank / dense_rank / ntile /
    percent_rank / cume_dist) as one device program: stable sort by
    (order keys, partition), local position per partition, and — for the
    peer-aware variants — peer-group detection by comparing ADJACENT
    sorted rows' key codes (null-neutralized exactly like the sort)."""
    kind = spec.func
    buckets = int(getattr(spec, "param", 0) or 0)  # ntile's N
    codes = _sort_code_columns(
        blocks, [(name, asc) for name, asc, _ in spec.order_by]
    )
    assert_or_throw(codes is not None, ValueError("sort key not on device"))
    na_first = [
        (nf if nf is not None else False) for _, _, nf in spec.order_by
    ]

    def _prog(
        code_arrs: Tuple[Any, ...],
        null_arrs: Dict[int, Any],
        seg_: Any,
        row_valid: Optional[Any],
        nrows_s: Any,
    ) -> Any:
        valid = groupby.materialize_validity(row_valid, p, nrows_s)
        order = _stable_sort_order(
            code_arrs, null_arrs,
            [asc for _, _, asc in codes],  # type: ignore[misc]
            na_first, valid, invalid_last=False,
        )
        segv = jnp.where(valid, seg_, S)
        order = order[jnp.argsort(segv[order], stable=True)]
        pos = jnp.arange(p, dtype=jnp.int32)
        cnt = jax.ops.segment_sum(
            valid.astype(jnp.int32), segv, num_segments=S + 1
        )[:S]
        starts = jnp.cumsum(cnt) - cnt
        sseg = segv[order]
        start_pos = starts[jnp.clip(sseg, 0, S - 1)]
        psize = cnt[jnp.clip(sseg, 0, S - 1)]
        local_sorted = pos - start_pos  # 0-based row number per partition
        if kind == "row_number":
            out_sorted: Any = local_sorted + 1
        elif kind == "ntile":
            # first (psize % n) buckets take the extra rows (standard)
            q_ = psize // buckets
            rem = psize % buckets
            cutoff = rem * (q_ + 1)
            head = local_sorted // jnp.maximum(q_ + 1, 1) + 1
            tail = rem + (local_sorted - cutoff) // jnp.maximum(q_, 1) + 1
            out_sorted = jnp.where(local_sorted < cutoff, head, tail)
        else:
            false0 = jnp.zeros((1,), dtype=bool)
            same_part = jnp.concatenate([false0, sseg[1:] == sseg[:-1]])
            is_peer = same_part
            for i, c in enumerate(code_arrs):
                sc = c
                if i in null_arrs:
                    sc = jnp.where(null_arrs[i], jnp.zeros_like(sc), sc)
                scs = sc[order]
                eq = jnp.concatenate([false0, scs[1:] == scs[:-1]])
                if i in null_arrs:
                    nn = null_arrs[i][order]
                    eq = eq & jnp.concatenate([false0, nn[1:] == nn[:-1]])
                is_peer = is_peer & eq
            if kind in ("rank", "percent_rank"):
                # the peer-group head's GLOBAL position carries forward
                # (cummax is safe: positions are globally increasing and
                # every partition head starts a new peer group)
                head_pos = jax.lax.cummax(jnp.where(~is_peer, pos, -1))
                rank_sorted = head_pos - start_pos + 1
                if kind == "rank":
                    out_sorted = rank_sorted
                else:
                    out_sorted = jnp.where(
                        psize > 1,
                        (rank_sorted - 1)
                        / jnp.maximum(psize - 1, 1).astype(jnp.float64),
                        0.0,
                    )
            elif kind == "dense_rank":
                cs = jnp.cumsum((~is_peer).astype(jnp.int32))
                cs_at_start = cs[jnp.clip(start_pos, 0, p - 1)]
                out_sorted = cs - cs_at_start + 1
            else:  # cume_dist: peers share the group's LAST position
                big = jnp.int32(p)
                heads = jnp.where(~is_peer, pos, big)
                # next peer-head strictly after each position, via a
                # reversed cummin of head positions shifted left
                nh = jnp.flip(jax.lax.cummin(jnp.flip(
                    jnp.concatenate([heads[1:], big[None]])
                )))
                part_end = start_pos + psize - 1
                last_pos = jnp.minimum(nh - 1, part_end)
                out_sorted = (
                    (last_pos - start_pos + 1)
                    / jnp.maximum(psize, 1).astype(jnp.float64)
                )
        if kind in ("percent_rank", "cume_dist"):
            return jnp.zeros((p,), dtype=jnp.float64).at[order].set(
                out_sorted.astype(jnp.float64)
            )
        return (
            jnp.zeros((p,), dtype=jnp.int64).at[order].set(
                out_sorted.astype(jnp.int64)
            )
        )

    rn = engine._jit_cached(
        (
            "win_rank", kind, buckets, p, S, tuple(spec.partition_by),
            tuple(
                (nm, asc, nf)
                for (nm, asc, _), nf in zip(spec.order_by, na_first)
            ),
            tuple(i for i in range(len(codes)) if codes[i][1] is not None),
        ),
        _prog,
    )(
        tuple(c for c, _, _ in codes),
        {i: nl for i, (_, nl, _) in enumerate(codes) if nl is not None},
        seg,
        blocks.row_valid,
        _nrows_arg(blocks),
    )
    tp = (
        pa.float64()
        if kind in ("percent_rank", "cume_dist")
        else pa.int64()
    )
    sharding = row_sharding(blocks.mesh)
    return (JaxColumn(tp, jax.device_put(rn, sharding)), tp)


def _window_frame_agg(
    engine: Any, blocks: JaxBlocks, spec: Any, seg: Any, S: int, p: int
) -> Optional[Tuple[JaxColumn, pa.DataType]]:
    """Ordered window programs in sorted space (the role the reference's
    DuckDB backend plays natively for framed/running windows,
    ``/root/reference/fugue_duckdb/execution_engine.py:37``): stable
    sort by (order keys, partition), then

    - running (default RANGE) aggregates: segment-offset prefix sums
      with peers sharing their group's LAST value,
    - ROWS-framed aggregates: prefix-sum differences over positional
      [lo, hi] bounds; min/max via a log2(p)-level sparse table,
    - GROUPS frames: peer-group ids with per-group start/end tables,
    - RANGE frames: peer bounds, with numeric offsets resolved by a
      vectorized per-partition bisect over the raw order key,
    - lag/lead: a shifted gather with partition-boundary masking,
    - first/last/nth_value: gathers at frame boundary positions,

    and one scatter back to row space. Returns None when the argument or
    a sort key is host-resident or the dtype is outside the device set.
    """
    func = "avg" if spec.func == "mean" else spec.func
    gather_like = func in (
        "lag", "lead", "first_value", "last_value", "nth_value"
    )
    if spec.arg is None:  # count(*)
        vcol = None
        arg_tp: Optional[pa.DataType] = None
    else:
        vcol = blocks.columns.get(spec.arg)
        if vcol is None or not vcol.on_device:
            return None
        if vcol.is_string and not gather_like:
            return None
        if vcol.is_string and spec.default is not None:
            return None  # a fill literal has no dictionary code
        if (
            spec.default is not None
            and isinstance(spec.default, float)
            and pa.types.is_integer(vcol.pa_type)
        ):
            return None  # the host upcasts int columns to float here
        arg_tp = vcol.pa_type
    cast_result = True
    if func == "count":
        tp: pa.DataType = pa.int64()
    elif func in ("sum", "avg"):
        if arg_tp is None or not (
            pa.types.is_integer(arg_tp)
            or pa.types.is_floating(arg_tp)
            or pa.types.is_boolean(arg_tp)
        ):
            return None
        tp = (
            pa.float64()
            if func == "avg"
            else (pa.int64() if pa.types.is_integer(arg_tp) else pa.float64())
        )
    elif func in ("min", "max"):
        if arg_tp is None or pa.types.is_boolean(arg_tp):
            return None
        tp = arg_tp
        if pa.types.is_timestamp(arg_tp) or pa.types.is_date32(arg_tp):
            cast_result = False
    else:  # gathers keep the argument's device representation
        assert arg_tp is not None
        tp = arg_tp
        cast_result = False
    codes = _sort_code_columns(
        blocks, [(name, asc) for name, asc, _ in spec.order_by]
    )
    if codes is None:
        return None
    na_first = [
        (nf if nf is not None else False) for _, _, nf in spec.order_by
    ]
    frame = spec.frame  # None = running default frame (peers share)
    off = int(spec.param or 0)  # lag/lead offset or nth_value position
    default = spec.default
    values = None if vcol is None else vcol.data
    vmask = None if vcol is None else vcol.mask
    okey = None
    okey_mask = None
    if frame is not None and frame[0] == "range" and any(
        kd in ("p", "f") for kd in (frame[1], frame[3])
    ):
        # numeric RANGE offsets: the raw single ORDER BY key drives the
        # per-partition value search (bridge guarantees one key)
        kcol = blocks.columns.get(spec.order_by[0][0])
        if (
            kcol is None
            or not kcol.on_device
            or kcol.is_string
            or not (
                pa.types.is_integer(kcol.pa_type)
                or pa.types.is_floating(kcol.pa_type)
                or pa.types.is_boolean(kcol.pa_type)
            )
        ):
            return None  # non-numeric key: host runner owns the error
        okey = kcol.data
        okey_mask = kcol.mask

    def _prog(
        code_arrs: Tuple[Any, ...],
        null_arrs: Dict[int, Any],
        values_: Optional[Any],
        vmask_: Optional[Any],
        okey_: Optional[Any],
        okey_mask_: Optional[Any],
        seg_: Any,
        row_valid: Optional[Any],
        nrows_s: Any,
    ) -> Tuple[Any, Optional[Any]]:
        valid = groupby.materialize_validity(row_valid, p, nrows_s)
        order = _stable_sort_order(
            code_arrs, null_arrs,
            [asc for _, _, asc in codes],  # type: ignore[misc]
            na_first, valid, invalid_last=False,
        )
        segv = jnp.where(valid, seg_, S)
        order = order[jnp.argsort(segv[order], stable=True)]
        pos = jnp.arange(p, dtype=jnp.int32)
        cnt = jax.ops.segment_sum(
            valid.astype(jnp.int32), segv, num_segments=S + 1
        )[:S]
        starts = jnp.cumsum(cnt) - cnt
        sseg = segv[order]
        part_start = starts[jnp.clip(sseg, 0, S - 1)]
        psize = cnt[jnp.clip(sseg, 0, S - 1)]
        part_end = part_start + psize - 1
        svalid = valid[order]
        sv = None if values_ is None else values_[order]
        if values_ is None:
            sm = svalid
        elif vmask_ is None:
            sm = svalid
        else:
            sm = svalid & vmask_[order]
        if sv is not None and jnp.issubdtype(sv.dtype, jnp.floating):
            sm = sm & ~jnp.isnan(sv)

        def _scatter(out_sorted: Any, m_sorted: Optional[Any]) -> Tuple[
            Any, Optional[Any]
        ]:
            out = jnp.zeros((p,), dtype=out_sorted.dtype).at[order].set(
                out_sorted
            )
            m = (
                None
                if m_sorted is None
                else jnp.zeros((p,), dtype=bool).at[order].set(m_sorted)
            )
            return out, m

        if func in ("lag", "lead"):
            src = pos - off if func == "lag" else pos + off
            inb = (src >= part_start) & (src <= part_end)
            srcc = jnp.clip(src, 0, p - 1)
            val = sv[srcc]
            vm = sm[srcc] & inb
            if default is not None:
                dv = jnp.asarray(default).astype(val.dtype)
                val = jnp.where(inb, val, dv)
                vm = vm | ~inb
            return _scatter(val, vm)

        # frame bounds [lo, hi] in sorted space
        unit = None if frame is None else frame[0]
        if unit is None or unit in ("groups", "range"):
            # peer detection (adjacent sorted rows tying on every key)
            false0 = jnp.zeros((1,), dtype=bool)
            same_part = jnp.concatenate([false0, sseg[1:] == sseg[:-1]])
            is_peer = same_part
            for i, c in enumerate(code_arrs):
                sc = c
                if i in null_arrs:
                    sc = jnp.where(null_arrs[i], jnp.zeros_like(sc), sc)
                scs = sc[order]
                eq = jnp.concatenate([false0, scs[1:] == scs[:-1]])
                if i in null_arrs:
                    nn = null_arrs[i][order]
                    eq = eq & jnp.concatenate([false0, nn[1:] == nn[:-1]])
                is_peer = is_peer & eq
        if unit in ("groups", "range"):
            gnew = ~is_peer
            g_glob = (jnp.cumsum(gnew.astype(jnp.int32)) - 1).astype(
                jnp.int32
            )
            g_start_by = jax.ops.segment_min(pos, g_glob, num_segments=p)
            g_end_by = jax.ops.segment_max(pos, g_glob, num_segments=p)
            peer_start = g_start_by[g_glob]
            peer_end = g_end_by[g_glob]
        if unit is None:
            # running: lo = partition start, hi = peer group's LAST row
            big = jnp.int32(p)
            heads = jnp.where(~is_peer, pos, big)
            nh = jnp.flip(jax.lax.cummin(jnp.flip(
                jnp.concatenate([heads[1:], big[None]])
            )))
            lo = part_start
            hi = jnp.minimum(nh - 1, part_end)
        elif unit == "rows":
            _, sk, sn, ek, en = frame

            def _bound(kd: str, nv: Optional[int]) -> Any:
                if kd == "up":
                    return part_start
                if kd == "uf":
                    return part_end
                if kd == "c":
                    return pos
                return pos + int(nv) if kd == "f" else pos - int(nv)

            lo = jnp.maximum(_bound(sk, sn), part_start)
            hi = jnp.minimum(_bound(ek, en), part_end)
        elif unit == "groups":
            _, sk, sn, ek, en = frame
            g_first = g_glob[part_start]
            g_last = g_glob[part_end]

            def _gbound(kd: str, nv: Optional[int], is_start: bool) -> Any:
                if kd == "up":
                    return part_start
                if kd == "uf":
                    return part_end
                if kd == "c":
                    return peer_start if is_start else peer_end
                tg = g_glob + (int(nv) if kd == "f" else -int(nv))
                tgc = jnp.clip(tg, 0, p - 1)
                if is_start:
                    out = jnp.where(
                        tg < g_first, part_start, g_start_by[tgc]
                    )
                    return jnp.where(tg > g_last, part_end + 1, out)
                out = jnp.where(tg > g_last, part_end, g_end_by[tgc])
                return jnp.where(tg < g_first, part_start - 1, out)

            lo = jnp.maximum(_gbound(sk, sn, True), part_start)
            hi = jnp.minimum(_gbound(ek, en, False), part_end)
        else:  # range (peer bounds; numeric offsets via bisect)
            _, sk, sn, ek, en = frame
            need_key = sk in ("p", "f") or ek in ("p", "f")
            if need_key:  # okey_ is loaded only for offset bounds
                kv = okey_.astype(jnp.float64)
                knull = (
                    jnp.zeros((p,), dtype=bool)
                    if okey_mask_ is None
                    else ~okey_mask_
                )
                knull = knull | jnp.isnan(okey_.astype(jnp.float64))
                asc = bool(spec.order_by[0][1])
                if not asc:
                    kv = -kv
                skv = kv[order]
                snull = (knull | ~valid)[order]
                # non-null span [a, b] per row: nulls sort to one end
                ncnt = jax.ops.segment_sum(
                    (knull & valid).astype(jnp.int32), segv,
                    num_segments=S + 1,
                )[:S][jnp.clip(sseg, 0, S - 1)]
                nf = spec.order_by[0][2]
                nulls_first = bool(nf) if nf is not None else False
                if nulls_first:
                    a_, b_ = part_start + ncnt, part_end
                else:
                    a_, b_ = part_start, part_end - ncnt
            steps = max(1, int(np.ceil(np.log2(max(p, 2)))) + 1)

            def _bisect(target: Any, right: bool) -> Any:
                lo_b, hi_b = a_, b_ + 1
                for _ in range(steps):
                    mid = (lo_b + hi_b) // 2
                    mv = skv[jnp.clip(mid, 0, p - 1)]
                    go = (mv <= target) if right else (mv < target)
                    go = go & (lo_b < hi_b)
                    stay = (lo_b < hi_b) & ~go
                    lo_b = jnp.where(go, mid + 1, lo_b)
                    hi_b = jnp.where(stay, mid, hi_b)
                return lo_b

            def _rbound(kd: str, nv: Any, is_start: bool) -> Any:
                if kd == "up":
                    return part_start
                if kd == "uf":
                    return part_end
                if kd == "c":
                    return peer_start if is_start else peer_end
                delta = float(nv) if kd == "f" else -float(nv)
                tgt = skv + delta
                res = (
                    _bisect(tgt, right=False)
                    if is_start
                    else _bisect(tgt, right=True) - 1
                )
                # null keys: the bound resolves to the null peer group
                return jnp.where(
                    snull, peer_start if is_start else peer_end, res
                )

            lo = jnp.maximum(_rbound(sk, sn, True), part_start)
            hi = jnp.minimum(_rbound(ek, en, False), part_end)
        empty = lo > hi
        lo_s = jnp.clip(lo, 0, p - 1)
        hi_s = jnp.clip(hi, 0, p - 1)

        if func == "count":
            if sv is None:
                out = jnp.where(empty, 0, hi - lo + 1).astype(jnp.int64)
            else:
                c = jnp.concatenate(
                    [jnp.zeros((1,), jnp.int64), jnp.cumsum(
                        sm.astype(jnp.int64)
                    )]
                )
                out = jnp.where(empty, 0, c[hi_s + 1] - c[lo_s])
            return _scatter(out.astype(jnp.int64), None)
        if func in ("sum", "avg"):
            acc = (
                jnp.int64
                if arg_tp is not None and pa.types.is_integer(arg_tp)
                else jnp.float64
            )
            fv = jnp.where(sm, sv.astype(acc), jnp.zeros((), acc))
            cs = jnp.concatenate(
                [jnp.zeros((1,), acc), jnp.cumsum(fv)]
            )
            cn = jnp.concatenate(
                [jnp.zeros((1,), jnp.int64), jnp.cumsum(
                    sm.astype(jnp.int64)
                )]
            )
            fcnt = jnp.where(empty, 0, cn[hi_s + 1] - cn[lo_s])
            tot = jnp.where(
                empty, jnp.zeros((), acc), cs[hi_s + 1] - cs[lo_s]
            )
            if func == "sum":
                return _scatter(tot, fcnt > 0)
            return _scatter(
                tot.astype(jnp.float64)
                / jnp.maximum(fcnt, 1).astype(jnp.float64),
                fcnt > 0,
            )
        if func in ("min", "max"):
            is_min = func == "min"
            if jnp.issubdtype(sv.dtype, jnp.floating):
                sentinel = jnp.array(
                    jnp.inf if is_min else -jnp.inf, dtype=sv.dtype
                )
            else:
                info = jnp.iinfo(sv.dtype)
                sentinel = jnp.array(
                    info.max if is_min else info.min, dtype=sv.dtype
                )
            op = jnp.minimum if is_min else jnp.maximum
            level = jnp.where(sm, sv, sentinel)
            levels = [level]
            w = 1
            while w < p:
                shifted = jnp.concatenate(
                    [level[w:], jnp.full((w,), sentinel, dtype=sv.dtype)]
                )
                level = op(level, shifted)
                levels.append(level)
                w *= 2
            stack = jnp.stack(levels)  # (K, p): min/max over [i, i+2^k-1]
            length = (hi_s - lo_s + 1).astype(jnp.float64)
            kq = jnp.floor(
                jnp.log2(jnp.maximum(length, 1.0))
            ).astype(jnp.int32)
            flat = stack.reshape(-1)
            a = flat[kq * p + lo_s]
            b = flat[kq * p + jnp.maximum(hi_s - (1 << kq) + 1, 0)]
            out = op(a, b)
            cn = jnp.concatenate(
                [jnp.zeros((1,), jnp.int64), jnp.cumsum(
                    sm.astype(jnp.int64)
                )]
            )
            fcnt = jnp.where(empty, 0, cn[hi_s + 1] - cn[lo_s])
            if cast_result:
                out = out.astype(tp.to_pandas_dtype())
            return _scatter(out, fcnt > 0)
        # first/last/nth_value: boundary gathers
        if func == "nth_value":
            at = lo + off - 1
            bad = empty | (at > hi)
        elif func == "first_value":
            at = lo
            bad = empty
        else:
            at = hi
            bad = empty
        atc = jnp.clip(at, 0, p - 1)
        return _scatter(sv[atc], sm[atc] & ~bad)

    out, outm = engine._jit_cached(
        (
            "win_frame", func, spec.arg, frame, off,
            None if default is None else float(default), p, S,
            tuple(spec.partition_by),
            tuple(
                (nm, asc, nf)
                for (nm, asc, _), nf in zip(spec.order_by, na_first)
            ),
            str(tp), vmask is not None,
            tuple(i for i in range(len(codes)) if codes[i][1] is not None),
        ),
        _prog,
    )(
        tuple(c for c, _, _ in codes),
        {i: nl for i, (_, nl, _) in enumerate(codes) if nl is not None},
        values,
        vmask,
        okey,
        okey_mask,
        seg,
        blocks.row_valid,
        _nrows_arg(blocks),
    )
    sharding = row_sharding(blocks.mesh)
    dictionary = None if vcol is None else (
        vcol.dictionary if gather_like else None
    )
    return (
        JaxColumn(
            tp,
            jax.device_put(out, sharding),
            None if outm is None else jax.device_put(outm, sharding),
            dictionary=dictionary,
        ),
        tp,
    )


def _window_segment_agg(
    engine: Any, blocks: JaxBlocks, spec: Any, seg: Any, S: int, p: int
) -> Optional[Tuple[JaxColumn, pa.DataType]]:
    if spec.arg is None:  # count(*)
        values = jnp.ones((p,), dtype=jnp.int32)
        vmask = None
        arg_tp: Optional[pa.DataType] = None
    else:
        col = blocks.columns.get(spec.arg)
        if col is None or not col.on_device or col.is_string:
            return None
        values, vmask = col.data, col.mask
        arg_tp = col.pa_type
    func = "avg" if spec.func == "mean" else spec.func
    cast_result = True
    if func == "count":
        tp: pa.DataType = pa.int64()
    elif func in ("avg", "sum"):
        # numeric payloads only — the host oracle owns the error for
        # SUM(timestamp) etc.
        if arg_tp is None or not (
            pa.types.is_integer(arg_tp)
            or pa.types.is_floating(arg_tp)
            or pa.types.is_boolean(arg_tp)
        ):
            return None
        tp = (
            pa.float64()
            if func == "avg"
            else (pa.int64() if pa.types.is_integer(arg_tp) else pa.float64())
        )
    else:  # min/max
        if arg_tp is None:
            return None
        tp = arg_tp
        if pa.types.is_timestamp(arg_tp) or pa.types.is_date32(arg_tp):
            # device representation is already the right integer encoding;
            # datetime64 is not a jax dtype (review finding)
            cast_result = False

    # windowed sum/avg/count are segment reductions too: same strategy
    # layer as the group-by (min/max stay scatter-native inside the impl)
    strat = engine._count_reduce_strategy(blocks, S + 1)

    def _prog(
        values_: Any,
        vmask_: Optional[Any],
        seg_: Any,
        row_valid: Optional[Any],
        nrows_s: Any,
    ) -> Tuple[Any, Optional[Any]]:
        valid = groupby.materialize_validity(row_valid, p, nrows_s)
        segv = jnp.where(valid, seg_, S)
        v, m = groupby._segment_agg_impl(
            func, values_, vmask_, segv, S + 1, valid, strategy=strat
        )
        segc = jnp.clip(seg_, 0, S - 1)
        out = v[:S][segc]
        if cast_result:
            out = out.astype(tp.to_pandas_dtype())
        outm = None if m is None else m[:S][segc]
        return out, outm

    out, outm = engine._jit_cached(
        (
            "win_agg", func, spec.arg, p, S, tuple(spec.partition_by),
            str(tp), vmask is not None, strat,
        ),
        _prog,
    )(values, vmask, seg, blocks.row_valid, _nrows_arg(blocks))
    sharding = row_sharding(blocks.mesh)
    return (
        JaxColumn(
            tp,
            jax.device_put(out, sharding),
            None if outm is None else jax.device_put(outm, sharding),
        ),
        tp,
    )


@_mesh_scoped(1)
def device_sample(
    engine: Any,
    blocks: JaxBlocks,
    n: Optional[int],
    frac: Optional[float],
    seed: Optional[int],
) -> JaxBlocks:
    """Sampling without replacement as a validity flip: every row draws a
    distinct priority (a random permutation, so no float-tie inflation);
    the k smallest priorities among valid rows are kept. k is `n` or
    ``round(nrows * frac)`` computed IN-program, so lazy counts stay lazy."""
    p = blocks.padded_nrows
    if seed is None:
        seed = int(np.random.default_rng().integers(0, 2**31 - 1))

    def _prog(key: Any, row_valid: Optional[Any], nrows_s: Any) -> Tuple[Any, Any]:
        valid = groupby.materialize_validity(row_valid, p, nrows_s)
        pri = jax.random.permutation(key, p).astype(
            jnp.int32
        )
        masked = jnp.where(valid, pri, p)
        srt = jnp.sort(masked)
        nvalid = jnp.sum(valid.astype(jnp.int32))
        if n is not None:
            k = jnp.int32(n)
        else:
            k = jnp.round(nvalid.astype(jnp.float64) * frac).astype(jnp.int32)
        k = jnp.minimum(k, nvalid)
        kth = srt[jnp.clip(k - 1, 0, p - 1)]
        keep = valid & (masked <= kth) & (k > 0)
        return keep, jnp.sum(keep).astype(jnp.int32)

    keep, cnt = engine._jit_cached(
        ("sample", p, n, frac), _prog
    )(jax.random.PRNGKey(seed), blocks.row_valid, _nrows_arg(blocks))
    return JaxBlocks(
        None, dict(blocks.columns), blocks.mesh, row_valid=keep, nrows_dev=cnt
    )
