"""JaxDataFrame: a DataFrame whose columns live as sharded jax.Arrays
(the ``fugue_jax`` sibling-backend dataframe of the BASELINE north star;
structural parity role: fugue_spark/dataframe.py:38 etc.)."""

from typing import Any, Dict, Iterable, List, Optional

import pandas as pd
import pyarrow as pa

from fugue_tpu.dataframe import ArrowDataFrame, DataFrame, LocalBoundedDataFrame
from fugue_tpu.dataframe.arrow_utils import cast_table
from fugue_tpu.jax_backend.blocks import (
    JaxBlocks,
    JaxColumn,
    from_arrow,
    to_arrow,
)
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class JaxDataFrame(DataFrame):
    """Columnar, device-resident, mesh-sharded dataframe."""

    def __init__(self, blocks: JaxBlocks, schema: Schema):
        super().__init__(schema)
        self._blocks = blocks

    @staticmethod
    def from_table(table: pa.Table, mesh: Any, schema: Optional[Schema] = None) -> "JaxDataFrame":
        schema = Schema(table.schema) if schema is None else schema
        return JaxDataFrame(from_arrow(table, schema, mesh), schema)

    @property
    def native(self) -> JaxBlocks:
        return self._blocks

    @property
    def blocks(self) -> JaxBlocks:
        return self._blocks

    @property
    def mesh(self) -> Any:
        return self._blocks.mesh

    @property
    def is_local(self) -> bool:
        return False

    @property
    def is_bounded(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return int(self._blocks.mesh.devices.size)

    @property
    def empty(self) -> bool:
        return self._blocks.nrows == 0

    def count(self) -> int:
        return self._blocks.nrows

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return self.head(1).as_array(type_safe=True)[0]

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        return to_arrow(self._blocks, self.schema)

    def as_pandas(self) -> pd.DataFrame:
        from fugue_tpu.dataframe.arrow_utils import table_to_pandas

        return table_to_pandas(self.as_arrow())

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        res = ArrowDataFrame(self.as_arrow(), self.schema)
        if self.has_metadata:
            res.reset_metadata(self.metadata)
        return res

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        return self.as_local_bounded().as_array(columns, type_safe)

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        return self.as_local_bounded().as_array_iterable(columns, type_safe)

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.exclude(cols)
        return self._select_schema(schema)

    def _select_cols(self, cols: List[Any]) -> DataFrame:
        schema = self.schema.extract(cols)
        return self._select_schema(schema)

    def _select_schema(self, schema: Schema) -> "JaxDataFrame":
        blocks = JaxBlocks(
            self._blocks._nrows,
            {n: self._blocks.columns[n] for n in schema.names},
            self._blocks.mesh,
            row_valid=self._blocks.row_valid,
            nrows_dev=self._blocks._nrows_dev,
        )
        return JaxDataFrame(blocks, schema)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        schema = self._rename_schema(columns)
        cols = {
            columns.get(n, n): c for n, c in self._blocks.columns.items()
        }
        return JaxDataFrame(
            JaxBlocks(
                self._blocks._nrows,
                cols,
                self._blocks.mesh,
                row_valid=self._blocks.row_valid,
                nrows_dev=self._blocks._nrows_dev,
            ),
            schema,
        )

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self._alter_schema(columns)
        if new_schema == self.schema:
            return self
        # general correctness path: cast at the host boundary, re-device
        table = cast_table(self.as_arrow(), new_schema)
        return JaxDataFrame.from_table(table, self._blocks.mesh, new_schema)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        assert_or_throw(n >= 0, ValueError("n must be >= 0"))
        schema = self.schema if columns is None else self.schema.extract(columns)
        src = self if columns is None else self[columns]
        blocks = src._blocks  # type: ignore
        if blocks.row_valid is not None:
            # masked layout: locate the first n valid rows (one mask
            # readback), gather them on device, export the small frame
            import numpy as np

            from fugue_tpu.jax_backend.blocks import gather_indices

            idx = np.nonzero(np.asarray(blocks.row_valid))[0][:n]
            small = gather_indices(blocks, idx, schema)
            return ArrowDataFrame(to_arrow(small, schema), schema)
        take_n = min(n, blocks.nrows)
        table = to_arrow(
            JaxBlocks(take_n, blocks.columns, blocks.mesh), schema
        )
        return ArrowDataFrame(table, schema)
