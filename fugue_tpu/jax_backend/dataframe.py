"""JaxDataFrame: a DataFrame whose columns live as sharded jax.Arrays
(the ``fugue_jax`` sibling-backend dataframe of the BASELINE north star;
structural parity role: fugue_spark/dataframe.py:38 etc.)."""

from typing import Any, Dict, Iterable, List, NamedTuple, Optional

import pandas as pd
import pyarrow as pa

from fugue_tpu.dataframe import ArrowDataFrame, DataFrame, LocalBoundedDataFrame
from fugue_tpu.dataframe.arrow_utils import cast_table
from fugue_tpu.jax_backend.blocks import (
    JaxBlocks,
    JaxColumn,
    from_arrow,
    to_arrow,
)
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class _LazyState(NamedTuple):
    """Loaders for a frame still sitting in storage (streamed ingest)."""

    load_blocks: Any  # () -> JaxBlocks: stream batches straight to mesh
    load_table: Any  # () -> pa.Table: host-only full decode
    mesh: Any
    nrows: int  # from file metadata: count is free
    load_head: Any  # (n) -> pa.Table reading only leading batches, or None
    narrow: Any  # (cols) -> JaxDataFrame re-planned column subset, or None


class JaxDataFrame(DataFrame):
    """Columnar, device-resident, mesh-sharded dataframe.

    Ingestion is LAZY: a frame built :meth:`from_table` keeps the arrow
    table and uploads to the mesh only when a device op first touches
    :attr:`blocks`. Host-path chains (host-fallback maps, string
    transforms, immediate ``as_local``) therefore never pay a device
    round trip — on a network-tunneled TPU that round trip costs seconds
    per GB each way. Once blocks materialize, the host copy is dropped
    (no double-residency); columns are immutable so the pending table is
    always an exact image of the frame."""

    def __init__(self, blocks: JaxBlocks, schema: Schema):
        super().__init__(schema)
        self._blocks: Optional[JaxBlocks] = blocks
        self._pending: Optional[Any] = None  # (pa.Table, mesh) before upload
        # (load_blocks, load_table, mesh, nrows) for storage-lazy frames
        self._lazy: Optional[Any] = None
        # memory-governance admission ticket (memory.AllocationGate) set
        # by the engine on governed pending frames; consumed at blocks
        # materialization
        self._mem_gate: Optional[Any] = None
        # () -> pa.Table reload plan set by engine.load_df on
        # storage-backed frames; becomes blocks.lineage at
        # materialization so device-loss recovery can re-read the
        # artifact (see engine.recover_from_device_loss)
        self._lineage_loader: Optional[Any] = None

    @staticmethod
    def from_table(table: pa.Table, mesh: Any, schema: Optional[Schema] = None) -> "JaxDataFrame":
        schema = Schema(table.schema) if schema is None else schema
        res = JaxDataFrame.__new__(JaxDataFrame)
        DataFrame.__init__(res, schema)
        res._blocks = None
        res._pending = (table, mesh)
        res._lazy = None
        res._mem_gate = None
        res._lineage_loader = None
        return res

    @staticmethod
    def from_lazy(
        load_blocks: Any,
        load_table: Any,
        mesh: Any,
        schema: Schema,
        nrows: int,
        load_head: Any = None,
        narrow: Any = None,
    ) -> "JaxDataFrame":
        """A frame still sitting IN STORAGE (streamed parquet ingest):
        ``load_blocks()`` streams record batches straight to the mesh
        when a device op first touches :attr:`blocks`; ``load_table()``
        is the host-only decode used by ``as_arrow`` chains that never
        need the device copy; ``load_head(n)`` (optional) reads only the
        leading batches so ``head``/``peek`` never decode the whole
        file; ``narrow(cols)`` (optional) re-plans the load over a
        column subset so selects prune decode/staging at the source.
        ``nrows`` comes from file metadata, so ``count`` is free in
        every state."""
        res = JaxDataFrame.__new__(JaxDataFrame)
        DataFrame.__init__(res, schema)
        res._blocks = None
        res._pending = None
        res._lazy = _LazyState(
            load_blocks, load_table, mesh, nrows, load_head, narrow
        )
        res._mem_gate = None
        res._lineage_loader = None
        return res

    @property
    def is_pending(self) -> bool:
        """True while the data only lives on host/storage (no device
        copy yet)."""
        return self._blocks is None

    @property
    def native(self) -> JaxBlocks:
        return self.blocks

    @property
    def blocks(self) -> JaxBlocks:
        if self._blocks is None:
            # governance runs at MATERIALIZATION time: before() may spill
            # LRU persisted frames to make room (and hosts the
            # device.alloc fault site); after() registers the real
            # footprint. A raised alloc failure leaves the gate armed so
            # a later touch is still governed.
            gate = getattr(self, "_mem_gate", None)
            if gate is not None:
                gate.before()
            if self._lazy is not None:
                # the host decode plan doubles as device-loss recovery
                # lineage: a dead device's shards can be re-read from
                # storage onto the degraded mesh
                loader = self._lazy.load_table
                self._blocks = self._lazy.load_blocks()
                self._blocks.lineage = loader
                self._lazy = None  # device copy is authoritative now
            else:
                table, mesh = self._pending  # type: ignore[misc]
                self._blocks = from_arrow(table, self.schema, mesh)
                self._blocks.lineage = getattr(
                    self, "_lineage_loader", None
                )
                self._pending = None  # device copy is authoritative now
            if gate is not None:
                gate.after(self._blocks)
                self._mem_gate = None
        return self._blocks

    @property
    def mesh(self) -> Any:
        if self._blocks is not None:
            return self._blocks.mesh
        if self._lazy is not None:
            return self._lazy.mesh
        return self._pending[1]  # type: ignore[index]

    @property
    def is_local(self) -> bool:
        return False

    @property
    def is_bounded(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def empty(self) -> bool:
        return self.count() == 0

    def count(self) -> int:
        if self._blocks is not None:
            return self._blocks.nrows
        if self._lazy is not None:
            return self._lazy.nrows
        return self._pending[0].num_rows  # type: ignore[index]

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return self.head(1).as_array(type_safe=True)[0]

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        if self._blocks is not None:
            return to_arrow(self._blocks, self.schema)
        if self._lazy is not None:
            # host-only decode, no device trip; memoize as an in-memory
            # pending frame so a second host touch (or a later device op)
            # never re-reads the file
            table = self._lazy.load_table()
            self._pending = (table, self._lazy.mesh)
            self._lazy = None
            return table
        return self._pending[0]  # type: ignore[index]

    def as_pandas(self) -> pd.DataFrame:
        from fugue_tpu.dataframe.arrow_utils import table_to_pandas

        return table_to_pandas(self.as_arrow())

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        res = ArrowDataFrame(self.as_arrow(), self.schema)
        if self.has_metadata:
            res.reset_metadata(self.metadata)
        return res

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        return self.as_local_bounded().as_array(columns, type_safe)

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        return self.as_local_bounded().as_array_iterable(columns, type_safe)

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.exclude(cols)
        return self._select_schema(schema)

    def _select_cols(self, cols: List[Any]) -> DataFrame:
        schema = self.schema.extract(cols)
        return self._select_schema(schema)

    def _select_schema(self, schema: Schema) -> "JaxDataFrame":
        if self._blocks is None and self._lazy is not None:
            load_blocks, load_table, mesh, nrows, load_head, narrow = self._lazy
            names = list(schema.names)
            if narrow is not None:
                res = narrow(names)
                if res is not None:
                    return res  # re-planned: unselected columns never decode
            return JaxDataFrame.from_lazy(
                lambda: _subset_blocks(load_blocks(), names),
                lambda: load_table().select(names),
                mesh, schema, nrows,
                None if load_head is None
                else lambda n: load_head(n).select(names),
            )
        if self._blocks is None:
            table, mesh = self._pending  # type: ignore[misc]
            res = JaxDataFrame.from_table(
                table.select(schema.names), mesh, schema
            )
            # the derived pending frame materializes under the same
            # admission ticket (sharing it is safe: the gate is
            # stateless and registers whatever blocks it is handed) and
            # inherits the reload plan (recovery re-selects the subset)
            res._mem_gate = self._mem_gate
            res._lineage_loader = self._lineage_loader
            return res
        blocks = JaxBlocks(
            self._blocks._nrows,
            {n: self._blocks.columns[n] for n in schema.names},
            self._blocks.mesh,
            row_valid=self._blocks.row_valid,
            nrows_dev=self._blocks._nrows_dev,
        )
        return JaxDataFrame(blocks, schema)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        schema = self._rename_schema(columns)
        if self._blocks is None and self._lazy is not None:
            load_blocks, load_table, mesh, nrows, load_head, _ = self._lazy
            mapping = dict(columns)
            names = list(schema.names)
            return JaxDataFrame.from_lazy(
                lambda: _rename_blocks(load_blocks(), mapping),
                lambda: load_table().rename_columns(names),
                mesh, schema, nrows,
                None if load_head is None
                else lambda n: load_head(n).rename_columns(names),
            )
        if self._blocks is None:
            table, mesh = self._pending  # type: ignore[misc]
            res = JaxDataFrame.from_table(
                table.rename_columns(schema.names), mesh, schema
            )
            res._mem_gate = self._mem_gate  # same admission ticket
            return res
        cols = {
            columns.get(n, n): c for n, c in self._blocks.columns.items()
        }
        return JaxDataFrame(
            JaxBlocks(
                self._blocks._nrows,
                cols,
                self._blocks.mesh,
                row_valid=self._blocks.row_valid,
                nrows_dev=self._blocks._nrows_dev,
            ),
            schema,
        )

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self._alter_schema(columns)
        if new_schema == self.schema:
            return self
        # general correctness path: cast at the host boundary, re-device
        table = cast_table(self.as_arrow(), new_schema)
        return JaxDataFrame.from_table(table, self.mesh, new_schema)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        assert_or_throw(n >= 0, ValueError("n must be >= 0"))
        schema = self.schema if columns is None else self.schema.extract(columns)
        src = self if columns is None else self[columns]
        if src._blocks is None:  # type: ignore[union-attr]
            lazy = src._lazy  # type: ignore[union-attr]
            if lazy is not None and lazy.load_head is not None:
                # bounded read: only the leading batches, not the file
                table = lazy.load_head(n)
            else:
                table = src.as_arrow()  # pending/lazy host path, no device
            return ArrowDataFrame(table.slice(0, n), schema)
        blocks = src._blocks  # type: ignore
        if blocks.row_valid is not None:
            # masked layout: locate the first n valid rows (one mask
            # readback), gather them on device, export the small frame
            import numpy as np

            from fugue_tpu.jax_backend.blocks import gather_indices

            idx = np.nonzero(np.asarray(blocks.row_valid))[0][:n]
            small = gather_indices(blocks, idx, schema)
            return ArrowDataFrame(to_arrow(small, schema), schema)
        take_n = min(n, blocks.nrows)
        table = to_arrow(
            JaxBlocks(take_n, blocks.columns, blocks.mesh), schema
        )
        return ArrowDataFrame(table, schema)


def _subset_blocks(blocks: JaxBlocks, names: List[str]) -> JaxBlocks:
    return JaxBlocks(
        blocks._nrows,
        {n: blocks.columns[n] for n in names},
        blocks.mesh,
        row_valid=blocks.row_valid,
        nrows_dev=blocks._nrows_dev,
    )


def _rename_blocks(blocks: JaxBlocks, mapping: Dict[str, str]) -> JaxBlocks:
    return JaxBlocks(
        blocks._nrows,
        {mapping.get(n, n): c for n, c in blocks.columns.items()},
        blocks.mesh,
        row_valid=blocks.row_valid,
        nrows_dev=blocks._nrows_dev,
    )
